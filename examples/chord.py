"""Chord DHT over s4u — BASELINE config #5 (reference
examples/s4u/dht-chord/s4u-dht-chord.cpp).

Every node owns one mailbox; find-successor queries are FORWARDED
node-to-node and answered directly to the asker's reply mailbox (the
reference's non-blocking design — no nested RPC, so no request
deadlocks).  Nodes periodically stabilize, fix a random finger, and
issue random lookups until the deadline, then notify their successor
and leave.

Run directly for a small demo, or through tools/chord_scale.py for the
10k-peer churn configuration.
"""

from __future__ import annotations

import random
from typing import List, Optional

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))
from simgrid_tpu import s4u

NB_BITS = 24
NB_KEYS = 1 << NB_BITS

#: message sizes (bytes), matching the reference's constants
COMM_SIZE = 10.0


def _in_range(value: int, start: int, end: int) -> bool:
    """value in (start, end] on the ring; (a, a] is the FULL circle
    (the single-node ring owns every key)."""
    value = (value - start) % NB_KEYS
    end = (end - start) % NB_KEYS
    if end == 0:
        return True
    return 0 < value <= end


class ChordNode:
    """One DHT node actor."""

    def __init__(self, node_id: int, deadline: float,
                 known_id: Optional[int], stats: dict,
                 lookup_period: float = 10.0, rng_seed: int = 0):
        self.id = node_id
        self.known_id = known_id
        self.deadline = deadline
        self.stats = stats
        self.lookup_period = lookup_period
        self.rng = random.Random((rng_seed << 32) | node_id)
        self.fingers: List[int] = [node_id] * NB_BITS
        self.pred_id: Optional[int] = None
        self.mailbox = s4u.Mailbox.by_name(f"chord-{node_id}")
        self._comm = None          # the ONE outstanding receive
        self._pending_answer = None

    # -- ring arithmetic ---------------------------------------------------
    def successor(self) -> int:
        return self.fingers[0]

    def closest_preceding(self, key: int) -> int:
        for finger in reversed(self.fingers):
            if _in_range(finger, self.id, (key - 1) % NB_KEYS):
                return finger
        return self.id

    # -- messaging ---------------------------------------------------------
    def _send(self, dst_id: int, msg: dict) -> None:
        s4u.Mailbox.by_name(f"chord-{dst_id}").put_init(
            msg, COMM_SIZE).detach()

    def _handle(self, msg: dict) -> None:
        kind = msg["type"]
        if kind == "find_successor":
            key = msg["key"]
            if _in_range(key, self.id, self.successor()):
                self._send(msg["answer_to"],
                           {"type": "found", "key": key,
                            "answer": self.successor()})
            else:
                # forward along the finger table (the reference's
                # remote_find_successor relay)
                self._send(self.closest_preceding(key), msg)
        elif kind == "found":
            self.stats["resolved"] = self.stats.get("resolved", 0) + 1
            self._pending_answer = msg
        elif kind == "get_predecessor":
            self._send(msg["answer_to"],
                       {"type": "predecessor", "answer": self.pred_id})
        elif kind == "predecessor":
            self._pending_answer = msg
        elif kind == "notify":
            candidate = msg["id"]
            if self.pred_id is None or _in_range(
                    candidate, self.pred_id, (self.id - 1) % NB_KEYS):
                self.pred_id = candidate
        elif kind == "predecessor_leaving":
            self.pred_id = msg["pred"]
        elif kind == "successor_leaving":
            self.fingers[0] = msg["succ"]

    #: polling quantum (simulated s) — the reference chord's pattern:
    #: test() the one posted receive, sleep when idle
    POLL = 0.05

    def _recv_until(self, want: str, timeout: float) -> Optional[dict]:
        """Pump messages until one of type `want` arrives (answering
        every request meanwhile) or the timeout elapses.  Exactly ONE
        receive stays posted; it is polled with test() + sleep, never
        abandoned (a dangling posted receive would steal messages, and
        a timed-out wait leaves the comm unusable)."""
        end = s4u.Engine.get_clock() + timeout
        self._pending_answer = None
        while s4u.Engine.get_clock() < end:
            if self._comm is None:
                self._comm = self.mailbox.get_async()
            if self._comm.test():
                payload = self._comm.get_payload()
                self._comm = None
                self._handle(payload)
                if (self._pending_answer is not None
                        and self._pending_answer["type"] == want):
                    return self._pending_answer
            else:
                s4u.this_actor.sleep_for(
                    min(self.POLL, end - s4u.Engine.get_clock()))
        return None

    # -- chord protocol ----------------------------------------------------
    def find_successor(self, key: int) -> Optional[int]:
        if _in_range(key, self.id, self.successor()):
            return self.successor()
        self.stats["lookups"] = self.stats.get("lookups", 0) + 1
        self._send(self.closest_preceding(key),
                   {"type": "find_successor", "key": key,
                    "answer_to": self.id})
        answer = self._recv_until("found", 50.0)
        return answer["answer"] if answer else None

    def join(self) -> bool:
        self._send(self.known_id,
                   {"type": "find_successor", "key": self.id,
                    "answer_to": self.id})
        answer = self._recv_until("found", 200.0)
        if answer is None:
            self.stats["join_failures"] = \
                self.stats.get("join_failures", 0) + 1
            return False
        self.fingers[0] = answer["answer"]
        return True

    def stabilize(self) -> None:
        self._send(self.successor(),
                   {"type": "get_predecessor", "answer_to": self.id})
        answer = self._recv_until("predecessor", 20.0)
        if answer and answer["answer"] is not None:
            candidate = answer["answer"]
            if _in_range(candidate, self.id,
                         (self.successor() - 1) % NB_KEYS):
                self.fingers[0] = candidate
        if self.successor() != self.id:
            self._send(self.successor(), {"type": "notify", "id": self.id})

    def fix_fingers(self) -> None:
        i = self.rng.randrange(NB_BITS)
        succ = self.find_successor((self.id + (1 << i)) % NB_KEYS)
        if succ is not None:
            self.fingers[i] = succ

    def leave(self) -> None:
        if self.pred_id is not None:
            self._send(self.successor(),
                       {"type": "predecessor_leaving",
                        "pred": self.pred_id})
            self._send(self.pred_id,
                       {"type": "successor_leaving",
                        "succ": self.successor()})

    # -- actor body --------------------------------------------------------
    def __call__(self) -> None:
        if self.known_id is not None:
            s4u.this_actor.sleep_for(self.rng.uniform(0.0, 2.0))
            if not self.join():
                return
        next_action = s4u.Engine.get_clock() + self.lookup_period
        while s4u.Engine.get_clock() < self.deadline:
            budget = min(self.deadline,
                         next_action) - s4u.Engine.get_clock()
            if budget > 0:
                self._recv_until("__none__", budget)   # serve requests
            if s4u.Engine.get_clock() >= self.deadline:
                break
            self.stabilize()
            self.fix_fingers()
            self.find_successor(self.rng.randrange(NB_KEYS))
            next_action = s4u.Engine.get_clock() + self.lookup_period
        self.leave()


def deploy(engine, n_nodes: int, deadline: float = 400.0,
           seed: int = 42, lookup_period: float = 10.0) -> dict:
    """Create n_nodes Chord actors round-robin over the platform's
    hosts; returns the shared stats dict filled during run()."""
    rng = random.Random(seed)
    ids = sorted(rng.sample(range(NB_KEYS), n_nodes))
    hosts = engine.get_all_hosts()
    stats: dict = {"ids": ids}
    # the first node bootstraps the ring; the others join via a random
    # already-placed node (the reference joins via a fixed known host)
    for i, node_id in enumerate(ids):
        known = None if i == 0 else ids[rng.randrange(i)]
        node = ChordNode(node_id, deadline, known, stats,
                         lookup_period=lookup_period, rng_seed=seed)
        s4u.Actor.create(f"node-{node_id}", hosts[i % len(hosts)], node)
    return stats


def main():
    import sys
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    e = s4u.Engine(["chord"])
    from simgrid_tpu.smpi.runtime import fabricate_platform
    import tempfile, os
    fd, plat = tempfile.mkstemp(suffix=".xml")
    os.close(fd)
    fabricate_platform(min(n, 64), plat)
    e.load_platform(plat)
    stats = deploy(e, n)
    e.run()
    os.unlink(plat)
    print(f"chord: {n} nodes, clock={e.clock:.3f}, "
          f"lookups={stats.get('lookups', 0)}, "
          f"resolved={stats.get('resolved', 0)}")


if __name__ == "__main__":
    main()
