"""Golden-output example: the reference replay.tesh allreduce oracle."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simgrid_tpu.smpi import replay

TRACE = "/tmp/example_ar3.txt"
with open(TRACE, "w") as f:
    for r in range(3):
        f.write(f"{r} init\n")
    for r in range(3):
        f.write(f"{r} allreduce 5e4 5e8\n")
    for r in range(3):
        f.write(f"{r} compute 5e8\n")
    for r in range(3):
        f.write(f"{r} finalize\n")

e = replay.smpi_replay_run(
    "/root/reference/examples/platforms/small_platform.xml", TRACE, 3,
    configs=["tracing:no", "surf/precision:1e-9", "network/model:SMPI"])
print(f"clock {e.clock:.6f}")
