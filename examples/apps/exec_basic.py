"""s4u-exec-basic replica (reference
examples/s4u/exec-basic/s4u-exec-basic.cpp): two executions sharing a
host, one with priority 2 (1/3 vs 2/3 sharing until the privileged one
ends)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def executor():
    s4u.this_actor.execute(98095)
    LOG.info("Done.")


def privileged():
    s4u.this_actor.execute(98095, priority=2)
    LOG.info("Done.")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("executor", e.host_by_name("Tremblay"), executor)
    s4u.Actor.create("privileged", e.host_by_name("Tremblay"), privileged)
    e.run()


if __name__ == "__main__":
    main()
