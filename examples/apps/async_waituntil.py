"""s4u-async-waituntil replica (reference
examples/s4u/async-waituntil/s4u-async-wait.cpp): like async-wait but each
wait is a bounded wait_for(1)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_async_waituntil")


def sender(messages_count, msg_size, receivers_count):
    messages_count, receivers_count = int(messages_count), \
        int(receivers_count)
    msg_size = float(msg_size)
    pending = []
    mboxes = [s4u.Mailbox.by_name(f"receiver-{i}")
              for i in range(receivers_count)]
    for i in range(messages_count):
        content = f"Message {i}"
        LOG.info("Send '%s' to '%s'", content,
                 mboxes[i % receivers_count].name)
        pending.append(mboxes[i % receivers_count].put_async(
            content, msg_size))
    for i in range(receivers_count):
        pending.append(mboxes[i % receivers_count].put_async(
            "finalize", 0))
        LOG.info("Send 'finalize' to 'receiver-%d'", i % receivers_count)
    LOG.info("Done dispatching all messages")
    while pending:
        pending.pop().wait_for(1)
    LOG.info("Goodbye now!")


def receiver(rid):
    mbox = s4u.Mailbox.by_name(f"receiver-{rid}")
    LOG.info("Wait for my first message")
    while True:
        received = mbox.get()
        LOG.info("I got a '%s'.", received)
        if received == "finalize":
            break


def main():
    e = s4u.Engine(sys.argv)
    e.register_function("sender", sender)
    e.register_function("receiver", receiver)
    e.load_platform(sys.argv[1])
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
