"""s4u-actor-migrate replica (reference
examples/s4u/actor-migrate/s4u-actor-migrate.cpp): self-migration mid
execution and monitor-driven migration of a suspended actor."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_actor_migration")


def worker(first, second):
    flop_amount = first.get_speed() * 5 + second.get_speed() * 5
    LOG.info("Let's move to %s to execute %.2f Mflops (5sec on %s and "
             "5sec on %s)", first.name, flop_amount / 1e6, first.name,
             second.name)
    s4u.this_actor.migrate(first)
    s4u.this_actor.execute(flop_amount)
    LOG.info("I wake up on %s. Let's suspend a bit",
             s4u.this_actor.get_host().name)
    s4u.this_actor.suspend()
    LOG.info("I wake up on %s", s4u.this_actor.get_host().name)
    LOG.info("Done")


def monitor():
    e = s4u.Engine.get_instance()
    boivin = e.host_by_name("Boivin")
    jacquelin = e.host_by_name("Jacquelin")
    fafard = e.host_by_name("Fafard")
    actor = s4u.Actor.create("worker", fafard,
                             lambda: worker(boivin, jacquelin))
    s4u.this_actor.sleep_for(5)
    LOG.info("After 5 seconds, move the process to %s", jacquelin.name)
    actor.migrate(jacquelin)
    s4u.this_actor.sleep_until(15)
    LOG.info("At t=15, move the process to %s and resume it.",
             fafard.name)
    actor.migrate(fafard)
    actor.resume()


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("monitor", e.host_by_name("Boivin"), monitor)
    e.run()


if __name__ == "__main__":
    main()
