"""s4u-actor-join replica (reference
examples/s4u/actor-join/s4u-actor-join.cpp): joins with timeouts, join
after termination."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def sleeper():
    LOG.info("Sleeper started")
    s4u.this_actor.sleep_for(3)
    LOG.info("I'm done. See you!")


def master():
    host = s4u.this_actor.get_host()
    LOG.info("Start sleeper")
    actor = s4u.Actor.create("sleeper from master", host, sleeper)
    LOG.info("Join the sleeper (timeout 2)")
    actor.join(2)

    LOG.info("Start sleeper")
    actor = s4u.Actor.create("sleeper from master", host, sleeper)
    LOG.info("Join the sleeper (timeout 4)")
    actor.join(4)

    LOG.info("Start sleeper")
    actor = s4u.Actor.create("sleeper from master", host, sleeper)
    LOG.info("Join the sleeper (timeout 2)")
    actor.join(2)

    LOG.info("Start sleeper")
    actor = s4u.Actor.create("sleeper from master", host, sleeper)
    LOG.info("Waiting 4")
    s4u.this_actor.sleep_for(4)
    LOG.info("Join the sleeper after its end (timeout 1)")
    actor.join(1)

    LOG.info("Goodbye now!")
    s4u.this_actor.sleep_for(1)
    LOG.info("Goodbye now!")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("master", e.host_by_name("Tremblay"), master)
    e.run()
    LOG.info("Simulation time %g" % e.clock)


if __name__ == "__main__":
    main()
