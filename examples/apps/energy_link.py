"""s4u-energy-link replica (reference
examples/s4u/energy-link/s4u-energy-link.cpp): link_energy plugin under
the CM02 network model."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.plugins import link_energy
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def sender(flow_amount, comm_size):
    LOG.info("Send %.0f bytes, in %d flows" % (comm_size, flow_amount))
    mailbox = s4u.Mailbox.by_name("message")
    s4u.this_actor.sleep_for(10)
    if flow_amount == 1:
        mailbox.put("%f" % comm_size, comm_size)
    else:
        comms = [mailbox.put_async(str(i), comm_size)
                 for i in range(flow_amount)]
        for c in comms:
            c.wait()
    LOG.info("sender done.")


def receiver(flow_amount):
    LOG.info("Receiving %d flows ..." % flow_amount)
    mailbox = s4u.Mailbox.by_name("message")
    if flow_amount == 1:
        mailbox.get()
    else:
        comms = [mailbox.get_async() for _ in range(flow_amount)]
        for c in comms:
            c.wait()
    LOG.info("receiver done.")


def main():
    e = s4u.Engine(sys.argv)
    LOG.info("Activating the SimGrid link energy plugin")
    rest = [a for a in sys.argv[1:]
            if not a.startswith("--cfg=") and not a.startswith("--log=")]
    e.load_platform(rest[0])
    link_energy.link_energy_plugin_init(e)
    flow_amount = int(rest[1]) if len(rest) > 1 else 1
    comm_size = float(rest[2]) if len(rest) > 2 else 25000.0
    s4u.Actor.create("sender", e.host_by_name("MyHost1"), sender,
                     flow_amount, comm_size)
    s4u.Actor.create("receiver", e.host_by_name("MyHost2"), receiver,
                     flow_amount)
    e.run()


if __name__ == "__main__":
    main()
