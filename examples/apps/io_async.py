"""s4u-io-async replica (reference
examples/s4u/io-async/s4u-io-async.cpp): async storage reads and a
cancelled write."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def test(size):
    e = s4u.Engine.get_instance()
    storage = e.pimpl.storages["Disk1"]
    LOG.info("Hello! read %d bytes from Storage %s", size, storage.name)
    activity = s4u.Io(storage, size, s4u.Io.OpType.READ)
    activity.start()
    activity.wait()
    LOG.info("Goodbye now!")


def test_cancel(size):
    e = s4u.Engine.get_instance()
    storage = e.pimpl.storages["Disk2"]
    LOG.info("Hello! write %d bytes from Storage %s", size, storage.name)
    activity = s4u.Io(storage, size, s4u.Io.OpType.WRITE)
    activity.start()
    s4u.this_actor.sleep_for(0.5)
    LOG.info("I changed my mind, cancel!")
    activity.cancel()
    LOG.info("Goodbye now!")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("test", e.host_by_name("bob"), lambda: test(int(2e7)))
    s4u.Actor.create("test_cancel", e.host_by_name("alice"),
                     lambda: test_cancel(int(5e7)))
    e.run()
    LOG.info("Simulation time %g", e.clock)


if __name__ == "__main__":
    main()
