"""s4u-async-wait replica (reference
examples/s4u/async-wait/s4u-async-wait.cpp): put_async fan-out, waits
in reverse creation order."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_async_wait")


def sender(messages_count, msg_size, receivers_count):
    messages_count, receivers_count = int(messages_count), \
        int(receivers_count)
    msg_size = float(msg_size)
    pending = []
    mboxes = [s4u.Mailbox.by_name(f"receiver-{i}")
              for i in range(receivers_count)]
    for i in range(messages_count):
        content = f"Message {i}"
        LOG.info("Send '%s' to '%s'", content,
                 mboxes[i % receivers_count].name)
        pending.append(mboxes[i % receivers_count].put_async(
            content, msg_size))
    for i in range(receivers_count):
        LOG.info("Send 'finalize' to 'receiver-%d'", i)
        pending.append(mboxes[i].put_async("finalize", 0))
    LOG.info("Done dispatching all messages")
    while pending:
        pending.pop().wait()
    LOG.info("Goodbye now!")


def receiver(rid):
    mbox = s4u.Mailbox.by_name(f"receiver-{rid}")
    LOG.info("Wait for my first message")
    while True:
        received = mbox.get()
        LOG.info("I got a '%s'.", received)
        if received == "finalize":
            break


def main():
    e = s4u.Engine(sys.argv)
    e.register_function("sender", sender)
    e.register_function("receiver", receiver)
    e.load_platform(sys.argv[1])
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
