"""s4u-engine-filtering replica (reference
examples/s4u/engine-filtering/s4u-engine-filtering.cpp): filter hosts
with predicates — plain functions, stateless and stateful functors."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_engine_filtering")


def filter_speed_more_than_50mf(host):
    return host.get_speed() > 50e6


class SingleCore:
    def __call__(self, host):
        return host.get_core_count() == 1


class FrequencyChanged:
    def __init__(self, e):
        self.host_list = {host: host.get_pstate()
                          for host in e.get_all_hosts()}

    def __call__(self, host):
        return host.get_pstate() != self.host_list[host]

    def get_old_speed(self, host):
        return self.host_list[host]


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    LOG.info("Hosts currently registered with this engine: %d",
             e.get_host_count())
    hosts = [h for h in e.get_all_hosts() if h.get_core_count() > 1]
    for host in hosts:
        LOG.info("The following hosts have more than one core: %s",
                 host.name)
    assert len(hosts) == 1

    for host in filter(SingleCore(), e.get_all_hosts()):
        LOG.info("The following hosts are SingleCore: %s", host.name)

    LOG.info("A simple example: Let's retrieve all hosts that changed "
             "their frequency")
    freq_filter = FrequencyChanged(e)
    e.host_by_name("MyHost2").set_pstate(2)
    for host in filter(freq_filter, e.get_all_hosts()):
        LOG.info("The following hosts changed their frequency: %s "
                 "(from %.1ff to %.1ff)", host.name,
                 host.get_pstate_speed(freq_filter.get_old_speed(host)),
                 host.get_speed())

    for host in filter(filter_speed_more_than_50mf, e.get_all_hosts()):
        LOG.info("The following hosts have a frequency > 50Mf: %s",
                 host.name)


if __name__ == "__main__":
    main()
