"""s4u-app-chainsend replica (reference
examples/s4u/app-chainsend/s4u-app-chainsend.cpp): pipeline broadcast —
a broadcaster streams file pieces down a chain of peers, each
forwarding asynchronously to its successor (BASELINE config-#5 family:
churnless pipelined fleet)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_chainsend")

PIECE_SIZE = 65536
MESSAGE_BUILD_CHAIN_SIZE = 40
MESSAGE_SEND_DATA_HEADER_SIZE = 1


def peer():
    me = s4u.Mailbox.by_name(s4u.this_actor.get_host().name)
    start_time = s4u.Engine.get_clock()
    # joinChain
    prev, nxt, total_pieces = me.get()
    received_bytes = 0
    received_pieces = 0
    pending_sends = []
    # forwardFile
    while received_pieces < total_pieces:
        received = me.get()
        if nxt is not None:
            pending_sends.append(s4u.Mailbox.by_name(nxt).put_async(
                received, MESSAGE_SEND_DATA_HEADER_SIZE + PIECE_SIZE))
        received_pieces += 1
        received_bytes += PIECE_SIZE
    s4u.Comm.wait_all(pending_sends)
    end_time = s4u.Engine.get_clock()
    LOG.info("### %f %d bytes (Avg %f MB/s); copy finished (simulated).",
             end_time - start_time, received_bytes,
             received_bytes / 1024.0 / 1024.0 / (end_time - start_time))


def broadcaster(hostcount, piece_count):
    names = [f"node-{i}.simgrid.org" for i in range(1, hostcount + 1)]
    # buildChain
    for i, name in enumerate(names):
        prev = names[i - 1] if i > 0 else None
        nxt = names[i + 1] if i < len(names) - 1 else None
        s4u.Mailbox.by_name(name).put((prev, nxt, piece_count),
                                      MESSAGE_BUILD_CHAIN_SIZE)
    # sendFile
    first = s4u.Mailbox.by_name(names[0])
    pending = [first.put_async("piece",
                               MESSAGE_SEND_DATA_HEADER_SIZE + PIECE_SIZE)
               for _ in range(piece_count)]
    s4u.Comm.wait_all(pending)


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("broadcaster",
                     e.host_by_name("node-0.simgrid.org"),
                     lambda: broadcaster(8, 256))
    for i in range(1, 9):
        s4u.Actor.create("peer",
                         e.host_by_name(f"node-{i}.simgrid.org"), peer)
    e.run()
    LOG.info("Total simulation time: %e", e.clock)


if __name__ == "__main__":
    main()
