"""s4u-exec-waitany replica (reference
examples/s4u/exec-waitany/s4u-exec-waitany.cpp): wait_any /
wait_any_for over concurrent executions on a multicore host."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog
from simgrid_tpu.exceptions import TimeoutException

LOG = xlog.get_category("s4u_exec_waitany")


def worker(with_timeout):
    pending = []
    for i in range(3):
        name = f"Exec-{i}"
        amount = (6 * (i % 2) + i + 1) * \
            s4u.this_actor.get_host().get_speed()
        exec_ = s4u.this_actor.exec_init(amount).set_name(name)
        pending.append(exec_)
        exec_.start()
        LOG.info("Activity %s has started for %.0f seconds", name,
                 amount / s4u.this_actor.get_host().get_speed())
    while pending:
        try:
            if with_timeout:
                pos = s4u.Exec.wait_any_for(pending, 4)
            else:
                pos = s4u.Exec.wait_any(pending)
        except TimeoutException:
            pos = -1
        if pos < 0:
            LOG.info("Do not wait any longer for an activity")
            pending.clear()
        else:
            LOG.info("Activity '%s' (at position %d) is complete",
                     pending[pos].name, pos)
            del pending[pos]
        LOG.info("%d activities remain pending", len(pending))


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("worker", e.host_by_name("Tremblay"),
                     lambda: worker(False))
    s4u.Actor.create("worker_timeout", e.host_by_name("Tremblay"),
                     lambda: worker(True))
    e.run()


if __name__ == "__main__":
    main()
