"""s4u-exec-async replica (reference
examples/s4u/exec-async/s4u-exec-async.cpp): start/wait, test-poll,
and cancel of asynchronous executions."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def waiter():
    amount = s4u.this_actor.get_host().get_speed()
    LOG.info("Execute %g flops, should take 1 second.", amount)
    activity = s4u.this_actor.exec_init(amount)
    activity.start()
    activity.wait()
    LOG.info("Goodbye now!")


def monitor():
    amount = s4u.this_actor.get_host().get_speed()
    LOG.info("Execute %g flops, should take 1 second.", amount)
    activity = s4u.this_actor.exec_init(amount)
    activity.start()
    while not activity.test():
        LOG.info("Remaining amount of flops: %g (%.0f%%)",
                 activity.get_remaining(),
                 100 * activity.get_remaining_ratio())
        s4u.this_actor.sleep_for(0.3)
    activity.wait()
    LOG.info("Goodbye now!")


def canceller():
    amount = s4u.this_actor.get_host().get_speed()
    LOG.info("Execute %g flops, should take 1 second.", amount)
    activity = s4u.this_actor.exec_async(amount)
    s4u.this_actor.sleep_for(0.5)
    LOG.info("I changed my mind, cancel!")
    activity.cancel()
    LOG.info("Goodbye now!")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("wait", e.host_by_name("Fafard"), waiter)
    s4u.Actor.create("monitor", e.host_by_name("Ginette"), monitor)
    s4u.Actor.create("cancel", e.host_by_name("Boivin"), canceller)
    e.run()
    LOG.info("Simulation time %g", e.clock)


if __name__ == "__main__":
    main()
