"""s4u-platform-properties replica (reference
examples/s4u/platform-properties/s4u-platform-properties.cpp): host,
zone, and actor properties from the platform/deployment XML."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def test_host(hostname):
    e = s4u.Engine.get_instance()
    thehost = e.host_by_name(hostname)
    props = thehost.properties
    LOG.info("== Print the properties of the host '%s'", hostname)
    for key in sorted(props):
        LOG.info("  Host property: '%s' -> '%s'", key, props[key])
    LOG.info("== Try to get a host property that does not exist")
    assert props.get("Unknown") is None
    LOG.info("== Try to get a host property that does exist")
    value = props.get("Hdd")
    assert value == "180"
    LOG.info("   Property: Hdd old value: %s", value)
    LOG.info("== Trying to modify a host property")
    props["Hdd"] = "250"
    value = props.get("Hdd")
    assert value == "250"
    LOG.info("   Property: Hdd old value: %s", value)
    props["Hdd"] = "180"
    zone = thehost.netpoint.englobing_zone
    LOG.info("== Print the properties of the zone '%s' that contains "
             "'%s'", zone.name, hostname)
    for key in sorted(zone.properties):
        LOG.info("  Zone property: '%s' -> '%s'", key,
                 zone.properties[key])


def alice():
    test_host("host1")


def carole():
    s4u.this_actor.sleep_for(1)
    test_host("host1")


def david():
    s4u.this_actor.sleep_for(2)
    test_host("node-0.simgrid.org")


def bob():
    root = s4u.Engine.get_instance().get_netzone_root()
    LOG.info("== Print the properties of the root zone")
    LOG.info("   Zone property: filename -> %s",
             root.properties.get("filename"))
    LOG.info("   Zone property: date -> %s", root.properties.get("date"))
    LOG.info("   Zone property: author -> %s",
             root.properties.get("author"))
    props = s4u.Actor.self().get_properties()
    LOG.info("== Print the properties of the actor")
    for k, v in props.items():
        LOG.info("   Actor property: %s -> %s", k, v)
    LOG.info("== Try to get an actor property that does not exist")
    assert props.get("UnknownProcessProp") is None


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    e.register_function("alice", alice)
    e.register_function("bob", bob)
    e.register_function("carole", carole)
    e.register_function("david", david)
    LOG.info("There are %d hosts in the environment", e.get_host_count())
    for host in e.get_all_hosts():
        LOG.info("Host '%s' runs at %.0f flops/s", host.name,
                 host.get_speed())
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
