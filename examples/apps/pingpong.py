"""s4u-app-pingpong replica (reference
examples/s4u/app-pingpong/s4u-app-pingpong.cpp): latency-bound ping,
bandwidth-bound pong, identical log lines so the reference tesh oracle
(s4u-app-pingpong.tesh) pins this program's output verbatim."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("pingpong")


def pinger(mailbox_in, mailbox_out):
    LOG.info("Ping from mailbox %s to mailbox %s"
             % (mailbox_in.name, mailbox_out.name))
    mailbox_out.put(s4u.Engine.get_clock(), 1)
    sender_time = mailbox_in.get()
    communication_time = s4u.Engine.get_clock() - sender_time
    LOG.info("Task received : large communication (bandwidth bound)")
    LOG.info("Pong time (bandwidth bound): %.3f" % communication_time)


def ponger(mailbox_in, mailbox_out):
    LOG.info("Pong from mailbox %s to mailbox %s"
             % (mailbox_in.name, mailbox_out.name))
    sender_time = mailbox_in.get()
    communication_time = s4u.Engine.get_clock() - sender_time
    LOG.info("Task received : small communication (latency bound)")
    LOG.info(" Ping time (latency bound) %f" % communication_time)
    payload = s4u.Engine.get_clock()
    LOG.info("task_bw->data = %.3f" % payload)
    mailbox_out.put(payload, 1e9)


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    mb1 = s4u.Mailbox.by_name("Mailbox 1")
    mb2 = s4u.Mailbox.by_name("Mailbox 2")
    s4u.Actor.create("pinger", e.host_by_name("Tremblay"), pinger, mb1, mb2)
    s4u.Actor.create("ponger", e.host_by_name("Jupiter"), ponger, mb2, mb1)
    e.run()
    LOG.info("Total simulation time: %.3f" % e.clock)


if __name__ == "__main__":
    main()
