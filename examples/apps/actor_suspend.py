"""s4u-actor-suspend replica (reference
examples/s4u/actor-suspend/s4u-actor-suspend.cpp): suspend/resume of a
sleeping actor (the sleep timer keeps running while suspended) and of a
computing actor (the execution IS paused)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_actor_suspend")


def lazy_guy():
    LOG.info("Nobody's watching me ? Let's go to sleep.")
    s4u.this_actor.suspend()
    LOG.info("Uuuh ? Did somebody call me ?")

    LOG.info("Going to sleep...")
    s4u.this_actor.sleep_for(10)
    LOG.info("Mmm... waking up.")

    LOG.info("Going to sleep one more time (for 10 sec)...")
    s4u.this_actor.sleep_for(10)
    LOG.info("Waking up once for all!")

    LOG.info("Ok, let's do some work, then (for 10 sec on Boivin).")
    s4u.this_actor.execute(980.95e6)

    LOG.info("Mmmh, I'm done now. Goodbye.")


def dream_master():
    LOG.info("Let's create a lazy guy.")
    lazy = s4u.Actor.create("Lazy", s4u.this_actor.get_host(), lazy_guy)
    LOG.info("Let's wait a little bit...")
    s4u.this_actor.sleep_for(10)
    LOG.info("Let's wake the lazy guy up! >:) BOOOOOUUUHHH!!!!")
    if lazy.is_suspended():
        lazy.resume()
    else:
        LOG.error("I was thinking that the lazy guy would be suspended now")

    s4u.this_actor.sleep_for(5)
    LOG.info("Suspend the lazy guy while he's sleeping...")
    lazy.suspend()
    LOG.info("Let him finish his siesta.")
    s4u.this_actor.sleep_for(10)
    LOG.info("Wake up, lazy guy!")
    lazy.resume()

    s4u.this_actor.sleep_for(5)
    LOG.info("Suspend again the lazy guy while he's sleeping...")
    lazy.suspend()
    LOG.info("This time, don't let him finish his siesta.")
    s4u.this_actor.sleep_for(2)
    LOG.info("Wake up, lazy guy!")
    lazy.resume()

    s4u.this_actor.sleep_for(5)
    LOG.info("Give a 2 seconds break to the lazy guy while he's working...")
    lazy.suspend()
    s4u.this_actor.sleep_for(2)
    LOG.info("Back to work, lazy guy!")
    lazy.resume()

    LOG.info("OK, I'm done here.")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("dream_master", e.host_by_name("Boivin"),
                     dream_master)
    e.run()


if __name__ == "__main__":
    main()
