"""s4u-actor-daemon replica (reference
examples/s4u/actor-daemon/s4u-actor-daemon.cpp): a daemonized actor
loops forever and dies with the last regular actor."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_actor_daemon")


def worker():
    LOG.info("Let's do some work (for 10 sec on Boivin).")
    s4u.this_actor.execute(980.95e6)
    LOG.info("I'm done now. I leave even if it makes the daemon die.")


def my_daemon():
    s4u.Actor.self().daemonize()
    while s4u.this_actor.get_host().is_on():
        LOG.info("Hello from the infinite loop")
        s4u.this_actor.sleep_for(3.0)
    LOG.info("I will never reach that point: daemons are killed when "
             "regular processes are done")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("worker", e.host_by_name("Boivin"), worker)
    s4u.Actor.create("daemon", e.host_by_name("Tremblay"), my_daemon)
    e.run()


if __name__ == "__main__":
    main()
