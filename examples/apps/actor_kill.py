"""s4u-actor-kill replica (reference
examples/s4u/actor-kill/s4u-actor-kill.cpp): kill a resumed-then-working
actor, kill an already-dead actor (no-op), kill a fresh actor before it
runs (on_exit still fires), kill_all, and self-exit."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_actor_kill")


def victim_a():
    s4u.this_actor.on_exit(lambda failed: LOG.info("I have been killed!"))
    LOG.info("Hello!")
    LOG.info("Suspending myself")
    s4u.this_actor.suspend()
    LOG.info("OK, OK. Let's work")
    s4u.this_actor.execute(1e9)
    LOG.info("Bye!")


def victim_b():
    LOG.info("Terminate before being killed")


def killer():
    e = s4u.Engine.get_instance()
    LOG.info("Hello!")
    victim_a_ref = s4u.Actor.create("victim A",
                                    e.host_by_name("Fafard"), victim_a)
    victim_b_ref = s4u.Actor.create("victim B",
                                    e.host_by_name("Jupiter"), victim_b)
    s4u.this_actor.sleep_for(10)

    LOG.info("Resume the victim A")
    victim_a_ref.resume()
    s4u.this_actor.sleep_for(2)

    LOG.info("Kill the victim A")
    s4u.Actor.by_pid(victim_a_ref.get_pid()).kill()

    s4u.this_actor.sleep_for(1)

    LOG.info("Kill victimB, even if it's already dead")
    victim_b_ref.kill()

    s4u.this_actor.sleep_for(1)

    LOG.info("Start a new actor, and kill it right away")
    victim_c = s4u.Actor.create("victim C", e.host_by_name("Jupiter"),
                                victim_a)
    victim_c.kill()

    s4u.this_actor.sleep_for(1)

    LOG.info("Killing everybody but myself")
    s4u.Actor.kill_all()

    LOG.info("OK, goodbye now. I commit a suicide.")
    s4u.this_actor.exit()

    LOG.info("This line never gets displayed: I'm already dead since the "
             "previous line.")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("killer", e.host_by_name("Tremblay"), killer)
    e.run()


if __name__ == "__main__":
    main()
