"""s4u-synchro-barrier replica (reference
examples/s4u/synchro-barrier/s4u-synchro-barrier.cpp)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def worker(barrier):
    LOG.info("Waiting on the barrier")
    barrier.wait()
    LOG.info("Bye")


def master(process_count):
    e = s4u.Engine.get_instance()
    barrier = s4u.Barrier(process_count)
    LOG.info("Spawning %d workers", process_count - 1)
    for _ in range(process_count - 1):
        s4u.Actor.create("worker", e.host_by_name("Jupiter"),
                         lambda: worker(barrier))
    LOG.info("Waiting on the barrier")
    barrier.wait()
    LOG.info("Bye")


def main():
    e = s4u.Engine(sys.argv)
    process_count = int(sys.argv[1])
    e.load_platform("/root/reference/examples/platforms/two_hosts.xml")
    s4u.Actor.create("master", e.host_by_name("Tremblay"),
                     lambda: master(process_count))
    e.run()


if __name__ == "__main__":
    main()
