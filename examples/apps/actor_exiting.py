"""s4u-actor-exiting replica (reference
examples/s4u/actor-exiting/s4u-actor-exiting.cpp): on_exit vs the
engine-wide on_termination / on_destruction signals."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_actor_exiting")


def actor_a():
    s4u.this_actor.on_exit(lambda failed: LOG.info("I stop now"))
    s4u.this_actor.execute(1e9)


def actor_b():
    s4u.this_actor.execute(2e9)


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.on_termination.connect(
        lambda actor: LOG.info("Actor %s terminates now", actor.name))
    s4u.Actor.on_destruction.connect(
        lambda actor: LOG.info("Actor %s gets destroyed now", actor.name))
    s4u.Actor.create("A", e.host_by_name("Tremblay"), actor_a)
    s4u.Actor.create("B", e.host_by_name("Fafard"), actor_b)
    e.run()


if __name__ == "__main__":
    main()
