"""s4u-io-file-system replica (reference
examples/s4u/io-file-system/s4u-io-file-system.cpp): file create/read/
write/move/unlink through the file_system plugin, storage usage info."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.plugins import file_system
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def show_info(mounts):
    LOG.info("Storage info on %s:" % s4u.this_actor.get_host().name)
    for mountpoint, storage in mounts.items():
        used = file_system.storage_used_size(storage)
        total = int(storage.size)
        LOG.info("    %s (%s) Used: %d; Free: %d; Total: %d."
                 % (storage.name, mountpoint, used, total - used, total))


def host():
    e = s4u.Engine.get_instance()
    mounts = file_system._mounts_of(s4u.this_actor.get_host(), e.pimpl)

    show_info(mounts)

    filename = "/home/tmp/data.txt"
    f = file_system.File(filename)

    write = f.write(200000)
    LOG.info("Create a %d bytes file named '%s' on /sd1"
             % (write, filename))

    show_info(mounts)

    file_size = f.get_size()
    f.seek(0)
    read = f.read(file_size)
    LOG.info("Read %d bytes on %s" % (read, filename))

    write = f.write(100000)
    LOG.info("Write %d bytes on %s" % (write, filename))

    storage = next(st for st in mounts.values() if st.name == "Disk4")

    newpath = "/home/tmp/simgrid.readme"
    LOG.info("Move '%s' to '%s'" % (filename, newpath))
    f.move(newpath)

    f.userdata = "777"
    LOG.info("User data attached to the file: %s" % f.userdata)

    LOG.info("Get/set data for storage element: %s" % storage.name)
    LOG.info("    Uninitialized storage data: '%s'"
             % (getattr(storage, "userdata", None) or "(null)"))
    storage.userdata = "Some user data"
    LOG.info("    Set and get data: '%s'" % storage.userdata)

    LOG.info("Unlink file: '%s'" % newpath)
    f.unlink()

    show_info(mounts)


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    file_system.file_system_plugin_init(e)
    s4u.Actor.create("host", e.host_by_name("denise"), host)
    e.run()


if __name__ == "__main__":
    main()
