"""s4u-actor-create replica (reference
examples/s4u/actor-create/s4u-actor-create.cpp): the three actor
creation styles — direct create, parameterized, and deployment-file."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_actor_create")


def receiver(mailbox_name):
    mailbox = s4u.Mailbox.by_name(mailbox_name)
    LOG.info("Hello s4u, I'm ready to get any message you'd want on %s",
             mailbox.name)
    msg1 = mailbox.get()
    msg2 = mailbox.get()
    msg3 = mailbox.get()
    LOG.info("I received '%s', '%s' and '%s'", msg1, msg2, msg3)
    LOG.info("I'm done. See you.")


def forwarder(in_name, out_name):
    in_box = s4u.Mailbox.by_name(in_name)
    out_box = s4u.Mailbox.by_name(out_name)
    msg = in_box.get()
    LOG.info("Forward '%s'.", msg)
    out_box.put(msg, len(msg))


def sender(msg="GaBuZoMeu", mbox="mb42"):
    LOG.info("Hello s4u, I have something to send")
    s4u.Mailbox.by_name(mbox).put(msg, len(msg))
    LOG.info("I'm done. See you.")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform("/root/reference/examples/platforms/"
                    "small_platform.xml")
    s4u.Actor.create("receiver", e.host_by_name("Fafard"),
                     lambda: receiver("mb42"))
    s4u.Actor.create("sender1", e.host_by_name("Tremblay"), sender)
    s4u.Actor.create("sender2", e.host_by_name("Jupiter"),
                     lambda: sender("GloubiBoulga"))
    e.register_function("sender", sender)
    e.register_function("forwarder", forwarder)
    e.load_deployment("/root/reference/examples/s4u/actor-create/"
                      "s4u-actor-create_d.xml")
    e.run()


if __name__ == "__main__":
    main()
