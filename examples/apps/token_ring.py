"""s4u-app-token-ring replica (reference
examples/s4u/app-token-ring/s4u-app-token-ring.cpp): a 1MB token
travels the ring of all hosts; the reference tesh pins every hop's
timestamp."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_app_token_ring")
TOKEN_SIZE = 1_000_000


def relay(n_hosts):
    rank = int(s4u.this_actor.get_name())
    my_mailbox = s4u.Mailbox.by_name(str(rank))
    neighbor = s4u.Mailbox.by_name(
        "0" if rank + 1 == n_hosts else str(rank + 1))
    if rank == 0:
        LOG.info('Host "%u" send \'Token\' to Host "%s"'
                 .replace("%u", str(rank)).replace("%s", neighbor.name))
        neighbor.put("Token", TOKEN_SIZE)
        res = my_mailbox.get()
        LOG.info(f'Host "{rank}" received "{res}"')
    else:
        res = my_mailbox.get()
        LOG.info(f'Host "{rank}" received "{res}"')
        LOG.info(f'Host "{rank}" send \'Token\' to Host "{neighbor.name}"')
        neighbor.put(res, TOKEN_SIZE)


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    hosts = e.get_all_hosts()
    LOG.info("Number of hosts '%d'" % len(hosts))
    for i, host in enumerate(hosts):
        s4u.Actor.create(str(i), host, relay, len(hosts))
    e.run()
    LOG.info("Simulation time %g" % e.clock)


if __name__ == "__main__":
    main()
