"""Fault injection demo: a seeded FaultCampaign kills workers, an
Injector cuts a link, senders survive with RetryPolicy backoff, and the
fault_stats plugin reports what happened.  Deterministic: the same seed
prints the same report every run."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.faults import FaultCampaign, Injector
from simgrid_tpu.plugins import fault_stats
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("faults_demo")

PLATFORM = """<?xml version='1.0'?>
<platform version="4.1">
  <zone id="world" routing="Full">
    <host id="master" speed="100Mf"/>
    <host id="worker" speed="100Mf"/>
    <link id="wire" bandwidth="1MBps" latency="100us"/>
    <route src="master" dst="worker"><link_ctn id="wire"/></route>
  </zone>
</platform>
"""


def sender():
    mb = s4u.Mailbox.by_name("jobs")
    policy = s4u.RetryPolicy(max_attempts=6, base_delay=1.0,
                             multiplier=2.0, jitter=0.25, seed=1)
    for job in range(4):
        attempts = s4u.Comm.send_with_retry(mb, f"job-{job}", 1e6,
                                            policy=policy, timeout=5.0)
        LOG.info("job-%d delivered after %d attempt(s)" % (job, attempts))
    s4u.Comm.send_with_retry(mb, "stop", 1, policy=policy, timeout=5.0)


def worker():
    mb = s4u.Mailbox.by_name("jobs")
    while True:
        payload = mb.get()
        if payload == "stop":
            break
        s4u.this_actor.execute(5e7)
        LOG.info("processed %s" % payload)


def main():
    e = s4u.Engine(sys.argv)
    plat = os.path.join(os.path.dirname(__file__) or ".",
                        "_fault_demo_platform.xml")
    with open(plat, "w") as f:
        f.write(PLATFORM)
    try:
        e.load_platform(plat)
    finally:
        os.remove(plat)
    stats = fault_stats.fault_stats_plugin_init(e)

    # seeded campaign: the worker host fails/recovers repeatedly
    campaign = FaultCampaign(seed=42, horizon=60.0)
    campaign.add_host("worker", mtbf=3.0, mttr=1.5)
    campaign.schedule(e)

    # scripted one-off: the wire drops to 25% capacity for a while
    inj = Injector(e)
    inj.at(3.0).link_degrade("wire", 0.25)
    inj.at(10.0).link_degrade("wire", 1.0)

    s4u.Actor.create("sender", e.host_by_name("master"), sender)
    s4u.Actor.create("worker", e.host_by_name("worker"),
                     worker).set_auto_restart(True)
    e.run()

    LOG.info("simulation ended at t=%g" % e.clock)
    for key, value in sorted(stats.summary().items()):
        LOG.info("  %s: %s" % (key, value))


if __name__ == "__main__":
    main()
