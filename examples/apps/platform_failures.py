"""s4u-platform-failures replica (reference
examples/s4u/platform-failures/s4u-platform-failures.cpp): state
profiles turn hosts/links off and on; RESTART actors come back; comms
fail or time out and the master keeps going."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.exceptions import NetworkFailureException, TimeoutException
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def master(*args):
    number_of_tasks = int(args[0])
    comp_size = float(args[1])
    comm_size = float(args[2])
    workers_count = int(args[3])

    LOG.info("Got %d workers and %d tasks to process"
             % (workers_count, number_of_tasks))

    for i in range(number_of_tasks):
        mailbox = s4u.Mailbox.by_name("worker-%d" % (i % workers_count))
        try:
            LOG.info("Send a message to %s" % mailbox.name)
            mailbox.put(comp_size, comm_size, timeout=10.0)
            LOG.info("Send to %s completed" % mailbox.name)
        except TimeoutException:
            LOG.info("Mmh. Got timeouted while speaking to '%s'. "
                     "Nevermind. Let's keep going!" % mailbox.name)
        except NetworkFailureException:
            LOG.info("Mmh. The communication with '%s' failed. "
                     "Nevermind. Let's keep going!" % mailbox.name)

    LOG.info("All tasks have been dispatched. Let's tell everybody the "
             "computation is over.")
    for i in range(workers_count):
        mailbox = s4u.Mailbox.by_name("worker-%d" % i)
        try:
            mailbox.put(-1.0, 0, timeout=1.0)
        except TimeoutException:
            LOG.info("Mmh. Got timeouted while speaking to '%s'. "
                     "Nevermind. Let's keep going!" % mailbox.name)
        except NetworkFailureException:
            LOG.info("Mmh. Something went wrong with '%s'. Nevermind. "
                     "Let's keep going!" % mailbox.name)

    LOG.info("Goodbye now!")


def worker(*args):
    wid = int(args[0])
    mailbox = s4u.Mailbox.by_name("worker-%d" % wid)
    while True:
        try:
            LOG.info("Waiting a message on %s" % mailbox.name)
            comp_size = mailbox.get()
            if comp_size < 0:
                LOG.info("I'm done. See you!")
                break
            LOG.info("Start execution...")
            s4u.this_actor.execute(comp_size)
            LOG.info("Execution complete.")
        except NetworkFailureException:
            LOG.info("Mmh. Something went wrong. Nevermind. "
                     "Let's keep going!")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    e.register_function("master", master)
    e.register_function("worker", worker)
    e.load_deployment(sys.argv[2])
    e.run()
    LOG.info("Simulation time %g" % e.get_clock())


if __name__ == "__main__":
    main()
