"""s4u-async-waitall replica (reference
examples/s4u/async-waitall/s4u-async-waitall.cpp): the sender launches
every put_async up front and waits for all of them in one call; the
reference tesh pins the arrival interleaving."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_async_waitall")


def sender(*args):
    messages_count, msg_size, receivers_count = \
        int(args[0]), float(args[1]), int(args[2])
    mboxes = [s4u.Mailbox.by_name(f"receiver-{i}")
              for i in range(receivers_count)]
    pending = []
    for i in range(messages_count):
        content = f"Message {i}"
        LOG.info(f"Send '{content}' to '{mboxes[i % receivers_count].name}'")
        pending.append(mboxes[i % receivers_count].put_async(content,
                                                             msg_size))
    for i in range(receivers_count):
        LOG.info(f"Send 'finalize' to 'receiver-{i}'")
        pending.append(mboxes[i].put_async("finalize", 0))
    LOG.info("Done dispatching all messages")
    s4u.Comm.wait_all(pending)
    LOG.info("Goodbye now!")


def receiver(*args):
    mbox = s4u.Mailbox.by_name(f"receiver-{args[0]}")
    LOG.info("Wait for my first message")
    while True:
        received = mbox.get()
        LOG.info(f"I got a '{received}'.")
        if received == "finalize":
            break


def main():
    e = s4u.Engine(sys.argv)
    e.register_function("sender", sender)
    e.register_function("receiver", receiver)
    e.load_platform(sys.argv[1])
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
