"""s4u-dht-kademlia replica (reference
examples/s4u/dht-kademlia/: node.cpp, routing_table.cpp, answer.cpp,
s4u-dht-kademlia.cpp): the Kademlia DHT — XOR-metric routing tables,
iterative FIND_NODE lookups with ALPHA parallelism, periodic random
lookups until a deadline (BASELINE config #5 family: churny DHT
fleet)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("kademlia")

FIND_NODE_TIMEOUT = 10.0
FIND_NODE_GLOBAL_TIMEOUT = 50.0
KADEMLIA_ALPHA = 3
BUCKET_SIZE = 20
IDENTIFIER_SIZE = 32
RANDOM_LOOKUP_INTERVAL = 100.0
MAX_STEPS = 10
JOIN_BUCKETS_QUERIES = 5
RANDOM_LOOKUP_NODE = 0


def get_id_in_prefix(node_id, prefix):
    if prefix == 0:
        return 0
    return (1 << (prefix - 1)) ^ node_id


def get_node_prefix(node_id, nb_bits):
    size = 32
    for j in range(size):
        if (node_id >> (size - 1 - j)) & 0x1:
            return nb_bits - j
    return 0


class Answer:
    """Sorted closest-node list for one destination (answer.cpp)."""

    def __init__(self, destination_id):
        self.destination_id = destination_id
        self.nodes = []        # (id, distance) pairs

    def size(self):
        return len(self.nodes)

    def add_bucket(self, bucket):
        for nid in bucket.nodes:
            self.nodes.append((nid, nid ^ self.destination_id))

    def merge(self, other):
        if other is self:
            return 0
        added = 0
        for contact in other.nodes:
            if contact not in self.nodes:
                self.nodes.append(contact)
                added += 1
        self.nodes.sort(key=lambda c: c[1])
        self.trim()
        return added

    def trim(self):
        del self.nodes[BUCKET_SIZE:]

    def destination_found(self):
        return bool(self.nodes) and self.nodes[0][1] == 0


class Bucket:
    def __init__(self, bucket_id):
        self.id = bucket_id
        self.nodes = []        # most-recent first


class RoutingTable:
    def __init__(self, node_id):
        self.id = node_id
        self.buckets = [Bucket(i) for i in range(IDENTIFIER_SIZE + 1)]

    def find_bucket(self, node_id):
        prefix = get_node_prefix(self.id ^ node_id, IDENTIFIER_SIZE)
        return self.buckets[prefix]


class Message:
    def __init__(self, sender_id, destination_id, answer, answer_to,
                 issuer_host_name):
        self.sender_id = sender_id
        self.destination_id = destination_id
        self.answer = answer
        self.answer_to = answer_to       # mailbox NAME to reply to
        self.issuer_host_name = issuer_host_name


class Node:
    def __init__(self, node_id):
        self.id = node_id
        self.table = RoutingTable(node_id)
        self.find_node_success = 0
        self.find_node_failed = 0
        self.receive_comm = None

    # -- routing table ------------------------------------------------
    def routing_table_update(self, node_id):
        bucket = self.table.find_bucket(node_id)
        if node_id not in bucket.nodes:
            if len(bucket.nodes) >= BUCKET_SIZE:
                bucket.nodes.pop()
            bucket.nodes.insert(0, node_id)
        else:
            bucket.nodes.remove(node_id)
            bucket.nodes.insert(0, node_id)

    def find_closest(self, destination_id):
        answer = Answer(destination_id)
        bucket = self.table.find_bucket(destination_id)
        bucket_id = bucket.id
        answer.add_bucket(bucket)
        i = 1
        while answer.size() < BUCKET_SIZE and \
                (bucket_id - i > 0 or bucket_id + i < IDENTIFIER_SIZE):
            if bucket_id - i >= 0:
                answer.add_bucket(self.table.buckets[bucket_id - i])
            if bucket_id + i <= IDENTIFIER_SIZE:
                answer.add_bucket(self.table.buckets[bucket_id + i])
            i += 1
        answer.nodes.sort(key=lambda c: c[1])
        answer.trim()
        return answer

    # -- messaging ----------------------------------------------------
    def send_find_node(self, node_id, destination):
        mailbox = s4u.Mailbox.by_name(str(node_id))
        msg = Message(self.id, destination, None, str(self.id),
                      s4u.this_actor.get_host().name)
        mailbox.put_init(msg, 1).detach()

    def send_find_node_to_best(self, node_list):
        i = j = 0
        destination = node_list.destination_id
        for node_to_query, _dist in node_list.nodes:
            if node_to_query != self.id:
                self.send_find_node(node_to_query, destination)
                j += 1
            i += 1
            if j == KADEMLIA_ALPHA:
                break
        return i

    def handle_find_node(self, msg):
        self.routing_table_update(msg.sender_id)
        answer = Message(self.id, msg.destination_id,
                         self.find_closest(msg.destination_id),
                         str(self.id),
                         s4u.this_actor.get_host().name)
        s4u.Mailbox.by_name(msg.answer_to).put_init(answer, 1).detach()

    # -- lookups ------------------------------------------------------
    def find_node(self, id_to_find, count_in_stats):
        e = s4u.Engine.get_instance()
        destination_found = False
        nodes_added = 0
        global_timeout = e.clock + FIND_NODE_GLOBAL_TIMEOUT
        steps = 0
        node_list = self.find_closest(id_to_find)
        mailbox = s4u.Mailbox.by_name(str(self.id))
        while True:
            answers = 0
            queries = self.send_find_node_to_best(node_list)
            nodes_added = 0
            timeout = e.clock + FIND_NODE_TIMEOUT
            steps += 1
            time_beginreceive = e.clock
            while True:
                if self.receive_comm is None:
                    self.receive_comm = mailbox.get_async()
                if self.receive_comm.test():
                    msg = self.receive_comm.get_payload()
                    if msg.answer is not None and \
                            msg.answer.destination_id == id_to_find:
                        self.routing_table_update(msg.sender_id)
                        for contact, _d in node_list.nodes:
                            self.routing_table_update(contact)
                        answers += 1
                        nodes_added = node_list.merge(msg.answer)
                    elif msg.answer is not None:
                        self.routing_table_update(msg.sender_id)
                    else:
                        self.handle_find_node(msg)
                        timeout += e.clock - time_beginreceive
                        time_beginreceive = e.clock
                    self.receive_comm = None
                else:
                    s4u.this_actor.sleep_for(1)
                if not (e.clock < timeout and answers < queries):
                    break
            destination_found = node_list.destination_found()
            if not (not destination_found
                    and (nodes_added > 0 or answers == 0)
                    and e.clock < global_timeout and steps < MAX_STEPS):
                break
        if destination_found:
            if count_in_stats:
                self.find_node_success += 1
            self.routing_table_update(id_to_find)
        elif count_in_stats:
            self.find_node_failed += 1
        return destination_found

    def random_lookup(self):
        self.find_node(RANDOM_LOOKUP_NODE, True)

    def join(self, known_id):
        e = s4u.Engine.get_instance()
        got_answer = False
        self.routing_table_update(self.id)
        self.routing_table_update(known_id)
        self.send_find_node(known_id, self.id)
        mailbox = s4u.Mailbox.by_name(str(self.id))
        while not got_answer:
            if self.receive_comm is None:
                self.receive_comm = mailbox.get_async()
            if self.receive_comm.test():
                msg = self.receive_comm.get_payload()
                if msg.answer is not None:
                    got_answer = True
                    for contact, _d in msg.answer.nodes:
                        self.routing_table_update(contact)
                else:
                    self.handle_find_node(msg)
                self.receive_comm = None
            else:
                s4u.this_actor.sleep_for(1)

        bucket_id = self.table.find_bucket(known_id).id
        i = 0
        while (bucket_id > i or bucket_id + i <= IDENTIFIER_SIZE) and \
                i < JOIN_BUCKETS_QUERIES:
            if bucket_id > i:
                self.find_node(get_id_in_prefix(self.id, bucket_id - i),
                               False)
            if bucket_id + i <= IDENTIFIER_SIZE:
                self.find_node(get_id_in_prefix(self.id, bucket_id + i),
                               False)
            i += 1
        return got_answer


def node(*args):
    e = s4u.Engine.get_instance()
    join_success = True
    node_id = int(args[0], 0)
    n = Node(node_id)
    if len(args) == 3:
        LOG.info("Hi, I'm going to join the network with id %u", n.id)
        known_id = int(args[1], 0)
        join_success = n.join(known_id)
        deadline = float(args[2])
    else:
        deadline = float(args[1])
        LOG.info("Hi, I'm going to create the network with id %u", n.id)
        n.routing_table_update(n.id)

    if join_success:
        next_lookup_time = e.clock + RANDOM_LOOKUP_INTERVAL
        mailbox = s4u.Mailbox.by_name(str(n.id))
        while e.clock < deadline:
            if n.receive_comm is None:
                n.receive_comm = mailbox.get_async()
            if n.receive_comm.test():
                msg = n.receive_comm.get_payload()
                if msg is not None:
                    n.handle_find_node(msg)
                    n.receive_comm = None
                else:
                    s4u.this_actor.sleep_for(1)
            elif e.clock >= next_lookup_time:
                n.random_lookup()
                next_lookup_time += RANDOM_LOOKUP_INTERVAL
            else:
                s4u.this_actor.sleep_for(1)
    else:
        LOG.info("I couldn't join the network :(")
    LOG.info("%u/%u FIND_NODE have succeeded", n.find_node_success,
             n.find_node_success + n.find_node_failed)


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    e.register_function("node", node)
    e.load_deployment(sys.argv[2])
    e.run()
    LOG.info("Simulated time: %g", e.clock)


if __name__ == "__main__":
    main()
