"""s4u-synchro-mutex replica (reference
examples/s4u/synchro-mutex/s4u-synchro-mutex.cpp): regular lock/unlock
vs context-manager locking (the lock_guard analogue)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")

NB_ACTOR = 6
result = [0]


def worker(mutex):
    mutex.lock()
    LOG.info("Hello s4u, I'm ready to compute after a regular lock")
    result[0] += 1
    LOG.info("I'm done, good bye")
    mutex.unlock()


def worker_lock_guard(mutex):
    with mutex:
        LOG.info("Hello s4u, I'm ready to compute after a lock_guard")
        result[0] += 1
        LOG.info("I'm done, good bye")


def master():
    e = s4u.Engine.get_instance()
    mutex = s4u.Mutex()
    for i in range(NB_ACTOR * 2):
        if i % 2 == 0:
            s4u.Actor.create("worker", e.host_by_name("Jupiter"),
                             lambda m=mutex: worker_lock_guard(m))
        else:
            s4u.Actor.create("worker", e.host_by_name("Tremblay"),
                             lambda m=mutex: worker(m))
    s4u.this_actor.sleep_for(10)
    LOG.info("Results is -> %d", result[0])


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform("/root/reference/examples/platforms/two_hosts.xml")
    s4u.Actor.create("main", e.host_by_name("Tremblay"), master)
    e.run()


if __name__ == "__main__":
    main()
