"""s4u-async-ready replica (reference
examples/s4u/async-ready/s4u-async-ready.cpp): permanent receivers +
Mailbox.ready() polling instead of blocking waits."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_async_ready")


def peer(my_id, messages_count, msg_size, peers_count):
    my_id, messages_count, peers_count = (int(my_id),
                                          int(messages_count),
                                          int(peers_count))
    msg_size = float(msg_size)
    my_mbox = s4u.Mailbox.by_name(f"peer-{my_id}")
    my_mbox.set_receiver(s4u.Actor.self())

    pending = []
    for i in range(messages_count):
        for peer_id in range(peers_count):
            if peer_id != my_id:
                name = f"peer-{peer_id}"
                msg = f"Message {i} from peer {my_id}"
                LOG.info("Send '%s' to '%s'", msg, name)
                pending.append(s4u.Mailbox.by_name(name).put_async(
                    msg, msg_size))
    for peer_id in range(peers_count):
        if peer_id != my_id:
            pending.append(s4u.Mailbox.by_name(
                f"peer-{peer_id}").put_async("finalize", msg_size))
            LOG.info("Send 'finalize' to 'peer-%d'", peer_id)
    LOG.info("Done dispatching all messages")

    pending_finalize = peers_count - 1
    while pending_finalize > 0:
        if my_mbox.ready():
            received = my_mbox.get()
            LOG.info("I got a '%s'.", received)
            if received == "finalize":
                pending_finalize -= 1
        else:
            LOG.info("Nothing ready to consume yet, I better sleep "
                     "for a while")
            s4u.this_actor.sleep_for(.01)

    LOG.info("I'm done, just waiting for my peers to receive the "
             "messages before exiting")
    s4u.Comm.wait_all(pending)
    LOG.info("Goodbye now!")


def main():
    e = s4u.Engine(sys.argv)
    e.register_function("peer", peer)
    e.load_platform(sys.argv[1])
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
