"""s4u-energy-exec replica (reference
examples/s4u/energy-exec/s4u-energy-exec.cpp): host_energy plugin with
pstate switches and a powered-off host."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.plugins import host_energy
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def fmt0e(x):
    """%.0E with C's two-digit exponent collapsed like glibc prints."""
    return "%.0E" % x


def dvfs():
    e = s4u.Engine.get_instance()
    host1 = e.host_by_name("MyHost1")
    host2 = e.host_by_name("MyHost2")

    LOG.info("Energetic profile: %s" % host1.properties["watt_per_state"])
    LOG.info("Initial peak speed=%s flop/s; Energy dissipated =%s J"
             % (fmt0e(host1.get_speed()),
                fmt0e(host_energy.get_consumed_energy(host1))))

    start = s4u.Engine.get_clock()
    LOG.info("Sleep for 10 seconds")
    s4u.this_actor.sleep_for(10)
    LOG.info("Done sleeping (duration: %.2f s). Current peak speed=%s; "
             "Energy dissipated=%.2f J"
             % (s4u.Engine.get_clock() - start, fmt0e(host1.get_speed()),
                host_energy.get_consumed_energy(host1)))

    start = s4u.Engine.get_clock()
    flop_amount = 100e6
    LOG.info("Run a task of %s flops" % fmt0e(flop_amount))
    s4u.this_actor.execute(flop_amount)
    LOG.info("Task done (duration: %.2f s). Current peak speed=%s flop/s;"
             " Current consumption: from %.0fW to %.0fW depending on load"
             "; Energy dissipated=%.0f J"
             % (s4u.Engine.get_clock() - start, fmt0e(host1.get_speed()),
                host_energy.get_watt_min_at(host1, host1.get_pstate()),
                host_energy.get_watt_max_at(host1, host1.get_pstate()),
                host_energy.get_consumed_energy(host1)))

    pstate = 2
    host1.set_pstate(pstate)
    LOG.info("========= Requesting pstate %d (speed should be of %s "
             "flop/s and is of %s flop/s)"
             % (pstate, fmt0e(host1.get_pstate_speed(pstate)),
                fmt0e(host1.get_speed())))

    start = s4u.Engine.get_clock()
    LOG.info("Run a task of %s flops" % fmt0e(flop_amount))
    s4u.this_actor.execute(flop_amount)
    LOG.info("Task done (duration: %.2f s). Current peak speed=%s flop/s;"
             " Energy dissipated=%.0f J"
             % (s4u.Engine.get_clock() - start, fmt0e(host1.get_speed()),
                host_energy.get_consumed_energy(host1)))

    start = s4u.Engine.get_clock()
    LOG.info("Sleep for 4 seconds")
    s4u.this_actor.sleep_for(4)
    LOG.info("Done sleeping (duration: %.2f s). Current peak speed=%s "
             "flop/s; Energy dissipated=%.0f J"
             % (s4u.Engine.get_clock() - start, fmt0e(host1.get_speed()),
                host_energy.get_consumed_energy(host1)))

    LOG.info("Turning MyHost2 off, and sleeping another 10 seconds. "
             "MyHost2 dissipated %.0f J so far."
             % host_energy.get_consumed_energy(host2))
    host2.turn_off()
    start = s4u.Engine.get_clock()
    s4u.this_actor.sleep_for(10)
    LOG.info("Done sleeping (duration: %.2f s). Current peak speed=%s "
             "flop/s; Energy dissipated=%.0f J"
             % (s4u.Engine.get_clock() - start, fmt0e(host1.get_speed()),
                host_energy.get_consumed_energy(host1)))


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    host_energy.host_energy_plugin_init(e)
    s4u.Actor.create("dvfs_test", e.host_by_name("MyHost1"), dvfs)
    e.run()
    LOG.info("End of simulation.")


if __name__ == "__main__":
    main()
