"""s4u-synchro-semaphore replica (reference
examples/s4u/synchro-semaphore/s4u-synchro-semaphore.cpp): a
producer/consumer pair over a 1-slot buffer guarded by two semaphores —
pins the acquire/release wake ordering."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")

state = {"buffer": ""}
sem_empty = None
sem_full = None


def producer(items):
    for s in items:
        sem_empty.acquire()
        LOG.info("Pushing '%s'" % s)
        state["buffer"] = s
        sem_full.release()
    LOG.info("Bye!")


def consumer():
    while True:
        sem_full.acquire()
        s = state["buffer"]
        LOG.info("Receiving '%s'" % s)
        sem_empty.release()
        if s == "":
            break
    LOG.info("Bye!")


def main():
    global sem_empty, sem_full
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    sem_empty = s4u.Semaphore(1)
    sem_full = s4u.Semaphore(0)
    s4u.Actor.create("producer", e.host_by_name("Tremblay"), producer,
                     ["one", "two", "three", ""])
    s4u.Actor.create("consumer", e.host_by_name("Jupiter"), consumer)
    e.run()


if __name__ == "__main__":
    main()
