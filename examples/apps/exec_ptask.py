"""s4u-exec-ptask replica (reference
examples/s4u/exec-ptask/s4u-exec-ptask.cpp): parallel tasks under the
L07 model, with timeout and uncategorized resource tracing."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.exceptions import TimeoutException
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_ptask")


def runner():
    e = s4u.Engine.get_instance()
    hosts = e.get_all_hosts()
    n = len(hosts)

    LOG.info("First, build a classical parallel task, with 1 Gflop to "
             "execute on each node, and 10MB to exchange between each "
             "pair")
    computation_amounts = [1e9] * n
    communication_amounts = [0.0] * (n * n)
    for i in range(n):
        for j in range(i + 1, n):
            communication_amounts[i * n + j] = 1e7
    s4u.this_actor.parallel_execute(hosts, computation_amounts,
                                    communication_amounts)

    LOG.info("We can do the same with a timeout of 10 seconds enabled.")
    computation_amounts = [1e9] * n
    communication_amounts = [0.0] * (n * n)
    for i in range(n):
        for j in range(i + 1, n):
            communication_amounts[i * n + j] = 1e7
    try:
        s4u.this_actor.parallel_execute(hosts, computation_amounts,
                                        communication_amounts, 10.0)
        raise AssertionError("Woops, this did not timeout as expected..."
                             " Please report that bug.")
    except TimeoutException:
        LOG.info("Caught the expected timeout exception.")

    LOG.info("Then, build a parallel task involving only computations "
             "(of different amounts) and no communication")
    computation_amounts = [3e8, 6e8, 1e9]
    s4u.this_actor.parallel_execute(hosts, computation_amounts, [])

    LOG.info("Then, build a parallel task with no computation nor "
             "communication (synchro only)")
    s4u.this_actor.parallel_execute(hosts, [], [])

    LOG.info("Goodbye now!")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("test", e.host_by_name("MyHost1"), runner)
    e.run()
    LOG.info("Simulation done.")


if __name__ == "__main__":
    main()
