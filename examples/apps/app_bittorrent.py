"""s4u-app-bittorrent replica (reference
examples/s4u/app-bittorrent/: s4u-bittorrent.cpp, s4u-tracker.cpp,
s4u-peer.cpp): the BitTorrent protocol — tracker-mediated peer
discovery, handshake/bitfield exchange, choke/unchoke rounds
(optimistic + fastest-download policies), rarest-first and end-game
piece selection (BASELINE config #5 family: churn-heavy fleet)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog
from simgrid_tpu.utils.rngstream import RngStream
from simgrid_tpu.exceptions import TimeoutException

TRACKER_LOG = xlog.get_category("s4u_bt_tracker")
PEER_LOG = xlog.get_category("s4u_bt_peer")

TRACKER_MAILBOX = "tracker_mailbox"
MAXIMUM_PEERS = 50
TRACKER_QUERY_INTERVAL = 1000
TRACKER_COMM_SIZE = 1
GET_PEERS_TIMEOUT = 10000.0
UPDATE_CHOKED_INTERVAL = 30

MESSAGE_SIZES = dict(HANDSHAKE=68, CHOKE=5, UNCHOKE=5, INTERESTED=5,
                     NOTINTERESTED=5, HAVE=9, BITFIELD=5, REQUEST=17,
                     PIECE=13, CANCEL=17)

FILE_PIECES = 10
PIECES_BLOCKS = 5
BLOCK_SIZE = 16384
BLOCKS_REQUESTED = 2
ENABLE_END_GAME_MODE = True
SLEEP_DURATION = 1.0

#: per-host RngStreams, created in host order like the reference's
#: extension install loop (s4u-bittorrent.cpp:24-26)
_HOST_STREAMS = {}


def install_streams(engine):
    for host in engine.get_all_hosts():
        _HOST_STREAMS[host.name] = RngStream(f"RngSream<{host.name}>")


def my_stream():
    return _HOST_STREAMS[s4u.this_actor.get_host().name]


class Message:
    def __init__(self, type_, peer_id, return_mailbox, bitfield=0,
                 piece=0, block_index=0, block_length=0):
        self.type = type_
        self.peer_id = peer_id
        self.return_mailbox = return_mailbox    # mailbox NAME
        self.bitfield = bitfield
        self.piece = piece
        self.block_index = block_index
        self.block_length = block_length


def tracker(deadline):
    deadline = float(deadline)
    stream = my_stream()
    e = s4u.Engine.get_instance()
    mailbox = s4u.Mailbox.by_name(TRACKER_MAILBOX)
    known_peers = []
    TRACKER_LOG.info("Tracker launched.")
    comm = None
    while e.clock < deadline:
        if comm is None:
            comm = mailbox.get_async()
        if comm.test():
            peer_id, return_mailbox = comm.get_payload()
            if peer_id not in known_peers:
                known_peers.append(peer_id)
            answer = set()
            max_tries = min(MAXIMUM_PEERS, len(known_peers))
            tried = 0
            while tried < max_tries:
                while True:
                    nxt = known_peers[stream.rand_int(
                        0, len(known_peers) - 1)]
                    if nxt not in answer:
                        break
                answer.add(nxt)
                tried += 1
            s4u.Mailbox.by_name(return_mailbox).put_init(
                sorted(answer), TRACKER_COMM_SIZE).detach()
            comm = None
        else:
            s4u.this_actor.sleep_for(1)
    TRACKER_LOG.info("Tracker is leaving")


class Connection:
    def __init__(self, peer_id):
        self.id = peer_id
        self.mailbox = str(peer_id)
        self.bitfield = 0
        self.peer_speed = 0.0
        self.last_unchoke = 0.0
        self.current_piece = -1
        self.am_interested = False
        self.interested = False
        self.choked_upload = True
        self.choked_download = True

    def add_speed_value(self, speed):
        self.peer_speed = self.peer_speed * 0.6 + speed * 0.4

    def has_piece(self, piece):
        return bool(self.bitfield & (1 << piece))


class Peer:
    def __init__(self, args):
        self.id = int(args[0])
        self.mailbox = s4u.Mailbox.by_name(str(self.id))
        self.deadline = float(args[1])
        self.stream = my_stream()
        self.bitfield = 0
        self.bitfield_blocks = 0
        if len(args) == 3 and args[2] == "1":
            self.bitfield = (1 << FILE_PIECES) - 1
            self.bitfield_blocks = (1 << (FILE_PIECES *
                                          PIECES_BLOCKS)) - 1
        self.pieces_count = [0] * FILE_PIECES
        self.connected_peers = {}
        self.active_peers = []
        self.current_pieces = 0
        self.begin_receive_time = 0.0
        self.round = 0
        self.comm_received = None
        PEER_LOG.info("Hi, I'm joining the network with id %d", self.id)

    # -- helpers ------------------------------------------------------
    def get_status(self):
        return "".join("1" if self.bitfield & (1 << i) else "0"
                       for i in range(FILE_PIECES - 1, -1, -1))

    def has_finished(self):
        return self.bitfield == (1 << FILE_PIECES) - 1

    def has_not_piece(self, piece):
        return not (self.bitfield & (1 << piece))

    def is_not_downloading_piece(self, piece):
        return not (self.current_pieces & (1 << piece))

    def is_interested_by(self, rp):
        return bool(rp.bitfield & (self.bitfield ^
                                   ((1 << FILE_PIECES) - 1)))

    def is_interested_by_free(self, rp):
        return any(self.has_not_piece(i) and rp.has_piece(i)
                   and self.is_not_downloading_piece(i)
                   for i in range(FILE_PIECES))

    @staticmethod
    def count_pieces(bitfield):
        return bin(bitfield).count("1")

    def nb_interested_peers(self):
        return sum(1 for c in self.connected_peers.values()
                   if c.interested)

    def update_pieces_count_from_bitfield(self, bitfield):
        for i in range(FILE_PIECES):
            if bitfield & (1 << i):
                self.pieces_count[i] += 1

    # -- block bookkeeping -------------------------------------------
    def update_bitfield_blocks(self, piece, block_index, block_length):
        for i in range(block_index, block_index + block_length):
            self.bitfield_blocks |= 1 << (piece * PIECES_BLOCKS + i)

    def has_completed_piece(self, piece):
        return all(self.bitfield_blocks &
                   (1 << (piece * PIECES_BLOCKS + i))
                   for i in range(PIECES_BLOCKS))

    def get_first_missing_block_from(self, piece):
        for i in range(PIECES_BLOCKS):
            if not (self.bitfield_blocks &
                    (1 << (piece * PIECES_BLOCKS + i))):
                return i
        return -1

    def partially_downloaded_piece(self, rp):
        for i in range(FILE_PIECES):
            if self.has_not_piece(i) and rp.has_piece(i) and \
                    self.is_not_downloading_piece(i) and \
                    self.get_first_missing_block_from(i) > 0:
                return i
        return -1

    # -- sending ------------------------------------------------------
    def send_message(self, mailbox_name, type_, size):
        s4u.Mailbox.by_name(mailbox_name).put_init(
            Message(type_, self.id, str(self.id),
                    bitfield=self.bitfield), size).detach()

    def send_bitfield(self, mailbox_name):
        s4u.Mailbox.by_name(mailbox_name).put_init(
            Message("BITFIELD", self.id, str(self.id),
                    bitfield=self.bitfield),
            MESSAGE_SIZES["BITFIELD"] + 1).detach()

    def send_piece(self, mailbox_name, piece, block_index, block_length):
        s4u.Mailbox.by_name(mailbox_name).put_init(
            Message("PIECE", self.id, str(self.id), piece=piece,
                    block_index=block_index,
                    block_length=block_length), BLOCK_SIZE).detach()

    def send_handshake_to_all_peers(self):
        for rp in self.connected_peers.values():
            s4u.Mailbox.by_name(rp.mailbox).put_init(
                Message("HANDSHAKE", self.id, str(self.id)),
                MESSAGE_SIZES["HANDSHAKE"]).detach()

    def send_have_to_all_peers(self, piece):
        for rp in self.connected_peers.values():
            s4u.Mailbox.by_name(rp.mailbox).put_init(
                Message("HAVE", self.id, str(self.id), piece=piece),
                MESSAGE_SIZES["HAVE"]).detach()

    def send_request_to(self, rp, piece):
        rp.current_piece = piece
        block_index = self.get_first_missing_block_from(piece)
        if block_index != -1:
            block_length = min(BLOCKS_REQUESTED,
                               PIECES_BLOCKS - block_index)
            s4u.Mailbox.by_name(rp.mailbox).put_init(
                Message("REQUEST", self.id, str(self.id), piece=piece,
                        block_index=block_index,
                        block_length=block_length),
                MESSAGE_SIZES["REQUEST"]).detach()

    # -- tracker ------------------------------------------------------
    def get_peers_from_tracker(self):
        tracker_mb = s4u.Mailbox.by_name(TRACKER_MAILBOX)
        try:
            tracker_mb.put((self.id, str(self.id)), TRACKER_COMM_SIZE,
                           GET_PEERS_TIMEOUT)
        except TimeoutException:
            return False
        try:
            answer = self.mailbox.get(GET_PEERS_TIMEOUT)
        except TimeoutException:
            return False
        for peer_id in answer:
            if peer_id != self.id:
                self.connected_peers[peer_id] = Connection(peer_id)
        return True

    # -- choking ------------------------------------------------------
    def update_active_peers_set(self, rp):
        if rp.interested and not rp.choked_upload:
            if rp not in self.active_peers:
                self.active_peers.append(rp)
        elif rp in self.active_peers:
            self.active_peers.remove(rp)

    def update_choked_peers(self):
        e = s4u.Engine.get_instance()
        if self.nb_interested_peers() == 0:
            return
        self.round = (self.round + 1) % 3
        chosen = None
        choked = self.active_peers.pop(0) if self.active_peers else None

        if self.has_finished():
            unchoke_time = e.clock + 1
            for rp in self.connected_peers.values():
                if rp.last_unchoke < unchoke_time and rp.interested \
                        and rp.choked_upload:
                    unchoke_time = rp.last_unchoke
                    chosen = rp
        elif self.round == 0:
            keys = list(self.connected_peers)
            for _ in range(MAXIMUM_PEERS):
                cand = self.connected_peers[keys[self.stream.rand_int(
                    0, len(keys) - 1)]]
                if cand.interested and cand.choked_upload:
                    chosen = cand
                    break
        else:
            fastest = 0.0
            for rp in self.connected_peers.values():
                if rp.peer_speed > fastest and rp.choked_upload and \
                        rp.interested:
                    fastest = rp.peer_speed
                    chosen = rp

        if choked is not chosen:
            if choked is not None:
                choked.choked_upload = True
                self.update_active_peers_set(choked)
                self.send_message(choked.mailbox, "CHOKE",
                                  MESSAGE_SIZES["CHOKE"])
            if chosen is not None:
                chosen.choked_upload = False
                chosen.last_unchoke = e.clock
                self.update_active_peers_set(chosen)
                self.send_message(chosen.mailbox, "UNCHOKE",
                                  MESSAGE_SIZES["UNCHOKE"])

    def update_interested_after_receive(self):
        for rp in self.connected_peers.values():
            if rp.am_interested:
                interested = any(
                    self.has_not_piece(i) and rp.has_piece(i)
                    for i in range(FILE_PIECES))
                if not interested:
                    rp.am_interested = False
                    self.send_message(rp.mailbox, "NOTINTERESTED",
                                      MESSAGE_SIZES["NOTINTERESTED"])

    # -- piece selection ----------------------------------------------
    def select_piece_to_download(self, rp):
        piece = self.partially_downloaded_piece(rp)
        if piece != -1:
            return piece
        if self.count_pieces(self.current_pieces) >= \
                (FILE_PIECES - self.count_pieces(self.bitfield)) and \
                self.is_interested_by(rp):
            if not ENABLE_END_GAME_MODE:
                return -1
            interesting = [i for i in range(FILE_PIECES)
                           if self.has_not_piece(i) and rp.has_piece(i)]
            return interesting[self.stream.rand_int(
                0, len(interesting) - 1)]
        if self.count_pieces(self.bitfield) < 4 and \
                self.is_interested_by_free(rp):
            interesting = [i for i in range(FILE_PIECES)
                           if self.has_not_piece(i) and rp.has_piece(i)
                           and self.is_not_downloading_piece(i)]
            return interesting[self.stream.rand_int(
                0, len(interesting) - 1)]
        # rarest-first
        candidates = [i for i in range(FILE_PIECES)
                      if self.has_not_piece(i) and rp.has_piece(i)
                      and self.is_not_downloading_piece(i)]
        if not candidates:
            return -1
        min_count = min(self.pieces_count[i] for i in candidates)
        rarest = [i for i in candidates
                  if self.pieces_count[i] == min_count]
        return rarest[self.stream.rand_int(0, len(rarest) - 1)]

    def request_new_piece_to(self, rp):
        piece = self.select_piece_to_download(rp)
        if piece != -1:
            self.current_pieces |= 1 << piece
            self.send_request_to(rp, piece)

    def remove_current_piece(self, rp, piece):
        self.current_pieces &= ~(1 << piece)
        rp.current_piece = -1

    # -- message handling ---------------------------------------------
    def handle_message(self, msg):
        e = s4u.Engine.get_instance()
        rp = self.connected_peers.get(msg.peer_id)
        t = msg.type
        if t == "HANDSHAKE":
            if rp is None:
                self.connected_peers[msg.peer_id] = \
                    Connection(msg.peer_id)
                rp = self.connected_peers[msg.peer_id]
                self.send_message(msg.return_mailbox, "HANDSHAKE",
                                  MESSAGE_SIZES["HANDSHAKE"])
            self.send_bitfield(msg.return_mailbox)
        elif t == "BITFIELD":
            self.update_pieces_count_from_bitfield(msg.bitfield)
            rp.bitfield = msg.bitfield
            if self.is_interested_by(rp):
                rp.am_interested = True
                self.send_message(msg.return_mailbox, "INTERESTED",
                                  MESSAGE_SIZES["INTERESTED"])
        elif t == "INTERESTED":
            rp.interested = True
            self.update_active_peers_set(rp)
        elif t == "NOTINTERESTED":
            rp.interested = False
            self.update_active_peers_set(rp)
        elif t == "UNCHOKE":
            rp.choked_download = False
            if rp.am_interested:
                self.request_new_piece_to(rp)
        elif t == "CHOKE":
            rp.choked_download = True
            if rp.current_piece != -1:
                self.remove_current_piece(rp, rp.current_piece)
        elif t == "HAVE":
            rp.bitfield |= 1 << msg.piece
            self.pieces_count[msg.piece] += 1
            if not rp.am_interested and self.has_not_piece(msg.piece):
                rp.am_interested = True
                self.send_message(msg.return_mailbox, "INTERESTED",
                                  MESSAGE_SIZES["INTERESTED"])
                if not rp.choked_download:
                    self.request_new_piece_to(rp)
        elif t == "REQUEST":
            if not rp.choked_upload and not self.has_not_piece(
                    msg.piece):
                self.send_piece(msg.return_mailbox, msg.piece,
                                msg.block_index, msg.block_length)
        elif t == "PIECE":
            if self.has_not_piece(msg.piece):
                self.update_bitfield_blocks(msg.piece, msg.block_index,
                                            msg.block_length)
                if self.has_completed_piece(msg.piece):
                    self.remove_current_piece(rp, msg.piece)
                    self.bitfield |= 1 << msg.piece
                    self.send_have_to_all_peers(msg.piece)
                    self.update_interested_after_receive()
                else:
                    self.send_request_to(rp, msg.piece)
            else:
                self.request_new_piece_to(rp)
        elif t == "CANCEL":
            pass
        if rp is not None:
            dt = e.clock - self.begin_receive_time
            # C computes 1.0/0.0 = inf here without complaint
            rp.add_speed_value(1.0 / dt if dt > 0 else float("inf"))
        self.begin_receive_time = e.clock

    # -- main loops ---------------------------------------------------
    def _loop(self, stop_when_complete):
        e = s4u.Engine.get_instance()
        next_choked_update = e.clock + UPDATE_CHOKED_INTERVAL
        while e.clock < self.deadline and not (
                stop_when_complete
                and self.count_pieces(self.bitfield) >= FILE_PIECES):
            if self.comm_received is None:
                self.comm_received = self.mailbox.get_async()
            if self.comm_received.test():
                msg = self.comm_received.get_payload()
                self.handle_message(msg)
                self.comm_received = None
            elif e.clock >= next_choked_update and (
                    not stop_when_complete
                    or self.count_pieces(self.bitfield) > 0):
                self.update_choked_peers()
                next_choked_update += UPDATE_CHOKED_INTERVAL
            else:
                s4u.this_actor.sleep_for(SLEEP_DURATION)

    def run(self):
        e = s4u.Engine.get_instance()
        if self.get_peers_from_tracker():
            self.begin_receive_time = e.clock
            self.mailbox.set_receiver(s4u.Actor.self())
            if self.has_finished():
                self.send_handshake_to_all_peers()
            else:
                # leech(): handshake everyone, then download
                self.send_handshake_to_all_peers()
                self._loop(stop_when_complete=True)
            self._loop(stop_when_complete=False)      # seed
        else:
            PEER_LOG.info("Couldn't contact the tracker.")
        PEER_LOG.info("Here is my current status: %s", self.get_status())


def peer(*args):
    Peer(list(args)).run()


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    install_streams(e)
    e.register_function("tracker", tracker)
    e.register_function("peer", peer)
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
