"""s4u-actor-lifetime replica (reference
examples/s4u/actor-lifetime/s4u-actor-lifetime.cpp): actors deployed
from XML with explicit start_time / kill_time; on_exit fires both on
natural termination and on the deployment-driven kill."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("test")


def sleeper():
    s4u.this_actor.on_exit(
        lambda failed: LOG.info("Exiting now (done sleeping or got "
                                "killed)."))
    LOG.info("Hello! I go to sleep.")
    s4u.this_actor.sleep_for(10)
    LOG.info("Done sleeping.")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    e.register_function("sleeper", sleeper)
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
