"""s4u-cloud-simple replica (reference
examples/s4u/cloud-simple/s4u-cloud-simple.cpp): computation and
communication on PMs and VMs, collocation, and live migration."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.plugins.vm import VirtualMachine, migrate
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def computation_fun():
    clock_sta = s4u.Engine.get_clock()
    s4u.this_actor.execute(1_000_000)
    clock_end = s4u.Engine.get_clock()
    LOG.info("%s:%s task executed %g"
             % (s4u.this_actor.get_host().name, s4u.this_actor.get_name(),
                clock_end - clock_sta))


def launch_computation_worker(host):
    s4u.Actor.create("compute", host, computation_fun)


def communication_tx_fun(mbox_name):
    mbox = s4u.Mailbox.by_name(mbox_name)
    payload = (s4u.this_actor.get_host(), s4u.this_actor.get_name(),
               s4u.Engine.get_clock())
    mbox.put(payload, 1_000_000)


def communication_rx_fun(mbox_name):
    actor_name = s4u.this_actor.get_name()
    host_name = s4u.this_actor.get_host().name
    mbox = s4u.Mailbox.by_name(mbox_name)
    tx_host, tx_name, clock_sta = mbox.get()
    clock_end = s4u.Engine.get_clock()
    LOG.info("%s:%s to %s:%s => %g sec"
             % (tx_host.name, tx_name, host_name, actor_name,
                clock_end - clock_sta))


def launch_communication_worker(tx_host, rx_host):
    mbox_name = "MBOX:%s-%s" % (tx_host.name, rx_host.name)
    s4u.Actor.create("comm_tx", tx_host, communication_tx_fun, mbox_name)
    s4u.Actor.create("comm_rx", rx_host, communication_rx_fun, mbox_name)


def master_main():
    e = s4u.Engine.get_instance()
    pm0 = e.host_by_name("Fafard")
    pm1 = e.host_by_name("Tremblay")
    pm2 = e.host_by_name("Bourassa")

    LOG.info("## Test 1 (started): check computation on normal PMs")
    LOG.info("### Put a task on a PM")
    launch_computation_worker(pm0)
    s4u.this_actor.sleep_for(2)

    LOG.info("### Put two tasks on a PM")
    launch_computation_worker(pm0)
    launch_computation_worker(pm0)
    s4u.this_actor.sleep_for(2)

    LOG.info("### Put a task on each PM")
    launch_computation_worker(pm0)
    launch_computation_worker(pm1)
    s4u.this_actor.sleep_for(2)
    LOG.info("## Test 1 (ended)")

    LOG.info("## Test 2 (started): check impact of running a task inside"
             " a VM (there is no degradation for the moment)")
    LOG.info("### Put a VM on a PM, and put a task to the VM")
    vm0 = VirtualMachine("VM0", pm0, 1)
    vm0.start()
    launch_computation_worker(vm0)
    s4u.this_actor.sleep_for(2)
    vm0.destroy()
    LOG.info("## Test 2 (ended)")

    LOG.info("## Test 3 (started): check impact of running a task "
             "collocated with a VM (there is no VM noise for the moment)")
    LOG.info("### Put a VM on a PM, and put a task to the PM")
    vm0 = VirtualMachine("VM0", pm0, 1)
    vm0.start()
    launch_computation_worker(pm0)
    s4u.this_actor.sleep_for(2)
    vm0.destroy()
    LOG.info("## Test 3 (ended)")

    LOG.info("## Test 4 (started): compare the cost of running two tasks"
             " inside two different VMs collocated or not (for the moment"
             ", there is no degradation for the VMs. Hence, the time "
             "should be equals to the time of test 1")
    LOG.info("### Put two VMs on a PM, and put a task to each VM")
    vm0 = VirtualMachine("VM0", pm0, 1)
    vm0.start()
    vm1 = VirtualMachine("VM1", pm0, 1)
    launch_computation_worker(vm0)
    launch_computation_worker(vm1)
    s4u.this_actor.sleep_for(2)
    vm0.destroy()
    vm1.destroy()

    LOG.info("### Put a VM on each PM, and put a task to each VM")
    vm0 = VirtualMachine("VM0", pm0, 1)
    vm1 = VirtualMachine("VM1", pm1, 1)
    vm0.start()
    vm1.start()
    launch_computation_worker(vm0)
    launch_computation_worker(vm1)
    s4u.this_actor.sleep_for(2)
    vm0.destroy()
    vm1.destroy()
    LOG.info("## Test 4 (ended)")

    LOG.info("## Test 5  (started): Analyse network impact")
    LOG.info("### Make a connection between PM0 and PM1")
    launch_communication_worker(pm0, pm1)
    s4u.this_actor.sleep_for(5)

    LOG.info("### Make two connection between PM0 and PM1")
    launch_communication_worker(pm0, pm1)
    launch_communication_worker(pm0, pm1)
    s4u.this_actor.sleep_for(5)

    LOG.info("### Make a connection between PM0 and VM0@PM0")
    vm0 = VirtualMachine("VM0", pm0, 1)
    vm0.start()
    launch_communication_worker(pm0, vm0)
    s4u.this_actor.sleep_for(5)
    vm0.destroy()

    LOG.info("### Make a connection between PM0 and VM0@PM1")
    vm0 = VirtualMachine("VM0", pm1, 1)
    launch_communication_worker(pm0, vm0)
    s4u.this_actor.sleep_for(5)
    vm0.destroy()

    LOG.info("### Make two connections between PM0 and VM0@PM1")
    vm0 = VirtualMachine("VM0", pm1, 1)
    vm0.start()
    launch_communication_worker(pm0, vm0)
    launch_communication_worker(pm0, vm0)
    s4u.this_actor.sleep_for(5)
    vm0.destroy()

    LOG.info("### Make a connection between PM0 and VM0@PM1, and also "
             "make a connection between PM0 and PM1")
    vm0 = VirtualMachine("VM0", pm1, 1)
    vm0.start()
    launch_communication_worker(pm0, vm0)
    launch_communication_worker(pm0, pm1)
    s4u.this_actor.sleep_for(5)
    vm0.destroy()

    LOG.info("### Make a connection between VM0@PM0 and PM1@PM1, and "
             "also make a connection between VM0@PM0 and VM1@PM1")
    vm0 = VirtualMachine("VM0", pm0, 1)
    vm1 = VirtualMachine("VM1", pm1, 1)
    vm0.start()
    vm1.start()
    launch_communication_worker(vm0, vm1)
    launch_communication_worker(vm0, vm1)
    s4u.this_actor.sleep_for(5)
    vm0.destroy()
    vm1.destroy()
    LOG.info("## Test 5 (ended)")

    LOG.info("## Test 6 (started): Check migration impact (not yet "
             "implemented neither on the CPU resource nor on the network"
             " one")
    LOG.info("### Relocate VM0 between PM0 and PM1")
    vm0 = VirtualMachine("VM0", pm0, 1, ramsize=1024 * 1024 * 1024)
    vm0.start()
    launch_communication_worker(vm0, pm2)
    s4u.this_actor.sleep_for(0.01)
    migrate(vm0, pm1)
    s4u.this_actor.sleep_for(0.01)
    migrate(vm0, pm0)
    s4u.this_actor.sleep_for(5)
    vm0.destroy()
    LOG.info("## Test 6 (ended)")


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("master_", e.host_by_name("Fafard"), master_main)
    e.run()
    LOG.info("Simulation time %g" % e.get_clock())


if __name__ == "__main__":
    main()
