"""s4u-cloud-migration replica (reference
examples/s4u/cloud-migration/s4u-cloud-migration.cpp): three-stage
pre-copy live migrations — serial, two-at-once over the same route,
and two-at-once to different destinations."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.plugins import vm as vm_plugin
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_cloud_migration")


def vm_migrate(vm, dst_pm):
    src_pm = vm.pm
    mig_sta = s4u.Engine.get_clock()
    vm_plugin.migrate(vm, dst_pm)
    mig_end = s4u.Engine.get_clock()
    LOG.info("%s migrated: %s->%s in %g s"
             % (vm.name, src_pm.name, dst_pm.name, mig_end - mig_sta))


def vm_migrate_async(vm, dst_pm):
    s4u.Actor.create("mig_wrk", s4u.this_actor.get_host(), vm_migrate,
                     vm, dst_pm)


def master_main():
    e = s4u.Engine.get_instance()
    pm0 = e.host_by_name("Fafard")
    pm1 = e.host_by_name("Tremblay")
    pm2 = e.host_by_name("Bourassa")

    vm0 = s4u.VirtualMachine("VM0", pm0, 1)
    vm0.ramsize = int(1e9)
    vm0.start()

    LOG.info("Test: Migrate a VM with %d Mbytes RAM"
             % (vm0.ramsize // 1000 // 1000))
    vm_migrate(vm0, pm1)

    vm0.destroy()

    vm0 = s4u.VirtualMachine("VM0", pm0, 1)
    vm0.ramsize = int(1e8)
    vm0.start()

    LOG.info("Test: Migrate a VM with %d Mbytes RAM"
             % (vm0.ramsize // 1000 // 1000))
    vm_migrate(vm0, pm1)

    vm0.destroy()

    vm0 = s4u.VirtualMachine("VM0", pm0, 1)
    vm1 = s4u.VirtualMachine("VM1", pm0, 1)
    vm0.ramsize = int(1e9)
    vm1.ramsize = int(1e9)
    vm0.start()
    vm1.start()

    LOG.info("Test: Migrate two VMs at once from PM0 to PM1")
    vm_migrate_async(vm0, pm1)
    vm_migrate_async(vm1, pm1)
    s4u.this_actor.sleep_for(10000)

    vm0.destroy()
    vm1.destroy()

    vm0 = s4u.VirtualMachine("VM0", pm0, 1)
    vm1 = s4u.VirtualMachine("VM1", pm0, 1)
    vm0.ramsize = int(1e9)
    vm1.ramsize = int(1e9)
    vm0.start()
    vm1.start()

    LOG.info("Test: Migrate two VMs at once to different PMs")
    vm_migrate_async(vm0, pm1)
    vm_migrate_async(vm1, pm2)
    s4u.this_actor.sleep_for(10000)

    vm0.destroy()
    vm1.destroy()


def main():
    e = s4u.Engine(sys.argv)
    vm_plugin.vm_live_migration_plugin_init(e.pimpl)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("master_", e.host_by_name("Fafard"), master_main)
    e.run()
    LOG.info("Bye (simulation time %g)" % e.clock)


if __name__ == "__main__":
    main()
