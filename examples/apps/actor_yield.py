"""s4u-actor-yield replica (reference
examples/s4u/actor-yield/s4u-actor-yield.cpp): over-polite actors yield
N times; deployment-file instantiation."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_actor_yield")


def yielder(n):
    for _ in range(int(n)):
        s4u.this_actor.yield_()
    LOG.info("I yielded %s times. Goodbye now!", int(n))


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    e.register_function("yielder", yielder)
    e.load_deployment(sys.argv[2])
    e.run()


if __name__ == "__main__":
    main()
