"""s4u-exec-dvfs replica (reference
examples/s4u/exec-dvfs/s4u-exec-dvfs.cpp): pstate introspection and
runtime pstate switching (a running exec continues at the new speed)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_test")


def dvfs():
    workload = 100e6
    host = s4u.this_actor.get_host()

    LOG.info("Count of Processor states=%d" % host.get_pstate_count())
    LOG.info("Current power peak=%f" % host.get_speed())

    s4u.this_actor.execute(workload)

    task_time = s4u.Engine.get_clock()
    LOG.info("Task1 duration: %.2f" % task_time)

    new_pstate = 2
    LOG.info("Changing power peak value to %f (at index %d)"
             % (host.get_pstate_speed(new_pstate), new_pstate))
    host.set_pstate(new_pstate)

    LOG.info("Current power peak=%f" % host.get_speed())

    s4u.this_actor.execute(workload)

    task_time = s4u.Engine.get_clock() - task_time
    LOG.info("Task2 duration: %.2f" % task_time)

    host = s4u.Engine.get_instance().host_by_name("MyHost2")
    LOG.info("Count of Processor states=%d" % host.get_pstate_count())
    LOG.info("Current power peak=%f" % host.get_speed())


def main():
    e = s4u.Engine(sys.argv)
    e.load_platform(sys.argv[1])
    s4u.Actor.create("dvfs_test", e.host_by_name("MyHost1"), dvfs)
    s4u.Actor.create("dvfs_test", e.host_by_name("MyHost2"), dvfs)
    e.run()
    LOG.info("Total simulation time: %e" % e.clock)


if __name__ == "__main__":
    main()
