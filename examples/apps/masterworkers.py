"""s4u-app-masterworkers replica (reference
examples/s4u/app-masterworkers/s4u-app-masterworkers-class.cpp):
round-robin task dispatch over mailbox-named workers, deployment XML."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from simgrid_tpu import s4u
from simgrid_tpu.utils import log as xlog

LOG = xlog.get_category("s4u_app_masterworker")


def master(*args):
    tasks_count = int(args[0])
    compute_cost = float(args[1])
    communicate_cost = float(args[2])
    workers = [s4u.Mailbox.by_name(name) for name in args[3:]]

    LOG.info("Got %d workers and %d tasks to process"
             % (len(workers), tasks_count))

    for i in range(tasks_count):
        mailbox = workers[i % len(workers)]
        if (tasks_count < 10000 or (tasks_count < 100000
                                    and i % 10000 == 0)
                or i % 100000 == 0):
            LOG.info("Sending task %d of %d to mailbox '%s'"
                     % (i, tasks_count, mailbox.name))
        mailbox.put(compute_cost, communicate_cost)

    LOG.info("All tasks have been dispatched. "
             "Request all workers to stop.")
    for i in range(len(workers)):
        workers[i % len(workers)].put(-1.0, 0)


def worker(*args):
    assert not args, "The worker expects to not get any argument"
    mailbox = s4u.Mailbox.by_name(s4u.this_actor.get_host().name)
    while True:
        compute_cost = mailbox.get()
        if compute_cost > 0:
            s4u.this_actor.execute(compute_cost)
        else:
            break
    LOG.info("Exiting now.")


def main():
    e = s4u.Engine(sys.argv)
    e.register_function("master", master)
    e.register_function("worker", worker)
    e.load_platform(sys.argv[1])
    e.load_deployment(sys.argv[2])
    e.run()
    LOG.info("Simulation is over")


if __name__ == "__main__":
    main()
