"""Master/workers over s4u — BASELINE config #2 (reference
examples/s4u/app-masterworkers/s4u-app-masterworkers.cpp): one master
scatters compute tasks round-robin to workers over mailboxes, then
ships one finalize token per worker."""

from __future__ import annotations

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))
from simgrid_tpu import s4u


def master(n_tasks: int, comp_size: float, comm_size: float,
           worker_names, stats: dict):
    mailboxes = [s4u.Mailbox.by_name(name) for name in worker_names]
    for i in range(n_tasks):
        mailboxes[i % len(mailboxes)].put(("task", comp_size), comm_size)
    for mbox in mailboxes:
        mbox.put(("finalize", 0.0), 0.0)
    stats["master_done"] = s4u.Engine.get_clock()


def worker(name: str, stats: dict):
    mbox = s4u.Mailbox.by_name(name)
    done = 0
    while True:
        kind, flops = mbox.get()
        if kind == "finalize":
            break
        s4u.this_actor.execute(flops)
        done += 1
    stats[name] = done


def deploy(engine, n_workers: int, n_tasks: int = 1000,
           comp_size: float = 50e6, comm_size: float = 1e6) -> dict:
    hosts = engine.get_all_hosts()
    assert len(hosts) >= 2, "need at least a master and one worker"
    names = [f"worker-{i}" for i in range(n_workers)]
    stats: dict = {}
    s4u.Actor.create("master", hosts[0], master, n_tasks, comp_size,
                     comm_size, names, stats)
    for i, name in enumerate(names):
        s4u.Actor.create(name, hosts[1 + i % (len(hosts) - 1)], worker,
                         name, stats)
    return stats


def main():
    import sys
    platform = sys.argv[1] if len(sys.argv) > 1 else \
        "/root/reference/examples/platforms/cluster_fat_tree.xml"
    n_workers = int(sys.argv[2]) if len(sys.argv) > 2 else 12
    e = s4u.Engine(["masterworkers"])
    e.load_platform(platform)
    stats = deploy(e, n_workers)
    e.run()
    total = sum(v for k, v in stats.items() if k.startswith("worker-"))
    print(f"masterworkers: {n_workers} workers processed {total} tasks, "
          f"clock={e.clock:.6f}")


if __name__ == "__main__":
    main()
