#!/usr/bin/env python3
"""Can Mosaic lower the solver's hot ops inside one pallas kernel on
this chip?  Probes, in order of ambition:

  1. in-kernel jnp.take: gather tab[C] at idx [V,4]  (VMEM gather)
  2. in-kernel segment-sum via jnp.zeros(C).at[idx].add(w)
  3. in-kernel fori_loop of K gather rounds (the whole-fixpoint shape)

Each probe checks CORRECTNESS against numpy and reports timing with
the chained-dispatch protocol.  Appends to bench_results/tpu_opcost.jsonl.
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "bench_results", "tpu_opcost.jsonl")


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    dev = jax.devices()[0]
    dtype = jnp.float32
    rec = {"platform": dev.platform, "probe": "pallas_ops",
           "ts": round(time.time(), 1)}

    C, V, DEG = 16384, 131072, 4
    E = V * DEG
    rng = np.random.default_rng(7)
    idx_np = rng.integers(0, C, (V, DEG)).astype(np.int32)
    tab_np = rng.uniform(1, 2, C).astype(np.float32)
    w_np = rng.uniform(0.5, 1.5, (V, DEG)).astype(np.float32)
    idx = jnp.asarray(idx_np)
    tab = jnp.asarray(tab_np)
    w = jnp.asarray(w_np)

    sync = 66.0

    def timed(name, f, K=24):
        s = jnp.asarray(0.0, dtype)
        float(np.asarray(f(s).ravel()[0]))
        t0 = time.perf_counter()
        s = jnp.asarray(0.0, dtype)
        for _ in range(K):
            s = f(s).ravel()[0] * 1e-30
        float(np.asarray(s))
        wall = time.perf_counter() - t0
        rec[name] = round((wall - sync / 1e3) / K * 1e3, 3)
        print(f"  {name}: {rec[name]} ms")

    # --- probe 1: gather ---
    def gk(tab_ref, idx_ref, o_ref):
        o_ref[:] = jnp.take(tab_ref[:], idx_ref[:], axis=0)

    try:
        @jax.jit
        def pgather(s):
            return pl.pallas_call(
                gk, out_shape=jax.ShapeDtypeStruct((V, DEG), dtype),
            )(tab + s, idx)
        got = np.asarray(pgather(jnp.asarray(0.0, dtype)))
        want = tab_np[idx_np]
        ok = np.allclose(got, want)
        rec["pallas_gather_ok"] = bool(ok)
        print(f"  gather correct: {ok}")
        if ok:
            timed("pallas_gather_ms", pgather)
    except Exception as exc:  # noqa: BLE001
        rec["pallas_gather_ok"] = f"{type(exc).__name__}: {exc}"[:400]
        print(f"  gather FAILED: {rec['pallas_gather_ok']}")

    # --- probe 2: segment-sum (scatter-add) ---
    def sk(idx_ref, w_ref, o_ref):
        o_ref[:] = jnp.zeros((C,), dtype).at[idx_ref[:].ravel()].add(
            w_ref[:].ravel())

    try:
        @jax.jit
        def pseg(s):
            return pl.pallas_call(
                sk, out_shape=jax.ShapeDtypeStruct((C,), dtype),
            )(idx, w + s)
        got = np.asarray(pseg(jnp.asarray(0.0, dtype)))
        want = np.zeros(C, np.float32)
        np.add.at(want, idx_np.ravel(), w_np.ravel())
        ok = np.allclose(got, want, rtol=1e-4)
        rec["pallas_segsum_ok"] = bool(ok)
        print(f"  segsum correct: {ok}")
        if ok:
            timed("pallas_segsum_ms", pseg)
    except Exception as exc:  # noqa: BLE001
        rec["pallas_segsum_ok"] = f"{type(exc).__name__}: {exc}"[:400]
        print(f"  segsum FAILED: {rec['pallas_segsum_ok']}")

    # --- probe 3: K gather-rounds inside one kernel ---
    K_ROUNDS = 16

    def lk(tab_ref, idx_ref, o_ref):
        def body(i, acc):
            g = jnp.take(tab_ref[:] + acc[0, 0] * 1e-30, idx_ref[:],
                         axis=0)
            return acc + g.sum(axis=1, keepdims=True)[:8, :1] * 0 + \
                g[:8, :1]
        o_ref[:] = jax.lax.fori_loop(0, K_ROUNDS, body,
                                     jnp.zeros((8, 1), dtype))

    try:
        @jax.jit
        def ploop(s):
            return pl.pallas_call(
                lk, out_shape=jax.ShapeDtypeStruct((8, 1), dtype),
            )(tab + s, idx)
        np.asarray(ploop(jnp.asarray(0.0, dtype)))
        rec["pallas_loop_ok"] = True
        print("  loop kernel ran")
        timed("pallas_loop16_ms", ploop)
    except Exception as exc:  # noqa: BLE001
        rec["pallas_loop_ok"] = f"{type(exc).__name__}: {exc}"[:400]
        print(f"  loop FAILED: {rec['pallas_loop_ok']}")

    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
