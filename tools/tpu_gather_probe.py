#!/usr/bin/env python3
"""Pin down the axon-TPU gather fast path: same gathered volume
(524288 elements), different index shapes / source sizes / modes.
Chained-dispatch timing protocol (see tpu_opcost.py)."""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "bench_results", "tpu_opcost.jsonl")


def main() -> int:
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    dtype = jnp.float32 if dev.platform != "cpu" else jnp.float64
    rec = {"platform": dev.platform, "probe": "gather_shapes",
           "ts": round(time.time(), 1)}

    C, V, E = 16384, 131072, 524288
    rng = np.random.default_rng(7)
    idxC = rng.integers(0, C, E).astype(np.int32)
    idxV = rng.integers(0, V, E).astype(np.int32)
    tabC = jnp.asarray(rng.uniform(1, 2, C), dtype)
    tabV = jnp.asarray(rng.uniform(1, 2, V), dtype)

    sync = None

    def timed(name, fn, K=24):
        nonlocal sync
        f = jax.jit(fn)
        s = jnp.asarray(0.0, dtype)
        float(np.asarray(f(s).ravel()[0]))
        t0 = time.perf_counter()
        s = jnp.asarray(0.0, dtype)
        for _ in range(K):
            s = f(s).ravel()[0] * 1e-30
        float(np.asarray(s))
        wall = time.perf_counter() - t0
        rec[name] = round((wall - (sync or 0.0) / 1e3) / K * 1e3, 3)
        print(f"  {name}: {rec[name]} ms")

    triv = jax.jit(lambda s: s + 1.0)
    float(np.asarray(triv(jnp.asarray(0.0, dtype))))
    ts = []
    for _ in range(6):
        t0 = time.perf_counter()
        float(np.asarray(triv(jnp.asarray(0.0, dtype))))
        ts.append(time.perf_counter() - t0)
    sync = rec["sync_ms"] = round(float(np.median(ts)) * 1e3, 3)
    print(f"  sync_ms: {sync}")

    shapes = {"flat": (E,), "x128": (E // 128, 128),
              "x4": (E // 4, 4), "x8": (E // 8, 8),
              "x512": (E // 512, 512)}
    for nm, shp in shapes.items():
        idx = jnp.asarray(idxC.reshape(shp))
        timed(f"gC_{nm}", lambda s, idx=idx: jnp.take(tabC + s, idx))
    for nm, shp in [("flat", (E,)), ("x4", (E // 4, 4)),
                    ("x128", (E // 128, 128))]:
        idx = jnp.asarray(idxV.reshape(shp))
        timed(f"gV_{nm}", lambda s, idx=idx: jnp.take(tabV + s, idx))
    # sorted indices, flat
    idxs = jnp.asarray(np.sort(idxC))
    timed("gC_flat_sorted", lambda s: jnp.take(tabC + s, idxs))
    # repeat-based expansion (var-major broadcast): [V] -> [V,4] -> flat
    timed("repeat_V4", lambda s: jnp.repeat(tabV + s, 4))
    # one flat gather then reshape out
    idxf = jnp.asarray(idxC)
    timed("gC_flat_reshaped_out",
          lambda s: jnp.take(tabC + s, idxf).reshape(-1, 128))

    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
