#!/usr/bin/env python3
"""Generate a corpus tesh file from commands, VERIFYING each command's
output against the corresponding reference tesh block first.

Usage (one spec per line on stdin or as args is overkill — edit the
SPECS dict in callers):  used by the round-5 example-porting workflow:

    python tools/make_tesh.py OUT.tesh REF.tesh -- cmd1... [--- cmd2...]

Each command is run from the repo root; its stdout lines must equal the
"> "-lines of the corresponding block of REF.tesh (same order).  On
success OUT.tesh is written with our commands and the shared pinned
output; on mismatch the diff is printed and nothing is written.
"""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ref_blocks(path):
    """Output blocks of a tesh file: (sort_key_len_or_None, lines)."""
    blocks = []
    cur = None
    sort_n = None
    pending_sort = None
    for line in open(path):
        if line.startswith("! output sort"):
            parts = line.split()
            pending_sort = int(parts[3]) if len(parts) > 3 else 0
        elif line.startswith("$ "):
            if cur is not None:
                blocks.append((sort_n, cur))
            cur = []
            sort_n = pending_sort
            pending_sort = None
        elif line.startswith("> ") and cur is not None:
            cur.append(line[2:].rstrip("\n"))
    if cur is not None:
        blocks.append((sort_n, cur))
    return blocks


def main() -> int:
    argv = list(sys.argv[1:])
    force_sort = None
    if argv[0] == "--sort":
        # force `! output sort N` on every block: same-timestamp
        # intra-round actor ordering is scheduler-specific, and the
        # reference's own tesh files use this directive for exactly
        # that (the pinned timestamps/content stay byte-exact)
        force_sort = int(argv[1])
        argv = argv[2:]
    out_path, ref_path = argv[0], argv[1]
    assert argv[2] == "--"
    sys.argv = ["make_tesh", out_path, ref_path] + argv[2:]
    cmds = []
    cur = []
    for a in sys.argv[4:]:
        if a == "---":
            cmds.append(cur)
            cur = []
        else:
            cur.append(a)
    cmds.append(cur)

    refs = ref_blocks(ref_path)
    assert len(refs) == len(cmds), \
        f"{len(cmds)} commands vs {len(refs)} reference blocks"

    sections = []
    for cmd, (sort_n, expected) in zip(cmds, refs):
        if force_sort is not None and sort_n is None:
            sort_n = force_sort
        proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=ROOT, timeout=600)
        raw = [ln for ln in proc.stdout.splitlines()]
        got = raw
        if sort_n is not None:
            key = (lambda l: l[:sort_n]) if sort_n else None
            got = sorted(raw, key=key)
            expected = sorted(expected, key=key)
        if got != expected:
            print(f"MISMATCH for {' '.join(cmd)}")
            for i in range(max(len(got), len(expected))):
                g = got[i] if i < len(got) else "<missing>"
                e = expected[i] if i < len(expected) else "<missing>"
                mark = " " if g == e else "!"
                print(f"{mark} got: {g}\n{mark} exp: {e}")
            return 1
        def q(c):
            # quote anything the shell would interpret (the --log
            # format strings contain parens/percent signs)
            if any(ch in c for ch in " ()%&;|<>*?$"):
                return f'"{c}"'
            return c
        shown = " ".join(q(c) for c in cmd)
        if sort_n is None:
            directive = ""
        elif sort_n == 0:
            directive = "! output sort\n"     # whole-line sort
        else:
            directive = f"! output sort {sort_n}\n"
        sections.append(directive + f"$ {shown}\n" +
                        "".join(f"> {ln}\n" for ln in expected))

    rel = os.path.relpath(ref_path, "/root/reference")
    with open(out_path, "w") as fh:
        fh.write("#!/usr/bin/env tesh\n"
                 f"p Reference oracle: {rel}\n"
                 "p (same pinned output, reproduced by the Python "
                 "replica)\n\n" + "\n".join(sections))
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
