#!/usr/bin/env python3
"""Generate a corpus tesh file from commands, VERIFYING each command's
output against the corresponding reference tesh block first.

Usage (one spec per line on stdin or as args is overkill — edit the
SPECS dict in callers):  used by the round-5 example-porting workflow:

    python tools/make_tesh.py OUT.tesh REF.tesh -- cmd1... [--- cmd2...]

Each command is run from the repo root; its stdout lines must equal the
"> "-lines of the corresponding block of REF.tesh (same order).  On
success OUT.tesh is written with our commands and the shared pinned
output; on mismatch the diff is printed and nothing is written.
"""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ref_blocks(path):
    """Output blocks of a tesh file: list of lists of expected lines."""
    blocks = []
    cur = None
    for line in open(path):
        if line.startswith("$ "):
            if cur is not None:
                blocks.append(cur)
            cur = []
        elif line.startswith("> ") and cur is not None:
            cur.append(line[2:].rstrip("\n"))
    if cur is not None:
        blocks.append(cur)
    return blocks


def main() -> int:
    out_path, ref_path = sys.argv[1], sys.argv[2]
    assert sys.argv[3] == "--"
    cmds = []
    cur = []
    for a in sys.argv[4:]:
        if a == "---":
            cmds.append(cur)
            cur = []
        else:
            cur.append(a)
    cmds.append(cur)

    refs = ref_blocks(ref_path)
    assert len(refs) == len(cmds), \
        f"{len(cmds)} commands vs {len(refs)} reference blocks"

    sections = []
    for cmd, expected in zip(cmds, refs):
        proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=ROOT, timeout=600)
        got = [ln for ln in proc.stdout.splitlines()]
        if got != expected:
            print(f"MISMATCH for {' '.join(cmd)}")
            for i in range(max(len(got), len(expected))):
                g = got[i] if i < len(got) else "<missing>"
                e = expected[i] if i < len(expected) else "<missing>"
                mark = " " if g == e else "!"
                print(f"{mark} got: {g}\n{mark} exp: {e}")
            return 1
        shown = " ".join(c if " " not in c else f'"{c}"' for c in cmd)
        sections.append(f"$ {shown}\n" +
                        "".join(f"> {ln}\n" for ln in expected))

    rel = os.path.relpath(ref_path, "/root/reference")
    with open(out_path, "w") as fh:
        fh.write("#!/usr/bin/env tesh\n"
                 f"p Reference oracle: {rel}\n"
                 "p (same pinned output, reproduced by the Python "
                 "replica)\n\n" + "\n".join(sections))
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
