"""Sweep a dir of the vendored MPICH3 test suite: compile + run each
test in its own subprocess, in parallel workers.

Usage: python tools/mpich3_sweep.py [dir] [--jobs N] [--timeout S]
       [--only name1,name2] [--out results.json]

Results stream to stderr as they land and the JSON summary is written
incrementally, so a partial sweep is still a committed artifact.
"""
import argparse
import glob
import json
import os

import subprocess
import sys
import threading

M = "/root/reference/teshsuite/smpi/mpich3-test"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a few tests are output-only and never print the mtest "No Errors"
# banner; their PASS criteria are pinned to exact expected/forbidden
# output instead of a soft error-pattern scan (a silent crash or an
# empty run can then never count as PASS)
PINNED_OUTPUT = {
    # zero-block-length vector Bcast transfers NOTHING: every rank
    # must keep its own initial values after the Bcast
    "zero-blklen-vector": (
        ("in process 0 of 2 after bcast: a = -1.000000,0.500000",
         "in process 1 of 2 after bcast: a = -1.100000,0.600000"),
        ("should be at least",)),
    # zeroblks prints "... should = ..." diagnostics on any mismatch
    # and the before-Bcast lines unconditionally
    "zeroblks": ((), ("should =",)),
}

# per-test config overrides: tests that busy-wait on MPI_Wtime need the
# bench clock (simulate-computation) to advance simulated time
TEST_CONFIGS = {
    "bsendpending": ("smpi/simulate-computation:true",),
}

# helper translation units that are not standalone tests (no main)
HELPER_SRC = {"mcs-mutex"}
# tests that link a helper .c from the same dir
EXTRA_SRC = {"mutex_bench": ["mcs-mutex.c"],
             "sendrecvt2": ["../util/dtypes.c"],
             "sendrecvt4": ["../util/dtypes.c"]}
# template tests built per-operation via -DTEST_x in MPICH's makefiles;
# sweep the PUT variant (the others are the same skeleton)
EXTRA_DEFS = {
    "wrma_flush_get": ["-DTEST_PUT"],
    "win_shared_rma_flush_load": ["-DTEST_PUT"],
    "overlap_wins_rma": ["-DTEST_PUT"],
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("dir", nargs="?", default="coll")
    ap.add_argument("--jobs", type=int, default=6)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--only", default="")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    d = args.dir
    out_path = args.out or f"/tmp/mpich3_{d}_results.json"
    os.makedirs("/tmp/mpich3", exist_ok=True)

    np_of = {}
    rtest_of = {}
    active = set()
    try:
        for line in open(f"{M}/{d}/testlist"):
            # honour np hints on commented-out entries too
            # (pt2pt/testlist:47 "#large_message 3")
            parts = line.lstrip("#").split()
            if len(parts) >= 2 and parts[1].isdigit():
                np_of.setdefault(parts[0], int(parts[1]))
            # MPICH runtests annotations: resultTest=TestStatus
            # (nonzero exit status is the expected result) and
            # resultTest=TestErrFatal (the program must abort).
            # Only ACTIVE lines count — a prose comment starting with
            # a test name must not invert the active entry's grading.
            if not line.startswith("#"):
                if parts:
                    active.add(parts[0])
                for p in parts[2:]:
                    if p.startswith("resultTest="):
                        rtest_of.setdefault(parts[0],
                                            p.split("=", 1)[1])
    except FileNotFoundError:
        pass

    # Sweep exactly the reference's ACTIVE testlist entries: files the
    # reference never runs (segtest needs MPICH-internal mpiimpl.h,
    # dims5 is commented out, glpid is absent from its dir's testlist)
    # must not count against parity.
    srcs = [s for s in sorted(glob.glob(f"{M}/{d}/*.c"))
            if os.path.basename(s)[:-2] not in HELPER_SRC]
    if args.only:
        # an explicit request overrides the testlist filter (debugging
        # a commented-out test must stay possible)
        keep = set(args.only.split(","))
        srcs = [s for s in srcs if os.path.basename(s)[:-2] in keep]
    elif active:
        srcs = [s for s in srcs if os.path.basename(s)[:-2] in active]
    results = {}
    lock = threading.Lock()

    def run_test(src: str) -> None:
        name = os.path.basename(src)[:-2]
        np_ranks = np_of.get(name, 2)   # MPICH runtests default: 2
        rtest = rtest_of.get(name)
        cfgs = TEST_CONFIGS.get(name,
                                ("smpi/simulate-computation:false",))
        if rtest in ("TestStatus", "TestErrFatal"):
            # inverted tests: the expected outcome is a nonzero exit
            # status (exit-status propagation / fatal-errhandler abort)
            check = "assert any(c != 0 for c in codes.values()), codes"
        else:
            check = "assert all(c == 0 for c in codes.values()), codes"
        extra_src = [f"{M}/{d}/{x}" for x in EXTRA_SRC.get(name, [])]
        extra_defs = EXTRA_DEFS.get(name, [])
        code = f"""
import sys; sys.path.insert(0, {REPO!r})
import jax; jax.config.update("jax_platforms", "cpu")
from simgrid_tpu.smpi.c_api import compile_program, run_c_program
compile_program([{src!r}, *{extra_src!r},
                 "{M}/util/mtest.c", "{M}/util/mtest_datatype.c",
                 "{M}/util/mtest_datatype_gen.c"],
                "/tmp/mpich3/{d}-{name}.so",
                extra_flags=["-I{M}/include", *{extra_defs!r}])
engine, codes = run_c_program("/tmp/mpich3/{d}-{name}.so",
    np_ranks={np_ranks}, configs={cfgs!r})
{check}
"""
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=args.timeout)
        except subprocess.TimeoutExpired:
            verdict = "timeout"
        else:
            out_l = r.stdout.lower()
            if name in PINNED_OUTPUT:
                required, forbidden = PINNED_OUTPUT[name]
                ok = (r.returncode == 0
                      and all(s in out_l for s in required)
                      and not any(s in out_l for s in forbidden))
            else:
                ok = r.returncode == 0 and (
                    "no errors" in out_l
                    or rtest in ("TestStatus", "TestErrFatal"))
            verdict = "PASS" if ok else (
                "compile-fail" if "smpicc failed" in r.stderr else "fail")
        with lock:
            results[name] = verdict
            n_done = len(results)
            print(f"[{n_done}/{len(srcs)}] {name:32s} {verdict} "
                  f"(np={np_ranks})", file=sys.stderr, flush=True)
            json.dump(results, open(out_path, "w"), indent=1, sort_keys=True)

    todo = list(srcs)

    def worker():
        while True:
            with lock:
                if not todo:
                    return
                src = todo.pop(0)
            run_test(src)

    threads = [threading.Thread(target=worker) for _ in range(args.jobs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    n = sum(1 for v in results.values() if v == "PASS")
    print(f"\nPASS {n}/{len(results)}", flush=True)
    json.dump(results, open(out_path, "w"), indent=1, sort_keys=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
