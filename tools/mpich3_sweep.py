"""Sweep mpich3-test/coll: compile+run each test in a subprocess."""
import glob, os, re as _re, subprocess, sys, json

M = "/root/reference/teshsuite/smpi/mpich3-test"
DIR = sys.argv[1] if len(sys.argv) > 1 else "coll"
OUT = {}
os.makedirs("/tmp/mpich3", exist_ok=True)
NP = {}
for line in open(f"{M}/{DIR}/testlist"):
    parts = line.split()
    if len(parts) >= 2 and parts[1].isdigit():
        NP.setdefault(parts[0], int(parts[1]))

for src in sorted(glob.glob(f"{M}/{DIR}/*.c")):
    name = os.path.basename(src)[:-2]
    np_ranks = NP.get(name, 4)
    code = f"""
import sys; sys.path.insert(0, "/root/repo")
from simgrid_tpu.smpi.c_api import compile_program, run_c_program
compile_program(["{src}", "{M}/util/mtest.c", "{M}/util/mtest_datatype.c", "{M}/util/mtest_datatype_gen.c"], "/tmp/mpich3/{DIR}-{name}.so",
                extra_flags=["-I{M}/include"])
engine, codes = run_c_program("/tmp/mpich3/{DIR}-{name}.so", np_ranks={np_ranks},
    configs=("smpi/simulate-computation:false",))
assert all(c == 0 for c in codes.values()), codes
"""
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=330)
    except subprocess.TimeoutExpired:
        OUT[name] = "timeout"
        print(f"{name:28s} timeout", flush=True)
        continue
    out_l = r.stdout.lower()
    # a few tests are output-only and never print the mtest "No Errors"
    # banner; for those alone a clean exit with no error markers passes
    OUTPUT_ONLY = {"zero-blklen-vector", "zeroblks"}
    ok = r.returncode == 0 and (
        "no errors" in out_l
        or (name in OUTPUT_ONLY
            and not _re.search(r"\berrors?\b|\bfail|abort|deadlock",
                               out_l)))
    OUT[name] = "PASS" if ok else (
        "compile-fail" if "smpicc failed" in r.stderr else "fail")
    print(f"{name:28s} {OUT[name]} (np={np_ranks})", flush=True)

n = sum(1 for v in OUT.values() if v == "PASS")
print(f"\nPASS {n}/{len(OUT)}")
json.dump(OUT, open(f"/tmp/mpich3_{DIR}_results.json", "w"), indent=1)
