#!/usr/bin/env python3
"""Drive the always-on campaign service from the command line.

Builds ONE scenario plan (synthetic maxmin-bench system or the seeded
64-host fat-tree drain — the same builders as tools/campaign_run.py),
stands up a :class:`~simgrid_tpu.serving.service.CampaignService` over
it (AOT plan cache + surrogate triage), submits a sweep of what-if
queries, drains the queue, and prints one JSON summary row:
submit→result latency percentiles, surrogate hit rate, plan-cache
hit/miss/compile-ms and admission counters.

The point of the service over the batch CLI: with ``--plan-cache DIR``
a warm restart deserializes every fleet program from disk (zero XLA
traces — ``plan_compile_ms`` 0), and with a seeded ``--corpus`` the
surrogate answers the easy bulk of the sweep from its conformal
predictor without touching the device.

Examples::

    tools/campaign_serve.py --scenarios 64 --batch 16
    tools/campaign_serve.py --scenarios 256 --plan-cache /tmp/plans \\
        --corpus bench_results/lmm_serve_corpus.jsonl
    tools/campaign_serve.py --platform fat-tree --flows 300 --exact
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from campaign_run import (build_fat_tree, build_synthetic,  # noqa: E402
                          force_host_device_count)


def build_specs(args):
    """A deterministic mixed sweep: bandwidth/size scaling families
    (surrogate-learnable structure) with a seeded fault stripe."""
    from simgrid_tpu.parallel.campaign import ScenarioSpec
    n_fault = int(round(args.scenarios * args.faults))
    specs = []
    for s in range(args.scenarios):
        specs.append(ScenarioSpec(
            seed=s,
            bw_scale=1.0 + 0.1 * (s % 5),
            size_scale=1.0 + 0.05 * (s % 3),
            fault_mtbf=args.mtbf if s < n_fault else None,
            fault_mttr=args.mttr,
            fault_horizon=args.horizon,
            label=f"serve{s}"))
    return specs


def percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--platform", choices=["synthetic", "fat-tree"],
                    default="synthetic")
    ap.add_argument("--n_c", type=int, default=96)
    ap.add_argument("--n_v", type=int, default=400)
    ap.add_argument("--deg", type=int, default=3)
    ap.add_argument("--flows", type=int, default=300,
                    help="fat-tree platform: number of drain flows")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--scenarios", type=int, default=64,
                    help="queries submitted to the service")
    ap.add_argument("--batch", type=int, default=None,
                    help="resident fleet width (default: the "
                         "serve/batch config flag)")
    ap.add_argument("--superstep", type=int, default=8)
    ap.add_argument("--pipeline", type=int, default=0)
    ap.add_argument("--mesh", type=int, default=0)
    ap.add_argument("--faults", type=float, default=0.25,
                    help="fraction of scenarios with a fault dimension")
    ap.add_argument("--fault-mode", choices=["on", "static", "off"],
                    default=None)
    ap.add_argument("--mtbf", type=float, default=400.0)
    ap.add_argument("--mttr", type=float, default=50.0)
    ap.add_argument("--horizon", type=float, default=600.0)
    ap.add_argument("--plan-cache", default=None, metavar="DIR",
                    help="AOT plan-cache directory (warm restarts "
                         "skip XLA tracing entirely)")
    ap.add_argument("--corpus", action="append", default=[],
                    metavar="JSONL",
                    help="seed the surrogate corpus from these jsonl "
                         "files (spec dict + final clock rows; "
                         "repeatable)")
    ap.add_argument("--corpus-log", default=None, metavar="JSONL",
                    help="append every device-served row here")
    ap.add_argument("--no-surrogate", action="store_true",
                    help="device path for every query")
    ap.add_argument("--exact", action="store_true",
                    help="submit every query with exact=True "
                         "(bypass surrogate triage)")
    ap.add_argument("--check", type=int, default=-1,
                    help="ticket index to spot-check against the solo "
                         "oracle (-1: skip; surrogate-answered "
                         "tickets report interval coverage instead)")
    ap.add_argument("--out", default=None,
                    help="append the summary row to this jsonl file")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU JAX backend")
    args = ap.parse_args()

    # before jax initializes its backends, for every stage
    force_host_device_count(args.mesh)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from simgrid_tpu.ops import opstats
    from simgrid_tpu.parallel.campaign import ScenarioPlan
    from simgrid_tpu.serving import (CampaignService, PlanCache,
                                     RuntimeSurrogate)
    from simgrid_tpu.utils.config import config

    base, meta = (build_fat_tree(args) if args.platform == "fat-tree"
                  else build_synthetic(args))
    plan = ScenarioPlan(superstep=args.superstep,
                        pipeline=args.pipeline,
                        mesh=args.mesh or None,
                        fault_mode=args.fault_mode, **base)

    plan_cache = PlanCache(args.plan_cache) if args.plan_cache else None
    surrogate = None
    if not args.no_surrogate and str(config["serve/surrogate"]) == "on":
        surrogate = RuntimeSurrogate(
            min_corpus=int(config["serve/surrogate-min-corpus"]),
            rel_tol=float(config["serve/surrogate-rel-tol"]),
            confidence=float(config["serve/surrogate-confidence"]))
        if args.corpus:
            surrogate.load_corpus(args.corpus)

    service = CampaignService(plan, batch=args.batch,
                              plan_cache=plan_cache,
                              surrogate=surrogate,
                              corpus_log=args.corpus_log,
                              pipeline=args.pipeline,
                              mesh=args.mesh or None)
    specs = build_specs(args)

    t0 = time.perf_counter()
    with opstats.scoped("campaign_serve") as stats:
        tickets = service.submit_many(specs, exact=args.exact)
        service.drain()
    wall = time.perf_counter() - t0

    lat = [t.latency_ms for t in tickets if t.latency_ms is not None]
    dev_lat = [t.latency_ms for t in tickets
               if t.result is not None and t.result.source == "device"]
    first_dev = min(
        (t.done_at for t in tickets
         if t.result is not None and t.result.source == "device"
         and t.done_at is not None), default=None)
    counters = service.counters()
    row = dict(meta, tool="campaign_serve",
               scenarios=args.scenarios, batch=service.batch,
               superstep=args.superstep, pipeline=args.pipeline,
               mesh=args.mesh,
               fault_scenarios=int(round(args.scenarios * args.faults)),
               wall_ms=round(wall * 1e3, 1),
               submit_to_first_device_ms=(
                   None if first_dev is None
                   else round((first_dev - t0) * 1e3, 1)),
               latency_p50_ms=round(percentile(lat, 50), 3),
               latency_p99_ms=round(percentile(lat, 99), 3),
               device_latency_p50_ms=(
                   round(percentile(dev_lat, 50), 3) if dev_lat
                   else None),
               surrogate_hit_rate=round(
                   counters["surrogate_answers"]
                   / max(1, args.scenarios), 4),
               dispatches=int(stats.get("dispatches", 0)),
               solver_fallbacks=int(
                   stats.get("solver_fallbacks", 0)),
               errors=[t.spec.label for t in tickets
                       if t.result is not None and t.result.error])
    row.update({k: (round(v, 1) if isinstance(v, float) else int(v))
                for k, v in counters.items()})
    if 0 <= args.check < len(tickets):
        t = tickets[args.check]
        solo = plan.solo(t.spec)
        if t.result is not None and t.result.source == "device":
            row["solo_check"] = dict(
                ticket=args.check, source="device",
                events_bit_identical=solo.events == t.result.events,
                clock_bit_identical=solo.t == t.result.t,
                fault_events_bit_identical=(
                    solo.fault_events == t.result.fault_events))
        elif t.result is not None:
            row["solo_check"] = dict(
                ticket=args.check, source=t.result.source,
                interval_covers_truth=(
                    t.result.lo <= solo.t <= t.result.hi))
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as fh:
            fh.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
