#!/usr/bin/env python
"""simlint — run the AST invariant rules over the tree.

Usage::

    python tools/simlint.py [paths...] [options]

Paths default to ``simgrid_tpu tools`` (repo-relative).  Exit status 0
means no NEW findings and no stale baseline entries; 1 means there is
something to fix; 2 is an operational error (bad arguments, unreadable
baseline).

Options:
    --json              machine-readable report on stdout
    --baseline PATH     baseline file (default tools/simlint_baseline.json;
                        pass --baseline '' to run baseline-less)
    --write-baseline    rewrite the baseline to grandfather every
                        current finding, then exit 0
    --rule ID           run only this rule (repeatable)
    --list-rules        print rule ids + one-line docs and exit

The baseline only ever shrinks: fix a grandfathered finding and the
now-stale entry fails the run until it is deleted (rerun with
``--write-baseline`` or edit the JSON).  New code never gets new
baseline entries — fix it or suppress it inline with
``# simlint: ignore[rule-id] -- reason``.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from simgrid_tpu.analysis import (ALL_RULES, apply_baseline,  # noqa: E402
                                  dump_baseline, findings_to_json,
                                  format_findings, lint_paths,
                                  load_baseline, make_baseline)

DEFAULT_PATHS = ("simgrid_tpu", "tools")
DEFAULT_BASELINE = os.path.join("tools", "simlint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="simlint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help="repo-relative files/dirs "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--root", default=REPO_ROOT,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:22s} {r.doc}")
        return 0

    rules = list(ALL_RULES)
    if args.rule:
        by_id = {r.id: r for r in ALL_RULES}
        unknown = [i for i in args.rule if i not in by_id]
        if unknown:
            print(f"simlint: unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [by_id[i] for i in args.rule]

    paths = args.paths or list(DEFAULT_PATHS)
    findings = lint_paths(args.root, paths, rules)

    baseline_path = (os.path.join(args.root, args.baseline)
                     if args.baseline
                     and not os.path.isabs(args.baseline)
                     else args.baseline)

    if args.write_baseline:
        if not baseline_path:
            print("simlint: --write-baseline needs --baseline",
                  file=sys.stderr)
            return 2
        dump_baseline(make_baseline(findings), baseline_path)
        print(f"simlint: baselined {len(findings)} finding(s) -> "
              f"{os.path.relpath(baseline_path, args.root)}")
        return 0

    baseline = None
    if baseline_path and os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, OSError) as e:
            print(f"simlint: cannot load baseline: {e}",
                  file=sys.stderr)
            return 2
    if baseline is not None and args.rule:
        # a --rule run only produced that rule's findings, so other
        # rules' grandfathered entries would all read as "stale" —
        # scope the baseline to the selected rules before diffing
        selected = {r.id for r in rules}
        baseline = dict(
            baseline,
            entries=[e for e in baseline.get("entries", [])
                     if e.get("rule") in selected])
    new, stale = apply_baseline(findings, baseline)
    baselined = len(findings) - len(new)

    if args.json:
        print(findings_to_json(new, stale, baselined))
    else:
        report = format_findings(new, stale)
        if report:
            print(report)
        print(f"simlint: {len(new)} new finding(s), {baselined} "
              f"baselined, {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
