#!/usr/bin/env python
"""proglint — check every registered compiled program's contract.

Usage::

    python tools/proglint.py [options]

Stages every program in ``simgrid_tpu.analysis.prog.registry``
(through the same ``jit().trace()`` / ``.lower()`` path the serving
plan cache compiles) and runs the IR contract rules over the jaxpr
and StableHLO.  Exit status 0 means no NEW findings and no stale
baseline entries; 1 means there is something to fix; 2 is an
operational error.

Options:
    --json              machine-readable report on stdout
    --baseline PATH     baseline file (default tools/proglint_baseline.json
                        when it exists; pass --baseline '' to run
                        baseline-less)
    --write-baseline    rewrite the baseline to grandfather every
                        current finding, then exit 0
    --rule ID           run only this rule (repeatable)
    --program NAME      check only this registry entry (substring
                        match, repeatable)
    --list-rules        print rule ids and exit
    --list-programs     print registered program names and exit

The baseline is shrink-only, exactly like simlint's: fix a
grandfathered finding and the now-stale entry fails the run until it
is removed.  The expected steady state of THIS baseline is empty —
every registered program satisfies its contract.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from simgrid_tpu.analysis import (apply_baseline,  # noqa: E402
                                  dump_baseline, findings_to_json,
                                  format_findings, load_baseline,
                                  make_baseline)

DEFAULT_BASELINE = os.path.join("tools", "proglint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="proglint", description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID")
    ap.add_argument("--program", action="append", default=None,
                    metavar="NAME")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-programs", action="store_true")
    ap.add_argument("--root", default=REPO_ROOT,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    # staging imports jax + the ops modules; keep that off the
    # --list-* fast paths' error surface but load lazily either way
    from simgrid_tpu.analysis.prog import (ALL_PROG_RULE_IDS,
                                           iter_programs,
                                           lint_programs)

    if args.list_rules:
        for rid in ALL_PROG_RULE_IDS:
            print(rid)
        return 0
    specs = iter_programs()
    if args.list_programs:
        for spec in specs:
            print(spec.name)
        return 0

    if args.rule:
        unknown = [i for i in args.rule
                   if i not in ALL_PROG_RULE_IDS]
        if unknown:
            print("proglint: unknown rule id(s): "
                  + ", ".join(unknown), file=sys.stderr)
            return 2
    if args.program:
        specs = [s for s in specs
                 if any(pat in s.name for pat in args.program)]
        if not specs:
            print("proglint: no registered program matches "
                  + ", ".join(args.program), file=sys.stderr)
            return 2

    findings = lint_programs(specs, rules=args.rule)

    baseline_path = (os.path.join(args.root, args.baseline)
                     if args.baseline
                     and not os.path.isabs(args.baseline)
                     else args.baseline)

    if args.write_baseline:
        if not baseline_path:
            print("proglint: --write-baseline needs --baseline",
                  file=sys.stderr)
            return 2
        dump_baseline(make_baseline(findings), baseline_path)
        print(f"proglint: baselined {len(findings)} finding(s) -> "
              f"{os.path.relpath(baseline_path, args.root)}")
        return 0

    baseline = None
    if baseline_path and os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, OSError) as e:
            print(f"proglint: cannot load baseline: {e}",
                  file=sys.stderr)
            return 2
    if baseline is not None and (args.rule or args.program):
        # a scoped run only produced the selected rules'/programs'
        # findings — scope the baseline the same way so everything
        # else doesn't read as stale (mirrors simlint --rule)
        checked = {f"program:{s.name}" for s in specs}
        entries = [e for e in baseline.get("entries", [])
                   if (not args.rule or e.get("rule") in args.rule)
                   and e.get("path") in checked]
        baseline = dict(baseline, entries=entries)
    new, stale = apply_baseline(findings, baseline)
    baselined = len(findings) - len(new)

    if args.json:
        print(findings_to_json(new, stale, baselined))
    else:
        report = format_findings(new, stale)
        if report:
            print(report)
        print(f"proglint: {len(specs)} program(s) checked, "
              f"{len(new)} new finding(s), {baselined} baselined, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
