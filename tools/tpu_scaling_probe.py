#!/usr/bin/env python3
"""Scaling laws for the axon-TPU's gather/scatter costs.

Two questions the round-body redesign hinges on:
  1. element scaling: cost of one [N/4,4] gather / scatter-add as N
     grows 64k -> 2M.  Linear => minimize gathered elements; flat =>
     per-op overhead dominates, minimize op COUNT.
  2. op-count scaling: K chained gathers in ONE jit at fixed N.

Chained-dispatch timing (one fetch), appends to tpu_opcost.jsonl.
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "bench_results", "tpu_opcost.jsonl")


def main() -> int:
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    dtype = jnp.float32
    rec = {"platform": dev.platform, "probe": "scaling",
           "ts": round(time.time(), 1)}
    C = 16384
    rng = np.random.default_rng(7)
    tab = jnp.asarray(rng.uniform(1, 2, C).astype(np.float32))
    sync = 66.0

    def timed(f, K=16):
        s = jnp.asarray(0.0, dtype)
        float(np.asarray(f(s).ravel()[0]))
        t0 = time.perf_counter()
        s = jnp.asarray(0.0, dtype)
        for _ in range(K):
            s = f(s).ravel()[0] * 1e-30
        float(np.asarray(s))
        return round((time.perf_counter() - t0 - sync / 1e3) / K * 1e3, 3)

    for N in (65536, 131072, 262144, 524288, 1048576, 2097152):
        idx = jnp.asarray(rng.integers(0, C, (N // 4, 4)).astype(np.int32))
        w = jnp.asarray(rng.uniform(0.5, 1.5, (N // 4, 4)).astype(
            np.float32))
        g = jax.jit(lambda s, idx=idx: jnp.take(tab + s, idx))
        rec[f"gather_{N}"] = timed(g)
        sc = jax.jit(lambda s, idx=idx, w=w: jnp.zeros(C, dtype)
                     .at[idx.ravel()].add(w.ravel() + s))
        rec[f"scatter_{N}"] = timed(sc)
        print(f"  N={N}: gather {rec[f'gather_{N}']} ms, "
              f"scatter {rec[f'scatter_{N}']} ms")

    # op-count scaling at N=524288
    idx = jnp.asarray(rng.integers(0, C, (131072, 4)).astype(np.int32))
    for K_OPS in (1, 2, 4, 8):
        def chain(s, K_OPS=K_OPS):
            x = tab + s
            acc = jnp.zeros((131072, 4), dtype)
            for i in range(K_OPS):
                acc = acc + jnp.take(x + i * 1e-30, idx)
            return acc
        rec[f"chain{K_OPS}_gathers"] = timed(jax.jit(chain))
        print(f"  {K_OPS} chained gathers: {rec[f'chain{K_OPS}_gathers']}"
              " ms")

    # dense-vector ops for comparison: elementwise + reduction over [N]
    big = jnp.asarray(rng.uniform(1, 2, 2097152).astype(np.float32))
    f = jax.jit(lambda s: ((big + s) * 1.5 - (big + s) ** 2).sum(
        keepdims=True))
    rec["dense_2M_elemwise_reduce"] = timed(f)
    print(f"  dense 2M elemwise+reduce: {rec['dense_2M_elemwise_reduce']}"
          " ms")

    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
