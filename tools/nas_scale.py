#!/usr/bin/env python3
"""BASELINE config #3 analog: NAS benchmark at rank-count on a torus
cluster platform (the reference ships EP/IS/DT; LU is not in its NAS
port, so IS — the communication-heavy kernel — is the headline).

Usage: python tools/nas_scale.py [is|ep|dt] [np] [CLASS]
Prints simulated-sec and wall-sec (the BASELINE.json metric shape)."""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from simgrid_tpu.smpi.c_api import compile_program, run_c_program

NAS = "/root/reference/examples/smpi/NAS"

TORUS = """<?xml version='1.0'?>
<platform version="4.1">
  <zone id="world" routing="Full">
    <cluster id="torus" prefix="node-" radical="0-{last}" suffix=""
             speed="1Gf" bw="10Gbps" lat="10us" topology="TORUS"
             topo_parameters="{topo}"/>
  </zone>
</platform>
"""

SRCS = {"ep": ["ep.c", "nas_common.c"],
        "is": ["is.c", "nas_common.c"],
        "dt": ["dt.c", "nas_common.c", "DGraph.c"]}


def main():
    bench = sys.argv[1] if len(sys.argv) > 1 else "is"
    np_ranks = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    klass = sys.argv[3] if len(sys.argv) > 3 else "S"

    # cube-ish torus covering np_ranks
    side = 2
    while side ** 3 < np_ranks:
        side += 1
    topo = f"{side},{side},{side}"
    fd, plat = tempfile.mkstemp(suffix=".xml")
    os.close(fd)
    with open(plat, "w") as f:
        f.write(TORUS.format(last=side ** 3 - 1, topo=topo))

    with tempfile.TemporaryDirectory() as d:
        so = os.path.join(d, f"{bench}.so")
        compile_program([os.path.join(NAS, s) for s in SRCS[bench]], so)
        args = [str(np_ranks), klass] + (["BH"] if bench == "dt" else [])
        t0 = time.perf_counter()
        engine, codes = run_c_program(
            so, np_ranks=np_ranks, platform=plat,
            hosts=[f"node-{i}" for i in range(np_ranks)],
            app_args=args)
        wall = time.perf_counter() - t0
    os.unlink(plat)
    bad = {r: c for r, c in codes.items() if c not in (0, 1)}
    print(f"nas-{bench}.{klass} np={np_ranks} on {topo} torus: "
          f"simulated {engine.clock:.3f}s, wall {wall:.1f}s "
          f"(sim/wall {engine.clock / wall:.3f}), "
          f"bad_exits={bad or 'none'}")


if __name__ == "__main__":
    main()
