#!/usr/bin/env python
"""graphicator: dump a platform's routing graph as Graphviz dot
(reference tools/graphicator/graphicator.cpp).

Usage: python tools/graphicator.py platform.xml out.dot
Hosts are boxes, routers are points, links are edges labeled with
bandwidth; every host-pair route contributes its edges once."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def graphicator(platform: str, out_path: str) -> None:
    from simgrid_tpu import s4u
    from simgrid_tpu.routing.zone import NetPointType

    e = s4u.Engine(["graphicator"])
    e.load_platform(platform)
    engine = e.pimpl

    lines = ["graph platform {", "  overlap=scale;"]
    for netpoint in engine.netpoints.values():
        if netpoint.kind == NetPointType.HOST:
            lines.append(f'  "{netpoint.name}" [shape=box];')
        elif netpoint.kind == NetPointType.ROUTER:
            lines.append(f'  "{netpoint.name}" [shape=point];')

    # Edge per link: endpoint resolution via every host-pair route.
    edges = set()
    hosts = list(engine.hosts.values())
    for i, src in enumerate(hosts):
        for dst in hosts[i + 1:]:
            route = []
            try:
                src.route_to(dst, route)
            except AssertionError:
                continue
            prev = src.name
            for link in route:
                edge = (prev, link.name)
                if edge not in edges:
                    edges.add(edge)
                prev = link.name
            edge = (prev, dst.name)
            edges.add(edge)
    for link in engine.links.values():
        lines.append(f'  "{link.name}" [shape=ellipse, '
                     f'label="{link.name}\\n{link.get_bandwidth():.3g}bps"];')
    for a, b in sorted(edges):
        lines.append(f'  "{a}" -- "{b}";')
    lines.append("}")
    with open(out_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"{out_path}: {len(engine.hosts)} hosts, "
          f"{len(engine.links)} links, {len(edges)} edges")


if __name__ == "__main__":
    graphicator(sys.argv[1], sys.argv[2])
