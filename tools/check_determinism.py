#!/usr/bin/env python3
"""Static determinism lint for the simulation core.

The kernel, the solver and the fault-injection subsystem must be
bit-reproducible: all randomness goes through the seeded RngStream
(simgrid_tpu/utils/rngstream.py) and all time through the simulated
clock.  This lint fails if any file under the audited packages reaches
for the wall clock or Python's global RNG:

    random.<anything>      (incl. np.random / jax.random)
    time.time(
    datetime.now(

Comments are stripped before matching so prose mentioning the banned
names stays legal; code and docstrings are audited as written.
Run directly (exit 1 on violations) or through tests/test_determinism_lint.py.

``--runtime-drain`` additionally executes the drain executor's three
dispatch shapes (unfused, fused, superstep) twice each on a seeded
system and verifies (a) run-to-run bit-reproducibility and (b)
cross-mode completion-order equality — the dynamic counterpart of the
static lint for the superstep path, whose ring-buffer event extraction
must stay deterministic.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

AUDITED_DIRS = (
    os.path.join("simgrid_tpu", "kernel"),
    os.path.join("simgrid_tpu", "ops"),
    os.path.join("simgrid_tpu", "faults"),
)

BANNED = [
    (re.compile(r"\brandom\s*\."), "random."),
    (re.compile(r"\btime\.time\s*\("), "time.time("),
    (re.compile(r"\bdatetime\.now\s*\("), "datetime.now("),
]

_COMMENT = re.compile(r"#.*$")


def collect_violations(repo_root: str) -> List[Tuple[str, int, str]]:
    """(relative path, line number, stripped line) for every banned
    pattern occurrence under the audited directories."""
    violations: List[Tuple[str, int, str]] = []
    for rel_dir in AUDITED_DIRS:
        top = os.path.join(repo_root, rel_dir)
        if not os.path.isdir(top):
            continue
        for dirpath, _dirnames, filenames in os.walk(top):
            for filename in sorted(filenames):
                if not filename.endswith(".py"):
                    continue
                path = os.path.join(dirpath, filename)
                with open(path, encoding="utf-8") as f:
                    for lineno, line in enumerate(f, 1):
                        code = _COMMENT.sub("", line)
                        for pattern, label in BANNED:
                            if pattern.search(code):
                                violations.append(
                                    (os.path.relpath(path, repo_root),
                                     lineno, line.strip()))
                                break
    return violations


def check_drain_runtime(seed: int = 13, n_c: int = 128, n_v: int = 800,
                        k: int = 8) -> List[str]:
    """Dynamic determinism of the drain executor incl. the superstep
    path: two runs per mode must be bit-identical (events, advance
    count, clock) and all modes must agree on completion ORDER.
    Returns a list of problem descriptions (empty = OK)."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_arrays
    from simgrid_tpu.ops.lmm_drain import DrainSim

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    sizes = rng.choice(np.linspace(1e5, 2e6, 32), n_v)
    E = arrays.n_elem

    def run(**kw):
        sim = DrainSim(arrays.e_var[:E], arrays.e_cnst[:E],
                       arrays.e_w[:E].astype(np.float64),
                       arrays.c_bound[:arrays.n_cnst].astype(np.float64),
                       sizes, eps=1e-9, dtype=np.float64,
                       repack_min=64, **kw)
        sim.run()
        return sim

    problems: List[str] = []
    streams = {}
    for label, kw in (("unfused", {}), ("fused", dict(fused=True)),
                      ("superstep", dict(superstep=k))):
        a, b = run(**kw), run(**kw)
        if a.events != b.events or a.advances != b.advances \
                or a.t != b.t:
            problems.append(f"{label}: two identical runs diverged "
                            f"({a.advances} vs {b.advances} advances)")
        streams[label] = [f for _, f in a.events]
    base = streams["unfused"]
    for label in ("fused", "superstep"):
        if streams[label] != base:
            problems.append(
                f"{label}: completion order differs from unfused")
    return problems


def main(argv: List[str]) -> int:
    if "--runtime-drain" in argv:
        problems = check_drain_runtime()
        if problems:
            print("check_determinism: drain runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: drain runtime OK "
              "(unfused/fused/superstep bit-reproducible, orders agree)")
        argv = [a for a in argv if a != "--runtime-drain"]
    repo_root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = collect_violations(repo_root)
    if not violations:
        print("check_determinism: OK (%s clean)" % ", ".join(AUDITED_DIRS))
        return 0
    print("check_determinism: nondeterminism sources found "
          "(use utils/rngstream.py and the simulated clock):")
    for path, lineno, text in violations:
        print(f"  {path}:{lineno}: {text}")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
