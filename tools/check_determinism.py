#!/usr/bin/env python3
"""Static determinism lint for the simulation core.

The kernel, the solver and the fault-injection subsystem must be
bit-reproducible: all randomness goes through the seeded RngStream
(simgrid_tpu/utils/rngstream.py) and all time through the simulated
clock.  The static half is simlint (simgrid_tpu/analysis +
tools/simlint.py): run bare, this tool runs the ``wallclock-rng``
rule — an AST lint with import/alias resolution, so ``from time
import time`` or ``import random as rnd`` can't dodge it — over the
audited packages; ``--quick`` runs the FULL simlint rule set (FMA
pinning, hidden host syncs, dtype discipline, iteration order,
opstats registry) against the checked-in
tools/simlint_baseline.json.
Run directly (exit 1 on violations) or through tests/test_determinism_lint.py.

``--runtime-drain`` additionally executes the drain executor's three
dispatch shapes (unfused, fused, superstep) twice each on a seeded
system and verifies (a) run-to-run bit-reproducibility and (b)
cross-mode completion-order equality — the dynamic counterpart of the
static lint for the superstep path, whose ring-buffer event extraction
must stay deterministic.

``--runtime-warmstart`` runs a seeded mutating workload (flow churn
over clustered constraints plus a deep background chain) twice per
solve mode — cold full restart every solve vs warm-started selective
(ops.lmm_warm) — and asserts (a) run-to-run bit-reproducibility per
mode and (b) bit-identical completion-event order and final clocks
ACROSS modes, plus that the warm runs actually reused their carry.

``--runtime-batch`` drains a 64-replica scenario fleet (mixed fault
seeds + sweep overrides over one shared platform flattening) through
the batched executor (ops.lmm_batch via parallel.campaign) and
asserts that sampled replicas extracted from the batch have
bit-identical event order AND clocks to the same scenario run solo
through ops.lmm_drain.DrainSim — the batching determinism contract.

``--runtime-pipeline`` runs the speculative pipelined drain (solo
DrainSim at depths 1 and 2, and a batched fleet through
parallel.campaign) against the unpipelined superstep path and asserts
bit-identical event order, timestamps and final clocks — INCLUDING
forced-mispredict runs (mid-drain device repacks and
round-budget-starved rescue exits, both of which must discard the
in-flight speculative superstep and replay it), where it additionally
asserts that speculation really was rolled back (otherwise nothing
was tested).

``--runtime-shard`` drains mesh-sharded scenario fleets — the replica
axis of the batched executor split across devices with
``NamedSharding(mesh, PartitionSpec("batch"))`` (ops.lmm_batch
``mesh=``) — and asserts every replica is bit-identical (event order,
timestamps, Kahan clocks) to the single-device vmapped fleet AND to
sampled solo runs, including ragged fleets (B not divisible by the
mesh: dead padding lanes must log zero events), budget-rescue exits
and pipeline depth >= 2 (where it additionally asserts the forced
mispredicts really rolled speculation back).  Needs >= 2 devices: on
CPU run under ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
(the standalone tool sets this itself before JAX initializes).

``--runtime-phase`` runs an NAS-style compute/comm alternation (every
completion posts its successor exec or comm — the mutating-phase
shape the device-resident transition payloads exist for) with the
drain fast path on vs off and asserts bit-identical completion
events, timestamps and engine clocks, including forced RESUMABLE
mutations (a mid-phase bandwidth change, absorbed as a bound
scatter), forced NON-RESUMABLE mutations (a deadline'd flow, which
must take the replay fallback — asserted via the invalidation-cause
histogram), and the pipelined fleet variant (speculative supersteps
riding the mutating phase).

``--runtime-fault`` drains a small fleet with per-replica fault event
tapes (2 faulted lanes + 1 clean lane) and asserts the tape contract:
``FaultCampaign.compile_tape`` carries bitwise the same dates as the
``generate()`` schedule an engine-side Profile would replay; every
lane of the batched fleet is bit-identical — events, fault fires AND
Kahan clocks — to the same scenario run solo; at least one tape event
actually FIRED mid-drain (otherwise nothing was tested) and the drain
kept completing after it; ``fault_mode="static"`` still reproduces
the pre-tape mean-availability folding exactly; and the tape composes
with pipeline depth 2 and a 2-device mesh unchanged.

``--runtime-serve`` drives the always-on campaign service
(simgrid_tpu/serving) with more exact queries than the resident fleet
has lanes, so ADMISSION BATCHING must revive dead lanes mid-flight,
and asserts the serving determinism contract: every device-served
ticket — including every lane admitted into a partially-drained fleet
— is bit-identical (completion events, fired fault events AND Kahan
clocks) to ``ScenarioPlan.solo`` on the same spec; at least one lane
really was admitted and at least one fault tape event fired
(otherwise nothing was tested); under pipeline depth 2 the admissions
must additionally have rolled speculation back; and the whole thing
routes through the AOT plan cache, so the executable path is the
audited path.

``--runtime-resume`` audits the preemption-safe campaign contract: a
service is KILLED at an arbitrary collect boundary (checkpoint +
halt), the object discarded, and a fresh service rebuilt with
``CampaignService.resume`` — every ticket must come out bit-identical
(events, fired fault events AND Kahan clocks) to the uninterrupted
run and to ``ScenarioPlan.solo``, including pipeline depth 2 (the
kill lands with speculation in flight, which is never persisted) and
active fault tapes; the resumed fleet must rebuild WARM through the
AOT plan cache (zero new compiles); resuming the same token twice is
idempotent; and a single NaN-poisoned lane quarantines with a
``nan_solve`` LaneFault on its own ticket while every other lane
stays bit-identical to solo.

``--runtime-collective`` proves the collective schedule tapes: the
captured comm sequences of the real ``smpi/coll.py`` algorithms equal
the mirrored generators at non-power-of-two rank counts, and the
tape-driven superstep runs — solo, k=1, pipelined, batched fleets and
fault-tape-composed — are bit-identical (completion events, fired
activations AND Kahan clocks) to the dispatch-per-advance
``HostMaestro`` baseline, at a fraction of its dispatch count; with a
C compiler present, a real NAS-style IS kernel (allreduce + alltoall
iterations through ``smpi/c_api``) is captured live and replayed on
the tape path end to end.

``--quick`` is the CI mode: the static lint plus small-N instances of
every runtime check (drain, warm-start, batch, pipeline, shard,
phase, fault, serve, resume, collective), sized to finish in seconds so the tier-1 suite
can run it on every test pass (tests/test_determinism_lint.py, whose
conftest forces an 8-virtual-device CPU so the mesh path is exercised
on every run).
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

#: what the static half audits (simlint path scopes govern per-rule
#: coverage inside these)
AUDITED_PATHS = ("simgrid_tpu", "tools")

_OWN_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _simlint():
    """The simlint package (from THIS repo, wherever the checker was
    loaded from — tests exec this file via importlib)."""
    if _OWN_ROOT not in sys.path:
        sys.path.insert(0, _OWN_ROOT)
    from simgrid_tpu import analysis
    return analysis


def collect_violations(repo_root: str) -> List[Tuple[str, int, str]]:
    """(relative path, line number, stripped line) for every
    wall-clock / global-RNG use under the audited packages.

    Backed by the simlint ``wallclock-rng`` AST rule (import/alias
    resolution, so ``from time import time`` or ``import random as
    rnd`` can't dodge it) — the successor of the old regex scan, same
    return shape."""
    analysis = _simlint()
    rules = [r for r in analysis.ALL_RULES if r.id == "wallclock-rng"]
    findings = analysis.lint_paths(repo_root, AUDITED_PATHS, rules)
    return [(f.path.replace("/", os.sep), f.line, f.snippet)
            for f in findings]


def collect_simlint_problems(repo_root: str) -> List[str]:
    """The full simlint rule set against the checked-in baseline:
    formatted problem strings for every NEW finding and every stale
    baseline entry (empty = clean)."""
    analysis = _simlint()
    findings = analysis.lint_paths(repo_root, AUDITED_PATHS)
    baseline_path = os.path.join(repo_root, "tools",
                                 "simlint_baseline.json")
    baseline = None
    if os.path.exists(baseline_path):
        baseline = analysis.load_baseline(baseline_path)
    new, stale = analysis.apply_baseline(findings, baseline)
    problems = [f"{f.path}:{f.line}: [{f.rule}] {f.message}"
                for f in new]
    problems += [f"{e['path']}: stale simlint baseline entry "
                 f"[{e['rule']}] {e['snippet']!r} — fixed findings "
                 f"must leave tools/simlint_baseline.json too"
                 for e in stale]
    return problems


def check_drain_runtime(seed: int = 13, n_c: int = 128, n_v: int = 800,
                        k: int = 8) -> List[str]:
    """Dynamic determinism of the drain executor incl. the superstep
    path: two runs per mode must be bit-identical (events, advance
    count, clock) and all modes must agree on completion ORDER.
    Returns a list of problem descriptions (empty = OK)."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_arrays
    from simgrid_tpu.ops.lmm_drain import DrainSim

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    sizes = rng.choice(np.linspace(1e5, 2e6, 32), n_v)
    E = arrays.n_elem

    def run(**kw):
        sim = DrainSim(arrays.e_var[:E], arrays.e_cnst[:E],
                       arrays.e_w[:E].astype(np.float64),
                       arrays.c_bound[:arrays.n_cnst].astype(np.float64),
                       sizes, eps=1e-9, dtype=np.float64,
                       repack_min=64, **kw)
        sim.run()
        return sim

    problems: List[str] = []
    streams = {}
    for label, kw in (("unfused", {}), ("fused", dict(fused=True)),
                      ("superstep", dict(superstep=k))):
        a, b = run(**kw), run(**kw)
        if a.events != b.events or a.advances != b.advances \
                or a.t != b.t:
            problems.append(f"{label}: two identical runs diverged "
                            f"({a.advances} vs {b.advances} advances)")
        streams[label] = [f for _, f in a.events]
    base = streams["unfused"]
    for label in ("fused", "superstep"):
        if streams[label] != base:
            problems.append(
                f"{label}: completion order differs from unfused")
    return problems


def check_warmstart_runtime(seed: int = 17, n_clusters=24, per=12,
                            chain=48, steps=20) -> List[str]:
    """Dynamic determinism of the warm-started selective solve path: a
    seeded churny mini-drain (solve -> advance to next completion ->
    retire+replace flows) must produce bit-identical completion order,
    event times and final clock whether every solve restarts cold or
    warm-starts from the carried modified component."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from simgrid_tpu.ops import lmm_jax, make_new_maxmin_system
    from simgrid_tpu.utils.config import config

    def run(mode):
        saved = config["lmm/warm-start"], config["lmm/delta-upload"]
        config["lmm/warm-start"] = mode
        config["lmm/delta-upload"] = "on"
        try:
            rng = np.random.default_rng(seed)
            s = make_new_maxmin_system(True)
            s.solve_fn = lmm_jax.solve_jax
            # background chain: deep cold fixpoint, untouched by churn
            cs = [s.constraint_new(None, float(2.0 ** i))
                  for i in range(chain)]
            for i in range(chain - 1):
                v = s.variable_new(None, 1, -1, 2)
                s.expand(cs[i], v, 1)
                s.expand(cs[i + 1], v, 1)
            clusters = [s.constraint_new(None, float(rng.uniform(50, 200)))
                        for _ in range(n_clusters)]
            flows = []      # (var, remains, fid) in creation order
            next_fid = [0]

            def add_flow(k):
                v = s.variable_new(None, 1.0)
                s.expand(clusters[k], v, float(rng.choice([0.5, 1.0])))
                flows.append([v, float(rng.uniform(1e3, 1e4)),
                              next_fid[0]])
                next_fid[0] += 1

            for k in range(n_clusters):
                for _ in range(per):
                    add_flow(k)
            t = 0.0
            events = []
            for step in range(steps):
                if step % 4 == 3:
                    s.update_constraint_bound(
                        clusters[int(rng.integers(n_clusters))],
                        float(rng.uniform(50, 200)))
                s.solve()
                rates = [f[0].value for f in flows]
                dts = [f[1] / r for f, r in zip(flows, rates) if r > 0]
                if not dts:
                    break
                dt = min(dts)
                t += dt
                done = []
                for f, r in zip(flows, rates):
                    if r > 0:
                        f[1] -= r * dt
                        if f[1] <= 1e-9:
                            done.append(f)
                for f in done:
                    events.append((t, f[2]))
                    k = int(rng.integers(n_clusters))
                    s.variable_free(f[0])
                    flows.remove(f)
                    add_flow(k)
            ws = s.warm_solver
            return events, t, (ws.warm_solves if ws else 0)
        finally:
            config["lmm/warm-start"], config["lmm/delta-upload"] = saved

    problems: List[str] = []
    streams = {}
    for mode in ("cold", "on"):
        a, b = run(mode), run(mode)
        if a[:2] != b[:2]:
            problems.append(f"warm-start:{mode}: two identical runs "
                            f"diverged ({len(a[0])} vs {len(b[0])} events)")
        streams[mode] = a
    if streams["cold"][:2] != streams["on"][:2]:
        problems.append(
            "warm-started selective run diverged from cold-every-solve "
            f"(events {len(streams['cold'][0])} vs "
            f"{len(streams['on'][0])}, clocks {streams['cold'][1]!r} vs "
            f"{streams['on'][1]!r})")
    if streams["on"][2] == 0:
        problems.append("warm mode never reused its carry "
                        "(nothing was actually tested)")
    return problems


def check_batch_runtime(seed: int = 23, n_c: int = 64, n_v: int = 256,
                        batch: int = 64, k: int = 8,
                        solo_check=(0, 13, 37, 63)) -> List[str]:
    """Dynamic determinism of the batched multi-replica executor:
    replica j extracted from a `batch`-wide fleet (mixed fault seeds +
    sweep overrides) must have bit-identical completion events (order
    AND times) and final clock to the same scenario drained solo.
    Returns a list of problem descriptions (empty = OK)."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_arrays
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    specs = [ScenarioSpec(seed=s,
                          bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=400.0 if s % 2 else None,
                          fault_mttr=50.0, fault_horizon=600.0,
                          dead_flows=(s % 7,) if s % 3 == 0 else ())
             for s in range(batch)]
    campaign = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                        arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                        specs, eps=1e-9, dtype=np.float64, superstep=k)
    results = campaign.run_batched(batch=batch)

    problems: List[str] = []
    for r in results:
        if r.error:
            problems.append(f"replica {r.spec.label}: batched run "
                            f"errored: {r.error}")
    for j in solo_check:
        if j >= batch:
            continue
        solo = campaign.run_solo(j)
        got = results[j]
        if solo.error or got.error:
            continue        # already reported above
        if solo.events != got.events:
            ndiff = sum(1 for a, b in zip(solo.events, got.events)
                        if a != b)
            problems.append(
                f"replica {j}: batched events differ from solo "
                f"({len(got.events)} vs {len(solo.events)} events, "
                f"{ndiff} mismatched pairs)")
        if solo.t != got.t:
            problems.append(
                f"replica {j}: batched clock {got.t!r} != solo "
                f"{solo.t!r}")
    return problems


def check_pipeline_runtime(seed: int = 29, n_c: int = 64, n_v: int = 400,
                           k: int = 8, depths=(1, 2), batch: int = 8
                           ) -> List[str]:
    """Dynamic determinism of the speculative pipelined drain: the
    pipelined executors must be bit-identical — event order,
    timestamps, final clock, advance count — to the unpipelined
    superstep path, for the solo DrainSim (at every depth in `depths`,
    plus forced-mispredict runs: mid-drain repacks and a starved round
    budget, both of which discard in-flight supersteps) and for a
    `batch`-wide campaign fleet.  Also asserts that speculation
    actually happened (commits > 0) and that the forced-mispredict
    runs really rolled speculation back."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_arrays
    from simgrid_tpu.ops.lmm_drain import DrainSim
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    E = arrays.n_elem

    def run(**kw):
        sim = DrainSim(arrays.e_var[:E], arrays.e_cnst[:E],
                       arrays.e_w[:E].astype(np.float64),
                       arrays.c_bound[:arrays.n_cnst].astype(np.float64),
                       sizes, eps=1e-9, dtype=np.float64, **kw)
        sim.run()
        return sim

    problems: List[str] = []
    # -- solo: plain + forced-mispredict variants -----------------------
    variants = {
        "plain": dict(repack_min=1 << 62),
        # small repack_min: mid-drain device repacks fire, each one a
        # forced mispredict (the in-flight superstep ran on the
        # un-repacked arrays and must be discarded + replayed)
        "repack": dict(repack_min=32),
        # starved round budget: _FLAG_BUDGET exits + fused rescues,
        # the other mispredict class
        "budget": dict(repack_min=1 << 62, superstep_rounds=3),
    }
    for label, kw in variants.items():
        ref = run(superstep=k, pipeline=0, **kw)
        for depth in depths:
            a = run(superstep=k, pipeline=depth, **kw)
            b = run(superstep=k, pipeline=depth, **kw)
            if (a.events, a.t, a.advances) != (b.events, b.t, b.advances):
                problems.append(f"pipeline:{label}:d{depth}: two "
                                f"identical runs diverged")
            if a.events != ref.events or a.t != ref.t \
                    or a.advances != ref.advances:
                problems.append(
                    f"pipeline:{label}:d{depth}: diverged from the "
                    f"unpipelined superstep drain ({len(a.events)} vs "
                    f"{len(ref.events)} events, clocks {a.t!r} vs "
                    f"{ref.t!r})")
            if a.spec_committed == 0:
                problems.append(f"pipeline:{label}:d{depth}: no "
                                f"speculation committed (nothing "
                                f"was actually tested)")
            if label in ("repack", "budget") and a.spec_rolled_back == 0:
                problems.append(
                    f"pipeline:{label}:d{depth}: the forced mispredict "
                    f"never rolled speculation back (forcing failed)")
    # -- fleet: pipelined batched campaign vs unpipelined ---------------
    specs = [ScenarioSpec(seed=s, bw_scale=1.0 + 0.15 * (s % 4),
                          size_scale=1.0 + 0.05 * (s % 3),
                          dead_flows=(s % 5,) if s % 3 == 0 else ())
             for s in range(batch)]
    camp = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                    arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                    specs, eps=1e-9, dtype=np.float64, superstep=k)
    ref_fleet = camp.run_batched(batch=batch, pipeline=0)
    for depth in depths:
        got = camp.run_batched(batch=batch, pipeline=depth)
        for j in range(batch):
            if got[j].events != ref_fleet[j].events \
                    or got[j].t != ref_fleet[j].t:
                problems.append(
                    f"pipeline:fleet:d{depth}: replica {j} diverged "
                    f"from the unpipelined fleet drain")
                break
    return problems


def check_shard_runtime(seed: int = 31, n_c: int = 48, n_v: int = 160,
                        batch: int = 8, k: int = 8, shards=(2, 4),
                        depths=(0, 2)) -> List[str]:
    """Dynamic determinism of the mesh-sharded fleet executor: a
    replica of a fleet whose batch axis is sharded over a device mesh
    must be bit-identical — events, timestamps, final Kahan clock — to
    the single-device vmapped fleet and to solo runs, for plain
    drains, ragged fleets (padded dead lanes must stay silent),
    budget-starved rescue exits, and speculative pipeline depths >= 2
    (whose forced mispredicts must actually roll back).  Returns a
    list of problem descriptions (empty = OK)."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    from bench import build_arrays
    from simgrid_tpu.ops import opstats
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    need = max(shards)
    if jax.device_count() < need:
        return [f"shard: only {jax.device_count()} device(s) visible; "
                f"the mesh path needs >= {need} — on CPU run under "
                f"XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{need}"]

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    specs = [ScenarioSpec(seed=s,
                          bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=400.0 if s % 2 else None,
                          fault_mttr=50.0, fault_horizon=600.0,
                          dead_flows=(s % 7,) if s % 3 == 0 else ())
             for s in range(batch)]
    camp = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                    arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                    specs, eps=1e-9, dtype=np.float64, superstep=k)

    problems: List[str] = []

    def diff_fleet(label, got, ref, n=None):
        for j in range(n if n is not None else len(ref)):
            if got[j].error or ref[j].error:
                problems.append(f"shard:{label}: replica {j} errored "
                                f"({got[j].error or ref[j].error})")
                return
            if got[j].events != ref[j].events or got[j].t != ref[j].t:
                problems.append(
                    f"shard:{label}: replica {j} diverged from the "
                    f"single-device fleet ({len(got[j].events)} vs "
                    f"{len(ref[j].events)} events, clocks "
                    f"{got[j].t!r} vs {ref[j].t!r})")
                return

    ref = camp.run_batched(batch=batch)          # single-device vmap
    for M in shards:
        for depth in depths:
            before = opstats.snapshot()
            got = camp.run_batched(batch=batch, mesh=M, pipeline=depth)
            d = opstats.diff(before)
            diff_fleet(f"m{M}:d{depth}", got, ref)
            if not d.get("demux_fetches"):
                problems.append(f"shard:m{M}:d{depth}: no per-shard "
                                f"demux fetch recorded (the mesh path "
                                f"was not actually exercised)")
    # vs solo (the standing oracle): one sharded fleet, sampled lanes
    got = camp.run_batched(batch=batch, mesh=shards[0])
    for j in (0, batch // 2, batch - 1):
        solo = camp.run_solo(j)
        if solo.events != got[j].events or solo.t != got[j].t:
            problems.append(f"shard:solo: replica {j} of the sharded "
                            f"fleet diverged from its solo run")
    # ragged fleet: B-1 replicas over the same mesh → one padded lane
    ragged = camp.specs[:batch - 1]
    camp_r = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                      arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                      ragged, eps=1e-9, dtype=np.float64, superstep=k)
    got_r = camp_r.run_batched(batch=batch - 1, mesh=shards[0])
    diff_fleet(f"ragged:m{shards[0]}", got_r, ref, n=batch - 1)
    # budget-starved rescue + deep pipeline: mispredicts must roll
    # speculation back AND stay bit-identical
    if max(depths) >= 2:
        ref_b = camp.run_batched(batch=batch, superstep_rounds=3)
        before = opstats.snapshot()
        got_b = camp.run_batched(batch=batch, superstep_rounds=3,
                                 mesh=shards[0], pipeline=max(depths))
        d = opstats.diff(before)
        diff_fleet(f"budget:m{shards[0]}:d{max(depths)}", got_b, ref_b)
        if not d.get("speculations_rolled_back"):
            problems.append(
                "shard:budget: the budget-starved pipelined fleet "
                "never rolled speculation back (forcing failed — "
                "nothing was actually tested)")
    return problems


def check_fault_runtime(seed: int = 41, n_c: int = 32, n_v: int = 96,
                        k: int = 4, depths=(0, 2), mesh: int = 2
                        ) -> List[str]:
    """Dynamic determinism of the device-resident fault event tapes: a
    3-lane fleet (2 seeded fault schedules + 1 clean lane) must (a)
    compile tapes whose dates are bitwise the generate() schedule an
    engine-side Profile would replay, (b) be bit-identical per lane —
    completion events, fired fault events AND Kahan clocks — to the
    same scenarios run solo, with at least one tape event actually
    firing mid-drain and at least one completion landing after it, (c)
    reproduce the pre-tape mean-availability folding exactly in
    ``fault_mode="static"``, and (d) stay bit-identical under pipeline
    depth 2 and a `mesh`-device replica-axis sharding.  Returns a list
    of problem descriptions (empty = OK)."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    from bench import build_arrays
    from simgrid_tpu.parallel.campaign import (Campaign, ScenarioSpec,
                                               MIN_LINK_FACTOR)

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    specs = [ScenarioSpec(seed=s, bw_scale=1.0 + 0.1 * s,
                          fault_mtbf=200.0 if s < 2 else None,
                          fault_mttr=60.0, fault_horizon=800.0,
                          fault_dist="weibull" if s == 1
                          else "exponential",
                          fault_shape=1.5)
             for s in range(3)]

    def make(**kw):
        return Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                        arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                        specs, eps=1e-9, dtype=np.float64,
                        superstep=k, **kw)

    problems: List[str] = []
    camp = make(fault_mode="on")

    # (a) the tape is the Profile schedule, bitwise: same dates in the
    # same order, states mapped to the clamp floor / full restore
    for s in range(2):
        fc, _ = camp._fault_campaign(specs[s])
        sched = sorted((date, name, value)
                       for (kind, name), pts in fc.generate().items()
                       for date, value in pts)
        ref = camp._fault_campaign(specs[s])[0]
        tape = ref.compile_tape(floor=MIN_LINK_FACTOR)
        if [(d, n, 1.0 if v > 0 else MIN_LINK_FACTOR)
                for d, n, v in sched] \
                != [(d, n, f) for d, _, n, f in tape]:
            problems.append(f"fault: replica {s}: compile_tape "
                            f"diverged from the generate() schedule")

    # (b) batched vs solo, bit-identical incl. the fired fault events
    fleet = camp.run_batched(batch=3)
    fired = 0
    for j in range(3):
        solo = camp.run_solo(j)
        got = fleet[j]
        if solo.error or got.error:
            problems.append(f"fault: replica {j} errored "
                            f"({got.error or solo.error})")
            continue
        if solo.events != got.events or solo.t != got.t:
            problems.append(
                f"fault: replica {j}: batched run diverged from solo "
                f"({len(got.events)} vs {len(solo.events)} events, "
                f"clocks {got.t!r} vs {solo.t!r})")
        if solo.fault_events != got.fault_events:
            problems.append(f"fault: replica {j}: fired fault events "
                            f"differ from solo ({len(got.fault_events)}"
                            f" vs {len(solo.fault_events)})")
        if j == 2 and got.fault_events:
            problems.append("fault: the clean lane fired tape events")
        fired += len(got.fault_events)
    if not fired:
        problems.append("fault: no tape event ever fired mid-drain "
                        "(nothing was actually tested)")
    for j in range(2):
        if fleet[j].fault_events and fleet[j].events:
            first_fire = fleet[j].fault_events[0][0]
            if not any(t >= first_fire for t, _ in fleet[j].events):
                problems.append(
                    f"fault: replica {j}: no completion after the "
                    f"first fire (the post-fault re-solve never ran)")

    # (c) static mode is the pre-tape behavior: identical to folding
    # the mean availabilities into explicit link_scale by hand
    camp_s = make(fault_mode="static")
    folded = []
    for spec in specs:
        ls = dict(spec.link_scale)
        if spec.fault_mtbf is not None:
            fc, names = camp_s._fault_campaign(spec)
            for (kind, name), av in fc.mean_availability().items():
                if av < 1.0:
                    slot = names[name]
                    ls[slot] = ls.get(slot, 1.0) \
                        * max(av, MIN_LINK_FACTOR)
        folded.append(ScenarioSpec(seed=spec.seed,
                                   bw_scale=spec.bw_scale,
                                   link_scale=ls))
    camp_f = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                      arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                      folded, eps=1e-9, dtype=np.float64,
                      superstep=k, fault_mode="off")
    for j, (a, b) in enumerate(zip(camp_s.run_batched(batch=3),
                                   camp_f.run_batched(batch=3))):
        if a.events != b.events or a.t != b.t or a.fault_events:
            problems.append(f"fault: replica {j}: static mode "
                            f"diverged from the hand-folded "
                            f"mean-availability scenario")

    # (d) pipeline + mesh compose: every variant bit-identical
    variants = [("d2", dict(pipeline=2))]
    if jax.device_count() >= mesh:
        variants += [(f"m{mesh}", dict(mesh=mesh)),
                     (f"m{mesh}:d{max(depths)}",
                      dict(mesh=mesh, pipeline=max(depths)))]
    else:
        problems.append(
            f"fault: only {jax.device_count()} device(s) visible; the "
            f"mesh leg needs >= {mesh} — on CPU run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={mesh}")
    for label, kw in variants:
        got = camp.run_batched(batch=3, **kw)
        for j in range(3):
            if got[j].events != fleet[j].events \
                    or got[j].t != fleet[j].t \
                    or got[j].fault_events != fleet[j].fault_events:
                problems.append(f"fault:{label}: replica {j} diverged "
                                f"from the plain batched fleet")
                break
    return problems


def check_serve_runtime(seed: int = 43, n_c: int = 32, n_v: int = 96,
                        batch: int = 3, scenarios: int = 9, k: int = 4,
                        depths=(0, 2)) -> List[str]:
    """Dynamic determinism of the always-on campaign service: more
    exact queries than the resident fleet has lanes (``scenarios >
    batch``), so most queries are ADMITTED into dead lanes of a
    partially-drained fleet mid-flight.  Every device-served ticket —
    initial and admitted alike, fault tapes included — must be
    bit-identical (events, fired fault events, Kahan clocks) to
    ``ScenarioPlan.solo`` on the same spec; admission and at least one
    tape fire must actually have happened (otherwise nothing was
    tested); at pipeline depth >= 1 the mid-flight admissions must
    have rolled in-flight speculation back; and every fleet program
    routes through the AOT plan cache so the executable path IS the
    audited path.  Returns a list of problems (empty = OK)."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_arrays
    from simgrid_tpu.parallel.campaign import ScenarioPlan, ScenarioSpec
    from simgrid_tpu.serving import CampaignService, PlanCache

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    specs = [ScenarioSpec(seed=s, bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=150.0 if s % 3 == 0 else None,
                          fault_mttr=50.0, fault_horizon=900.0,
                          label=f"q{s}")
             for s in range(scenarios)]
    plan = ScenarioPlan(arrays.e_var[:E], arrays.e_cnst[:E],
                        arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                        eps=1e-9, superstep=k, fault_mode="on")
    solos = {spec.label: plan.solo(spec) for spec in specs}

    problems: List[str] = []
    cache = PlanCache()  # memory-resident; same executables every depth
    for depth in depths:
        tag = f"serve:d{depth}"
        svc = CampaignService(plan, batch=batch, plan_cache=cache,
                              pipeline=depth)
        tickets = svc.submit_many(specs, exact=True)
        svc.drain()
        fired = 0
        for t in tickets:
            if t.result is None or t.result.source != "device":
                problems.append(f"{tag}: {t.spec.label} never got a "
                                f"device result")
                continue
            if t.result.error:
                problems.append(f"{tag}: {t.spec.label} errored "
                                f"({t.result.error})")
                continue
            solo = solos[t.spec.label]
            if solo.events != t.result.events \
                    or solo.t != t.result.t:
                problems.append(
                    f"{tag}: {t.spec.label}: served run diverged from "
                    f"solo ({len(t.result.events)} vs "
                    f"{len(solo.events)} events, clocks "
                    f"{t.result.t!r} vs {solo.t!r})")
            if solo.fault_events != t.result.fault_events:
                problems.append(f"{tag}: {t.spec.label}: fired fault "
                                f"events differ from solo")
            fired += len(t.result.fault_events)
        if svc.lanes_admitted == 0:
            problems.append(f"{tag}: no lane was ever admitted "
                            f"mid-flight (nothing was actually tested)")
        if not fired:
            problems.append(f"{tag}: no fault tape event ever fired")
        if depth >= 1 and svc.spec_rolled_back == 0:
            problems.append(f"{tag}: admissions never rolled "
                            f"speculation back (the clean=False "
                            f"contract was not exercised)")
    if cache.hits == 0 or cache.fallbacks:
        problems.append(f"serve: plan cache never took the AOT path "
                        f"(hits={cache.hits}, "
                        f"fallbacks={cache.fallbacks})")
    return problems


def check_resume_runtime(seed: int = 47, n_c: int = 32, n_v: int = 96,
                         batch: int = 3, scenarios: int = 8, k: int = 4,
                         depths=(0, 2), stop_after: int = 3
                         ) -> List[str]:
    """Dynamic determinism of preemption-safe campaigns (ISSUE 12):

    * kill/resume — a campaign service is KILLED at an arbitrary
      collect boundary (``drain(stop_after=...)`` checkpoints and
      halts; the service object is then discarded, simulating the
      preemption) and a fresh service is rebuilt with
      ``CampaignService.resume``: every ticket's completion events,
      fired-fault stream and Kahan clock must be bit-identical to the
      uninterrupted run AND to ``ScenarioPlan.solo`` — including
      pipeline depth 2 (in-flight speculation at the kill point is
      never persisted) and active fault tapes;
    * warm resume — the resumed fleet must rebuild through the AOT
      plan cache without ONE new compile (same plan key);
    * double resume — resuming the same token twice must re-run
      bit-identically (the token is never mutated);
    * lane quarantine — a single poisoned lane (NaN link capacity)
      must die with a ``nan_solve`` LaneFault on ITS ticket while
      every other lane stays bit-identical to solo.

    Returns a list of problems (empty = OK)."""
    import tempfile
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import build_arrays
    from simgrid_tpu.ops import opstats
    from simgrid_tpu.parallel.campaign import ScenarioPlan, ScenarioSpec
    from simgrid_tpu.serving import CampaignService, PlanCache

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, 3, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    specs = [ScenarioSpec(seed=s, bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=150.0 if s % 3 == 0 else None,
                          fault_mttr=50.0, fault_horizon=900.0,
                          label=f"q{s}")
             for s in range(scenarios)]
    plan = ScenarioPlan(arrays.e_var[:E], arrays.e_cnst[:E],
                        arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                        eps=1e-9, superstep=k, fault_mode="on")
    solos = {spec.label: plan.solo(spec) for spec in specs}

    def digest(tickets):
        """The comparable outcome of one service run: per-label
        (events, fault events, clock, error) — latency metadata and
        ticket ordering are excluded on purpose."""
        out = {}
        for t in tickets:
            r = t.result
            out[t.spec.label] = (
                None if r is None
                else (r.source, [tuple(e) for e in (r.events or [])],
                      [tuple(e) for e in (r.fault_events or [])],
                      r.t, r.error))
        return out

    problems: List[str] = []
    cache = PlanCache()  # memory-resident; shared across every leg
    tmpdir = tempfile.mkdtemp(prefix="simgrid_resume_")
    for depth in depths:
        tag = f"resume:d{depth}"
        # leg 1: the uninterrupted oracle run
        svc_a = CampaignService(plan, batch=batch, plan_cache=cache,
                                pipeline=depth)
        svc_a.submit_many(specs, exact=True)
        ref = digest(svc_a.drain())
        # leg 2: kill at a collect boundary, then resume cold
        path = os.path.join(tmpdir, f"ck_d{depth}")
        svc_b = CampaignService(plan, batch=batch, plan_cache=cache,
                                pipeline=depth)
        svc_b.submit_many(specs, exact=True)
        svc_b.drain(stop_after=stop_after, checkpoint_path=path)
        if svc_b._fleet is None:
            problems.append(f"{tag}: drain finished before "
                            f"stop_after={stop_after} — the kill "
                            f"window was never exercised")
            continue
        del svc_b  # the preemption: nothing survives but the token
        misses_before = cache.misses
        svc_c = CampaignService.resume(path, plan_cache=cache)
        if svc_c._fleet is None:
            problems.append(f"{tag}: resume rebuilt no resident fleet")
            continue
        if cache.misses != misses_before:
            problems.append(
                f"{tag}: resume compiled "
                f"{cache.misses - misses_before} new executable(s) — "
                f"the AOT plan cache was not hit warm")
        got = digest(svc_c.drain())
        if got != ref:
            bad = [lbl for lbl in ref
                   if got.get(lbl) != ref[lbl]]
            problems.append(
                f"{tag}: resumed run diverged from the uninterrupted "
                f"run on {len(bad)} quer{'y' if len(bad) == 1 else 'ies'} "
                f"({', '.join(bad[:4])})")
        for spec in specs:
            r = got.get(spec.label)
            solo = solos[spec.label]
            if r is None or r[4] is not None:
                problems.append(f"{tag}: {spec.label} has no clean "
                                f"resumed result")
                continue
            if (r[1] != [tuple(e) for e in solo.events]
                    or r[2] != [tuple(e) for e in solo.fault_events]
                    or r[3] != solo.t):
                problems.append(f"{tag}: {spec.label}: resumed run "
                                f"diverged from solo")
        if not any(r and r[2] for r in got.values()):
            problems.append(f"{tag}: no fault tape event ever fired "
                            f"(tapes were not actually exercised)")
        # leg 3: double resume from the SAME token is idempotent
        svc_d = CampaignService.resume(path, plan_cache=cache)
        got2 = digest(svc_d.drain())
        if got2 != got:
            problems.append(f"{tag}: second resume from the same "
                            f"token diverged from the first")
    if cache.hits == 0 or cache.fallbacks:
        problems.append(f"resume: plan cache never took the AOT path "
                        f"(hits={cache.hits}, "
                        f"fallbacks={cache.fallbacks})")

    # leg 4: single-lane NaN quarantine — a poisoned scenario (NaN
    # sizes: every remaining-work entry of that lane is NaN) kills
    # exactly its own lane, with a structured cause on the ticket
    poison = ScenarioSpec(seed=99, size_scale=float("nan"),
                          label="poison")
    clean = [ScenarioSpec(seed=s, bw_scale=1.0 + 0.1 * s,
                          label=f"clean{s}") for s in range(batch)]
    clean_solos = {s.label: plan.solo(s) for s in clean}
    before = opstats.snapshot()
    svc_q = CampaignService(plan, batch=batch, plan_cache=cache)
    tickets = svc_q.submit_many([poison] + clean, exact=True)
    svc_q.drain()
    d = opstats.diff(before)
    for t in tickets:
        if t.spec.label == "poison":
            if t.fault is None or t.fault.cause != "nan_solve":
                problems.append(
                    f"resume:quarantine: poisoned lane was not "
                    f"quarantined with cause nan_solve (fault="
                    f"{t.fault!r}, error={t.result and t.result.error!r})")
            continue
        r = t.result
        solo = clean_solos[t.spec.label]
        if r is None or r.error is not None \
                or r.events != solo.events or r.t != solo.t:
            problems.append(f"resume:quarantine: clean lane "
                            f"{t.spec.label} diverged from solo after "
                            f"a neighbour's NaN quarantine")
    if not d.get("lane_quarantined_nan_solve"):
        problems.append("resume:quarantine: the nan_solve quarantine "
                        "counter never moved (nothing was actually "
                        "tested)")
    return problems


_FAT_TREE_64 = """<?xml version='1.0'?>
<platform version="4.1">
  <zone id="world" routing="Full">
    <cluster id="ft" prefix="node-" radical="0-63" suffix=""
             speed="1Gf" bw="125MBps" lat="50us" topology="FAT_TREE"
             topo_parameters="2;8,8;1,2;1,1"/>
  </zone>
</platform>
"""


def check_phase_runtime(seed: int = 37, ranks: int = 48, rounds: int = 3,
                        min_flows: int = 16, superstep: int = 16,
                        depths=(0, 2)) -> List[str]:
    """Dynamic determinism of the device-resident mutating phases: an
    NAS-style compute/comm alternation (each rank chains comm -> exec
    -> comm ... over a 64-host fat tree, every completion immediately
    posting its successor) must produce bit-identical completion
    events — order AND finish timestamps — and final engine clock with
    the drain fast path on vs off, under

      * the plain alternation (every completion is a wake/send/exec
        transition the absorb classifier must turn into a payload),
      * a forced RESUMABLE mutation (a backbone link's bandwidth is
        halved mid-phase: a bound-change scatter, not a replay),
      * a forced NON-RESUMABLE mutation (a deadline'd flow joins: the
        classifier has no drain semantics for max_duration and must
        take the bit-identical replay fallback), and
      * the pipelined fleet variant (every depth in `depths`: the
        speculative superstep machinery riding the mutating phase).

    Each variant also asserts the machinery it targets actually fired
    (served advances, absorbed transitions, the unrecognized-cause
    fallback) — otherwise nothing was tested.  Returns a list of
    problem descriptions (empty = OK)."""
    import tempfile
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from simgrid_tpu import s4u
    from simgrid_tpu.ops import opstats

    plat = os.path.join(tempfile.mkdtemp(prefix="simgrid_phase_"),
                        "ft64.xml")
    with open(plat, "w") as f:
        f.write(_FAT_TREE_64)

    def bw_mutation(e, model, hosts):
        # resumable: a c_bound scatter in the transition payload
        link = next(iter(e.pimpl.links.values()))
        link.set_bandwidth(link.get_bandwidth() * 0.5)

    def deadline_mutation(e, model, hosts):
        # non-resumable: max_duration has no drain-program semantics,
        # so _absorb must refuse and _invalidate(cause="unrecognized")
        a = model.communicate(hosts[0], hosts[1], 3e5, -1.0)
        a.set_max_duration(1e9)

    def run(cfg, mutate=None):
        """One alternation phase; mutations fire at the first solve
        after t=0.005 — a pure function of the simulated timeline, so
        the fast-path-on and -off runs mutate at the same instant."""
        s4u.Engine._reset()
        try:
            e = s4u.Engine(["phase"] + [f"--cfg={c}" for c in cfg])
            e.load_platform(plat)
            hosts = e.get_all_hosts()[:ranks]
            model = e.pimpl.network_model
            rng = np.random.default_rng(seed)
            dst = rng.integers(0, ranks, size=(ranks, rounds))
            sizes = rng.choice(np.linspace(2e5, 2e6, 12),
                               (ranks, rounds))
            flops = rng.choice(np.linspace(5e5, 5e6, 8),
                               (ranks, rounds))
            stage = [0] * ranks
            tag_of = {}
            events = []

            def post_next(r):
                st = stage[r]
                k = st // 2
                if k >= rounds:
                    return
                if st % 2 == 0:
                    d = int(dst[r, k])
                    if d == r:
                        d = (d + 1) % ranks
                    a = model.communicate(hosts[r], hosts[d],
                                          float(sizes[r, k]), -1.0)
                else:
                    a = hosts[r].cpu.execution_start(float(flops[r, k]))
                tag_of[id(a)] = (r, st)
                stage[r] = st + 1

            for r in range(ranks):
                post_next(r)
            pending = mutate
            for _ in range(200_000):
                if not any(len(m.started_action_set)
                           for m in e.pimpl.models):
                    break
                if pending is not None and e.pimpl.now > 0.005:
                    pending(e, model, hosts)
                    pending = None
                e.pimpl.surf_solve(-1.0)
                for m in list(e.pimpl.models):
                    while True:
                        done = m.extract_done_action()
                        if done is None:
                            break
                        t = tag_of.pop(id(done), None)
                        if t is not None:
                            events.append((done.finish_time, t))
                            post_next(t[0])
                        done.unref()
            return events, e.pimpl.now
        finally:
            s4u.Engine._reset()

    base = ["network/optim:Full", "network/maxmin-selective-update:no",
            "lmm/backend:jax"]
    fast = base + ["drain/fastpath:auto",
                   f"drain/min-flows:{min_flows}",
                   f"drain/superstep:{superstep}"]
    variants = [("plain", [], None),
                ("resumable", [], bw_mutation),
                ("invalidate", [], deadline_mutation)]
    for depth in depths:
        if depth:
            variants.append((f"fleet:d{depth}",
                             [f"drain/pipeline:{depth}"], bw_mutation))

    problems: List[str] = []
    for label, extra, mutate in variants:
        ref = run(base + ["drain/fastpath:off"] + extra, mutate)
        before = opstats.snapshot()
        a = run(fast + extra, mutate)
        d = opstats.diff(before)
        b = run(fast + extra, mutate)
        if a != b:
            problems.append(f"phase:{label}: two identical fast-path "
                            f"runs diverged ({len(a[0])} vs "
                            f"{len(b[0])} events)")
        if a[0] != ref[0] or a[1] != ref[1]:
            ndiff = sum(1 for x, y in zip(a[0], ref[0]) if x != y)
            problems.append(
                f"phase:{label}: fast-path run diverged from the "
                f"native loop ({len(a[0])} vs {len(ref[0])} events, "
                f"{ndiff} mismatched pairs, clocks {a[1]!r} vs "
                f"{ref[1]!r})")
        if not d.get("fastpath_advances"):
            problems.append(f"phase:{label}: the device plan never "
                            f"served an advance (nothing was "
                            f"actually tested)")
        if not d.get("drain_transitions"):
            problems.append(f"phase:{label}: no transition payload was "
                            f"absorbed — the alternation ran on the "
                            f"replay fallback only")
        if label == "invalidate" \
                and not d.get("drain_cause_unrecognized"):
            problems.append(
                "phase:invalidate: the deadline'd flow never forced an "
                "unrecognized-mutation replay (forcing failed — "
                "nothing was actually tested)")
    return problems


#: IS-style NAS comm skeleton: each iteration is the integer sort's
#: bucket-count allreduce followed by the key alltoall, with data
#: checks so a wrong reduction fails the exit code (ITERS via -D).
_NAS_IS_KERNEL = r"""
#include <mpi.h>
#include <stdlib.h>

#ifndef ITERS
#define ITERS 3
#endif

int main(int argc, char **argv) {
    int rank, size, i, it;
    MPI_Init(&argc, &argv);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    int n = 32 * size;                 /* bucket counts */
    int per = 16;                      /* keys per destination */
    double *cnt = malloc(n * sizeof(double));
    double *tot = malloc(n * sizeof(double));
    double *keys = malloc(per * size * sizeof(double));
    double *sorted = malloc(per * size * sizeof(double));
    for (it = 0; it < ITERS; it++) {
        for (i = 0; i < n; i++) cnt[i] = rank + i + it;
        MPI_Allreduce(cnt, tot, n, MPI_DOUBLE, MPI_SUM, MPI_COMM_WORLD);
        for (i = 0; i < n; i++)
            if (tot[i] != size * (double)(i + it)
                          + size * (size - 1) / 2.0) {
                MPI_Finalize();
                return 20 + it;
            }
        for (i = 0; i < per * size; i++) keys[i] = rank * 1000.0 + i;
        MPI_Alltoall(keys, per, MPI_DOUBLE, sorted, per, MPI_DOUBLE,
                     MPI_COMM_WORLD);
        for (i = 0; i < size; i++)
            if (sorted[i * per] != i * 1000.0 + rank * per) {
                MPI_Finalize();
                return 40 + it;
            }
    }
    MPI_Finalize();
    return 0;
}
"""


def check_collective_runtime(seed: int = 53, ranks: int = 6, k: int = 8,
                             depths=(0, 2), nas: bool = True,
                             nas_ranks: int = 8, nas_iters: int = 3,
                             ratio: float = 10.0) -> List[str]:
    """Dynamic determinism of the collective schedule tapes:

    * capture parity — the comm sequence (src, dst, tag, size,
      dependency order) the REAL ``smpi/coll.py`` algorithms post on
      recording threads must equal the mirrored ``collectives.schedule``
      generators, at `ranks` and the non-power-of-two `ranks`+1;
    * tape vs maestro — the superstep-resident DAG walk (solo, k=1
      grouping and pipeline depth ``max(depths)``) must be
      bit-identical — completion events, fired activations AND the
      Kahan clock pair — to the dispatch-per-advance ``HostMaestro``
      over the same compiled arrays, while issuing at least 3x fewer
      dispatches;
    * fleets — a 3-lane ``Campaign.for_collective`` sweep (plain,
      bw-scaled, size+link-scaled), plain and pipelined, must be
      bit-identical per lane to solo runs including the activation
      stream;
    * fault composition — a seeded link-flip tape firing mid-collective
      must keep tape, maestro and the pipelined variant bit-identical
      while actually moving the event stream;
    * NAS leg (``nas=True``, needs a C compiler) — a real IS-style MPI
      C kernel (bucket-count allreduce + key alltoall per iteration)
      is compiled with ``smpi/c_api``, its live collectives captured
      via ``CaptureScope``, and the replayed schedule must complete on
      the tape path bit-identically to the maestro with >= `ratio`x
      fewer dispatches per collective step.

    Returns a list of problem descriptions (empty = OK)."""
    import numpy as np
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from simgrid_tpu.collectives import (CollectiveSpec, DeviceCollective,
                                         HostMaestro, Topology, generate)
    from simgrid_tpu.smpi.schedule_capture import (CaptureScope,
                                                   capture_schedule,
                                                   default_payload)

    problems: List[str] = []

    # (a) capture parity: real algorithm vs mirrored generator.  The
    # generator payload is bytes except lr (elements); capture always
    # takes bytes.
    cases = [("allreduce", "lr", 23, 23 * 8),
             ("allreduce", "rdb", 4096, 4096),
             ("alltoall", "pairwise", 2e5, 2e5),
             ("alltoall", "bruck", 64, 64),
             ("bcast", "binomial_tree", 4096, 4096)]
    for R in (ranks, ranks + 1):
        for op, algo, gen_pay, nbytes in cases:
            gen = generate(op, algo, R, gen_pay)
            cap = capture_schedule(op, algo, R,
                                   default_payload(op, R, nbytes))
            if cap.sequence() != gen.sequence():
                problems.append(
                    f"collective: {op}/{algo} R={R}: captured comm "
                    f"sequence diverged from the generator "
                    f"({cap.n_comms} vs {gen.n_comms} comms)")

    # (b) tape vs maestro, bit-identical at every grouping
    combos = [CollectiveSpec("allreduce", "lr", ranks - 1, "ring",
                             64, bw=1e8),
              CollectiveSpec("allreduce", "rdb", ranks, "nic",
                             4096, bw=1e8),
              CollectiveSpec("alltoall", "pairwise", ranks, "star",
                             2e5, bw=1e8),
              CollectiveSpec("bcast", "binomial_tree", ranks + 3,
                             "ring", 5e5, bw=1e8)]
    fired_acts = 0
    for cs in combos:
        tag = f"collective: {cs.label()}"
        dc = cs.build()
        sim = dc.make_sim(superstep=k)
        sim.run()
        if len(sim.events) != dc.n_v:
            problems.append(f"{tag}: tape run retired "
                            f"{len(sim.events)}/{dc.n_v} flows")
            continue
        ma = HostMaestro(dc)
        ma.run()
        clk = np.asarray(sim._coll_clk)
        if ma.events != sim.events \
                or ma.collective_events != sim.collective_events:
            problems.append(f"{tag}: tape events diverged from the "
                            f"host maestro")
        if ma.clock != (float(clk[0]), float(clk[1])):
            problems.append(f"{tag}: tape Kahan clock "
                            f"{tuple(map(float, clk))!r} != maestro "
                            f"{ma.clock!r}")
        if ma.dispatches < 3 * max(sim.supersteps, 1):
            problems.append(
                f"{tag}: tape path won no dispatch advantage "
                f"({sim.supersteps} supersteps vs {ma.dispatches} "
                f"maestro dispatches)")
        fired_acts += len(sim.collective_events)
        for label, kw in [("k1", dict(superstep=1)),
                          ("d%d" % max(depths),
                           dict(superstep=max(2, k // 2),
                                pipeline=max(depths)))]:
            alt = dc.make_sim(**kw)
            alt.run()
            if alt.events != sim.events \
                    or alt.collective_events != sim.collective_events:
                problems.append(f"{tag}:{label}: regrouped tape run "
                                f"diverged from superstep k={k}")
    if not fired_acts:
        problems.append("collective: no activation ever fired (the "
                        "DAG walk was not actually tested)")

    # (c) fleet sweep: batched + pipelined lanes == solo, incl. the
    # activation stream
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec
    cs = combos[0]
    specs = [ScenarioSpec(seed=seed, collective=cs, label="plain"),
             ScenarioSpec(seed=seed + 1, bw_scale=0.5, collective=cs,
                          label="bw"),
             ScenarioSpec(seed=seed + 2, size_scale=2.0,
                          link_scale={0: 0.25}, label="scaled")]
    camp = Campaign.for_collective(cs, specs, fault_mode="off",
                                   superstep=k, dtype=np.float64)
    fleet = camp.run_batched(batch=3)
    for j in range(3):
        solo = camp.run_solo(j)
        got = fleet[j]
        if got.error or solo.error:
            problems.append(f"collective: lane {j} errored "
                            f"({got.error or solo.error})")
            continue
        if got.events != solo.events or got.t != solo.t \
                or got.collective_events != solo.collective_events:
            problems.append(f"collective: lane {j}: batched run "
                            f"diverged from solo")
    for depth in depths:
        if not depth:
            continue
        piped = camp.run_batched(batch=3, pipeline=depth)
        for j in range(3):
            if piped[j].events != fleet[j].events \
                    or piped[j].collective_events \
                    != fleet[j].collective_events:
                problems.append(f"collective: lane {j}: pipelined "
                                f"d{depth} fleet diverged")
                break

    # (d) fault-tape composition: a link flip mid-collective
    dc = combos[2].build()
    base = dc.make_sim(superstep=k)
    base.run()
    mid = base.events[len(base.events) // 2][0]
    # drop rank 0's uplink far below its fair share of the star core
    # (merely shaving it would stay core-bottlenecked and move nothing)
    bw = combos[2].bw
    ft = (np.asarray([mid * 0.7, mid * 1.3]),
          np.asarray([0, 0], np.int32), np.asarray([bw * 0.02, bw]))
    simf = dc.make_sim(superstep=k, tape=ft)
    simf.run()
    maf = HostMaestro(dc, tape=ft)
    maf.run()
    clk = np.asarray(simf._coll_clk)
    if maf.events != simf.events \
            or maf.collective_events != simf.collective_events \
            or maf.fault_events != simf.fault_events \
            or maf.clock != (float(clk[0]), float(clk[1])):
        problems.append("collective:fault: composed tape run diverged "
                        "from the host maestro")
    if not simf.fault_events:
        problems.append("collective:fault: no fault event fired "
                        "mid-collective (nothing was actually tested)")
    if simf.events == base.events:
        problems.append("collective:fault: the link flip never moved "
                        "the event stream (nothing was actually tested)")
    piped = dc.make_sim(superstep=max(2, k // 2),
                        pipeline=max(depths) or 2, tape=ft)
    piped.run()
    if piped.events != simf.events \
            or piped.fault_events != simf.fault_events:
        problems.append("collective:fault: pipelined composed run "
                        "diverged")

    # (e) the NAS leg: a real MPI C kernel captured live end to end
    if nas:
        import shutil
        import tempfile
        if shutil.which("gcc") is None \
                and os.environ.get("SMPI_CC") is None:
            problems.append("collective:nas: no C compiler — the NAS "
                            "leg cannot run (install gcc or set "
                            "SMPI_CC)")
            return problems
        from simgrid_tpu.smpi.c_api import compile_program, run_c_program
        tmp = tempfile.mkdtemp(prefix="simgrid_nas_")
        src = os.path.join(tmp, "nas_is.c")
        with open(src, "w") as f:
            f.write(_NAS_IS_KERNEL)
        so = os.path.join(tmp, "nas_is.so")
        compile_program([src], so,
                        extra_flags=(f"-DITERS={nas_iters}",))
        with CaptureScope() as scope:
            _engine, codes = run_c_program(
                so, np_ranks=nas_ranks,
                configs=("smpi/simulate-computation:false",))
        if any(codes.get(r) != 0 for r in range(nas_ranks)):
            problems.append(f"collective:nas: kernel exit codes "
                            f"{codes} (data corrupted under capture)")
            return problems
        if scope.n_phases != 2 * nas_iters:
            problems.append(f"collective:nas: captured "
                            f"{scope.n_phases} collective phases, "
                            f"expected {2 * nas_iters}")
        sched = scope.schedule()
        dc = DeviceCollective(sched, Topology(nas_ranks, "nic", bw=1e8))
        sim = dc.make_sim(superstep=4 * k)
        sim.run()
        if len(sim.events) != dc.n_v:
            problems.append(f"collective:nas: tape run retired "
                            f"{len(sim.events)}/{dc.n_v} flows")
            return problems
        ma = HostMaestro(dc)
        ma.run()
        clk = np.asarray(sim._coll_clk)
        if ma.events != sim.events \
                or ma.collective_events != sim.collective_events \
                or ma.clock != (float(clk[0]), float(clk[1])):
            problems.append("collective:nas: tape run diverged from "
                            "the host maestro")
        if ma.dispatches < ratio * max(sim.supersteps, 1):
            problems.append(
                f"collective:nas: dispatch advantage below {ratio}x "
                f"({sim.supersteps} supersteps vs {ma.dispatches} "
                f"maestro dispatches over {scope.n_phases} collective "
                f"steps)")
    return problems


def quick_checks() -> List[str]:
    """The CI bundle: static lint + small-N instances of every runtime
    check, sized for seconds, so determinism regressions fail pytest
    instead of waiting for a manual tool run."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # the full static gate: simlint + proglint (compiled-program
    # contracts staged over the registered kernel programs) + the
    # opstats counter registry — same bundle as tools/lint_all.py
    tools_dir = os.path.join(repo_root, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    from lint_all import collect_problems as collect_lint_problems
    problems = collect_lint_problems(repo_root)
    problems += check_drain_runtime(n_c=32, n_v=128, k=4)
    problems += check_batch_runtime(n_c=32, n_v=96, batch=6,
                                    solo_check=(0, 3, 5))
    problems += check_pipeline_runtime(n_c=32, n_v=128, k=4,
                                       depths=(1,), batch=4)
    problems += check_shard_runtime(n_c=24, n_v=64, batch=4, k=4,
                                    shards=(2,), depths=(0, 2))
    problems += check_phase_runtime(ranks=24, rounds=2, min_flows=8,
                                    superstep=8, depths=(0, 2))
    problems += check_fault_runtime(n_c=24, n_v=64, k=4, mesh=2)
    problems += check_serve_runtime(n_c=24, n_v=64, batch=3,
                                    scenarios=7, k=4, depths=(0, 2))
    problems += check_resume_runtime(n_c=24, n_v=64, batch=3,
                                     scenarios=6, k=4, depths=(0, 2),
                                     stop_after=2)
    problems += check_collective_runtime(ranks=5, k=4, depths=(0, 2),
                                         nas=False)
    return problems


def main(argv: List[str]) -> int:
    if ("--runtime-shard" in argv or "--runtime-fault" in argv
            or "--runtime-serve" in argv or "--runtime-resume" in argv
            or "--quick" in argv) and "jax" not in sys.modules:
        # the mesh checks need >= 2 devices; the forced host-platform
        # count must land before JAX initializes and only affects the
        # CPU backend (harmless elsewhere)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
    if "--runtime-shard" in argv:
        problems = check_shard_runtime()
        if problems:
            print("check_determinism: shard runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: shard runtime OK (mesh-sharded "
              "replica-axis fleets — 2/4-shard, ragged padding, "
              "budget rescue, pipeline depth 2 incl. forced-rollback "
              "assertion — bit-identical to the single-device vmapped "
              "fleet and to solo runs: event order, timestamps and "
              "clocks)")
        argv = [a for a in argv if a != "--runtime-shard"]
    if "--runtime-fault" in argv:
        problems = check_fault_runtime()
        if problems:
            print("check_determinism: fault runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: fault runtime OK (device fault "
              "tapes — 2 faulted + 1 clean lane, tape dates bitwise "
              "the generate() schedule, >= 1 event fired mid-drain, "
              "static mode = hand-folded availabilities, pipeline "
              "depth 2 and 2-device mesh compose — bit-identical to "
              "solo runs: events, fired faults and Kahan clocks)")
        argv = [a for a in argv if a != "--runtime-fault"]
    if "--runtime-serve" in argv:
        problems = check_serve_runtime()
        if problems:
            print("check_determinism: serve runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: serve runtime OK (campaign service "
              "— queries admitted mid-flight into partially-drained "
              "fleets through the AOT plan cache, incl. fault tapes "
              "and pipeline depth 2 with forced-rollback assertion — "
              "bit-identical to ScenarioPlan.solo: events, fired "
              "faults and Kahan clocks)")
        argv = [a for a in argv if a != "--runtime-serve"]
    if "--runtime-resume" in argv:
        problems = check_resume_runtime()
        if problems:
            print("check_determinism: resume runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: resume runtime OK (preemption-safe "
              "campaigns — service killed at a collect boundary and "
              "rebuilt from its FleetCheckpoint token, warm through "
              "the AOT plan cache, incl. fault tapes and pipeline "
              "depth 2; double resume idempotent; a NaN-poisoned "
              "lane quarantines with a nan_solve LaneFault while "
              "every other lane stays bit-identical to "
              "ScenarioPlan.solo: events, fired faults and Kahan "
              "clocks)")
        argv = [a for a in argv if a != "--runtime-resume"]
    if "--runtime-collective" in argv:
        problems = check_collective_runtime()
        if problems:
            print("check_determinism: collective runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: collective runtime OK (schedule "
              "tapes — captured smpi/coll.py comm sequences equal the "
              "mirrored generators at non-power-of-two ranks; tape "
              "runs solo/k=1/pipelined/batched/fault-composed "
              "bit-identical to the host maestro: events, activations "
              "and Kahan clocks, at a >= 3x dispatch advantage; and a "
              "live-captured NAS IS kernel replayed end to end on the "
              "tape path at >= 10x fewer dispatches per collective "
              "step)")
        argv = [a for a in argv if a != "--runtime-collective"]
    if "--quick" in argv:
        problems = quick_checks()
        if problems:
            print("check_determinism: quick checks FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: quick OK (lint + small-N drain + "
              "batch + pipeline + shard + phase + fault + serve + "
              "resume + collective runtime)")
        return 0
    if "--runtime-phase" in argv:
        problems = check_phase_runtime()
        if problems:
            print("check_determinism: phase runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: phase runtime OK (device-resident "
              "mutating phases — compute/comm alternation incl. "
              "forced resumable (bandwidth change) and non-resumable "
              "(deadline'd flow) mutations and the pipelined fleet "
              "variant — bit-identical to the native loop: event "
              "order, timestamps and clocks)")
        argv = [a for a in argv if a != "--runtime-phase"]
    if "--runtime-pipeline" in argv:
        problems = check_pipeline_runtime()
        if problems:
            print("check_determinism: pipeline runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: pipeline runtime OK (speculative "
              "pipelined drain — solo depths 1/2 incl. forced "
              "repack/budget mispredicts, and a batched fleet — "
              "bit-identical to the unpipelined superstep path: "
              "event order, timestamps and clocks)")
        argv = [a for a in argv if a != "--runtime-pipeline"]
    if "--runtime-batch" in argv:
        problems = check_batch_runtime()
        if problems:
            print("check_determinism: batch runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: batch runtime OK (replicas from a "
              "64-wide mixed fault/sweep fleet bit-identical to solo: "
              "event order and clocks)")
        argv = [a for a in argv if a != "--runtime-batch"]
    if "--runtime-warmstart" in argv:
        problems = check_warmstart_runtime()
        if problems:
            print("check_determinism: warm-start runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: warm-start runtime OK (cold vs "
              "warm-started selective bit-identical: event order and "
              "final clocks)")
        argv = [a for a in argv if a != "--runtime-warmstart"]
    if "--runtime-drain" in argv:
        problems = check_drain_runtime()
        if problems:
            print("check_determinism: drain runtime check FAILED:")
            for p in problems:
                print(f"  {p}")
            return 1
        print("check_determinism: drain runtime OK "
              "(unfused/fused/superstep bit-reproducible, orders agree)")
        argv = [a for a in argv if a != "--runtime-drain"]
    repo_root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = collect_violations(repo_root)
    if not violations:
        print("check_determinism: OK (%s clean — simlint wallclock-rng)"
              % ", ".join(AUDITED_PATHS))
        return 0
    print("check_determinism: nondeterminism sources found "
          "(use utils/rngstream.py and the simulated clock):")
    for path, lineno, text in violations:
        print(f"  {path}:{lineno}: {text}")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
