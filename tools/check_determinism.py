#!/usr/bin/env python3
"""Static determinism lint for the simulation core.

The kernel, the solver and the fault-injection subsystem must be
bit-reproducible: all randomness goes through the seeded RngStream
(simgrid_tpu/utils/rngstream.py) and all time through the simulated
clock.  This lint fails if any file under the audited packages reaches
for the wall clock or Python's global RNG:

    random.<anything>      (incl. np.random / jax.random)
    time.time(
    datetime.now(

Comments are stripped before matching so prose mentioning the banned
names stays legal; code and docstrings are audited as written.
Run directly (exit 1 on violations) or through tests/test_determinism_lint.py.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

AUDITED_DIRS = (
    os.path.join("simgrid_tpu", "kernel"),
    os.path.join("simgrid_tpu", "ops"),
    os.path.join("simgrid_tpu", "faults"),
)

BANNED = [
    (re.compile(r"\brandom\s*\."), "random."),
    (re.compile(r"\btime\.time\s*\("), "time.time("),
    (re.compile(r"\bdatetime\.now\s*\("), "datetime.now("),
]

_COMMENT = re.compile(r"#.*$")


def collect_violations(repo_root: str) -> List[Tuple[str, int, str]]:
    """(relative path, line number, stripped line) for every banned
    pattern occurrence under the audited directories."""
    violations: List[Tuple[str, int, str]] = []
    for rel_dir in AUDITED_DIRS:
        top = os.path.join(repo_root, rel_dir)
        if not os.path.isdir(top):
            continue
        for dirpath, _dirnames, filenames in os.walk(top):
            for filename in sorted(filenames):
                if not filename.endswith(".py"):
                    continue
                path = os.path.join(dirpath, filename)
                with open(path, encoding="utf-8") as f:
                    for lineno, line in enumerate(f, 1):
                        code = _COMMENT.sub("", line)
                        for pattern, label in BANNED:
                            if pattern.search(code):
                                violations.append(
                                    (os.path.relpath(path, repo_root),
                                     lineno, line.strip()))
                                break
    return violations


def main(argv: List[str]) -> int:
    repo_root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = collect_violations(repo_root)
    if not violations:
        print("check_determinism: OK (%s clean)" % ", ".join(AUDITED_DIRS))
        return 0
    print("check_determinism: nondeterminism sources found "
          "(use utils/rngstream.py and the simulated clock):")
    for path, lineno, text in violations:
        print(f"  {path}:{lineno}: {text}")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
