#!/usr/bin/env python
"""Measure the LMM solver baseline across backends on the reference's
maxmin_bench classes (maxmin_bench.cpp:110-129) and emit a markdown
table + JSON for BASELINE_MEASURED.md.

Backends:
  ref-native  the C++ solver in native/ driven through the reference's
              exact bench protocol (native/maxmin_bench binary). The
              reference itself cannot be compiled in this image (SimGrid
              3.23 hard-requires boost::intrusive; no boost is installed),
              so this — same construction LCG, solver pinned bit-for-bit
              against the Python oracle, which is pinned against the
              reference's tesh outputs — is the C++ proxy baseline.
  host-python the exact Python list solver (simgrid_tpu/ops/lmm_host.py)
  jax-cpu     the vectorized fixpoint on CPU
  jax-dev     the vectorized fixpoint on the default accelerator, if any

Usage: python tools/measure_baseline.py [--classes small,medium,big,huge]
           [--iters 5] [--json out.json]
"""
import argparse
import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def time_native(cls, iters, timeout=3600):
    bench = os.path.join(NATIVE_DIR, "maxmin_bench")
    if not os.path.exists(bench):
        subprocess.run(["make", "-C", NATIVE_DIR, "maxmin_bench"], check=True)
    out = subprocess.run([bench, cls, str(iters), "perf"],
                         capture_output=True, text=True, timeout=timeout)
    m = re.search(r"mean_us=([\d.]+) stdev_us=([\d.]+)", out.stdout)
    if not m:
        return {"error": out.stderr[-500:]}
    return {"mean_ms": float(m.group(1)) / 1000,
            "stdev_ms": float(m.group(2)) / 1000}


def time_host_python(cls, iters):
    from simgrid_tpu.ops.bench_systems import build_class
    times = []
    for it in range(iters):
        s, _ = build_class(cls, seed=it + 1)
        t0 = time.perf_counter()
        s.solve_exact()
        times.append(time.perf_counter() - t0)
    return _stats(times)


def time_jax(cls, iters, platform):
    """Time the device fixpoint: flatten once per seed, then time
    steady-state solve_arrays (compile cached after warmup)."""
    import jax
    if platform == "cpu":
        # The axon sitecustomize overrides JAX_PLATFORMS at interpreter
        # start; forcing via jax.config wins (tests/conftest.py does the
        # same).
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from simgrid_tpu.ops import lmm_jax
    from simgrid_tpu.ops.bench_systems import build_class
    from simgrid_tpu.utils.config import config

    devs = [d for d in jax.devices() if d.platform == platform]
    if not devs:
        return {"error": f"no {platform} device"}
    dev = devs[0]
    eps = config["maxmin/precision"]
    times, flat_times, rounds = [], [], 0
    for it in range(iters):
        s, _ = build_class(cls, seed=it + 1)
        t0 = time.perf_counter()
        flat = lmm_jax.flatten(list(s.active_constraint_set), np.float64)
        flat_times.append(time.perf_counter() - t0)
        arrays, _vars = flat
        # warmup (compile + first solve)
        lmm_jax.solve_arrays(arrays, eps, device=dev)
        t0 = time.perf_counter()
        _, _, _, rounds = lmm_jax.solve_arrays(arrays, eps, device=dev)
        times.append(time.perf_counter() - t0)
    st = _stats(times)
    st["flatten_ms"] = round(sum(flat_times) / len(flat_times) * 1000, 3)
    st["rounds"] = rounds
    return st


def _stats(times):
    n = len(times)
    mean = sum(times) / n
    var = sum((t - mean) ** 2 for t in times) / n
    return {"mean_ms": round(mean * 1000, 3),
            "stdev_ms": round(var ** 0.5 * 1000, 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--classes", default="small,medium,big")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--huge-iters", type=int, default=1)
    ap.add_argument("--json", default=None)
    ap.add_argument("--skip", default="",
                    help="comma list of backends to skip")
    args = ap.parse_args()
    skip = set(filter(None, args.skip.split(",")))

    results = {}
    for cls in args.classes.split(","):
        iters = args.huge_iters if cls == "huge" else args.iters
        row = {}
        if "native" not in skip:
            row["ref-native"] = time_native(cls, iters)
            print(f"{cls} ref-native: {row['ref-native']}", flush=True)
        if "python" not in skip:
            row["host-python"] = time_host_python(cls, iters)
            print(f"{cls} host-python: {row['host-python']}", flush=True)
        if "jax-cpu" not in skip:
            row["jax-cpu"] = _run_jax_subprocess(cls, iters, "cpu")
            print(f"{cls} jax-cpu: {row['jax-cpu']}", flush=True)
        if "jax-dev" not in skip:
            row["jax-dev"] = _run_jax_subprocess(cls, iters, "device")
            print(f"{cls} jax-dev: {row['jax-dev']}", flush=True)
        results[cls] = row

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    print(json.dumps(results, indent=1))


def _run_jax_subprocess(cls, iters, kind):
    """Run the jax timing in a subprocess so a wedged accelerator or OOM
    cannot take down the whole measurement run (bench.py's lesson)."""
    env = dict(os.environ)
    if kind == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        platform = "cpu"
    else:
        platform = env.get("MEASURE_DEVICE_PLATFORM", "tpu")
    code = (
        "import sys, json; sys.path.insert(0, {root!r})\n"
        "import tools.measure_baseline as mb\n"
        "print('RESULT ' + json.dumps(mb.time_jax({cls!r}, {iters}, "
        "{platform!r})))\n").format(
            root=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            cls=cls, iters=iters, platform=platform)
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=1800)
    except subprocess.TimeoutExpired:
        return {"error": "timeout"}
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    return {"error": (out.stderr or out.stdout)[-500:]}


if __name__ == "__main__":
    main()
