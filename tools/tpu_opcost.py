#!/usr/bin/env python3
"""Trustworthy per-op cost table for the tunneled accelerator.

Protocol: `block_until_ready` does not reliably block through the axon
tunnel (round-4 finding), so every measurement here chains K
data-dependent executions of the op (each rep consumes a scalar derived
from the previous output) and ends with ONE host fetch; per-op time is
(wall - one_sync) / K.  The sync cost itself is measured the same way
with a trivial kernel.

Ops measured at the 100k-flow bench-class shapes (C=16384 cnst,
V=100k vars, deg 4 -> E=400k, bucketed E=524288 / V=131072):

  flat gather        rou[e_cnst]            (the fast path per r4)
  2d gather          rou[vc_cnst [V,4]]
  scatter-add        zeros(C).at[e_cnst].add(w)
  scatter-min        full(C,inf).at[e_cnst].min(w)
  scatter-add3       stacked 3-channel scatter-add
  cumsum             jnp.cumsum over [E]
  cummin             lax.associative_scan(min) over [E]
  seg-sum-sorted     cumsum + boundary flat gather (needs e_cnst sorted)
  seg-min-sorted     cummin + boundary flat gather
  round-current      one body_local_vc-equivalent round
  round-sorted       one candidate scatter-free round
  pallas-probe       trivial pallas kernel (is pallas usable at all?)

Appends one JSON line per run to bench_results/tpu_opcost.jsonl.
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "bench_results", "tpu_opcost.jsonl")


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax

    dev = jax.devices()[0]
    platform = dev.platform
    dtype = jnp.float32 if platform != "cpu" else jnp.float64
    rec = {"platform": platform, "ts": round(time.time(), 1)}

    C, V, DEG = 16384, 100_000, 4
    E = V * DEG
    Eb, Vb = 524288, 131072
    rng = np.random.default_rng(7)
    e_cnst_np = np.zeros(Eb, np.int32)
    e_cnst_np[:E] = np.sort(rng.integers(0, C, E).astype(np.int32))
    e_var_np = np.zeros(Eb, np.int32)
    e_var_np[:E] = np.repeat(np.arange(V, dtype=np.int32), DEG)
    e_w_np = np.zeros(Eb, np.float64)
    e_w_np[:E] = rng.uniform(0.5, 1.5, E)
    vc_cnst_np = np.zeros((Vb, DEG), np.int32)
    vc_cnst_np[:V] = rng.integers(0, C, (V, DEG)).astype(np.int32)

    e_cnst = jnp.asarray(e_cnst_np)
    e_var = jnp.asarray(e_var_np)
    e_w = jnp.asarray(e_w_np, dtype)
    vc_cnst = jnp.asarray(vc_cnst_np)
    rou = jnp.asarray(rng.uniform(1.0, 2.0, C), dtype)
    # segment boundaries for the sorted layout (host-precomputed, like
    # the solver would)
    seg_end_np = np.searchsorted(e_cnst_np[:E], np.arange(1, C + 1),
                                 side="left")
    seg_end = jnp.asarray(np.concatenate([[0], seg_end_np]).astype(np.int32))

    def timed(name, make_fn, K=24):
        """make_fn(seed_scalar) -> array; chained K times, one fetch."""
        fn = jax.jit(make_fn)
        s = jnp.asarray(0.0, dtype)
        # warm (compile) + one fetch
        float(np.asarray(fn(s).ravel()[0]))
        t0 = time.perf_counter()
        s = jnp.asarray(0.0, dtype)
        for _ in range(K):
            out = fn(s)
            s = out.ravel()[0] * 1e-30
        float(np.asarray(s))
        wall = time.perf_counter() - t0
        rec[name] = round((wall - rec.get("sync_ms", 0.0) / 1e3) / K * 1e3,
                          3)
        print(f"  {name}: {rec[name]} ms")

    # sync cost: trivial chained op, K=1 fetch each of 8 reps
    triv = jax.jit(lambda s: s + 1.0)
    float(np.asarray(triv(jnp.asarray(0.0, dtype))))
    times = []
    for _ in range(8):
        t0 = time.perf_counter()
        float(np.asarray(triv(jnp.asarray(0.0, dtype))))
        times.append(time.perf_counter() - t0)
    rec["sync_ms"] = round(float(np.median(times)) * 1e3, 3)
    print(f"  sync_ms: {rec['sync_ms']}")

    timed("gather_flat_E", lambda s: jnp.take(rou + s, e_cnst))
    timed("gather_2d_V4", lambda s: jnp.take(rou + s, vc_cnst))
    timed("scatter_add", lambda s: jnp.zeros(C, dtype).at[e_cnst].add(
        e_w + s))
    timed("scatter_min", lambda s: jnp.full(C, jnp.inf, dtype)
          .at[e_cnst].min(e_w + s))
    timed("scatter_add3", lambda s: jnp.zeros((C, 3), dtype)
          .at[e_cnst].add(jnp.stack([e_w + s, e_w, e_w], axis=-1)))
    timed("cumsum_E", lambda s: jnp.cumsum(e_w + s))
    timed("cummin_E", lambda s: lax.associative_scan(jnp.minimum, e_w + s))
    timed("seg_sum_sorted", lambda s: jnp.diff(
        jnp.concatenate([jnp.zeros(1, dtype),
                         jnp.cumsum(e_w + s)])[seg_end]))

    def seg_min_sorted(s):
        cm = lax.associative_scan(jnp.minimum, e_w + s)
        # min of segment c = cummin at (end-1) is wrong across segment
        # boundary; proper: shifted-prefix trick needs segmented scan.
        # Approximation for COST purposes only: cummin + boundary gather.
        return jnp.take(cm, jnp.maximum(seg_end[1:] - 1, 0))
    timed("seg_min_sorted", seg_min_sorted)

    # current round-equivalent: 2 gathers + scatter-min + 3ch scatter-add
    def round_current(s):
        rv = jnp.take(rou + s, vc_cnst)                       # gather 2d
        nmin_v = rv.min(axis=1)
        nmin_c = jnp.full(C, jnp.inf, dtype).at[vc_cnst.ravel()].min(
            jnp.broadcast_to(nmin_v[:, None], vc_cnst.shape).ravel())
        proc = jnp.take(nmin_c, vc_cnst)                      # gather 2d
        fix = (rv <= proc).all(axis=1)
        contrib = jnp.stack([jnp.broadcast_to(fix[:, None].astype(dtype),
                                              vc_cnst.shape).ravel(),
                             jnp.broadcast_to(nmin_v[:, None],
                                              vc_cnst.shape).ravel(),
                             jnp.ones(Vb * DEG, dtype)], axis=-1)
        sums = jnp.zeros((C, 3), dtype).at[vc_cnst.ravel()].add(contrib)
        return sums
    timed("round_current_like", round_current)

    # candidate sorted round: flat gathers + cumsum-based segment ops
    def round_sorted(s):
        re_ = jnp.take(rou + s, e_cnst)                       # flat gather
        nmin_v = re_.reshape(-1, DEG).min(axis=1)             # var-major?
        # (cost probe only: uses e_var-major reshape which matches the
        # repeat layout above)
        nmin_e = jnp.repeat(nmin_v, DEG)
        cm = lax.associative_scan(jnp.minimum, nmin_e)
        nmin_c = jnp.take(cm, jnp.maximum(seg_end[1:] - 1, 0))
        proc_e = jnp.take(nmin_c, e_cnst)                     # flat gather
        fix_v = (re_.reshape(-1, DEG) <= proc_e.reshape(-1, DEG)).all(
            axis=1)
        contrib = jnp.repeat(jnp.where(fix_v, nmin_v, 0.0), DEG) * e_w
        cs = jnp.cumsum(contrib)
        d_rem = jnp.diff(jnp.concatenate([jnp.zeros(1, dtype), cs])[
            seg_end])
        return d_rem
    timed("round_sorted_like", round_sorted)

    # pallas probe
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def pk(x_ref, o_ref):
            o_ref[:] = x_ref[:] * 2.0

        @jax.jit
        def pdouble(x):
            return pl.pallas_call(
                pk, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        x = jnp.ones((256, 128), dtype)
        v = float(np.asarray(pdouble(x))[0, 0])
        rec["pallas_probe"] = "ok" if v == 2.0 else f"bad value {v}"
    except Exception as exc:  # noqa: BLE001
        rec["pallas_probe"] = f"error: {type(exc).__name__}: {exc}"[:300]
    print(f"  pallas: {rec['pallas_probe']}")

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
