"""Extract the ppn=1 tuning tables from smpi_intel_mpi_selector.cpp
into a compact Python data module."""
import re

src = open("/root/reference/src/smpi/colls/smpi_intel_mpi_selector.cpp").read()

ops = ["allreduce", "alltoall", "barrier", "bcast", "reduce",
       "reduce_scatter", "allgather", "allgatherv", "gather", "scatter",
       "alltoallv"]

def extract_table(op):
    m = re.search(rf"intel_tuning_table_element intel_{op}_table\[\]\s*=\s*", src)
    if not m:
        return None
    i = src.index("{", m.end())
    # scan matching braces
    depth = 0
    start = i
    while True:
        if src[i] == "{": depth += 1
        elif src[i] == "}":
            depth -= 1
            if depth == 0: break
        i += 1
    body = src[start:i+1]
    # strip comments
    body = re.sub(r"/\*.*?\*/", "", body, flags=re.S)
    body = re.sub(r"//[^\n]*", "", body)
    # tokenize nested braces into python lists
    py = body.replace("{", "[").replace("}", "]")
    data = eval(py)
    out = []
    for elem in data:                     # top level: ppn entries
        ppn = elem[0]
        if ppn != 1:
            continue
        for np_elem in elem[1:]:          # numproc entries
            for e in np_elem:
                max_np, n, entries = e[0], e[1], e[2:]
                pairs = [(s, a) for s, a in entries[0][:n]]
                out.append((max_np, pairs))
    return out

print("# Intel-MPI ppn=1 tuning tables, extracted from the reference's")
print("# smpi_intel_mpi_selector.cpp (I_MPI_ADJUST_* regime data) by")
print("# tools/extract_intel_tables.py. Each op: [(max_num_proc,")
print("# [(max_size, algo_index_1based), ...]), ...].")
print()
print("INTEL_TABLES = {")
for op in ops:
    t = extract_table(op)
    if t is None:
        continue
    print(f"    {op!r}: [")
    for max_np, pairs in t:
        print(f"        ({max_np}, {pairs}),")
    print("    ],")
print("}")
