#!/usr/bin/env python3
"""TPU chip-watch: probe the axon chip on a timer, log liveness transitions.

The chip behind the axon tunnel can be wedged for hours (it recovers after
idle time).  This watcher runs ``jax.devices()`` in a throwaway subprocess
with a hard timeout, appending one JSON line per probe to
``bench_results/chip_watch.jsonl``.  The moment the chip answers, the
prepared one-experiment-per-process scripts (tools/tpu_experiments.py) should
be run and their numbers committed.

Usage:  python tools/chip_watch.py [--interval 300] [--once]
"""
import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "bench_results", "chip_watch.jsonl")

PROBE_SRC = r"""
import json, time
t0 = time.time()
import jax
devs = jax.devices()
kind = devs[0].device_kind if devs else "none"
plat = devs[0].platform if devs else "none"
# A tiny real dispatch proves the chip executes, not just enumerates.
import jax.numpy as jnp
x = jnp.ones((128, 128), dtype=jnp.bfloat16)
y = (x @ x).block_until_ready()
print(json.dumps({"platform": plat, "kind": kind, "n": len(devs),
                  "probe_s": round(time.time() - t0, 2)}))
"""


def probe(timeout: float = 120.0) -> dict:
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            capture_output=True, text=True, timeout=timeout,
            cwd=ROOT, env={**os.environ},
        )
        if out.returncode == 0 and out.stdout.strip():
            info = json.loads(out.stdout.strip().splitlines()[-1])
            info["alive"] = info.get("platform") not in (None, "none", "cpu")
            return info
        return {"alive": False, "error": (out.stderr or "")[-300:],
                "wall_s": round(time.time() - t0, 2)}
    except subprocess.TimeoutExpired:
        return {"alive": False, "error": f"timeout after {timeout:.0f}s",
                "wall_s": round(time.time() - t0, 2)}
    except Exception as exc:  # noqa: BLE001
        return {"alive": False, "error": repr(exc)[:300],
                "wall_s": round(time.time() - t0, 2)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=300.0)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    while True:
        rec = probe(args.timeout)
        rec["ts"] = round(time.time(), 1)
        with open(LOG, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
        if args.once or rec.get("alive"):
            return 0 if rec.get("alive") else 1
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
