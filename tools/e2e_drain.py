#!/usr/bin/env python3
"""End-to-end north-star bench (BASELINE config #4): drain a 100k-flow
set on a 65,536-host dragonfly to completion, native C++ maxmin vs the
JAX backend (CPU or TPU), comparing WALL-CLOCK and EVENT ORDER.

The simulation phase measured is the whole network drain: every
solve, every time advance, every completion event, until no flow
remains.  Platform parse + route expansion are reported separately
(identical work for every backend).

Workloads:
  random   N random host pairs (the literal config-#4 stress shape)
  alltoall R ranks spread evenly, all ordered pairs (the north-star
           text's SMPI alltoall shape; contention depth ~R)

Usage:
  python tools/e2e_drain.py --backend native|jax [--platform cpu|tpu]
         [--workload random|alltoall] [--flows 100000] [--ranks 320]
         [--fused] [--superstep K]
         [--out bench_results/e2e_drain.jsonl] [--events-out FILE.npz]

`--fused` runs the jax drain with the single-dispatch solve+advance
kernel (1 sync/advance); `--superstep K` batches K advances per
dispatch with the device completion ring (~1/K syncs/advance) and
on-device repacks; `--pipeline D` additionally keeps D speculative
supersteps in flight (double-buffered rings: the host processes ring
N while the device runs ring N+1 — bit-identical results, and the
row carries the blocking-fetch split + speculation commit counters).  `--phase-stats` prints, per phase (build/route,
latency advance, drain), the device dispatch count, uploaded bytes
split full vs delta (ops.opstats counters fed by _device_args, the
warm solver and the drain executor), fixpoint rounds, and the runtime
fast-path coverage split (`fastpath_advances` vs `native_advances`
with the invalidation-cause histogram, for engine-driven runs), and
appends the counters to the labeled bench row.  Rows are labeled with mode/superstep_k/syncs so
bench.py reports each shape separately.  Completion grouping is
RELATIVE (done_eps * size) on every backend, the reference's
sg_maxmin_precision semantics — the fix for the round-5 f32
tie-splitting abort.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def build_system(workload: str, flows: int, ranks: int, size: float):
    """Parse the 65k dragonfly, post the flow set, advance past the
    latency phase, and flatten to COO arrays + flow action order."""
    import numpy as np
    from simgrid_tpu import s4u
    from simgrid_tpu.ops import lmm_jax
    from tools.scale_proof import build_platform

    t0 = time.perf_counter()
    platform = build_platform("/tmp/dragonfly65k.xml", 65536)
    e = s4u.Engine(["e2e", "--cfg=lmm/backend:list",
                    "--cfg=network/maxmin-selective-update:no",
                    "--cfg=network/optim:Full"])
    e.load_platform(platform)
    hosts = e.get_all_hosts()
    n_hosts = len(hosts)
    build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    model = e.pimpl.network_model
    actions = []
    if workload == "alltoall":
        stride = n_hosts // ranks
        rh = [hosts[i * stride] for i in range(ranks)]
        for i in range(ranks):
            for j in range(ranks):
                if i != j:
                    actions.append(model.communicate(rh[i], rh[j],
                                                     size, -1.0))
    else:
        rng = np.random.default_rng(42)
        pairs = rng.integers(0, n_hosts, size=(flows, 2))
        for k in range(flows):
            src, dst = int(pairs[k, 0]), int(pairs[k, 1])
            if src == dst:
                dst = (dst + 1) % n_hosts
            actions.append(model.communicate(hosts[src], hosts[dst],
                                             size, -1.0))
    for _ in range(400):
        n_live = sum(1 for a in actions
                     if a.variable is not None
                     and a.variable.sharing_penalty > 0)
        if n_live == len(actions):
            break
        e.pimpl.surf_solve(-1.0)
    route_s = time.perf_counter() - t0

    flat = lmm_jax.flatten(list(model.system.active_constraint_set))
    arrays, vars_in_order = flat
    # flow id per variable slot = index into `actions`
    var_slot = {id(a.variable): k for k, a in enumerate(actions)}
    slot_flow = np.array([var_slot[id(v)] for v in vars_in_order],
                         np.int64)
    return arrays, slot_flow, dict(build_s=round(build_s, 1),
                                   route_s=round(route_s, 1),
                                   n_hosts=n_hosts,
                                   flows=len(actions))


def drain_native(arrays, slot_flow, size, done_eps=1e-4):
    """Reference-architecture baseline: the exact C++ maxmin list
    solver (native/lmm.cc) drives the same drain loop.  Per advance the
    live system is repacked with vectorized numpy (cheap next to the
    solve) so the C++ solver only ever sees live flows — the same
    favor the JAX path gets from its repacks.  Completion grouping is
    relative (done_eps * size), matching DrainSim's default rule."""
    import numpy as np
    from simgrid_tpu.ops import lmm_native

    E = arrays.n_elem
    e_var = arrays.e_var[:E].copy()
    e_cnst = arrays.e_cnst[:E].copy()
    e_w = arrays.e_w[:E].astype(np.float64)
    c_bound = arrays.c_bound.astype(np.float64)
    n_c = len(c_bound)
    n_v = arrays.n_var
    rem = np.full(n_v, float(size))
    live = np.ones(n_v, bool)
    ids = np.arange(n_v)
    t = 0.0
    events = []
    advances = 0
    t0 = time.perf_counter()
    while live.any():
        keep = np.flatnonzero(live)
        old2new = np.full(n_v, -1, np.int32)
        old2new[keep] = np.arange(len(keep), dtype=np.int32)
        emask = live[e_var]
        ev, ec, ew = old2new[e_var[emask]], e_cnst[emask], e_w[emask]
        pen = np.ones(len(keep))
        vb = np.full(len(keep), -1.0)
        vals, _, _ = lmm_native.solve_coo(
            ev, ec, ew, c_bound, np.zeros(n_c, np.uint8), pen, vb,
            1e-5, len(ev), n_c, len(keep))
        rate = np.asarray(vals)
        flowing = rate > 0
        rl = rem[keep]
        dts = np.where(flowing, rl / np.where(flowing, rate, 1.0),
                       np.inf)
        dt = dts.min()
        if not np.isfinite(dt):
            raise RuntimeError("native drain stalled")
        rl2 = np.where(flowing, rl - rate * dt, rl)
        done = flowing & (rl2 < done_eps * size)
        t += dt
        advances += 1
        for fid in ids[keep[np.flatnonzero(done)]]:
            events.append((t, int(slot_flow[fid])))
        rem[keep] = np.where(done, 0.0, rl2)
        live[keep[done]] = False
    wall = time.perf_counter() - t0
    return events, dict(advances=advances, wall_s=round(wall, 1),
                        t_sim=t)


def drain_jax(arrays, slot_flow, size, platform=None, done_eps=1e-4,
              fused=False, superstep=0, pipeline=0):
    import numpy as np
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)
    import jax
    from simgrid_tpu.ops import opstats
    from simgrid_tpu.ops.lmm_drain import DrainSim

    dev = jax.devices()[0]
    dtype = np.float32 if dev.platform != "cpu" else np.float64
    E = arrays.n_elem
    sim = DrainSim(arrays.e_var[:E], arrays.e_cnst[:E],
                   arrays.e_w[:E].astype(dtype),
                   arrays.c_bound[:arrays.n_cnst].astype(dtype),
                   np.full(arrays.n_var, float(size)),
                   eps=1e-5, done_eps=done_eps, dtype=dtype,
                   fused=fused, superstep=superstep,
                   pipeline=pipeline)
    # warm the jits on the first advance before timing?  No: honest
    # end-to-end wall-clock includes compiles once per shape; report
    # both (first advance separately).
    fetch_mark = opstats.snapshot()
    t0 = time.perf_counter()
    n = sim.n_v
    if superstep and pipeline:
        # the pipelined driver owns the loop (speculative in-flight
        # supersteps; progress reported per collected ring)
        last = [time.perf_counter()]

        def report(batches):
            if time.perf_counter() - last[0] >= 10.0:
                last[0] = time.perf_counter()
                print(f"[drain] superstep {sim.supersteps}: "
                      f"advances {sim.advances}, t_sim {sim.t:.4f}, "
                      f"spec {sim.spec_committed}/{sim.spec_issued}, "
                      f"wall {time.perf_counter()-t0:.0f}s",
                      flush=True)
        sim.on_batches = report
        sim.run()
        n = 0
    elif superstep:
        while n:
            before = sim.advances
            n, _ = sim.superstep_batch()
            if n and sim.advances == before:
                n = sim._advance_fused()
            print(f"[drain] superstep {sim.supersteps}: "
                  f"advances {sim.advances}, live {n}, "
                  f"t_sim {sim.t:.4f}, syncs {sim.syncs}, "
                  f"wall {time.perf_counter()-t0:.0f}s", flush=True)
    else:
        while n:
            n = sim.advance()
            if sim.advances % 50 == 0 or sim.advances <= 2:
                print(f"[drain] advance {sim.advances}: live {n}, "
                      f"t_sim {sim.t:.4f}, "
                      f"wall {time.perf_counter()-t0:.0f}s", flush=True)
    wall = time.perf_counter() - t0
    fetch_stats = opstats.diff(fetch_mark)
    events = [(t, int(slot_flow[fid])) for t, fid in sim.events]
    mode = ("pipeline" if superstep and pipeline else
            "superstep" if superstep else
            "fused" if fused else "unfused")
    rec = dict(advances=sim.advances, wall_s=round(wall, 1),
               t_sim=sim.t, rounds=sim.rounds, syncs=sim.syncs,
               repacks=sim.repacks, jax_platform=dev.platform,
               mode=mode, superstep_k=superstep,
               supersteps=sim.supersteps,
               syncs_per_advance=round(
                   sim.syncs / max(sim.advances, 1), 4))
    if pipeline:
        rec.update(pipeline_depth=pipeline,
                   spec_issued=sim.spec_issued,
                   spec_committed=sim.spec_committed,
                   spec_rolled_back=sim.spec_rolled_back,
                   fetches=int(fetch_stats.get("fetches", 0)),
                   blocking_fetches=int(
                       fetch_stats.get("blocking_fetches", 0)),
                   host_block_ms=round(
                       fetch_stats.get("host_block_ms", 0), 1))
    return events, rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", choices=["native", "jax"],
                    required=True)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--workload", default="random",
                    choices=["random", "alltoall"])
    ap.add_argument("--flows", type=int, default=100_000)
    ap.add_argument("--ranks", type=int, default=320)
    ap.add_argument("--size", type=float, default=1e6)
    ap.add_argument("--fused", action="store_true",
                    help="jax: fused solve+advance, 1 sync/advance")
    ap.add_argument("--superstep", type=int, default=0, metavar="K",
                    help="jax: K advances per dispatch (~1/K "
                         "syncs/advance, on-device repacks)")
    ap.add_argument("--pipeline", type=int, default=0, metavar="D",
                    help="jax: keep D speculative supersteps in "
                         "flight (requires --superstep; bit-identical "
                         "results, blocking-fetch split on the row)")
    ap.add_argument("--phase-stats", action="store_true",
                    help="report per-phase dispatch count, uploaded "
                         "bytes (full vs delta) and fixpoint rounds; "
                         "counters ride the bench row")
    ap.add_argument("--out", default=None)
    ap.add_argument("--events-out", default=None)
    args = ap.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import numpy as np
    from simgrid_tpu.ops import opstats

    phase_marks = [opstats.snapshot()]
    arrays, slot_flow, info = build_system(args.workload, args.flows,
                                           args.ranks, args.size)
    phase_marks.append(opstats.snapshot())
    rec = {"backend": args.backend, "platform": args.platform,
           "workload": args.workload, **info,
           "n_cnst": arrays.n_cnst, "n_var": arrays.n_var,
           "n_elem": arrays.n_elem}
    print(json.dumps(rec), flush=True)

    if args.backend == "native":
        events, stats = drain_native(arrays, slot_flow, args.size)
    else:
        events, stats = drain_jax(arrays, slot_flow, args.size,
                                  args.platform, fused=args.fused,
                                  superstep=args.superstep,
                                  pipeline=args.pipeline)
    rec.update(stats)
    rec["n_events"] = len(events)
    if args.phase_stats:
        drain_mark = opstats.snapshot()
        keys = ("dispatches", "uploaded_bytes_full",
                "uploaded_bytes_delta", "fixpoint_rounds",
                "warm_solves", "cold_solves",
                # fast-path coverage: advances served from the device
                # plan vs the generic native loop, plus the
                # invalidation-cause histogram (ops.drain_path)
                "fastpath_advances", "native_advances",
                "drain_transitions", "drain_transition_slots",
                "drain_cause_transition", "drain_cause_partial_advance",
                "drain_cause_profile_event", "drain_cause_stall",
                "drain_cause_unrecognized",
                # fault-tape activity (ops.lmm_drain tape=): compiled
                # entries, mid-drain fires, speculative replays
                "fault_tape_slots", "fault_tape_events",
                "fault_replays", "warm_bound_restarts",
                # collective-tape activity (ops.lmm_drain
                # collective=): compiled DAG slots, fired
                # activations, speculative replays
                "collective_tape_slots", "collective_tape_fires",
                "collective_replays")
        phases = {}
        for name, before, after in (
                ("build+latency", phase_marks[0], phase_marks[1]),
                ("drain", phase_marks[1], drain_mark)):
            delta = {k: after.get(k, 0) - before.get(k, 0) for k in keys}
            phases[name] = {k: v for k, v in delta.items() if v}
            print(json.dumps({"phase": name, **phases[name]}),
                  flush=True)
        rec["phase_stats"] = phases
        fp = phases["drain"].get("fastpath_advances", 0)
        nat = phases["drain"].get("native_advances", 0)
        if fp or nat:
            rec["fastpath_coverage"] = round(fp / max(nat, 1), 3)
    print(json.dumps(rec), flush=True)

    if args.events_out:
        np.savez_compressed(args.events_out,
                            t=np.array([e[0] for e in events]),
                            flow=np.array([e[1] for e in events]))
    if args.out:
        with open(args.out, "a") as fh:
            fh.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
