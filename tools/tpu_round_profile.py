#!/usr/bin/env python3
"""Micro-profile of ONE solver round on the real chip: is the ~130 ms/
round at the 100k class the gathers themselves, the while_loop
lowering, or dispatch overhead?  Times straight-line jitted pieces:

  a. one ELL round body, straight-line (no loop)
  b. one COO round body, straight-line
  c. the raw primitives at the same shapes (take / segment-sum)
  d. K rounds inside one lax.while_loop vs K separate dispatches

Appends results to bench_results/tpu_round_profile.jsonl.
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "bench_results", "tpu_round_profile.jsonl")


def bench(fn, *args, reps=10):
    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times)) * 1e3


def main() -> int:
    global jax
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bench import build_arrays
    from simgrid_tpu.ops import lmm_jax

    dev = jax.devices()[0]
    rec = {"platform": dev.platform, "ts": round(time.time(), 1)}
    dtype = np.float32 if dev.platform != "cpu" else np.float64

    arrays = build_arrays(np.random.default_rng(42), 16384, 100_000, 4,
                          dtype)
    ell = lmm_jax.ell_from_arrays(arrays)
    rec["ell_shape"] = (None if ell is None else
                       [list(ell.cv_var.shape), list(ell.vc_cnst.shape)])

    E = arrays.n_elem
    e_var = jnp.asarray(arrays.e_var)
    e_cnst = jnp.asarray(arrays.e_cnst)
    e_w = jnp.asarray(arrays.e_w)
    n_c, n_v = len(arrays.c_bound), len(arrays.v_penalty)
    pen = jnp.asarray(arrays.v_penalty)

    # c. raw primitives at the same shapes
    take_v = jax.jit(lambda p, idx: jnp.take(p, idx))
    rec["take_E_ms"] = bench(take_v, pen, e_var)
    seg_sum = jax.jit(lambda w: jnp.zeros(n_c, dtype).at[e_cnst].add(w))
    rec["segsum_E_ms"] = bench(seg_sum, e_w)
    seg_max = jax.jit(lambda w: jnp.zeros(n_c, dtype).at[e_cnst].max(w))
    rec["segmax_E_ms"] = bench(seg_max, e_w)
    if ell is not None:
        cv_var = jnp.asarray(ell.cv_var)
        take2d = jax.jit(lambda p, idx: jnp.take(p, idx))
        rec["take_CW_ms"] = bench(take2d, pen, cv_var)
        cv_w = jnp.asarray(ell.cv_w)
        rowred = jax.jit(lambda w: w.sum(axis=1))
        rec["rowsum_CW_ms"] = bench(rowred, cv_w)

    # d. loop lowering: K iterations of a gather+reduce inside
    #    while_loop vs the same dispatched K times from host
    K = 8

    def one(x):
        u = jnp.take(x, e_var) * e_w
        s = jnp.zeros(n_c, dtype).at[e_cnst].add(u)
        return x * 0.5 + jnp.take(s, e_cnst % n_c).sum() * 0

    one_j = jax.jit(one)

    def k_in_loop(x):
        def body(c):
            i, x = c
            return (i + 1, one(x))
        return lax.while_loop(lambda c: c[0] < K, body,
                              (jnp.int32(0), x))[1]

    k_loop_j = jax.jit(k_in_loop)
    x0 = jnp.ones(n_v, dtype)
    t0 = time.time()
    rec["one_round_like_ms"] = bench(one_j, x0)
    rec["k_dispatches_ms"] = rec["one_round_like_ms"] * K
    rec["while_compile_s"] = None
    t0 = time.time()
    out = k_loop_j(x0)
    jax.block_until_ready(out)
    rec["while_compile_s"] = round(time.time() - t0, 2)
    rec["k_in_while_ms"] = bench(k_loop_j, x0, reps=5)

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
