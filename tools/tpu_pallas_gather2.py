#!/usr/bin/env python3
"""Which 2D-gather forms does Mosaic accept on this chip, and how fast?

Forms probed (all gather 524288 f32 from a 16384-entry table):
  A. take_along_axis(tab_bcast [8, C], idx [8, K], axis=1), looped
  B. take_along_axis(tab_rows [R, C], idx [R, Kc], axis=1) one shot,
     R x C table materialized in-kernel by broadcast
  C. jnp.take(tab [C, 1], idx [Vr, 4], axis=0)  (row gather)
  D. tab2d[idx, lane_iota] style take_along_axis along axis 0
Appends to bench_results/tpu_opcost.jsonl."""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "bench_results", "tpu_opcost.jsonl")


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    dev = jax.devices()[0]
    dtype = jnp.float32
    rec = {"platform": dev.platform, "probe": "pallas_gather_forms",
           "ts": round(time.time(), 1)}
    C, V, DEG = 16384, 131072, 4
    rng = np.random.default_rng(7)
    idx_np = rng.integers(0, C, (V, DEG)).astype(np.int32)
    tab_np = rng.uniform(1, 2, C).astype(np.float32)
    want = tab_np[idx_np]
    tab = jnp.asarray(tab_np)
    sync = 66.0

    def timed(f, K=16):
        s = jnp.asarray(0.0, dtype)
        float(np.asarray(f(s).ravel()[0]))
        t0 = time.perf_counter()
        s = jnp.asarray(0.0, dtype)
        for _ in range(K):
            s = f(s).ravel()[0] * 1e-30
        float(np.asarray(s))
        return round((time.perf_counter() - t0 - sync / 1e3) / K * 1e3, 3)

    def try_form(name, build):
        try:
            f = jax.jit(build())
            got = np.asarray(f(jnp.asarray(0.0, dtype)))
            ok = got.shape == want.reshape(got.shape).shape and \
                np.allclose(got.ravel(), want.ravel())
            if not ok:
                rec[name] = f"WRONG (shape {got.shape})"
            else:
                rec[name] = timed(f)
            print(f"  {name}: {rec[name]}")
        except Exception as exc:  # noqa: BLE001
            rec[name] = f"{type(exc).__name__}: {exc}"[:250]
            print(f"  {name}: {rec[name]}")

    # Form A: [8, C] broadcast table, idx rows of 8 x K, fori over V/8/K'
    # simplest variant: idx reshaped [8, E/8], one take_along_axis call
    idxA = jnp.asarray(idx_np.reshape(8, -1))

    def buildA():
        def k(tab_ref, idx_ref, o_ref):
            t8 = jnp.broadcast_to(tab_ref[:].reshape(1, C), (8, C))
            o_ref[:] = jnp.take_along_axis(t8, idx_ref[:], axis=1)
        return lambda s: pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((8, V * DEG // 8), dtype),
        )(tab + s, idxA)
    try_form("A_tala_8xK", buildA)

    # Form B: [64, C] table rows, idx [64, E/64]
    idxB = jnp.asarray(idx_np.reshape(64, -1))

    def buildB():
        def k(tab_ref, idx_ref, o_ref):
            t = jnp.broadcast_to(tab_ref[:].reshape(1, C), (64, C))
            o_ref[:] = jnp.take_along_axis(t, idx_ref[:], axis=1)
        return lambda s: pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((64, V * DEG // 64), dtype),
        )(tab + s, idxB)
    try_form("B_tala_64xK", buildB)

    # Form C: row gather from [C, 1]
    idxC2 = jnp.asarray(idx_np)

    def buildC():
        def k(tab_ref, idx_ref, o_ref):
            o_ref[:] = jnp.take(tab_ref[:], idx_ref[:], axis=0)[..., 0]
        return lambda s: pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((V, DEG), dtype),
        )((tab + s).reshape(C, 1), idxC2)
    try_form("C_rowgather", buildC)

    # Form D: take_along_axis along axis 0: tab2d [C, 128], idx [E/128,
    # 128] -> out[i, j] = tab2d[idx[i, j], j]; table replicated to 128
    # lanes in-kernel
    idxD = jnp.asarray(idx_np.reshape(-1, 128))

    def buildD():
        def k(tab_ref, idx_ref, o_ref):
            t = jnp.broadcast_to(tab_ref[:].reshape(C, 1), (C, 128))
            o_ref[:] = jnp.take_along_axis(t, idx_ref[:], axis=0)
        return lambda s: pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((V * DEG // 128, 128),
                                              dtype),
        )(tab + s, idxD)
    try_form("D_tala_axis0", buildD)

    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
