#!/usr/bin/env python3
"""Generate the Fortran-77 binding layer from the C prototypes in
include/smpi/mpi.h (the approach the reference hand-writes across
src/smpi/bindings/smpi_f77*.cpp, ~2,000 LoC).

The gfortran ABI makes this mechanical: every argument is passed by
reference, all handles are MPI_Fint (our C handles are ints, so
translation is the identity), MPI_Status is ABI-identical to a
6-integer array, and symbols are lowercase with a trailing underscore.
So each wrapper simply dereferences scalars and forwards pointers.

Skipped (hand-written in smpi_shim.c or not expressible in F77):
functions taking function pointers, char* strings (hidden-length
convention), varargs, or argv.  Output: native/smpi_f77_gen.c,
#included at the end of native/smpi_shim.c and committed to the repo
(regenerate with: python tools/gen_f77.py).
"""

import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(ROOT, "include", "smpi", "mpi.h")
OUT = os.path.join(ROOT, "native", "smpi_f77_gen.c")

#: C types that are ints by construction: deref an MPI_Fint*
INT_LIKE = {
    "int", "MPI_Comm", "MPI_Datatype", "MPI_Op", "MPI_Request",
    "MPI_Group", "MPI_Info", "MPI_File", "MPI_Win", "MPI_Errhandler",
    "MPI_Message",
}
#: 64-bit scalars: deref the wider Fortran kind
WIDE = {"MPI_Aint", "MPI_Count", "MPI_Offset"}

#: symbols already hand-written in smpi_shim.c (kept there because
#: they need argc/argv, string, or status-shape special handling)
def handwritten():
    src = open(os.path.join(ROOT, "native", "smpi_shim.c")).read()
    return set(re.findall(r"^(?:void|double) (mpi_[a-z0-9_]+_)\(", src,
                          re.M))


def parse_protos(text):
    """Yield (name, [(type, is_ptr, is_array)]) for each
    `int MPI_X(...)` prototype."""
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    for m in re.finditer(r"\bint\s+(MPI_[A-Za-z0-9_]+)\s*\(([^;{]*)\)\s*;",
                         text):
        if "typedef" in text[max(0, m.start() - 40):m.start()]:
            continue                     # function TYPE, not a function
        name, argstr = m.group(1), " ".join(m.group(2).split())
        if not argstr or argstr == "void":
            yield name, []
            continue
        args = []
        ok = True
        depth = 0
        parts, cur = [], ""
        for ch in argstr:
            if ch == "," and depth == 0:
                parts.append(cur)
                cur = ""
                continue
            if ch in "([":
                depth += 1
            elif ch in ")]":
                depth -= 1
            cur += ch
        parts.append(cur)
        for p in parts:
            p = p.strip()
            if "(" in p or "..." in p:   # function pointer / varargs
                ok = False
                break
            p = re.sub(r"\bconst\b", "", p).strip()
            mm = re.match(r"([A-Za-z_][A-Za-z0-9_ ]*?)\s*(\*{0,3})\s*"
                          r"([A-Za-z_][A-Za-z0-9_]*)?\s*(\[\s*\]|\[\s*3\s*\])?$", p)
            if not mm:
                ok = False
                break
            ctype = mm.group(1).strip()
            ptr = len(mm.group(2) or "")
            arr = bool(mm.group(4))
            args.append((ctype, ptr, arr, mm.group(4) or ""))
        if ok:
            yield name, args


def wrapper(name, args):
    fname = name.lower() + "_"
    params, call = [], []
    for i, (ctype, ptr, arr, arrsfx) in enumerate(args):
        an = "a%d" % i
        if ctype == "char" or ctype.startswith("char"):
            return None                  # hidden-length convention
        if arr and arrsfx.strip("[] ") == "3":
            # int ranges[][3]
            params.append("MPI_Fint* %s" % an)
            call.append("(int(*)[3])%s" % an)
        elif arr and (ctype in INT_LIKE or ctype in WIDE):
            # `type name[]` decays to a pointer: forward it
            params.append("%s* %s" % (ctype, an))
            call.append(an)
        elif ptr == 0 and ctype in INT_LIKE:
            params.append("MPI_Fint* %s" % an)
            call.append("*%s" % an)
        elif ptr == 0 and ctype in WIDE:
            params.append("%s* %s" % (ctype, an))
            call.append("*%s" % an)
        elif ptr == 0 and ctype == "double":
            params.append("double* %s" % an)
            call.append("*%s" % an)
        elif ptr == 1 and (ctype in INT_LIKE or ctype in WIDE
                           or ctype == "double"):
            params.append("%s* %s" % (ctype, an))
            call.append(an)
        elif ptr == 1 and ctype == "MPI_Status":
            params.append("MPI_Fint* %s" % an)
            call.append("(MPI_Status*)%s" % an)
        elif ptr >= 1 and ctype == "void":
            params.append("void* %s" % an)
            call.append(an)
        else:
            return None
    sig = ", ".join(params + ["MPI_Fint* ierr"])
    body = "  *ierr = %s(%s);" % (name, ", ".join(call))
    return "void %s(%s) {\n%s\n}\n" % (fname, sig, body)


def main():
    text = open(HEADER).read()
    skip = handwritten()
    out = [
        "/* GENERATED by tools/gen_f77.py — do not edit by hand.",
        " * F77 wrappers derived from include/smpi/mpi.h prototypes",
        " * (role of reference src/smpi/bindings/smpi_f77*.cpp). */",
        "",
    ]
    n = 0
    seen = set()
    for name, args in parse_protos(text):
        fname = name.lower() + "_"
        if fname in skip or fname in seen:
            continue
        w = wrapper(name, args)
        if w is None:
            continue
        seen.add(fname)
        out.append(w)
        n += 1
    with open(OUT, "w") as fh:
        fh.write("\n".join(out))
    print("generated %d wrappers -> %s" % (n, OUT))
    return 0


if __name__ == "__main__":
    sys.exit(main())
