#!/usr/bin/env python3
"""smpirun CLI (reference src/smpi/smpirun.in): run an MPI C program
(source or shared object) on a simulated platform.

    smpirun.py [-map] -hostfile HF -platform P.xml -np N \
               [--cfg=...] [--log=...] program[.c|.so] [program args]

`-map` prints the rank->host map like the reference's SMPI_MAP output.
C sources are compiled on the fly through the same smpicc pipeline the
MPICH3 conformance sweeps use."""

import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv) -> int:
    show_map = False
    hostfile = None
    platform = None
    np = None
    passthrough = []   # --cfg=... / --log=... handed to the engine
    program = None
    prog_args = []

    i = 0
    while i < len(argv):
        a = argv[i]
        if program is not None:
            prog_args.append(a)
        elif a == "-map":
            show_map = True
        elif a == "-hostfile":
            i += 1
            hostfile = argv[i]
        elif a == "-platform":
            i += 1
            platform = argv[i]
        elif a == "-np":
            i += 1
            np = int(argv[i])
        elif a.startswith("--cfg=") or a.startswith("--log="):
            passthrough.append(a)
        else:
            program = a
        i += 1

    if program is None:
        print("smpirun: no program given", file=sys.stderr)
        return 1

    from simgrid_tpu.smpi import runtime
    from simgrid_tpu.smpi.c_api import compile_program, run_c_program

    hosts = None
    if hostfile:
        hosts = runtime.parse_hostfile(hostfile)
    if np is None:
        np = len(hosts) if hosts else 4

    if show_map and hosts:
        for r in range(np):
            print("[rank %d] -> %s" % (r, hosts[r % len(hosts)]))
        sys.stdout.flush()

    if program.endswith(".c"):
        so = os.path.join(tempfile.mkdtemp(prefix="smpirun-"),
                          os.path.basename(program)[:-2] + ".so")
        compile_program([program], so)
        program = so

    configs = tuple(a[len("--cfg="):] for a in passthrough
                    if a.startswith("--cfg="))
    logs = [a for a in passthrough if a.startswith("--log=")]
    if logs:
        from simgrid_tpu.utils import log as _xlog
        for spec in logs:
            _xlog.apply_control(spec[len("--log="):])

    _, codes = run_c_program(program, np_ranks=np, platform=platform,
                             hosts=hosts, configs=configs,
                             app_args=prog_args)
    return max(codes.values(), default=0)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
