#!/usr/bin/env python3
"""Aggregate the committed per-dir MPICH3 sweep JSONs into one summary
(bench_results/mpich3_summary.json) with pass counts and the names of
every non-passing test, so conformance claims are reproducible from
artifacts rather than commit messages."""

import glob
import json
import os
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BR = os.path.join(ROOT, "bench_results")


def main() -> int:
    summary = {"ts": time.time(), "dirs": {}}
    total_pass = total = 0
    for path in sorted(glob.glob(os.path.join(BR, "mpich3_*.json"))):
        name = os.path.basename(path)[len("mpich3_"):-len(".json")]
        if name == "summary":
            continue
        results = json.load(open(path))
        n_pass = sum(1 for v in results.values() if v == "PASS")
        summary["dirs"][name] = {
            "pass": n_pass,
            "total": len(results),
            "failing": {k: v for k, v in sorted(results.items())
                        if v != "PASS"},
        }
        total_pass += n_pass
        total += len(results)
    summary["total_pass"] = total_pass
    summary["total"] = total
    out = os.path.join(BR, "mpich3_summary.json")
    json.dump(summary, open(out, "w"), indent=1, sort_keys=True)
    print(f"{total_pass}/{total} across {len(summary['dirs'])} dirs "
          f"-> {out}")
    for name, d in sorted(summary["dirs"].items()):
        print(f"  {name:10s} {d['pass']}/{d['total']}"
              + (f"  ({', '.join(d['failing'])})" if d["failing"] else ""))
    return 0


if __name__ == "__main__":
    main()
