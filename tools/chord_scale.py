#!/usr/bin/env python3
"""BASELINE config #5 at scale: Chord with N peers (default 10,000)
and churn-heavy message traffic on the smpirun default fabric.

Usage: python tools/chord_scale.py [n_peers] [deadline]
Prints one summary line with wall time, simulated clock, lookup and
resolution counts, and peak RSS."""

import os
import resource
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from examples import chord
from simgrid_tpu import s4u
from simgrid_tpu.smpi.runtime import fabricate_platform


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    deadline = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    chord.ChordNode.POLL = 0.25        # coarser pump at scale
    fd, plat = tempfile.mkstemp(suffix=".xml")
    os.close(fd)
    fabricate_platform(min(n, 256), plat)

    t0 = time.perf_counter()
    e = s4u.Engine(["chord-scale"])
    e.load_platform(plat)
    stats = chord.deploy(e, n, deadline=deadline, lookup_period=20.0)
    built = time.perf_counter() - t0

    t0 = time.perf_counter()
    e.run()
    ran = time.perf_counter() - t0
    os.unlink(plat)

    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    print(f"chord-scale: {n} peers, clock={e.clock:.1f}s, "
          f"build {built:.1f}s + run {ran:.1f}s wall, "
          f"lookups={stats.get('lookups', 0)}, "
          f"resolved={stats.get('resolved', 0)}, "
          f"join_failures={stats.get('join_failures', 0)}, "
          f"peak RSS {rss:.2f} GB")


if __name__ == "__main__":
    main()
