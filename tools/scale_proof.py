#!/usr/bin/env python
"""Scale proof (BASELINE config #4): a 65k-host dragonfly with 100k+
concurrent flows, solved by the JAX backend without crashing.

Drives the model layer directly (network_model.communicate per flow —
the same calls the kernel's comm activities make) because the flow
count, not actor count, is the scaling axis under test: route
resolution over the dragonfly topology, LMM system construction, the
vectorized solve, and a few time advances.

Usage: python tools/scale_proof.py [--flows 100000]
           [--backend jax] [--out SCALE_PROOF.md]
"""

import argparse
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_platform(path: str, n_hosts: int) -> str:
    # dragonfly hosts = groups * chassis * routers * nodes;
    # minimal routing needs routers-per-chassis >= groups:
    # 16 * 4 * 16 * 64 = 65536
    assert n_hosts == 65536, "layout below is sized for 65536 hosts"
    xml = """<?xml version='1.0'?>
<platform version="4.1">
  <zone id="world" routing="Full">
    <cluster id="dfly" prefix="node-" radical="0-65535" suffix=""
             speed="1Gf" bw="125MBps" lat="50us" topology="DRAGONFLY"
             topo_parameters="16,3;4,2;16,2;64"/>
  </zone>
</platform>
"""
    with open(path, "w") as f:
        f.write(xml)
    return path


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--flows", type=int, default=100_000)
    ap.add_argument("--backend", default="jax")
    ap.add_argument("--layout", default="auto")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", os.environ.get(
        "SCALE_PLATFORM", "cpu"))
    import numpy as np

    from simgrid_tpu import s4u

    lines = []

    def log(msg):
        print(msg, flush=True)
        lines.append(msg)

    t0 = time.perf_counter()
    platform = build_platform("/tmp/dragonfly65k.xml", 65536)
    e = s4u.Engine(["scale", f"--cfg=lmm/backend:{args.backend}",
                    f"--cfg=lmm/layout:{args.layout}",
                    "--cfg=network/maxmin-selective-update:no",
                    "--cfg=network/optim:Full"])
    e.load_platform(platform)
    n_hosts = e.get_host_count()
    log(f"platform: {n_hosts} hosts, {len(e.get_all_links())} links, "
        f"parsed+built in {time.perf_counter() - t0:.1f}s")

    hosts = e.get_all_hosts()
    rng = np.random.default_rng(42)
    pairs = rng.integers(0, n_hosts, size=(args.flows, 2))

    t0 = time.perf_counter()
    model = e.pimpl.network_model
    actions = []
    for k in range(args.flows):
        src, dst = int(pairs[k, 0]), int(pairs[k, 1])
        if src == dst:
            dst = (dst + 1) % n_hosts
        actions.append(model.communicate(hosts[src], hosts[dst], 1e6, -1.0))
    t_routes = time.perf_counter() - t0
    n_cnst = sum(1 for _ in model.system.active_constraint_set)
    log(f"{args.flows} flows routed + expanded in {t_routes:.1f}s "
        f"({n_cnst} active link constraints)")

    t0 = time.perf_counter()
    model.system.solve()
    t_solve1 = time.perf_counter() - t0
    log(f"first solve ({args.backend}): {t_solve1 * 1e3:.0f} ms")
    # Kernel time advances: flows pay their (hop-dependent) latencies
    # over the first few events, then hold real bandwidth.
    t0 = time.perf_counter()
    advances = 0
    opened = False
    for _ in range(10):
        delta = e.pimpl.surf_solve(-1.0)
        if delta < 0:
            break
        advances += 1
        rates = [a.variable.value for a in actions[:5] if a.variable]
        if rates and all(r > 0 for r in rates):
            log(f"flows hold bandwidth after {advances} advances: "
                f"{[f'{r:.3g}' for r in rates]}")
            opened = True
            break
    assert opened, "sampled flows never received bandwidth"
    log(f"{advances} time advances in {time.perf_counter() - t0:.1f}s, "
        f"clock={e.clock:.4f}")
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    log(f"peak RSS: {peak:.2f} GB")
    log("RESULT: OK")

    if args.out:
        with open(args.out, "a") as f:
            f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
