#!/usr/bin/env python
"""lint_all — the one-stop static gate: simlint + proglint + the
opstats counter registry, one merged exit code.

Usage::

    python tools/lint_all.py [--json]

Runs, in order:

1. **simlint** — the AST invariant rules over the audited source
   paths, against ``tools/simlint_baseline.json``;
2. **proglint** — the compiled-program contract rules over every
   registered jitted kernel program, against
   ``tools/proglint_baseline.json`` (expected steady state: empty);
3. **opstats registry** — the counter table in
   ``ops/opstats.py``'s docstring must parse and carry the core
   counters every tool dashboards on.

Exit 0 only when all three are clean; 1 when any has findings; 2 on
operational errors.  ``check_determinism.py --quick`` runs the same
bundle (via :func:`collect_problems`), so CI and the command line
can't drift apart.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

#: counters the bench/serve tooling hard-depends on — their
#: disappearance from the registry is a lint failure even though the
#: docstring would still parse
CORE_COUNTERS = ("dispatches", "fetches", "fetched_bytes",
                 "blocking_fetches", "host_block_ms", "retraces",
                 "donated_buffers", "plan_cache_hits",
                 "plan_cache_misses")


def simlint_problems(root: str) -> List[str]:
    from simgrid_tpu import analysis

    findings = analysis.lint_paths(root, ("simgrid_tpu", "tools"))
    baseline_path = os.path.join(root, "tools",
                                 "simlint_baseline.json")
    baseline = None
    if os.path.exists(baseline_path):
        baseline = analysis.load_baseline(baseline_path)
    new, stale = analysis.apply_baseline(findings, baseline)
    out = [f"simlint: {f.path}:{f.line}: [{f.rule}] {f.message}"
           for f in new]
    out += [f"simlint: {e['path']}: stale baseline entry "
            f"[{e['rule']}] {e['snippet']!r}" for e in stale]
    return out


def proglint_problems(root: str) -> List[str]:
    from simgrid_tpu import analysis
    from simgrid_tpu.analysis.prog import lint_programs

    findings = lint_programs()
    baseline_path = os.path.join(root, "tools",
                                 "proglint_baseline.json")
    baseline = None
    if os.path.exists(baseline_path):
        baseline = analysis.load_baseline(baseline_path)
    new, stale = analysis.apply_baseline(findings, baseline)
    out = [f"proglint: {f.path}: [{f.rule}] {f.message}"
           for f in new]
    out += [f"proglint: {e['path']}: stale baseline entry "
            f"[{e['rule']}] {e['snippet']!r}" for e in stale]
    return out


def opstats_registry_problems(root: str) -> List[str]:
    from simgrid_tpu.analysis.rules.opstats_discipline import \
        declared_counters
    from simgrid_tpu.ops import opstats

    doc = opstats.__doc__ or ""
    exact, wild = declared_counters(doc)
    out: List[str] = []
    if not exact:
        out.append("opstats: counter registry parsed EMPTY from the "
                   "module docstring — the table format drifted")
        return out
    for name in CORE_COUNTERS:
        if name not in exact:
            out.append(f"opstats: core counter `{name}` missing from "
                       f"the registry docstring")
    if not wild:
        out.append("opstats: no wildcard counter families declared "
                   "(expected e.g. ``lane_quarantined_<cause>``)")
    return out


def collect_problems(root: str = REPO_ROOT) -> List[str]:
    """Every problem from all three gates (empty = clean); the hook
    ``check_determinism.py --quick`` calls."""
    problems = simlint_problems(root)
    problems += proglint_problems(root)
    problems += opstats_registry_problems(root)
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint_all", description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--root", default=REPO_ROOT,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    try:
        problems = collect_problems(args.root)
    except Exception as e:  # noqa: BLE001 — operational failure
        print(f"lint_all: gate crashed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({"problems": problems,
                          "clean": not problems}, indent=1))
    else:
        for p in problems:
            print(p)
        print(f"lint_all: {len(problems)} problem(s) "
              f"(simlint + proglint + opstats registry)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
