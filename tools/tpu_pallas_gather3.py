#!/usr/bin/env python3
"""tpu.dynamic_gather probe: exact-shape take_along_axis forms.

Requirement from the Mosaic lowering rule: x.shape == idx.shape, 2D,
gather along axis 0 or 1.  To gather E=524288 elements from a [C]
table: x = broadcast_to(tab, (R, C)) with idx [R, C] (R*C == E).
Probes table widths C' in {128, 2048, 16384} at constant E by varying
R, plus in-kernel cumulative ops needed for segment reductions.
Appends to bench_results/tpu_opcost.jsonl."""
import functools
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "bench_results", "tpu_opcost.jsonl")


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    dev = jax.devices()[0]
    dtype = jnp.float32
    rec = {"platform": dev.platform, "probe": "dynamic_gather",
           "ts": round(time.time(), 1)}
    C, E = 16384, 524288
    rng = np.random.default_rng(7)
    tab_np = rng.uniform(1, 2, C).astype(np.float32)
    idx_np = rng.integers(0, C, E).astype(np.int32)
    tab = jnp.asarray(tab_np)
    sync = 66.0

    def timed(f, K=16):
        s = jnp.asarray(0.0, dtype)
        float(np.asarray(f(s).ravel()[0]))
        t0 = time.perf_counter()
        s = jnp.asarray(0.0, dtype)
        for _ in range(K):
            s = f(s).ravel()[0] * 1e-30
        float(np.asarray(s))
        return round((time.perf_counter() - t0 - sync / 1e3) / K * 1e3, 3)

    def try_form(name, fn, want):
        try:
            f = jax.jit(fn)
            got = np.asarray(f(jnp.asarray(0.0, dtype)))
            if not np.allclose(got.ravel(), want.ravel()):
                rec[name] = "WRONG VALUES"
            else:
                rec[name] = timed(f)
        except Exception as exc:  # noqa: BLE001
            rec[name] = f"{type(exc).__name__}: {exc}"[:200]
        print(f"  {name}: {rec[name]}")

    # gather at reduced table width Cw: indices taken mod Cw so the
    # semantic check still holds
    for Cw in (128, 2048, 16384):
        R = E // Cw
        idx_w = (idx_np % Cw).reshape(R, Cw)
        idxj = jnp.asarray(idx_w)
        want = tab_np[:Cw][idx_w]

        def k(tab_ref, idx_ref, o_ref, Cw=Cw, R=R):
            x = jnp.broadcast_to(tab_ref[:].reshape(1, Cw), (R, Cw))
            o_ref[:] = jnp.take_along_axis(x, idx_ref[:], axis=1)

        def fn(s, Cw=Cw, R=R, idxj=idxj, k=k):
            return pl.pallas_call(
                k, out_shape=jax.ShapeDtypeStruct((R, Cw), dtype),
            )(tab[:Cw] + s, idxj)
        try_form(f"dg_w{Cw}", fn, want)

    # XLA equivalent of the same op for comparison (take_along_axis
    # outside pallas)
    R = E // 16384
    idxj = jnp.asarray((idx_np % 16384).reshape(R, 16384))
    want = tab_np[(idx_np % 16384).reshape(R, 16384)]
    try_form("xla_tala_w16384",
             lambda s: jnp.take_along_axis(
                 jnp.broadcast_to((tab + s).reshape(1, 16384),
                                  (R, 16384)), idxj, axis=1), want)

    # in-kernel cumsum along lanes (needed for segment sums)
    w_np = rng.uniform(0.5, 1.5, (32, 16384)).astype(np.float32)
    wj = jnp.asarray(w_np)

    def ck(w_ref, o_ref):
        o_ref[:] = jnp.cumsum(w_ref[:], axis=1)

    try_form("pallas_cumsum_axis1",
             lambda s: pl.pallas_call(
                 ck, out_shape=jax.ShapeDtypeStruct((32, 16384), dtype),
             )(wj + s), np.cumsum(w_np, axis=1))

    # in-kernel iota-compare one-hot matmul segment-sum:
    # sum_e w[e] * (idx[e] == c)  via [Rb, C] blocks on the MXU
    idx2 = jnp.asarray(idx_np.reshape(-1, 128))
    w2 = jnp.asarray(rng.uniform(0.5, 1.5, E).astype(np.float32)
                     .reshape(-1, 128))
    want_seg = np.zeros(C, np.float32)
    np.add.at(want_seg, idx_np, np.asarray(w2).ravel())

    def mk(idx_ref, w_ref, o_ref):
        # process in row-blocks of 256x128 elements -> one-hot [32768,
        # C] is too big; instead loop over 16 chunks of 2048x128? keep
        # simple: one-hot per 8-row chunk (1024 elems) against C lanes
        def body(i, acc):
            ii = idx_ref[pl.ds(i * 8, 8), :].reshape(1024)
            ww = w_ref[pl.ds(i * 8, 8), :].reshape(1024)
            oh = (ii[:, None] == jax.lax.broadcasted_iota(
                jnp.int32, (1024, C), 1)).astype(dtype)
            return acc + jnp.dot(ww.reshape(1, 1024), oh,
                                 preferred_element_type=dtype)
        acc = jax.lax.fori_loop(0, E // 1024,
                                functools.partial(body),
                                jnp.zeros((1, C), dtype))
        o_ref[:] = acc

    try_form("pallas_onehot_segsum",
             lambda s: pl.pallas_call(
                 mk, out_shape=jax.ShapeDtypeStruct((1, C), dtype),
             )(idx2, w2 + s), want_seg.reshape(1, C))

    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
