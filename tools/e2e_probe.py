#!/usr/bin/env python3
"""Per-solve cost on the REAL config-#4 system (65k-host dragonfly,
alltoall flow set): native C++ list solver vs JAX backend on the
current platform (set JAX_PLATFORMS / SCALE_PLATFORM).

Builds the platform once, posts R*(R-1) alltoall flows from R ranks
spread over the hosts, flattens the LMM system, then times:
  - native C++ solve (ops.lmm_native solve path on the flattened copy)
  - JAX solve_arrays (the production device path), warm, median of 3

Prints a JSON line; append with --out.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=320)
    ap.add_argument("--platform", default=None,
                    help="jax platform override (cpu/tpu)")
    ap.add_argument("--skip-native", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from simgrid_tpu import s4u
    from simgrid_tpu.ops import lmm_jax, lmm_native
    from simgrid_tpu.utils.config import config
    from tools.scale_proof import build_platform

    rec = {}
    t0 = time.perf_counter()
    platform = build_platform("/tmp/dragonfly65k.xml", 65536)
    e = s4u.Engine(["e2e", "--cfg=lmm/backend:list",
                    "--cfg=network/maxmin-selective-update:no",
                    "--cfg=network/optim:Full"])
    e.load_platform(platform)
    hosts = e.get_all_hosts()
    n_hosts = len(hosts)
    rec["build_s"] = round(time.perf_counter() - t0, 1)

    # R ranks spread evenly; alltoall: every ordered pair, 1 MB
    R = args.ranks
    stride = n_hosts // R
    rank_hosts = [hosts[i * stride] for i in range(R)]
    model = e.pimpl.network_model
    t0 = time.perf_counter()
    actions = []
    for i in range(R):
        for j in range(R):
            if i != j:
                actions.append(model.communicate(
                    rank_hosts[i], rank_hosts[j], 1e6, -1.0))
    rec["flows"] = len(actions)
    rec["route_s"] = round(time.perf_counter() - t0, 1)

    # advance past the latency phase so every flow's variable is live
    t0 = time.perf_counter()
    for _ in range(200):
        n_live = sum(1 for a in actions
                     if a.variable is not None
                     and a.variable.sharing_penalty > 0)
        if n_live == len(actions):
            break
        e.pimpl.surf_solve(-1.0)
    rec["latency_adv_s"] = round(time.perf_counter() - t0, 1)

    system = model.system
    flat = lmm_jax.flatten(list(system.active_constraint_set))
    arrays, _ = flat
    rec.update(n_cnst=arrays.n_cnst, n_var=arrays.n_var,
               n_elem=arrays.n_elem)
    print(json.dumps(rec), flush=True)

    eps = config["maxmin/precision"]
    if not args.skip_native and lmm_native.available():
        t0 = time.perf_counter()
        vals = lmm_native._solve_flat(arrays, eps)
        rec["native_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        rec["native_val0"] = float(vals[0][0])
        print(f"native: {rec['native_ms']} ms", flush=True)

    import jax
    dtype = np.float32 if jax.devices()[0].platform != "cpu" \
        else np.float64
    arrays_t = lmm_jax.LmmArrays(
        arrays.e_var, arrays.e_cnst, arrays.e_w.astype(dtype),
        arrays.c_bound.astype(dtype), arrays.c_fatpipe,
        arrays.v_penalty.astype(dtype), arrays.v_bound.astype(dtype),
        arrays.n_elem, arrays.n_cnst, arrays.n_var)
    rec["jax_platform"] = jax.devices()[0].platform
    t0 = time.perf_counter()
    v, r, u, rounds = lmm_jax.solve_arrays(arrays_t, eps)
    rec["jax_first_s"] = round(time.perf_counter() - t0, 1)
    rec["jax_rounds"] = int(rounds)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        v, r, u, rounds = lmm_jax.solve_arrays(arrays_t, eps)
        times.append(time.perf_counter() - t0)
    rec["jax_warm_ms"] = round(float(np.median(times)) * 1e3, 1)
    # cold-path cost (fresh arrays each solve: ELL re-pack + re-upload)
    times = []
    for _ in range(2):
        arrays_c = lmm_jax.LmmArrays(
            arrays_t.e_var.copy(), arrays_t.e_cnst.copy(),
            arrays_t.e_w.copy(), arrays_t.c_bound.copy(),
            arrays_t.c_fatpipe.copy(), arrays_t.v_penalty.copy(),
            arrays_t.v_bound.copy(), arrays.n_elem, arrays.n_cnst,
            arrays.n_var)
        t0 = time.perf_counter()
        v, r, u, rounds = lmm_jax.solve_arrays(arrays_c, eps)
        times.append(time.perf_counter() - t0)
    rec["jax_cold_ms"] = round(float(np.median(times)) * 1e3, 1)
    rec["jax_val0"] = float(v[0])
    print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "a") as fh:
            fh.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
