#!/usr/bin/env python3
"""Prepared TPU experiment matrix: run the moment the chip answers.

One experiment per subprocess (a wedged chip costs one experiment, and
killing a process mid-compile wedges the chip — so each child gets a
timeout ABOVE worst-case compile time and is never killed early unless
it exceeds it).  Matrix: layout {ell, coo} x unroll {on, off} x class
{2k, 20k, 100k}, 3 reps each, preceded by a warm-up solve in the same
process to populate the persistent compile cache.

Each result is appended to bench_results/tpu_experiments.jsonl
immediately, so partial sweeps survive.

Usage:
  python tools/tpu_experiments.py            # full matrix (probe first)
  python tools/tpu_experiments.py --one ell:on:2000   # single experiment
"""
import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "bench_results", "tpu_experiments.jsonl")

CLASSES = {
    2000: dict(n_c=2000, n_v=2000, deg=3, seed=1),
    20000: dict(n_c=20000, n_v=20000, deg=3, seed=2),
    100000: dict(n_c=16384, n_v=100_000, deg=4, seed=42),
}

CHILD_SRC = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, {root!r})
from simgrid_tpu.utils.config import config
config["lmm/layout"] = {layout!r}
config["lmm/unroll"] = {unroll!r}
import jax
dev = jax.devices()[0]
sys.path.insert(0, {root!r})
from bench import build_arrays
from simgrid_tpu.ops.lmm_jax import solve_arrays
on_tpu = dev.platform != "cpu"
dtype = np.float32 if on_tpu else np.float64
eps = 1e-5 if on_tpu else 1e-9
arrays = build_arrays(np.random.default_rng({seed}), {n_c}, {n_v}, {deg},
                      dtype)
t0 = time.time()
_, _, _, rounds = solve_arrays(arrays, eps, parallel_rounds=True)
compile_s = time.time() - t0
times = []
for _ in range(3):
    t0 = time.perf_counter()
    solve_arrays(arrays, eps, parallel_rounds=True)
    times.append(time.perf_counter() - t0)
print(json.dumps({{"platform": dev.platform,
                   "ms": round(float(np.median(times)) * 1e3, 2),
                   "first_s": round(compile_s, 2),
                   "rounds": int(rounds)}}))
"""


def run_one(layout: str, unroll: str, cls: int, timeout: float) -> dict:
    p = CLASSES[cls]
    src = CHILD_SRC.format(root=ROOT, layout=layout, unroll=unroll,
                           seed=p["seed"], n_c=p["n_c"], n_v=p["n_v"],
                           deg=p["deg"])
    rec = {"layout": layout, "unroll": unroll, "cls": cls,
           "ts": round(time.time(), 1)}
    t0 = time.time()
    try:
        proc = subprocess.run([sys.executable, "-c", src],
                              capture_output=True, text=True,
                              timeout=timeout, cwd=ROOT)
        if proc.returncode == 0 and proc.stdout.strip():
            rec.update(json.loads(proc.stdout.strip().splitlines()[-1]))
        else:
            rec["error"] = (proc.stderr or "")[-400:]
    except subprocess.TimeoutExpired:
        rec["error"] = f"timeout after {timeout:.0f}s"
    rec["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)
    return rec


def probe_alive(timeout: float = 120.0) -> bool:
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from chip_watch import probe
    rec = probe(timeout)
    print(f"[probe] {rec}", file=sys.stderr, flush=True)
    return bool(rec.get("alive"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--one", help="layout:unroll:class, e.g. ell:on:2000")
    ap.add_argument("--no-probe", action="store_true")
    args = ap.parse_args()
    if args.one:
        layout, unroll, cls = args.one.split(":")
        run_one(layout, unroll, int(cls), timeout=3600)
        return 0
    if not args.no_probe and not probe_alive():
        print("[tpu_experiments] chip not answering; aborting",
              file=sys.stderr)
        return 1
    # Small classes first (cheap compiles warm the cache), unroll=off
    # first (unroll compiles scale with the factor).  100k COO is the
    # known-pathological gather-in-loop case: run it LAST so a wedge
    # costs nothing else, with the biggest timeout.
    matrix = [("ell", "off", 2000), ("ell", "on", 2000),
              ("coo", "off", 2000), ("coo", "on", 2000),
              ("ell", "off", 20000), ("ell", "on", 20000),
              ("coo", "off", 20000),
              ("ell", "off", 100000), ("ell", "on", 100000),
              ("coo", "off", 100000)]
    for layout, unroll, cls in matrix:
        timeout = 900 if cls <= 20000 else 3600
        rec = run_one(layout, unroll, cls, timeout)
        if "error" in rec and "timeout" in rec.get("error", ""):
            # a timeout usually means the chip is wedged: re-probe
            # before burning the rest of the matrix
            if not probe_alive():
                print("[tpu_experiments] chip wedged mid-matrix; stop",
                      file=sys.stderr)
                return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
