#!/usr/bin/env python
"""tesh: the golden-output testing shell (reference tools/tesh/tesh.py).

Runs the commands of a ``.tesh`` file and diffs every stdout line
against the ``>``-prefixed expectations. Supported syntax:

    $ cmd                run cmd, diff its output
    & cmd                run cmd in background (not diffed)
    > line               expected output line of the preceding command
    < line               stdin line fed to the next command
    ! timeout N          per-command timeout in seconds
    ! expect return N    expected exit code of the next command
    ! output sort        sort actual+expected output before diffing
    ! output ignore      discard the next command's output
    ! setenv K=V         environment for subsequent commands
    p message            progress message
    # comment

Variable substitution: ``${name:=default}`` and ``${name}`` from the
environment (bindir/srcdir settable via --cfg bindir=... srcdir=...).
Exit code 0 when every command matched."""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
from typing import List, Optional


class Cmd:
    def __init__(self):
        self.args: Optional[str] = None
        self.input: List[str] = []
        self.expected: List[str] = []
        self.timeout: Optional[float] = None
        self.expect_return = 0
        self.sort_output = False
        self.ignore_output = False
        self.background = False


def _substitute(line: str, env: dict) -> str:
    def repl(m):
        name, default = m.group(1), m.group(2)
        return env.get(name, default if default is not None else "")
    return re.sub(r"\$\{(\w+)(?::=([^}]*))?\}", repl, line)


def run_cmd(cmd: Cmd, env: dict, verbose: bool) -> bool:
    args = _substitute(cmd.args, env)
    if verbose:
        print(f"[tesh] $ {args}", file=sys.stderr)
    try:
        # the reference tesh merges stdout+stderr (log appenders write
        # to stderr; the oracles pin those lines)
        proc = subprocess.run(
            args, shell=True, text=True, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            input="\n".join(cmd.input) + ("\n" if cmd.input else ""),
            timeout=cmd.timeout, env={**os.environ, **env})
    except subprocess.TimeoutExpired:
        print(f"Test suite timed out: {args}", file=sys.stderr)
        return False
    if proc.returncode != cmd.expect_return:
        print(f"Command returned {proc.returncode}, expected "
              f"{cmd.expect_return}: {args}", file=sys.stderr)
        sys.stderr.write(proc.stdout)
        return False
    if cmd.ignore_output:
        return True
    actual = [l for l in proc.stdout.splitlines()]
    expected = list(cmd.expected)
    if cmd.sort_output:
        if cmd.sort_output is True:
            actual, expected = sorted(actual), sorted(expected)
        else:
            n = cmd.sort_output
            actual = sorted(actual, key=lambda l: l[:n])
            expected = sorted(expected, key=lambda l: l[:n])
    if actual != expected:
        print(f"Output mismatch for: {args}", file=sys.stderr)
        import difflib
        for line in difflib.unified_diff(expected, actual,
                                         "expected", "actual",
                                         lineterm=""):
            print(line, file=sys.stderr)
        return False
    return True


def run_tesh(path: str, env: dict, verbose: bool = False) -> bool:
    cmds: List[Cmd] = []
    current = Cmd()
    pending_input: List[str] = []

    with open(path) as f:
        for raw in f:
            line = raw.rstrip("\n")
            if not line or line.startswith("#"):
                continue
            tag, rest = line[:1], line[2:] if len(line) > 2 else ""
            if tag == "$" or tag == "&":
                if current.args is not None:
                    cmds.append(current)
                    current = Cmd()
                current.args = line[1:].strip()
                current.background = tag == "&"
                current.input = pending_input
                pending_input = []
            elif tag == ">":
                current.expected.append(rest)
            elif tag == "<":
                pending_input.append(rest)
            elif tag == "!":
                # Directives configure the NEXT command: close the
                # previous one first.
                if current.args is not None:
                    cmds.append(current)
                    current = Cmd()
                directive = line[1:].strip()
                if directive.startswith("timeout"):
                    current.timeout = float(directive.split()[1])
                elif directive.startswith("expect return"):
                    current.expect_return = int(directive.split()[2])
                elif directive.startswith("output sort"):
                    # "output sort N" compares only the first N chars
                    # (stable), the reference's timestamp-prefix sort
                    rest_d = directive[len("output sort"):].strip()
                    current.sort_output = int(rest_d) if rest_d else True
                elif directive == "output ignore":
                    current.ignore_output = True
                elif directive.startswith("setenv"):
                    key, _, value = directive[len("setenv"):].strip() \
                        .partition("=")
                    env[key] = _substitute(value, env)
                else:
                    print(f"[tesh] unknown directive: {directive}",
                          file=sys.stderr)
            elif tag == "p":
                print(f"[tesh] {line[1:].strip()}", file=sys.stderr)
    if current.args is not None:
        cmds.append(current)

    ok = True
    background: List[subprocess.Popen] = []
    try:
        for cmd in cmds:
            if cmd.background:
                background.append(subprocess.Popen(
                    _substitute(cmd.args, env), shell=True))
                continue
            if not run_cmd(cmd, env, verbose):
                ok = False
                break
    finally:
        # Background commands die with the file (reference tesh kills
        # them at end-of-file).
        for proc in background:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("tesh_file")
    ap.add_argument("--cfg", action="append", default=[],
                    help="variable definitions name=value")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    env = dict(os.environ)
    for cfg in args.cfg:
        key, _, value = cfg.partition("=")
        env[key] = value
    ok = run_tesh(args.tesh_file, env, args.verbose)
    print("[tesh] " + ("OK" if ok else "FAILED"), file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
