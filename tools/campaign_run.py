#!/usr/bin/env python3
"""Run a batched multi-replica scenario campaign from the command line.

Builds ONE pure-drain scenario — either a synthetic maxmin-bench-style
system (default) or a seeded fat-tree drain captured from a real engine
(``--platform fat-tree``, exercising the whole platform/routing stack
and ``NetworkCm02Model.capture_drain_scenario``) — then drains a fleet
of N what-if replicas (mixed fault seeds + parameter sweeps) through
the batched executor (ops.lmm_batch via parallel.campaign) and prints
one JSON summary line: per-replica completion stats, fleet dispatch /
upload counters, and an optional solo spot-check (bit-identity of a
sampled replica against its solo run).

Examples::

    tools/campaign_run.py --replicas 64 --batch 64 --faults 0.5
    tools/campaign_run.py --platform fat-tree --flows 300 --replicas 16
    tools/campaign_run.py --replicas 8 --batch 8 --check 3 --out rows.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def force_host_device_count(n: int) -> None:
    """Pin the CPU backend's forced device count BEFORE jax initializes
    its backends: once a backend exists the flag is silently ignored,
    so every stage must route through here first — not just the
    ``--mesh`` path.  Pinning it unconditionally (n=1 included) also
    keeps the plan-cache artifact digest (which includes the device
    count) identical between cold and warm invocations."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{max(int(n), 1)}").strip()


def build_synthetic(args):
    import numpy as np
    from bench import build_arrays
    rng = np.random.default_rng(args.seed)
    arrays = build_arrays(rng, args.n_c, args.n_v, args.deg, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), args.n_v)
    return dict(e_var=arrays.e_var[:E], e_cnst=arrays.e_cnst[:E],
                e_w=arrays.e_w[:E], c_bound=arrays.c_bound[:args.n_c],
                sizes=sizes), {"platform": "synthetic",
                               "n_c": args.n_c, "n_v": args.n_v}


def build_fat_tree(args):
    """A seeded random-pair drain on the 64-host fat tree, captured
    from a live engine once every flow is past its latency phase."""
    import numpy as np
    from simgrid_tpu import s4u

    xml = """<?xml version='1.0'?>
<platform version="4.1">
  <zone id="world" routing="Full">
    <cluster id="ft" prefix="node-" radical="0-63" suffix=""
             speed="1Gf" bw="125MBps" lat="50us" topology="FAT_TREE"
             topo_parameters="2;8,8;1,2;1,1"/>
  </zone>
</platform>
"""
    import tempfile
    s4u.Engine._reset()
    e = s4u.Engine(["campaign", "--cfg=lmm/backend:list",
                    "--cfg=network/maxmin-selective-update:no",
                    "--cfg=network/optim:Full",
                    "--cfg=drain/fastpath:off"])
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "ft64.xml")
        with open(path, "w") as fh:
            fh.write(xml)
        e.load_platform(path)
    hosts = e.get_all_hosts()
    model = e.pimpl.network_model
    rng = np.random.default_rng(args.seed)
    pairs = rng.integers(0, len(hosts), size=(args.flows, 2))
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), args.flows)
    actions = []
    for k in range(args.flows):
        src, dst = int(pairs[k, 0]), int(pairs[k, 1])
        if src == dst:
            dst = (dst + 1) % len(hosts)
        actions.append(model.communicate(hosts[src], hosts[dst],
                                         float(sizes[k]), -1.0))
    snap = None
    for _ in range(200):
        # reap finished latency-phase stragglers: an unreaped done
        # action keeps a live variable that is not a started flow,
        # which the pure-drain preconditions (correctly) reject
        while True:
            done = model.extract_done_action()
            if done is None:
                break
            done.unref()
        if model.latency_phase_count == 0 \
                and len(model.started_action_set):
            snap = model.capture_drain_scenario()
            if snap is not None:
                break
        e.pimpl.surf_solve(-1.0)
    s4u.Engine._reset()
    if snap is None:
        raise SystemExit("fat-tree scenario never reached a pure "
                         "drain (latency phase still pending)")
    snap.pop("slot_action", None)
    return snap, {"platform": "fat-tree64", "flows": args.flows}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--platform", choices=["synthetic", "fat-tree"],
                    default="synthetic")
    ap.add_argument("--n_c", type=int, default=96)
    ap.add_argument("--n_v", type=int, default=400)
    ap.add_argument("--deg", type=int, default=3)
    ap.add_argument("--flows", type=int, default=300,
                    help="fat-tree platform: number of drain flows")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--replicas", type=int, default=16)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--superstep", type=int, default=8)
    ap.add_argument("--pipeline", type=int, default=0,
                    help="speculative fleet supersteps kept in "
                         "flight (bit-identical results; see "
                         "ops.lmm_drain)")
    ap.add_argument("--mesh", type=int, default=0,
                    help="shard each fleet's replica axis over this "
                         "many devices (NamedSharding batch axis, "
                         "bit-identical results; 0 = single-device "
                         "vmap).  On CPU the device count is forced "
                         "via XLA_FLAGS="
                         "--xla_force_host_platform_device_count)")
    ap.add_argument("--faults", type=float, default=0.5,
                    help="fraction of replicas with a fault dimension "
                    "(seeded MTBF/MTTR link degradation)")
    ap.add_argument("--fault-mode", choices=["on", "static", "off"],
                    default=None,
                    help="how fault schedules are realized: on = "
                    "device event tapes (links flip mid-drain at the "
                    "exact seeded dates), static = folded "
                    "mean-availability multipliers, off = ignored "
                    "(default: the faults/tape config flag)")
    ap.add_argument("--mtbf", type=float, default=400.0)
    ap.add_argument("--mttr", type=float, default=50.0)
    ap.add_argument("--horizon", type=float, default=600.0)
    ap.add_argument("--check", type=int, default=-1,
                    help="replica index to spot-check against a solo "
                    "run (-1: skip)")
    ap.add_argument("--plan-cache", default=None, metavar="DIR",
                    help="route fleet programs through an AOT plan "
                         "cache rooted at DIR (serving.plancache): "
                         "repeat invocations deserialize compiled "
                         "executables instead of re-tracing")
    ap.add_argument("--out", default=None,
                    help="append the summary row to this jsonl file")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU JAX backend")
    args = ap.parse_args()

    # must land before jax initializes its backends for EVERY stage
    # (the forced host-platform device count only affects the CPU
    # platform, so it is harmless on accelerator runs)
    force_host_device_count(args.mesh)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    plan_cache = None
    if args.plan_cache:
        from simgrid_tpu.serving.plancache import PlanCache
        plan_cache = PlanCache(args.plan_cache)

    base, meta = (build_fat_tree(args) if args.platform == "fat-tree"
                  else build_synthetic(args))
    n_fault = int(round(args.replicas * args.faults))
    specs = [ScenarioSpec(seed=s,
                          bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=args.mtbf if s < n_fault else None,
                          fault_mttr=args.mttr,
                          fault_horizon=args.horizon)
             for s in range(args.replicas)]
    campaign = Campaign(specs=specs, superstep=args.superstep,
                        pipeline=args.pipeline,
                        mesh=args.mesh or None,
                        fault_mode=args.fault_mode,
                        plan_cache=plan_cache, **base)

    t0 = time.perf_counter()
    results, stats = campaign.run_scoped(batch=args.batch,
                                         stage="campaign_run")
    wall = time.perf_counter() - t0

    row = dict(meta, replicas=args.replicas, batch=args.batch,
               superstep=args.superstep, pipeline=args.pipeline,
               mesh=args.mesh, fault_replicas=n_fault,
               wall_ms=round(wall * 1e3, 1),
               dispatches=int(stats.get("dispatches", 0)),
               dispatches_per_replica=round(
                   stats.get("dispatches", 0) / args.replicas, 3),
               upload_bytes=int(stats.get("uploaded_bytes_full", 0)
                                + stats.get("uploaded_bytes_delta", 0)),
               demux_fetches=int(stats.get("demux_fetches", 0)),
               sharded_upload_bytes=int(
                   stats.get("sharded_upload_bytes", 0)),
               replicated_upload_bytes=int(
                   stats.get("replicated_upload_bytes", 0)),
               events=sum(len(r.events) for r in results),
               errors=[r.spec.label for r in results if r.error],
               clocks=[round(r.t, 6) for r in results[:8]],
               fault_mode=campaign.fault_mode,
               fault_tape_slots=int(stats.get("fault_tape_slots", 0)),
               fault_tape_events=int(
                   stats.get("fault_tape_events", 0)),
               fault_replays=int(stats.get("fault_replays", 0)),
               collective_tape_slots=int(
                   stats.get("collective_tape_slots", 0)),
               collective_tape_fires=int(
                   stats.get("collective_tape_fires", 0)),
               collective_replays=int(
                   stats.get("collective_replays", 0)),
               lanes_admitted=int(stats.get("lanes_admitted", 0)),
               solver_fallbacks=int(
                   stats.get("solver_fallbacks", 0)))
    if plan_cache is not None:
        row.update({k: (round(v, 1) if isinstance(v, float) else v)
                    for k, v in plan_cache.stats().items()})
    if 0 <= args.check < args.replicas:
        solo = campaign.run_solo(args.check)
        row["solo_check"] = dict(
            replica=args.check,
            events_bit_identical=(solo.events
                                  == results[args.check].events),
            clock_bit_identical=solo.t == results[args.check].t,
            fault_events_bit_identical=(
                solo.fault_events == results[args.check].fault_events))
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as fh:
            fh.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
