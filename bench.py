"""Benchmark: LMM max-min solve on device vs the exact host list solver.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}

* value        — device (JAX/TPU) solve latency in ms on a 100k-flow
                 system (the BASELINE.json target scale: 100k+ concurrent
                 flows over a 16k-link platform).
* vs_baseline  — speedup of the device solve over the exact host list
                 solver (the reference architecture's algorithm,
                 maxmin.cpp:502-693 semantics) measured on the largest
                 maxmin_bench-style class the host can finish quickly
                 (teshsuite/surf/maxmin_bench/maxmin_bench.cpp classes).

All diagnostics go to stderr; stdout carries exactly the JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_arrays(rng, n_c, n_v, deg, dtype):
    from simgrid_tpu.ops.lmm_jax import LmmArrays, _bucket

    E = n_v * deg
    Eb, Cb, Vb = _bucket(E), _bucket(n_c), _bucket(n_v)
    e_var = np.zeros(Eb, np.int32)
    e_cnst = np.zeros(Eb, np.int32)
    e_w = np.zeros(Eb, dtype)
    e_var[:E] = np.repeat(np.arange(n_v, dtype=np.int32), deg)
    e_cnst[:E] = rng.integers(0, n_c, size=E).astype(np.int32)
    e_w[:E] = rng.uniform(0.5, 1.5, size=E).astype(dtype)
    c_bound = np.zeros(Cb, dtype)
    c_bound[:n_c] = rng.uniform(1.0, 10.0, size=n_c).astype(dtype)
    c_fatpipe = np.zeros(Cb, bool)
    v_penalty = np.zeros(Vb, dtype)
    v_penalty[:n_v] = 1.0
    v_bound = np.full(Vb, -1.0, dtype)
    return LmmArrays(e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty,
                     v_bound, E, n_c, n_v)


def host_solve_time(arrays) -> float:
    """Build the same system in the exact host solver and time one solve."""
    from simgrid_tpu.ops.lmm_host import System

    sys_ = System(selective_update=False)
    cnsts = [sys_.constraint_new(None, float(arrays.c_bound[i]))
             for i in range(arrays.n_cnst)]
    E = arrays.n_elem
    by_var = {}
    for k in range(E):
        by_var.setdefault(int(arrays.e_var[k]), []).append(k)
    for vi, elems in by_var.items():
        var = sys_.variable_new(None, 1.0, -1.0, len(elems))
        seen = set()
        for k in elems:
            ci = int(arrays.e_cnst[k])
            if ci in seen:
                sys_.expand_add(cnsts[ci], var, float(arrays.e_w[k]))
            else:
                seen.add(ci)
                sys_.expand(cnsts[ci], var, float(arrays.e_w[k]))
    t0 = time.perf_counter()
    sys_.solve_exact()
    return time.perf_counter() - t0


def device_solve_time(arrays, eps, reps=5) -> float:
    import jax

    from simgrid_tpu.ops.lmm_jax import solve_arrays

    solve_arrays(arrays, eps)  # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        solve_arrays(arrays, eps)
        times.append(time.perf_counter() - t0)
    del jax
    return float(np.median(times))


def main():
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    dtype = np.float32 if on_tpu else np.float64
    eps = 1e-5 if on_tpu else 1e-9
    log(f"device: {dev} platform={dev.platform} dtype={dtype.__name__}")

    rng = np.random.default_rng(42)

    # --- headline: 100k flows over 16k links, 4 links per flow ---------
    # (on a CPU-only dev box, drop to 20k flows so the bench stays fast)
    n_flows = 100_000 if on_tpu else 20_000
    big = build_arrays(rng, 16384, n_flows, 4, dtype)
    t_dev_100k = device_solve_time(big, eps)
    log(f"device solve @{n_flows} flows: {t_dev_100k*1e3:.2f} ms")

    # --- speedup vs exact host solver on maxmin_bench classes ----------
    # Start at the reference's "big" class (2000x2000), escalate to
    # "huge" (20000x20000) only if the host is fast enough to finish.
    cls = dict(n_c=2000, n_v=2000, deg=3, name="big 2000x2000")
    arrays = build_arrays(np.random.default_rng(1), dtype=dtype, **{
        k: cls[k] for k in ("n_c", "n_v", "deg")})
    t_host = host_solve_time(arrays)
    t_dev = device_solve_time(arrays, eps)
    log(f"{cls['name']}: host {t_host*1e3:.1f} ms, device {t_dev*1e3:.2f} ms")

    if t_host < 0.8:  # projected huge host time ~100x big: keep under ~80 s
        cls = dict(n_c=20000, n_v=20000, deg=3, name="huge 20000x20000")
        arrays = build_arrays(np.random.default_rng(2), dtype=dtype, **{
            k: cls[k] for k in ("n_c", "n_v", "deg")})
        t_host = host_solve_time(arrays)
        t_dev = device_solve_time(arrays, eps)
        log(f"{cls['name']}: host {t_host*1e3:.1f} ms, "
            f"device {t_dev*1e3:.2f} ms")

    speedup = t_host / t_dev if t_dev > 0 else float("inf")
    print(json.dumps({
        "metric": f"LMM solve latency @{n_flows} flows on {dev.platform} "
                  f"(vs_baseline: speedup over exact host list solver, "
                  f"{cls['name']} class)",
        "value": round(t_dev_100k * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
