"""Benchmark: LMM max-min solve on device vs the exact host list solver.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}

* value        — device (JAX/TPU) solve latency in ms on a 100k-flow
                 system (the BASELINE.json target scale: 100k+ concurrent
                 flows over a 16k-link platform).
* vs_baseline  — speedup of the device solve over the exact host list
                 solver (the reference architecture's algorithm,
                 maxmin.cpp:502-693 semantics) on the largest
                 maxmin_bench-style class measured
                 (teshsuite/surf/maxmin_bench/maxmin_bench.cpp classes).

Crash-robust by construction: every measurement runs in a *subprocess*
with a timeout, so a wedged/dead TPU backend (the round-1 failure: the
chip hung jax.devices() for every later process) costs one stage, not
the bench.  Stages that die are recorded in the "errors" field; whatever
was measured is still reported, and the device stages are retried on the
CPU backend when the accelerator is unusable.

All diagnostics go to stderr; stdout carries exactly the JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Schema-stable result rows
# ---------------------------------------------------------------------------
# Every stage that persists results appends rows carrying the same
# identity keys, so bench_results/*.jsonl files merge across PRs (and
# across machines) without hand-editing: filter on (stage, mode, batch,
# platform), order by git_rev history.

# v2: +mesh_shape/+device_count on every row (topology identity)
SCHEMA_VERSION = 2
_GIT_REV = None


def git_rev() -> str:
    global _GIT_REV
    if _GIT_REV is None:
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            _GIT_REV = out.stdout.strip() or "unknown"
        except Exception:
            _GIT_REV = "unknown"
    return _GIT_REV


def schema_row(stage: str, payload: dict, mode=None, batch=None,
               platform: str = "cpu", mesh_shape=None) -> dict:
    """One mergeable result row: identity keys first, payload after.

    ``mesh_shape`` (e.g. ``[4]`` for a 4-way batch-axis mesh, None for
    single-device runs) and ``device_count`` (visible JAX devices in
    the measuring process, None when the stage never touched JAX)
    identify the topology, so sharded and unsharded rows in the same
    JSONL file cannot be confused."""
    device_count = None
    if "jax" in sys.modules:
        try:
            device_count = sys.modules["jax"].device_count()
        except Exception:
            device_count = None
    row = {"schema": SCHEMA_VERSION, "git_rev": git_rev(),
           "stage": stage, "mode": mode, "batch": batch,
           "platform": platform, "mesh_shape": mesh_shape,
           "device_count": device_count}
    for k, v in payload.items():
        if k not in row:
            row[k] = v
    return row


def append_rows(filename: str, rows) -> str:
    """Append rows to bench_results/<filename>; returns the path."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_results", filename)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    return path


# ---------------------------------------------------------------------------
# Measurement stages (each runs in its own subprocess)
# ---------------------------------------------------------------------------

def _force_cpu():
    import jax
    jax.config.update("jax_platforms", "cpu")


def build_arrays(rng, n_c, n_v, deg, dtype):
    from simgrid_tpu.ops.lmm_jax import LmmArrays, _bucket

    E = n_v * deg
    Eb, Cb, Vb = _bucket(E), _bucket(n_c), _bucket(n_v)
    e_var = np.zeros(Eb, np.int32)
    e_cnst = np.zeros(Eb, np.int32)
    e_w = np.zeros(Eb, dtype)
    e_var[:E] = np.repeat(np.arange(n_v, dtype=np.int32), deg)
    e_cnst[:E] = rng.integers(0, n_c, size=E).astype(np.int32)
    e_w[:E] = rng.uniform(0.5, 1.5, size=E).astype(dtype)
    c_bound = np.zeros(Cb, dtype)
    c_bound[:n_c] = rng.uniform(1.0, 10.0, size=n_c).astype(dtype)
    c_fatpipe = np.zeros(Cb, bool)
    v_penalty = np.zeros(Vb, dtype)
    v_penalty[:n_v] = 1.0
    v_bound = np.full(Vb, -1.0, dtype)
    return LmmArrays(e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty,
                     v_bound, E, n_c, n_v)


def stage_probe() -> dict:
    """Identify the default device (this is the call that hangs on a
    wedged TPU — hence subprocess + timeout)."""
    import jax
    dev = jax.devices()[0]
    return {"platform": dev.platform, "device": str(dev)}


def stage_device(n_c: int, n_v: int, deg: int, seed: int,
                 cpu: bool, reps: int, dtype: str = "auto") -> dict:
    """Median device solve latency on one maxmin_bench-style class, for
    both round strategies."""
    if cpu:
        _force_cpu()
    import jax

    from simgrid_tpu.ops.lmm_jax import solve_arrays
    from simgrid_tpu.utils.config import config

    # One-shot solves of a fixed big system: pay per-system compiles
    # for padding that tracks the real element count (up to 2x less
    # gathered volume than the pow2 simulation buckets).
    config["lmm/pad"] = "tight"

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if dtype == "auto":
        dtype = "f32" if on_tpu else "f64"
    # f32 runs at chip precision (eps 1e-5 ~ the reference's default
    # maxmin/precision); f64 at the list-solver oracle precision.
    dtype = np.float32 if dtype == "f32" else np.float64
    eps = 1e-5 if dtype == np.float32 else 1e-9
    arrays = build_arrays(np.random.default_rng(seed), n_c, n_v, deg, dtype)

    out = {"platform": dev.platform, "dtype": np.dtype(dtype).name}
    modes = [("local", True), ("global", False)]
    if (on_tpu and n_v > 5_000) or n_v > 20_000:
        # global mode fixes ~one variable per round (7k+ sequential
        # rounds at 20k, ~40k at the giant class) — minutes of device
        # time for a number nobody uses; local is the device mode.
        # Measure global up to the huge class on CPU, small class on
        # accelerators.
        modes = [("local", True)]
    for name, parallel in modes:
        _, _, _, rounds = solve_arrays(arrays, eps, parallel_rounds=parallel)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            solve_arrays(arrays, eps, parallel_rounds=parallel)
            times.append(time.perf_counter() - t0)
        out[f"ms_{name}"] = round(float(np.median(times)) * 1e3, 3)
        out[f"rounds_{name}"] = rounds
        # Emit partial progress to stderr so a later-stage death still
        # leaves the numbers in the log.
        log(f"[stage dev] {name}: {out[f'ms_{name}']} ms, {rounds} rounds")
    return out


def stage_host(n_c: int, n_v: int, deg: int, seed: int) -> dict:
    """One exact host list solve (the reference architecture's algorithm)
    on the same class."""
    from simgrid_tpu.ops.lmm_host import System

    arrays = build_arrays(np.random.default_rng(seed), n_c, n_v, deg,
                          np.float64)
    sys_ = System(selective_update=False)
    cnsts = [sys_.constraint_new(None, float(arrays.c_bound[i]))
             for i in range(arrays.n_cnst)]
    E = arrays.n_elem
    by_var = {}
    for k in range(E):
        by_var.setdefault(int(arrays.e_var[k]), []).append(k)
    for vi, elems in by_var.items():
        var = sys_.variable_new(None, 1.0, -1.0, len(elems))
        seen = set()
        for k in elems:
            ci = int(arrays.e_cnst[k])
            if ci in seen:
                sys_.expand_add(cnsts[ci], var, float(arrays.e_w[k]))
            else:
                seen.add(ci)
                sys_.expand(cnsts[ci], var, float(arrays.e_w[k]))
    t0 = time.perf_counter()
    sys_.solve_exact()
    return {"ms": round((time.perf_counter() - t0) * 1e3, 3)}


def stage_native(n_c: int, n_v: int, deg: int, seed: int) -> dict:
    """One exact native (C++) solve on the same class via the COO entry."""
    from simgrid_tpu.ops import lmm_native

    if not lmm_native.available():
        raise RuntimeError("native solver unavailable")
    arrays = build_arrays(np.random.default_rng(seed), n_c, n_v, deg,
                          np.float64)
    t0 = time.perf_counter()
    lmm_native.solve_coo(arrays.e_var, arrays.e_cnst, arrays.e_w,
                         arrays.c_bound, arrays.c_fatpipe, arrays.v_penalty,
                         arrays.v_bound, 1e-9, arrays.n_elem, arrays.n_cnst,
                         arrays.n_var)
    return {"ms": round((time.perf_counter() - t0) * 1e3, 3)}


def stage_churn(n_v: int, seed: int, cpu: bool, mode: str,
                clusters: int = 960, chain: int = 96,
                churn: float = 0.01, steps: int = 6) -> dict:
    """Incremental-churn scenario (the warm-start trajectory metric):
    `n_v` flows spread over independent cluster constraints plus a deep
    background saturation chain (bounds doubling => ~`chain` fixpoint
    rounds from a cold start in local-rounds mode).  Between solves,
    `churn` of the flows retire and are replaced — the SMPI-style
    mutating phase.  Modes map to the lmm/warm-start x lmm/delta-upload
    grid:

      legacy-subset   warm-start:off  (re-flatten the modified subset)
      cold-full       cold + delta-upload:off (device-resident arrays,
                      whole-field re-uploads, cold fixpoint)
      cold-delta      cold + delta-upload:on  (indexed uploads only)
      warm-selective  on   + delta-upload:on  (modified-component
                      restarts: the headline)

    Reported per mode: per-solve wall, fixpoint rounds, upload bytes
    (full vs delta) and dirty-slot counts, medians over the churn
    steps with the cold first solve separated out."""
    if cpu:
        _force_cpu()
    import jax  # noqa: F401  (select backend before importing ops)
    from simgrid_tpu.ops import lmm_jax, make_new_maxmin_system, opstats
    from simgrid_tpu.utils.config import config

    flags = {"legacy-subset": ("off", "off"),
             "cold-full": ("cold", "off"),
             "cold-delta": ("cold", "on"),
             "warm-selective": ("on", "on")}[mode]
    config["lmm/warm-start"], config["lmm/delta-upload"] = flags

    rng = np.random.default_rng(seed)
    s = make_new_maxmin_system(True)
    s.solve_fn = lmm_jax.solve_jax
    chain_cs = [s.constraint_new(None, float(2.0 ** i))
                for i in range(chain)]
    for i in range(chain - 1):
        v = s.variable_new(None, 1, -1, 2)
        s.expand(chain_cs[i], v, 1)
        s.expand(chain_cs[i + 1], v, 1)
    n_flows = n_v - (chain - 1)
    cluster_cs = [s.constraint_new(None, float(rng.uniform(50, 200)))
                  for _ in range(clusters)]
    flows = [[] for _ in range(clusters)]
    weights = rng.choice([0.5, 1.0, 2.0], size=n_flows)
    for i in range(n_flows):
        k = i % clusters
        v = s.variable_new(None, 1.0)
        s.expand(cluster_cs[k], v, float(weights[i]))
        flows[k].append(v)

    out = {"mode": mode, "flows": n_flows, "clusters": clusters,
           "chain": chain, "churn": churn, "steps": steps}
    before = opstats.snapshot()
    t0 = time.perf_counter()
    s.solve()
    out["first_solve_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    d = opstats.diff(before)
    out["rounds_first"] = int(d.get("fixpoint_rounds", 0))
    out["bytes_full_first"] = int(d.get("uploaded_bytes_full", 0))

    churn_n = max(1, int(n_flows * churn))
    walls, rounds, b_full, b_delta, dirt = [], [], [], [], []
    donated = int(d.get("donated_buffers", 0))
    for step in range(steps):
        ks = rng.integers(0, clusters, size=churn_n)
        for k in ks:
            k = int(k)
            if flows[k]:
                s.variable_free(flows[k].pop(0))
            v = s.variable_new(None, 1.0)
            s.expand(cluster_cs[k], v, float(rng.choice([0.5, 1.0, 2.0])))
            flows[k].append(v)
        before = opstats.snapshot()
        t0 = time.perf_counter()
        s.solve()
        walls.append((time.perf_counter() - t0) * 1e3)
        d = opstats.diff(before)
        rounds.append(int(d.get("fixpoint_rounds", 0)))
        b_full.append(int(d.get("uploaded_bytes_full", 0)))
        b_delta.append(int(d.get("uploaded_bytes_delta", 0)))
        donated += int(d.get("donated_buffers", 0))
        ws = s.warm_solver
        dirt.append(ws.last_dirty_slots if ws else -1)
        log(f"[stage churn/{mode}] step {step}: {walls[-1]:.1f} ms, "
            f"{rounds[-1]} rounds, full {b_full[-1]}B, "
            f"delta {b_delta[-1]}B")
    med = lambda xs: round(float(np.median(xs)), 1)  # noqa: E731
    out.update(solve_ms_med=med(walls), rounds_med=int(np.median(rounds)),
               bytes_full_med=int(np.median(b_full)),
               bytes_delta_med=int(np.median(b_delta)),
               dirty_slots_med=int(np.median(dirt)),
               # carried-state buffers handed to XLA for in-place
               # reuse over the whole stage (0 on solve-only paths —
               # only donating drain dispatches bump it; recorded so
               # churn rows compose with drain-stage rows downstream)
               donated_buffers=donated,
               warm_solves=(s.warm_solver.warm_solves
                            if s.warm_solver else 0))
    return out


def stage_sweep(n_c: int, n_v: int, deg: int, seed: int,
                replicas: int = 64, superstep: int = 8) -> dict:
    """Batched multi-replica campaign throughput (the lmm_batch
    trajectory metric): one shared platform flattening, `replicas`
    mixed fault/sweep scenarios, drained at fleet batch sizes
    {1, 8, 64}.  Reported per batch size (opstats-scoped, so stages
    sharing this process cannot double-count): device dispatches and
    upload bytes PER REPLICA — the two costs the tunneled accelerator
    charges per transfer, which batching amortizes across the fleet —
    plus wall time and a cross-batch event-stream consistency check
    (every batch size must produce bit-identical per-replica events).

    CPU-measured by design: the contract is the per-replica dispatch /
    upload *count* scaling, which is platform-independent; tools own
    the on-hardware wall-clock story."""
    _force_cpu()
    import jax  # noqa: F401  (select backend before importing ops)
    from simgrid_tpu.ops import opstats
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, deg, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    specs = [ScenarioSpec(seed=s,
                          bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=400.0 if s % 2 else None,
                          fault_mttr=50.0, fault_horizon=600.0,
                          dead_flows=(s % 11,) if s % 3 == 0 else ())
             for s in range(replicas)]
    campaign = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                        arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                        specs, eps=1e-9, dtype=np.float64,
                        superstep=superstep)

    rows = []
    streams = {}
    for batch in (1, 8, 64):
        if batch > replicas:
            continue
        t0 = time.perf_counter()
        results, st = campaign.run_scoped(batch=batch,
                                          stage=f"sweep/b{batch}")
        wall = time.perf_counter() - t0
        errors = sum(1 for r in results if r.error)
        streams[batch] = [[(t, f) for t, f in r.events]
                          for r in results]
        upload = (st.get("uploaded_bytes_full", 0)
                  + st.get("uploaded_bytes_delta", 0))
        row = {"bench": "lmm_batch", "replicas": replicas,
               "n_c": n_c, "n_v": n_v, "deg": deg, "seed": seed,
               "superstep": superstep,
               "dispatches": int(st.get("dispatches", 0)),
               "dispatches_per_replica":
                   round(st.get("dispatches", 0) / replicas, 3),
               "upload_bytes": int(upload),
               "upload_bytes_per_replica": round(upload / replicas, 1),
               "fixpoint_rounds": int(st.get("fixpoint_rounds", 0)),
               "wall_ms": round(wall * 1e3, 1),
               "wall_ms_per_replica": round(wall * 1e3 / replicas, 2),
               "errors": errors}
        rows.append(schema_row("sweep", row, mode="batched-drain",
                               batch=batch, platform="cpu"))
        log(f"[stage sweep] batch={batch}: "
            f"{row['dispatches_per_replica']} dispatches/replica, "
            f"{row['upload_bytes_per_replica']} B/replica, "
            f"{row['wall_ms']} ms")
    base = streams.get(1)
    consistent = all(streams[b] == base for b in streams)
    for row in rows:
        row["events_consistent"] = consistent
    path = append_rows("lmm_batch.jsonl", rows)
    log(f"[stage sweep] rows appended to {path} "
        f"(events_consistent={consistent})")
    out = {"rows": rows, "events_consistent": consistent}
    by_batch = {r["batch"]: r for r in rows}
    if 1 in by_batch and 64 in by_batch:
        b1, b64 = by_batch[1], by_batch[64]
        out["dispatch_amortization"] = round(
            b1["dispatches_per_replica"]
            / max(b64["dispatches_per_replica"], 1e-9), 1)
        out["upload_amortization"] = round(
            b1["upload_bytes_per_replica"]
            / max(b64["upload_bytes_per_replica"], 1e-9), 1)
    return out


def stage_fault(n_c: int, n_v: int, deg: int, seed: int,
                replicas: int = 32, superstep: int = 8) -> dict:
    """Device-resident fault event tapes (the ISSUE-10 trajectory
    metric): one campaign fleet — half the replicas carrying seeded
    MTBF/MTTR link-failure schedules — drained once per fault mode:
    ``off`` (fault dimension ignored: the no-tape baseline the tape
    rows are compared against), ``static`` (pre-tape time-averaged
    capacity folding), ``on`` (event tapes: links flip mid-drain at
    the exact schedule dates) and ``on`` + pipeline depth 2 (tape
    fires as clean-collect boundaries for the speculative path, the
    discarded supersteps counted as ``fault_replays``).

    Honest counters per row: compiled tape slots, events that actually
    FIRED mid-drain, speculative replays, dispatches and wall time per
    replica.  The ``on`` row also carries a solo spot check (a faulted
    replica's events, fired faults and Kahan clock bit-identical to
    its solo drain) and asserts the tape fired at all — a row whose
    tape never fired measured nothing.

    CPU-measured by design: the contract is the counter structure
    (fires, replays, dispatch scaling), which is platform-independent;
    tools own the on-hardware wall-clock story."""
    _force_cpu()
    import jax  # noqa: F401  (select backend before importing ops)
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, deg, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    specs = [ScenarioSpec(seed=s,
                          bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=400.0 if s % 2 else None,
                          fault_mttr=50.0, fault_horizon=600.0)
             for s in range(replicas)]

    rows = []
    fired = 0
    variants = [("off", "off", 0), ("static", "static", 0),
                ("on", "on", 0), ("on-d2", "on", 2)]
    for label, mode, depth in variants:
        campaign = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                            arrays.e_w[:E], arrays.c_bound[:n_c],
                            sizes, specs, eps=1e-9, dtype=np.float64,
                            superstep=superstep, fault_mode=mode)
        t0 = time.perf_counter()
        results, st = campaign.run_scoped(batch=replicas,
                                          stage=f"fault/{label}",
                                          pipeline=depth or None)
        wall = time.perf_counter() - t0
        row = {"bench": "lmm_fault", "replicas": replicas,
               "n_c": n_c, "n_v": n_v, "deg": deg, "seed": seed,
               "superstep": superstep, "fault_mode": mode,
               "pipeline": depth,
               "fault_replicas": sum(1 for s in specs
                                     if s.fault_mtbf is not None),
               "fault_tape_slots": int(st.get("fault_tape_slots", 0)),
               "fault_tape_events":
                   int(st.get("fault_tape_events", 0)),
               "fault_replays": int(st.get("fault_replays", 0)),
               "dispatches": int(st.get("dispatches", 0)),
               "dispatches_per_replica":
                   round(st.get("dispatches", 0) / replicas, 3),
               "wall_ms": round(wall * 1e3, 1),
               "wall_ms_per_replica": round(wall * 1e3 / replicas, 2),
               "errors": sum(1 for r in results if r.error)}
        if label == "on":
            fired = row["fault_tape_events"]
            j = 1        # first faulted replica (odd seeds)
            solo = campaign.run_solo(j)
            row["solo_bit_identical"] = (
                solo.events == results[j].events
                and solo.t == results[j].t
                and solo.fault_events == results[j].fault_events)
            row["tape_fired"] = fired > 0
        rows.append(schema_row("fault", row, mode=f"fault-{label}",
                               batch=replicas, platform="cpu"))
        log(f"[stage fault] {label}: "
            f"{row['fault_tape_events']} fires / "
            f"{row['fault_tape_slots']} slots, "
            f"{row['fault_replays']} replays, {row['wall_ms']} ms")
    path = append_rows("lmm_fault.jsonl", rows)
    log(f"[stage fault] rows appended to {path}")
    by = {r["fault_mode"] + (f"-d{r['pipeline']}" if r["pipeline"]
                             else ""): r for r in rows}
    out = {"rows": rows, "tape_fired": fired > 0}
    if "off" in by and "on" in by:
        out["tape_wall_overhead"] = round(
            by["on"]["wall_ms"] / max(by["off"]["wall_ms"], 1e-9), 2)
    return out


def stage_collective(seed: int, superstep: int = 16) -> dict:
    """Collective schedule tapes (the ISSUE-13 trajectory metric):
    host-maestro vs tape-driven allreduce at 64 / 256 / 1024 ranks.
    The maestro drives the SAME compiled comm DAG the SMPI way — every
    advance is >= 2 dispatches and >= 3 fetches, every activation an
    extra scatter upload — while the tape path walks the DAG inside
    the superstep while_loop, one dispatch per K advances and no host
    involvement until the phase barrier.

    Algorithm per rank count: ring (lr) at 64 ranks (2(R-1)·R comm
    records — the quadratic schedule the tape must absorb), recursive
    doubling at 256 and 1024 (R·log2 R records; lr at 1k would be
    ~2.1M flow slots, beyond a sensible maestro run).  Every row
    checks the two event streams, activation streams and Kahan clocks
    are bit-identical — a fast row with different events measured
    nothing — and reports dispatches per collective step plus uploaded
    bytes for both drivers.

    CPU-measured by design: the contract is the dispatch/upload
    structure, which is platform-independent; tools own the
    on-hardware wall-clock story (ROADMAP sweep list carries the TPU
    row)."""
    _force_cpu()
    import jax  # noqa: F401  (select backend before importing ops)
    from simgrid_tpu.collectives import CollectiveSpec, HostMaestro
    from simgrid_tpu.ops import opstats

    cases = [CollectiveSpec("allreduce", "lr", 64, "nic",
                            1 << 17, bw=1e9),
             CollectiveSpec("allreduce", "rdb", 256, "nic",
                            1 << 20, bw=1e9),
             CollectiveSpec("allreduce", "rdb", 1024, "nic",
                            1 << 20, bw=1e9)]
    rows = []
    for cs in cases:
        dc = cs.build()
        legs = {}
        for label in ("tape", "maestro"):
            before = opstats.snapshot()
            t0 = time.perf_counter()
            if label == "tape":
                drv = dc.make_sim(superstep=superstep)
                drv.run()
                dispatches = drv.supersteps
                events = (drv.events, drv.collective_events)
                clk = tuple(float(x) for x in np.asarray(drv._coll_clk))
            else:
                drv = HostMaestro(dc)
                drv.run()
                dispatches = drv.dispatches
                events = (drv.events, drv.collective_events)
                clk = drv.clock
            wall = time.perf_counter() - t0
            st = opstats.diff(before)
            legs[label] = {
                "dispatches": int(st.get("dispatches", dispatches)),
                "upload_bytes": int(st.get("uploaded_bytes_full", 0)
                                    + st.get("uploaded_bytes_delta", 0)),
                "wall_ms": round(wall * 1e3, 1),
                "events": events, "clock": clk}
        ok = (legs["tape"]["events"] == legs["maestro"]["events"]
              and legs["tape"]["clock"] == legs["maestro"]["clock"])
        row = {"bench": "lmm_collective", "op": cs.op, "algo": cs.algo,
               "ranks": cs.ranks, "topo": cs.topo,
               "payload": cs.payload, "superstep": superstep,
               "n_v": dc.n_v, "n_c": dc.n_c, "n_edges": dc.n_edges,
               "events_bit_identical": ok,
               "activations": len(legs["tape"]["events"][1])}
        for label in ("tape", "maestro"):
            for k in ("dispatches", "upload_bytes", "wall_ms"):
                row[f"{label}_{k}"] = legs[label][k]
            # one collective == one step: per-step == per-row totals
            row[f"{label}_dispatches_per_step"] = legs[label][
                "dispatches"]
        row["dispatch_ratio"] = round(
            row["maestro_dispatches"]
            / max(row["tape_dispatches"], 1), 1)
        rows.append(schema_row("collective", row,
                               mode=f"{cs.algo}-r{cs.ranks}",
                               platform="cpu"))
        log(f"[stage collective] {cs.algo} r{cs.ranks}: "
            f"{dc.n_v} comms, tape {row['tape_dispatches']} vs "
            f"maestro {row['maestro_dispatches']} dispatches "
            f"({row['dispatch_ratio']}x), bit_identical={ok}")
    path = append_rows("lmm_collective.jsonl", rows)
    log(f"[stage collective] rows appended to {path}")
    return {"rows": rows,
            "events_bit_identical": all(r["events_bit_identical"]
                                        for r in rows),
            "min_dispatch_ratio": min(r["dispatch_ratio"]
                                      for r in rows)}


def stage_shard(n_c: int, n_v: int, deg: int, seed: int,
                per_shard: int = 16, superstep: int = 8,
                max_mesh: int = 4) -> dict:
    """Mesh-sharded campaign fleets (the ISSUE-6 trajectory metric):
    the replica axis of the batched drain sharded over a virtual CPU
    device mesh at FIXED per-device batch — the pod-scale contract is
    that per-replica dispatches and upload bytes stay flat (or fall)
    as the mesh doubles, because one fleet superstep is still one
    logical dispatch and every payload byte lands on exactly one
    device.  Mesh sizes {1, 2, ..., max_mesh} (powers of two), fleet
    B = per_shard * M; mesh 1 is the single-device vmapped baseline.

    Honest counters per row: dispatches, logical upload bytes
    (full+delta), the replicated-per-device vs sharded split,
    per-shard demux fetches and fetched bytes — all per replica where
    it matters.  Every row carries mesh_shape/device_count; the first
    per_shard replicas exist in every fleet and their event streams
    must be bit-identical across mesh sizes.

    CPU-measured by design (forced host-platform device count): the
    contract is counter SCALING, which is platform-independent; the
    wall-clock story belongs to real multi-chip hardware."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count"
            f"={max_mesh}").strip()
    _force_cpu()
    import jax
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    mesh_sizes = [1]
    while mesh_sizes[-1] * 2 <= min(max_mesh, jax.device_count()):
        mesh_sizes.append(mesh_sizes[-1] * 2)
    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, deg, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    B_max = per_shard * mesh_sizes[-1]
    specs = [ScenarioSpec(seed=s,
                          bw_scale=1.0 + 0.1 * (s % 5),
                          size_scale=1.0 + 0.05 * (s % 3),
                          fault_mtbf=400.0 if s % 2 else None,
                          fault_mttr=50.0, fault_horizon=600.0,
                          dead_flows=(s % 11,) if s % 3 == 0 else ())
             for s in range(B_max)]

    rows = []
    streams = {}
    for M in mesh_sizes:
        B = per_shard * M
        campaign = Campaign(arrays.e_var[:E], arrays.e_cnst[:E],
                            arrays.e_w[:E], arrays.c_bound[:n_c],
                            sizes, specs[:B], eps=1e-9,
                            dtype=np.float64, superstep=superstep)
        t0 = time.perf_counter()
        results, st = campaign.run_scoped(
            batch=B, stage=f"shard/m{M}",
            mesh=(M if M > 1 else None))
        wall = time.perf_counter() - t0
        # the replicas shared by every fleet size must agree bit-for-bit
        streams[M] = [[(t, f) for t, f in r.events]
                      for r in results[:per_shard]]
        upload = (st.get("uploaded_bytes_full", 0)
                  + st.get("uploaded_bytes_delta", 0))
        row = {"bench": "lmm_shard", "replicas": B,
               "per_shard": per_shard, "mesh": M,
               "n_c": n_c, "n_v": n_v, "deg": deg, "seed": seed,
               "superstep": superstep,
               "dispatches": int(st.get("dispatches", 0)),
               "dispatches_per_replica":
                   round(st.get("dispatches", 0) / B, 3),
               "upload_bytes": int(upload),
               "upload_bytes_per_replica": round(upload / B, 1),
               "replicated_upload_bytes":
                   int(st.get("replicated_upload_bytes", 0)),
               "sharded_upload_bytes":
                   int(st.get("sharded_upload_bytes", 0)),
               "fetches": int(st.get("fetches", 0)),
               "demux_fetches": int(st.get("demux_fetches", 0)),
               "fetched_bytes": int(st.get("fetched_bytes", 0)),
               "fetched_bytes_per_replica":
                   round(st.get("fetched_bytes", 0) / B, 1),
               "fixpoint_rounds": int(st.get("fixpoint_rounds", 0)),
               "wall_ms": round(wall * 1e3, 1),
               "errors": sum(1 for r in results if r.error)}
        rows.append(schema_row("shard", row, mode="sharded-drain",
                               batch=B, platform="cpu",
                               mesh_shape=[M]))
        log(f"[stage shard] mesh={M} B={B}: "
            f"{row['dispatches_per_replica']} dispatches/replica, "
            f"{row['upload_bytes_per_replica']} B/replica up, "
            f"{row['fetched_bytes_per_replica']} B/replica down, "
            f"{row['wall_ms']} ms")
    base = streams[mesh_sizes[0]]
    consistent = all(streams[m] == base for m in streams)
    for row in rows:
        row["events_consistent"] = consistent
    path = append_rows("lmm_shard.jsonl", rows)
    log(f"[stage shard] rows appended to {path} "
        f"(events_consistent={consistent})")

    out = {"rows": rows, "events_consistent": consistent}
    by_mesh = {r["mesh"]: r for r in rows}
    flat = {}
    for a, b in zip(mesh_sizes, mesh_sizes[1:]):
        for key in ("dispatches_per_replica", "upload_bytes_per_replica",
                    "fetched_bytes_per_replica"):
            prev = by_mesh[a][key]
            ratio = by_mesh[b][key] / prev if prev else float("inf")
            flat.setdefault(key, []).append(round(ratio, 3))
    # flat-or-falling per-replica counters as the mesh doubles
    out["per_replica_scaling"] = flat
    out["per_replica_flat_or_falling"] = all(
        r <= 1.1 for rs in flat.values() for r in rs)
    return out


def build_wave_arrays(n_c: int, per: int, waves: int, seed: int):
    """deg=1 drain system shaped like the north-star alltoall phase:
    `per` flows per (link, size-wave) tie group — every advance
    retires one whole group, solves converge in ~1 round, and the
    completion rings run fat.  The shape where the host-side event
    consumer (engine bookkeeping) is a real fraction of the advance
    cost, i.e. where pipelining has latency to hide."""
    rng = np.random.default_rng(seed)
    n_v = n_c * per * waves
    e_var = np.arange(n_v, dtype=np.int32)
    e_cnst = (np.arange(n_v) // (per * waves)).astype(np.int32)
    e_w = np.ones(n_v)
    c_bound = rng.uniform(1e5, 1e6, n_c)
    wave = (np.arange(n_v) // per) % waves
    sizes = 1e6 * (1.0 + 0.21 * wave)
    return e_var, e_cnst, e_w, c_bound, sizes


def stage_pipeline(seed: int, k: int = 8, host_work_us: float = 500.0,
                   n_c: int = 32, per: int = 1, waves: int = 8,
                   replicas: int = 64) -> dict:
    """Speculative pipelined drain (the ISSUE-5 trajectory metric):
    blocking fetches per advance, pipelined vs superstep-only at equal
    K, plus speculation commit rate and the compact per-replica
    element-weight payload bytes.

    Two workloads, both CPU (the contract is the count of fetches the
    host genuinely stalled on, which opstats classifies via
    Array.is_ready at fetch time):

    * **solo** — a wave-drain (build_wave_arrays) with an event
      consumer attached (DrainSim.on_batches) that emulates
      `host_work_us` of per-advance maestro bookkeeping (the engine
      fast path's finish/wakeup/heap work — measured at a few hundred
      µs/advance at engine scale).  The SAME consumer runs at every
      depth, so the comparison is fair: superstep-only pays the device
      round trip on every fetch ON TOP of the host work, the pipelined
      driver hides it behind the host work.  `host_work_us` is
      recorded on every row.
    * **fleet** — a `replicas`-wide campaign chunk (per-lane demux is
      the natural host work, no emulation), with per-replica elem_w
      overrides so the indexed-payload upload bytes land on the row
      next to the dense B×E bytes they replace.

    Rows (schema-stable: stage/mode/batch/platform + depth/superstep)
    are appended to bench_results/lmm_pipeline.jsonl."""
    _force_cpu()
    import time as _time

    import jax  # noqa: F401
    from simgrid_tpu.ops import opstats
    from simgrid_tpu.ops.lmm_drain import DrainSim
    from simgrid_tpu.parallel.campaign import Campaign, ScenarioSpec

    ev, ec, ew, cb, sizes = build_wave_arrays(n_c, per, waves, seed)
    n_v = len(sizes)

    def spin(us):
        t_end = _time.perf_counter() + us * 1e-6
        while _time.perf_counter() < t_end:
            pass

    def run_solo(depth):
        sim = DrainSim(ev, ec, ew, cb, sizes, eps=1e-9,
                       dtype=np.float64, repack_min=1 << 62,
                       superstep=k, pipeline=depth)
        if host_work_us:
            sim.on_batches = lambda bs: spin(host_work_us * len(bs))
        t0 = _time.perf_counter()
        sim.run()
        return sim, (_time.perf_counter() - t0) * 1e3

    rows = []
    streams = {}
    run_solo(0)                       # warm the jits once, unscoped
    for depth in (0, 1, 2):
        with opstats.scoped(f"pipeline/solo-d{depth}") as st:
            sim, wall = run_solo(depth)
        streams[depth] = (sim.events, sim.t)
        adv = max(sim.advances, 1)
        row = {"bench": "lmm_pipeline", "workload": "solo-wave",
               "n_c": n_c, "n_v": n_v, "seed": seed,
               "depth": depth, "superstep": k,
               "host_work_us": host_work_us,
               "advances": sim.advances,
               "supersteps": sim.supersteps,
               "fetches": int(st.get("fetches", 0)),
               "blocking_fetches": int(st.get("blocking_fetches", 0)),
               "blocking_per_advance":
                   round(st.get("blocking_fetches", 0) / adv, 5),
               "host_block_ms": round(st.get("host_block_ms", 0), 1),
               "wall_ms": round(wall, 1),
               "spec_issued": sim.spec_issued,
               "spec_committed": sim.spec_committed,
               "spec_rolled_back": sim.spec_rolled_back,
               "spec_commit_rate":
                   round(sim.spec_committed / sim.spec_issued, 3)
                   if sim.spec_issued else None}
        rows.append(schema_row("pipeline", row, mode="solo",
                               platform="cpu"))
        log(f"[stage pipeline] solo depth={depth}: "
            f"{row['blocking_fetches']}/{row['fetches']} blocking, "
            f"{row['host_block_ms']} ms blocked, wall {row['wall_ms']}")
    consistent = all(streams[d] == streams[0] for d in streams)

    # -- fleet chunk with compact elem_w overrides ----------------------
    E = len(ev)
    specs = [ScenarioSpec(seed=s, bw_scale=1.0 + 0.01 * (s % 37),
                          elem_w={(5 * s) % E: 1.5, (5 * s + 2) % E: 0.5})
             for s in range(replicas)]
    camp = Campaign(ev, ec, ew, cb, sizes, specs, eps=1e-9,
                    dtype=np.float64, superstep=k)
    camp.run_batched(batch=replicas, pipeline=2)   # warm
    fleet_streams = {}
    for depth in (0, 1, 2):
        t0 = _time.perf_counter()
        res, st = camp.run_scoped(batch=replicas,
                                  stage=f"pipeline/fleet-d{depth}",
                                  pipeline=depth)
        wall = (_time.perf_counter() - t0) * 1e3
        adv = max(sum(r.advances for r in res), 1)
        fleet_streams[depth] = [(r.events, r.t) for r in res]
        dense = replicas * E * np.dtype(np.float64).itemsize
        row = {"bench": "lmm_pipeline", "workload": "fleet-wave",
               "n_c": n_c, "n_v": n_v, "seed": seed,
               "depth": depth, "superstep": k, "host_work_us": 0.0,
               "advances": int(adv),
               "fetches": int(st.get("fetches", 0)),
               "blocking_fetches": int(st.get("blocking_fetches", 0)),
               "blocking_per_advance":
                   round(st.get("blocking_fetches", 0) / adv, 6),
               "host_block_ms": round(st.get("host_block_ms", 0), 1),
               "wall_ms": round(wall, 1),
               "spec_issued": int(st.get("speculations_issued", 0)),
               "spec_committed":
                   int(st.get("speculations_committed", 0)),
               "spec_rolled_back":
                   int(st.get("speculations_rolled_back", 0)),
               "elem_w_payload_bytes":
                   int(st.get("uploaded_bytes_delta", 0)),
               "elem_w_dense_bytes": dense}
        rows.append(schema_row("pipeline", row, mode="fleet",
                               batch=replicas, platform="cpu"))
        log(f"[stage pipeline] fleet depth={depth}: "
            f"{row['blocking_fetches']}/{row['fetches']} blocking, "
            f"payload {row['elem_w_payload_bytes']}B vs dense "
            f"{dense}B")
    consistent = consistent and all(fleet_streams[d] == fleet_streams[0]
                                    for d in fleet_streams)
    for row in rows:
        row["events_consistent"] = consistent
    path = append_rows("lmm_pipeline.jsonl", rows)
    log(f"[stage pipeline] rows appended to {path} "
        f"(events_consistent={consistent})")

    out = {"rows": rows, "events_consistent": consistent}
    solo = {r["depth"]: r for r in rows if r["mode"] == "solo"}
    if solo.get(0, {}).get("blocking_fetches"):
        best = min(r["blocking_fetches"] for d, r in solo.items() if d)
        out["blocking_fetch_reduction"] = round(
            solo[0]["blocking_fetches"] / max(best, 1), 1)
    fleet = {r["depth"]: r for r in rows if r["mode"] == "fleet"}
    if fleet:
        f0 = fleet[0]
        out["elem_w_bytes_vs_dense"] = round(
            f0["elem_w_dense_bytes"]
            / max(f0["elem_w_payload_bytes"], 1), 1)
    return out


_FAT_TREE_64 = """<?xml version='1.0'?>
<platform version="4.1">
  <zone id="world" routing="Full">
    <cluster id="ft" prefix="node-" radical="0-63" suffix=""
             speed="1Gf" bw="125MBps" lat="50us" topology="FAT_TREE"
             topo_parameters="2;8,8;1,2;1,1"/>
  </zone>
</platform>
"""


def stage_phase(seed: int = 7, ranks: int = 64, rounds: int = 4,
                k: int = 16, min_flows: int = 32) -> dict:
    """NAS-style compute/comm alternation through the engine (the
    ISSUE-9 trajectory metric): every completion immediately posts its
    successor exec or comm, so the phase is a continuous stream of the
    mutations that used to invalidate the device plan.  Three modes
    over the identical seeded workload on the 64-host fat tree:

    * **device** — the full PR-9 path: transition payloads absorb the
      wake/send/exec churn, supersteps keep serving.
    * **transitions-off** — PR 6's fast path (``drain/transitions:off``):
      every mutation discards the plan, so coverage collapses to
      whatever pure-drain windows survive between completions.
    * **fastpath-off** — the native per-advance host loop.

    The headline is **coverage** (fastpath_advances per native-loop
    advance, from the opstats counters satellite 2 added): the
    acceptance bar is device >= 2x transitions-off.  Every row carries
    the invalidation-cause histogram, wall time and the event-stream
    consistency flag; rows append to bench_results/lmm_phase.jsonl."""
    _force_cpu()
    import tempfile
    import time as _time

    from simgrid_tpu import s4u
    from simgrid_tpu.ops import opstats

    plat = os.path.join(tempfile.mkdtemp(prefix="simgrid_phase_"),
                        "ft64.xml")
    with open(plat, "w") as f:
        f.write(_FAT_TREE_64)

    def run(cfg):
        s4u.Engine._reset()
        try:
            e = s4u.Engine(["phase"] + [f"--cfg={c}" for c in cfg])
            e.load_platform(plat)
            hosts = e.get_all_hosts()[:ranks]
            model = e.pimpl.network_model
            rng = np.random.default_rng(seed)
            dst = rng.integers(0, ranks, size=(ranks, rounds))
            sizes = rng.choice(np.linspace(2e5, 2e6, 12),
                               (ranks, rounds))
            flops = rng.choice(np.linspace(5e5, 5e6, 8),
                               (ranks, rounds))
            stage = [0] * ranks
            tag_of = {}
            events = []

            def post_next(r):
                st = stage[r]
                j = st // 2
                if j >= rounds:
                    return
                if st % 2 == 0:
                    d = int(dst[r, j])
                    if d == r:
                        d = (d + 1) % ranks
                    a = model.communicate(hosts[r], hosts[d],
                                          float(sizes[r, j]), -1.0)
                else:
                    a = hosts[r].cpu.execution_start(float(flops[r, j]))
                tag_of[id(a)] = (r, st)
                stage[r] = st + 1

            for r in range(ranks):
                post_next(r)
            t0 = _time.perf_counter()
            for _ in range(200_000):
                if not any(len(m.started_action_set)
                           for m in e.pimpl.models):
                    break
                e.pimpl.surf_solve(-1.0)
                for m in list(e.pimpl.models):
                    while True:
                        done = m.extract_done_action()
                        if done is None:
                            break
                        t = tag_of.pop(id(done), None)
                        if t is not None:
                            events.append((done.finish_time, t))
                            post_next(t[0])
                        done.unref()
            wall = (_time.perf_counter() - t0) * 1e3
            return events, e.pimpl.now, wall
        finally:
            s4u.Engine._reset()

    base = ["network/optim:Full", "network/maxmin-selective-update:no",
            "lmm/backend:jax"]
    fast = base + ["drain/fastpath:auto",
                   f"drain/min-flows:{min_flows}",
                   f"drain/superstep:{k}"]
    modes = {
        "device": fast,
        "transitions-off": fast + ["drain/transitions:off"],
        "fastpath-off": base + ["drain/fastpath:off"],
    }
    run(modes["device"])               # warm the jits once, unscoped
    rows, streams, coverage = [], {}, {}
    cause_keys = ("transition", "partial_advance", "profile_event",
                  "stall", "unrecognized")
    for mode, cfg in modes.items():
        before = opstats.snapshot()
        events, t_end, wall = run(cfg)
        d = opstats.diff(before)
        fp = int(d.get("fastpath_advances", 0))
        nat = int(d.get("native_advances", 0))
        coverage[mode] = round(fp / max(nat, 1), 3)
        streams[mode] = (events, t_end)
        row = {"bench": "lmm_phase", "workload": "nas-alternation",
               "ranks": ranks, "rounds": rounds, "seed": seed,
               "superstep": k, "min_flows": min_flows,
               "events": len(events), "wall_ms": round(wall, 1),
               "fastpath_advances": fp, "native_advances": nat,
               "coverage": coverage[mode],
               "drain_transitions": int(d.get("drain_transitions", 0)),
               "drain_transition_slots":
                   int(d.get("drain_transition_slots", 0))}
        for key in cause_keys:
            row[f"cause_{key}"] = int(d.get(f"drain_cause_{key}", 0))
        rows.append(schema_row("phase", row, mode=mode, platform="cpu"))
        log(f"[stage phase] {mode}: {len(events)} events, "
            f"fp/native {fp}/{nat} (coverage {coverage[mode]}), "
            f"wall {row['wall_ms']} ms")
    consistent = all(streams[m] == streams["fastpath-off"]
                     for m in streams)
    for row in rows:
        row["events_consistent"] = consistent
    path = append_rows("lmm_phase.jsonl", rows)
    log(f"[stage phase] rows appended to {path} "
        f"(events_consistent={consistent})")

    out = {"rows": rows, "events_consistent": consistent,
           "coverage": coverage}
    if coverage.get("transitions-off"):
        out["coverage_vs_pr6"] = round(
            coverage["device"] / max(coverage["transitions-off"], 1e-9),
            1)
    return out


def _serve_specs(scenarios: int, faults: float = 0.25):
    """The replayed serving sweep: deterministic bw/size scaling
    families with a seeded fault stripe — structured enough that the
    surrogate trained on the cold pass's device results can answer
    the warm replay from its conformal predictor."""
    from simgrid_tpu.parallel.campaign import ScenarioSpec
    n_fault = int(round(scenarios * faults))
    return [ScenarioSpec(seed=s, bw_scale=1.0 + 0.1 * (s % 5),
                         size_scale=1.0 + 0.05 * (s % 3),
                         fault_mtbf=400.0 if s < n_fault else None,
                         fault_mttr=50.0, fault_horizon=600.0,
                         label=f"serve{s}")
            for s in range(scenarios)]


def stage_serve_phase(n_c: int, n_v: int, deg: int, seed: int,
                      scenarios: int, batch: int, superstep: int,
                      phase: str, cache_dir: str) -> dict:
    """One serving-process lifetime (cold start or warm restart)
    against a shared on-disk AOT plan cache + surrogate corpus: build
    the plan, stand up a CampaignService, submit ``scenarios`` what-if
    queries and drain.  The warm phase seeds its surrogate from the
    cold phase's corpus log and resubmits every 8th query with
    ``exact=True`` so the device path (and therefore the disk plan
    cache) is exercised even when the surrogate answers the rest."""
    _force_cpu()
    from simgrid_tpu.parallel.campaign import ScenarioPlan
    from simgrid_tpu.serving import (CampaignService, PlanCache,
                                     RuntimeSurrogate)

    rng = np.random.default_rng(seed)
    arrays = build_arrays(rng, n_c, n_v, deg, np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), n_v)
    plan = ScenarioPlan(arrays.e_var[:E], arrays.e_cnst[:E],
                        arrays.e_w[:E], arrays.c_bound[:n_c], sizes,
                        eps=1e-9, superstep=superstep, fault_mode="on")
    cache = PlanCache(cache_dir)
    corpus_log = os.path.join(cache_dir, "serve_corpus.jsonl")
    surrogate = RuntimeSurrogate()
    corpus_rows = (surrogate.load_corpus(corpus_log)
                   if phase == "warm" else 0)
    svc = CampaignService(plan, batch=batch, plan_cache=cache,
                          surrogate=surrogate, corpus_log=corpus_log)
    specs = _serve_specs(scenarios)
    exact_every = 8 if phase == "warm" else 0
    t0 = time.perf_counter()
    tickets = [svc.submit(spec, exact=bool(exact_every
                                           and i % exact_every == 0))
               for i, spec in enumerate(specs)]
    svc.drain()
    wall_ms = (time.perf_counter() - t0) * 1e3
    lat = sorted(t.latency_ms for t in tickets
                 if t.latency_ms is not None)

    def pct(q):
        return round(lat[min(len(lat) - 1,
                             int(round(q * (len(lat) - 1))))], 3)

    first = min((t.done_at for t in tickets if t.done_at is not None),
                default=None)
    counters = svc.counters()
    payload = {"bench": "lmm_serve", "phase": phase, "n_c": n_c,
               "n_v": n_v, "scenarios": scenarios,
               "superstep": superstep, "corpus_rows": corpus_rows,
               "wall_ms": round(wall_ms, 1),
               "submit_to_first_result_ms": (
                   None if first is None
                   else round((first - t0) * 1e3, 3)),
               "latency_p50_ms": pct(0.50),
               "latency_p99_ms": pct(0.99),
               "surrogate_hit_rate": round(
                   counters["surrogate_answers"] / max(scenarios, 1),
                   4),
               "result_errors": sum(
                   1 for t in tickets
                   if t.result is not None and t.result.error)}
    payload.update({k: (round(v, 1) if isinstance(v, float)
                        else int(v))
                    for k, v in counters.items()})
    return payload


def stage_serve(args) -> dict:
    """Cold start vs warm restart of the always-on campaign service
    (simgrid_tpu/serving): the cold phase traces + AOT-compiles every
    fleet program and serves all 256 queries on device (seeding the
    surrogate corpus); the warm phase runs in a FRESH subprocess
    sharing only the on-disk plan cache + corpus — an honest process
    restart — and must show plan_compile_ms == 0, plan_cache_hits > 0
    and a majority-surrogate hit rate.  Rows land in
    bench_results/lmm_serve.jsonl."""
    import tempfile
    cache_dir = args.serve_cache or tempfile.mkdtemp(
        prefix="lmm_serve_")
    if args.serve_phase:
        return stage_serve_phase(args.n_c, args.n_v, args.deg,
                                 args.seed, args.scenarios,
                                 args.serve_batch, args.superstep,
                                 args.serve_phase, cache_dir)
    out = {}
    for phase in ("cold", "warm"):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--stage", "serve", "--serve-phase", phase,
               "--serve-cache", cache_dir,
               "--n_c", str(args.n_c), "--n_v", str(args.n_v),
               "--deg", str(args.deg), "--seed", str(args.seed),
               "--scenarios", str(args.scenarios),
               "--serve-batch", str(args.serve_batch),
               "--superstep", str(args.superstep)]
        log(f"[stage serve] {phase}: {' '.join(cmd[2:])}")
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=1800,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(
                f"serve {phase} phase failed rc={proc.returncode}")
        out[phase] = json.loads(proc.stdout.strip().splitlines()[-1])
    cold, warm = out["cold"], out["warm"]
    speed = {}
    for key, name in (("submit_to_first_result_ms",
                       "warm_speedup_first_result"),
                      ("latency_p50_ms", "warm_speedup_p50")):
        if cold.get(key) and warm.get(key) is not None:
            speed[name] = round(cold[key] / max(warm[key], 1e-9), 1)
    warm.update(speed)
    rows = [schema_row("serve", out[phase], mode=phase,
                       batch=args.serve_batch, platform="cpu")
            for phase in ("cold", "warm")]
    path = append_rows("lmm_serve.jsonl", rows)
    log(f"[stage serve] rows appended to {path}")
    return {"cold": cold, "warm": warm, **speed}


def stage_resume(args) -> dict:
    """Preemption-safe campaign overhead (ISSUE 12): (a) checkpoint
    cost — an uninterrupted drain vs the same drain writing a
    FleetCheckpoint every 2 committed supersteps (wall delta,
    per-checkpoint milliseconds, artifact bytes); (b) the preemption
    gap — a drain KILLED at the halfway collect boundary, the service
    discarded, and a fresh one rebuilt with CampaignService.resume
    over a fresh PlanCache sharing only the on-disk artifact store (a
    restarted process in spirit), timed from token load to last
    ticket.  Every leg must stay bit-identical to the uninterrupted
    run.  Rows land in bench_results/lmm_resume.jsonl."""
    _force_cpu()
    import tempfile
    from simgrid_tpu.ops import opstats
    from simgrid_tpu.parallel.campaign import ScenarioPlan
    from simgrid_tpu.serving import CampaignService, PlanCache

    rng = np.random.default_rng(args.seed)
    arrays = build_arrays(rng, args.n_c, args.n_v, args.deg,
                          np.float64)
    E = arrays.n_elem
    sizes = rng.choice(np.linspace(1e5, 2e6, 16), args.n_v)
    plan = ScenarioPlan(arrays.e_var[:E], arrays.e_cnst[:E],
                        arrays.e_w[:E], arrays.c_bound[:args.n_c],
                        sizes, eps=1e-9, superstep=args.superstep,
                        fault_mode="on")
    specs = _serve_specs(args.scenarios)
    workdir = tempfile.mkdtemp(prefix="lmm_resume_")
    plan_dir = os.path.join(workdir, "plans")

    def run(cache, **drain_kw):
        svc = CampaignService(plan, batch=args.serve_batch,
                              plan_cache=cache)
        svc.submit_many(specs, exact=True)
        t0 = time.perf_counter()
        svc.drain(**drain_kw)
        return svc, (time.perf_counter() - t0) * 1e3

    def digest(svc):
        return {t.spec.label: (tuple(map(tuple, t.result.events or ())),
                               tuple(map(tuple,
                                         t.result.fault_events or ())),
                               t.result.t)
                for t in svc.completed if t.result is not None}

    # leg 0: warmup — populate the disk plan cache so every timed leg
    # below runs warm and the cadence comparison is compile-free
    run(PlanCache(plan_dir))

    # leg 1: uninterrupted baseline
    base_svc, base_ms = run(PlanCache(plan_dir))
    ref = digest(base_svc)
    base_steps = base_svc.supersteps

    # leg 2: checkpoint cadence overhead
    ck = os.path.join(workdir, "cadence")
    before = opstats.snapshot()
    ck_svc, ck_ms = run(PlanCache(plan_dir), checkpoint_every=2,
                        checkpoint_path=ck)
    d = opstats.diff(before)
    n_ckpt = int(d.get("fleet_checkpoints", 0))
    ckpt_bytes = (os.path.getsize(ck)
                  + os.path.getsize(ck + ".fleet.npz"))
    cadence_identical = digest(ck_svc) == ref

    # leg 3: kill at the halfway boundary, resume in a fresh service
    kill_at = max(1, base_steps // 2)
    ck2 = os.path.join(workdir, "kill")
    kill_svc, _ = run(PlanCache(plan_dir), stop_after=kill_at,
                      checkpoint_path=ck2)
    killed_with_fleet = kill_svc._fleet is not None
    del kill_svc
    warm = PlanCache(plan_dir)
    t0 = time.perf_counter()
    back = CampaignService.resume(ck2, plan_cache=warm)
    resume_ms = (time.perf_counter() - t0) * 1e3
    n_done = len(back.completed)
    back.drain()
    finish_ms = (time.perf_counter() - t0) * 1e3
    resume_identical = digest(back) == ref

    payload = {"bench": "lmm_resume", "n_c": args.n_c,
               "n_v": args.n_v, "scenarios": args.scenarios,
               "superstep": args.superstep,
               "supersteps": base_steps, "kill_at": kill_at,
               "killed_with_fleet": killed_with_fleet,
               "base_wall_ms": round(base_ms, 1),
               "cadence_wall_ms": round(ck_ms, 1),
               "checkpoints": n_ckpt,
               "checkpoint_ms_total": round(
                   d.get("checkpoint_ms", 0.0), 2),
               "checkpoint_ms_each": round(
                   d.get("checkpoint_ms", 0.0) / max(n_ckpt, 1), 2),
               "checkpoint_bytes": int(ckpt_bytes),
               "checkpoint_overhead_pct": round(
                   100.0 * (ck_ms - base_ms) / max(base_ms, 1e-9), 1),
               "resume_rebuild_ms": round(resume_ms, 2),
               "resume_finish_ms": round(finish_ms, 1),
               "restored_tickets": n_done,
               "plan_cache_misses_on_resume": warm.misses,
               "cadence_bit_identical": cadence_identical,
               "resume_bit_identical": resume_identical}
    rows = [schema_row("resume", payload, batch=args.serve_batch,
                       platform="cpu")]
    path = append_rows("lmm_resume.jsonl", rows)
    log(f"[stage resume] rows appended to {path} "
        f"(cadence_bit_identical={cadence_identical}, "
        f"resume_bit_identical={resume_identical})")
    return payload


STAGES = {
    "probe": lambda args: stage_probe(),
    "dev": lambda args: stage_device(args.n_c, args.n_v, args.deg,
                                     args.seed, args.cpu, args.reps,
                                     args.dtype),
    "host": lambda args: stage_host(args.n_c, args.n_v, args.deg,
                                    args.seed),
    "native": lambda args: stage_native(args.n_c, args.n_v, args.deg,
                                        args.seed),
    "churn": lambda args: stage_churn(args.n_v, args.seed, args.cpu,
                                      args.mode, args.clusters,
                                      args.chain, args.churn, args.steps),
    "sweep": lambda args: stage_sweep(args.n_c, args.n_v, args.deg,
                                      args.seed, args.replicas,
                                      args.superstep),
    "pipeline": lambda args: stage_pipeline(args.seed, args.superstep,
                                            args.host_work_us,
                                            replicas=args.replicas),
    "phase": lambda args: stage_phase(args.seed, args.ranks,
                                      args.rounds, args.superstep,
                                      args.min_flows),
    "shard": lambda args: stage_shard(args.n_c, args.n_v, args.deg,
                                      args.seed, args.per_shard,
                                      args.superstep, args.mesh),
    "collective": lambda args: stage_collective(args.seed,
                                                args.superstep),
    "fault": lambda args: stage_fault(args.n_c, args.n_v, args.deg,
                                      args.seed, args.replicas,
                                      args.superstep),
    "serve": lambda args: stage_serve(args),
    "resume": lambda args: stage_resume(args),
}


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------

def run_stage(stage: str, timeout: float, errors: dict, cpu=False,
              **params) -> dict | None:
    """Run one stage in a subprocess; None (+ an errors entry) on any
    failure so later stages still run."""
    cmd = [sys.executable, os.path.abspath(__file__), "--stage", stage]
    for k, v in params.items():
        cmd += [f"--{k}", str(v)]
    if cpu:
        cmd += ["--cpu"]
    sysname = (f"{params.get('n_c', '?')}x{params['n_v']}"
               if "n_v" in params else "")
    label = (f"{stage}({sysname}"
             f"{',cpu' if cpu else ''}"
             f"{',' + str(params['dtype']) if 'dtype' in params else ''})")
    log(f"[bench] {label}: {' '.join(cmd[2:])}")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired as exc:
        # Preserve whatever the child already measured (its stderr carries
        # the per-strategy partial numbers).
        for stream in (exc.stderr, exc.stdout):
            if stream:
                sys.stderr.write(stream if isinstance(stream, str)
                                 else stream.decode(errors="replace"))
        errors[label] = f"timeout after {timeout}s"
        log(f"[bench] {label}: TIMEOUT {timeout}s")
        return None
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        errors[label] = f"rc={proc.returncode}: {' | '.join(tail)}"
        log(f"[bench] {label}: FAILED rc={proc.returncode}")
        return None
    try:
        out = json.loads(proc.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError) as exc:
        errors[label] = f"bad stage output: {exc}"
        return None
    log(f"[bench] {label}: {out}")
    return out


def probe_accel(errors: dict, tries: int = 3, wait_s: float = 20.0):
    """Probe the accelerator with retries: a tunneled TPU can be
    transiently wedged, and three rounds of benches died on a single
    unlucky probe (BENCH_r01..r03).  Called again before every device
    stage — the chip's health at bench START says nothing about its
    health twenty minutes in."""
    for i in range(tries):
        probe = run_stage("probe", timeout=120, errors=errors)
        if probe is not None:
            return probe
        if i + 1 < tries:
            log(f"[bench] probe attempt {i + 1} failed; "
                f"retrying in {wait_s:.0f}s")
            time.sleep(wait_s)
    return None


def main() -> None:
    errors: dict = {}
    detail: dict = {}

    probe = probe_accel(errors)
    platform = probe["platform"] if probe else "unavailable"
    accel = probe is not None and platform != "cpu"
    if probe is None:
        log("[bench] accelerator unusable; device stages fall back to CPU")
    detail["platform"] = platform if accel else "cpu"

    # --- headline: 100k flows over 16k links, 4 links per flow ---------
    # The device stage runs on BOTH backends: the solver dispatches by
    # system size in production, so the honest headline is the best
    # backend for the class (TPU at 100k, CPU for the small classes
    # where the ~70ms tunnel round-trip dominates).
    big100k = dict(n_c=16384, n_v=100_000, deg=4, seed=42, reps=3)
    dev100k = None
    if accel:   # the initial probe just succeeded; no need to re-probe
        dev100k = run_stage("dev", timeout=2400, errors=errors,
                            cpu=False, **big100k)
    dev100k_cpu = run_stage("dev", timeout=2400, errors=errors, cpu=True,
                            **big100k)
    # chip-precision solve on the CPU backend: the production fast path
    # for hosts without an accelerator (lmm/dtype:float32), ~2.5-5x the
    # f64 throughput on the same XLA kernels
    dev100k_cpu32 = run_stage("dev", timeout=2400, errors=errors, cpu=True,
                              dtype="f32", **big100k)
    if dev100k:
        detail["dev_100k"] = dev100k
    if dev100k_cpu:
        detail["dev_100k_cpu"] = dev100k_cpu
    if dev100k_cpu32:
        detail["dev_100k_cpu_f32"] = dev100k_cpu32

    def best_ms(*stage_outs):
        cands = [v for out in stage_outs if out
                 for k, v in out.items() if k.startswith("ms_")]
        return min(cands) if cands else None

    # --- speedup vs exact host solver on maxmin_bench classes ----------
    # big/huge mirror the reference harness's own classes
    # (teshsuite/surf/maxmin_bench/maxmin_bench.cpp:110-129); giant
    # scales the same generator to the BASELINE target scale (100k+
    # concurrent flows), where the sequential solver's round count
    # keeps growing with system size but the local-rounds device
    # solve stays at ~14 rounds.
    classes = [("big 2000x2000", dict(n_c=2000, n_v=2000, deg=3, seed=1)),
               ("huge 20000x20000", dict(n_c=20000, n_v=20000, deg=3,
                                         seed=2)),
               ("giant 100000x100000", dict(n_c=100_000, n_v=100_000,
                                            deg=3, seed=3))]
    speedup = None
    speedup_class = None
    host_slow = False
    for name, params in classes:
        # Baseline = the native C++ solver (the honest stand-in for the
        # reference's maxmin.cpp); the Python host solver is measured as
        # a secondary column and is only the fallback denominator.
        native = run_stage("native", timeout=600, errors=errors, **params)
        host = None
        if not host_slow:
            host = run_stage("host", timeout=600, errors=errors, **params)
            if host is None or host["ms"] > 6_000:
                host_slow = True  # next class is ~100x: skip its host stage
        if native is None and host is None:
            break
        dev_acc = None
        if accel and probe_accel(errors, tries=2) is not None:
            dev_acc = run_stage("dev", timeout=900, errors=errors,
                                cpu=False, reps=5, **params)
        dev = run_stage("dev", timeout=900, errors=errors, cpu=True,
                        reps=5, **params)
        dev32 = run_stage("dev", timeout=900, errors=errors, cpu=True,
                          dtype="f32", reps=5, **params)
        detail[name] = {"host_ms": host["ms"] if host else "skipped",
                        "native_ms": native["ms"] if native else "failed",
                        "dev": dev if dev else "failed"}
        if dev_acc:
            detail[name]["dev_accel"] = dev_acc
        if dev32:
            detail[name]["dev_f32"] = dev32
        dev_ms = best_ms(dev, dev_acc, dev32)
        if dev_ms:
            base_ms = native["ms"] if native else host["ms"]
            speedup = round(base_ms / dev_ms, 2) if dev_ms > 0 else None
            speedup_class = name + ("" if native else " (vs host python)")
            # honesty: the accelerator-only ratio is reported alongside
            # the best-backend number, so a CPU-carried headline can
            # never mask a TPU gap (VERDICT r4 weakness #1)
            acc_ms = best_ms(dev_acc)
            if acc_ms and native:
                speedup_tpu = round(native["ms"] / acc_ms, 2)
                detail[name]["vs_baseline_tpu"] = speedup_tpu

    value = best_ms(dev100k, dev100k_cpu, dev100k_cpu32)
    # the reported platform is the backend the headline number actually
    # came from — a dead TPU stage must not attribute the CPU fallback
    # latency to the accelerator
    if value is not None and value != best_ms(dev100k):
        detail["platform"] = "cpu"
    detail["headline_platform"] = detail["platform"]

    # --- incremental churn: warm-started selective solves --------------
    # 100k flows, 1% retired+replaced between solves, against a deep
    # background chain the churn never touches.  The trajectory metric:
    # warm-started modified-component restarts vs cold full restarts
    # (fixpoint rounds) and indexed delta uploads vs whole-field
    # re-uploads (bytes/solve).  Rows land in
    # bench_results/lmm_churn.jsonl for the record.
    churn_rows = []
    churn_params = dict(n_v=100_000, seed=42)
    for mode in ("legacy-subset", "cold-full", "cold-delta",
                 "warm-selective"):
        row = run_stage("churn", timeout=1800, errors=errors, cpu=True,
                        mode=mode, **churn_params)
        if row:
            row["bench"] = "lmm_churn"
            churn_rows.append(schema_row("churn", row, mode=mode,
                                         platform="cpu"))
    if churn_rows:
        append_rows("lmm_churn.jsonl", churn_rows)
        detail["lmm_churn"] = churn_rows
        by_mode = {r["mode"]: r for r in churn_rows}
        cold, warm = by_mode.get("cold-full"), by_mode.get("warm-selective")
        if cold and warm and warm.get("rounds_med"):
            detail["churn_rounds_cold_over_warm"] = round(
                cold["rounds_med"] / max(warm["rounds_med"], 1), 1)

    # --- batched multi-replica campaigns (ops.lmm_batch) ---------------
    # one shared platform flattening, 64 mixed fault/sweep scenarios,
    # fleet batch sizes {1, 8, 64}: the per-replica dispatch and upload
    # amortization rows land in bench_results/lmm_batch.jsonl (the
    # sweep stage writes them itself, schema-stable)
    sweep = run_stage("sweep", timeout=1800, errors=errors,
                      n_c=96, n_v=400, deg=3, seed=42, replicas=64,
                      superstep=8)
    if sweep:
        detail["lmm_batch_sweep"] = sweep

    # --- speculative pipelined drain (ops.lmm_drain pipeline=D) --------
    # blocking fetches per advance, pipelined vs superstep-only at
    # equal K, with speculation commit rate and the indexed elem_w
    # payload bytes; rows land in bench_results/lmm_pipeline.jsonl
    pipeline = run_stage("pipeline", timeout=1800, errors=errors,
                         seed=42, replicas=64, superstep=8)
    if pipeline:
        detail["lmm_pipeline"] = pipeline

    # --- device-resident mutating phases (ops.drain_path transitions) --
    # NAS-style compute/comm alternation through the engine: coverage
    # (fastpath vs native advances) for the transition-payload path vs
    # PR 6's invalidate-on-mutation fast path vs the native loop; rows
    # land in bench_results/lmm_phase.jsonl
    phase = run_stage("phase", timeout=1800, errors=errors,
                      seed=7, ranks=64, rounds=4, superstep=16)
    if phase:
        detail["lmm_phase"] = phase
        if phase.get("coverage_vs_pr6") is not None:
            detail["phase_coverage_vs_pr6"] = phase["coverage_vs_pr6"]

    # --- device fault event tapes (ops.lmm_drain tape=) ----------------
    # one fleet per fault mode (off / static / tape / tape+pipeline):
    # fires, speculative replays and per-replica dispatch structure;
    # rows land in bench_results/lmm_fault.jsonl
    fault = run_stage("fault", timeout=1800, errors=errors,
                      n_c=96, n_v=400, deg=3, seed=42, replicas=32,
                      superstep=8)
    if fault:
        detail["lmm_fault"] = fault

    # --- collective schedule tapes (simgrid_tpu/collectives) -----------
    # host-maestro vs tape-driven allreduce at 64/256/1k ranks:
    # dispatches per collective step, upload bytes, event streams
    # bit-identical; rows land in bench_results/lmm_collective.jsonl
    collective = run_stage("collective", timeout=3600, errors=errors,
                           seed=42, superstep=16)
    if collective:
        detail["lmm_collective"] = collective
        detail["collective_dispatch_ratio"] = \
            collective.get("min_dispatch_ratio")

    # --- always-on campaign service (simgrid_tpu/serving) --------------
    # cold start vs warm restart over a shared disk plan cache +
    # surrogate corpus; rows land in bench_results/lmm_serve.jsonl
    serve = run_stage("serve", timeout=3600, errors=errors,
                      n_c=96, n_v=400, deg=3, seed=42,
                      scenarios=256, superstep=8)
    if serve:
        detail["lmm_serve"] = serve
        if serve.get("warm_speedup_first_result") is not None:
            detail["serve_warm_speedup"] = \
                serve["warm_speedup_first_result"]

    # mergeable per-class solve rows for the record (same schema as the
    # churn/sweep files: bench_results/*.jsonl concatenate across PRs)
    solve_rows = []
    for name, cls in detail.items():
        if not (isinstance(cls, dict) and "native_ms" in cls):
            continue
        solve_rows.append(schema_row(
            "solve", {"class": name, "host_ms": cls.get("host_ms"),
                      "native_ms": cls.get("native_ms"),
                      "dev": cls.get("dev"),
                      "dev_f32": cls.get("dev_f32"),
                      "dev_accel": cls.get("dev_accel")},
            mode="maxmin-class", platform=detail["platform"]))
    if solve_rows:
        append_rows("lmm_solve.jsonl", solve_rows)

    # committed end-to-end drain results (tools/e2e_drain.py, run
    # separately because the native baseline alone takes ~an hour):
    # full config-#4 simulations to completion, with event-order
    # equality checked across backends
    e2e_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_results", "e2e_drain.jsonl")
    if os.path.exists(e2e_path):
        rows = []
        for line in open(e2e_path):
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if r.get("flows") == 100_000 and "wall_s" in r:
                rows.append({k: r.get(k) for k in
                             ("backend", "jax_platform", "workload",
                              "advances", "wall_s", "t_sim",
                              "n_events", "rounds", "mode",
                              "superstep_k", "syncs",
                              "syncs_per_advance")})
        if rows:
            detail["e2e_drain_100k"] = rows

    # top-level accelerator-only ratio for the largest class that has
    # both a native and an accelerator measurement
    vs_tpu = None
    for name, _ in reversed(classes):
        cls = detail.get(name)
        if isinstance(cls, dict) and "vs_baseline_tpu" in cls:
            vs_tpu = cls["vs_baseline_tpu"]
            detail["vs_baseline_tpu_class"] = name
            break

    result = {
        "metric": (f"LMM solve latency @{big100k['n_v']} flows on "
                   f"{detail['platform']} (vs_baseline: speedup over native "
                   f"C++ maxmin solver, {speedup_class or 'n/a'} class)"),
        "value": value,
        "unit": "ms",
        "vs_baseline": speedup,
        "vs_baseline_tpu": vs_tpu,
        "detail": detail,
    }
    if errors:
        result["errors"] = errors
    print(json.dumps(result))


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--stage", choices=sorted(STAGES))
    parser.add_argument("--n_c", type=int, default=100)
    parser.add_argument("--n_v", type=int, default=100)
    parser.add_argument("--deg", type=int, default=3)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU JAX backend")
    parser.add_argument("--mode", default="warm-selective",
                        help="churn stage: legacy-subset | cold-full | "
                        "cold-delta | warm-selective")
    parser.add_argument("--replicas", type=int, default=64,
                        help="sweep stage: scenario fleet size")
    parser.add_argument("--superstep", type=int, default=8,
                        help="sweep/pipeline stages: advances per "
                        "drain dispatch")
    parser.add_argument("--per-shard", type=int, default=16,
                        dest="per_shard",
                        help="shard stage: replicas per device (fleet "
                        "B = per_shard * mesh size)")
    parser.add_argument("--mesh", type=int, default=4,
                        help="shard stage: largest mesh size swept "
                        "(powers of two from 1; forces the virtual "
                        "CPU device count)")
    parser.add_argument("--ranks", type=int, default=64,
                        help="phase stage: alternating actors (<= 64 "
                        "fat-tree hosts)")
    parser.add_argument("--rounds", type=int, default=4,
                        help="phase stage: comm+exec pairs per rank")
    parser.add_argument("--min-flows", type=int, default=32,
                        dest="min_flows",
                        help="phase stage: drain/min-flows eligibility "
                        "floor for the fast path")
    parser.add_argument("--host-work-us", type=float, default=500.0,
                        dest="host_work_us",
                        help="pipeline stage: emulated per-advance "
                        "host bookkeeping (µs) the speculative "
                        "dispatch overlaps; recorded on every row")
    parser.add_argument("--scenarios", type=int, default=256,
                        help="serve stage: queries submitted to the "
                        "campaign service")
    parser.add_argument("--serve-batch", type=int, default=16,
                        dest="serve_batch",
                        help="serve stage: resident fleet width")
    parser.add_argument("--serve-phase", choices=["cold", "warm"],
                        default=None, dest="serve_phase",
                        help="serve stage internal: run ONE service "
                        "process lifetime against --serve-cache "
                        "(the orchestrating invocation spawns both)")
    parser.add_argument("--serve-cache", default=None,
                        dest="serve_cache",
                        help="serve stage: shared AOT plan-cache + "
                        "corpus directory (default: fresh tempdir)")
    parser.add_argument("--clusters", type=int, default=960)
    parser.add_argument("--chain", type=int, default=96)
    parser.add_argument("--churn", type=float, default=0.01)
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--dtype", choices=["auto", "f32", "f64"],
                        default="auto",
                        help="solve precision (auto: f32 on TPU, f64 on "
                        "CPU)")
    args = parser.parse_args()
    if args.stage:
        print(json.dumps(STAGES[args.stage](args)))
    else:
        main()
