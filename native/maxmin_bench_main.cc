// maxmin_bench replica: drives the native solver through the exact same
// system-construction protocol as the reference's benchmark
// (/root/reference/teshsuite/surf/maxmin_bench/maxmin_bench.cpp:37-129) —
// same LCG (Lehmer 16807 mod 2^31-1, seeded per iteration), same four
// classes (small/medium/big/huge), same concurrency-limit draws — so the
// timed solves run on structurally identical systems and the numbers are
// comparable across the reference, this native solver, the Python host
// solver and the JAX backends (see BASELINE_MEASURED.md).
//
// Usage: maxmin_bench <small|medium|big|huge> <count> [test|perf]

#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
void* lmm_system_new(double precision);
void lmm_system_free(void* sys);
int32_t lmm_constraint_new(void* sys, double bound);
void lmm_constraint_set_limit(void* sys, int32_t c, int32_t limit);
int32_t lmm_variable_new(void* sys, double penalty, double bound);
void lmm_variable_set_share(void* sys, int32_t v, int32_t share);
void lmm_expand(void* sys, int32_t c, int32_t v, double w);
void lmm_expand_add(void* sys, int32_t c, int32_t v, double w);
void lmm_variable_free(void* sys, int32_t v);
void lmm_solve(void* sys);
double lmm_variable_value(void* sys, int32_t v);
}

static int64_t seedx = 0;
static double date_us = 0;

static int myrand() {
  seedx = seedx * 16807 % 2147483647;
  return static_cast<int32_t>(seedx % 1000);
}

static double float_random(double max) {
  constexpr double MYRANDMAX = 1000.0;
  return ((max * myrand()) / (MYRANDMAX + 1.0));
}

static unsigned int int_random(int max) {
  return static_cast<uint32_t>(float_random(max));
}

static void test(int nb_cnst, int nb_var, int nb_elem,
                 unsigned pw_base_limit, unsigned pw_max_limit,
                 float rate_no_limit, int max_share, int mode) {
  std::vector<int32_t> cnst(nb_cnst);
  std::vector<int32_t> var(nb_var);
  std::vector<int> used(nb_cnst);

  void* sys = lmm_system_new(1e-5 /* maxmin/precision default */);

  for (int i = 0; i < nb_cnst; i++) {
    cnst[i] = lmm_constraint_new(sys, float_random(10.0));
    int l;
    if (rate_no_limit > float_random(1.0))
      l = -1;
    else
      l = (1 << pw_base_limit) + (1 << int_random(static_cast<int>(pw_max_limit)));
    lmm_constraint_set_limit(sys, cnst[i], l);
  }

  for (int i = 0; i < nb_var; i++) {
    var[i] = lmm_variable_new(sys, 1.0, -1.0);
    int concurrency_share = 1 + static_cast<int>(int_random(max_share));
    lmm_variable_set_share(sys, var[i], concurrency_share);

    for (int j = 0; j < nb_cnst; j++)
      used[j] = 0;
    for (int j = 0; j < nb_elem; j++) {
      int k = static_cast<int>(int_random(nb_cnst));
      if (used[k] >= concurrency_share) {
        j--;
        continue;
      }
      lmm_expand(sys, cnst[k], var[i], float_random(1.5));
      lmm_expand_add(sys, cnst[k], var[i], float_random(1.5));
      used[k]++;
    }
  }

  fprintf(stderr, "Starting to solve(%i)\n", myrand() % 1000);
  auto t0 = std::chrono::steady_clock::now();
  lmm_solve(sys);
  auto t1 = std::chrono::steady_clock::now();
  date_us = std::chrono::duration<double, std::micro>(t1 - t0).count();

  if (mode == 1) {
    // "test" mode: print a few variable values for cross-checking.
    for (int i = 0; i < nb_var && i < 16; i++)
      printf("var %d = %.9g\n", i, lmm_variable_value(sys, var[i]));
  }

  for (int i = 0; i < nb_var; i++)
    lmm_variable_free(sys, var[i]);
  lmm_system_free(sys);
}

static unsigned TestClasses[][4] = {
    // Nbcnst Nbvar Baselimit Maxlimit
    {10, 10, 1, 2},        // small
    {100, 100, 3, 6},      // medium
    {2000, 2000, 5, 8},    // big
    {20000, 20000, 7, 10}  // huge
};

int main(int argc, char** argv) {
  float rate_no_limit = 0.2f;
  double acc_date = 0, acc_date2 = 0;
  int testclass;

  if (argc < 3) {
    fprintf(stderr, "Syntax: <small|medium|big|huge> <count> [test|perf]\n");
    return -1;
  }
  if (!strcmp(argv[1], "small"))
    testclass = 0;
  else if (!strcmp(argv[1], "medium"))
    testclass = 1;
  else if (!strcmp(argv[1], "big"))
    testclass = 2;
  else if (!strcmp(argv[1], "huge"))
    testclass = 3;
  else {
    fprintf(stderr, "Unknown class \"%s\", aborting!\n", argv[1]);
    return -2;
  }

  int testcount = atoi(argv[2]);
  int mode = 0;
  if (argc >= 4 && strcmp(argv[3], "test") == 0)
    mode = 1;
  if (argc >= 4 && strcmp(argv[3], "perf") == 0)
    mode = 3;

  unsigned nb_cnst = TestClasses[testclass][0];
  unsigned nb_var = TestClasses[testclass][1];
  unsigned pw_base_limit = TestClasses[testclass][2];
  unsigned pw_max_limit = TestClasses[testclass][3];
  unsigned max_share = 2;
  unsigned nb_elem = (1 << pw_base_limit) + (1 << (8 * pw_max_limit / 10));

  for (int i = 0; i < testcount; i++) {
    seedx = i + 1;
    fprintf(stderr, "Starting %i: (%i)\n", i, myrand() % 1000);
    test(static_cast<int>(nb_cnst), static_cast<int>(nb_var),
         static_cast<int>(nb_elem), pw_base_limit, pw_max_limit,
         rate_no_limit, static_cast<int>(max_share), mode);
    acc_date += date_us;
    acc_date2 += date_us * date_us;
    if (mode == 3)
      fprintf(stderr, "  solve %d: %.1f us\n", i, date_us);
  }

  double mean = acc_date / testcount;
  double stdev = std::sqrt(acc_date2 / testcount - mean * mean);
  fprintf(stderr,
          "%ix One shot execution time for a total of %u constraints, %u "
          "variables with %u active constraint each, concurrency in [%i,%i] "
          "and max concurrency share %u\n",
          testcount, nb_cnst, nb_var, nb_elem, 1 << pw_base_limit,
          (1 << pw_base_limit) + (1 << pw_max_limit), max_share);
  printf("mean_us=%.1f stdev_us=%.1f\n", mean, stdev);
  return 0;
}
