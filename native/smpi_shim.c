/* MPI shim: compiled by tools/smpicc INTO every simulated MPI program.
 *
 * Every MPI_* entry point marshals its arguments into a flat long-long
 * array and forwards to ONE dispatch callback that the Python runtime
 * (simgrid_tpu/smpi/c_api.py) installs via smpi_set_callbacks() right
 * after dlopen'ing the program.  The callback runs on the calling rank's
 * actor thread, issues the simcall, and blocks until the simulated
 * operation completes — so unmodified MPI C code runs against the
 * simulation kernel (role of the reference's src/smpi/bindings/
 * smpi_pmpi*.cpp, redesigned: one generic trampoline instead of 300
 * hand-written PMPI wrappers, since Python does the semantic work).
 *
 * Rank isolation: smpirun dlopens a PRIVATE COPY of the program .so per
 * rank, so each rank gets its own globals (.data/.bss) — the in-process
 * equivalent of the reference's mmap-based privatization
 * (smpi_global.cpp:540-608).
 */
#include "../include/smpi/mpi.h"

typedef long long smpi_arg_t;
typedef int (*smpi_dispatch_fn)(int opcode, smpi_arg_t* args);
typedef double (*smpi_time_fn)(void);

static smpi_dispatch_fn smpi_dispatch = 0;
static smpi_time_fn smpi_wtime_cb = 0;

void smpi_set_callbacks(smpi_dispatch_fn dispatch, smpi_time_fn wtime) {
  smpi_dispatch = dispatch;
  smpi_wtime_cb = wtime;
}

/* Opcode values are mirrored byte-for-byte in c_api.py (_OPCODES). */
enum {
  SMPI_OP_INIT = 1,
  SMPI_OP_FINALIZE,
  SMPI_OP_INITIALIZED,
  SMPI_OP_FINALIZED,
  SMPI_OP_ABORT,
  SMPI_OP_COMM_RANK,
  SMPI_OP_COMM_SIZE,
  SMPI_OP_COMM_DUP,
  SMPI_OP_COMM_SPLIT,
  SMPI_OP_COMM_FREE,
  SMPI_OP_SEND,
  SMPI_OP_SSEND,
  SMPI_OP_RECV,
  SMPI_OP_ISEND,
  SMPI_OP_IRECV,
  SMPI_OP_WAIT,
  SMPI_OP_TEST,
  SMPI_OP_WAITALL,
  SMPI_OP_WAITANY,
  SMPI_OP_TESTALL,
  SMPI_OP_PROBE,
  SMPI_OP_IPROBE,
  SMPI_OP_SENDRECV,
  SMPI_OP_GET_COUNT,
  SMPI_OP_BARRIER,
  SMPI_OP_BCAST,
  SMPI_OP_REDUCE,
  SMPI_OP_ALLREDUCE,
  SMPI_OP_GATHER,
  SMPI_OP_GATHERV,
  SMPI_OP_ALLGATHER,
  SMPI_OP_ALLGATHERV,
  SMPI_OP_SCATTER,
  SMPI_OP_SCATTERV,
  SMPI_OP_ALLTOALL,
  SMPI_OP_ALLTOALLV,
  SMPI_OP_SCAN,
  SMPI_OP_EXSCAN,
  SMPI_OP_REDUCE_SCATTER,
  SMPI_OP_REDUCE_SCATTER_BLOCK,
  SMPI_OP_TYPE_SIZE,
  SMPI_OP_TYPE_GET_EXTENT,
  SMPI_OP_TYPE_CONTIGUOUS,
  SMPI_OP_TYPE_VECTOR,
  SMPI_OP_TYPE_COMMIT,
  SMPI_OP_TYPE_FREE,
  SMPI_OP_OP_CREATE,
  SMPI_OP_OP_FREE,
  SMPI_OP_COMM_GROUP,
  SMPI_OP_GROUP_SIZE,
  SMPI_OP_GROUP_RANK,
  SMPI_OP_GET_PROCESSOR_NAME,
  SMPI_OP_FILE_OPEN,          /* 53 */
  SMPI_OP_FILE_CLOSE,
  SMPI_OP_FILE_DELETE,
  SMPI_OP_FILE_SEEK,
  SMPI_OP_FILE_SEEK_SHARED,
  SMPI_OP_FILE_GET_POSITION,
  SMPI_OP_FILE_GET_SIZE,
  SMPI_OP_FILE_READ,          /* also at/all/shared via the mode arg */
  SMPI_OP_FILE_WRITE,
  SMPI_OP_FILE_SYNC,
  SMPI_OP_SHARED_MALLOC,      /* 63 */
  SMPI_OP_SHARED_FREE,
  SMPI_OP_EXECUTE,
  SMPI_OP_SAMPLE_1,
  SMPI_OP_SAMPLE_2,
  SMPI_OP_SAMPLE_3,
  SMPI_OP_SAMPLE_EXIT,
  SMPI_OP_COMM_GET_NAME,      /* 70 */
  SMPI_OP_COMM_CREATE,
  SMPI_OP_GROUP_INCL,
  SMPI_OP_GROUP_EXCL,
  SMPI_OP_GROUP_RANGE_INCL,
  SMPI_OP_KEYVAL_CREATE,
  SMPI_OP_KEYVAL_FREE,
  SMPI_OP_ATTR_PUT,
  SMPI_OP_ATTR_GET,
  SMPI_OP_ATTR_DELETE,
  SMPI_OP_WIN_CREATE,         /* 80 */
  SMPI_OP_WIN_FREE,
  SMPI_OP_WIN_FENCE,
  SMPI_OP_WIN_GET_ATTR,
  SMPI_OP_WIN_SET_ATTR,
  SMPI_OP_TYPE_STRUCT,        /* 85 */
  SMPI_OP_IBARRIER,
  SMPI_OP_IBCAST,
  SMPI_OP_IREDUCE,
  SMPI_OP_IALLREDUCE,
  SMPI_OP_IGATHER,            /* 90 */
  SMPI_OP_ISCATTER,
  SMPI_OP_IALLGATHER,
  SMPI_OP_IALLTOALL,
  SMPI_OP_TYPE_GET_NAME,
  SMPI_OP_CART_CREATE,        /* 95 */
  SMPI_OP_CART_GET,
  SMPI_OP_CART_RANK,
  SMPI_OP_CART_COORDS,
  SMPI_OP_CART_SHIFT,
  SMPI_OP_CART_SUB,           /* 100 */
  SMPI_OP_CARTDIM_GET,
  SMPI_OP_DIMS_CREATE,
  SMPI_OP_TOPO_TEST,
  SMPI_OP_ALLTOALLW,          /* 104 */
  SMPI_OP_IALLTOALLW,
  SMPI_OP_ISCATTERV,
  SMPI_OP_IGATHERV,
  SMPI_OP_IALLGATHERV,
  SMPI_OP_IALLTOALLV,
  SMPI_OP_IREDUCE_SCATTER,    /* 110 */
  SMPI_OP_ISCAN,
  SMPI_OP_IEXSCAN,
  SMPI_OP_TYPE_RESIZED,
  SMPI_OP_BSEND,
  SMPI_OP_IBSEND,             /* 115 */
  SMPI_OP_SEND_INIT,          /* mode arg: 0 send, 1 bsend, 2 ssend */
  SMPI_OP_RECV_INIT,
  SMPI_OP_START,
  SMPI_OP_STARTALL,
  SMPI_OP_REQUEST_FREE,       /* 120 */
  SMPI_OP_SENDRECV_REPLACE,
  SMPI_OP_TESTANY,
  SMPI_OP_WAITSOME,           /* also testsome via the blocking arg */
  SMPI_OP_TYPE_INDEXED,       /* flag arg: displs in elements(0)/bytes(1) */
  SMPI_OP_TYPE_HVECTOR,       /* 125 */
  SMPI_OP_TYPE_INDEXED_BLOCK, /* flag arg as TYPE_INDEXED */
  SMPI_OP_TYPE_DUP,
  SMPI_OP_TYPE_SUBARRAY,
  SMPI_OP_PACK,               /* unpack via the direction arg */
  SMPI_OP_GRAPH_CREATE,       /* 130 */
  SMPI_OP_GRAPH_NEIGHBORS,
  SMPI_OP_GRAPHDIMS_GET,
  SMPI_OP_GRAPH_GET,
  SMPI_OP_REQUEST_GET_STATUS,
  SMPI_OP_COMM_CREATE_GROUP,  /* 135 */
  SMPI_OP_COMM_IDUP,
  SMPI_OP_COMM_SET_NAME,
  SMPI_OP_COMM_SPLIT_TYPE,
  SMPI_OP_GROUP_SETOP,        /* mode: 0 union 1 inter 2 diff 3 range_excl */
  SMPI_OP_GROUP_TRANSLATE,    /* 140 */
  SMPI_OP_GROUP_COMPARE,
  SMPI_OP_COMM_COMPARE,
  SMPI_OP_INTERCOMM_CREATE,
  SMPI_OP_INTERCOMM_MERGE,
  SMPI_OP_COMM_REMOTE_SIZE,   /* 145 */
  SMPI_OP_COMM_TEST_INTER,
  SMPI_OP_CANCEL,             /* 147 */
  SMPI_OP_TYPE_GET_ENVELOPE,
  SMPI_OP_TYPE_GET_CONTENTS,
  SMPI_OP_GET_ELEMENTS,       /* 150 */
  SMPI_OP_TYPE_LBUB,          /* mode: 0 lb, 1 ub, 2 extent */
  SMPI_OP_TYPE_DARRAY,
  SMPI_OP_PACK_EXTERNAL,      /* mode: 0 pack, 1 unpack, 2 size */
  SMPI_OP_TYPE_MATCH_SIZE,
  SMPI_OP_TOPO_MAP,           /* 155; mode: 0 cart, 1 graph */
  SMPI_OP_DIST_GRAPH_CREATE,  /* mode: 0 general, 1 adjacent */
  SMPI_OP_DIST_GRAPH_NEIGHBORS, /* mode: 0 counts, 1 lists */
  /* -- one-sided (MPI-3 RMA) -- */
  SMPI_OP_PUT,                /* 158 */
  SMPI_OP_GET,
  SMPI_OP_ACCUMULATE,         /* 160 */
  SMPI_OP_GET_ACCUMULATE,
  SMPI_OP_FETCH_AND_OP,
  SMPI_OP_COMPARE_AND_SWAP,
  SMPI_OP_RPUT,
  SMPI_OP_RGET,               /* 165 */
  SMPI_OP_RACCUMULATE,
  SMPI_OP_RGET_ACCUMULATE,
  SMPI_OP_WIN_ALLOCATE,
  SMPI_OP_WIN_ALLOCATE_SHARED,
  SMPI_OP_WIN_CREATE_DYNAMIC, /* 170 */
  SMPI_OP_WIN_ATTACH,
  SMPI_OP_WIN_DETACH,
  SMPI_OP_WIN_SHARED_QUERY,
  SMPI_OP_WIN_LOCK,
  SMPI_OP_WIN_UNLOCK,         /* 175 */
  SMPI_OP_WIN_LOCK_ALL,
  SMPI_OP_WIN_UNLOCK_ALL,
  SMPI_OP_WIN_FLUSH,
  SMPI_OP_WIN_FLUSH_LOCAL,
  SMPI_OP_WIN_FLUSH_ALL,      /* 180 */
  SMPI_OP_WIN_FLUSH_LOCAL_ALL,
  SMPI_OP_WIN_SYNC,
  SMPI_OP_WIN_START,
  SMPI_OP_WIN_COMPLETE,
  SMPI_OP_WIN_POST,           /* 185 */
  SMPI_OP_WIN_WAIT,
  SMPI_OP_WIN_TEST,
  SMPI_OP_WIN_GET_GROUP,
  SMPI_OP_WIN_SET_NAME,
  SMPI_OP_WIN_GET_NAME,       /* 190 */
  SMPI_OP_WIN_KEYVAL_CREATE,
  SMPI_OP_WIN_KEYVAL_FREE,
  SMPI_OP_WIN_DELETE_ATTR,
  SMPI_OP_WIN_SET_ERRHANDLER,
  SMPI_OP_WIN_GET_ERRHANDLER, /* 195 */
  SMPI_OP_WIN_CALL_ERRHANDLER,
  SMPI_OP_MPROBE,             /* 197 */
  SMPI_OP_IMPROBE,
  SMPI_OP_MRECV,
  SMPI_OP_IMRECV,             /* 200 */
  SMPI_OP_GREQUEST_START,
  SMPI_OP_GREQUEST_COMPLETE,
  SMPI_OP_TYPE_KEYVAL_CREATE, /* 203 */
  SMPI_OP_TYPE_SET_ATTR,
  SMPI_OP_TYPE_GET_ATTR,
  SMPI_OP_TYPE_DELETE_ATTR,
  SMPI_OP_ERRHANDLER_CREATE,  /* 207 */
  SMPI_OP_ERRHANDLER_FREE,
  SMPI_OP_COMM_SET_ERRHANDLER,
  SMPI_OP_COMM_GET_ERRHANDLER, /* 210 */
  SMPI_OP_COMM_CALL_ERRHANDLER,
  SMPI_OP_ADD_ERROR_CLASS,
  SMPI_OP_ADD_ERROR_CODE,
  SMPI_OP_ADD_ERROR_STRING,
  SMPI_OP_ERROR_STRING,       /* 215 */
  SMPI_OP_ERROR_CLASS,
  SMPI_OP_OP_COMMUTATIVE,
  SMPI_OP_REDUCE_LOCAL,
};

/* sub-modes for FILE_READ / FILE_WRITE */
enum { SMPI_IO_PLAIN = 0, SMPI_IO_AT = 1, SMPI_IO_ALL = 2,
       SMPI_IO_SHARED = 3 };

#define A(x) ((smpi_arg_t)(x))
#define CALL(op, ...)                                  \
  do {                                                 \
    smpi_arg_t args_[] = {__VA_ARGS__};                \
    if (!smpi_dispatch) return MPI_ERR_INTERN;         \
    return smpi_dispatch(op, args_);                   \
  } while (0)

/* -- environment -------------------------------------------------------- */
int MPI_Init(int* argc, char*** argv) { CALL(SMPI_OP_INIT, A(argc), A(argv)); }
int MPI_Init_thread(int* argc, char*** argv, int required, int* provided) {
  if (provided) *provided = required < 2 ? required : 2; /* SERIALIZED */
  return MPI_Init(argc, argv);
}
int MPI_Query_thread(int* provided) {
  if (provided) *provided = 2;
  return MPI_SUCCESS;
}
int MPI_Finalize(void) { CALL(SMPI_OP_FINALIZE, 0); }
int MPI_Initialized(int* flag) { CALL(SMPI_OP_INITIALIZED, A(flag)); }
int MPI_Finalized(int* flag) { CALL(SMPI_OP_FINALIZED, A(flag)); }
int MPI_Abort(MPI_Comm comm, int errorcode) {
  CALL(SMPI_OP_ABORT, A(comm), A(errorcode));
}
double MPI_Wtime(void) { return smpi_wtime_cb ? smpi_wtime_cb() : 0.0; }
double MPI_Wtick(void) { return 1e-9; }
int MPI_Get_processor_name(char* name, int* resultlen) {
  CALL(SMPI_OP_GET_PROCESSOR_NAME, A(name), A(resultlen));
}
int MPI_Error_string(int errorcode, char* string, int* resultlen) {
  if (smpi_dispatch) {
    smpi_arg_t args_[] = {A(errorcode), A(string), A(resultlen)};
    return smpi_dispatch(SMPI_OP_ERROR_STRING, args_);
  }
  {
    static const char msg[] = "MPI error";
    int i = 0;
    (void)errorcode;
    for (; msg[i]; i++) string[i] = msg[i];
    string[i] = 0;
    *resultlen = i;
    return MPI_SUCCESS;
  }
}
int MPI_Get_address(const void* location, MPI_Aint* address) {
  *address = (MPI_Aint)location;
  return MPI_SUCCESS;
}
int MPI_Address(void* location, MPI_Aint* address) {
  return MPI_Get_address(location, address);
}
int MPI_Request_get_status(MPI_Request request, int* flag,
                           MPI_Status* status) {
  CALL(SMPI_OP_REQUEST_GET_STATUS, A(request), A(flag), A(status));
}
int MPI_Get_version(int* version, int* subversion) {
  *version = MPI_VERSION;
  *subversion = MPI_SUBVERSION;
  return MPI_SUCCESS;
}
int MPI_Get_library_version(char* version, int* resultlen) {
  static const char msg[] =
      "simgrid-tpu SMPI (MPI 3.1 subset over a simulated platform)";
  int i = 0;
  for (; msg[i]; i++) version[i] = msg[i];
  version[i] = 0;
  *resultlen = i;
  return MPI_SUCCESS;
}
int MPI_Is_thread_main(int* flag) {
  /* every simulated rank is its own main thread */
  if (flag) *flag = 1;
  return MPI_SUCCESS;
}
int MPI_Comm_create_errhandler(MPI_Comm_errhandler_function* fn,
                               MPI_Errhandler* errhandler) {
  CALL(SMPI_OP_ERRHANDLER_CREATE, A(fn), A(errhandler));
}
int MPI_Errhandler_create(MPI_Handler_function* fn,
                          MPI_Errhandler* errhandler) {
  return MPI_Comm_create_errhandler(fn, errhandler);
}
int MPI_Errhandler_free(MPI_Errhandler* errhandler) {
  CALL(SMPI_OP_ERRHANDLER_FREE, A(errhandler));
}
int MPI_Comm_set_errhandler(MPI_Comm comm, MPI_Errhandler errhandler) {
  CALL(SMPI_OP_COMM_SET_ERRHANDLER, A(comm), A(errhandler));
}
int MPI_Errhandler_set(MPI_Comm comm, MPI_Errhandler errhandler) {
  return MPI_Comm_set_errhandler(comm, errhandler);
}
int MPI_Comm_get_errhandler(MPI_Comm comm, MPI_Errhandler* errhandler) {
  CALL(SMPI_OP_COMM_GET_ERRHANDLER, A(comm), A(errhandler));
}
int MPI_Errhandler_get(MPI_Comm comm, MPI_Errhandler* errhandler) {
  return MPI_Comm_get_errhandler(comm, errhandler);
}
int MPI_Comm_call_errhandler(MPI_Comm comm, int errorcode) {
  CALL(SMPI_OP_COMM_CALL_ERRHANDLER, A(comm), A(errorcode));
}
int MPI_Add_error_class(int* errorclass) {
  CALL(SMPI_OP_ADD_ERROR_CLASS, A(errorclass));
}
int MPI_Add_error_code(int errorclass, int* errorcode) {
  CALL(SMPI_OP_ADD_ERROR_CODE, A(errorclass), A(errorcode));
}
int MPI_Add_error_string(int errorcode, const char* string) {
  CALL(SMPI_OP_ADD_ERROR_STRING, A(errorcode), A(string));
}

/* -- communicators ------------------------------------------------------- */
int MPI_Comm_rank(MPI_Comm comm, int* rank) {
  CALL(SMPI_OP_COMM_RANK, A(comm), A(rank));
}
int MPI_Comm_size(MPI_Comm comm, int* size) {
  CALL(SMPI_OP_COMM_SIZE, A(comm), A(size));
}
int MPI_Comm_dup(MPI_Comm comm, MPI_Comm* newcomm) {
  CALL(SMPI_OP_COMM_DUP, A(comm), A(newcomm));
}
int MPI_Comm_split(MPI_Comm comm, int color, int key, MPI_Comm* newcomm) {
  CALL(SMPI_OP_COMM_SPLIT, A(comm), A(color), A(key), A(newcomm));
}
int MPI_Comm_free(MPI_Comm* comm) { CALL(SMPI_OP_COMM_FREE, A(comm)); }
int MPI_Comm_group(MPI_Comm comm, MPI_Group* group) {
  CALL(SMPI_OP_COMM_GROUP, A(comm), A(group));
}
int MPI_Group_free(MPI_Group* group) {
  *group = MPI_GROUP_NULL;
  return MPI_SUCCESS;
}
int MPI_Group_size(MPI_Group group, int* size) {
  CALL(SMPI_OP_GROUP_SIZE, A(group), A(size));
}
int MPI_Group_rank(MPI_Group group, int* rank) {
  CALL(SMPI_OP_GROUP_RANK, A(group), A(rank));
}

/* -- point-to-point ------------------------------------------------------- */
int MPI_Send(const void* buf, int count, MPI_Datatype datatype, int dest,
             int tag, MPI_Comm comm) {
  CALL(SMPI_OP_SEND, A(buf), A(count), A(datatype), A(dest), A(tag), A(comm));
}
int MPI_Ssend(const void* buf, int count, MPI_Datatype datatype, int dest,
              int tag, MPI_Comm comm) {
  CALL(SMPI_OP_SSEND, A(buf), A(count), A(datatype), A(dest), A(tag), A(comm));
}
int MPI_Recv(void* buf, int count, MPI_Datatype datatype, int source,
             int tag, MPI_Comm comm, MPI_Status* status) {
  CALL(SMPI_OP_RECV, A(buf), A(count), A(datatype), A(source), A(tag),
       A(comm), A(status));
}
int MPI_Isend(const void* buf, int count, MPI_Datatype datatype, int dest,
              int tag, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_ISEND, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm), A(request), 0);
}
int MPI_Issend(const void* buf, int count, MPI_Datatype datatype, int dest,
               int tag, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_ISEND, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm), A(request), 1);
}
int MPI_Irecv(void* buf, int count, MPI_Datatype datatype, int source,
              int tag, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IRECV, A(buf), A(count), A(datatype), A(source), A(tag),
       A(comm), A(request));
}
int MPI_Wait(MPI_Request* request, MPI_Status* status) {
  CALL(SMPI_OP_WAIT, A(request), A(status));
}
int MPI_Test(MPI_Request* request, int* flag, MPI_Status* status) {
  CALL(SMPI_OP_TEST, A(request), A(flag), A(status));
}
int MPI_Waitall(int count, MPI_Request* requests, MPI_Status* statuses) {
  CALL(SMPI_OP_WAITALL, A(count), A(requests), A(statuses));
}
int MPI_Waitany(int count, MPI_Request* requests, int* index,
                MPI_Status* status) {
  CALL(SMPI_OP_WAITANY, A(count), A(requests), A(index), A(status));
}
int MPI_Testall(int count, MPI_Request* requests, int* flag,
                MPI_Status* statuses) {
  CALL(SMPI_OP_TESTALL, A(count), A(requests), A(flag), A(statuses));
}
int MPI_Probe(int source, int tag, MPI_Comm comm, MPI_Status* status) {
  CALL(SMPI_OP_PROBE, A(source), A(tag), A(comm), A(status));
}
int MPI_Iprobe(int source, int tag, MPI_Comm comm, int* flag,
               MPI_Status* status) {
  CALL(SMPI_OP_IPROBE, A(source), A(tag), A(comm), A(flag), A(status));
}
int MPI_Mprobe(int source, int tag, MPI_Comm comm, MPI_Message* message,
               MPI_Status* status) {
  CALL(SMPI_OP_MPROBE, A(source), A(tag), A(comm), A(message), A(status));
}
int MPI_Improbe(int source, int tag, MPI_Comm comm, int* flag,
                MPI_Message* message, MPI_Status* status) {
  CALL(SMPI_OP_IMPROBE, A(source), A(tag), A(comm), A(flag), A(message),
       A(status));
}
int MPI_Mrecv(void* buf, int count, MPI_Datatype datatype,
              MPI_Message* message, MPI_Status* status) {
  CALL(SMPI_OP_MRECV, A(buf), A(count), A(datatype), A(message), A(status));
}
int MPI_Imrecv(void* buf, int count, MPI_Datatype datatype,
               MPI_Message* message, MPI_Request* request) {
  CALL(SMPI_OP_IMRECV, A(buf), A(count), A(datatype), A(message),
       A(request));
}
int MPI_Grequest_start(MPI_Grequest_query_function* query_fn,
                       MPI_Grequest_free_function* free_fn,
                       MPI_Grequest_cancel_function* cancel_fn,
                       void* extra_state, MPI_Request* request) {
  CALL(SMPI_OP_GREQUEST_START, A(query_fn), A(free_fn), A(cancel_fn),
       A(extra_state), A(request));
}
int MPI_Grequest_complete(MPI_Request request) {
  CALL(SMPI_OP_GREQUEST_COMPLETE, A(request));
}
int MPI_Status_set_cancelled(MPI_Status* status, int flag) {
  if (status) status->cancelled_ = flag;
  return MPI_SUCCESS;
}
int MPI_Sendrecv(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                 int dest, int sendtag, void* recvbuf, int recvcount,
                 MPI_Datatype recvtype, int source, int recvtag,
                 MPI_Comm comm, MPI_Status* status) {
  CALL(SMPI_OP_SENDRECV, A(sendbuf), A(sendcount), A(sendtype), A(dest),
       A(sendtag), A(recvbuf), A(recvcount), A(recvtype), A(source),
       A(recvtag), A(comm), A(status));
}
int MPI_Get_count(const MPI_Status* status, MPI_Datatype datatype,
                  int* count) {
  CALL(SMPI_OP_GET_COUNT, A(status), A(datatype), A(count));
}

/* -- buffered / ready modes, persistent requests ---------------------------- */
static void* smpi_bsend_buffer = 0;
static int smpi_bsend_buffer_size = 0;
int MPI_Buffer_attach(void* buffer, int size) {
  smpi_bsend_buffer = buffer;
  smpi_bsend_buffer_size = size;
  return MPI_SUCCESS;
}
int MPI_Buffer_detach(void* buffer_addr, int* size) {
  *(void**)buffer_addr = smpi_bsend_buffer;
  *size = smpi_bsend_buffer_size;
  smpi_bsend_buffer = 0;
  smpi_bsend_buffer_size = 0;
  return MPI_SUCCESS;
}
int MPI_Bsend(const void* buf, int count, MPI_Datatype datatype, int dest,
              int tag, MPI_Comm comm) {
  CALL(SMPI_OP_BSEND, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm));
}
int MPI_Ibsend(const void* buf, int count, MPI_Datatype datatype, int dest,
               int tag, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IBSEND, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm), A(request));
}
int MPI_Rsend(const void* buf, int count, MPI_Datatype datatype, int dest,
              int tag, MPI_Comm comm) {
  /* ready mode: the receive is already posted, a plain send matches */
  return MPI_Send(buf, count, datatype, dest, tag, comm);
}
int MPI_Irsend(const void* buf, int count, MPI_Datatype datatype, int dest,
               int tag, MPI_Comm comm, MPI_Request* request) {
  return MPI_Isend(buf, count, datatype, dest, tag, comm, request);
}
int MPI_Send_init(const void* buf, int count, MPI_Datatype datatype,
                  int dest, int tag, MPI_Comm comm,
                  MPI_Request* request) {
  CALL(SMPI_OP_SEND_INIT, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm), A(request), 0);
}
int MPI_Bsend_init(const void* buf, int count, MPI_Datatype datatype,
                   int dest, int tag, MPI_Comm comm,
                   MPI_Request* request) {
  CALL(SMPI_OP_SEND_INIT, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm), A(request), 1);
}
int MPI_Ssend_init(const void* buf, int count, MPI_Datatype datatype,
                   int dest, int tag, MPI_Comm comm,
                   MPI_Request* request) {
  CALL(SMPI_OP_SEND_INIT, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm), A(request), 2);
}
int MPI_Rsend_init(const void* buf, int count, MPI_Datatype datatype,
                   int dest, int tag, MPI_Comm comm,
                   MPI_Request* request) {
  CALL(SMPI_OP_SEND_INIT, A(buf), A(count), A(datatype), A(dest), A(tag),
       A(comm), A(request), 0);
}
int MPI_Recv_init(void* buf, int count, MPI_Datatype datatype, int source,
                  int tag, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_RECV_INIT, A(buf), A(count), A(datatype), A(source),
       A(tag), A(comm), A(request));
}
int MPI_Start(MPI_Request* request) { CALL(SMPI_OP_START, A(request)); }
int MPI_Startall(int count, MPI_Request* requests) {
  CALL(SMPI_OP_STARTALL, A(count), A(requests));
}
int MPI_Request_free(MPI_Request* request) {
  CALL(SMPI_OP_REQUEST_FREE, A(request));
}
int MPI_Sendrecv_replace(void* buf, int count, MPI_Datatype datatype,
                         int dest, int sendtag, int source, int recvtag,
                         MPI_Comm comm, MPI_Status* status) {
  CALL(SMPI_OP_SENDRECV_REPLACE, A(buf), A(count), A(datatype), A(dest),
       A(sendtag), A(source), A(recvtag), A(comm), A(status));
}
int MPI_Testany(int count, MPI_Request* requests, int* index, int* flag,
                MPI_Status* status) {
  CALL(SMPI_OP_TESTANY, A(count), A(requests), A(index), A(flag),
       A(status));
}
int MPI_Waitsome(int incount, MPI_Request* requests, int* outcount,
                 int* indices, MPI_Status* statuses) {
  CALL(SMPI_OP_WAITSOME, A(incount), A(requests), A(outcount), A(indices),
       A(statuses), 1);
}
int MPI_Testsome(int incount, MPI_Request* requests, int* outcount,
                 int* indices, MPI_Status* statuses) {
  CALL(SMPI_OP_WAITSOME, A(incount), A(requests), A(outcount), A(indices),
       A(statuses), 0);
}

/* -- collectives ---------------------------------------------------------- */
int MPI_Barrier(MPI_Comm comm) { CALL(SMPI_OP_BARRIER, A(comm)); }
int MPI_Bcast(void* buf, int count, MPI_Datatype datatype, int root,
              MPI_Comm comm) {
  CALL(SMPI_OP_BCAST, A(buf), A(count), A(datatype), A(root), A(comm));
}
int MPI_Reduce(const void* sendbuf, void* recvbuf, int count,
               MPI_Datatype datatype, MPI_Op op, int root, MPI_Comm comm) {
  CALL(SMPI_OP_REDUCE, A(sendbuf), A(recvbuf), A(count), A(datatype), A(op),
       A(root), A(comm));
}
int MPI_Allreduce(const void* sendbuf, void* recvbuf, int count,
                  MPI_Datatype datatype, MPI_Op op, MPI_Comm comm) {
  CALL(SMPI_OP_ALLREDUCE, A(sendbuf), A(recvbuf), A(count), A(datatype),
       A(op), A(comm));
}
int MPI_Gather(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
               void* recvbuf, int recvcount, MPI_Datatype recvtype,
               int root, MPI_Comm comm) {
  CALL(SMPI_OP_GATHER, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcount), A(recvtype), A(root), A(comm));
}
int MPI_Gatherv(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                void* recvbuf, const int* recvcounts, const int* displs,
                MPI_Datatype recvtype, int root, MPI_Comm comm) {
  CALL(SMPI_OP_GATHERV, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcounts), A(displs), A(recvtype), A(root), A(comm));
}
int MPI_Allgather(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                  void* recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm) {
  CALL(SMPI_OP_ALLGATHER, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcount), A(recvtype), A(comm));
}
int MPI_Allgatherv(const void* sendbuf, int sendcount,
                   MPI_Datatype sendtype, void* recvbuf,
                   const int* recvcounts, const int* displs,
                   MPI_Datatype recvtype, MPI_Comm comm) {
  CALL(SMPI_OP_ALLGATHERV, A(sendbuf), A(sendcount), A(sendtype),
       A(recvbuf), A(recvcounts), A(displs), A(recvtype), A(comm));
}
int MPI_Scatter(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                void* recvbuf, int recvcount, MPI_Datatype recvtype,
                int root, MPI_Comm comm) {
  CALL(SMPI_OP_SCATTER, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcount), A(recvtype), A(root), A(comm));
}
int MPI_Scatterv(const void* sendbuf, const int* sendcounts,
                 const int* displs, MPI_Datatype sendtype, void* recvbuf,
                 int recvcount, MPI_Datatype recvtype, int root,
                 MPI_Comm comm) {
  CALL(SMPI_OP_SCATTERV, A(sendbuf), A(sendcounts), A(displs), A(sendtype),
       A(recvbuf), A(recvcount), A(recvtype), A(root), A(comm));
}
int MPI_Alltoall(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                 void* recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm) {
  CALL(SMPI_OP_ALLTOALL, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcount), A(recvtype), A(comm));
}
int MPI_Alltoallv(const void* sendbuf, const int* sendcounts,
                  const int* sdispls, MPI_Datatype sendtype, void* recvbuf,
                  const int* recvcounts, const int* rdispls,
                  MPI_Datatype recvtype, MPI_Comm comm) {
  CALL(SMPI_OP_ALLTOALLV, A(sendbuf), A(sendcounts), A(sdispls), A(sendtype),
       A(recvbuf), A(recvcounts), A(rdispls), A(recvtype), A(comm));
}
int MPI_Scan(const void* sendbuf, void* recvbuf, int count,
             MPI_Datatype datatype, MPI_Op op, MPI_Comm comm) {
  CALL(SMPI_OP_SCAN, A(sendbuf), A(recvbuf), A(count), A(datatype), A(op),
       A(comm));
}
int MPI_Exscan(const void* sendbuf, void* recvbuf, int count,
               MPI_Datatype datatype, MPI_Op op, MPI_Comm comm) {
  CALL(SMPI_OP_EXSCAN, A(sendbuf), A(recvbuf), A(count), A(datatype), A(op),
       A(comm));
}
int MPI_Reduce_scatter(const void* sendbuf, void* recvbuf,
                       const int* recvcounts, MPI_Datatype datatype,
                       MPI_Op op, MPI_Comm comm) {
  CALL(SMPI_OP_REDUCE_SCATTER, A(sendbuf), A(recvbuf), A(recvcounts),
       A(datatype), A(op), A(comm));
}
int MPI_Reduce_scatter_block(const void* sendbuf, void* recvbuf,
                             int recvcount, MPI_Datatype datatype,
                             MPI_Op op, MPI_Comm comm) {
  CALL(SMPI_OP_REDUCE_SCATTER_BLOCK, A(sendbuf), A(recvbuf), A(recvcount),
       A(datatype), A(op), A(comm));
}

/* -- datatypes ------------------------------------------------------------- */
int MPI_Type_size(MPI_Datatype datatype, int* size) {
  CALL(SMPI_OP_TYPE_SIZE, A(datatype), A(size), A(0));
}
int MPI_Type_get_extent(MPI_Datatype datatype, MPI_Aint* lb,
                        MPI_Aint* extent) {
  CALL(SMPI_OP_TYPE_GET_EXTENT, A(datatype), A(lb), A(extent), A(0));
}
int MPI_Type_contiguous(int count, MPI_Datatype oldtype,
                        MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_CONTIGUOUS, A(count), A(oldtype), A(newtype));
}
int MPI_Type_vector(int count, int blocklength, int stride,
                    MPI_Datatype oldtype, MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_VECTOR, A(count), A(blocklength), A(stride), A(oldtype),
       A(newtype));
}
int MPI_Type_commit(MPI_Datatype* datatype) {
  CALL(SMPI_OP_TYPE_COMMIT, A(datatype));
}
int MPI_Type_free(MPI_Datatype* datatype) {
  CALL(SMPI_OP_TYPE_FREE, A(datatype));
}

/* -- reduction ops ---------------------------------------------------------- */
int MPI_Op_commutative(MPI_Op op, int* commute) {
  CALL(SMPI_OP_OP_COMMUTATIVE, A(op), A(commute));
}
int MPI_Reduce_local(const void* inbuf, void* inoutbuf, int count,
                     MPI_Datatype datatype, MPI_Op op) {
  CALL(SMPI_OP_REDUCE_LOCAL, A(inbuf), A(inoutbuf), A(count),
       A(datatype), A(op));
}
int MPI_Op_create(MPI_User_function* fn, int commute, MPI_Op* op) {
  CALL(SMPI_OP_OP_CREATE, A(fn), A(commute), A(op));
}
int MPI_Op_free(MPI_Op* op) { CALL(SMPI_OP_OP_FREE, A(op)); }

/* -- MPI-IO ------------------------------------------------------------------ */
int MPI_File_open(MPI_Comm comm, const char* filename, int amode,
                  MPI_Info info, MPI_File* fh) {
  (void)info;
  CALL(SMPI_OP_FILE_OPEN, A(comm), A(filename), A(amode), A(fh));
}
int MPI_File_close(MPI_File* fh) { CALL(SMPI_OP_FILE_CLOSE, A(fh)); }
int MPI_File_delete(const char* filename, MPI_Info info) {
  (void)info;
  CALL(SMPI_OP_FILE_DELETE, A(filename));
}
int MPI_File_seek(MPI_File fh, MPI_Offset offset, int whence) {
  CALL(SMPI_OP_FILE_SEEK, A(fh), A(offset), A(whence));
}
int MPI_File_seek_shared(MPI_File fh, MPI_Offset offset, int whence) {
  CALL(SMPI_OP_FILE_SEEK_SHARED, A(fh), A(offset), A(whence));
}
int MPI_File_get_position(MPI_File fh, MPI_Offset* offset) {
  CALL(SMPI_OP_FILE_GET_POSITION, A(fh), A(offset));
}
int MPI_File_get_size(MPI_File fh, MPI_Offset* size) {
  CALL(SMPI_OP_FILE_GET_SIZE, A(fh), A(size));
}
int MPI_File_read(MPI_File fh, void* buf, int count, MPI_Datatype datatype,
                  MPI_Status* status) {
  CALL(SMPI_OP_FILE_READ, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_PLAIN, 0);
}
int MPI_File_write(MPI_File fh, const void* buf, int count,
                   MPI_Datatype datatype, MPI_Status* status) {
  CALL(SMPI_OP_FILE_WRITE, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_PLAIN, 0);
}
int MPI_File_read_at(MPI_File fh, MPI_Offset offset, void* buf, int count,
                     MPI_Datatype datatype, MPI_Status* status) {
  CALL(SMPI_OP_FILE_READ, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_AT, A(offset));
}
int MPI_File_write_at(MPI_File fh, MPI_Offset offset, const void* buf,
                      int count, MPI_Datatype datatype, MPI_Status* status) {
  CALL(SMPI_OP_FILE_WRITE, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_AT, A(offset));
}
int MPI_File_read_all(MPI_File fh, void* buf, int count,
                      MPI_Datatype datatype, MPI_Status* status) {
  CALL(SMPI_OP_FILE_READ, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_ALL, 0);
}
int MPI_File_write_all(MPI_File fh, const void* buf, int count,
                       MPI_Datatype datatype, MPI_Status* status) {
  CALL(SMPI_OP_FILE_WRITE, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_ALL, 0);
}
int MPI_File_read_shared(MPI_File fh, void* buf, int count,
                         MPI_Datatype datatype, MPI_Status* status) {
  CALL(SMPI_OP_FILE_READ, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_SHARED, 0);
}
int MPI_File_write_shared(MPI_File fh, const void* buf, int count,
                          MPI_Datatype datatype, MPI_Status* status) {
  CALL(SMPI_OP_FILE_WRITE, A(fh), A(buf), A(count), A(datatype), A(status),
       SMPI_IO_SHARED, 0);
}
int MPI_File_sync(MPI_File fh) { CALL(SMPI_OP_FILE_SYNC, A(fh)); }

/* -- SMPI extensions ---------------------------------------------------------- */
static smpi_arg_t smpi_pack_double(double v) {
  smpi_arg_t r = 0;
  __builtin_memcpy(&r, &v, sizeof(double));
  return r;
}

void* smpi_shared_malloc(size_t size, const char* file, int line) {
  smpi_arg_t out = 0;
  smpi_arg_t args_[] = {A(size), A(file), A(line), A(&out)};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_SHARED_MALLOC, args_);
  return (void*)out;
}

void smpi_shared_free(void* data) {
  smpi_arg_t args_[] = {A(data)};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_SHARED_FREE, args_);
}

void smpi_execute(double duration) {
  smpi_arg_t args_[] = {smpi_pack_double(duration), 0};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_EXECUTE, args_);
}

void smpi_execute_flops(double flops) {
  smpi_arg_t args_[] = {smpi_pack_double(flops), 1};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_EXECUTE, args_);
}

void smpi_sample_1(int global, const char* file, int line, int iters,
                   double threshold) {
  smpi_arg_t args_[] = {A(global), A(file), A(line), A(iters),
                        smpi_pack_double(threshold)};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_SAMPLE_1, args_);
}

int smpi_sample_2(int global, const char* file, int line, int iter_count) {
  smpi_arg_t out = 0;
  smpi_arg_t args_[] = {A(global), A(file), A(line), A(iter_count),
                        A(&out)};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_SAMPLE_2, args_);
  return (int)out;
}

void smpi_sample_3(int global, const char* file, int line) {
  smpi_arg_t args_[] = {A(global), A(file), A(line)};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_SAMPLE_3, args_);
}

int smpi_sample_exit(int global, const char* file, int line,
                     int iter_count) {
  smpi_arg_t args_[] = {A(global), A(file), A(line), A(iter_count)};
  if (smpi_dispatch) smpi_dispatch(SMPI_OP_SAMPLE_EXIT, args_);
  return 0;
}

/* -- memory / info / naming: host-local, no simulation involvement -------- */
#include <stdlib.h>

int MPI_Alloc_mem(MPI_Aint size, MPI_Info info, void* baseptr) {
  (void)info;
  *(void**)baseptr = malloc((size_t)size);
  return *(void**)baseptr || size == 0 ? MPI_SUCCESS : MPI_ERR_INTERN;
}
int MPI_Free_mem(void* base) {
  free(base);
  return MPI_SUCCESS;
}
int MPI_Error_class(int errorcode, int* errorclass) {
  if (smpi_dispatch) {
    smpi_arg_t args_[] = {A(errorcode), A(errorclass)};
    return smpi_dispatch(SMPI_OP_ERROR_CLASS, args_);
  }
  *errorclass = errorcode;
  return MPI_SUCCESS;
}
int MPI_Type_size_x(MPI_Datatype datatype, MPI_Count* size) {
  CALL(SMPI_OP_TYPE_SIZE, A(datatype), A(size), A(1));
}
int MPI_Type_get_extent_x(MPI_Datatype datatype, MPI_Count* lb,
                          MPI_Count* extent) {
  CALL(SMPI_OP_TYPE_GET_EXTENT, A(datatype), A(lb), A(extent), A(0));
}
int MPI_Type_get_true_extent_x(MPI_Datatype datatype, MPI_Count* true_lb,
                               MPI_Count* true_extent) {
  CALL(SMPI_OP_TYPE_GET_EXTENT, A(datatype), A(true_lb), A(true_extent),
       A(1));
}
int MPI_Get_elements_x(const MPI_Status* status, MPI_Datatype datatype,
                       MPI_Count* count) {
  CALL(SMPI_OP_GET_ELEMENTS, A(status), A(datatype), A(count), A(1));
}
int MPI_Status_set_elements(MPI_Status* status, MPI_Datatype datatype,
                            int count) {
  return MPI_Status_set_elements_x(status, datatype, count);
}
int MPI_Status_set_elements_x(MPI_Status* status, MPI_Datatype datatype,
                              MPI_Count count) {   /* count BY VALUE */
  MPI_Count c = count;
  CALL(SMPI_OP_GET_ELEMENTS, A(status), A(datatype), A(&c), A(2));
}
int MPI_Type_get_envelope(MPI_Datatype datatype, int* num_integers,
                          int* num_addresses, int* num_datatypes,
                          int* combiner) {
  CALL(SMPI_OP_TYPE_GET_ENVELOPE, A(datatype), A(num_integers),
       A(num_addresses), A(num_datatypes), A(combiner));
}
int MPI_Type_get_contents(MPI_Datatype datatype, int max_integers,
                          int max_addresses, int max_datatypes,
                          int array_of_integers[],
                          MPI_Aint array_of_addresses[],
                          MPI_Datatype array_of_datatypes[]) {
  CALL(SMPI_OP_TYPE_GET_CONTENTS, A(datatype), A(max_integers),
       A(max_addresses), A(max_datatypes), A(array_of_integers),
       A(array_of_addresses), A(array_of_datatypes));
}
int MPI_Get_elements(const MPI_Status* status, MPI_Datatype datatype,
                     int* count) {
  CALL(SMPI_OP_GET_ELEMENTS, A(status), A(datatype), A(count), A(0));
}
int MPI_Type_lb(MPI_Datatype datatype, MPI_Aint* displacement) {
  CALL(SMPI_OP_TYPE_LBUB, A(datatype), A(displacement), A(0));
}
int MPI_Type_ub(MPI_Datatype datatype, MPI_Aint* displacement) {
  CALL(SMPI_OP_TYPE_LBUB, A(datatype), A(displacement), A(1));
}
int MPI_Type_create_darray(int size, int rank, int ndims,
                           const int array_of_gsizes[],
                           const int array_of_distribs[],
                           const int array_of_dargs[],
                           const int array_of_psizes[], int order,
                           MPI_Datatype oldtype, MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_DARRAY, A(size), A(rank), A(ndims), A(array_of_gsizes),
       A(array_of_distribs), A(array_of_dargs), A(array_of_psizes),
       A(order), A(oldtype), A(newtype));
}
int MPI_Pack_external(const char datarep[], const void* inbuf, int incount,
                      MPI_Datatype datatype, void* outbuf,
                      MPI_Aint outsize, MPI_Aint* position) {
  (void)datarep;
  CALL(SMPI_OP_PACK_EXTERNAL, A(inbuf), A(incount), A(datatype), A(outbuf),
       A(outsize), A(position), A(0));
}
int MPI_Unpack_external(const char datarep[], const void* inbuf,
                        MPI_Aint insize, MPI_Aint* position, void* outbuf,
                        int outcount, MPI_Datatype datatype) {
  (void)datarep;
  CALL(SMPI_OP_PACK_EXTERNAL, A(outbuf), A(outcount), A(datatype), A(inbuf),
       A(insize), A(position), A(1));
}
int MPI_Pack_external_size(const char datarep[], int incount,
                           MPI_Datatype datatype, MPI_Aint* size) {
  (void)datarep;
  CALL(SMPI_OP_PACK_EXTERNAL, A(0), A(incount), A(datatype), A(0), A(0),
       A(size), A(2));
}
int MPI_Type_match_size(int typeclass, int size, MPI_Datatype* datatype) {
  CALL(SMPI_OP_TYPE_MATCH_SIZE, A(typeclass), A(size), A(datatype));
}
int MPI_Cart_map(MPI_Comm comm, int ndims, const int* dims,
                 const int* periods, int* newrank) {
  (void)periods;
  CALL(SMPI_OP_TOPO_MAP, A(comm), A(ndims), A(dims), A(newrank), A(0));
}
int MPI_Graph_map(MPI_Comm comm, int nnodes, const int* index,
                  const int* edges, int* newrank) {
  (void)index;
  (void)edges;
  CALL(SMPI_OP_TOPO_MAP, A(comm), A(1), A(nnodes), A(newrank), A(1));
}
int MPI_Dist_graph_create(MPI_Comm comm, int n, const int sources[],
                          const int degrees[], const int destinations[],
                          const int weights[], MPI_Info info, int reorder,
                          MPI_Comm* newcomm) {
  (void)info;
  (void)reorder;
  CALL(SMPI_OP_DIST_GRAPH_CREATE, A(comm), A(n), A(sources), A(degrees),
       A(destinations), A(weights), A(newcomm), A(0), A(0));
}
int MPI_Dist_graph_create_adjacent(MPI_Comm comm, int indegree,
                                   const int sources[],
                                   const int sourceweights[], int outdegree,
                                   const int destinations[],
                                   const int destweights[], MPI_Info info,
                                   int reorder, MPI_Comm* newcomm) {
  (void)info;
  (void)reorder;
  CALL(SMPI_OP_DIST_GRAPH_CREATE, A(comm), A(indegree), A(sources),
       A(outdegree), A(destinations), A(sourceweights), A(newcomm), A(1),
       A(destweights));
}
int MPI_Dist_graph_neighbors_count(MPI_Comm comm, int* indegree,
                                   int* outdegree, int* weighted) {
  CALL(SMPI_OP_DIST_GRAPH_NEIGHBORS, A(comm), A(indegree), A(outdegree),
       A(weighted), A(0), A(0), A(0), A(0));
}
int MPI_Dist_graph_neighbors(MPI_Comm comm, int maxindegree, int sources[],
                             int sourceweights[], int maxoutdegree,
                             int destinations[], int destweights[]) {
  CALL(SMPI_OP_DIST_GRAPH_NEIGHBORS, A(comm), A(maxindegree), A(sources),
       A(sourceweights), A(maxoutdegree), A(destinations), A(destweights),
       A(1));
}
int MPI_Cancel(MPI_Request* request) {
  CALL(SMPI_OP_CANCEL, A(request));
}
int MPI_Test_cancelled(const MPI_Status* status, int* flag) {
  /* purely local: the cancelled flag lives in the status struct */
  *flag = status ? status->cancelled_ : 0;
  return MPI_SUCCESS;
}
int MPI_Comm_test_inter(MPI_Comm comm, int* flag) {
  CALL(SMPI_OP_COMM_TEST_INTER, A(comm), A(flag));
}
int MPI_Comm_remote_size(MPI_Comm comm, int* size) {
  CALL(SMPI_OP_COMM_REMOTE_SIZE, A(comm), A(size));
}
int MPI_Intercomm_create(MPI_Comm local_comm, int local_leader,
                         MPI_Comm peer_comm, int remote_leader, int tag,
                         MPI_Comm* newintercomm) {
  CALL(SMPI_OP_INTERCOMM_CREATE, A(local_comm), A(local_leader),
       A(peer_comm), A(remote_leader), A(tag), A(newintercomm));
}
int MPI_Intercomm_merge(MPI_Comm intercomm, int high,
                        MPI_Comm* newintracomm) {
  CALL(SMPI_OP_INTERCOMM_MERGE, A(intercomm), A(high),
       A(newintracomm));
}
int MPI_Comm_set_name(MPI_Comm comm, const char* name) {
  CALL(SMPI_OP_COMM_SET_NAME, A(comm), A(name));
}
int MPI_Comm_create_group(MPI_Comm comm, MPI_Group group, int tag,
                          MPI_Comm* newcomm) {
  CALL(SMPI_OP_COMM_CREATE_GROUP, A(comm), A(group), A(tag), A(newcomm));
}
int MPI_Comm_idup(MPI_Comm comm, MPI_Comm* newcomm,
                  MPI_Request* request) {
  CALL(SMPI_OP_COMM_IDUP, A(comm), A(newcomm), A(request));
}
int MPI_Comm_dup_with_info(MPI_Comm comm, MPI_Info info,
                           MPI_Comm* newcomm) {
  (void)info;
  return MPI_Comm_dup(comm, newcomm);
}
int MPI_Comm_set_info(MPI_Comm comm, MPI_Info info) {
  (void)comm; (void)info;
  return MPI_SUCCESS;
}
int MPI_Comm_get_info(MPI_Comm comm, MPI_Info* info) {
  (void)comm;
  *info = MPI_INFO_NULL;
  return MPI_SUCCESS;
}
int MPI_Comm_split_type(MPI_Comm comm, int split_type, int key,
                        MPI_Info info, MPI_Comm* newcomm) {
  (void)info;
  CALL(SMPI_OP_COMM_SPLIT_TYPE, A(comm), A(split_type), A(key),
       A(newcomm));
}
int MPI_Comm_compare(MPI_Comm comm1, MPI_Comm comm2, int* result) {
  CALL(SMPI_OP_COMM_COMPARE, A(comm1), A(comm2), A(result));
}
int MPI_Group_union(MPI_Group group1, MPI_Group group2,
                    MPI_Group* newgroup) {
  CALL(SMPI_OP_GROUP_SETOP, A(group1), A(group2), A(newgroup), 0, 0, 0);
}
int MPI_Group_intersection(MPI_Group group1, MPI_Group group2,
                           MPI_Group* newgroup) {
  CALL(SMPI_OP_GROUP_SETOP, A(group1), A(group2), A(newgroup), 1, 0, 0);
}
int MPI_Group_difference(MPI_Group group1, MPI_Group group2,
                         MPI_Group* newgroup) {
  CALL(SMPI_OP_GROUP_SETOP, A(group1), A(group2), A(newgroup), 2, 0, 0);
}
int MPI_Group_range_excl(MPI_Group group, int n, int ranges[][3],
                         MPI_Group* newgroup) {
  CALL(SMPI_OP_GROUP_SETOP, A(group), 0, A(newgroup), 3, A(n),
       A(ranges));
}
int MPI_Group_translate_ranks(MPI_Group group1, int n, const int* ranks1,
                              MPI_Group group2, int* ranks2) {
  CALL(SMPI_OP_GROUP_TRANSLATE, A(group1), A(n), A(ranks1), A(group2),
       A(ranks2));
}
int MPI_Group_compare(MPI_Group group1, MPI_Group group2, int* result) {
  CALL(SMPI_OP_GROUP_COMPARE, A(group1), A(group2), A(result));
}
static int smpi_info_counter = 2; /* 1 is MPI_INFO_ENV (empty) */
/* Info objects are a pure C-side key/value store: the simulation kernel
 * treats hints as opaque, so no dispatch round-trip is needed (the
 * reference's smpi_info.cpp is likewise a plain std::map). */
typedef struct smpi_info_kv {
  char key[MPI_MAX_INFO_KEY + 1];
  char val[MPI_MAX_INFO_VAL + 1];
  struct smpi_info_kv* next;
} smpi_info_kv;
static smpi_info_kv** smpi_info_store = 0;
static int smpi_info_cap = 0;
/* grow-on-demand handle table: info/infomany creates thousands */
static int smpi_info_ok(int h) {
  if (h <= 0 || h >= smpi_info_counter) return 0;
  if (h >= smpi_info_cap) {
    int i, ncap = smpi_info_cap ? smpi_info_cap * 2 : 1024;
    while (ncap <= h) ncap *= 2;
    smpi_info_store =
        (smpi_info_kv**)realloc(smpi_info_store, ncap * sizeof(*smpi_info_store));
    for (i = smpi_info_cap; i < ncap; i++) smpi_info_store[i] = 0;
    smpi_info_cap = ncap;
  }
  return 1;
}

int MPI_Info_create(MPI_Info* info) {
  *info = smpi_info_counter++;
  if (smpi_info_ok(*info)) smpi_info_store[*info] = 0;
  return MPI_SUCCESS;
}
static int smpi_strcpy_n(char* dst, const char* src, int cap) {
  int i = 0;
  for (; src[i] && i < cap; i++) dst[i] = src[i];
  dst[i] = 0;
  return i;
}
static int smpi_streq(const char* a, const char* b) {
  while (*a && *a == *b) { a++; b++; }
  return *a == *b;
}
int MPI_Info_set(MPI_Info info, const char* key, const char* value) {
  smpi_info_kv* kv;
  if (!smpi_info_ok(info)) return MPI_ERR_INFO;
  for (kv = smpi_info_store[info]; kv; kv = kv->next)
    if (smpi_streq(kv->key, key)) {
      smpi_strcpy_n(kv->val, value, MPI_MAX_INFO_VAL);
      return MPI_SUCCESS;
    }
  kv = (smpi_info_kv*)malloc(sizeof(smpi_info_kv));
  smpi_strcpy_n(kv->key, key, MPI_MAX_INFO_KEY);
  smpi_strcpy_n(kv->val, value, MPI_MAX_INFO_VAL);
  kv->next = 0;
  /* append (MPI_Info_get_nthkey exposes insertion order) */
  if (!smpi_info_store[info]) smpi_info_store[info] = kv;
  else {
    smpi_info_kv* tail = smpi_info_store[info];
    while (tail->next) tail = tail->next;
    tail->next = kv;
  }
  return MPI_SUCCESS;
}
static smpi_info_kv* smpi_info_find(MPI_Info info, const char* key) {
  smpi_info_kv* kv;
  if (!smpi_info_ok(info)) return 0;
  for (kv = smpi_info_store[info]; kv; kv = kv->next)
    if (smpi_streq(kv->key, key)) return kv;
  return 0;
}
int MPI_Info_get(MPI_Info info, const char* key, int valuelen, char* value,
                 int* flag) {
  smpi_info_kv* kv = smpi_info_find(info, key);
  if (flag) *flag = kv != 0;
  if (kv && value) smpi_strcpy_n(value, kv->val, valuelen);
  return MPI_SUCCESS;
}
int MPI_Info_get_valuelen(MPI_Info info, const char* key, int* valuelen,
                          int* flag) {
  smpi_info_kv* kv = smpi_info_find(info, key);
  if (flag) *flag = kv != 0;
  if (kv && valuelen) {
    int n = 0;
    while (kv->val[n]) n++;
    *valuelen = n;
  }
  return MPI_SUCCESS;
}
int MPI_Info_get_nkeys(MPI_Info info, int* nkeys) {
  int n = 0;
  smpi_info_kv* kv;
  if (!smpi_info_ok(info)) return MPI_ERR_INFO;
  for (kv = smpi_info_store[info]; kv; kv = kv->next) n++;
  *nkeys = n;
  return MPI_SUCCESS;
}
int MPI_Info_get_nthkey(MPI_Info info, int n, char* key) {
  smpi_info_kv* kv;
  if (!smpi_info_ok(info)) return MPI_ERR_INFO;
  kv = smpi_info_store[info];
  while (n-- > 0 && kv) kv = kv->next;
  if (!kv) return MPI_ERR_ARG;
  smpi_strcpy_n(key, kv->key, MPI_MAX_INFO_KEY);
  return MPI_SUCCESS;
}
int MPI_Info_delete(MPI_Info info, const char* key) {
  smpi_info_kv **p, *kv;
  if (!smpi_info_ok(info)) return MPI_ERR_INFO;
  for (p = &smpi_info_store[info]; (kv = *p); p = &kv->next)
    if (smpi_streq(kv->key, key)) {
      *p = kv->next;
      free(kv);
      return MPI_SUCCESS;
    }
  return MPI_ERR_INFO;
}
int MPI_Info_dup(MPI_Info info, MPI_Info* newinfo) {
  smpi_info_kv* kv;
  MPI_Info_create(newinfo);
  if (smpi_info_ok(info))
    for (kv = smpi_info_store[info]; kv; kv = kv->next)
      MPI_Info_set(*newinfo, kv->key, kv->val);
  return MPI_SUCCESS;
}
int MPI_Info_free(MPI_Info* info) {
  if (smpi_info_ok(*info)) {
    smpi_info_kv* kv = smpi_info_store[*info];
    while (kv) {
      smpi_info_kv* next = kv->next;
      free(kv);
      kv = next;
    }
    smpi_info_store[*info] = 0;
  }
  *info = MPI_INFO_NULL;
  return MPI_SUCCESS;
}

/* -- dispatch-backed group/comm/attr/window calls -------------------------- */
int MPI_Comm_get_name(MPI_Comm comm, char* name, int* resultlen) {
  CALL(SMPI_OP_COMM_GET_NAME, A(comm), A(name), A(resultlen));
}
int MPI_Comm_create(MPI_Comm comm, MPI_Group group, MPI_Comm* newcomm) {
  CALL(SMPI_OP_COMM_CREATE, A(comm), A(group), A(newcomm));
}
int MPI_Group_incl(MPI_Group group, int n, const int* ranks,
                   MPI_Group* newgroup) {
  CALL(SMPI_OP_GROUP_INCL, A(group), A(n), A(ranks), A(newgroup));
}
int MPI_Group_excl(MPI_Group group, int n, const int* ranks,
                   MPI_Group* newgroup) {
  CALL(SMPI_OP_GROUP_EXCL, A(group), A(n), A(ranks), A(newgroup));
}
int MPI_Group_range_incl(MPI_Group group, int n, int ranges[][3],
                         MPI_Group* newgroup) {
  CALL(SMPI_OP_GROUP_RANGE_INCL, A(group), A(n), A(ranges), A(newgroup));
}
int MPI_Comm_create_keyval(MPI_Comm_copy_attr_function* copy_fn,
                           MPI_Comm_delete_attr_function* delete_fn,
                           int* keyval, void* extra_state) {
  CALL(SMPI_OP_KEYVAL_CREATE, A(copy_fn), A(delete_fn), A(keyval),
       A(extra_state));
}
/* the portable dup fn (reference smpi_keyvals.hpp exposes it the same
   way: copies the value verbatim and accepts the copy) */
int MPI_DUP_FN(MPI_Comm oldcomm, int keyval, void* extra_state,
               void* attribute_val_in, void* attribute_val_out, int* flag) {
  (void)oldcomm; (void)keyval; (void)extra_state;
  *(void**)attribute_val_out = attribute_val_in;
  *flag = 1;
  return MPI_SUCCESS;
}
int MPI_Type_create_keyval(MPI_Type_copy_attr_function* copy_fn,
                           MPI_Type_delete_attr_function* delete_fn,
                           int* keyval, void* extra_state) {
  CALL(SMPI_OP_TYPE_KEYVAL_CREATE, A(copy_fn), A(delete_fn), A(keyval),
       A(extra_state));
}
int MPI_Type_free_keyval(int* keyval) {
  CALL(SMPI_OP_KEYVAL_FREE, A(keyval));
}
int MPI_Type_set_attr(MPI_Datatype type, int keyval, void* value) {
  CALL(SMPI_OP_TYPE_SET_ATTR, A(type), A(keyval), A(value));
}
int MPI_Type_get_attr(MPI_Datatype type, int keyval, void* value,
                      int* flag) {
  CALL(SMPI_OP_TYPE_GET_ATTR, A(type), A(keyval), A(value), A(flag));
}
int MPI_Type_delete_attr(MPI_Datatype type, int keyval) {
  CALL(SMPI_OP_TYPE_DELETE_ATTR, A(type), A(keyval));
}
int MPI_Comm_free_keyval(int* keyval) {
  CALL(SMPI_OP_KEYVAL_FREE, A(keyval));
}
int MPI_Comm_set_attr(MPI_Comm comm, int keyval, void* value) {
  CALL(SMPI_OP_ATTR_PUT, A(comm), A(keyval), A(value));
}
int MPI_Comm_get_attr(MPI_Comm comm, int keyval, void* value, int* flag) {
  CALL(SMPI_OP_ATTR_GET, A(comm), A(keyval), A(value), A(flag));
}
int MPI_Comm_delete_attr(MPI_Comm comm, int keyval) {
  CALL(SMPI_OP_ATTR_DELETE, A(comm), A(keyval));
}
int MPI_Keyval_create(MPI_Copy_function* copy_fn,
                      MPI_Delete_function* delete_fn, int* keyval,
                      void* extra_state) {
  return MPI_Comm_create_keyval(copy_fn, delete_fn, keyval, extra_state);
}
int MPI_Keyval_free(int* keyval) { return MPI_Comm_free_keyval(keyval); }
int MPI_Attr_put(MPI_Comm comm, int keyval, void* value) {
  return MPI_Comm_set_attr(comm, keyval, value);
}
int MPI_Attr_get(MPI_Comm comm, int keyval, void* value, int* flag) {
  return MPI_Comm_get_attr(comm, keyval, value, flag);
}
int MPI_Attr_delete(MPI_Comm comm, int keyval) {
  return MPI_Comm_delete_attr(comm, keyval);
}
/* Per-window info store, kept entirely C-side like the info objects
 * themselves: the simulation kernel treats hints as opaque
 * (rma/win_info checks the set->get round trip). */
static MPI_Info* smpi_win_info_tbl = 0;
static int smpi_win_info_cap = 0;
static MPI_Info* smpi_win_info_slot(MPI_Win win) {
  int i;
  if (win < 0) return 0;
  if (win >= smpi_win_info_cap) {
    int ncap = smpi_win_info_cap ? smpi_win_info_cap * 2 : 64;
    MPI_Info* grown;
    while (ncap <= win) ncap *= 2;
    grown = (MPI_Info*)realloc(smpi_win_info_tbl, ncap * sizeof(MPI_Info));
    if (!grown) return 0;   /* out of memory: hints are best-effort */
    smpi_win_info_tbl = grown;
    for (i = smpi_win_info_cap; i < ncap; i++)
      smpi_win_info_tbl[i] = MPI_INFO_NULL;
    smpi_win_info_cap = ncap;
  }
  return &smpi_win_info_tbl[win];
}
static void smpi_win_record_info(const MPI_Win* win, MPI_Info info) {
  MPI_Info* slot;
  if (!win) return;
  slot = smpi_win_info_slot(*win);
  if (!slot) return;
  if (*slot != MPI_INFO_NULL) MPI_Info_free(slot);
  if (info != MPI_INFO_NULL) MPI_Info_dup(info, slot);
  else *slot = MPI_INFO_NULL;
}

int MPI_Win_create(void* base, MPI_Aint size, int disp_unit,
                   MPI_Info info, MPI_Comm comm, MPI_Win* win) {
  int rc;
  smpi_arg_t args_[] = {A(base), A(size), A(disp_unit), A(comm), A(win)};
  if (!smpi_dispatch) return MPI_ERR_INTERN;
  rc = smpi_dispatch(SMPI_OP_WIN_CREATE, args_);
  if (rc == MPI_SUCCESS) smpi_win_record_info(win, info);
  return rc;
}
int MPI_Win_free(MPI_Win* win) {
  if (win && *win >= 0 && *win < smpi_win_info_cap &&
      smpi_win_info_tbl[*win] != MPI_INFO_NULL)
    MPI_Info_free(&smpi_win_info_tbl[*win]);
  CALL(SMPI_OP_WIN_FREE, A(win));
}
int MPI_Win_fence(int assertion, MPI_Win win) {
  CALL(SMPI_OP_WIN_FENCE, A(assertion), A(win));
}
int MPI_Win_get_attr(MPI_Win win, int keyval, void* value, int* flag) {
  CALL(SMPI_OP_WIN_GET_ATTR, A(win), A(keyval), A(value), A(flag));
}
int MPI_Win_set_attr(MPI_Win win, int keyval, void* value) {
  CALL(SMPI_OP_WIN_SET_ATTR, A(win), A(keyval), A(value));
}

/* -- one-sided communication (MPI-3 RMA) ---------------------------------- */
int MPI_Put(const void* origin_addr, int origin_count,
            MPI_Datatype origin_datatype, int target_rank,
            MPI_Aint target_disp, int target_count,
            MPI_Datatype target_datatype, MPI_Win win) {
  CALL(SMPI_OP_PUT, A(origin_addr), A(origin_count), A(origin_datatype),
       A(target_rank), A(target_disp), A(target_count), A(target_datatype),
       A(win));
}
int MPI_Get(void* origin_addr, int origin_count,
            MPI_Datatype origin_datatype, int target_rank,
            MPI_Aint target_disp, int target_count,
            MPI_Datatype target_datatype, MPI_Win win) {
  CALL(SMPI_OP_GET, A(origin_addr), A(origin_count), A(origin_datatype),
       A(target_rank), A(target_disp), A(target_count), A(target_datatype),
       A(win));
}
int MPI_Accumulate(const void* origin_addr, int origin_count,
                   MPI_Datatype origin_datatype, int target_rank,
                   MPI_Aint target_disp, int target_count,
                   MPI_Datatype target_datatype, MPI_Op op, MPI_Win win) {
  CALL(SMPI_OP_ACCUMULATE, A(origin_addr), A(origin_count),
       A(origin_datatype), A(target_rank), A(target_disp), A(target_count),
       A(target_datatype), A(op), A(win));
}
int MPI_Get_accumulate(const void* origin_addr, int origin_count,
                       MPI_Datatype origin_datatype, void* result_addr,
                       int result_count, MPI_Datatype result_datatype,
                       int target_rank, MPI_Aint target_disp,
                       int target_count, MPI_Datatype target_datatype,
                       MPI_Op op, MPI_Win win) {
  CALL(SMPI_OP_GET_ACCUMULATE, A(origin_addr), A(origin_count),
       A(origin_datatype), A(result_addr), A(result_count),
       A(result_datatype), A(target_rank), A(target_disp), A(target_count),
       A(target_datatype), A(op), A(win));
}
int MPI_Fetch_and_op(const void* origin_addr, void* result_addr,
                     MPI_Datatype datatype, int target_rank,
                     MPI_Aint target_disp, MPI_Op op, MPI_Win win) {
  CALL(SMPI_OP_FETCH_AND_OP, A(origin_addr), A(result_addr), A(datatype),
       A(target_rank), A(target_disp), A(op), A(win));
}
int MPI_Compare_and_swap(const void* origin_addr, const void* compare_addr,
                         void* result_addr, MPI_Datatype datatype,
                         int target_rank, MPI_Aint target_disp,
                         MPI_Win win) {
  CALL(SMPI_OP_COMPARE_AND_SWAP, A(origin_addr), A(compare_addr),
       A(result_addr), A(datatype), A(target_rank), A(target_disp), A(win));
}
int MPI_Rput(const void* origin_addr, int origin_count,
             MPI_Datatype origin_datatype, int target_rank,
             MPI_Aint target_disp, int target_count,
             MPI_Datatype target_datatype, MPI_Win win,
             MPI_Request* request) {
  CALL(SMPI_OP_RPUT, A(origin_addr), A(origin_count), A(origin_datatype),
       A(target_rank), A(target_disp), A(target_count), A(target_datatype),
       A(win), A(request));
}
int MPI_Rget(void* origin_addr, int origin_count,
             MPI_Datatype origin_datatype, int target_rank,
             MPI_Aint target_disp, int target_count,
             MPI_Datatype target_datatype, MPI_Win win,
             MPI_Request* request) {
  CALL(SMPI_OP_RGET, A(origin_addr), A(origin_count), A(origin_datatype),
       A(target_rank), A(target_disp), A(target_count), A(target_datatype),
       A(win), A(request));
}
int MPI_Raccumulate(const void* origin_addr, int origin_count,
                    MPI_Datatype origin_datatype, int target_rank,
                    MPI_Aint target_disp, int target_count,
                    MPI_Datatype target_datatype, MPI_Op op, MPI_Win win,
                    MPI_Request* request) {
  CALL(SMPI_OP_RACCUMULATE, A(origin_addr), A(origin_count),
       A(origin_datatype), A(target_rank), A(target_disp), A(target_count),
       A(target_datatype), A(op), A(win), A(request));
}
int MPI_Rget_accumulate(const void* origin_addr, int origin_count,
                        MPI_Datatype origin_datatype, void* result_addr,
                        int result_count, MPI_Datatype result_datatype,
                        int target_rank, MPI_Aint target_disp,
                        int target_count, MPI_Datatype target_datatype,
                        MPI_Op op, MPI_Win win, MPI_Request* request) {
  CALL(SMPI_OP_RGET_ACCUMULATE, A(origin_addr), A(origin_count),
       A(origin_datatype), A(result_addr), A(result_count),
       A(result_datatype), A(target_rank), A(target_disp), A(target_count),
       A(target_datatype), A(op), A(win), A(request));
}
int MPI_Win_allocate(MPI_Aint size, int disp_unit, MPI_Info info,
                     MPI_Comm comm, void* baseptr, MPI_Win* win) {
  int rc;
  smpi_arg_t args_[] = {A(size), A(disp_unit), A(info), A(comm),
                        A(baseptr), A(win)};
  if (!smpi_dispatch) return MPI_ERR_INTERN;
  rc = smpi_dispatch(SMPI_OP_WIN_ALLOCATE, args_);
  if (rc == MPI_SUCCESS) smpi_win_record_info(win, info);
  return rc;
}
int MPI_Win_allocate_shared(MPI_Aint size, int disp_unit, MPI_Info info,
                            MPI_Comm comm, void* baseptr, MPI_Win* win) {
  int rc;
  smpi_arg_t args_[] = {A(size), A(disp_unit), A(info), A(comm),
                        A(baseptr), A(win)};
  if (!smpi_dispatch) return MPI_ERR_INTERN;
  rc = smpi_dispatch(SMPI_OP_WIN_ALLOCATE_SHARED, args_);
  if (rc == MPI_SUCCESS) smpi_win_record_info(win, info);
  return rc;
}
int MPI_Win_create_dynamic(MPI_Info info, MPI_Comm comm, MPI_Win* win) {
  int rc;
  smpi_arg_t args_[] = {A(info), A(comm), A(win)};
  if (!smpi_dispatch) return MPI_ERR_INTERN;
  rc = smpi_dispatch(SMPI_OP_WIN_CREATE_DYNAMIC, args_);
  if (rc == MPI_SUCCESS) smpi_win_record_info(win, info);
  return rc;
}
int MPI_Win_attach(MPI_Win win, void* base, MPI_Aint size) {
  CALL(SMPI_OP_WIN_ATTACH, A(win), A(base), A(size));
}
int MPI_Win_detach(MPI_Win win, const void* base) {
  CALL(SMPI_OP_WIN_DETACH, A(win), A(base));
}
int MPI_Win_shared_query(MPI_Win win, int rank, MPI_Aint* size,
                         int* disp_unit, void* baseptr) {
  CALL(SMPI_OP_WIN_SHARED_QUERY, A(win), A(rank), A(size), A(disp_unit),
       A(baseptr));
}
int MPI_Win_lock(int lock_type, int rank, int assertion, MPI_Win win) {
  CALL(SMPI_OP_WIN_LOCK, A(lock_type), A(rank), A(assertion), A(win));
}
int MPI_Win_unlock(int rank, MPI_Win win) {
  CALL(SMPI_OP_WIN_UNLOCK, A(rank), A(win));
}
int MPI_Win_lock_all(int assertion, MPI_Win win) {
  CALL(SMPI_OP_WIN_LOCK_ALL, A(assertion), A(win));
}
int MPI_Win_unlock_all(MPI_Win win) {
  CALL(SMPI_OP_WIN_UNLOCK_ALL, A(win));
}
int MPI_Win_flush(int rank, MPI_Win win) {
  CALL(SMPI_OP_WIN_FLUSH, A(rank), A(win));
}
int MPI_Win_flush_local(int rank, MPI_Win win) {
  CALL(SMPI_OP_WIN_FLUSH_LOCAL, A(rank), A(win));
}
int MPI_Win_flush_all(MPI_Win win) {
  CALL(SMPI_OP_WIN_FLUSH_ALL, A(win));
}
int MPI_Win_flush_local_all(MPI_Win win) {
  CALL(SMPI_OP_WIN_FLUSH_LOCAL_ALL, A(win));
}
int MPI_Win_sync(MPI_Win win) {
  CALL(SMPI_OP_WIN_SYNC, A(win));
}
int MPI_Win_start(MPI_Group group, int assertion, MPI_Win win) {
  CALL(SMPI_OP_WIN_START, A(group), A(assertion), A(win));
}
int MPI_Win_complete(MPI_Win win) {
  CALL(SMPI_OP_WIN_COMPLETE, A(win));
}
int MPI_Win_post(MPI_Group group, int assertion, MPI_Win win) {
  CALL(SMPI_OP_WIN_POST, A(group), A(assertion), A(win));
}
int MPI_Win_wait(MPI_Win win) {
  CALL(SMPI_OP_WIN_WAIT, A(win));
}
int MPI_Win_test(MPI_Win win, int* flag) {
  CALL(SMPI_OP_WIN_TEST, A(win), A(flag));
}
int MPI_Win_get_group(MPI_Win win, MPI_Group* group) {
  CALL(SMPI_OP_WIN_GET_GROUP, A(win), A(group));
}
int MPI_Win_set_name(MPI_Win win, const char* name) {
  CALL(SMPI_OP_WIN_SET_NAME, A(win), A(name));
}
int MPI_Win_get_name(MPI_Win win, char* name, int* resultlen) {
  CALL(SMPI_OP_WIN_GET_NAME, A(win), A(name), A(resultlen));
}
int MPI_Win_create_keyval(MPI_Win_copy_attr_function* copy_fn,
                          MPI_Win_delete_attr_function* delete_fn,
                          int* keyval, void* extra_state) {
  CALL(SMPI_OP_WIN_KEYVAL_CREATE, A(copy_fn), A(delete_fn), A(keyval),
       A(extra_state));
}
int MPI_Win_free_keyval(int* keyval) {
  CALL(SMPI_OP_WIN_KEYVAL_FREE, A(keyval));
}
int MPI_Win_delete_attr(MPI_Win win, int keyval) {
  CALL(SMPI_OP_WIN_DELETE_ATTR, A(win), A(keyval));
}
int MPI_Win_set_errhandler(MPI_Win win, MPI_Errhandler errhandler) {
  CALL(SMPI_OP_WIN_SET_ERRHANDLER, A(win), A(errhandler));
}
int MPI_Win_get_errhandler(MPI_Win win, MPI_Errhandler* errhandler) {
  CALL(SMPI_OP_WIN_GET_ERRHANDLER, A(win), A(errhandler));
}
int MPI_Win_create_errhandler(MPI_Win_errhandler_function* fn,
                              MPI_Errhandler* errhandler) {
  CALL(SMPI_OP_ERRHANDLER_CREATE, A(fn), A(errhandler));
}
int MPI_Win_call_errhandler(MPI_Win win, int errorcode) {
  CALL(SMPI_OP_WIN_CALL_ERRHANDLER, A(win), A(errorcode));
}
int MPI_Win_get_info(MPI_Win win, MPI_Info* info) {
  MPI_Info* slot = smpi_win_info_slot(win);
  if (slot && *slot != MPI_INFO_NULL) return MPI_Info_dup(*slot, info);
  return MPI_Info_create(info);
}
int MPI_Win_set_info(MPI_Win win, MPI_Info info) {
  /* merge the supplied hints into the window's info (MPI-3 11.2.7) */
  MPI_Info* slot = smpi_win_info_slot(win);
  int n = 0, i;
  char key[MPI_MAX_INFO_KEY + 1], val[MPI_MAX_INFO_VAL + 1];
  int flag;
  if (!slot || info == MPI_INFO_NULL) return MPI_SUCCESS;
  if (*slot == MPI_INFO_NULL) MPI_Info_create(slot);
  MPI_Info_get_nkeys(info, &n);
  for (i = 0; i < n; i++) {
    MPI_Info_get_nthkey(info, i, key);
    MPI_Info_get(info, key, MPI_MAX_INFO_VAL, val, &flag);
    if (flag) MPI_Info_set(*slot, key, val);
  }
  return MPI_SUCCESS;
}

/* -- struct datatypes -------------------------------------------------------- */
int MPI_Type_create_struct(int count, const int* blocklengths,
                           const MPI_Aint* displacements,
                           const MPI_Datatype* types,
                           MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_STRUCT, A(count), A(blocklengths), A(displacements),
       A(types), A(newtype));
}
int MPI_Type_struct(int count, int* blocklengths, MPI_Aint* displacements,
                    MPI_Datatype* types, MPI_Datatype* newtype) {
  return MPI_Type_create_struct(count, blocklengths, displacements, types,
                                newtype);
}
int MPI_Type_extent(MPI_Datatype datatype, MPI_Aint* extent) {
  MPI_Aint lb;
  return MPI_Type_get_extent(datatype, &lb, extent);
}
int MPI_Type_create_resized(MPI_Datatype oldtype, MPI_Aint lb,
                            MPI_Aint extent, MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_RESIZED, A(oldtype), A(lb), A(extent), A(newtype));
}
int MPI_Type_indexed(int count, const int* blocklengths,
                     const int* displacements, MPI_Datatype oldtype,
                     MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_INDEXED, A(count), A(blocklengths), A(displacements),
       A(oldtype), A(newtype), 0);
}
int MPI_Type_create_hindexed(int count, const int* blocklengths,
                             const MPI_Aint* displacements,
                             MPI_Datatype oldtype, MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_INDEXED, A(count), A(blocklengths), A(displacements),
       A(oldtype), A(newtype), 1);
}
int MPI_Type_hindexed(int count, int* blocklengths,
                      MPI_Aint* displacements, MPI_Datatype oldtype,
                      MPI_Datatype* newtype) {
  return MPI_Type_create_hindexed(count, blocklengths, displacements,
                                  oldtype, newtype);
}
int MPI_Type_create_hvector(int count, int blocklength, MPI_Aint stride,
                            MPI_Datatype oldtype, MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_HVECTOR, A(count), A(blocklength), A(stride),
       A(oldtype), A(newtype));
}
int MPI_Type_hvector(int count, int blocklength, MPI_Aint stride,
                     MPI_Datatype oldtype, MPI_Datatype* newtype) {
  return MPI_Type_create_hvector(count, blocklength, stride, oldtype,
                                 newtype);
}
int MPI_Type_create_indexed_block(int count, int blocklength,
                                  const int* displacements,
                                  MPI_Datatype oldtype,
                                  MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_INDEXED_BLOCK, A(count), A(blocklength),
       A(displacements), A(oldtype), A(newtype), 0);
}
int MPI_Type_create_hindexed_block(int count, int blocklength,
                                   const MPI_Aint* displacements,
                                   MPI_Datatype oldtype,
                                   MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_INDEXED_BLOCK, A(count), A(blocklength),
       A(displacements), A(oldtype), A(newtype), 1);
}
int MPI_Type_dup(MPI_Datatype oldtype, MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_DUP, A(oldtype), A(newtype));
}
int MPI_Type_create_subarray(int ndims, const int* array_of_sizes,
                             const int* array_of_subsizes,
                             const int* array_of_starts, int order,
                             MPI_Datatype oldtype, MPI_Datatype* newtype) {
  CALL(SMPI_OP_TYPE_SUBARRAY, A(ndims), A(array_of_sizes),
       A(array_of_subsizes), A(array_of_starts), A(order), A(oldtype),
       A(newtype));
}
int MPI_Type_get_true_extent(MPI_Datatype datatype, MPI_Aint* true_lb,
                             MPI_Aint* true_extent) {
  /* data travels packed here: the true extent never exceeds the
   * declared extent, which is all callers rely on for sizing */
  CALL(SMPI_OP_TYPE_GET_EXTENT, A(datatype), A(true_lb), A(true_extent),
       A(1));
}

int MPI_Type_get_name(MPI_Datatype datatype, char* name, int* resultlen) {
  CALL(SMPI_OP_TYPE_GET_NAME, A(datatype), A(name), A(resultlen), A(0));
}
int MPI_Type_set_name(MPI_Datatype datatype, const char* name) {
  CALL(SMPI_OP_TYPE_GET_NAME, A(datatype), A(name), A(0), A(1));
}

/* -- cartesian topologies ------------------------------------------------------ */
int MPI_Cart_create(MPI_Comm comm, int ndims, const int* dims,
                    const int* periods, int reorder, MPI_Comm* newcomm) {
  CALL(SMPI_OP_CART_CREATE, A(comm), A(ndims), A(dims), A(periods),
       A(reorder), A(newcomm));
}
int MPI_Cart_get(MPI_Comm comm, int maxdims, int* dims, int* periods,
                 int* coords) {
  CALL(SMPI_OP_CART_GET, A(comm), A(maxdims), A(dims), A(periods),
       A(coords));
}
int MPI_Cart_rank(MPI_Comm comm, const int* coords, int* rank) {
  CALL(SMPI_OP_CART_RANK, A(comm), A(coords), A(rank));
}
int MPI_Cart_coords(MPI_Comm comm, int rank, int maxdims, int* coords) {
  CALL(SMPI_OP_CART_COORDS, A(comm), A(rank), A(maxdims), A(coords));
}
int MPI_Cart_shift(MPI_Comm comm, int direction, int disp,
                   int* rank_source, int* rank_dest) {
  CALL(SMPI_OP_CART_SHIFT, A(comm), A(direction), A(disp), A(rank_source),
       A(rank_dest));
}
int MPI_Cart_sub(MPI_Comm comm, const int* remain_dims,
                 MPI_Comm* newcomm) {
  CALL(SMPI_OP_CART_SUB, A(comm), A(remain_dims), A(newcomm));
}
int MPI_Cartdim_get(MPI_Comm comm, int* ndims) {
  CALL(SMPI_OP_CARTDIM_GET, A(comm), A(ndims));
}
int MPI_Dims_create(int nnodes, int ndims, int* dims) {
  CALL(SMPI_OP_DIMS_CREATE, A(nnodes), A(ndims), A(dims));
}
int MPI_Topo_test(MPI_Comm comm, int* status) {
  CALL(SMPI_OP_TOPO_TEST, A(comm), A(status));
}

int MPI_Pack(const void* inbuf, int incount, MPI_Datatype datatype,
             void* outbuf, int outsize, int* position, MPI_Comm comm) {
  CALL(SMPI_OP_PACK, A(inbuf), A(incount), A(datatype), A(outbuf),
       A(outsize), A(position), A(comm), 0);
}
int MPI_Unpack(const void* inbuf, int insize, int* position, void* outbuf,
               int outcount, MPI_Datatype datatype, MPI_Comm comm) {
  CALL(SMPI_OP_PACK, A(outbuf), A(outcount), A(datatype), A(inbuf),
       A(insize), A(position), A(comm), 1);
}
int MPI_Pack_size(int incount, MPI_Datatype datatype, MPI_Comm comm,
                  int* size) {
  int one = 0;
  int rc = MPI_Type_size(datatype, &one);
  (void)comm;
  *size = incount * one;
  return rc;
}
int MPI_Graph_create(MPI_Comm comm, int nnodes, const int* index,
                     const int* edges, int reorder, MPI_Comm* newcomm) {
  CALL(SMPI_OP_GRAPH_CREATE, A(comm), A(nnodes), A(index), A(edges),
       A(reorder), A(newcomm));
}
int MPI_Graph_neighbors(MPI_Comm comm, int rank, int maxneighbors,
                        int* neighbors) {
  CALL(SMPI_OP_GRAPH_NEIGHBORS, A(comm), A(rank), A(maxneighbors),
       A(neighbors), 0);
}
int MPI_Graph_neighbors_count(MPI_Comm comm, int rank, int* nneighbors) {
  CALL(SMPI_OP_GRAPH_NEIGHBORS, A(comm), A(rank), 0, A(nneighbors), 1);
}
int MPI_Graphdims_get(MPI_Comm comm, int* nnodes, int* nedges) {
  CALL(SMPI_OP_GRAPHDIMS_GET, A(comm), A(nnodes), A(nedges));
}
int MPI_Graph_get(MPI_Comm comm, int maxindex, int maxedges, int* index,
                  int* edges) {
  CALL(SMPI_OP_GRAPH_GET, A(comm), A(maxindex), A(maxedges), A(index),
       A(edges));
}

/* -- non-blocking collectives -------------------------------------------------- */
int MPI_Ibarrier(MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IBARRIER, A(comm), A(request));
}
int MPI_Ibcast(void* buf, int count, MPI_Datatype datatype, int root,
               MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IBCAST, A(buf), A(count), A(datatype), A(root), A(comm),
       A(request));
}
int MPI_Ireduce(const void* sendbuf, void* recvbuf, int count,
                MPI_Datatype datatype, MPI_Op op, int root, MPI_Comm comm,
                MPI_Request* request) {
  CALL(SMPI_OP_IREDUCE, A(sendbuf), A(recvbuf), A(count), A(datatype),
       A(op), A(root), A(comm), A(request));
}
int MPI_Iallreduce(const void* sendbuf, void* recvbuf, int count,
                   MPI_Datatype datatype, MPI_Op op, MPI_Comm comm,
                   MPI_Request* request) {
  CALL(SMPI_OP_IALLREDUCE, A(sendbuf), A(recvbuf), A(count), A(datatype),
       A(op), A(comm), A(request));
}
int MPI_Igather(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                void* recvbuf, int recvcount, MPI_Datatype recvtype,
                int root, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IGATHER, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcount), A(recvtype), A(root), A(comm), A(request));
}
int MPI_Iscatter(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                 void* recvbuf, int recvcount, MPI_Datatype recvtype,
                 int root, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_ISCATTER, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcount), A(recvtype), A(root), A(comm), A(request));
}
int MPI_Iallgather(const void* sendbuf, int sendcount,
                   MPI_Datatype sendtype, void* recvbuf, int recvcount,
                   MPI_Datatype recvtype, MPI_Comm comm,
                   MPI_Request* request) {
  CALL(SMPI_OP_IALLGATHER, A(sendbuf), A(sendcount), A(sendtype),
       A(recvbuf), A(recvcount), A(recvtype), A(comm), A(request));
}
int MPI_Ialltoall(const void* sendbuf, int sendcount,
                  MPI_Datatype sendtype, void* recvbuf, int recvcount,
                  MPI_Datatype recvtype, MPI_Comm comm,
                  MPI_Request* request) {
  CALL(SMPI_OP_IALLTOALL, A(sendbuf), A(sendcount), A(sendtype),
       A(recvbuf), A(recvcount), A(recvtype), A(comm), A(request));
}
int MPI_Alltoallw(const void* sendbuf, const int* sendcounts,
                  const int* sdispls, const MPI_Datatype* sendtypes,
                  void* recvbuf, const int* recvcounts, const int* rdispls,
                  const MPI_Datatype* recvtypes, MPI_Comm comm) {
  CALL(SMPI_OP_ALLTOALLW, A(sendbuf), A(sendcounts), A(sdispls),
       A(sendtypes), A(recvbuf), A(recvcounts), A(rdispls), A(recvtypes),
       A(comm));
}
int MPI_Ialltoallw(const void* sendbuf, const int* sendcounts,
                   const int* sdispls, const MPI_Datatype* sendtypes,
                   void* recvbuf, const int* recvcounts,
                   const int* rdispls, const MPI_Datatype* recvtypes,
                   MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IALLTOALLW, A(sendbuf), A(sendcounts), A(sdispls),
       A(sendtypes), A(recvbuf), A(recvcounts), A(rdispls), A(recvtypes),
       A(comm), A(request));
}
int MPI_Iscatterv(const void* sendbuf, const int* sendcounts,
                  const int* displs, MPI_Datatype sendtype, void* recvbuf,
                  int recvcount, MPI_Datatype recvtype, int root,
                  MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_ISCATTERV, A(sendbuf), A(sendcounts), A(displs),
       A(sendtype), A(recvbuf), A(recvcount), A(recvtype), A(root),
       A(comm), A(request));
}
int MPI_Igatherv(const void* sendbuf, int sendcount, MPI_Datatype sendtype,
                 void* recvbuf, const int* recvcounts, const int* displs,
                 MPI_Datatype recvtype, int root, MPI_Comm comm,
                 MPI_Request* request) {
  CALL(SMPI_OP_IGATHERV, A(sendbuf), A(sendcount), A(sendtype), A(recvbuf),
       A(recvcounts), A(displs), A(recvtype), A(root), A(comm),
       A(request));
}
int MPI_Iallgatherv(const void* sendbuf, int sendcount,
                    MPI_Datatype sendtype, void* recvbuf,
                    const int* recvcounts, const int* displs,
                    MPI_Datatype recvtype, MPI_Comm comm,
                    MPI_Request* request) {
  CALL(SMPI_OP_IALLGATHERV, A(sendbuf), A(sendcount), A(sendtype),
       A(recvbuf), A(recvcounts), A(displs), A(recvtype), A(comm),
       A(request));
}
int MPI_Ialltoallv(const void* sendbuf, const int* sendcounts,
                   const int* sdispls, MPI_Datatype sendtype,
                   void* recvbuf, const int* recvcounts,
                   const int* rdispls, MPI_Datatype recvtype,
                   MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IALLTOALLV, A(sendbuf), A(sendcounts), A(sdispls),
       A(sendtype), A(recvbuf), A(recvcounts), A(rdispls), A(recvtype),
       A(comm), A(request));
}
int MPI_Ireduce_scatter(const void* sendbuf, void* recvbuf,
                        const int* recvcounts, MPI_Datatype datatype,
                        MPI_Op op, MPI_Comm comm, MPI_Request* request) {
  CALL(SMPI_OP_IREDUCE_SCATTER, A(sendbuf), A(recvbuf), A(recvcounts),
       A(datatype), A(op), A(comm), A(request), 0);
}
int MPI_Ireduce_scatter_block(const void* sendbuf, void* recvbuf,
                              int recvcount, MPI_Datatype datatype,
                              MPI_Op op, MPI_Comm comm,
                              MPI_Request* request) {
  CALL(SMPI_OP_IREDUCE_SCATTER, A(sendbuf), A(recvbuf), A(recvcount),
       A(datatype), A(op), A(comm), A(request), 1);
}
int MPI_Iscan(const void* sendbuf, void* recvbuf, int count,
              MPI_Datatype datatype, MPI_Op op, MPI_Comm comm,
              MPI_Request* request) {
  CALL(SMPI_OP_ISCAN, A(sendbuf), A(recvbuf), A(count), A(datatype),
       A(op), A(comm), A(request));
}
int MPI_Iexscan(const void* sendbuf, void* recvbuf, int count,
                MPI_Datatype datatype, MPI_Op op, MPI_Comm comm,
                MPI_Request* request) {
  CALL(SMPI_OP_IEXSCAN, A(sendbuf), A(recvbuf), A(count), A(datatype),
       A(op), A(comm), A(request));
}

/* ======================================================================
 * Fortran 77/90 bindings (reference src/smpi/bindings/smpi_f77*.cpp).
 *
 * The gfortran/flang ABI: every argument passed by reference, handles
 * are MPI_Fint (int — our C handles are ints already, so translation
 * is the identity), status is an int[MPI_STATUS_SIZE=6] laid out like
 * our MPI_Status, and symbols are lowercase with a trailing
 * underscore.  This image ships no Fortran compiler, so conformance is
 * exercised by calling these exact symbols by reference from C
 * (tests/test_smpi_fortran.py), which is ABI-equivalent to what
 * compiled F77 object code does.
 * ==================================================================== */

typedef int MPI_Fint;
#define SMPI_F2C_COMM(c) ((MPI_Comm)(*(c)))
#define SMPI_F2C_TYPE(t) ((MPI_Datatype)(*(t)))
#define SMPI_F2C_OP(o) ((MPI_Op)(*(o)))

void mpi_init_(MPI_Fint* ierr) { *ierr = MPI_Init(0, 0); }
void mpi_finalize_(MPI_Fint* ierr) { *ierr = MPI_Finalize(); }
void mpi_initialized_(MPI_Fint* flag, MPI_Fint* ierr) {
  int f; *ierr = MPI_Initialized(&f); *flag = f;
}
void mpi_abort_(MPI_Fint* comm, MPI_Fint* errorcode, MPI_Fint* ierr) {
  *ierr = MPI_Abort(SMPI_F2C_COMM(comm), *errorcode);
}
double mpi_wtime_(void) { return MPI_Wtime(); }
double mpi_wtick_(void) { return MPI_Wtick(); }

void mpi_comm_rank_(MPI_Fint* comm, MPI_Fint* rank, MPI_Fint* ierr) {
  *ierr = MPI_Comm_rank(SMPI_F2C_COMM(comm), rank);
}
void mpi_comm_size_(MPI_Fint* comm, MPI_Fint* size, MPI_Fint* ierr) {
  *ierr = MPI_Comm_size(SMPI_F2C_COMM(comm), size);
}
void mpi_comm_dup_(MPI_Fint* comm, MPI_Fint* newcomm, MPI_Fint* ierr) {
  MPI_Comm out;
  *ierr = MPI_Comm_dup(SMPI_F2C_COMM(comm), &out);
  *newcomm = (MPI_Fint)out;
}
void mpi_comm_split_(MPI_Fint* comm, MPI_Fint* color, MPI_Fint* key,
                     MPI_Fint* newcomm, MPI_Fint* ierr) {
  MPI_Comm out;
  *ierr = MPI_Comm_split(SMPI_F2C_COMM(comm), *color, *key, &out);
  *newcomm = (MPI_Fint)out;
}
void mpi_comm_free_(MPI_Fint* comm, MPI_Fint* ierr) {
  MPI_Comm c = SMPI_F2C_COMM(comm);
  *ierr = MPI_Comm_free(&c);
  *comm = (MPI_Fint)c;
}

void mpi_send_(void* buf, MPI_Fint* count, MPI_Fint* datatype,
               MPI_Fint* dest, MPI_Fint* tag, MPI_Fint* comm,
               MPI_Fint* ierr) {
  *ierr = MPI_Send(buf, *count, SMPI_F2C_TYPE(datatype), *dest, *tag,
                   SMPI_F2C_COMM(comm));
}
void mpi_recv_(void* buf, MPI_Fint* count, MPI_Fint* datatype,
               MPI_Fint* source, MPI_Fint* tag, MPI_Fint* comm,
               MPI_Fint* status, MPI_Fint* ierr) {
  *ierr = MPI_Recv(buf, *count, SMPI_F2C_TYPE(datatype), *source, *tag,
                   SMPI_F2C_COMM(comm), (MPI_Status*)status);
}
void mpi_isend_(void* buf, MPI_Fint* count, MPI_Fint* datatype,
                MPI_Fint* dest, MPI_Fint* tag, MPI_Fint* comm,
                MPI_Fint* request, MPI_Fint* ierr) {
  MPI_Request req;
  *ierr = MPI_Isend(buf, *count, SMPI_F2C_TYPE(datatype), *dest, *tag,
                    SMPI_F2C_COMM(comm), &req);
  *request = (MPI_Fint)req;
}
void mpi_irecv_(void* buf, MPI_Fint* count, MPI_Fint* datatype,
                MPI_Fint* source, MPI_Fint* tag, MPI_Fint* comm,
                MPI_Fint* request, MPI_Fint* ierr) {
  MPI_Request req;
  *ierr = MPI_Irecv(buf, *count, SMPI_F2C_TYPE(datatype), *source, *tag,
                    SMPI_F2C_COMM(comm), &req);
  *request = (MPI_Fint)req;
}
void mpi_wait_(MPI_Fint* request, MPI_Fint* status, MPI_Fint* ierr) {
  MPI_Request req = (MPI_Request)(*request);
  *ierr = MPI_Wait(&req, (MPI_Status*)status);
  *request = (MPI_Fint)req;
}
void mpi_waitall_(MPI_Fint* count, MPI_Fint* requests, MPI_Fint* statuses,
                  MPI_Fint* ierr) {
  int i, rc, n = *count;
  *ierr = MPI_SUCCESS;
  for (i = 0; i < n; i++) {   /* complete every request, keep 1st error */
    MPI_Request req = (MPI_Request)requests[i];
    rc = MPI_Wait(&req, statuses == (MPI_Fint*)0
                            ? MPI_STATUS_IGNORE
                            : (MPI_Status*)(statuses +
                                  (sizeof(MPI_Status) / sizeof(MPI_Fint))
                                  * i));
    requests[i] = (MPI_Fint)req;
    if (rc != MPI_SUCCESS && *ierr == MPI_SUCCESS) *ierr = rc;
  }
}
void mpi_test_(MPI_Fint* request, MPI_Fint* flag, MPI_Fint* status,
               MPI_Fint* ierr) {
  MPI_Request req = (MPI_Request)(*request);
  int f;
  *ierr = MPI_Test(&req, &f, (MPI_Status*)status);
  *flag = f;
  *request = (MPI_Fint)req;
}
void mpi_get_count_(MPI_Fint* status, MPI_Fint* datatype, MPI_Fint* count,
                    MPI_Fint* ierr) {
  *ierr = MPI_Get_count((MPI_Status*)status, SMPI_F2C_TYPE(datatype),
                        count);
}

void mpi_barrier_(MPI_Fint* comm, MPI_Fint* ierr) {
  *ierr = MPI_Barrier(SMPI_F2C_COMM(comm));
}
void mpi_bcast_(void* buf, MPI_Fint* count, MPI_Fint* datatype,
                MPI_Fint* root, MPI_Fint* comm, MPI_Fint* ierr) {
  *ierr = MPI_Bcast(buf, *count, SMPI_F2C_TYPE(datatype), *root,
                    SMPI_F2C_COMM(comm));
}
void mpi_reduce_(void* sendbuf, void* recvbuf, MPI_Fint* count,
                 MPI_Fint* datatype, MPI_Fint* op, MPI_Fint* root,
                 MPI_Fint* comm, MPI_Fint* ierr) {
  *ierr = MPI_Reduce(sendbuf, recvbuf, *count, SMPI_F2C_TYPE(datatype),
                     SMPI_F2C_OP(op), *root, SMPI_F2C_COMM(comm));
}
void mpi_allreduce_(void* sendbuf, void* recvbuf, MPI_Fint* count,
                    MPI_Fint* datatype, MPI_Fint* op, MPI_Fint* comm,
                    MPI_Fint* ierr) {
  *ierr = MPI_Allreduce(sendbuf, recvbuf, *count, SMPI_F2C_TYPE(datatype),
                        SMPI_F2C_OP(op), SMPI_F2C_COMM(comm));
}
void mpi_gather_(void* sendbuf, MPI_Fint* sendcount, MPI_Fint* sendtype,
                 void* recvbuf, MPI_Fint* recvcount, MPI_Fint* recvtype,
                 MPI_Fint* root, MPI_Fint* comm, MPI_Fint* ierr) {
  *ierr = MPI_Gather(sendbuf, *sendcount, SMPI_F2C_TYPE(sendtype), recvbuf,
                     *recvcount, SMPI_F2C_TYPE(recvtype), *root,
                     SMPI_F2C_COMM(comm));
}
void mpi_scatter_(void* sendbuf, MPI_Fint* sendcount, MPI_Fint* sendtype,
                  void* recvbuf, MPI_Fint* recvcount, MPI_Fint* recvtype,
                  MPI_Fint* root, MPI_Fint* comm, MPI_Fint* ierr) {
  *ierr = MPI_Scatter(sendbuf, *sendcount, SMPI_F2C_TYPE(sendtype), recvbuf,
                      *recvcount, SMPI_F2C_TYPE(recvtype), *root,
                      SMPI_F2C_COMM(comm));
}
void mpi_allgather_(void* sendbuf, MPI_Fint* sendcount, MPI_Fint* sendtype,
                    void* recvbuf, MPI_Fint* recvcount, MPI_Fint* recvtype,
                    MPI_Fint* comm, MPI_Fint* ierr) {
  *ierr = MPI_Allgather(sendbuf, *sendcount, SMPI_F2C_TYPE(sendtype),
                        recvbuf, *recvcount, SMPI_F2C_TYPE(recvtype),
                        SMPI_F2C_COMM(comm));
}
void mpi_alltoall_(void* sendbuf, MPI_Fint* sendcount, MPI_Fint* sendtype,
                   void* recvbuf, MPI_Fint* recvcount, MPI_Fint* recvtype,
                   MPI_Fint* comm, MPI_Fint* ierr) {
  *ierr = MPI_Alltoall(sendbuf, *sendcount, SMPI_F2C_TYPE(sendtype),
                       recvbuf, *recvcount, SMPI_F2C_TYPE(recvtype),
                       SMPI_F2C_COMM(comm));
}
/* Completion calls returning request INDICES need hand translation:
 * Fortran indices are 1-based, and MPI_UNDEFINED passes through
 * unchanged (reference smpi_f77_request.cpp does the same +1). */
void mpi_waitany_(MPI_Fint* count, MPI_Fint* requests, MPI_Fint* index,
                  MPI_Fint* status, MPI_Fint* ierr) {
  *ierr = MPI_Waitany(*count, requests, index, (MPI_Status*)status);
  if (*index != MPI_UNDEFINED) *index += 1;
}
void mpi_testany_(MPI_Fint* count, MPI_Fint* requests, MPI_Fint* index,
                  MPI_Fint* flag, MPI_Fint* status, MPI_Fint* ierr) {
  *ierr = MPI_Testany(*count, requests, index, flag, (MPI_Status*)status);
  if (*index != MPI_UNDEFINED) *index += 1;
}
void mpi_waitsome_(MPI_Fint* incount, MPI_Fint* requests,
                   MPI_Fint* outcount, MPI_Fint* indices,
                   MPI_Fint* statuses, MPI_Fint* ierr) {
  int i;
  *ierr = MPI_Waitsome(*incount, requests, outcount, indices,
                       (MPI_Status*)statuses);
  if (*outcount != MPI_UNDEFINED)
    for (i = 0; i < *outcount; i++) indices[i] += 1;
}
void mpi_testsome_(MPI_Fint* incount, MPI_Fint* requests,
                   MPI_Fint* outcount, MPI_Fint* indices,
                   MPI_Fint* statuses, MPI_Fint* ierr) {
  int i;
  *ierr = MPI_Testsome(*incount, requests, outcount, indices,
                       (MPI_Status*)statuses);
  if (*outcount != MPI_UNDEFINED)
    for (i = 0; i < *outcount; i++) indices[i] += 1;
}

/* Generated F77 wrappers for everything not hand-written above
 * (tools/gen_f77.py over include/smpi/mpi.h). */
#include "smpi_f77_gen.c"
