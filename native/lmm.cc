// Native (C++) exact max-min fairness solver.
//
// Host-side companion of the JAX/TPU fixpoint (simgrid_tpu/ops/lmm_jax.py):
// the framework dispatches small live sets to this solver and large ones to
// the device, so the crossover floor is native-speed, not Python-speed.
// Also the measured stand-in for the reference's C++ solver in
// BASELINE_MEASURED.md (the reference itself needs boost::intrusive, absent
// here): same algorithm — the saturate-bottleneck fixpoint with bound-first
// rounds, epsilon double_update semantics, concurrency limits/shares and
// FATPIPE max-sharing (reference semantics:
// /root/reference/src/kernel/lmm/maxmin.cpp:502-693, concurrency
// maxmin.hpp:104-129) — but an arena/index design (flat vectors, integer
// handles, index-linked element lists, swap-erase light list) instead of
// boost intrusive lists.  List orderings (enabled push-front, disabled
// push-back, staged wake-up scan order) mirror the Python host solver
// (simgrid_tpu/ops/lmm_host.py), which is the validated oracle.
//
// Exposed as a C ABI for ctypes:
//   * incremental ops (constraint_new/variable_new/expand/solve/value) used
//     by the maxmin_bench replica driver;
//   * one-shot lmm_solve_coo() over flattened arrays used as the Python
//     System's "native" backend (same handoff shape as the JAX backend).

#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <unordered_map>
#include <vector>

namespace {

inline double double_update(double value, double delta, double precision) {
  value -= delta;
  if (value < precision)
    value = 0.0;
  return value;
}

inline bool double_positive(double value, double precision) {
  return value > precision;
}

inline bool double_equals(double a, double b, double precision) {
  return std::fabs(a - b) < precision;
}

enum class ListId : uint8_t { kNone, kEnabled, kDisabled };

struct Element {
  int32_t cnst = -1;
  int32_t var = -1;
  double consumption_weight = 0.0;
  // membership in the constraint's enabled/disabled element list
  ListId list = ListId::kNone;
  int32_t prev = -1;
  int32_t next = -1;
  bool active = false;  // participates in the current solve round

  // weight < 1 (e.g. cross-traffic at 0.05) does not count toward the
  // constraint's concurrency (maxmin.cpp:30-40).
  int32_t concurrency() const { return consumption_weight >= 1 ? 1 : 0; }
};

struct ElemList {
  int32_t head = -1;
  int32_t tail = -1;
  int32_t size = 0;
};

struct Constraint {
  double bound = 0.0;
  bool fatpipe = false;
  int32_t concurrency_limit = -1;  // -1 = unlimited
  int32_t concurrency_current = 0;
  int32_t concurrency_maximum = 0;
  ElemList enabled;
  ElemList disabled;
  // solve scratch
  double remaining = 0.0;
  double usage = 0.0;
  int32_t light_idx = -1;
  bool active = false;
  // staged variables whose cached blocker is this constraint
  // (registration order; see System::on_disabled_var)
  std::vector<int32_t> waiters;
};

struct Variable {
  double sharing_penalty = 0.0;
  double staged_penalty = 0.0;
  double bound = -1.0;
  double value = 0.0;
  int32_t concurrency_share = 1;
  std::vector<int32_t> elems;  // element indices, creation order
  // constraint -> element indices on it (creation order): O(1) lookup
  // for expand's current-share scan and expand_add's edge search; a
  // linear elems walk made huge-class construction (384 elems/var)
  // quadratic per variable
  std::unordered_map<int32_t, std::vector<int32_t>> by_cnst;
  // the constraint whose slack last blocked can_enable (-1 = none):
  // wake-up probes are O(1) until that constraint frees capacity, and
  // on_disabled_var probes only its registered waiters
  int32_t blocker = -1;
  int32_t waiter_pos = -1;  // index in blocker's waiters vector
  bool saturated = false;
};

struct LightEntry {
  int32_t cnst;
  double rou;  // remaining / usage
};

class System {
 public:
  explicit System(double precision) : eps_(precision) {}

  // -- element list plumbing (index-linked, per constraint) ---------------
  ElemList& list_of(Element& e) {
    Constraint& c = cnsts_[e.cnst];
    return e.list == ListId::kEnabled ? c.enabled : c.disabled;
  }

  void list_push_front(ElemList& l, int32_t ei, ListId which) {
    Element& e = elems_[ei];
    e.list = which;
    e.prev = -1;
    e.next = l.head;
    if (l.head >= 0)
      elems_[l.head].prev = ei;
    l.head = ei;
    if (l.tail < 0)
      l.tail = ei;
    ++l.size;
  }

  void list_push_back(ElemList& l, int32_t ei, ListId which) {
    Element& e = elems_[ei];
    e.list = which;
    e.next = -1;
    e.prev = l.tail;
    if (l.tail >= 0)
      elems_[l.tail].next = ei;
    l.tail = ei;
    if (l.head < 0)
      l.head = ei;
    ++l.size;
  }

  void list_remove(int32_t ei) {
    Element& e = elems_[ei];
    if (e.list == ListId::kNone)
      return;
    ElemList& l = list_of(e);
    if (e.prev >= 0)
      elems_[e.prev].next = e.next;
    else
      l.head = e.next;
    if (e.next >= 0)
      elems_[e.next].prev = e.prev;
    else
      l.tail = e.prev;
    --l.size;
    e.list = ListId::kNone;
    e.prev = e.next = -1;
  }

  // -- construction -------------------------------------------------------
  int32_t constraint_new(double bound) {
    Constraint c;
    c.bound = bound;
    cnsts_.push_back(std::move(c));
    return static_cast<int32_t>(cnsts_.size() - 1);
  }

  int32_t variable_new(double sharing_penalty, double bound) {
    Variable v;
    v.sharing_penalty = sharing_penalty > 0 ? sharing_penalty : 0.0;
    v.bound = bound;
    vars_.push_back(std::move(v));
    return static_cast<int32_t>(vars_.size() - 1);
  }

  void variable_set_share(int32_t v, int32_t share) {
    vars_[v].concurrency_share = share;
  }

  void constraint_set_limit(int32_t c, int32_t limit) {
    cnsts_[c].concurrency_limit = limit;
    // A raised limit frees slack without an on_disabled_var event:
    // probe registered waiters now (failed probes re-register on
    // their real blocker) — mirrors lmm_host.set_concurrency_limit.
    std::vector<int32_t> probe = cnsts_[c].waiters;
    for (int32_t vi : probe)
      if (vars_[vi].staged_penalty > 0 && can_enable(vi))
        enable_var(vi);
  }

  void constraint_set_fatpipe(int32_t c, bool fat) {
    cnsts_[c].fatpipe = fat;
  }

  int32_t concurrency_slack(const Constraint& c) const {
    if (c.concurrency_limit < 0)
      return INT32_MAX;
    return c.concurrency_limit - c.concurrency_current;
  }

  int32_t min_concurrency_slack(const Variable& v) const {
    int32_t minslack = INT32_MAX;
    for (int32_t ei : v.elems) {
      int32_t slack = concurrency_slack(cnsts_[elems_[ei].cnst]);
      if (slack < minslack)
        minslack = slack;
    }
    return minslack;
  }

  void set_blocker(int32_t v, int32_t c) {
    Variable& var = vars_[v];
    if (var.blocker == c)
      return;
    if (var.blocker >= 0) {
      // O(1) swap-remove (probe order is already a documented
      // divergence, so order preservation is not required)
      auto& w = cnsts_[var.blocker].waiters;
      int32_t last = w.back();
      w[var.waiter_pos] = last;
      vars_[last].waiter_pos = var.waiter_pos;
      w.pop_back();
    }
    var.blocker = c;
    if (c >= 0) {
      var.waiter_pos = static_cast<int32_t>(cnsts_[c].waiters.size());
      cnsts_[c].waiters.push_back(v);
    } else {
      var.waiter_pos = -1;
    }
  }

  bool can_enable(int32_t vi) {
    // Early-exit slack scan with a cached blocking constraint: while
    // the blocker's slack stays below our share, the probe is O(1),
    // and on_disabled_var only probes its own registered waiters —
    // without this, bench-protocol construction on the huge class
    // (20k vars x 384 elems) is quadratic in the staged-variable
    // population (the reference rescans fully every time).
    Variable& v = vars_[vi];
    if (v.staged_penalty <= 0)
      return false;
    if (v.blocker >= 0 &&
        concurrency_slack(cnsts_[v.blocker]) < v.concurrency_share)
      return false;
    for (int32_t ei : v.elems)
      if (concurrency_slack(cnsts_[elems_[ei].cnst]) < v.concurrency_share) {
        set_blocker(vi, elems_[ei].cnst);
        return false;
      }
    set_blocker(vi, -1);
    return true;
  }

  void increase_concurrency(int32_t ei) {
    Element& e = elems_[ei];
    Constraint& c = cnsts_[e.cnst];
    c.concurrency_current += e.concurrency();
    if (c.concurrency_current > c.concurrency_maximum)
      c.concurrency_maximum = c.concurrency_current;
  }

  void decrease_concurrency(int32_t ei) {
    Element& e = elems_[ei];
    cnsts_[e.cnst].concurrency_current -= e.concurrency();
  }

  void expand(int32_t c, int32_t v, double weight) {
    // lmm_host.System.expand (maxmin.cpp:234-285 behavior)
    modified_ = true;
    Variable& var = vars_[v];
    Constraint& cnst = cnsts_[c];

    int32_t current_share = 0;
    if (var.concurrency_share > 1) {
      auto it = var.by_cnst.find(c);
      if (it != var.by_cnst.end())
        for (int32_t ei : it->second)
          if (elems_[ei].list == ListId::kEnabled)
            current_share += elems_[ei].concurrency();
    }

    if (var.sharing_penalty > 0 &&
        var.concurrency_share - current_share > concurrency_slack(cnst)) {
      double penalty = var.sharing_penalty;
      disable_var(v);
      for (int32_t ei : var.elems)
        on_disabled_var(elems_[ei].cnst);
      weight = 0.0;
      var.staged_penalty = penalty;
      if (can_enable(v))          // registers the real blocker on failure
        set_blocker(v, c);        // conservatively wait on the trigger
    }

    Element e;
    e.cnst = c;
    e.var = v;
    e.consumption_weight = weight;
    int32_t ei = static_cast<int32_t>(elems_.size());
    elems_.push_back(e);
    var.elems.push_back(ei);
    var.by_cnst[c].push_back(ei);

    if (var.sharing_penalty > 0) {
      list_push_front(cnst.enabled, ei, ListId::kEnabled);
      increase_concurrency(ei);
    } else {
      list_push_back(cnst.disabled, ei, ListId::kDisabled);
    }
    if (!cnst.active) {
      cnst.active = true;
      active_cnsts_.push_back(c);
    }
  }

  void expand_add(int32_t c, int32_t v, double weight) {
    // lmm_host.System.expand_add
    modified_ = true;
    Variable& var = vars_[v];
    int32_t found = -1;
    auto it = var.by_cnst.find(c);
    if (it != var.by_cnst.end() && !it->second.empty())
      found = it->second.front();
    if (found < 0) {
      expand(c, v, weight);
      return;
    }
    Element& e = elems_[found];
    if (var.sharing_penalty > 0)
      decrease_concurrency(found);
    if (!cnsts_[c].fatpipe)
      e.consumption_weight += weight;
    else if (e.consumption_weight < weight)
      e.consumption_weight = weight;
    if (var.sharing_penalty > 0) {
      if (concurrency_slack(cnsts_[c]) < e.concurrency()) {
        double penalty = var.sharing_penalty;
        disable_var(v);
        for (int32_t ei : var.elems)
          on_disabled_var(elems_[ei].cnst);
        var.staged_penalty = penalty;
        if (can_enable(v))
          set_blocker(v, c);
      }
      increase_concurrency(found);
    }
  }

  void enable_var(int32_t v) {
    set_blocker(v, -1);
    Variable& var = vars_[v];
    var.sharing_penalty = var.staged_penalty;
    var.staged_penalty = 0.0;
    for (int32_t ei : var.elems) {
      list_remove(ei);
      list_push_front(cnsts_[elems_[ei].cnst].enabled, ei, ListId::kEnabled);
      increase_concurrency(ei);
    }
  }

  void disable_var(int32_t v) {
    // NB: unlike enable, callers trigger on_disabled_var themselves
    // (mirrors lmm_host.System.disable_var).
    Variable& var = vars_[v];
    for (int32_t ei : var.elems) {
      Element& e = elems_[ei];
      list_remove(ei);
      list_push_back(cnsts_[e.cnst].disabled, ei, ListId::kDisabled);
      e.active = false;
      decrease_concurrency(ei);
    }
    var.sharing_penalty = 0.0;
    var.staged_penalty = 0.0;
    var.value = 0.0;
  }

  void on_disabled_var(int32_t c) {
    // Wake staged variables now that a slot freed up, probing only the
    // variables registered as blocked on THIS constraint (see
    // lmm_host.System.on_disabled_var for the divergence note).
    Constraint& cnst = cnsts_[c];
    if (cnst.concurrency_limit < 0)
      return;
    if (cnst.waiters.empty())
      return;
    std::vector<int32_t> probe = cnst.waiters;  // enable mutates it
    for (int32_t vi : probe) {
      if (cnst.concurrency_current == cnst.concurrency_limit)
        break;
      if (vars_[vi].staged_penalty > 0 && can_enable(vi))
        enable_var(vi);
    }
  }

  void variable_free(int32_t v) {
    // lmm_host.System._var_free
    modified_ = true;
    Variable& var = vars_[v];
    for (int32_t ei : var.elems) {
      if (var.sharing_penalty > 0)
        decrease_concurrency(ei);
      list_remove(ei);
      Constraint& c = cnsts_[elems_[ei].cnst];
      if (c.enabled.size + c.disabled.size > 0)
        on_disabled_var(elems_[ei].cnst);
    }
    set_blocker(v, -1);
    var.elems.clear();
    var.by_cnst.clear();
    var.sharing_penalty = 0.0;
    var.staged_penalty = 0.0;
  }

  double value(int32_t v) const { return vars_[v].value; }

  void solve() {
    if (!modified_)
      return;
    solve_list(active_cnsts_);
    modified_ = false;
  }

 public:
  // The saturate-bottleneck fixpoint (maxmin.cpp:502-693 semantics).
  void solve_list(const std::vector<int32_t>& cnst_list) {
    light_.clear();
    saturated_cnsts_.clear();
    saturated_vars_.clear();

    for (int32_t ci : cnst_list)
      for (int32_t ei = cnsts_[ci].enabled.head; ei >= 0;
           ei = elems_[ei].next)
        vars_[elems_[ei].var].value = 0.0;

    double min_usage = -1.0;
    double min_bound = -1.0;

    for (int32_t ci : cnst_list) {
      Constraint& c = cnsts_[ci];
      c.light_idx = -1;
      c.remaining = c.bound;
      if (!double_positive(c.remaining, c.bound * eps_))
        continue;
      c.usage = 0.0;
      for (int32_t ei = c.enabled.head; ei >= 0; ei = elems_[ei].next) {
        Element& e = elems_[ei];
        if (e.consumption_weight > 0) {
          double w = e.consumption_weight / vars_[e.var].sharing_penalty;
          if (!c.fatpipe)
            c.usage += w;
          else if (c.usage < w)
            c.usage = w;
          e.active = true;
        }
      }
      if (c.usage > 0) {
        c.light_idx = static_cast<int32_t>(light_.size());
        light_.push_back({ci, c.remaining / c.usage});
        saturated_constraint_update(light_.back().rou, c.light_idx,
                                    &min_usage);
      }
    }
    light_size_ = light_.size();
    saturated_variable_set_update();

    while (true) {
      for (int32_t v : saturated_vars_) {
        Variable& var = vars_[v];
        if (var.bound > 0 && var.bound * var.sharing_penalty < min_usage) {
          double bp = var.bound * var.sharing_penalty;
          if (min_bound < 0 || bp < min_bound)
            min_bound = bp;
        }
      }

      for (int32_t v : saturated_vars_) {
        Variable& var = vars_[v];
        var.saturated = false;
        if (min_bound < 0) {
          var.value = min_usage / var.sharing_penalty;
        } else if (double_equals(min_bound, var.bound * var.sharing_penalty,
                                 eps_)) {
          var.value = var.bound;
        } else {
          continue;  // not part of this bound-first round
        }

        for (int32_t ei : var.elems) {
          Element& e = elems_[ei];
          if (e.list != ListId::kEnabled)
            continue;
          Constraint& c = cnsts_[e.cnst];
          if (!c.fatpipe) {
            c.remaining = double_update(
                c.remaining, e.consumption_weight * var.value,
                c.bound * eps_);
            c.usage = double_update(
                c.usage, e.consumption_weight / var.sharing_penalty, eps_);
            e.active = false;
            light_update(c);
          } else {
            c.usage = 0.0;
            e.active = false;
            for (int32_t ei2 = c.enabled.head; ei2 >= 0;
                 ei2 = elems_[ei2].next) {
              const Element& e2 = elems_[ei2];
              if (vars_[e2.var].value > 0 || e2.consumption_weight <= 0)
                continue;
              double w = e2.consumption_weight / vars_[e2.var].sharing_penalty;
              if (c.usage < w)
                c.usage = w;
            }
            light_update(c);
          }
        }
      }
      saturated_vars_.clear();

      min_usage = -1.0;
      min_bound = -1.0;
      saturated_cnsts_.clear();
      for (size_t pos = 0; pos < light_size_; ++pos)
        saturated_constraint_update(light_[pos].rou,
                                    static_cast<int32_t>(pos), &min_usage);
      saturated_variable_set_update();
      if (light_size_ == 0)
        break;
    }
  }

 private:
  // swap-erase a constraint out of the light list when it empties,
  // else refresh its rou.
  void light_update(Constraint& c) {
    if (!double_positive(c.usage, eps_) ||
        !double_positive(c.remaining, c.bound * eps_)) {
      if (c.light_idx >= 0) {
        int32_t idx = c.light_idx;
        light_[idx] = light_[light_size_ - 1];
        cnsts_[light_[idx].cnst].light_idx = idx;
        --light_size_;
        c.light_idx = -1;
      }
    } else if (c.light_idx >= 0) {
      light_[c.light_idx].rou = c.remaining / c.usage;
    }
  }

  void saturated_constraint_update(double rou, int32_t pos,
                                   double* min_usage) {
    // reference saturated_constraints_update (maxmin.cpp:397-417)
    if (rou <= 0)
      return;
    if (*min_usage < 0 || *min_usage > rou) {
      *min_usage = rou;
      saturated_cnsts_.clear();
      saturated_cnsts_.push_back(pos);
    } else if (*min_usage == rou) {
      saturated_cnsts_.push_back(pos);
    }
  }

  void saturated_variable_set_update() {
    // reference saturated_variable_set_update (maxmin.cpp:419-430)
    for (int32_t pos : saturated_cnsts_) {
      const Constraint& c = cnsts_[light_[pos].cnst];
      for (int32_t ei = c.enabled.head; ei >= 0; ei = elems_[ei].next) {
        const Element& e = elems_[ei];
        if (e.active && !vars_[e.var].saturated) {
          vars_[e.var].saturated = true;
          saturated_vars_.push_back(e.var);
        }
      }
    }
  }

 public:
  double eps_;
  bool modified_ = false;
  std::vector<Constraint> cnsts_;
  std::vector<Variable> vars_;
  std::vector<Element> elems_;
  std::vector<int32_t> active_cnsts_;
  std::vector<LightEntry> light_;
  size_t light_size_ = 0;
  std::vector<int32_t> saturated_cnsts_;  // positions in light_
  std::vector<int32_t> saturated_vars_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* lmm_system_new(double precision) { return new System(precision); }

void lmm_system_free(void* sys) { delete static_cast<System*>(sys); }

int32_t lmm_constraint_new(void* sys, double bound) {
  return static_cast<System*>(sys)->constraint_new(bound);
}

void lmm_constraint_set_limit(void* sys, int32_t c, int32_t limit) {
  static_cast<System*>(sys)->constraint_set_limit(c, limit);
}

void lmm_constraint_set_fatpipe(void* sys, int32_t c, int32_t fat) {
  static_cast<System*>(sys)->constraint_set_fatpipe(c, fat != 0);
}

int32_t lmm_variable_new(void* sys, double penalty, double bound) {
  return static_cast<System*>(sys)->variable_new(penalty, bound);
}

void lmm_variable_set_share(void* sys, int32_t v, int32_t share) {
  static_cast<System*>(sys)->variable_set_share(v, share);
}

void lmm_expand(void* sys, int32_t c, int32_t v, double w) {
  static_cast<System*>(sys)->expand(c, v, w);
}

void lmm_expand_add(void* sys, int32_t c, int32_t v, double w) {
  static_cast<System*>(sys)->expand_add(c, v, w);
}

void lmm_variable_free(void* sys, int32_t v) {
  static_cast<System*>(sys)->variable_free(v);
}

void lmm_solve(void* sys) { static_cast<System*>(sys)->solve(); }

double lmm_variable_value(void* sys, int32_t v) {
  return static_cast<System*>(sys)->value(v);
}

// One-shot solve over flattened COO arrays: the Python System's "native"
// backend entry (same flatten/solve/scatter handoff as the JAX backend —
// concurrency staging already happened host-side, only enabled elements
// arrive here).
int32_t lmm_solve_coo(int32_t n_c, int32_t n_v, int32_t n_e,
                      const int32_t* e_var, const int32_t* e_cnst,
                      const double* e_w, const double* c_bound,
                      const uint8_t* c_fatpipe, const double* v_penalty,
                      const double* v_bound, double eps, double* values_out,
                      double* c_remaining_out, double* c_usage_out) {
  System sys(eps);
  for (int32_t i = 0; i < n_c; ++i) {
    int32_t c = sys.constraint_new(c_bound[i]);
    sys.constraint_set_fatpipe(c, c_fatpipe[i] != 0);
  }
  for (int32_t i = 0; i < n_v; ++i)
    sys.variable_new(v_penalty[i], v_bound[i]);
  for (int32_t k = 0; k < n_e; ++k)
    sys.expand_add(e_cnst[k], e_var[k], e_w[k]);
  sys.solve();
  for (int32_t i = 0; i < n_v; ++i)
    values_out[i] = sys.value(i);
  for (int32_t i = 0; i < n_c; ++i) {
    c_remaining_out[i] = sys.cnsts_[i].remaining;
    c_usage_out[i] = sys.cnsts_[i].usage;
  }
  return 0;
}

}  // extern "C"
