"""Simulation checkpoint/resume: deterministic re-execution with a
recorded solve stream.

The reference checkpoints by copying dirty memory pages of the whole
simulated process (src/mc/sosp/PageStore.hpp:62-97) — a design forced
by C actor stacks that cannot be rebuilt any other way.  This kernel
is deterministic by construction (serial scheduling rounds, FIFO
simcall answering, deterministic solver), so a checkpoint does not
need the memory image at all: it is

    (how to rebuild the simulation, the simulated date reached,
     the solver results produced along the way)

and resuming is rebuilding + fast-forwarding with Engine.run_until.
The third element is the state-dict half: actor control flow re-runs
(Python continuations cannot be serialized), but every max-min solve
— what dominates a long simulation at scale — is INSTALLED from the
recording instead of re-solved, so fast-forward pays O(system state)
per step rather than O(fixpoint rounds).  Bit-identical by the same
determinism argument that lets the model checker re-execute instead
of snapshotting (mc/explorer.py); any structural mismatch falls back
to a real solve.  Tokens serialize to JSON + a numeric .npz and
survive process restarts, which page-store snapshots cannot.

SECURITY: ``resume()`` imports and CALLS the module-level callable
named in the token, so only load checkpoint files you trust — the
token format is plain JSON (no pickle), so loading alone executes
nothing, but resuming executes the named setup function.

Contract: `setup` must be an importable module-level callable that
builds the engine (platform + actors) from its arguments and returns
the s4u Engine, without consuming wall-clock entropy (no real RNG /
time dependence — the usual determinism requirement).
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class CheckpointError(RuntimeError):
    """A checkpoint artifact that cannot be honored: a missing or
    truncated ``.npz`` sidecar, arrays whose shapes/dtypes disagree
    with the token, inconsistent ragged offsets, or an unknown format
    version.  Raised at LOAD time with the offending key named, so a
    corrupt artifact fails fast instead of surfacing as a deep numpy
    broadcast error mid-resume."""


def _load_npz(path: str):
    """Open one checkpoint ``.npz`` sidecar, normalizing every failure
    mode (absent file, truncated zip, foreign bytes) to
    :class:`CheckpointError`."""
    if not os.path.exists(path):
        raise CheckpointError(
            f"checkpoint sidecar {path!r} is missing (the token "
            f"promises arrays; save() writes them next to the token)")
    try:
        return np.load(path)
    except Exception as exc:
        raise CheckpointError(
            f"checkpoint sidecar {path!r} is unreadable (truncated or "
            f"corrupt): {exc}")


def _npz_get(z, key: str, dtype=None, ndim: Optional[int] = None,
             shape: Optional[Tuple[int, ...]] = None,
             cols: Optional[int] = None) -> "np.ndarray":
    """Fetch one array from a loaded npz, validating it against what
    the token promised.  Shared by :meth:`Checkpoint.load` and
    :meth:`FleetCheckpoint.load` — the single place a stale or
    truncated artifact turns into a clear :class:`CheckpointError`."""
    if key not in z.files:
        raise CheckpointError(
            f"checkpoint npz is missing array {key!r} (truncated or "
            f"corrupt artifact, or a token/sidecar mismatch)")
    arr = z[key]
    if dtype is not None and arr.dtype != np.dtype(dtype):
        raise CheckpointError(
            f"checkpoint array {key!r} has dtype {arr.dtype}, token "
            f"expects {np.dtype(dtype).name}")
    if ndim is not None and arr.ndim != ndim:
        raise CheckpointError(
            f"checkpoint array {key!r} has {arr.ndim} dimension(s), "
            f"token expects {ndim}")
    if shape is not None and tuple(arr.shape) != tuple(shape):
        raise CheckpointError(
            f"checkpoint array {key!r} has shape {tuple(arr.shape)}, "
            f"token expects {tuple(shape)}")
    if cols is not None and (arr.ndim != 2 or arr.shape[1] != cols):
        raise CheckpointError(
            f"checkpoint array {key!r} has shape {tuple(arr.shape)}, "
            f"token expects (*, {cols})")
    return arr


class _SolveStream:
    """Recorded solver results, one record per ACTUAL solve (calls that
    early-return on `not modified` record nothing — the replay side
    gates identically, so the streams stay aligned by determinism).

    This is the state-dict half of the checkpoint: re-execution still
    replays the actor control flow (Python continuations cannot be
    serialized), but every max-min solve — the cost that dominates a
    long simulation at scale — is replaced by installing the recorded
    fixpoint, turning fast-forward solver cost from O(fixpoint rounds)
    into O(state) per step.  Sound for the same reason re-execution is:
    the kernel is deterministic, so call k of the resumed run has
    exactly the inputs call k of the original had."""

    def __init__(self):
        #: per-system (in creation order) list of records
        self.per_system: List[list] = []
        self._order: dict = {}

    def _sys_idx(self, system) -> int:
        key = id(system)
        idx = self._order.get(key)
        if idx is None:
            idx = self._order[key] = len(self.per_system)
            self.per_system.append([])
        return idx

    @staticmethod
    def snapshot(system, new_flags) -> dict:
        """Full post-solve solver state: variable values, per-active-
        constraint (remaining, usage, enabled count, active element
        positions), and the indices of the variables whose action this
        particular solve reported as modified — so install() replays
        EXACTLY the state transition, not an approximation (extra
        flags would split double_update intervals and change float
        rounding; a different active-element arrangement would change
        later accumulation order)."""
        vs = list(system.variable_set)
        index_of = {id(var): i for i, var in enumerate(vs)}
        values = [var.value for var in vs]
        cnst = []
        for c in system.active_constraint_set:
            enabled = list(c.enabled_element_set)
            pos_of = {id(elem): pos for pos, elem in enumerate(enabled)}
            # the SEQUENCE of the active list is state too: it drives
            # the accumulation order of later real solves
            active = [pos_of[id(e)] for e in c.active_element_set
                      if id(e) in pos_of]
            cnst.append((c.remaining, c.usage, len(enabled), active))
        flags = [index_of[id(action.variable)] for action in new_flags
                 if id(action.variable) in index_of]
        return {"values": values, "cnst": cnst, "flags": flags}

    @staticmethod
    def install(system, rec: dict) -> bool:
        """Install one recorded solve; False when the structure no
        longer matches (the caller then abandons replay for good —
        once alignment is lost a later coincidental size match would
        install a stale record)."""
        vs = list(system.variable_set)
        cs = list(system.active_constraint_set)
        if len(vs) != len(rec["values"]) or len(cs) != len(rec["cnst"]):
            return False
        for c, (_, _, n_enabled, _) in zip(cs, rec["cnst"]):
            if len(c.enabled_element_set) != n_enabled:
                return False
        for var, value in zip(vs, rec["values"]):
            var.value = value
        for c, (remaining, usage, _, active) in zip(cs, rec["cnst"]):
            c.remaining = remaining
            c.usage = usage
            enabled = list(c.enabled_element_set)
            for elem in enabled:
                elem.make_inactive()
            # make_active pushes FRONT: reverse reproduces the sequence
            for pos in reversed(active):
                enabled[pos].make_active()
        for i in rec["flags"]:
            system.flag_action_modified(vs[i].id)
        system.modified = False
        if system.selective_update_active:
            system.remove_all_modified_set()
        return True


def record_solves(stream: _SolveStream):
    """Class-level patch of System.solve that tees each result into
    `stream`; returns an uninstall callable."""
    from .ops.lmm_host import System

    orig = System.solve

    def recording_solve(self):
        if not self.modified:
            return
        before = len(self.modified_actions or ())
        orig(self)
        new_flags = (self.modified_actions or [])[before:]
        stream.per_system[stream._sys_idx(self)].append(
            _SolveStream.snapshot(self, new_flags))

    System.solve = recording_solve
    return lambda: setattr(System, "solve", orig)


def replay_solves(stream: _SolveStream):
    """Class-level patch of System.solve that installs recorded
    results instead of solving; exhausted or mismatched streams fall
    back to the real solver (sound: same inputs, just slower)."""
    from .ops.lmm_host import System

    orig = System.solve
    cursors: dict = {}
    order: dict = {}
    poisoned: set = set()

    def replaying_solve(self):
        if not self.modified:
            return
        idx = order.setdefault(id(self), len(order))
        if idx not in poisoned and idx < len(stream.per_system):
            recs = stream.per_system[idx]
            k = cursors.get(idx, 0)
            if k < len(recs):
                if _SolveStream.install(self, recs[k]):
                    cursors[idx] = k + 1
                    return
                # structure diverged: alignment is gone for THIS system
                # for good — a later coincidental size match would
                # install a stale record, so abandon its stream
                poisoned.add(idx)
        orig(self)

    System.solve = replaying_solve
    return lambda: setattr(System, "solve", orig)


class Checkpoint:
    """A resumable point of a deterministic simulation."""

    def __init__(self, setup, args: Tuple = (), at: float = 0.0):
        if not callable(setup):
            raise TypeError("setup must be a callable building the engine")
        self._module = setup.__module__
        self._qualname = setup.__qualname__
        if "<" in self._qualname:    # <lambda>, <locals> — not importable
            raise TypeError(
                "setup must be an importable module-level callable "
                f"(got {self._qualname!r}); lambdas and closures cannot "
                "be resolved when the checkpoint is loaded later")
        self.args = tuple(args)
        self.at = float(at)
        self.solves: Optional[_SolveStream] = None

    # -- capture -------------------------------------------------------
    @classmethod
    def capture(cls, setup, args: Tuple = (), at: float = 0.0,
                record: bool = True):
        """Build the simulation, advance it to `at`, and return
        (engine paused at `at`, checkpoint token).  The caller may keep
        running the engine; the token is independent of it.

        With ``record=True`` every solver fixpoint along the way is
        recorded into the token, so ``resume()`` fast-forwards by
        INSTALLING results instead of re-solving — O(state) per step
        for the part that dominates long simulations."""
        token = cls(setup, args, at)
        stream = _SolveStream() if record else None
        uninstall = record_solves(stream) if record else None
        try:
            engine = token._rebuild()
            engine.run_until(at)
        finally:
            if uninstall is not None:
                uninstall()
        token.solves = stream
        return engine, token

    # -- resume --------------------------------------------------------
    def _rebuild(self):
        from .s4u import Engine
        Engine._reset()
        fn = importlib.import_module(self._module)
        for part in self._qualname.split("."):
            fn = getattr(fn, part)
        engine = fn(*self.args)
        if engine is None or not hasattr(engine, "run_until"):
            raise TypeError("setup must return the s4u Engine it built")
        return engine

    def resume(self):
        """Rebuild the simulation and fast-forward to the checkpointed
        date; returns the engine paused there, ready for run().  When
        the token carries a solve stream, the fast-forward installs
        the recorded fixpoints instead of re-solving (falling back to
        real solves on any structural mismatch)."""
        uninstall = (replay_solves(self.solves)
                     if self.solves is not None else None)
        try:
            engine = self._rebuild()
            engine.run_until(self.at)
        finally:
            if uninstall is not None:
                uninstall()
        return engine

    # -- persistence ---------------------------------------------------
    def save(self, path: str) -> None:
        """JSON on purpose: a checkpoint file must be data, not code
        (pickle.load would execute arbitrary payloads).  Args are
        therefore restricted to JSON-representable plain data.  The
        recorded solve stream rides along in `path`.solves.npz (pure
        numeric arrays — also data, not code)."""
        try:
            blob = json.dumps({"module": self._module,
                               "qualname": self._qualname,
                               "args": list(self.args), "at": self.at,
                               "has_solves": self.solves is not None})
        except TypeError as exc:
            raise TypeError(
                "checkpoint args must be JSON-serializable plain data "
                f"(module={self._module}, args={self.args!r}): {exc}")
        with open(path, "w") as f:
            f.write(blob)
        if self.solves is not None:
            import numpy as np
            arrays = {}
            for i, recs in enumerate(self.solves.per_system):
                for k, rec in enumerate(recs):
                    p = f"s{i}r{k}"
                    arrays[p + "v"] = np.asarray(rec["values"],
                                                 np.float64)
                    arrays[p + "c"] = np.asarray(
                        [(r, u, n) for r, u, n, _ in rec["cnst"]],
                        np.float64).reshape(-1, 3)
                    # ragged active-position lists: flat + offsets
                    flat, offs = [], [0]
                    for _, _, _, active in rec["cnst"]:
                        flat.extend(active)
                        offs.append(len(flat))
                    arrays[p + "a"] = np.asarray(flat, np.int64)
                    arrays[p + "o"] = np.asarray(offs, np.int64)
                    arrays[p + "f"] = np.asarray(rec["flags"], np.int64)
            arrays["shape"] = np.asarray(
                [len(recs) for recs in self.solves.per_system], np.int64)
            np.savez_compressed(path + ".solves.npz", **arrays)

    @classmethod
    def load(cls, path: str) -> "Checkpoint":
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as exc:
            raise CheckpointError(
                f"checkpoint token {path!r} is unreadable: {exc}")
        for field in ("module", "qualname", "args", "at"):
            if field not in d:
                raise CheckpointError(
                    f"checkpoint token {path!r} is missing the "
                    f"{field!r} field (truncated or foreign file)")
        token = cls.__new__(cls)
        token._module = str(d["module"])
        token._qualname = str(d["qualname"])
        token.args = tuple(d["args"])
        token.at = float(d["at"])
        token.solves = None
        if d.get("has_solves"):
            # every array is validated against the token BEFORE any of
            # it is consumed: a truncated artifact fails here with the
            # offending key named, not as a numpy broadcast error deep
            # inside a resume
            with _load_npz(path + ".solves.npz") as z:
                stream = _SolveStream()
                shape = _npz_get(z, "shape", dtype=np.int64, ndim=1)
                for i, n in enumerate(shape):
                    recs = []
                    for k in range(int(n)):
                        p = f"s{i}r{k}"
                        cn = _npz_get(z, p + "c", dtype=np.float64,
                                      cols=3)
                        flat = _npz_get(z, p + "a", dtype=np.int64,
                                        ndim=1).tolist()
                        offs = _npz_get(z, p + "o", dtype=np.int64,
                                        ndim=1).tolist()
                        if (len(offs) != len(cn) + 1 or offs[0] != 0
                                or offs[-1] != len(flat)
                                or any(a > b for a, b in
                                       zip(offs, offs[1:]))):
                            raise CheckpointError(
                                f"checkpoint record {p!r} has "
                                f"inconsistent active-position offsets "
                                f"(corrupt artifact)")
                        cnst = []
                        for j, (r, u, ne) in enumerate(cn):
                            cnst.append((float(r), float(u), int(ne),
                                         flat[offs[j]:offs[j + 1]]))
                        recs.append({
                            "values": _npz_get(z, p + "v",
                                               dtype=np.float64,
                                               ndim=1).tolist(),
                            "cnst": cnst,
                            "flags": _npz_get(z, p + "f",
                                              dtype=np.int64,
                                              ndim=1).tolist(),
                        })
                    stream.per_system.append(recs)
                token.solves = stream
        return token


class FleetCheckpoint:
    """A superstep-boundary snapshot of a campaign fleet/service: one
    JSON token (plain data — loading executes nothing) plus a
    ``path + ".fleet.npz"`` sidecar of numeric arrays.

    The token embeds a MANIFEST of every sidecar array's shape and
    dtype; :meth:`load` validates the npz against it through the same
    :func:`_npz_get` gate :class:`Checkpoint` uses, so a truncated or
    mismatched artifact raises :class:`CheckpointError` with the
    offending key named instead of corrupting a resume.

    This class is format only — WHAT goes into the token/arrays is
    owned by the producer (``serving.service.CampaignService.
    checkpoint`` snapshots the BatchDrainSim committed state + ticket
    journal; ``CampaignService.resume`` consumes it).  Captured at
    collect boundaries exclusively: in-flight pipeline speculation is
    never represented, so resuming replays from committed state
    exactly like a speculation mispredict."""

    #: bumped when the fleet token layout changes incompatibly
    FORMAT = 1

    def __init__(self, token: Dict, arrays: Dict[str, "np.ndarray"]):
        self.token = dict(token)
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}

    def save(self, path: str) -> None:
        """JSON token + compressed npz sidecar (both data, not code).
        The token carries the array manifest the loader validates
        against."""
        manifest = {name: [list(a.shape), a.dtype.name]
                    for name, a in self.arrays.items()}
        try:
            blob = json.dumps({"kind": "fleet", "format": self.FORMAT,
                               "token": self.token,
                               "arrays": manifest})
        except TypeError as exc:
            raise TypeError(
                "fleet checkpoint token must be JSON-serializable "
                f"plain data: {exc}")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(blob)
        np.savez_compressed(path + ".fleet.npz", **self.arrays)
        # token last, atomically: a crash mid-save leaves no token
        # pointing at a half-written sidecar
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "FleetCheckpoint":
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as exc:
            raise CheckpointError(
                f"fleet checkpoint token {path!r} is unreadable: {exc}")
        if d.get("kind") != "fleet":
            raise CheckpointError(
                f"{path!r} is not a fleet checkpoint token "
                f"(kind={d.get('kind')!r})")
        if d.get("format") != cls.FORMAT:
            raise CheckpointError(
                f"fleet checkpoint format {d.get('format')!r} is not "
                f"supported (this build reads format {cls.FORMAT})")
        manifest = d.get("arrays")
        if not isinstance(manifest, dict) or "token" not in d:
            raise CheckpointError(
                f"fleet checkpoint token {path!r} is missing its "
                f"array manifest or payload (truncated file)")
        arrays = {}
        with _load_npz(path + ".fleet.npz") as z:
            for name, spec in manifest.items():
                shape, dtype = tuple(spec[0]), spec[1]
                arrays[name] = _npz_get(z, name, dtype=dtype,
                                        shape=shape)
        return cls(d["token"], arrays)
