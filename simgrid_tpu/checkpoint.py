"""Simulation checkpoint/resume via deterministic re-execution.

The reference checkpoints by copying dirty memory pages of the whole
simulated process (src/mc/sosp/PageStore.hpp:62-97) — a design forced
by C actor stacks that cannot be rebuilt any other way.  This kernel
is deterministic by construction (serial scheduling rounds, FIFO
simcall answering, deterministic solver), so a checkpoint does not
need the memory image at all: it is the pair

    (how to rebuild the simulation, the simulated date reached)

and resuming is rebuilding + fast-forwarding with Engine.run_until —
bit-identical state by determinism, the same argument that lets the
model checker re-execute instead of snapshotting (mc/explorer.py).
Tokens serialize to a few hundred bytes of JSON and survive process
restarts, which page-store snapshots cannot.

SECURITY: ``resume()`` imports and CALLS the module-level callable
named in the token, so only load checkpoint files you trust — the
token format is plain JSON (no pickle), so loading alone executes
nothing, but resuming executes the named setup function.

Contract: `setup` must be an importable module-level callable that
builds the engine (platform + actors) from its arguments and returns
the s4u Engine, without consuming wall-clock entropy (no real RNG /
time dependence — the usual determinism requirement).
"""

from __future__ import annotations

import importlib
import json
from typing import Any, Optional, Tuple


class Checkpoint:
    """A resumable point of a deterministic simulation."""

    def __init__(self, setup, args: Tuple = (), at: float = 0.0):
        if not callable(setup):
            raise TypeError("setup must be a callable building the engine")
        self._module = setup.__module__
        self._qualname = setup.__qualname__
        if "<" in self._qualname:    # <lambda>, <locals> — not importable
            raise TypeError(
                "setup must be an importable module-level callable "
                f"(got {self._qualname!r}); lambdas and closures cannot "
                "be resolved when the checkpoint is loaded later")
        self.args = tuple(args)
        self.at = float(at)

    # -- capture -------------------------------------------------------
    @classmethod
    def capture(cls, setup, args: Tuple = (), at: float = 0.0):
        """Build the simulation, advance it to `at`, and return
        (engine paused at `at`, checkpoint token).  The caller may keep
        running the engine; the token is independent of it."""
        token = cls(setup, args, at)
        engine = token._rebuild()
        engine.run_until(at)
        return engine, token

    # -- resume --------------------------------------------------------
    def _rebuild(self):
        from .s4u import Engine
        Engine._reset()
        fn = importlib.import_module(self._module)
        for part in self._qualname.split("."):
            fn = getattr(fn, part)
        engine = fn(*self.args)
        if engine is None or not hasattr(engine, "run_until"):
            raise TypeError("setup must return the s4u Engine it built")
        return engine

    def resume(self):
        """Rebuild the simulation and fast-forward to the checkpointed
        date; returns the engine paused there, ready for run()."""
        engine = self._rebuild()
        engine.run_until(self.at)
        return engine

    # -- persistence ---------------------------------------------------
    def save(self, path: str) -> None:
        """JSON on purpose: a checkpoint file must be data, not code
        (pickle.load would execute arbitrary payloads).  Args are
        therefore restricted to JSON-representable plain data."""
        try:
            blob = json.dumps({"module": self._module,
                               "qualname": self._qualname,
                               "args": list(self.args), "at": self.at})
        except TypeError as exc:
            raise TypeError(
                "checkpoint args must be JSON-serializable plain data "
                f"(module={self._module}, args={self.args!r}): {exc}")
        with open(path, "w") as f:
            f.write(blob)

    @classmethod
    def load(cls, path: str) -> "Checkpoint":
        with open(path) as f:
            d = json.load(f)
        token = cls.__new__(cls)
        token._module = str(d["module"])
        token._qualname = str(d["qualname"])
        token.args = tuple(d["args"])
        token.at = float(d["at"])
        return token
