"""SMPI-equivalent: an MPI implementation running on simulated actors.

The reference runs unmodified MPI C/Fortran binaries inside the simulator
(src/smpi/, 44k LoC).  The tpu-native rebuild keeps the *simulation*
semantics — eager/rendezvous protocol selection, injected o/Os/Or
overheads, the collective-algorithm library and its selectors, trace
replay — behind an mpi4py-style Python API: ranks are actors of the
deterministic kernel, payloads are numpy arrays, and per-rank "global
variable privatization" is free because each rank is its own actor
(reference smpi_global.cpp:540-608's mmap/dlopen machinery has no
Python analog to need).
"""

from .datatype import (Datatype, MPI_BYTE, MPI_CHAR, MPI_INT, MPI_LONG,
                       MPI_FLOAT, MPI_DOUBLE, MPI_DOUBLE_INT, MPI_UNSIGNED,
                       MPI_UNSIGNED_LONG, MPI_SHORT)
from .op import (Op, MPI_SUM, MPI_MAX, MPI_MIN, MPI_PROD, MPI_LAND, MPI_LOR,
                 MPI_BAND, MPI_BOR, MPI_BXOR, MPI_MAXLOC, MPI_MINLOC)
from .group import Group
from .comm import Comm
from .request import (Request, MPI_ANY_SOURCE, MPI_ANY_TAG, Status,
                      MPI_REQUEST_NULL)
from .runtime import (smpirun, smpirun_multi, smpi_main,
                      smpi_instance_register, this_rank, COMM_WORLD,
                      smpi_execute, smpi_execute_flops, wtime,
                      sample, shared_malloc, shared_free)
from .nbc import (NbcRequest, iallgather, iallreduce, ialltoall, ibarrier,
                  ibcast, igather, ireduce, iscatter)
from .topo import (CartTopology, GraphTopology, MPI_PROC_NULL, dims_create)
from .win import Win

__all__ = [
    "Datatype", "MPI_BYTE", "MPI_CHAR", "MPI_INT", "MPI_LONG", "MPI_FLOAT",
    "MPI_DOUBLE", "MPI_DOUBLE_INT", "MPI_UNSIGNED", "MPI_UNSIGNED_LONG",
    "MPI_SHORT",
    "Op", "MPI_SUM", "MPI_MAX", "MPI_MIN", "MPI_PROD", "MPI_LAND", "MPI_LOR",
    "MPI_BAND", "MPI_BOR", "MPI_BXOR", "MPI_MAXLOC", "MPI_MINLOC",
    "Group", "Comm", "Request", "Status", "MPI_ANY_SOURCE", "MPI_ANY_TAG",
    "MPI_REQUEST_NULL",
    "smpirun", "smpirun_multi", "smpi_main", "smpi_instance_register",
    "this_rank", "COMM_WORLD", "smpi_execute",
    "smpi_execute_flops", "wtime", "sample", "shared_malloc", "shared_free",
    "NbcRequest", "ibarrier", "ibcast", "ireduce", "iallreduce", "igather",
    "iscatter", "iallgather", "ialltoall",
    "CartTopology", "GraphTopology", "MPI_PROC_NULL", "dims_create", "Win",
]
