"""mvapich2, Intel-MPI (impi) and automatic collective selectors.

Completes the selector family of smpi_coll.cpp:33-118:

* ``mvapich2`` — table-driven decisions re-derived from the stampede
  1-ppn tuning tables (smpi_mvapich2_selector.cpp +
  smpi_mvapich2_selector_stampede.hpp).  Simulated deployments place
  one rank per host, so the SMP two-level / shmem / zero-copy variants
  degenerate to their flat equivalents, which is what the maps below
  encode (each non-obvious mapping is commented).
* ``impi`` — the I_MPI_ADJUST decision procedure of
  smpi_intel_mpi_selector.cpp: pick the numproc row, then the first
  size regime with block < max_size, then the 1-based algorithm index
  (ppn=1 tables, extracted to coll_intel_tables.py by
  tools/extract_intel_tables.py).
* ``automatic`` — runs every concrete algorithm for the requested
  collective, timing each between default barriers and reporting the
  per-rank and global quickest (smpi_automatic_selector.cpp
  AUTOMATIC_COLL_BENCH), then leaves the last result standing.
"""

from __future__ import annotations

from typing import List

from ..utils.log import get_category
from .coll import _ALGOS, dispatch_name, register
from .coll_intel_tables import INTEL_TABLES
from .coll_selectors import _require_symmetric
from .datatype import payload_size
from .op import MPI_MAX, Op

log = get_category("smpi_coll")


def _is_pof2(n: int) -> bool:
    return n & (n - 1) == 0


# ---------------------------------------------------------------------------
# mvapich2 (stampede 1-ppn tables; two-level variants degenerate)
# ---------------------------------------------------------------------------

def _mv2_pick(table, comm_size: int, nbytes: float) -> str:
    """The selector's double range walk (smpi_mvapich2_selector.cpp:
    40-52): numproc row, then first size band with nbytes <= max
    (max = -1 is the open-ended band)."""
    i = 0
    while i < len(table) - 1 and comm_size > table[i][0]:
        i += 1
    bands = table[i][1]
    k = 0
    while (k < len(bands) - 1 and bands[k][1] != -1
           and nbytes > bands[k][1]):
        k += 1
    return bands[k][2]


# RD -> rdb; Scatter_dest / bruck / pairwise are direct equivalents
_MV2_ALLTOALL = [
    (2, [(0, -1, "pair")]),
    (4, [(0, 262144, "mvapich2_scatter_dest"), (262144, -1, "pair")]),
    (8, [(0, 8, "rdb"), (8, -1, "mvapich2_scatter_dest")]),
    (16, [(0, 64, "rdb"), (64, 512, "bruck"),
          (512, -1, "mvapich2_scatter_dest")]),
    (32, [(0, 32, "rdb"), (32, 2048, "bruck"),
          (2048, -1, "mvapich2_scatter_dest")]),
    (64, [(0, 8, "rdb"), (8, 1024, "bruck"),
          (1024, -1, "mvapich2_scatter_dest")]),
]

_MV2_ALLGATHER = [
    (2, [(0, -1, "ring")]),
    (4, [(0, 262144, "rdb"), (262144, -1, "ring")]),
    (8, [(0, 131072, "rdb"), (131072, -1, "ring")]),
    (16, [(0, 131072, "rdb"), (131072, -1, "ring")]),
    (32, [(0, 65536, "rdb"), (65536, -1, "ring")]),
    (64, [(0, 32768, "rdb"), (32768, -1, "ring")]),
]

# pt2pt_rd -> rdb; pt2pt_rs (reduce-scatter + allgather) -> rab_rdb,
# the closest registered Rabenseifner shape
_MV2_ALLREDUCE = [
    (16, [(0, 1024, "rdb"), (1024, -1, "rab_rdb")]),
    (32, [(0, 16384, "rdb"), (16384, -1, "rab_rdb")]),
    (64, [(0, 16384, "rdb"), (16384, -1, "rab_rdb")]),
    (128, [(0, 16384, "rdb"), (16384, -1, "rab_rdb")]),
    (256, [(0, 16384, "rdb"), (16384, -1, "rab_rdb")]),
    (512, [(0, 16384, "rdb"), (16384, -1, "rab_rdb")]),
    (1024, [(0, 8192, "rdb"), (8192, -1, "rab_rdb")]),
    (2048, [(0, 8192, "rdb"), (8192, -1, "rab_rdb")]),
]

# Zcpy-pipelined and Shmem variants degenerate to the mpich chooser
# (their own SimGrid mapping, stampede hpp:958-964); the scatter-
# allgather composites are direct equivalents
_MV2_BCAST = [
    (16, [(0, 8192, "mpich"), (8192, 16384, "binomial_tree"),
          (16384, 65536, "mpich"), (65536, 262144, "scatter_LR_allgather"),
          (262144, 524288, "scatter_rdb_allgather"),
          (524288, -1, "scatter_LR_allgather")]),
    (2048, [(0, -1, "mpich")]),    # rows >16: pipelined-zcpy everywhere
]

# knomial(k=4) degenerates to the binomial tree; redscat_gather is the
# registered scatter_gather (Rabenseifner) reduce
_MV2_REDUCE = [
    (16, [(0, 1048576, "binomial"), (1048576, -1, "scatter_gather")]),
    (32, [(0, 1048576, "binomial"), (1048576, -1, "scatter_gather")]),
    (64, [(0, 262144, "binomial"), (262144, -1, "scatter_gather")]),
    (2048, [(0, 1048576, "binomial"), (1048576, -1, "scatter_gather")]),
]

_MV2_SCATTER = [
    (2, [(0, -1, "ompi_binomial")]),
    (32, [(0, -1, "ompi_basic_linear")]),
    (64, [(0, 32, "ompi_binomial"), (32, -1, "ompi_basic_linear")]),
]

# two_level_Direct degenerates to Direct (= ompi_basic_linear);
# MPIR_Gather_intra is the mpich chooser
_MV2_GATHER = [
    (16, [(0, 524288, "ompi_basic_linear"), (524288, -1, "mpich")]),
    (32, [(0, 16384, "ompi_basic_linear"), (16384, 131072, "mpich"),
          (131072, -1, "ompi_basic_linear")]),
    (2048, [(0, -1, "ompi_basic_linear")]),
]


@register("alltoall", "mvapich2")
def alltoall_mvapich2(comm, sendobjs: List):
    nbytes = payload_size(sendobjs[0], None) if sendobjs else 0
    name = _mv2_pick(_MV2_ALLTOALL, comm.size(), nbytes)
    return dispatch_name("alltoall", name)(comm, sendobjs)


@register("allgather", "mvapich2")
def allgather_mvapich2(comm, sendobj):
    _require_symmetric(sendobj, "allgather")
    nbytes = payload_size(sendobj, None)
    name = _mv2_pick(_MV2_ALLGATHER, comm.size(), nbytes)
    return dispatch_name("allgather", name)(comm, sendobj)


@register("allreduce", "mvapich2")
def allreduce_mvapich2(comm, sendobj, op: Op):
    nbytes = payload_size(sendobj, None)
    name = _mv2_pick(_MV2_ALLREDUCE, comm.size(), nbytes)
    return dispatch_name("allreduce", name)(comm, sendobj, op)


@register("bcast", "mvapich2")
def bcast_mvapich2(comm, obj, root: int = 0):
    _require_symmetric(obj, "bcast")
    nbytes = payload_size(obj, None)
    name = _mv2_pick(_MV2_BCAST, comm.size(), nbytes)
    return dispatch_name("bcast", name)(comm, obj, root)


@register("reduce", "mvapich2")
def reduce_mvapich2(comm, sendobj, op: Op, root: int = 0):
    _require_symmetric(sendobj, "reduce")
    nbytes = payload_size(sendobj, None)
    name = _mv2_pick(_MV2_REDUCE, comm.size(), nbytes)
    return dispatch_name("reduce", name)(comm, sendobj, op, root)


@register("scatter", "mvapich2")
def scatter_mvapich2(comm, sendobjs, root: int = 0):
    nbytes = payload_size(sendobjs[0], None) if sendobjs else 0
    name = _mv2_pick(_MV2_SCATTER, comm.size(), nbytes)
    return dispatch_name("scatter", name)(comm, sendobjs, root)


@register("gather", "mvapich2")
def gather_mvapich2(comm, sendobj, root: int = 0):
    _require_symmetric(sendobj, "gather")
    nbytes = payload_size(sendobj, None)
    name = _mv2_pick(_MV2_GATHER, comm.size(), nbytes)
    return dispatch_name("gather", name)(comm, sendobj, root)


@register("barrier", "mvapich2")
def barrier_mvapich2(comm):
    """mvapich2_pair = pairwise-exchange barrier = the registered
    recursive-doubling barrier (smpi_mvapich2_selector.cpp:456)."""
    return dispatch_name("barrier", "ompi_recursivedoubling")(comm)


@register("reduce_scatter", "mvapich2")
def reduce_scatter_mvapich2(comm, sendobjs: List, op: Op):
    """mvapich2 has no reduce_scatter table; its fallback is the mpich
    chooser (smpi_coll.cpp default wiring)."""
    return dispatch_name("reduce_scatter", "mpich")(comm, sendobjs, op)


# ---------------------------------------------------------------------------
# Intel MPI (impi)
# ---------------------------------------------------------------------------

#: 1-based algorithm index -> registered algorithm, one list per op
#: (the intel_*_functions_table arrays; SMP/two-level and the unknown
#: proprietary entries map to their flat SimGrid substitutes exactly as
#: the reference's own tables do)
_INTEL_FUNCS = {
    "allreduce": ["rdb", "rab_rdb", "redbcast", "rdb", "redbcast",
                  "rdb", "ompi_ring_segmented", "ompi_ring_segmented"],
    "alltoall": ["bruck", "mvapich2_scatter_dest", "pair", "mvapich2"],
    "barrier": ["ompi_basic_linear", "ompi_recursivedoubling",
                "ompi_basic_linear", "ompi_recursivedoubling",
                # gather+scatter through root ~ centralized linear
                "ompi_basic_linear", "ompi_basic_linear"],
    "bcast": ["binomial_tree", "ompi_pipeline", "ompi_pipeline",
              "binomial_tree", "ompi_pipeline", "flat_tree", "mvapich2"],
    "reduce": ["mvapich2", "binomial", "mvapich2", "binomial",
               "scatter_gather", "scatter_gather"],
    "reduce_scatter": ["ompi_basic_recursivehalving", "mpich_pair",
                       "mpich_rdb", "default", "default"],
    "allgather": ["rdb", "bruck", "ring", "GB"],
    "allgatherv": ["rdb", "bruck", "ring", "GB"],
    "gather": ["ompi_binomial", "ompi_binomial", "mvapich2"],
    "scatter": ["ompi_binomial", "ompi_binomial", "mvapich2"],
    "alltoallv": ["basic_linear", "bruck"],
}


def _intel_pick(op: str, comm_size: int, block_dsize: float) -> str:
    """IMPI_COLL_SELECT: numproc row (first max_num_proc >= size),
    then first size regime with block < max_size (strict, the C loop
    advances while block >= max), then the 1-based index."""
    table = INTEL_TABLES[op]
    j = 0
    while j < len(table) - 1 and comm_size > table[j][0]:
        j += 1
    regimes = table[j][1]
    k = 0
    while k < len(regimes) - 1 and block_dsize >= regimes[k][0]:
        k += 1
    return _INTEL_FUNCS[op][regimes[k][1] - 1]


@register("allreduce", "impi")
def allreduce_impi(comm, sendobj, op: Op):
    name = _intel_pick("allreduce", comm.size(),
                       payload_size(sendobj, None))
    return dispatch_name("allreduce", name)(comm, sendobj, op)


@register("alltoall", "impi")
def alltoall_impi(comm, sendobjs: List):
    block = payload_size(sendobjs[0], None) if sendobjs else 0
    name = _intel_pick("alltoall", comm.size(), block)
    return dispatch_name("alltoall", name)(comm, sendobjs)


@register("barrier", "impi")
def barrier_impi(comm):
    name = _intel_pick("barrier", comm.size(), 1)
    return dispatch_name("barrier", name)(comm)


@register("bcast", "impi")
def bcast_impi(comm, obj, root: int = 0):
    _require_symmetric(obj, "bcast")
    name = _intel_pick("bcast", comm.size(), payload_size(obj, None))
    return dispatch_name("bcast", name)(comm, obj, root)


@register("reduce", "impi")
def reduce_impi(comm, sendobj, op: Op, root: int = 0):
    _require_symmetric(sendobj, "reduce")
    name = _intel_pick("reduce", comm.size(), payload_size(sendobj, None))
    return dispatch_name("reduce", name)(comm, sendobj, op, root)


@register("reduce_scatter", "impi")
def reduce_scatter_impi(comm, sendobjs: List, op: Op):
    total = sum(payload_size(o, None) for o in (sendobjs or []))
    name = _intel_pick("reduce_scatter", comm.size(), total)
    return dispatch_name("reduce_scatter", name)(comm, sendobjs, op)


@register("allgather", "impi")
def allgather_impi(comm, sendobj):
    _require_symmetric(sendobj, "allgather")
    name = _intel_pick("allgather", comm.size(),
                       payload_size(sendobj, None))
    return dispatch_name("allgather", name)(comm, sendobj)


@register("gather", "impi")
def gather_impi(comm, sendobj, root: int = 0):
    _require_symmetric(sendobj, "gather")
    name = _intel_pick("gather", comm.size(), payload_size(sendobj, None))
    return dispatch_name("gather", name)(comm, sendobj, root)


@register("scatter", "impi")
def scatter_impi(comm, sendobjs, root: int = 0):
    block = payload_size(sendobjs[0], None) if sendobjs else 0
    name = _intel_pick("scatter", comm.size(), block)
    return dispatch_name("scatter", name)(comm, sendobjs, root)


# ---------------------------------------------------------------------------
# automatic (run them all, report the quickest)
# ---------------------------------------------------------------------------

_SELECTOR_NAMES = frozenset(
    ["default", "automatic", "mpich", "ompi", "mvapich2", "impi"])


def _automatic(op: str):
    def auto(comm, *args):
        from ..s4u import Engine
        result = None
        best_name, best_t = None, float("inf")
        gbest_name, gbest_t = None, float("inf")
        me = comm.rank()
        for name in sorted(_ALGOS[op]):
            if name in _SELECTOR_NAMES:
                continue
            fn = _ALGOS[op][name]
            dispatch_name("barrier", "default")(comm)
            t0 = Engine.get_clock()
            try:
                result = fn(comm, *args)
            except Exception:
                continue
            dt = Engine.get_clock() - t0
            # slowest rank defines the collective's cost (the
            # reference reduces MPI_MAX to rank 0 the same way)
            worst = dispatch_name("reduce", "default")(
                comm, dt, MPI_MAX, 0)
            if dt < best_t:
                best_name, best_t = name, dt
            if me == 0 and worst is not None and worst < gbest_t:
                gbest_name, gbest_t = name, float(worst)
        if me == 0:
            log.warning(
                f"For rank 0, the quickest {op} was {best_name}: "
                f"{best_t:f}, but global was {gbest_name}: {gbest_t:f} "
                f"at max")
        else:
            log.warning(f"The quickest {op} was {best_name} on rank "
                        f"{me} and took {best_t:f}")
        return result
    return auto


for _op in ("allreduce", "alltoall", "barrier", "bcast", "reduce",
            "reduce_scatter", "allgather", "gather", "scatter"):
    register(_op, "automatic")(_automatic(_op))
