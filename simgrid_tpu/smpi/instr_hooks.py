"""SMPI binding-layer tracing hooks.

The reference instruments every MPI entry point in its PMPI bindings
(TRACE_smpi_comm_in/out, smpi_pmpi_*.cpp) and hides the point-to-point
traffic generated *inside* collective algorithms unless
tracing/smpi/internals is set (TRACE_smpi_view_internals). Here the
binding layer is Comm's public methods; each span tracks per-world-rank
nesting depth and yields its own visibility, which call sites use to
gate the pt2pt arrows — so suppression is symmetric on both sides of a
matched message and free of cross-rank depth confusion.

When tracing is off every span builder returns one shared null context:
no lambda, no generator, no TIData — the hot p2p path pays a single
enabled() check.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Callable, Dict

from .. import instr
from ..instr import ti
from ..utils.config import config, declare_flag

declare_flag("tracing/smpi/internals",
             "Also display the communications produced inside collective "
             "operations", False)

_depth: Dict[int, int] = {}

#: Shared disabled-span: `with span(...) as visible` yields False.
_NULL = nullcontext(False)


def _rank() -> int:
    from . import runtime
    return runtime.this_rank()


def _instance() -> str:
    from . import runtime
    return runtime.this_rank_state().instance


class _Span:
    """One traced MPI call: push state (or emit the TI action line) on
    entry, pop on exit. Yields True when this call is visible (top-level
    or internals tracing on)."""

    __slots__ = ("op_name", "extra_factory", "ti_line", "rank", "depth",
                 "visible", "_key")

    def __init__(self, op_name: str,
                 extra_factory: Callable[[], ti.TIData], ti_line: bool):
        self.op_name = op_name
        self.extra_factory = extra_factory
        self.ti_line = ti_line

    def __enter__(self) -> bool:
        self.rank = _rank()
        self._key = (_instance(), self.rank)
        self.depth = _depth.get(self._key, 0)
        _depth[self._key] = self.depth + 1
        self.visible = self.depth == 0 or config["tracing/smpi/internals"]
        if self.visible:
            instr.smpi_in(self.rank, self.op_name, self.extra_factory(),
                          ti_line=self.ti_line, instance=self._key[0])
        return self.visible

    def __exit__(self, *exc) -> None:
        _depth[self._key] = self.depth
        if self.visible:
            instr.smpi_out(self.rank, instance=self._key[0])


def span(op_name: str, extra_factory: Callable[[], ti.TIData],
         ti_line: bool = True):
    if not instr.smpi_enabled():
        return _NULL
    return _Span(op_name, extra_factory, ti_line)


def _elem_count(req) -> int:
    """TI traces size p2p ops in datatype elements when a datatype is
    known, bytes (MPI_BYTE) otherwise — matching the reference's
    Pt2PtTIData usage in smpi_pmpi_request.cpp. An any-size recv
    (unknown until matched) is encoded as -1; the replay engine probes
    for the real size (smpi_replay.cpp RecvAction)."""
    if req.datatype is not None:
        return int(req.count)
    return int(req.size) if req.size != float("inf") else -1


def _encode(datatype) -> str:
    from .datatype import encode
    return encode(datatype) if datatype is not None else "6"


def p2p_span(name: str, peer: int, tag: int, req):
    if not instr.smpi_enabled():
        return _NULL
    return _Span(name, lambda: ti.Pt2PtTIData(
        name, peer, _elem_count(req), tag, _encode(req.datatype)), True)


def wait_span(req):
    if not instr.smpi_enabled():
        return _NULL
    return _Span("wait",
                 lambda: ti.WaitTIData(req.src, req.dst, req.tag), True)


def coll_span(name: str, send_size, recv_size=-1, amount=-1.0, root=-1,
              send_type: str = "6", recv_type: str = ""):
    if not instr.smpi_enabled():
        return _NULL
    return _Span(name, lambda: ti.CollTIData(
        name, root, amount, int(send_size), int(recv_size),
        send_type, recv_type), True)


def varcoll_span(name: str, root: int = -1, send_size: int = -1,
                 sendcounts=None, recv_size: int = 0, recvcounts=None,
                 send_type: str = "0", recv_type: str = "6"):
    if not instr.smpi_enabled():
        return _NULL
    return _Span(name, lambda: ti.VarCollTIData(
        name, root, send_size, sendcounts, recv_size, recvcounts,
        send_type, recv_type), True)


def cpu_span(name: str, amount: float):
    """compute/sleep states; gated like TRACE_smpi_computing_in
    (instr_smpi.cpp:191-202)."""
    if not instr.smpi_enabled() or not config["tracing/smpi/computing"]:
        return _NULL
    return _Span(name, lambda: ti.CpuTIData(name, amount), True)


def noop_span(name: str, ti_line: bool = True):
    if not instr.smpi_enabled():
        return _NULL
    return _Span(name, lambda: ti.NoOpTIData(name), ti_line)


# ---------------------------------------------------------------------------
# pt2pt arrows — call ONLY when the enclosing span yielded visible=True.
# ---------------------------------------------------------------------------

def _ensure_rank_container(world_rank: int) -> None:
    """The pt2pt arrow may reference a peer whose actor has not started
    yet (so its own smpi_init has not run); create its container now."""
    from . import runtime
    state = runtime.state_of_world_rank(world_rank)
    instr.smpi_init(world_rank, state.host, instance=state.instance)


def send_arrow(comm, dst: int, tag: int, size) -> None:
    rank = comm.rank()
    world_dst = comm.world_rank_of(dst)
    _ensure_rank_container(world_dst)
    instr.smpi_send(rank, comm.world_rank_of(rank), world_dst, tag,
                    int(size), instance=_instance())


def recv_arrow_once(req) -> None:
    """Emit the EndLink for a completed recv request exactly once, no
    matter how it completed (wait, recv, test, waitany)."""
    if getattr(req, "_arrow_done", False) or req.real_src < 0 \
            or not req.finished:
        return
    req._arrow_done = True
    comm = req.comm
    world_src = comm.world_rank_of(req.real_src)
    _ensure_rank_container(world_src)
    instr.smpi_recv(world_src, comm.world_rank_of(comm.rank()),
                    req.real_tag, instance=_instance())
