"""Intercommunicators (reference src/smpi/mpi/smpi_comm.cpp intercomm
paths + smpi_intercomm coll semantics).

An InterComm pairs a LOCAL group with a REMOTE group: point-to-point
ranks address the remote group, and rooted collectives move data
between the two sides (MPI_ROOT / MPI_PROC_NULL on the origin side).
Communicator ids are canonical functions of both groups + the creation
tag, so the two sides build matching ids without extra traffic.
"""

from __future__ import annotations

from typing import List, Optional

from .comm import Comm
from .group import Group
from .op import MPI_SUM, Op

MPI_ROOT = -3
MPI_PROC_NULL = -2

TAG_IC_CREATE = -130
TAG_IC_COLL = -131
TAG_IC_MGMT = -132


def _canon(a: List[int], b: List[int]):
    """Order the two groups canonically so both sides derive the SAME
    communicator id."""
    return (tuple(a), tuple(b)) if min(a) <= min(b) else (tuple(b),
                                                          tuple(a))


class InterComm(Comm):
    """A communicator whose peers live in the remote group."""

    def __init__(self, local_group: Group, remote_group: Group, id):
        super().__init__(local_group, id)
        self.remote_group = remote_group
        #: intra-communicator over the local side, for the local phases
        #: of intercomm collectives (disjoint member sets cannot
        #: cross-match even with related ids — matching is per-rank
        #: mailbox + comm id)
        self.local_intra = Comm(
            local_group,
            id=("icl", tuple(local_group.world_ranks), id))

    def is_inter(self) -> bool:
        return True

    def remote_size(self) -> int:
        return self.remote_group.size()

    # -- communicator management over an intercomm ----------------------
    def _exchange_with_remote(self, payload):
        """Leaders swap `payload` across the bridge, then broadcast the
        remote value locally (the standard intercomm-collective
        exchange pattern, used by dup/create/split id agreement)."""
        if self.rank() == 0:
            rreq = self.irecv(0, TAG_IC_MGMT)
            self.isend(payload, 0, TAG_IC_MGMT).wait()
            remote = rreq.wait()
            self.local_intra.bcast(remote, 0)
        else:
            remote = self.local_intra.bcast(None, 0)
        return remote

    def dup(self) -> "InterComm":
        """MPI_Comm_dup of an intercommunicator yields an
        intercommunicator (mtest case 'splitting then dup'ing')."""
        return InterComm(Group(list(self.group.world_ranks)),
                         Group(list(self.remote_group.world_ranks)),
                         self._next_cc_id("dup"))

    def create(self, group: Group) -> Optional["InterComm"]:
        """MPI_Comm_create on an intercomm: each side passes a subset
        of its LOCAL group; the result pairs the two subsets.  The
        subsets are exchanged through the leaders (standard
        algorithm)."""
        cid_seq = self._next_cc_id("create")
        local_subset = list(group.world_ranks) if group is not None else []
        remote_subset = self._exchange_with_remote(local_subset)
        my_world = self.group.actor(self.rank())
        if group is None or group.rank(my_world) < 0:
            return None
        if not remote_subset:
            return None
        cid = (("interc",) + _canon(local_subset, list(remote_subset))
               + (cid_seq[1],))
        return InterComm(Group(local_subset), Group(list(remote_subset)),
                         cid)

    def split(self, color: int, key: int) -> Optional["InterComm"]:
        """MPI_Comm_split on an intercomm: same-color groups pair up
        across the two sides; an empty remote color group yields
        MPI_COMM_NULL (icsplit.c:94-105 semantics)."""
        cid_seq = self._next_cc_id(("split", color))
        me = self.rank()
        all_local = self.local_intra.allgather((color, key, me))
        remote_triples = self._exchange_with_remote(all_local)
        if color < 0:
            return None
        local_members = sorted((k, r) for c, k, r in all_local
                               if c == color)
        remote_members = sorted((k, r) for c, k, r in remote_triples
                                if c == color)
        if not remote_members:
            return None
        lg = Group([self.group.actor(r) for _, r in local_members])
        rg = Group([self.remote_group.actor(r) for _, r in remote_members])
        cid = (("inters",) + _canon(list(lg.world_ranks),
                                    list(rg.world_ranks))
               + (cid_seq[1], color))
        return InterComm(lg, rg, cid)

    def world_rank_of(self, group_rank: int) -> int:
        """P2P targets address the REMOTE group."""
        return self.remote_group.actor(group_rank)

    # -- intercommunicator collectives ---------------------------------
    # (leader = local rank 0 on each side; data crosses between the
    # leaders, local phases ride the local intracomm)

    def barrier(self) -> None:
        me = self.rank()
        self.local_intra.barrier()
        if me == 0:
            sreq = self.isend(0, 0, TAG_IC_COLL)   # token exchange
            self.recv(0, TAG_IC_COLL)
            sreq.wait()
        self.local_intra.barrier()

    def bcast(self, obj, root: int = 0):
        me = self.rank()
        if root == MPI_PROC_NULL:
            return None
        if root == MPI_ROOT:
            self.send(obj, 0, TAG_IC_COLL)      # to remote leader
            return obj
        # leaf side: local leader receives from the remote root rank
        if me == 0:
            obj = self.recv(root, TAG_IC_COLL)
        return self.local_intra.bcast(obj, 0)

    def reduce(self, sendobj, op: Op = MPI_SUM, root: int = 0):
        me = self.rank()
        if root == MPI_PROC_NULL:
            return None
        if root == MPI_ROOT:
            return self.recv(0, TAG_IC_COLL)    # combined remote data
        combined = self.local_intra.reduce(sendobj, op, 0)
        if me == 0:
            self.send(combined, root, TAG_IC_COLL)
        return None

    def allreduce(self, sendobj, op: Op = MPI_SUM):
        """Each side receives the reduction of the OTHER side's data
        (MPI-2 intercomm allreduce semantics)."""
        me = self.rank()
        combined = self.local_intra.reduce(sendobj, op, 0)
        if me == 0:
            # isend+recv: two leaders exchanging large payloads must
            # not both block in rendezvous sends
            sreq = self.isend(combined, 0, TAG_IC_COLL)
            remote = self.recv(0, TAG_IC_COLL)
            sreq.wait()
        else:
            remote = None
        return self.local_intra.bcast(remote, 0)

    def gather(self, sendobj, root: int = 0):
        me = self.rank()
        if root == MPI_PROC_NULL:
            return None
        if root == MPI_ROOT:
            return self.recv(0, TAG_IC_COLL)    # remote side's vector
        parts = self.local_intra.gather(sendobj, 0)
        if me == 0:
            self.send(parts, root, TAG_IC_COLL)
        return None

    def scatter(self, sendobjs, root: int = 0):
        me = self.rank()
        if root == MPI_PROC_NULL:
            return None
        if root == MPI_ROOT:
            self.send(list(sendobjs), 0, TAG_IC_COLL)
            return None
        if me == 0:
            sendobjs = self.recv(root, TAG_IC_COLL)
        else:
            sendobjs = None
        return self.local_intra.scatter(sendobjs, 0)

    def allgather(self, sendobj):
        me = self.rank()
        mine = self.local_intra.gather(sendobj, 0)
        if me == 0:
            sreq = self.isend(mine, 0, TAG_IC_COLL)
            remote = self.recv(0, TAG_IC_COLL)
            sreq.wait()
        else:
            remote = None
        return self.local_intra.bcast(remote, 0)

    def alltoall(self, sendobjs):
        """Rank i sends sendobjs[j] to remote rank j; receives one
        payload from every remote rank."""
        reqs = [self.isend(sendobjs[j], j, TAG_IC_COLL)
                for j in range(self.remote_size())]
        out = [self.recv(src, TAG_IC_COLL)
               for src in range(self.remote_size())]
        for r in reqs:
            r.wait()
        return out

    def merge(self, high: bool) -> Comm:
        """MPI_Intercomm_merge: one intracomm over both groups; the
        low side orders first (ties broken by smaller leader world
        rank, like the reference). Intercomm allreduce returns the
        OTHER side's reduction, which is exactly the remote high
        count."""
        remote_highs = self.allreduce(1 if high else 0, MPI_SUM)
        my_high, remote_high = bool(high), int(remote_highs) > 0
        local = list(self.group.world_ranks)
        remote = list(self.remote_group.world_ranks)
        if my_high == remote_high:
            first = local if min(local) < min(remote) else remote
        else:
            first = remote if my_high else local
        second = remote if first is local else local
        cid = ("merged",) + _canon(local, remote)
        return Comm(Group(first + second), id=cid)


def intercomm_create(local_comm: Comm, local_leader: int,
                     peer_comm: Optional[Comm], remote_leader: int,
                     tag: int) -> InterComm:
    """MPI_Intercomm_create: the two leaders exchange their group
    lists over peer_comm, then broadcast them within their local
    communicators (smpi_comm.cpp / standard algorithm)."""
    me = local_comm.rank()
    local_ranks = list(local_comm.group.world_ranks)
    if me == local_leader:
        assert peer_comm is not None, \
            "the leaders must share the peer communicator"
        rreq = peer_comm.irecv(remote_leader, tag)
        peer_comm.isend(local_ranks, remote_leader, tag).wait()
        remote_ranks = rreq.wait()
        local_comm.bcast(remote_ranks, local_leader)
    else:
        remote_ranks = local_comm.bcast(None, local_leader)
    cid = ("inter",) + _canon(local_ranks, list(remote_ranks)) + (tag,)
    return InterComm(local_comm.group, Group(list(remote_ranks)), cid)


# -- v-variants: payloads carry their own sizes in this object model,
# so the base intercomm patterns serve directly
def _alias_v(cls):
    cls.allgatherv = cls.allgather
    cls.alltoallv = cls.alltoall
    cls.gatherv = cls.gather
    cls.scatterv = cls.scatter
    return cls


_alias_v(InterComm)


def _ic_reduce_scatter(self, sendobjs, op: Op = MPI_SUM):
    """Intercomm reduce_scatter: every rank gets its segment of the
    reduction of the REMOTE side's data = intercomm allreduce of the
    full vector + local segmentation."""
    full = list(sendobjs)
    remote_combined = InterComm.allreduce(self, full, op)
    return remote_combined[self.rank()]


InterComm.reduce_scatter = _ic_reduce_scatter
