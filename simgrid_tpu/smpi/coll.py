"""SMPI collective algorithms + selector (reference src/smpi/colls/).

Each operation has a registry of named algorithms; the active one is
chosen by ``--cfg=smpi/<op>:<name>`` with ``default`` mirroring the
reference's default selector choices (smpi_default_selector.cpp):
binomial-tree bcast, linear barrier/gather/scatter/allgather,
reduce+bcast allreduce, size-staged OpenMPI-style alltoall, chained
scan.  All algorithms decompose into Request send/recv pairs, so the
eager/rendezvous protocol, detached sends and o/Os/Or overheads apply
exactly as they do to user point-to-point traffic.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ..utils.config import config
from .datatype import payload_size
from .op import Op

# Collective tags (negative, outside the user range, one per op family —
# reference smpi/include/private.hpp COLL_TAG_*)
TAG_BCAST = -10
TAG_BARRIER = -11
TAG_REDUCE = -12
TAG_ALLREDUCE = -13
TAG_ALLTOALL = -14
TAG_GATHER = -15
TAG_ALLGATHER = -16
TAG_SCATTER = -17
TAG_REDUCE_SCATTER = -18
TAG_SCAN = -19

_ALGOS: Dict[str, Dict[str, Callable]] = {}


_SELECTORS = ("default", "mpich", "ompi", "mvapich2", "impi",
              "automatic")


def register(op: str, name: str):
    def deco(fn):
        registry = _ALGOS.setdefault(op, {})
        assert name not in registry, \
            f"duplicate registration of {op}/{name}"
        registry[name] = fn
        return fn
    return deco


def dispatch(op: str) -> Callable:
    """Pick the active algorithm: the per-op smpi/<op> flag wins; when
    it is 'default', the smpi/coll-selector flag (default|mpich|ompi)
    routes through the matching decision tree (smpi_coll.cpp:33-118
    COLL_SETTER precedence). Ops a selector doesn't cover fall back to
    the default algorithm."""
    name = config[f"smpi/{op}"]
    algos = _ALGOS[op]
    if name == "default":
        selector = config["smpi/coll-selector"]
        if selector not in _SELECTORS:
            # Unknown selectors abort like the reference's COLL_SETTER
            # lookup (smpi_coll.cpp) instead of silently running default.
            raise ValueError(
                f"Unknown smpi/coll-selector {selector!r}; "
                f"known: {_SELECTORS}")
        if selector != "default" and selector in algos:
            name = selector
    if name not in algos:
        raise ValueError(
            f"Unknown {op} algorithm {name!r}; known: {sorted(algos)}")
    return algos[name]


def dispatch_name(op: str, name: str) -> Callable:
    """Fetch a specific named algorithm (used by the selector trees)."""
    return _ALGOS[op][name]


# ---------------------------------------------------------------------------
# bcast
# ---------------------------------------------------------------------------

@register("bcast", "default")
@register("bcast", "binomial_tree")
def bcast_binomial_tree(comm, obj, root: int = 0):
    """Binomial tree broadcast (colls/bcast/bcast-binomial-tree.cpp)."""
    rank, size = comm.rank(), comm.size()
    relrank = (rank - root + size) % size
    mask = 1
    while mask < size:
        if relrank & mask:
            obj = comm.recv((rank - mask + size) % size, TAG_BCAST)
            break
        mask <<= 1
    mask >>= 1
    while mask > 0:
        if relrank + mask < size:
            comm.send(obj, (rank + mask) % size, TAG_BCAST)
        mask >>= 1
    return obj


@register("bcast", "flat_tree")
def bcast_flat_tree(comm, obj, root: int = 0):
    """Root sends to everyone (colls/bcast/bcast-flat-tree.cpp)."""
    rank, size = comm.rank(), comm.size()
    if rank == root:
        reqs = [comm.isend(obj, dst, TAG_BCAST)
                for dst in range(size) if dst != root]
        for r in reqs:
            r.wait()
        return obj
    return comm.recv(root, TAG_BCAST)


# ---------------------------------------------------------------------------
# barrier
# ---------------------------------------------------------------------------

@register("barrier", "default")
@register("barrier", "ompi_basic_linear")
def barrier_linear(comm):
    """All ranks report to 0, 0 releases all (barrier-ompi.cpp
    basic_linear)."""
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return
    if rank == 0:
        for _ in range(size - 1):
            comm.recv(tag=TAG_BARRIER)
        reqs = [comm.isend(b"", dst, TAG_BARRIER) for dst in range(1, size)]
        for r in reqs:
            r.wait()
    else:
        comm.send(b"", 0, TAG_BARRIER)
        comm.recv(0, TAG_BARRIER)


@register("barrier", "bruck")
def barrier_bruck(comm):
    """log2(n) rounds of shifted token exchange (barrier-bruck.cpp)."""
    rank, size = comm.rank(), comm.size()
    distance = 1
    while distance < size:
        to = (rank + distance) % size
        frm = (rank - distance + size) % size
        comm.sendrecv(b"", to, frm, TAG_BARRIER, TAG_BARRIER)
        distance <<= 1


# ---------------------------------------------------------------------------
# reduce
# ---------------------------------------------------------------------------

@register("reduce", "default")
def reduce_flat_ireduce(comm, sendobj, op: Op, root: int = 0):
    """The reference default: Colls::ireduce + wait
    (smpi_default_selector.cpp Coll_reduce_default) — the root posts
    irecvs from every rank up front so all incoming transfers share the
    network concurrently, then folds in canonical order."""
    if not op.is_commutative():
        return reduce_linear(comm, sendobj, op, root)
    rank, size = comm.rank(), comm.size()
    if rank != root:
        comm.send(sendobj, root, TAG_REDUCE)
        return None
    reqs = [comm.irecv(src, TAG_REDUCE) for src in range(size)
            if src != root]
    parts = [None] * size
    parts[root] = sendobj
    others = [src for src in range(size) if src != root]
    for src, req in zip(others, reqs):
        parts[src] = req.wait()
    result = parts[size - 1]
    for i in range(size - 2, -1, -1):
        result = op(parts[i], result)
    return result


@register("reduce", "binomial")
def reduce_binomial(comm, sendobj, op: Op, root: int = 0):
    """Binomial-tree reduction (colls/reduce/reduce-binomial.cpp);
    falls back to the order-preserving linear algorithm for
    non-commutative ops like the reference default selector."""
    if not op.is_commutative():
        return reduce_linear(comm, sendobj, op, root)
    rank, size = comm.rank(), comm.size()
    relrank = (rank - root + size) % size
    result = sendobj
    mask = 1
    while mask < size:
        if relrank & mask:
            comm.send(result, (relrank - mask + root) % size, TAG_REDUCE)
            break
        peer_rel = relrank | mask
        if peer_rel < size:
            data = comm.recv((peer_rel + root) % size, TAG_REDUCE)
            result = op(result, data)
        mask <<= 1
    return result if rank == root else None


@register("reduce", "linear")
def reduce_linear(comm, sendobj, op: Op, root: int = 0):
    """Root receives from everyone in rank order and folds right-to-left
    so non-commutative ops see MPI's canonical ordering
    (reduce-ompi.cpp basic_linear)."""
    rank, size = comm.rank(), comm.size()
    if rank != root:
        comm.send(sendobj, root, TAG_REDUCE)
        return None
    parts = [None] * size
    parts[root] = sendobj
    for src in range(size):
        if src != root:
            parts[src] = comm.recv(src, TAG_REDUCE)
    result = parts[size - 1]
    for i in range(size - 2, -1, -1):
        result = op(parts[i], result)
    return result


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

@register("allreduce", "default")
@register("allreduce", "redbcast")
def allreduce_redbcast(comm, sendobj, op: Op):
    """reduce to 0 + bcast (the reference default,
    smpi_default_selector.cpp Coll_allreduce_default)."""
    result = dispatch("reduce")(comm, sendobj, op, 0)
    return dispatch("bcast")(comm, result, 0)


@register("allreduce", "rdb")
def allreduce_rdb(comm, sendobj, op: Op):
    """Recursive doubling with non-power-of-two fold-in
    (colls/allreduce/allreduce-rdb.cpp)."""
    rank, size = comm.rank(), comm.size()
    pof2 = 1
    while pof2 * 2 <= size:
        pof2 *= 2
    rem = size - pof2
    result = sendobj

    if rank < 2 * rem:
        if rank % 2 == 0:
            comm.send(result, rank + 1, TAG_ALLREDUCE)
            newrank = -1
        else:
            data = comm.recv(rank - 1, TAG_ALLREDUCE)
            result = op(data, result)
            newrank = rank // 2
    else:
        newrank = rank - rem

    if newrank >= 0:
        mask = 1
        while mask < pof2:
            peer_new = newrank ^ mask
            peer = peer_new * 2 + 1 if peer_new < rem else peer_new + rem
            data = comm.sendrecv(result, peer, peer,
                                 TAG_ALLREDUCE, TAG_ALLREDUCE)
            result = op(data, result) if peer < rank else op(result, data)
            mask <<= 1

    if rank < 2 * rem:
        if rank % 2:
            comm.send(result, rank - 1, TAG_ALLREDUCE)
        else:
            result = comm.recv(rank + 1, TAG_ALLREDUCE)
    return result


@register("allreduce", "lr")
def allreduce_lr(comm, sendobj, op: Op):
    """Logical-ring reduce-scatter + all-gather
    (colls/allreduce/allreduce-lr.cpp:24-108), including its observable
    timing quirks: an initial sendrecv-to-self copy (which rides the
    loopback link in simulation), equal rcount//size chunks, and the
    remaining rcount % size elements reduced by a recursive default
    allreduce at the end."""
    import numpy as np
    rank, size = comm.rank(), comm.size()
    if not (isinstance(sendobj, np.ndarray) and len(sendobj) >= size):
        # "when communication size is smaller than number of process
        # (not support)" -> default (allreduce-lr.cpp:41-45)
        return allreduce_rdb(comm, sendobj, op)
    rcount = len(sendobj)
    count = rcount // size
    remainder = rcount % size
    buf = sendobj.copy()
    chunk = lambda idx: buf[idx * count:(idx + 1) * count]

    # One constant tag throughout: the reference's per-step tag + i walk
    # would leave the reserved negative range and collide with other
    # collectives' tags (and user tags); per-(pair,tag) FIFO ordering
    # already sequences the ring steps, so one tag is equivalent and safe.
    # copy partial data: sendrecv to self (allreduce-lr.cpp:69-73)
    idx0 = (rank - 1 + size) % size
    chunk_copy = comm.sendrecv(chunk(idx0).copy(), rank, rank,
                               TAG_ALLREDUCE, TAG_ALLREDUCE)
    buf[idx0 * count:(idx0 + 1) * count] = chunk_copy

    # reduce-scatter (allreduce-lr.cpp:76-88); reduction applies
    # sbuf + rbuf into the received chunk
    for i in range(size - 1):
        send_idx = (rank - 1 - i + 2 * size) % size
        recv_idx = (rank - 2 - i + 2 * size) % size
        data = comm.sendrecv(chunk(send_idx).copy(), (rank + 1) % size,
                             (rank - 1 + size) % size,
                             TAG_ALLREDUCE, TAG_ALLREDUCE)
        reduced = op(sendobj[recv_idx * count:(recv_idx + 1) * count], data)
        buf[recv_idx * count:(recv_idx + 1) * count] = reduced

    # all-gather (allreduce-lr.cpp:91-97)
    for i in range(size - 1):
        send_idx = (rank - i + 2 * size) % size
        recv_idx = (rank - 1 - i + 2 * size) % size
        data = comm.sendrecv(chunk(send_idx).copy(), (rank + 1) % size,
                             (rank - 1 + size) % size,
                             TAG_ALLREDUCE, TAG_ALLREDUCE)
        buf[recv_idx * count:(recv_idx + 1) * count] = data

    if remainder:
        # remainder chunk via the default algorithm (allreduce-lr.cpp:101-105)
        tail = dispatch("allreduce")(comm, sendobj[size * count:], op)
        buf[size * count:] = tail
    return buf


# ---------------------------------------------------------------------------
# gather / allgather / scatter
# ---------------------------------------------------------------------------

@register("gather", "default")
@register("gather", "linear")
def gather_linear(comm, sendobj, root: int = 0):
    rank, size = comm.rank(), comm.size()
    if rank != root:
        comm.send(sendobj, root, TAG_GATHER)
        return None
    out = [None] * size
    out[root] = sendobj
    reqs = [(src, comm.irecv(src, TAG_GATHER))
            for src in range(size) if src != root]
    for src, req in reqs:
        out[src] = req.wait()
    return out


@register("allgather", "default")
@register("allgather", "linear")
def allgather_linear(comm, sendobj):
    """Everyone isends to everyone (the NBC linear scheme the reference
    default selector uses via iallgather)."""
    rank, size = comm.rank(), comm.size()
    out = [None] * size
    out[rank] = sendobj
    rreqs = [(src, comm.irecv(src, TAG_ALLGATHER))
             for src in range(size) if src != rank]
    sreqs = [comm.isend(sendobj, dst, TAG_ALLGATHER)
             for dst in range(size) if dst != rank]
    for src, req in rreqs:
        out[src] = req.wait()
    for req in sreqs:
        req.wait()
    return out


@register("allgather", "ring")
def allgather_ring(comm, sendobj):
    rank, size = comm.rank(), comm.size()
    out = [None] * size
    out[rank] = sendobj
    right = (rank + 1) % size
    left = (rank - 1 + size) % size
    block = sendobj
    for step in range(size - 1):
        block = comm.sendrecv(block, right, left,
                              TAG_ALLGATHER, TAG_ALLGATHER)
        out[(rank - step - 1 + size) % size] = block
    return out


@register("allgather", "rdb")
def allgather_rdb(comm, sendobj):
    """Recursive doubling (power-of-two comms; falls back to linear)."""
    rank, size = comm.rank(), comm.size()
    if size & (size - 1):
        return allgather_linear(comm, sendobj)
    have = {rank: sendobj}
    mask = 1
    while mask < size:
        peer = rank ^ mask
        # ship a snapshot: the live dict is mutated below while the
        # message is conceptually still in flight
        got = comm.sendrecv(dict(have), peer, peer,
                            TAG_ALLGATHER, TAG_ALLGATHER)
        have.update(got)
        mask <<= 1
    return [have[i] for i in range(size)]


@register("scatter", "default")
@register("scatter", "linear")
def scatter_linear(comm, sendobjs, root: int = 0):
    rank, size = comm.rank(), comm.size()
    if rank == root:
        assert sendobjs is not None and len(sendobjs) == size
        reqs = [comm.isend(sendobjs[dst], dst, TAG_SCATTER)
                for dst in range(size) if dst != root]
        for req in reqs:
            req.wait()
        return sendobjs[root]
    return comm.recv(root, TAG_SCATTER)


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------

@register("alltoall", "basic_linear")
def alltoall_basic_linear(comm, sendobjs):
    """Post everything at once (alltoall-basic-linear.cpp)."""
    rank, size = comm.rank(), comm.size()
    out = [None] * size
    out[rank] = sendobjs[rank]
    rreqs = [(src, comm.irecv(src, TAG_ALLTOALL))
             for src in range(size) if src != rank]
    sreqs = [comm.isend(sendobjs[dst], dst, TAG_ALLTOALL)
             for dst in range(size) if dst != rank]
    for src, req in rreqs:
        out[src] = req.wait()
    for req in sreqs:
        req.wait()
    return out


@register("alltoall", "pairwise")
def alltoall_pairwise(comm, sendobjs):
    """size-1 sendrecv steps with XOR/shift partners
    (alltoall-pair.cpp)."""
    rank, size = comm.rank(), comm.size()
    out = [None] * size
    out[rank] = sendobjs[rank]
    for step in range(1, size):
        dst = (rank + step) % size
        src = (rank - step + size) % size
        out[src] = comm.sendrecv(sendobjs[dst], dst, src,
                                 TAG_ALLTOALL, TAG_ALLTOALL)
    return out


@register("alltoall", "bruck")
def alltoall_bruck(comm, sendobjs):
    """log2(n) rounds shipping combined blocks (alltoall-bruck.cpp)."""
    rank, size = comm.rank(), comm.size()
    # local rotation: block for destination (rank+i)%size at slot i
    blocks = [sendobjs[(rank + i) % size] for i in range(size)]
    pof2 = 1
    while pof2 < size:
        to = (rank + pof2) % size
        frm = (rank - pof2 + size) % size
        idx = [i for i in range(size) if i & pof2]
        packed = {i: blocks[i] for i in idx}
        got = comm.sendrecv(packed, to, frm, TAG_ALLTOALL, TAG_ALLTOALL)
        for i, blk in got.items():
            blocks[i] = blk
        pof2 <<= 1
    # inverse rotation: what I now hold at slot i came from (rank-i)%size
    out = [None] * size
    for i in range(size):
        out[(rank - i + size) % size] = blocks[i]
    return out


@register("alltoall", "default")
def alltoall_ompi(comm, sendobjs):
    """The default selector's size staging (Coll_alltoall_default mirrors
    the ompi shape: bruck for tiny blocks on big comms, linear for mid,
    pairwise for large). The faithful ompi decision tree lives in
    coll_selectors.py under the name "ompi"."""
    size = comm.size()
    block = max(payload_size(b, None) for b in sendobjs) if sendobjs else 0
    if size >= 12 and block <= 200:
        return alltoall_bruck(comm, sendobjs)
    if block <= 3000:
        return alltoall_basic_linear(comm, sendobjs)
    return alltoall_pairwise(comm, sendobjs)


# ---------------------------------------------------------------------------
# reduce_scatter / scan
# ---------------------------------------------------------------------------

@register("reduce_scatter", "default")
def reduce_scatter_default(comm, sendobjs, op: Op):
    """reduce to 0 then scatter (smpi_default_selector.cpp)."""
    reduced = dispatch("reduce")(comm, sendobjs, _ListwiseOp(op), 0)
    return dispatch("scatter")(comm, reduced, 0)


class _ListwiseOp(Op):
    """Lift an element op to per-slot application over rank-indexed
    lists (for reduce_scatter's reduce phase)."""

    def __init__(self, op: Op):
        super().__init__(None, f"listwise({op.name})", op.commutative)
        self._op = op

    def __call__(self, a, b):
        return [self._op(x, y) for x, y in zip(a, b)]


@register("scan", "default")
@register("scan", "linear")
def scan_linear(comm, sendobj, op: Op):
    """Chained prefix reduction: recv partial from rank-1, combine,
    forward to rank+1."""
    rank, size = comm.rank(), comm.size()
    result = sendobj
    if rank > 0:
        partial = comm.recv(rank - 1, TAG_SCAN)
        result = op(partial, result)
    if rank < size - 1:
        comm.send(result, rank + 1, TAG_SCAN)
    return result


@register("exscan", "default")
@register("exscan", "linear")
def exscan_linear(comm, sendobj, op: Op):
    """Exclusive prefix reduction (MPI_Exscan): rank r receives the
    reduction of ranks 0..r-1, forwards 0..r to r+1; rank 0's result is
    undefined (returned as None)."""
    rank, size = comm.rank(), comm.size()
    below = None
    if rank > 0:
        below = comm.recv(rank - 1, TAG_SCAN)
    if rank < size - 1:
        inclusive = sendobj if below is None else op(below, sendobj)
        comm.send(inclusive, rank + 1, TAG_SCAN)
    return below


# Extra algorithms + the mpich/ompi selector decision trees register
# themselves into _ALGOS on import (kept in separate modules to keep
# this one at the reference's default-selector scope).
from . import coll_extra  # noqa: E402,F401  (registration side effects)
from . import coll_selectors  # noqa: E402,F401
from . import coll_selectors_extra  # noqa: E402,F401
