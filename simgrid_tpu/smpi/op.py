"""MPI reduction operations (reference src/smpi/mpi/smpi_op.cpp) as
numpy element-wise functions."""

from __future__ import annotations

import numpy as np


class Op:
    def __init__(self, fn, name: str = "", commutative: bool = True):
        self.fn = fn
        self.name = name
        self.commutative = commutative

    def __call__(self, a, b):
        """Combine two buffers: returns op(a, b) element-wise, numpy-aware."""
        return self.fn(a, b)

    def is_commutative(self) -> bool:
        return self.commutative

    def __repr__(self):
        return f"<Op {self.name}>"


def _pairwise(np_fn, py_fn):
    def fn(a, b):
        if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
            return np_fn(a, b)
        if isinstance(a, (list, tuple)):
            return type(a)(py_fn(x, y) for x, y in zip(a, b))
        return py_fn(a, b)
    return fn


MPI_SUM = Op(_pairwise(np.add, lambda x, y: x + y), "MPI_SUM")
MPI_PROD = Op(_pairwise(np.multiply, lambda x, y: x * y), "MPI_PROD")
MPI_MAX = Op(_pairwise(np.maximum, max), "MPI_MAX")
MPI_MIN = Op(_pairwise(np.minimum, min), "MPI_MIN")
MPI_LAND = Op(_pairwise(np.logical_and, lambda x, y: bool(x) and bool(y)),
              "MPI_LAND")
MPI_LOR = Op(_pairwise(np.logical_or, lambda x, y: bool(x) or bool(y)),
             "MPI_LOR")
MPI_LXOR = Op(_pairwise(np.logical_xor,
                        lambda x, y: bool(x) != bool(y)), "MPI_LXOR")
MPI_BAND = Op(_pairwise(np.bitwise_and, lambda x, y: x & y), "MPI_BAND")
MPI_BOR = Op(_pairwise(np.bitwise_or, lambda x, y: x | y), "MPI_BOR")
MPI_BXOR = Op(_pairwise(np.bitwise_xor, lambda x, y: x ^ y), "MPI_BXOR")


def _maxloc(a, b):
    # operands are (value, index) pairs or arrays of them
    if isinstance(a, np.ndarray):
        take_b = (b[..., 0] > a[..., 0]) | ((b[..., 0] == a[..., 0])
                                            & (b[..., 1] < a[..., 1]))
        return np.where(take_b[..., None], b, a)
    return b if (b[0] > a[0] or (b[0] == a[0] and b[1] < a[1])) else a


def _minloc(a, b):
    if isinstance(a, np.ndarray):
        take_b = (b[..., 0] < a[..., 0]) | ((b[..., 0] == a[..., 0])
                                            & (b[..., 1] < a[..., 1]))
        return np.where(take_b[..., None], b, a)
    return b if (b[0] < a[0] or (b[0] == a[0] and b[1] < a[1])) else a


MPI_MAXLOC = Op(_maxloc, "MPI_MAXLOC")
MPI_MINLOC = Op(_minloc, "MPI_MINLOC")
