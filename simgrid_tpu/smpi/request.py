"""The SMPI point-to-point engine (reference src/smpi/mpi/smpi_request.cpp).

Keeps the reference's simulation semantics:

* two mailboxes per destination process — eager messages (size below
  smpi/async-small-thresh) go to the small one, rendezvous messages to
  the large one, with the posted-peer probing dance of Request::start()
  (smpi_request.cpp:336-502);
* sends below smpi/send-is-detached-thresh are detached (the sender does
  not wait for the receiver; the payload is copied at send time);
* injected overhead times: os/ois before (i)sends, or at receive
  completion of a detached message (smpi_request.cpp:433-444, 853-861);
* two-way match functions on (comm, src, tag) with MPI_ANY_SOURCE /
  MPI_ANY_TAG wildcards (match_recv/match_send, smpi_request.cpp:60-88).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ..kernel import activity as kact
from ..utils.config import config
from .datatype import Datatype, payload_size

MPI_ANY_SOURCE = -555
MPI_ANY_TAG = -444
MPI_REQUEST_NULL = None


class Status:
    __slots__ = ("source", "tag", "count", "cancelled")

    def __init__(self):
        self.source = MPI_ANY_SOURCE
        self.tag = MPI_ANY_TAG
        self.count = 0
        self.cancelled = False

    def __repr__(self):
        return f"<Status src={self.source} tag={self.tag} count={self.count}>"


def _match_common(ref: "Request", req: "Request") -> bool:
    if ref.comm_id != req.comm_id:
        return False
    if ref.src != MPI_ANY_SOURCE and ref.src != req.src:
        return False
    if ref.tag == MPI_ANY_TAG:
        # the wildcard only matches USER tags: internal collective/NBC
        # traffic rides negative tags and must never be stolen by a
        # posted MPI_ANY_TAG receive (smpi_request.cpp match_common's
        # `tag >= 0` guard)
        return req.tag >= 0
    return ref.tag == req.tag


def match_recv(ref: "Request", req: "Request", _comm) -> bool:
    """Called with ref = the receive request, req = the send request."""
    if req is None or ref is None or ref.kind != "recv":
        return True  # non-smpi peer: accept (reference asserts instead)
    ok = _match_common(ref, req)
    if ok:
        ref.real_src = req.src
        ref.real_tag = req.tag
        ref.real_size = req.size
        ref.detached_sender = req if req.detached else None
    return ok


def match_send(ref: "Request", req: "Request", _comm) -> bool:
    """Called with ref = the send request, req = the receive request."""
    if req is None or ref is None or req.kind != "recv":
        return True
    ok = _match_common(req, ref)
    if ok:
        req.real_src = ref.src
        req.real_tag = ref.tag
        req.real_size = ref.size
        req.detached_sender = ref if ref.detached else None
    return ok


class Request:
    """One pending point-to-point operation."""

    def __init__(self, kind: str, buf, count: int,
                 datatype: Optional[Datatype], peer: int, tag: int, comm,
                 detached: bool = False, is_isend: bool = False,
                 ssend: bool = False):
        from . import runtime
        self.kind = kind                   # "send" | "recv"
        self.buf = buf
        self.count = count
        self.datatype = datatype
        self.comm = comm
        self.comm_id = comm.id
        self.tag = tag
        self.detached = detached
        self.is_isend = is_isend
        self.ssend = ssend
        me = runtime.this_rank_state()
        if kind == "send":
            self.src = comm.rank()
            self.dst = peer
            self.size = (count * datatype.size() if datatype is not None
                         else payload_size(buf, None))
        else:
            self.src = peer                # may be MPI_ANY_SOURCE
            self.dst = comm.rank()
            self.size = (count * datatype.size() if datatype is not None
                         else float("inf"))
        self.real_src = self.src
        self.real_tag = tag
        self.real_size = self.size
        self.detached_sender: Optional["Request"] = None
        self._arrow_done = False        # pt2pt EndLink emitted (tracing)
        self.pimpl: Optional[kact.CommImpl] = None
        self._dst_slot: Optional[list] = None
        self._me = me
        self.finished = False
        self.cancelled = False

    # ------------------------------------------------------------------
    def start(self) -> "Request":
        from . import runtime
        from ..s4u import this_actor
        me = self._me
        issuer = me.actor_impl
        thresh = config["smpi/async-small-thresh"]

        if self.kind == "recv":
            peer_state = runtime.state_of_world_rank(
                self.comm.recv_world_rank_of(self.dst))
            if thresh == 0:
                mbox = peer_state.mailbox
            elif self.size < thresh:
                # eager expected: look in the small mailbox first, then the
                # large one (SSEND goes there), fall back to small
                mbox = peer_state.mailbox_small
                if mbox.iprobe(False, match_recv, self) is None:
                    big = peer_state.mailbox
                    if big.iprobe(False, match_recv, self) is not None:
                        mbox = big
            else:
                mbox = peer_state.mailbox_small
                if mbox.iprobe(False, match_recv, self) is None:
                    mbox = peer_state.mailbox

            self._dst_slot = [None]

            def handler(sc):
                sc.result = kact.comm_irecv(
                    sc.issuer.engine, sc.issuer, mbox, self._dst_slot,
                    match_recv, None, self, -1.0)
                sc.issuer.simcall_answer()
            self.pimpl = issuer.simcall("comm_irecv", handler,
                                        mc_object=mbox)
            return self

        # send side
        payload = self.buf
        if (not self.ssend
                and self.size < config["smpi/send-is-detached-thresh"]):
            self.detached = True
            if isinstance(payload, np.ndarray):
                payload = payload.copy()

        sleeptime = 0.0
        if self.detached or self.is_isend or self.ssend:
            sleeptime = (self._me.host_factors.oisend(self.size)
                         if self.is_isend
                         else self._me.host_factors.osend(self.size))
        if sleeptime > 0.0:
            this_actor.sleep_for(sleeptime)

        peer_state = runtime.state_of_world_rank(
            self.comm.world_rank_of(self.dst))
        if thresh == 0:
            mbox = peer_state.mailbox
        elif self.size < thresh:      # eager mode
            mbox = peer_state.mailbox
            if mbox.iprobe(True, match_send, self) is None:
                mbox = peer_state.mailbox_small
                # SSEND must rendezvous: if no recv is posted on the small
                # mailbox either, park the send in the large one
                if self.ssend and mbox.iprobe(True, match_send, self) is None:
                    mbox = peer_state.mailbox
        else:
            mbox = peer_state.mailbox

        def handler(sc):
            sc.result = kact.comm_isend(
                sc.issuer.engine, sc.issuer, mbox, self.size, -1.0,
                [payload], match_send, None, None, self, self.detached)
            sc.issuer.simcall_answer()
        self.pimpl = issuer.simcall("comm_isend", handler,
                                    mc_object=mbox)
        return self

    # ------------------------------------------------------------------
    def _finish(self, status: Optional[Status]) -> None:
        from ..s4u import this_actor
        if self.kind == "recv":
            data = self._dst_slot[0] if self._dst_slot else None
            if isinstance(self.buf, np.ndarray) and isinstance(data, np.ndarray):
                if self.buf.dtype == data.dtype:
                    flat = data.reshape(-1)[:self.buf.size]
                    np.copyto(self.buf.reshape(-1)[:flat.size], flat)
                else:
                    # MPI moves BYTES: mismatched container dtypes
                    # (sender basic vs receiver derived-as-uint8) must
                    # not value-cast
                    src = np.ascontiguousarray(data).reshape(-1)
                    src = src.view(np.uint8)
                    dst = self.buf.reshape(-1).view(np.uint8)
                    n = min(dst.size, src.size)
                    np.copyto(dst[:n], src[:n])
            elif self.buf is None:
                self.buf = data
            if status is not None:
                status.source = self.real_src
                status.tag = self.real_tag
                status.count = self.real_size
            # pseudo-timing for the buffering of a detached (eager) message
            if self.detached_sender is not None:
                sleeptime = self._me.host_factors.orecv(self.real_size)
                if sleeptime > 0.0:
                    this_actor.sleep_for(sleeptime)
        self.finished = True

    def wait(self, status: Optional[Status] = None):
        from . import instr_hooks as tr
        with tr.wait_span(self) as visible:
            result = self._wait_inner(status)
            if visible and self.kind == "recv":
                tr.recv_arrow_once(self)
            return result

    def _wait_inner(self, status: Optional[Status] = None):
        if self.cancelled:
            if status is not None:
                status.cancelled = True
            return None
        if self.finished:
            # a prior test/get_status already completed the op; replay
            # the reception status (MPI_Request_get_status then
            # MPI_Wait must both see source/tag/count — pt2pt/rqstatus)
            if status is not None and self.kind == "recv":
                status.source = self.real_src
                status.tag = self.real_tag
                status.count = self.real_size
            return self._result()
        if self.kind == "send" and self.detached:
            self._finish(status)
            return self._result()
        issuer = self._me.actor_impl
        comm_impl = self.pimpl

        def handler(sc):
            kact.comm_wait(sc, comm_impl, -1.0)
        issuer.simcall("comm_wait", handler)
        self._finish(status)
        return self._result()

    def test(self, status: Optional[Status] = None) -> bool:
        from . import instr_hooks as tr
        with tr.noop_span("test") as visible:
            return self._test_inner(status, visible, tr)

    def _test_inner(self, status, visible=False, tr=None) -> bool:
        if self.cancelled:
            if status is not None:
                status.cancelled = True
            return True
        if self.finished:
            if status is not None and self.kind == "recv":
                status.source = self.real_src
                status.tag = self.real_tag
                status.count = self.real_size
            return True
        if self.kind == "send" and self.detached:
            self._finish(status)
            return True
        issuer = self._me.actor_impl
        comm_impl = self.pimpl
        res = issuer.simcall("comm_test",
                             lambda sc: kact.comm_test(sc, comm_impl))
        if res:
            self._finish(status)
            if visible and self.kind == "recv":
                tr.recv_arrow_once(self)
        else:
            # an unsuccessful test advances the clock a little, or a
            # busy test loop would freeze simulated time forever
            # (smpi_request.cpp::test nsleeps injection, smpi/test)
            sleep = config["smpi/test"]
            if sleep > 0:
                from ..s4u import this_actor
                this_actor.sleep_for(sleep)
        return bool(res)

    def cancel(self) -> None:
        """MPI_Cancel: succeeds only while the operation is unmatched —
        the kernel comm still WAITING in its mailbox (MPI-3.0 §3.8.4);
        a matched operation completes normally and Test_cancelled
        reports False."""
        if self.finished or self.cancelled or self.pimpl is None:
            return
        issuer = self._me.actor_impl
        comm_impl = self.pimpl

        def handler(sc):
            if comm_impl.state == kact.State.WAITING:
                comm_impl.cancel()
            sc.issuer.simcall_answer()
        issuer.simcall("comm_cancel", handler)
        if comm_impl.state == kact.State.CANCELED:
            self.cancelled = True
            self.finished = True

    def _result(self):
        return self.buf if self.kind == "recv" else None

    # ------------------------------------------------------------------
    @staticmethod
    def waitall(requests: List["Request"],
                statuses: Optional[List[Status]] = None) -> None:
        for i, req in enumerate(requests):
            if req is None:
                continue
            req.wait(statuses[i] if statuses else None)

    @staticmethod
    def waitany(requests: List["Request"],
                status: Optional[Status] = None) -> int:
        from . import instr_hooks as tr
        # The TI/replay grammar has no waitany action (the reference's
        # own StateEvent::print lists it as unimplemented); Paje gets
        # the state push only.
        with tr.noop_span("waitAny", ti_line=False) as visible:
            return Request._waitany_inner(requests, status, visible, tr)

    @staticmethod
    def _waitany_inner(requests, status, visible=False, tr=None) -> int:
        pending = [(i, r) for i, r in enumerate(requests)
                   if r is not None and not r.finished]
        if not pending:
            return -1
        for i, r in pending:            # completed detached sends first
            if r.kind == "send" and r.detached:
                r._finish(status)
                return i
        issuer = pending[0][1]._me.actor_impl
        impls = [r.pimpl for _, r in pending]

        def handler(sc):
            kact.comm_waitany(sc, impls, -1.0)
        idx = issuer.simcall("comm_waitany", handler)
        if idx is None or idx < 0:
            return -1
        i, req = pending[idx]
        req._finish(status)
        if visible and req.kind == "recv":
            tr.recv_arrow_once(req)
        return i

    @staticmethod
    def testall(requests: List["Request"]) -> bool:
        return all(r is None or r.test() for r in requests)
