"""SMPI runtime: per-rank state, the smpirun launcher, bench hooks.

Reference equivalents: smpi_global.cpp (smpi_main, process setup),
smpi_actor.cpp (per-rank mailboxes), smpi_host.cpp (os/or/ois injected
overhead tables), smpi_bench.cpp (smpi_execute / cpu-threshold).
Per-rank global-variable privatization (smpi_global.cpp:540-608) is
unnecessary: each rank is an actor with its own Python frame.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.config import config, declare_flag

declare_flag("smpi/async-small-thresh",
             "Maximal size of messages that are to be sent asynchronously, "
             "without waiting for the receiver", 0)
declare_flag("smpi/send-is-detached-thresh",
             "Threshold of message size where MPI_Send stops behaving like "
             "MPI_Isend and becomes MPI_Ssend", 65536)
declare_flag("smpi/host-speed",
             "Speed of the host running the simulation (in flop/s)",
             20000.0)
declare_flag("smpi/cpu-threshold",
             "Minimal computation time (in seconds) not discarded, "
             "or -1 for infinity", 1e-6)
declare_flag("smpi/os",
             "Small messages timings (MPI_Send minimum time for small "
             "messages)", "0:0:0:0:0")
declare_flag("smpi/ois",
             "Small messages timings (MPI_Isend minimum time for small "
             "messages)", "0:0:0:0:0")
declare_flag("smpi/or",
             "Small messages timings (MPI_Recv minimum time for small "
             "messages)", "0:0:0:0:0")
declare_flag("smpi/coll-selector", "Which collective selector to use",
             "default")
declare_flag("smpi/test",
             "Minimum time to inject inside an unsuccessful MPI_Test "
             "(simulated seconds; lets busy test loops advance the "
             "clock, smpi_request.cpp::test nsleeps)", 1e-4)
declare_flag("smpi/iprobe",
             "Minimum time to inject inside an unsuccessful MPI_Iprobe",
             1e-4)
for _op in ("bcast", "barrier", "reduce", "allreduce", "alltoall",
            "allgather", "allgatherv", "gather", "scatter",
            "reduce_scatter", "scan", "exscan"):
    declare_flag(f"smpi/{_op}",
                 f"Which collective algorithm to use for {_op}", "default")


def parse_factor(spec: str) -> List[Tuple[float, List[float]]]:
    """Parse 'size:v0:v1[:..];size2:...' into sorted (threshold, values)
    (reference smpi_utils.cpp parse_factor)."""
    out = []
    for part in spec.split(";"):
        if not part:
            continue
        nums = [float(x) for x in part.split(":")]
        out.append((nums[0], nums[1:] + [0.0, 0.0]))
    out.sort(key=lambda t: t[0])
    return out


def _piecewise(table, size: float) -> float:
    """Reference smpi_host.cpp os/or/ois evaluation: the last section
    whose threshold is < size wins; values are (offset, per-byte)."""
    if not table:
        return 0.0
    current = table[0][1][0] + table[0][1][1] * size
    for factor, values in table:
        if size <= factor:
            return current
        current = values[0] + values[1] * size
    return current


class HostFactors:
    """Per-host injected overhead tables; host properties smpi/os,
    smpi/or, smpi/ois override the global config (smpi_host.cpp:90-120)."""

    def __init__(self, host):
        def table(key):
            prop = None
            if host is not None:
                prop = host.properties.get(key) \
                    if hasattr(host, "properties") else None
            return parse_factor(prop if prop else config[key])
        self._os = table("smpi/os")
        self._or = table("smpi/or")
        self._ois = table("smpi/ois")

    def osend(self, size: float) -> float:
        return _piecewise(self._os, size)

    def orecv(self, size: float) -> float:
        return _piecewise(self._or, size)

    def oisend(self, size: float) -> float:
        return _piecewise(self._ois, size)


class _RankState:
    __slots__ = ("world_rank", "actor_impl", "host", "mailbox",
                 "mailbox_small", "host_factors", "instance", "world")

    def __init__(self, world_rank, actor_impl, host, mailbox, mailbox_small,
                 host_factors, instance="main", world=None):
        self.world_rank = world_rank
        self.actor_impl = actor_impl
        self.host = host
        self.mailbox = mailbox
        self.mailbox_small = mailbox_small
        self.host_factors = host_factors
        self.instance = instance    # multi-instance/AMPI job name
        self.world = world          # this instance's MPI_COMM_WORLD


_registry: Dict[int, _RankState] = {}
_by_world_rank: Dict[tuple, _RankState] = {}
_world = None


def this_rank_state() -> _RankState:
    from ..s4u.actor import _current_impl
    state = _registry.get(id(_current_impl()))
    assert state is not None, "not inside an SMPI rank actor"
    return state


def this_rank() -> int:
    return this_rank_state().world_rank


def state_of_world_rank(rank: int) -> _RankState:
    """Resolve within the calling actor's instance (each MPI job of a
    multi-instance simulation has its own rank space,
    smpi_deployment.cpp)."""
    instance = this_rank_state().instance
    return _by_world_rank[(instance, rank)]


def world():
    """The calling rank's MPI_COMM_WORLD (instance-local); outside a
    rank actor, the last deployment's world (post-run inspection)."""
    from ..s4u.actor import _current_impl
    state = _registry.get(id(_current_impl()))
    if state is not None and state.world is not None:
        return state.world
    assert _world is not None, "SMPI world not initialized (use smpirun)"
    return _world


class _CommWorldProxy:
    """Module-level COMM_WORLD handle valid inside any rank actor."""

    def __getattr__(self, name):
        return getattr(world(), name)

    def __repr__(self):
        return "<COMM_WORLD proxy>"


COMM_WORLD = _CommWorldProxy()


def smpi_execute_flops(flops: float) -> None:
    from ..s4u import this_actor
    from . import instr_hooks as tr
    with tr.cpu_span("compute", flops):
        this_actor.execute(flops)


def smpi_execute(duration: float) -> None:
    """Inject `duration` seconds of (benched) host compute as simulated
    flops at smpi/host-speed, skipping below smpi/cpu-threshold
    (smpi_bench.cpp:53-78)."""
    threshold = config["smpi/cpu-threshold"]
    if duration >= threshold or threshold < 0:
        smpi_execute_flops(duration * config["smpi/host-speed"])


def wtime() -> float:
    from ..s4u import Engine
    return Engine.get_clock()


# ---------------------------------------------------------------------------
# SMPI_SAMPLE loop extrapolation (smpi_bench.cpp:150-280)
# ---------------------------------------------------------------------------

class _SampleState:
    __slots__ = ("count", "sum")

    def __init__(self):
        self.count = 0
        self.sum = 0.0

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


_samples: Dict = {}


def sample(key, iters: int, flops_per_iter=None, threshold: int = 3,
           shared: bool = False):
    """SMPI_SAMPLE_LOCAL/GLOBAL analog: a generator driving a benched
    loop. The first `threshold` iterations run (and cost) their real
    simulated work; afterwards each remaining iteration is *skipped*
    and charged `flops_per_iter` as compute when given, or the measured
    mean simulated duration otherwise — the loop still "executes"
    `iters` times observably but only samples pay the full path
    (smpi_bench.cpp sample_enough_benchs).

    Usage:
        for running in smpi.sample("kernel", 100):
            if running:
                this_actor.execute(1e7)    # the real benched body
    With shared=True the sample state is shared by all ranks (GLOBAL
    flavor: one rank's measurements serve everyone)."""
    from ..s4u import Engine, this_actor
    state_key = key if shared else (key, this_rank())
    state = _samples.get(state_key)
    if state is None:
        state = _samples[state_key] = _SampleState()
    charged_rest = False
    for it in range(iters):
        if state.count < threshold:
            t0 = Engine.get_clock()
            yield True                      # caller runs the real body
            state.count += 1
            state.sum += Engine.get_clock() - t0
        else:
            # Charge ALL remaining iterations in one kernel event (the
            # point of SMPI_SAMPLE: O(1) events for the skipped tail).
            if not charged_rest:
                remaining = iters - it
                if flops_per_iter is not None:
                    this_actor.execute(flops_per_iter * remaining)
                elif state.mean() > 0:
                    this_actor.sleep_for(state.mean() * remaining)
                charged_rest = True
            yield False


# ---------------------------------------------------------------------------
# SMPI_SHARED_MALLOC analog (smpi_shared.cpp)
# ---------------------------------------------------------------------------

_shared_blocks: Dict = {}


def shared_malloc(key, shape, dtype=None):
    """One physical buffer per call-site key, shared by every rank —
    the memory-footprint trick of SMPI_SHARED_MALLOC (smpi_shared.cpp:
    6-60: all ranks' "allocations" alias the same backing block, fine
    because replayed kernels don't care about the data)."""
    import numpy as np
    block = _shared_blocks.get(key)
    if block is None:
        block = np.zeros(shape, dtype or np.float64)
        _shared_blocks[key] = block
    return block


def shared_free(key) -> None:
    _shared_blocks.pop(key, None)


def clear_process_data() -> None:
    """Reset cross-run module state (new smpirun)."""
    _samples.clear()
    _shared_blocks.clear()
    from . import file as smpi_file
    smpi_file._shared.clear()


def smpi_instance_register(engine, fn, hosts: Sequence,
                           np: Optional[int] = None, args: tuple = (),
                           instance: str = "main") -> None:
    """Deploy one MPI job (SMPI_app_instance_register +
    smpi_deployment.cpp): its own COMM_WORLD, rank space and mailbox
    namespace, so several MPI applications share one simulation."""
    from ..s4u import Actor, Mailbox
    from .comm import Comm
    from .group import Group

    assert hosts, "platform has no hosts"
    n = np if np is not None else len(hosts)
    world = Comm(Group(list(range(n))), id=("world", instance))

    def rank_main():
        from .. import instr
        state = this_rank_state()
        instr.smpi_init(state.world_rank, state.host,
                        instance=state.instance)
        try:
            fn(*args)
        finally:
            instr.smpi_finalize(state.world_rank,
                                instance=state.instance)

    # Register every rank's state before any actor runs: rank 0's first
    # send must be able to resolve rank N's mailboxes.
    prefix = "" if instance == "main" else f"{instance}-"
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        actor = Actor.create(f"{prefix}rank-{rank}", host, rank_main)
        state = _RankState(
            rank, actor.pimpl, host,
            Mailbox.by_name(f"SMPI-{prefix}{rank}").pimpl,
            Mailbox.by_name(f"SMPI-SMALL-{prefix}{rank}").pimpl,
            HostFactors(host), instance=instance, world=world)
        _registry[id(actor.pimpl)] = state
        _by_world_rank[(instance, rank)] = state


def smpi_main(fn, engine, hosts: Optional[Sequence] = None,
              np: Optional[int] = None, args: tuple = ()) -> None:
    """Register one actor per rank on an existing engine (reference
    smpi_global.cpp:612-650 deployment phase)."""
    global _world
    all_hosts = hosts if hosts is not None else engine.get_all_hosts()
    _registry.clear()
    _by_world_rank.clear()
    clear_process_data()
    smpi_instance_register(engine, fn, all_hosts, np=np, args=args)
    _world = _by_world_rank[("main", 0)].world


#: smpirun default fabric (smpirun.in:13-18)
_FABRIC_LOOPBACK_BW = "498000000Bps"
_FABRIC_LOOPBACK_LAT = "0.000004s"
_FABRIC_NETWORK_BW = f"{26 * 1024 * 1024}Bps"
_FABRIC_NETWORK_LAT = "0.000005s"
_FABRIC_SPEED = "100flops"   # yes, 100 flop/s — the reference's own
                             # DEFAULT_SPEED (smpirun.in:18)


def fabricate_platform(n_hosts: int, path: str,
                       names: Optional[Sequence[str]] = None) -> str:
    """Generate the smpirun default fabric (smpirun.in:371-406): per
    host a loopback link and a private uplink; route i->j rides
    link_i + link_j. ``names`` overrides the default host1..hostN
    naming (hostfile-driven fabrication)."""
    if names is None:
        names = [f"host{i}" for i in range(1, n_hosts + 1)]
    assert len(names) == n_hosts
    lines = ["<?xml version='1.0'?>", '<platform version="4.1">',
             '<zone id="AS0" routing="Full">']
    from xml.sax.saxutils import quoteattr
    for i, name in enumerate(names, start=1):
        lines.append(f'  <host id={quoteattr(name)} '
                     f'speed="{_FABRIC_SPEED}"/>')
        lines.append(f'  <link id="loop{i}" '
                     f'bandwidth="{_FABRIC_LOOPBACK_BW}" '
                     f'latency="{_FABRIC_LOOPBACK_LAT}"/>')
        lines.append(f'  <link id="link{i}" '
                     f'bandwidth="{_FABRIC_NETWORK_BW}" '
                     f'latency="{_FABRIC_NETWORK_LAT}"/>')
    for i, src in enumerate(names, start=1):
        for j, dst in enumerate(names, start=1):
            if i == j:
                lines.append(f'  <route src={quoteattr(src)} '
                             f'dst={quoteattr(dst)} symmetrical="NO">'
                             f'<link_ctn id="loop{i}"/></route>')
            else:
                lines.append(f'  <route src={quoteattr(src)} '
                             f'dst={quoteattr(dst)} symmetrical="NO">'
                             f'<link_ctn id="link{i}"/>'
                             f'<link_ctn id="link{j}"/></route>')
    lines += ["</zone>", "</platform>"]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def parse_hostfile(path: str) -> List[str]:
    """Hostnames, honoring 'name:count' multiplicity (smpirun.in
    hostfile unrolling)."""
    out: List[str] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            name, _, count = line.partition(":")
            out.extend([name] * (int(count) if count else 1))
    return out


def smpirun(fn, platform: Optional[str] = None, np: Optional[int] = None,
            hosts: Optional[Sequence[str]] = None,
            hostfile: Optional[str] = None,
            configs: Sequence[str] = (), args: tuple = ()):
    """smpirun equivalent (src/smpi/smpirun.in): build the engine, load
    (or fabricate) the platform, deploy `np` ranks of `fn` round-robin
    over the hosts, run the simulation. Returns the Engine (inspect
    .clock). Without a platform, the default fabric is generated for
    `np` hosts (smpirun.in:371-406); a hostfile selects/duplicates
    hosts like `-hostfile` (including name:count lines)."""
    import os
    import tempfile

    from ..s4u import Engine

    if hostfile is not None:
        assert hosts is None, "pass either hosts or hostfile"
        hosts = parse_hostfile(hostfile)
        if np is None:
            np = len(hosts)
    tmp_platform = None
    if platform is None:
        if hosts:
            # Fabricate a host per distinct hostfile name (rank
            # multiplicity maps several ranks per host).
            names = list(dict.fromkeys(hosts))
        else:
            n = np if np is not None else 4
            names = [f"host{i}" for i in range(1, n + 1)]
            hosts = list(names)
        fd, tmp_platform = tempfile.mkstemp(suffix=".xml",
                                            prefix="smpitmp-plat")
        os.close(fd)
        platform = fabricate_platform(len(names), tmp_platform, names)

    try:
        e = Engine(["smpirun"] + [f"--cfg={c}" for c in configs])
        e.load_platform(platform)
        host_objs = ([e.host_by_name(h) for h in hosts] if hosts
                     else e.get_all_hosts())
        smpi_main(fn, e, hosts=host_objs, np=np, args=args)
        e.run()
        return e
    finally:
        if tmp_platform is not None:
            os.unlink(tmp_platform)   # the reference removes its temps too


def smpirun_multi(instances, platform: str, configs: Sequence[str] = ()):
    """Run several MPI jobs in one simulation (the reference's
    multi-instance mode, examples/smpi/replay_multiple):
    ``instances`` is a list of (name, fn, np[, hosts]) tuples, each
    getting its own COMM_WORLD and rank namespace."""
    from ..s4u import Engine

    global _world
    e = Engine(["smpirun"] + [f"--cfg={c}" for c in configs])
    e.load_platform(platform)
    _registry.clear()
    _by_world_rank.clear()
    clear_process_data()
    _world = None    # multi-instance: worlds are per-instance only
    all_hosts = e.get_all_hosts()
    offset = 0
    for spec in instances:
        name, fn, n = spec[0], spec[1], spec[2]
        hosts = ([e.host_by_name(h) for h in spec[3]] if len(spec) > 3
                 else [all_hosts[(offset + i) % len(all_hosts)]
                       for i in range(n)])
        smpi_instance_register(e, fn, hosts, np=n, instance=name)
        offset += n
    e.run()
    return e
