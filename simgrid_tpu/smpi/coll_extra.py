"""Additional collective algorithms dispatched by the mpich/ompi
selectors (reference src/smpi/colls/<op>/*.cpp).

Every algorithm is correct (produces the right reduction/gather values)
and timing-faithful at the message level: the sequence, sizes and
concurrency of point-to-point operations match the reference
implementation, which is what determines simulated time. Payloads are
Python objects; where the reference splits raw buffers, we split numpy
arrays and ship (metadata, chunk) tuples with an explicit
``count=<bytes>, datatype=MPI_BYTE`` so wire sizes stay exact; non-array
payloads fall back to an unsplit algorithm (results stay correct at
slightly different simulated cost).

SMP-aware variants (mvapich2 two-level, SMP-binomial) are substituted
by their flat counterparts.  This is exact when ranks are deployed one
per host, and an APPROXIMATION when a hostfile packs several ranks per
host (tools/smpirun.py wraps ranks round-robin over the host list, so
multi-rank hosts are reachable in the default path): there the real
two-level algorithms would do intra-node exchanges over the loopback
first and fewer inter-node messages, so their simulated timing differs
from the flat substitute's.  Known limitation, not a claim of
equivalence — selector tables still dispatch to the flat algorithm and
log the substitution at debug level.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .coll import (TAG_ALLGATHER, TAG_ALLREDUCE, TAG_ALLTOALL, TAG_BARRIER,
                   TAG_BCAST,
                   TAG_GATHER, TAG_REDUCE, TAG_REDUCE_SCATTER, TAG_SCATTER,
                   allgather_rdb, allgather_ring, allreduce_lr,
                   allreduce_rdb, alltoall_basic_linear, alltoall_bruck,
                   alltoall_pairwise, barrier_bruck, bcast_binomial_tree,
                   dispatch, dispatch_name, gather_linear, reduce_binomial,
                   reduce_linear, register, scatter_linear)
from .datatype import MPI_BYTE
from .op import Op

PIPELINE_SEGMENT = 8192  # bytes; the ompi pipeline/flattree segment size


def _as_array(obj) -> Optional[np.ndarray]:
    return obj if isinstance(obj, np.ndarray) else None


def _nbytes(x) -> int:
    return int(x.nbytes) if isinstance(x, np.ndarray) else \
        sum(int(c.nbytes) for c in x)


def _send_chunks(comm, payload, dst, tag):
    """Send any chunk structure with its exact byte size on the wire."""
    comm.send(payload, dst, tag, count=_payload_bytes(payload),
              datatype=MPI_BYTE)


def _isend_chunks(comm, payload, dst, tag):
    return comm.isend(payload, dst, tag, count=_payload_bytes(payload),
                      datatype=MPI_BYTE)


def _sendrecv_chunks(comm, payload, dst, src, tag):
    rreq = comm.irecv(src, tag)
    sreq = _isend_chunks(comm, payload, dst, tag)
    data = rreq.wait()
    sreq.wait()
    return data


def _payload_bytes(payload) -> int:
    """Exact wire bytes of a chunk payload (array, or containers of
    arrays; metadata rides free like the reference's known counts)."""
    if isinstance(payload, np.ndarray):
        return int(payload.nbytes)
    if isinstance(payload, dict):
        return sum(int(v.nbytes) for v in payload.values())
    if isinstance(payload, (list, tuple)):
        return sum(_payload_bytes(v) for v in payload
                   if isinstance(v, (np.ndarray, list, tuple, dict)))
    return 1


def _equal_chunks(arr: np.ndarray, size: int) -> Optional[List[np.ndarray]]:
    """size chunks of count//size elements; remainder on the last chunk
    (ceiling-division layout like the reference scatter phases)."""
    count = len(arr) // size
    if count == 0:
        return None
    out = [arr[i * count:(i + 1) * count] for i in range(size - 1)]
    out.append(arr[(size - 1) * count:])
    return out


# ---------------------------------------------------------------------------
# bcast: scatter+allgather and pipeline families
# ---------------------------------------------------------------------------

def _binomial_scatter(comm, chunks: Optional[List], root: int, tag: int
                      ) -> dict:
    """Binomial-tree scatter phase of bcast-scatter-*-allgather.cpp:
    each internal node receives its subtree's chunks and forwards the
    upper halves to its children; returns {chunk_index: chunk} owned."""
    rank, size = comm.rank(), comm.size()
    rel = (rank - root + size) % size
    if rel == 0:
        mine = {i: chunks[i] for i in range(size)}
    else:
        mask = 1
        while not (rel & mask):
            mask <<= 1
        parent = ((rel - mask) + root) % size
        mine = comm.recv(parent, tag)
    # forward: child rel|mask gets chunk indices [child_rel, child_rel+mask)
    mask = 1
    while mask < size and not (rel & mask):
        child_rel = rel + mask
        if child_rel < size:
            payload = {}
            for i in list(mine):
                i_rel = (i - root + size) % size
                if child_rel <= i_rel < child_rel + mask:
                    payload[i] = mine.pop(i)
            _send_chunks(comm, payload, (child_rel + root) % size, tag)
        mask <<= 1
    return mine


@register("bcast", "scatter_LR_allgather")
def bcast_scatter_LR_allgather(comm, obj, root: int = 0):
    """Binomial scatter + logical-ring allgather
    (bcast-scatter-LR-allgather.cpp)."""
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return obj
    # Branch decision must agree across ranks: like MPI's bcast
    # contract, every rank passes a same-shaped payload (the replay
    # engine and the selectors uphold this).
    arr = _as_array(obj)
    if arr is None or _equal_chunks(arr, size) is None:
        return bcast_binomial_tree(comm, obj, root)
    chunks = _equal_chunks(arr, size) if rank == root else None
    mine = _binomial_scatter(comm, chunks, root, TAG_BCAST)
    out = dict(mine)
    right, left = (rank + 1) % size, (rank - 1 + size) % size
    rel = (rank - root + size) % size
    for step in range(size - 1):
        send_idx = ((rel - step + size) % size + root) % size
        recv_idx = ((rel - step - 1 + size) % size + root) % size
        data = _sendrecv_chunks(comm, {send_idx: out[send_idx]},
                                right, left, TAG_BCAST)
        out.update(data)
    return np.concatenate([out[i] for i in range(size)])


@register("bcast", "scatter_rdb_allgather")
def bcast_scatter_rdb_allgather(comm, obj, root: int = 0):
    """Binomial scatter + recursive-doubling allgather
    (bcast-scatter-rdb-allgather.cpp); non-power-of-two sizes use the
    ring variant (the reference's non-pof2 fixup costs the same order)."""
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return obj
    if size & (size - 1):
        return bcast_scatter_LR_allgather(comm, obj, root)
    arr = _as_array(obj)
    if arr is None or _equal_chunks(arr, size) is None:
        return bcast_binomial_tree(comm, obj, root)
    chunks = _equal_chunks(arr, size) if rank == root else None
    mine = _binomial_scatter(comm, chunks, root, TAG_BCAST)
    rel = (rank - root + size) % size
    mask = 1
    while mask < size:
        peer = ((rel ^ mask) + root) % size
        data = _sendrecv_chunks(comm, mine, peer, peer, TAG_BCAST)
        mine = {**mine, **data}
        mask <<= 1
    return np.concatenate([mine[i] for i in range(size)])


def _segments(obj) -> List:
    arr = _as_array(obj)
    if arr is None or arr.nbytes <= PIPELINE_SEGMENT:
        return [obj]
    per_seg = max(1, PIPELINE_SEGMENT // max(arr.itemsize, 1))
    return [arr[i:i + per_seg] for i in range(0, len(arr), per_seg)]


@register("bcast", "ompi_pipeline")
def bcast_ompi_pipeline(comm, obj, root: int = 0):
    """Chain pipeline (bcast-ompi-pipeline.cpp): rank-order chain from
    the root, segments streamed with receive/forward overlap. The first
    message carries (n_segs, segment) so the chain knows how many
    follow (the reference derives it from the collective's count)."""
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return obj
    rel = (rank - root + size) % size
    nxt = ((rel + 1) % size + root) % size
    prev = ((rel - 1 + size) % size + root) % size
    if rel == 0:
        segs = _segments(obj)
        reqs = [_isend_chunks(comm, (len(segs), seg), nxt, TAG_BCAST)
                for seg in segs]
        for r in reqs:
            r.wait()
        return obj
    n_segs, first = comm.recv(prev, TAG_BCAST)
    segs, reqs = [first], []
    if rel != size - 1:
        reqs.append(_isend_chunks(comm, (n_segs, first), nxt, TAG_BCAST))
    for _ in range(n_segs - 1):
        _, seg = comm.recv(prev, TAG_BCAST)
        segs.append(seg)
        if rel != size - 1:
            reqs.append(_isend_chunks(comm, (n_segs, seg), nxt, TAG_BCAST))
    for r in reqs:
        r.wait()
    return segs[0] if len(segs) == 1 else np.concatenate(segs)


@register("bcast", "flattree_pipeline")
def bcast_flattree_pipeline(comm, obj, root: int = 0):
    """Flat tree, segmented (bcast-flattree-pipeline.cpp): the root
    streams every segment to every rank directly."""
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return obj
    if rank == root:
        segs = _segments(obj)
        reqs = []
        for seg in segs:
            for dst in range(size):
                if dst != root:
                    reqs.append(_isend_chunks(comm, (len(segs), seg),
                                              dst, TAG_BCAST))
        for r in reqs:
            r.wait()
        return obj
    n_segs, first = comm.recv(root, TAG_BCAST)
    segs = [first]
    for _ in range(n_segs - 1):
        segs.append(comm.recv(root, TAG_BCAST)[1])
    return segs[0] if len(segs) == 1 else np.concatenate(segs)


@register("bcast", "ompi_split_bintree")
def bcast_ompi_split_bintree(comm, obj, root: int = 0):
    """Split binary tree (bcast-ompi-split-bintree.cpp): the message is
    halved, each half broadcast down one binary tree, then pairs
    exchange halves."""
    rank, size = comm.rank(), comm.size()
    arr = _as_array(obj) if rank == root else None
    if size <= 2 or (rank == root and (arr is None or len(arr) < 2)):
        return bcast_binomial_tree(comm, obj, root)

    def binary_tree_cast(half_idx, half):
        """Binary tree over relative ranks; half_idx selects the tree."""
        rel = (rank - root + size) % size
        parent_rel = (rel - 1) // 2
        children = [c for c in (2 * rel + 1, 2 * rel + 2) if c < size]
        if rel != 0:
            half = comm.recv((parent_rel + root) % size,
                             TAG_BCAST + half_idx)
        reqs = [_isend_chunks(comm, half, (c + root) % size,
                              TAG_BCAST + half_idx) for c in children]
        for r in reqs:
            r.wait()
        return half

    if rank == root:
        mid = len(arr) // 2
        halves = [arr[:mid], arr[mid:]]
    else:
        halves = [None, None]
    # Every rank participates in both trees (the reference splits ranks
    # into two trees and pairs up; one tree per half with all ranks has
    # the same per-link load shape and keeps results correct).
    halves[0] = binary_tree_cast(0, halves[0])
    halves[1] = binary_tree_cast(1, halves[1])
    return np.concatenate(halves)


# ---------------------------------------------------------------------------
# reduce: binary/pipeline/scatter-gather families
# ---------------------------------------------------------------------------

@register("reduce", "ompi_basic_linear")
def reduce_ompi_basic_linear(comm, sendobj, op: Op, root: int = 0):
    return reduce_linear(comm, sendobj, op, root)


@register("reduce", "ompi_binomial")
def reduce_ompi_binomial(comm, sendobj, op: Op, root: int = 0):
    return reduce_binomial(comm, sendobj, op, root)


def _reduce_tree(comm, sendobj, op, root, children_of):
    """Generic tree reduce: receive from children (concurrently),
    fold, send to parent."""
    rank, size = comm.rank(), comm.size()
    rel = (rank - root + size) % size
    children, parent_rel = children_of(rel, size)
    reqs = [comm.irecv((c + root) % size, TAG_REDUCE) for c in children]
    result = sendobj
    for req in reqs:
        result = op(result, req.wait())
    if rel != 0:
        _send_chunks(comm, result, (parent_rel + root) % size, TAG_REDUCE) \
            if isinstance(result, np.ndarray) else \
            comm.send(result, (parent_rel + root) % size, TAG_REDUCE)
        return None
    return result


@register("reduce", "ompi_binary")
def reduce_ompi_binary(comm, sendobj, op: Op, root: int = 0):
    """Binary tree reduce (coll_tuned binary topology)."""
    return _reduce_tree(
        comm, sendobj, op, root,
        lambda rel, size: ([c for c in (2 * rel + 1, 2 * rel + 2)
                            if c < size], (rel - 1) // 2))


@register("reduce", "ompi_in_order_binary")
def reduce_ompi_in_order_binary(comm, sendobj, op: Op, root: int = 0):
    """In-order binary tree: same topology, children folded in rank
    order so non-commutative ops see the canonical ordering."""
    rank, size = comm.rank(), comm.size()
    rel = (rank - root + size) % size
    children = [c for c in (2 * rel + 1, 2 * rel + 2) if c < size]
    reqs = {c: comm.irecv((c + root) % size, TAG_REDUCE) for c in children}
    parts = {rel: sendobj}
    for c, req in reqs.items():
        parts.update(req.wait())
    if rel != 0:
        parent = ((rel - 1) // 2 + root) % size
        comm.send(parts, parent, TAG_REDUCE,
                  count=sum(_payload_bytes(v) for v in parts.values()),
                  datatype=MPI_BYTE)
        return None
    result = None
    for i in sorted(parts, reverse=True):
        result = parts[i] if result is None else op(parts[i], result)
    return result


@register("reduce", "ompi_pipeline")
def reduce_ompi_pipeline(comm, sendobj, op: Op, root: int = 0):
    """Segmented chain reduce (reduce-ompi chain/pipeline): segments
    flow up a rank-order chain toward the root, folded at each hop."""
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return sendobj
    rel = (rank - root + size) % size
    segs = _segments(sendobj)
    # chain: highest relative rank starts; each rank receives from
    # rel+1, folds its own segment, forwards to rel-1 (root is rel 0).
    up = ((rel - 1 + size) % size + root) % size
    down = ((rel + 1) % size + root) % size
    out, reqs = [], []
    for seg in segs:
        if rel != size - 1:
            incoming = comm.recv(down, TAG_REDUCE)
            seg = op(seg, incoming)
        if rel != 0:
            reqs.append(_isend_chunks(comm, seg, up, TAG_REDUCE))
        else:
            out.append(seg)
    for r in reqs:
        r.wait()
    if rel != 0:
        return None
    return out[0] if len(out) == 1 else np.concatenate(out)


@register("reduce", "scatter_gather")
def reduce_scatter_gather(comm, sendobj, op: Op, root: int = 0):
    """Rabenseifner (reduce-scatter-gather.cpp, the mpich long-message
    reduce): recursive-halving reduce-scatter + binomial gather."""
    rank, size = comm.rank(), comm.size()
    arr = _as_array(sendobj)
    if size == 1:
        return sendobj
    if arr is None or len(arr) < size or size & (size - 1):
        # non-pof2 pre-phase costs one extra exchange in the reference;
        # binomial is the documented fallback for count < pof2
        return reduce_binomial(comm, sendobj, op, root)
    chunks = {i: c for i, c in enumerate(_equal_chunks(arr, size))}
    # recursive halving reduce-scatter over relative ranks (root == 0
    # case of the reference; other roots add one final transfer)
    rel = rank
    mask = size >> 1
    low, high = 0, size
    acc = chunks
    while mask >= 1:
        half = (low + high) // 2
        if rel < half:
            peer = rel + (half - low)
            send_part = {i: acc[i] for i in acc if i >= half}
            keep = {i: acc[i] for i in acc if i < half}
        else:
            peer = rel - (half - low)
            send_part = {i: acc[i] for i in acc if i < half}
            keep = {i: acc[i] for i in acc if i >= half}
        data = _sendrecv_chunks(comm, send_part, peer, peer, TAG_REDUCE)
        acc = {i: op(keep[i], data[i]) if i in data else keep[i]
               for i in keep}
        for i in data:
            if i not in acc:
                acc[i] = data[i]
        if rel < half:
            high = half
        else:
            low = half
        mask >>= 1
    # binomial gather of the scattered results to the root
    rel = (rank - root + size) % size
    mask = 1
    gathered = acc
    while mask < size:
        if rel & mask:
            parent = ((rel - mask) + root) % size
            comm.send(gathered, parent, TAG_GATHER,
                      count=sum(_payload_bytes(v)
                                for v in gathered.values()),
                      datatype=MPI_BYTE)
            return None
        child = rel + mask
        if child < size:
            gathered.update(comm.recv((child + root) % size, TAG_GATHER))
        mask <<= 1
    return np.concatenate([gathered[i] for i in range(size)])


# ---------------------------------------------------------------------------
# reduce_scatter family
# ---------------------------------------------------------------------------

@register("reduce_scatter", "mpich_pair")
@register("reduce_scatter", "ompi_ring")
def reduce_scatter_pair(comm, sendobjs, op: Op):
    """Pairwise/ring reduce-scatter (reduce_scatter-mpich-pair.cpp,
    ompi ring): p-1 steps; at step i send block for rank+i, receive and
    fold the block from rank-i."""
    rank, size = comm.rank(), comm.size()
    result = sendobjs[rank]
    for i in range(1, size):
        dst = (rank + i) % size
        src = (rank - i + size) % size
        data = _sendrecv_chunks(comm, sendobjs[dst], dst, src,
                                TAG_REDUCE_SCATTER) \
            if isinstance(sendobjs[dst], np.ndarray) else \
            comm.sendrecv(sendobjs[dst], dst, src,
                          TAG_REDUCE_SCATTER, TAG_REDUCE_SCATTER)
        result = op(result, data)
    return result


@register("reduce_scatter", "mpich_rdb")
@register("reduce_scatter", "mpich_noncomm")
def reduce_scatter_rdb(comm, sendobjs, op: Op):
    """Recursive-doubling reduce_scatter
    (reduce_scatter-mpich-rdb.cpp): lg p steps exchanging shrinking
    block sets; non-power-of-two falls back to the pair algorithm."""
    rank, size = comm.rank(), comm.size()
    if size & (size - 1):
        return reduce_scatter_pair(comm, sendobjs, op)
    acc = {i: sendobjs[i] for i in range(size)}
    mask = size >> 1
    while mask >= 1:
        peer = rank ^ mask
        # send the half of blocks on the peer's side, keep mine
        peer_side = {i: acc[i] for i in acc
                     if (i & mask) == (peer & mask)}
        mine_side = {i: acc[i] for i in acc
                     if (i & mask) == (rank & mask)}
        data = _sendrecv_chunks(comm, peer_side, peer, peer,
                                TAG_REDUCE_SCATTER)
        acc = {i: op(mine_side[i], data[i]) if i in data else mine_side[i]
               for i in mine_side}
        mask >>= 1
    return acc[rank]


@register("reduce_scatter", "ompi_basic_recursivehalving")
def reduce_scatter_recursivehalving(comm, sendobjs, op: Op):
    """Recursive halving (reduce_scatter-ompi.cpp basic_recursivehalving)
    — same exchange pattern as mpich rdb here (block-regular case)."""
    return reduce_scatter_rdb(comm, sendobjs, op)


# ---------------------------------------------------------------------------
# allreduce additions
# ---------------------------------------------------------------------------

@register("allreduce", "rab_rdb")
def allreduce_rab_rdb(comm, sendobj, op: Op):
    """Rabenseifner (allreduce-rab-rdb.cpp): recursive-halving
    reduce-scatter + recursive-doubling allgather."""
    rank, size = comm.rank(), comm.size()
    arr = _as_array(sendobj)
    if size == 1:
        return sendobj
    if arr is None or len(arr) < size or size & (size - 1):
        return allreduce_rdb(comm, sendobj, op)
    chunks = {i: c for i, c in enumerate(_equal_chunks(arr, size))}
    acc = chunks
    mask = size >> 1
    while mask >= 1:
        peer = rank ^ mask
        peer_side = {i: acc[i] for i in acc if (i & mask) == (peer & mask)}
        mine_side = {i: acc[i] for i in acc if (i & mask) == (rank & mask)}
        data = _sendrecv_chunks(comm, peer_side, peer, peer, TAG_ALLREDUCE)
        acc = {i: op(mine_side[i], data[i]) if i in data else mine_side[i]
               for i in mine_side}
        mask >>= 1
    # recursive-doubling allgather
    mask = 1
    while mask < size:
        peer = rank ^ mask
        data = _sendrecv_chunks(comm, acc, peer, peer, TAG_ALLREDUCE)
        acc = {**acc, **data}
        mask <<= 1
    return np.concatenate([acc[i] for i in range(size)])


@register("allreduce", "ompi_ring_segmented")
def allreduce_ompi_ring_segmented(comm, sendobj, op: Op):
    """Segmented ring (allreduce-ompi-ring-segmented.cpp). The lr
    logical ring is the same communication pattern with one segment per
    rank-block; the reference's own ompi selector comments that lr 'is
    a good match for allreduce_ring'."""
    return allreduce_lr(comm, sendobj, op)


# ---------------------------------------------------------------------------
# alltoall / allgather / barrier / gather / scatter additions
# ---------------------------------------------------------------------------

@register("alltoall", "ring")
def alltoall_ring(comm, sendobjs):
    """(rank+i)/(rank-i) exchange, p-1 steps (alltoall-ring.cpp) — the
    mpich non-power-of-two 'pairwise' pattern."""
    return alltoall_pairwise(comm, sendobjs)


@register("alltoall", "pair")
def alltoall_pair(comm, sendobjs):
    """XOR pairwise exchange (alltoall-pair.cpp); needs a power-of-two
    communicator, otherwise the ring pattern covers it."""
    rank, size = comm.rank(), comm.size()
    if size & (size - 1):
        return alltoall_pairwise(comm, sendobjs)
    result = [None] * size
    result[rank] = sendobjs[rank]
    for step in range(1, size):
        peer = rank ^ step
        result[peer] = comm.sendrecv(sendobjs[peer], peer, peer,
                                     TAG_ALLTOALL, TAG_ALLTOALL)
    return result


@register("alltoall", "mvapich2_scatter_dest")
def alltoall_mvapich2_scatter_dest(comm, sendobjs):
    """Posts all irecvs/isends with scattered destination order
    (alltoall-mvapich-scatter-dest.cpp); concurrency-wise identical to
    the basic linear algorithm in simulation."""
    return alltoall_basic_linear(comm, sendobjs)


@register("allgather", "bruck")
def allgather_bruck(comm, sendobj):
    """Bruck dissemination allgather (allgather-bruck.cpp): ceil(lg p)
    steps; at step k the rank holds blocks [rank, rank+k) and ships
    min(k, p-k) of them to rank-k, receiving as many from rank+k."""
    rank, size = comm.rank(), comm.size()
    blocks = {rank: sendobj}
    k = 1
    while k < size:
        dst = (rank - k + size) % size
        src = (rank + k) % size
        ship = {}
        for j in range(min(k, size - k)):
            idx = (rank + j) % size
            ship[idx] = blocks[idx]
        data = comm.sendrecv(ship, dst, src, TAG_ALLGATHER, TAG_ALLGATHER)
        blocks.update(data)
        k <<= 1
    return [blocks[i] for i in range(size)]


@register("allgather", "pair")
def allgather_pair(comm, sendobj):
    """Two-process exchange (allgather-pair.cpp)."""
    rank, size = comm.rank(), comm.size()
    if size != 2:
        return allgather_ring(comm, sendobj)
    other = comm.sendrecv(sendobj, 1 - rank, 1 - rank,
                          TAG_ALLGATHER, TAG_ALLGATHER)
    out = [None, None]
    out[rank] = sendobj
    out[1 - rank] = other
    return out


@register("allgather", "ompi_neighborexchange")
def allgather_neighborexchange(comm, sendobj):
    """Neighbor exchange (allgather-ompi-neighborexchange.cpp): p/2
    steps with alternating left/right neighbors, each shipping the pair
    of blocks acquired in the previous step; odd p uses ring like the
    reference's guard."""
    rank, size = comm.rank(), comm.size()
    if size % 2:
        return allgather_ring(comm, sendobj)
    blocks = {rank: sendobj}
    even = rank % 2 == 0
    first = (rank + 1) % size if even else (rank - 1 + size) % size
    data = comm.sendrecv({rank: sendobj}, first, first,
                         TAG_ALLGATHER, TAG_ALLGATHER)
    blocks.update(data)
    prev_pair = {**{rank: sendobj}, **data}
    for step in range(1, size // 2):
        if (step % 2 == 1) == even:
            peer = (rank - 1 + size) % size
        else:
            peer = (rank + 1) % size
        data = comm.sendrecv(prev_pair, peer, peer,
                             TAG_ALLGATHER, TAG_ALLGATHER)
        blocks.update(data)
        prev_pair = data
    return [blocks[i] for i in range(size)]


@register("barrier", "ompi_two_procs")
def barrier_ompi_two_procs(comm):
    """Two-process barrier (barrier-ompi.cpp two_procs)."""
    rank, size = comm.rank(), comm.size()
    if size != 2:
        return barrier_bruck(comm)
    comm.sendrecv(b"", 1 - rank, 1 - rank, TAG_BARRIER, TAG_BARRIER)


@register("barrier", "ompi_recursivedoubling")
def barrier_recursivedoubling(comm):
    """Recursive-doubling barrier (barrier-ompi.cpp recursivedoubling);
    non-power-of-two ranks do the reference's pre/post folding."""
    rank, size = comm.rank(), comm.size()
    adjsize = 1
    while adjsize * 2 <= size:
        adjsize *= 2
    extra = size - adjsize
    if rank >= adjsize:
        comm.send(b"", rank - adjsize, TAG_BARRIER)
        comm.recv(rank - adjsize, TAG_BARRIER)
        return
    if rank < extra:
        comm.recv(rank + adjsize, TAG_BARRIER)
    mask = 1
    while mask < adjsize:
        peer = rank ^ mask
        comm.sendrecv(b"", peer, peer, TAG_BARRIER, TAG_BARRIER)
        mask <<= 1
    if rank < extra:
        comm.send(b"", rank + adjsize, TAG_BARRIER)


@register("barrier", "ompi_bruck")
def barrier_ompi_bruck(comm):
    return barrier_bruck(comm)


@register("gather", "ompi_basic_linear")
def gather_ompi_basic_linear(comm, sendobj, root: int = 0):
    return gather_linear(comm, sendobj, root)


@register("gather", "ompi_binomial")
def gather_ompi_binomial(comm, sendobj, root: int = 0):
    """Binomial-tree gather (gather-ompi.cpp binomial)."""
    rank, size = comm.rank(), comm.size()
    rel = (rank - root + size) % size
    gathered = {rank: sendobj}
    mask = 1
    while mask < size:
        if rel & mask:
            parent = ((rel - mask) + root) % size
            comm.send(gathered, parent, TAG_GATHER,
                      count=sum(_payload_bytes(v)
                                for v in gathered.values()),
                      datatype=MPI_BYTE)
            return None
        child_rel = rel + mask
        if child_rel < size:
            gathered.update(comm.recv((child_rel + root) % size,
                                      TAG_GATHER))
        mask <<= 1
    return [gathered[i] for i in range(size)]


@register("gather", "ompi_linear_sync")
def gather_ompi_linear_sync(comm, sendobj, root: int = 0):
    """Linear with a zero-byte synchronization handshake before each
    transfer (gather-ompi.cpp linear_sync)."""
    rank, size = comm.rank(), comm.size()
    if rank != root:
        comm.recv(root, TAG_GATHER)           # sync token
        comm.send(sendobj, root, TAG_GATHER)
        return None
    parts = [None] * size
    parts[root] = sendobj
    for src in range(size):
        if src != root:
            comm.send(b"", src, TAG_GATHER)   # sync token
            parts[src] = comm.recv(src, TAG_GATHER)
    return parts


@register("scatter", "ompi_basic_linear")
def scatter_ompi_basic_linear(comm, sendobjs, root: int = 0):
    return scatter_linear(comm, sendobjs, root)


@register("scatter", "ompi_binomial")
def scatter_ompi_binomial(comm, sendobjs, root: int = 0):
    """Binomial-tree scatter (scatter-ompi.cpp binomial)."""
    rank, size = comm.rank(), comm.size()
    rel = (rank - root + size) % size
    if rel == 0:
        mine = {(i + root) % size: sendobjs[(i + root) % size]
                for i in range(size)}
    else:
        mask = 1
        while not (rel & mask):
            mask <<= 1
        parent = ((rel - mask) + root) % size
        mine = comm.recv(parent, TAG_SCATTER)
    mask = 1
    while mask < size and not (rel & mask):
        child_rel = rel + mask
        if child_rel < size:
            payload = {}
            for key in list(mine):
                key_rel = (key - root + size) % size
                if child_rel <= key_rel < child_rel + mask:
                    payload[key] = mine.pop(key)
            comm.send(payload, (child_rel + root) % size, TAG_SCATTER,
                      count=sum(_payload_bytes(v)
                                for v in payload.values()),
                      datatype=MPI_BYTE)
        mask <<= 1
    return mine[rank]


@register("alltoall", "rdb")
def alltoall_rdb(comm, sendobjs):
    """Recursive-doubling alltoall (alltoall-rdb.cpp, the
    MPIR_Alltoall_RD_MV2 of the mvapich2 tables): log2(p) rounds, each
    shipping the half of the working set whose destination bit is set;
    non-power-of-two communicators fall back to bruck like the
    reference's guard."""
    rank, size = comm.rank(), comm.size()
    if size & (size - 1):
        return alltoall_bruck(comm, sendobjs)
    # working set: src -> {dst -> payload}; starts with my column
    working = {rank: dict(enumerate(sendobjs))}
    mask = 1
    while mask < size:
        peer = rank ^ mask
        ship = {}
        for src in list(working):
            row = working[src]
            give = {dst: row.pop(dst) for dst in list(row)
                    if (dst & mask) != (rank & mask)}
            if give:
                ship[src] = give
        got = comm.sendrecv(ship, peer, peer, TAG_ALLTOALL, TAG_ALLTOALL)
        for src, row in got.items():
            working.setdefault(src, {}).update(row)
        mask <<= 1
    return [working[src][rank] for src in range(size)]


@register("allgather", "GB")
def allgather_gb(comm, sendobj):
    """Gather-then-broadcast allgather (allgather-GB.cpp, the intel
    tables' fourth allgather entry): default gather to root 0, then
    default bcast of the assembled vector."""
    gathered = dispatch_name("gather", "default")(comm, sendobj, 0)
    return dispatch_name("bcast", "default")(comm, gathered, 0)
