"""One-sided communication: the MPI-3 RMA window.

Role of reference src/smpi/mpi/smpi_win.cpp (752 LoC: fence, PSCW
epochs, passive-target lock/unlock/lock_all, the flush family, and the
atomic ops) — redesigned for this framework's actor kernel:

The reference issues both sides of each RMA transfer itself (Win::put
posts the send *and* the matching receive, since it owns every rank's
request queues).  Here passive progress is modeled explicitly: window
creation spawns one daemon actor per rank on the window's host that
serves its mailbox — an RMA transfer is a real simulated message riding
the origin->target route, applied to the target's memory by the
target-side daemon without the target rank's participation.  Because
the daemon applies each message in one uninterrupted step, accumulate
atomicity (MPI-3 §11.7.1) holds by construction, and per-origin
ordering (rar/war/raw/waw) follows from mailbox FIFO.

Synchronization is counter-based: every origin keeps a monotonic count
of data ops sent to each target; every daemon keeps a monotonic count
of ops applied from each origin.  An epoch-closing call tells the
target how many ops to expect (fence: via alltoall; complete: in the
epoch-closing token; flush: in the flush request) and the daemon
answers when its applied counter catches up.  This replaces the
reference's finish_comms() request-reaping (smpi_win.cpp:450-520).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .op import Op

# lock types (mirror include/smpi/mpi.h)
LOCK_EXCLUSIVE = 234
LOCK_SHARED = 235

# assertions (any combination may be passed; they are hints)
MODE_NOCHECK = 1024
MODE_NOSTORE = 2048
MODE_NOPUT = 4096
MODE_NOPRECEDE = 8192
MODE_NOSUCCEED = 16384

FLAVOR_CREATE = 1
FLAVOR_ALLOCATE = 2
FLAVOR_DYNAMIC = 3
FLAVOR_SHARED = 4

_CTRL_BYTES = 8          # simulated size of a control token


class SlotMemory:
    """Python-API windows: the rank's window is any indexable object;
    displacements are slot keys and payloads arbitrary objects."""

    def __init__(self, obj):
        self.obj = obj

    def put(self, slot, payload) -> None:
        try:
            self.obj[slot] = payload
        except TypeError:
            setattr(self.obj, slot, payload)

    def get(self, slot):
        return self.obj[slot] if slot is not None else self.obj

    def acc(self, slot, payload, op: Op):
        self.obj[slot] = op(self.obj[slot], payload)

    def gacc(self, slot, payload, op: Op):
        old = self.obj[slot]
        if op is not None:                    # None = MPI_NO_OP
            self.obj[slot] = op(old, payload)
        return old

    def cas(self, slot, compare, new):
        old = self.obj[slot]
        if old == compare:
            self.obj[slot] = new
        return old


class CMemory:
    """C-API windows: the window is the caller's raw memory.  All ranks
    live in one address space (per-rank .so copies), so the daemon
    reads/writes the target buffer with ctypes through the datatype
    type map.  ``disp`` is scaled by the TARGET's disp_unit here —
    exactly MPI's addressing rule; dynamic windows use absolute
    addresses (disp_unit 1, base 0)."""

    def __init__(self, base: int, disp_unit: int = 1, size: int = 0):
        self.base = int(base)
        self.disp_unit = int(disp_unit)
        self.size = int(size)

    def _addr(self, disp: int) -> int:
        return self.base + int(disp) * self.disp_unit

    @staticmethod
    def _elems(arr, leaf_np):
        """View a packed payload as its LEAF element type (derived
        C-API types travel as packed uint8; accumulate math needs the
        basic elements — MPI requires a uniform predefined leaf)."""
        import numpy as np
        if (leaf_np is None or arr is None
                or arr.dtype == np.dtype(leaf_np)):
            return arr
        itemsize = np.dtype(leaf_np).itemsize
        if itemsize and arr.nbytes % itemsize == 0:
            return np.frombuffer(arr.tobytes(), dtype=leaf_np)
        return arr

    # payloads are packed numpy arrays; dt a c_api Datatype describing
    # the TARGET-side layout (count elements scattered via its typemap);
    # leaf_np the basic element dtype for op application
    def put(self, args, payload) -> None:
        from .c_api import _arr_out
        disp, count, dt = args[:3]
        _arr_out(self._addr(disp), payload, dt=dt)

    def get(self, args):
        from .c_api import _arr_in
        disp, count, dt = args[:3]
        return _arr_in(self._addr(disp), count, dt)

    def acc(self, args, payload, op: Optional[Op]) -> None:
        from .c_api import _arr_in, _arr_out
        disp, count, dt = args[:3]
        leaf_np = args[3] if len(args) > 3 else None
        if op == "replace":
            _arr_out(self._addr(disp), payload, dt=dt)
            return
        cur = self._elems(_arr_in(self._addr(disp), count, dt), leaf_np)
        payload = self._elems(payload, leaf_np)
        n = min(len(cur), len(payload))
        out = op(cur[:n], payload[:n])
        _arr_out(self._addr(disp), out, dt=dt)

    def gacc(self, args, payload, op: Optional[Op]):
        from .c_api import _arr_in
        disp, count, dt = args[:3]
        old = _arr_in(self._addr(disp), count, dt).copy()
        if op is not None:
            self.acc(args, payload, op)
        return old

    def cas(self, args, compare, new):
        from .c_api import _arr_in, _arr_out
        disp, count, dt = args[:3]
        old = _arr_in(self._addr(disp), 1, dt).copy()
        if old.tobytes() == compare.tobytes():
            _arr_out(self._addr(disp), new, dt=dt)
        return old


class Win:
    """Collective window: every rank of ``comm`` constructs one.

    Python surface (slot mode): ``Win(comm, local_data)`` then
    put/get/accumulate with slot keys — matches the legacy API.
    C surface: ``Win(comm, memory=CMemory(base, unit))`` driven by
    smpi/c_api.py with datatype-mapped addressing.
    """

    def __init__(self, comm, local_data=None, size_bytes: Optional[int] = None,
                 memory=None, flavor: int = FLAVOR_CREATE,
                 name: Optional[str] = None):
        from ..s4u import Actor, Mailbox, Semaphore
        from . import runtime

        self.comm = comm
        self.flavor = flavor
        self.name = name or ""
        self.mem = memory if memory is not None else SlotMemory(local_data)
        self.local_data = local_data
        rank = comm.rank()
        self.rank = rank
        n = comm.size()
        # Deterministic collective id without communication: window
        # creation is collective and ordered, so every rank's per-comm
        # creation sequence agrees (same rule as communicator ids).
        self.win_id = str(comm._next_cc_id("win"))
        self._mbox = Mailbox.by_name(f"__win{self.win_id}-{rank}")
        self._pscw_mbox = Mailbox.by_name(f"__win{self.win_id}-pscw-{rank}")

        # -- origin-side state --
        self._sent_total = [0] * n          # data ops sent per target
        self._fast_bytes = [0] * n          # coalesced fast-op traffic
        self._reply_seq = 0
        self._lock_held: Dict[int, int] = {}    # target -> lock type
        self._pscw_targets: Optional[List[int]] = None  # access epoch
        self._post_stash: Dict[int, int] = {}   # unconsumed post tokens

        # -- daemon-side (exposure) state --
        self._applied_from: Dict[int, int] = {}
        self._lock_holders: Dict[int, int] = {}  # origin -> type
        self._lock_queue: List[Tuple[int, int, str]] = []
        self._pending_flushes: List[Tuple[int, int, str]] = []
        self._complete_tokens: Dict[int, List[int]] = {}
        self._pscw_exposed: Optional[List[int]] = None
        self._trigger = None                # (pred, Semaphore) of main
        self._free_pending = False
        self._async_reqs: List = []         # outstanding Rget/Rgacc

        me = runtime.this_rank_state()
        self._daemon = Actor.create(f"__win{self.win_id}_rma_{rank}",
                                    me.host, self._serve)
        self._daemon.daemonize()
        self._Semaphore = Semaphore
        self._Mailbox = Mailbox
        # Peer registry scoped to the engine object: every rank's Win
        # is reachable in-process, enabling the fast-atomics path.
        from ..s4u import Engine
        eng = Engine.get_instance().pimpl
        if not hasattr(eng, "_win_registry"):
            eng._win_registry = {}
        self._registry = eng._win_registry
        self._registry[(self.win_id, rank)] = self
        comm.barrier()

    def _peer(self, rank: int) -> Optional["Win"]:
        return self._registry.get((self.win_id, rank))

    def _fast_ready(self, target: int) -> Optional["Win"]:
        """The immediate-linearization condition: every op I have
        issued to ``target`` has been applied there, so an atomic read
        linearized NOW preserves my program order (cross-origin order
        is unconstrained between synchronizations).  Sound because the
        cooperative kernel makes the whole apply one atomic step, and
        immediate visibility is legal under MPI_WIN_UNIFIED."""
        from ..utils.config import config
        if not config["smpi/rma-fast-atomics"]:
            return None
        peer = self._peer(target)
        if peer is None:
            return None
        if peer._applied_from.get(self.rank, 0) < self._sent_total[target]:
            return None
        return peer

    # ------------------------------------------------------------------
    # daemon (exposure side)
    # ------------------------------------------------------------------
    def _serve(self) -> None:
        from ..exceptions import SimgridException
        try:
            self._serve_loop()
        except SimgridException:
            # engine teardown (daemonized actors are killed with their
            # pending receives): exit quietly
            return

    def _serve_loop(self) -> None:
        while True:
            msg = self._mbox.get()
            kind = msg[0]
            if kind == "free":
                break
            if kind in ("put", "acc", "get", "gacc", "cas",
                        "sput", "sacc", "sget", "tick"):
                self._apply_op(msg)
            elif kind == "lock":
                _, origin, lt, reply = msg
                self._lock_queue.append((origin, lt, reply))
                self._grant_locks()
            elif kind == "unlock":
                _, origin = msg
                self._lock_holders.pop(origin, None)
                self._grant_locks()
            elif kind == "flush":
                _, origin, upto, reply = msg
                if self._applied_from.get(origin, 0) >= upto:
                    self._reply(reply, True)
                else:
                    self._pending_flushes.append((origin, upto, reply))
            elif kind == "complete":
                _, origin, total = msg
                self._complete_tokens.setdefault(origin, []).append(total)
            self._poke()

    def _apply_op(self, msg) -> None:
        kind, origin = msg[0], msg[1]
        if kind == "put":
            self.mem.put(msg[2], msg[3])
        elif kind == "acc":
            self.mem.acc(msg[2], msg[3], msg[4])
        elif kind == "get":
            _, _, reply, args = msg
            self._reply(reply, self.mem.get(args),
                        nbytes=_payload_bytes(args))
        elif kind == "gacc":
            _, _, reply, args, payload, op = msg
            old = self.mem.gacc(args, payload, op)
            self._reply(reply, old, nbytes=_payload_bytes(args))
        elif kind == "cas":
            _, _, reply, args, compare, new = msg
            self._reply(reply, self.mem.cas(args, compare, new))
        elif kind == "sput":
            self.mem.put(msg[2], msg[3])
        elif kind == "sacc":
            self.mem.acc(msg[2], msg[3], msg[4])
        elif kind == "sget":
            _, _, reply, slot, nbytes = msg
            self._reply(reply, self.mem.get(slot), nbytes=nbytes)
        # "tick": coalesced timing traffic of fast ops already applied
        # at the origin — counts toward the epoch, moves no memory
        self._applied_from[origin] = self._applied_from.get(origin, 0) + 1
        if self._pending_flushes:
            done = self._applied_from
            keep = []
            for origin, upto, reply in self._pending_flushes:
                if done.get(origin, 0) >= upto:
                    self._reply(reply, True)
                else:
                    keep.append((origin, upto, reply))
            self._pending_flushes = keep

    def _reply(self, mbox_name: str, payload, nbytes: int = _CTRL_BYTES):
        """Detached reply: the daemon must never block on a consumer
        (a blocked daemon would deadlock flush-before-request-reap
        patterns like Rget;unlock;wait)."""
        self._Mailbox.by_name(mbox_name).put_async(
            (payload,), max(nbytes, 1))

    def _grant_locks(self) -> None:
        """FIFO lock admission: grant the queue head while compatible
        (an exclusive needs an empty table; shareds coalesce)."""
        while self._lock_queue:
            origin, lt, reply = self._lock_queue[0]
            if lt == LOCK_EXCLUSIVE:
                if self._lock_holders:
                    return
            else:
                if any(t == LOCK_EXCLUSIVE
                       for t in self._lock_holders.values()):
                    return
            self._lock_queue.pop(0)
            self._lock_holders[origin] = lt
            self._reply(reply, True)

    def _poke(self) -> None:
        """Wake the main actor if its wait predicate now holds."""
        if self._trigger is not None:
            pred, sem = self._trigger
            if pred():
                self._trigger = None
                sem.release()

    # ------------------------------------------------------------------
    # origin-side helpers
    # ------------------------------------------------------------------
    def _target_mbox(self, rank: int):
        return self._Mailbox.by_name(f"__win{self.win_id}-{rank}")

    def _new_reply(self) -> str:
        self._reply_seq += 1
        return f"__win{self.win_id}-r{self.rank}-{self._reply_seq}"

    def _send(self, target: int, msg, nbytes: float, data_op=True) -> None:
        self._target_mbox(target).put_async(msg, max(nbytes, 1))
        if data_op:
            self._sent_total[target] += 1

    def _await(self, pred) -> None:
        """Block the main actor until the daemon satisfies ``pred``."""
        if pred():
            return
        sem = self._Semaphore(0)
        self._trigger = (pred, sem)
        sem.acquire()

    def _recv_reply(self, reply: str):
        return self._Mailbox.by_name(reply).get()[0]

    def _fast(self, target: int, nbytes: int) -> Optional["Win"]:
        """Fast-op admission + traffic coalescing: the op is applied
        immediately by the CALLER; its bytes join one bulk timing
        message sent at the next epoch-close (fence/flush/complete)."""
        peer = self._fast_ready(target)
        if peer is not None:
            self._fast_bytes[target] += max(int(nbytes), 1)
        return peer

    def _flush_fast(self, target: int) -> None:
        nbytes = self._fast_bytes[target]
        if nbytes:
            self._fast_bytes[target] = 0
            self._send(target, ("tick", self.rank), nbytes)

    # ------------------------------------------------------------------
    # RMA operations — C mode (args = (disp, count, target_dt[, leaf]))
    # ------------------------------------------------------------------
    def c_put(self, target: int, args, payload, nbytes: int) -> None:
        peer = self._fast(target, nbytes)
        if peer is not None:
            peer.mem.put(args, payload)
            return
        self._send(target, ("put", self.rank, args, payload), nbytes)

    def c_get(self, target: int, args, nbytes: int):
        peer = self._fast(target, nbytes)
        if peer is not None:
            return peer.mem.get(args)
        reply = self._new_reply()
        self._send(target, ("get", self.rank, reply, args), _CTRL_BYTES)
        return self._recv_reply(reply)

    def c_get_async(self, target: int, args, nbytes: int):
        """Returns the reply Comm + mailbox for request-based Rget."""
        reply = self._new_reply()
        self._send(target, ("get", self.rank, reply, args), _CTRL_BYTES)
        return self._Mailbox.by_name(reply).get_async()

    def c_acc(self, target: int, args, payload, op, nbytes: int) -> None:
        peer = self._fast(target, nbytes)
        if peer is not None:
            peer.mem.acc(args, payload, op)
            return
        self._send(target, ("acc", self.rank, args, payload, op), nbytes)

    def c_gacc(self, target: int, args, payload, op, nbytes: int):
        peer = self._fast(target, max(nbytes, _CTRL_BYTES))
        if peer is not None:
            return peer.mem.gacc(args, payload, op)
        reply = self._new_reply()
        self._send(target, ("gacc", self.rank, reply, args, payload, op),
                   max(nbytes, _CTRL_BYTES))
        return self._recv_reply(reply)

    def c_gacc_async(self, target: int, args, payload, op, nbytes: int):
        reply = self._new_reply()
        self._send(target, ("gacc", self.rank, reply, args, payload, op),
                   max(nbytes, _CTRL_BYTES))
        return self._Mailbox.by_name(reply).get_async()

    def c_cas(self, target: int, args, compare, new):
        peer = self._fast(target, _CTRL_BYTES)
        if peer is not None:
            return peer.mem.cas(args, compare, new)
        reply = self._new_reply()
        self._send(target, ("cas", self.rank, reply, args, compare, new),
                   _CTRL_BYTES)
        return self._recv_reply(reply)

    # ------------------------------------------------------------------
    # RMA operations — slot mode (legacy Python API)
    # ------------------------------------------------------------------
    def put(self, target_rank: int, slot, data, nbytes: int) -> None:
        peer = self._fast(target_rank, nbytes)
        if peer is not None:
            peer.mem.put(slot, data)
            return
        self._send(target_rank, ("sput", self.rank, slot, data), nbytes)

    def accumulate(self, target_rank: int, slot, data, nbytes: int,
                   op: Op) -> None:
        peer = self._fast(target_rank, nbytes)
        if peer is not None:
            peer.mem.acc(slot, data, op)
            return
        self._send(target_rank, ("sacc", self.rank, slot, data, op), nbytes)

    def get(self, target_rank: int, slot, nbytes: int) -> Any:
        peer = self._fast(target_rank, nbytes)
        if peer is not None:
            return peer.mem.get(slot)
        reply = self._new_reply()
        self._send(target_rank, ("sget", self.rank, reply, slot, nbytes),
                   _CTRL_BYTES)
        return self._recv_reply(reply)

    # ------------------------------------------------------------------
    # active-target synchronization
    # ------------------------------------------------------------------
    def fence(self, assertion: int = 0) -> None:
        """Close the access+exposure epoch (Win::fence): every daemon
        has applied the traffic addressed to it, then a barrier."""
        self._drain_async()
        for t in range(self.comm.size()):
            self._flush_fast(t)
        expected = self.comm.alltoall(list(self._sent_total))

        def caught_up():
            return all(self._applied_from.get(o, 0) >= e
                       for o, e in enumerate(expected) if e)
        self._await(caught_up)
        self.comm.barrier()

    def start(self, targets: List[int], assertion: int = 0) -> None:
        """Open an access epoch toward ``targets`` (comm ranks): waits
        for each target's matching post token (out-of-order tokens from
        other epochs are stashed, pscw_ordering-safe)."""
        self._pscw_targets = list(targets)
        if assertion & MODE_NOCHECK:
            return
        need = set(targets)
        while need:
            avail = [t for t in need if self._post_stash.get(t, 0) > 0]
            if avail:
                for t in avail:
                    self._post_stash[t] -= 1
                    need.discard(t)
                continue
            tok = self._pscw_mbox.get()
            self._post_stash[tok[1]] = self._post_stash.get(tok[1], 0) + 1

    def complete(self) -> None:
        """Close the access epoch: each target learns how many of my
        ops to expect; its wait() blocks until they are applied."""
        targets, self._pscw_targets = self._pscw_targets or [], None
        self._drain_async()
        for t in targets:
            self._flush_fast(t)
            self._send(t, ("complete", self.rank, self._sent_total[t]),
                       _CTRL_BYTES, data_op=False)

    def post(self, origins: List[int], assertion: int = 0) -> None:
        """Open an exposure epoch for ``origins``."""
        self._pscw_exposed = list(origins)
        if assertion & MODE_NOCHECK:
            return
        from ..s4u import Mailbox
        for o in origins:
            Mailbox.by_name(f"__win{self.win_id}-pscw-{o}").put_async(
                ("post", self.rank), _CTRL_BYTES)

    def _pscw_done(self) -> bool:
        return all(self._complete_tokens.get(o) and
                   self._applied_from.get(o, 0) >= self._complete_tokens[o][0]
                   for o in (self._pscw_exposed or []))

    def _pscw_consume(self) -> None:
        for o in (self._pscw_exposed or []):
            self._complete_tokens[o].pop(0)
        self._pscw_exposed = None

    def wait(self) -> None:
        """Close the exposure epoch: every origin in the posted group
        has completed and all its ops have landed."""
        self._await(self._pscw_done)
        self._pscw_consume()

    def test(self) -> bool:
        if self._pscw_done():
            self._pscw_consume()
            return True
        # an unsuccessful MPI_Win_test advances the clock a little, or
        # a busy wait-for-exposure loop freezes simulated time forever
        # (same smpi/test injection as MPI_Test; rma/wintest)
        from ..utils.config import config
        sleep = config["smpi/test"]
        if sleep > 0:
            from ..s4u import this_actor
            this_actor.sleep_for(sleep)
        return False

    # ------------------------------------------------------------------
    # passive-target synchronization
    # ------------------------------------------------------------------
    def lock(self, lock_type: int, target: int, assertion: int = 0) -> None:
        """Acquires at call time — the MPI standard explicitly permits
        blocking lock acquisition (MPI-3 §11.5.3); programs holding
        exclusive locks on multiple targets in crossing order are
        deadlock-prone under any serializing implementation."""
        if target in self._lock_held:
            raise RuntimeError("MPI_Win_lock: already locked")
        self._lock_held[target] = lock_type
        if assertion & MODE_NOCHECK:
            return
        reply = self._new_reply()
        self._send(target, ("lock", self.rank, lock_type, reply),
                   _CTRL_BYTES, data_op=False)
        self._recv_reply(reply)

    def unlock(self, target: int) -> None:
        checked = self._lock_held.pop(target, None)
        self.flush(target)
        if checked is not None:
            self._send(target, ("unlock", self.rank), _CTRL_BYTES,
                       data_op=False)

    def lock_all(self, assertion: int = 0) -> None:
        for t in range(self.comm.size()):
            self.lock(LOCK_SHARED, t, assertion)

    def unlock_all(self) -> None:
        for t in range(self.comm.size()):
            self.unlock(t)

    def register_async(self, rreq) -> None:
        """Track a request-based op: window syncs (flush/unlock/fence/
        complete) force-complete it so the user may reuse the result
        buffer right after the sync (MPI-3 §11.5.4, rma/rget-unlock);
        the later MPI_Wait is then a no-op."""
        self._async_reqs.append(rreq)

    def _drain_async(self) -> None:
        for r in self._async_reqs:
            r.force()
        self._async_reqs.clear()

    def flush(self, target: int) -> None:
        """Remote completion of all my outstanding ops to ``target``."""
        self._drain_async()
        self._flush_fast(target)
        if self._sent_total[target] == 0:
            return
        reply = self._new_reply()
        self._send(target, ("flush", self.rank, self._sent_total[target],
                            reply), _CTRL_BYTES, data_op=False)
        self._recv_reply(reply)

    def flush_all(self) -> None:
        for t in range(self.comm.size()):
            self.flush(t)

    def flush_local(self, target: int) -> None:
        """Local completion: payloads are copied at issue time, so the
        origin buffers are already reusable — nothing to wait for."""

    def flush_local_all(self) -> None:
        pass

    def sync(self) -> None:
        """Memory barrier between window copies — a single unified
        address space here (MPI_WIN_UNIFIED), so a no-op."""

    # ------------------------------------------------------------------
    def free(self) -> None:
        """Collective destructor: drain and stop the daemons."""
        self.fence()
        self._registry.pop((self.win_id, self.rank), None)
        self._mbox.put_async(("free",), 1)


def _payload_bytes(args) -> int:
    disp, count, dt = args[:3]
    return max(int(count) * dt.size_, 1) if dt is not None else 1
