"""One-sided communication: MPI_Win put/get/accumulate + fence
(reference src/smpi/mpi/smpi_win.cpp).

The reference issues both sides of each RMA transfer itself (it owns
every rank's request queues, smpi_win.cpp Win::put posts the send *and*
the matching receive). Here passive progress is modeled explicitly: Win
creation spawns one daemon actor per rank on the window's host that
serves its mailbox — so an RMA transfer is a real simulated message
riding the origin->target route, applied by the target-side daemon
without the target rank's participation. fence() follows the
reference's semantics: it completes all outstanding accesses (an
alltoall of op counts tells each daemon how much traffic to expect,
the daemon signals local completion, then a barrier closes the epoch).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .op import Op

_win_seq = 0


class Win:
    """Collective window object: every rank constructs it with its
    local data object (an np.ndarray or dict-like)."""

    def __init__(self, comm, local_data, size_bytes: Optional[int] = None):
        global _win_seq
        from ..s4u import Actor, Mailbox, Semaphore
        from . import runtime

        self.comm = comm
        self.local_data = local_data
        rank = comm.rank()
        # Deterministic collective id without communication: window
        # creation is collective and ordered, so every rank's per-comm
        # creation sequence agrees (same rule as communicator ids).
        self.win_id = str(comm._next_cc_id("win"))
        self._mbox = Mailbox.by_name(f"__win{self.win_id}-{rank}")
        self._pending_counts = [0] * comm.size()   # ops sent per target
        self._sends: List = []
        self._consumed = 0          # ops my daemon applied this epoch
        self._expected: Optional[int] = None
        self._epoch_sem = Semaphore(0)

        me = runtime.this_rank_state()
        win = self

        def daemon():
            while True:
                msg = win._mbox.get()
                if msg == "__win_free__":
                    break
                kind, payload = msg
                if kind == "put":
                    slot, data = payload
                    win._apply_put(slot, data)
                elif kind == "acc":
                    slot, data, op = payload
                    win._apply_acc(slot, data, op)
                elif kind == "get":
                    reply_to, slot, nbytes = payload
                    data = win._read(slot)
                    Mailbox.by_name(reply_to).put(data, nbytes)
                win._consumed += 1
                if win._expected is not None and \
                        win._consumed >= win._expected:
                    win._epoch_sem.release()

        self._daemon = Actor.create(f"__win{self.win_id}_rma_{rank}",
                                    me.host, daemon)
        self._daemon.daemonize()
        comm.barrier()

    # -- local window application -----------------------------------------
    def _apply_put(self, slot, data) -> None:
        try:
            self.local_data[slot] = data
        except TypeError:
            setattr(self.local_data, slot, data)

    def _apply_acc(self, slot, data, op: Op) -> None:
        self.local_data[slot] = op(self.local_data[slot], data)

    def _read(self, slot):
        return self.local_data[slot] if slot is not None else \
            self.local_data

    # -- RMA calls (smpi_win.cpp put/get/accumulate) ----------------------
    def put(self, target_rank: int, slot, data, nbytes: int) -> None:
        from ..s4u import Mailbox
        mbox = Mailbox.by_name(f"__win{self.win_id}-{target_rank}")
        self._sends.append(mbox.put_async(("put", (slot, data)), nbytes))
        self._pending_counts[target_rank] += 1

    def accumulate(self, target_rank: int, slot, data, nbytes: int,
                   op: Op) -> None:
        from ..s4u import Mailbox
        mbox = Mailbox.by_name(f"__win{self.win_id}-{target_rank}")
        self._sends.append(
            mbox.put_async(("acc", (slot, data, op)), nbytes))
        self._pending_counts[target_rank] += 1

    def get(self, target_rank: int, slot, nbytes: int) -> Any:
        """Synchronous within the access epoch (the reference's get is
        also a paired transfer): a tiny request message to the target's
        daemon, the data rides back over the same route."""
        from ..s4u import Mailbox
        reply = f"__win{self.win_id}-get-{self.comm.rank()}-{target_rank}"
        mbox = Mailbox.by_name(f"__win{self.win_id}-{target_rank}")
        self._pending_counts[target_rank] += 1
        mbox.put(("get", (reply, slot, nbytes)), 8)
        return Mailbox.by_name(reply).get()

    # -- synchronization ---------------------------------------------------
    def fence(self) -> None:
        """Close the access epoch (Win::fence): local sends complete,
        every daemon has applied the traffic addressed to it, barrier."""
        for req in self._sends:
            req.wait()
        self._sends.clear()
        incoming = self.comm.alltoall(list(self._pending_counts))
        self._pending_counts = [0] * self.comm.size()
        expected = sum(incoming)
        if expected > self._consumed:
            self._expected = expected
            self._epoch_sem.acquire()
        self._expected = None
        self._consumed = 0
        self.comm.barrier()

    def free(self) -> None:
        """Collective destructor: stop the daemons."""
        self.fence()
        self._mbox.put("__win_free__", 1)
