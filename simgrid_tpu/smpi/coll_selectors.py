"""mpich and ompi collective-selector decision trees.

Re-implements the decision functions of smpi_mpich_selector.cpp and
smpi_openmpi_selector.cpp: pick a concrete algorithm from message size
and communicator size, with the same thresholds. Registered as
algorithms named "mpich"/"ompi" for every operation, so either
``--cfg=smpi/coll-selector:mpich`` (all ops at once) or
``--cfg=smpi/<op>:mpich`` (a single op) selects them.

Like MPI itself, size-staged selection assumes every rank passes a
same-shaped payload to the collective (message_size must agree across
ranks or different ranks would pick different algorithms).

SMP-topology branches (mvapich2 two-level, SMP-binomial) are not taken:
simulated deployments place one rank per host, where those algorithms
degenerate to the flat equivalents chosen here (see coll_extra.py).
"""

from __future__ import annotations

from .coll import dispatch_name, register
from .datatype import payload_size
from .op import Op


def _pof2_below(n: int) -> int:
    p = 1
    while p <= n:
        p <<= 1
    return p >> 1


def _is_pof2(n: int) -> bool:
    return n & (n - 1) == 0


def _require_symmetric(payload, what: str):
    """Size-staged selection for rooted collectives needs the message
    size on *every* rank (MPI gets it from count/datatype, which all
    ranks pass). A None payload on a non-root rank would silently pick
    a different algorithm than the root and deadlock — fail fast with
    the contract instead."""
    if payload is None:
        raise ValueError(
            f"smpi/coll-selector requires every rank to pass a "
            f"same-shaped payload to {what} (the MPI count contract); "
            f"pass a buffer of the right size on non-root ranks or use "
            f"the default selector")


# ---------------------------------------------------------------------------
# mpich (smpi_mpich_selector.cpp)
# ---------------------------------------------------------------------------

@register("allreduce", "mpich")
def allreduce_mpich(comm, sendobj, op: Op):
    """smpi_mpich_selector.cpp:61-92 (SMP branch degenerate, see
    module docstring)."""
    block_dsize = payload_size(sendobj, None)
    pof2 = _pof2_below(comm.size())
    count = len(sendobj) if hasattr(sendobj, "__len__") else 1
    if block_dsize > 2048 and count >= pof2 and op.is_commutative():
        return dispatch_name("allreduce", "rab_rdb")(comm, sendobj, op)
    return dispatch_name("allreduce", "rdb")(comm, sendobj, op)


@register("alltoall", "mpich")
def alltoall_mpich(comm, sendobjs):
    """smpi_mpich_selector.cpp:141-188."""
    size = comm.size()
    block_dsize = payload_size(sendobjs[0], None) if sendobjs else 0
    if block_dsize < 256 and size >= 8:
        return dispatch_name("alltoall", "bruck")(comm, sendobjs)
    if block_dsize < 32768:
        return dispatch_name("alltoall",
                             "mvapich2_scatter_dest")(comm, sendobjs)
    if size % 2:
        return dispatch_name("alltoall", "pair")(comm, sendobjs)
    return dispatch_name("alltoall", "ring")(comm, sendobjs)


@register("barrier", "mpich")
def barrier_mpich(comm):
    """smpi_mpich_selector.cpp:204-207: always ompi_bruck."""
    return dispatch_name("barrier", "ompi_bruck")(comm)


@register("bcast", "mpich")
def bcast_mpich(comm, obj, root: int = 0):
    """smpi_mpich_selector.cpp:252-296."""
    _require_symmetric(obj, "bcast")
    size = comm.size()
    message_size = payload_size(obj, None)
    if message_size < 12288 or size <= 8:
        return dispatch_name("bcast", "binomial_tree")(comm, obj, root)
    if message_size < 524288 and size % 2 == 0:
        return dispatch_name("bcast",
                             "scatter_rdb_allgather")(comm, obj, root)
    return dispatch_name("bcast", "scatter_LR_allgather")(comm, obj, root)


@register("reduce", "mpich")
def reduce_mpich(comm, sendobj, op: Op, root: int = 0):
    """smpi_mpich_selector.cpp:356-390."""
    message_size = payload_size(sendobj, None)
    pof2 = _pof2_below(comm.size())
    count = len(sendobj) if hasattr(sendobj, "__len__") else 1
    if count < pof2 or message_size < 2048 or not op.is_commutative():
        return dispatch_name("reduce", "binomial")(comm, sendobj, op, root)
    return dispatch_name("reduce", "scatter_gather")(comm, sendobj, op,
                                                     root)


@register("reduce_scatter", "mpich")
def reduce_scatter_mpich(comm, sendobjs, op: Op):
    """smpi_mpich_selector.cpp:439-482. The threshold is over total
    *element counts* (the reference sums rcounts, never multiplied by
    the datatype size)."""
    total = sum(len(o) if hasattr(o, "__len__") else 1 for o in sendobjs)
    if op.is_commutative() and total > 524288:
        return dispatch_name("reduce_scatter",
                             "mpich_pair")(comm, sendobjs, op)
    if not op.is_commutative():
        sizes = [payload_size(o, None) for o in sendobjs]
        regular = all(s == sizes[0] for s in sizes)
        if _is_pof2(comm.size()) and regular:
            return dispatch_name("reduce_scatter",
                                 "mpich_noncomm")(comm, sendobjs, op)
    return dispatch_name("reduce_scatter", "mpich_rdb")(comm, sendobjs, op)


@register("allgather", "mpich")
def allgather_mpich(comm, sendobj):
    """smpi_mpich_selector.cpp:535-570."""
    size = comm.size()
    total_dsize = payload_size(sendobj, None) * size
    if _is_pof2(size) and total_dsize < 524288:
        return dispatch_name("allgather", "rdb")(comm, sendobj)
    if total_dsize <= 81920:
        return dispatch_name("allgather", "bruck")(comm, sendobj)
    return dispatch_name("allgather", "ring")(comm, sendobj)


@register("gather", "mpich")
def gather_mpich(comm, sendobj, root: int = 0):
    """smpi_mpich_selector.cpp:671-683: always ompi_binomial."""
    return dispatch_name("gather", "ompi_binomial")(comm, sendobj, root)


@register("scatter", "mpich")
def scatter_mpich(comm, sendobjs, root: int = 0):
    """smpi_mpich_selector.cpp:706-723: always ompi_binomial."""
    _require_symmetric(sendobjs, "scatter")
    return dispatch_name("scatter", "ompi_binomial")(comm, sendobjs, root)


# ---------------------------------------------------------------------------
# ompi (smpi_openmpi_selector.cpp)
# ---------------------------------------------------------------------------

@register("allreduce", "ompi")
def allreduce_ompi(comm, sendobj, op: Op):
    """smpi_openmpi_selector.cpp:14-56."""
    size = comm.size()
    block_dsize = payload_size(sendobj, None)
    count = len(sendobj) if hasattr(sendobj, "__len__") else 1
    if block_dsize < 10000:
        return dispatch_name("allreduce", "rdb")(comm, sendobj, op)
    if op.is_commutative() and count > size:
        if size * (1 << 20) >= block_dsize:
            return dispatch_name("allreduce", "lr")(comm, sendobj, op)
        return dispatch_name("allreduce",
                             "ompi_ring_segmented")(comm, sendobj, op)
    return dispatch_name("allreduce", "redbcast")(comm, sendobj, op)


@register("alltoall", "ompi")
def alltoall_ompi_selector(comm, sendobjs):
    """smpi_openmpi_selector.cpp:58-89."""
    size = comm.size()
    block_dsize = payload_size(sendobjs[0], None) if sendobjs else 0
    if block_dsize < 200 and size > 12:
        return dispatch_name("alltoall", "bruck")(comm, sendobjs)
    if block_dsize < 3000:
        return dispatch_name("alltoall", "basic_linear")(comm, sendobjs)
    return dispatch_name("alltoall", "ring")(comm, sendobjs)


@register("barrier", "ompi")
def barrier_ompi(comm):
    """smpi_openmpi_selector.cpp:105-124."""
    size = comm.size()
    if size == 2:
        return dispatch_name("barrier", "ompi_two_procs")(comm)
    if _is_pof2(size):
        return dispatch_name("barrier", "ompi_recursivedoubling")(comm)
    return dispatch_name("barrier", "ompi_bruck")(comm)


@register("bcast", "ompi")
def bcast_ompi(comm, obj, root: int = 0):
    """smpi_openmpi_selector.cpp:126-199 (segment sizes are folded into
    the single pipeline implementation)."""
    _require_symmetric(obj, "bcast")
    size = comm.size()
    message_size = payload_size(obj, None)
    count = len(obj) if hasattr(obj, "__len__") else 1
    if message_size < 2048 or count <= 1:
        return dispatch_name("bcast", "binomial_tree")(comm, obj, root)
    if message_size < 370728:
        return dispatch_name("bcast",
                             "ompi_split_bintree")(comm, obj, root)
    if size < (1.6134e-6 * message_size + 2.1102):
        return dispatch_name("bcast", "ompi_pipeline")(comm, obj, root)
    if size < 13:
        return dispatch_name("bcast",
                             "ompi_split_bintree")(comm, obj, root)
    if size < (2.3679e-6 * message_size + 1.1787) or \
            size < (3.2118e-6 * message_size + 8.7936):
        return dispatch_name("bcast", "ompi_pipeline")(comm, obj, root)
    return dispatch_name("bcast", "flattree_pipeline")(comm, obj, root)


@register("reduce", "ompi")
def reduce_ompi_selector(comm, sendobj, op: Op, root: int = 0):
    """smpi_openmpi_selector.cpp:227-302."""
    size = comm.size()
    message_size = payload_size(sendobj, None)
    if not op.is_commutative():
        if size < 12 and message_size < 2048:
            return dispatch_name("reduce",
                                 "ompi_basic_linear")(comm, sendobj, op,
                                                      root)
        return dispatch_name("reduce",
                             "ompi_in_order_binary")(comm, sendobj, op,
                                                     root)
    count = len(sendobj) if hasattr(sendobj, "__len__") else 1
    if size < 8 and message_size < 512:
        return dispatch_name("reduce", "ompi_basic_linear")(comm, sendobj,
                                                            op, root)
    if (size < 8 and message_size < 20480) or message_size < 2048 \
            or count <= 1:
        return dispatch_name("reduce", "ompi_binomial")(comm, sendobj, op,
                                                        root)
    if size > (0.6016 / 1024.0 * message_size + 1.3496):
        return dispatch_name("reduce", "ompi_binomial")(comm, sendobj, op,
                                                        root)
    if size > (0.0410 / 1024.0 * message_size + 9.7128):
        return dispatch_name("reduce", "ompi_pipeline")(comm, sendobj, op,
                                                        root)
    if size > (0.0422 / 1024.0 * message_size + 1.1614):
        return dispatch_name("reduce", "ompi_binary")(comm, sendobj, op,
                                                      root)
    return dispatch_name("reduce", "ompi_pipeline")(comm, sendobj, op,
                                                    root)


@register("reduce_scatter", "ompi")
def reduce_scatter_ompi_selector(comm, sendobjs, op: Op):
    """smpi_openmpi_selector.cpp:330-373."""
    size = comm.size()
    total = sum(payload_size(o, None) for o in sendobjs)
    if not op.is_commutative():
        return dispatch_name("reduce_scatter",
                             "default")(comm, sendobjs, op)
    pof2 = _is_pof2(size)
    if total <= 12 * 1024 or (total <= 256 * 1024 and pof2) or \
            size >= 0.0012 * total + 8.0:
        return dispatch_name(
            "reduce_scatter",
            "ompi_basic_recursivehalving")(comm, sendobjs, op)
    return dispatch_name("reduce_scatter", "ompi_ring")(comm, sendobjs, op)


@register("allgather", "ompi")
def allgather_ompi(comm, sendobj):
    """smpi_openmpi_selector.cpp:384-427."""
    size = comm.size()
    if size == 2:
        return dispatch_name("allgather", "pair")(comm, sendobj)
    total_dsize = payload_size(sendobj, None) * size
    if total_dsize < 50000:
        if _is_pof2(size):
            return dispatch_name("allgather", "rdb")(comm, sendobj)
        return dispatch_name("allgather", "bruck")(comm, sendobj)
    if size % 2:
        return dispatch_name("allgather", "ring")(comm, sendobj)
    return dispatch_name("allgather",
                         "ompi_neighborexchange")(comm, sendobj)


@register("gather", "ompi")
def gather_ompi(comm, sendobj, root: int = 0):
    """smpi_openmpi_selector.cpp:511-556 (the large-block linear_sync
    branch included)."""
    size = comm.size()
    block_size = payload_size(sendobj, None)
    if block_size > 6000:
        return dispatch_name("gather", "ompi_linear_sync")(comm, sendobj,
                                                           root)
    if size > 60 or (size > 10 and block_size < 1024):
        return dispatch_name("gather", "ompi_binomial")(comm, sendobj,
                                                        root)
    return dispatch_name("gather", "ompi_basic_linear")(comm, sendobj,
                                                        root)


@register("scatter", "ompi")
def scatter_ompi(comm, sendobjs, root: int = 0):
    """smpi_openmpi_selector.cpp:571-603."""
    _require_symmetric(sendobjs, "scatter")
    size = comm.size()
    block_size = payload_size(sendobjs[0], None) if sendobjs else 0
    if size > 10 and block_size < 300:
        return dispatch_name("scatter", "ompi_binomial")(comm, sendobjs,
                                                         root)
    return dispatch_name("scatter", "ompi_basic_linear")(comm, sendobjs,
                                                         root)
