"""SMPI time-independent trace replay.

Re-implements the reference's replay engine
(src/smpi/internals/smpi_replay.cpp): each rank actor reads its action
stream (one file per rank, or a merged file whose lines start with the
rank id — src/xbt/xbt_replay.cpp queues per-rank), parses args with the
same grammars (smpi_replay.cpp:143-200), and executes the corresponding
MPI calls with dummy payloads sized by count x datatype. Asynchronous
requests live in a per-rank RequestStorage keyed by (src, dst, tag)
(smpi_replay.cpp:87-140).

Replay is the fast path for studying real applications: the network/
compute timings come entirely from the simulated platform, so a 16-rank
allreduce trace replays in milliseconds while exercising the full
collective + LMM stack (BASELINE config #1).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import datatype as dt
from .request import MPI_ANY_SOURCE, Request, Status


def _parse_double(s: str) -> float:
    return float(s)


def _buf(nbytes: float):
    """Replay payloads only need a wire size; a tiny ndarray views work
    as well as the reference's shared send/recv scratch buffers
    (smpi_replay.cpp send_buffer/recv_buffer)."""
    return None


class RequestStorage:
    """Pending request registry keyed (src, dst, tag) in world ranks
    (smpi_replay.cpp:87-140)."""

    def __init__(self):
        self.store: Dict[Tuple[int, int, int], Optional[Request]] = {}

    def find(self, src: int, dst: int, tag: int) -> Optional[Request]:
        return self.store.get((src, dst, tag))

    def remove(self, key: Tuple[int, int, int]) -> None:
        self.store.pop(key, None)

    def add(self, req: Request) -> None:
        if req is not None:
            self.store[(req.src, req.dst, req.tag)] = req

    def add_null(self, src: int, dst: int, tag: int) -> None:
        self.store[(src, dst, tag)] = None

    def all_requests(self) -> List[Request]:
        return [r for r in self.store.values() if r is not None]

    def clear(self) -> None:
        self.store.clear()


class ReplayContext:
    """Per-rank replay state: request storage + the default datatype
    chosen by the init action (MPE double vs TAU byte)."""

    def __init__(self, comm):
        self.comm = comm
        self.storage = RequestStorage()
        self.default_type = dt.MPI_BYTE

    def decode(self, token: Optional[str]) -> dt.Datatype:
        return dt.decode(token) if token else self.default_type


ActionHandler = Callable[[ReplayContext, List[str]], None]
_handlers: Dict[str, ActionHandler] = {}
#: out-of-band per-rank checkpoint staging (replay_main)
_ckpt_pending: Dict[str, dict] = {}


def action(name: str):
    def deco(fn: ActionHandler) -> ActionHandler:
        _handlers[name] = fn
        return fn
    return deco


# ---------------------------------------------------------------------------
# Action kernels (smpi_replay.cpp:398-700). action[0]=rank, action[1]=name.
# ---------------------------------------------------------------------------

@action("init")
def _init(ctx, args):
    # action[2] selects the MPE flavor whose default datatype is double
    # (InitAction::kernel, smpi_replay.cpp:514-520).
    ctx.default_type = dt.MPI_DOUBLE if len(args) > 2 else dt.MPI_BYTE


@action("finalize")
def _finalize(ctx, args):
    pass


@action("comm_size")
def _comm_size(ctx, args):
    pass  # communicator actions only sync in the reference too


@action("comm_split")
def _comm_split(ctx, args):
    pass


@action("comm_dup")
def _comm_dup(ctx, args):
    pass


@action("compute")
def _compute(ctx, args):
    from .runtime import smpi_execute_flops
    smpi_execute_flops(_parse_double(args[2]))


@action("sleep")
def _sleep(ctx, args):
    from ..s4u import this_actor
    this_actor.sleep_for(_parse_double(args[2]))


@action("send")
def _send(ctx, args):
    partner, tag = int(args[2]), int(args[3])
    size = _parse_double(args[4])
    datatype = ctx.decode(args[5] if len(args) > 5 else None)
    ctx.comm.send(_buf(size), partner, tag, count=int(size),
                  datatype=datatype)


@action("isend")
def _isend(ctx, args):
    partner, tag = int(args[2]), int(args[3])
    size = _parse_double(args[4])
    datatype = ctx.decode(args[5] if len(args) > 5 else None)
    req = ctx.comm.isend(_buf(size), partner, tag, count=int(size),
                         datatype=datatype)
    ctx.storage.add(req)


@action("recv")
def _recv(ctx, args):
    partner, tag = int(args[2]), int(args[3])
    size = _parse_double(args[4]) if len(args) > 4 else -1.0
    datatype = ctx.decode(args[5] if len(args) > 5 else None)
    count = int(size) if size > 0 else None
    ctx.comm.recv(partner, tag, count=count,
                  datatype=datatype if size > 0 else None)


@action("irecv")
def _irecv(ctx, args):
    partner, tag = int(args[2]), int(args[3])
    size = _parse_double(args[4]) if len(args) > 4 else -1.0
    datatype = ctx.decode(args[5] if len(args) > 5 else None)
    req = ctx.comm.irecv(partner, tag,
                         count=int(size) if size > 0 else None,
                         datatype=datatype if size > 0 else None)
    ctx.storage.add(req)


@action("test")
def _test(ctx, args):
    src, dst, tag = int(args[2]), int(args[3]), int(args[4])
    req = ctx.storage.find(src, dst, tag)
    ctx.storage.remove((src, dst, tag))
    if req is not None:
        if req.test(Status()):
            ctx.storage.add_null(src, dst, tag)
        else:
            ctx.storage.add(req)


@action("wait")
def _wait(ctx, args):
    src, dst, tag = int(args[2]), int(args[3]), int(args[4])
    req = ctx.storage.find(src, dst, tag)
    ctx.storage.remove((src, dst, tag))
    if req is None:
        # Possibly completed by an earlier test (WaitAction::kernel).
        return
    req.wait(Status())


@action("waitall")
def _waitall(ctx, args):
    reqs = ctx.storage.all_requests()
    ctx.storage.clear()
    if reqs:
        Request.waitall(reqs)


@action("barrier")
def _barrier(ctx, args):
    ctx.comm.barrier()


@action("bcast")
def _bcast(ctx, args):
    size = _parse_double(args[2])
    root = int(args[3]) if len(args) > 3 else 0
    datatype = ctx.decode(args[4] if len(args) > 4 else None)
    ctx.comm.bcast(_payload(size, datatype), root=root)


@action("reduce")
def _reduce(ctx, args):
    comm_size = _parse_double(args[2])
    comp_size = _parse_double(args[3])
    root = int(args[4]) if len(args) > 4 else 0
    datatype = ctx.decode(args[5] if len(args) > 5 else None)
    from .op import MPI_SUM
    from .runtime import smpi_execute_flops
    ctx.comm.reduce(_payload(comm_size, datatype), MPI_SUM, root=root)
    smpi_execute_flops(comp_size)


@action("allreduce")
def _allreduce(ctx, args):
    comm_size = _parse_double(args[2])
    comp_size = _parse_double(args[3])
    datatype = ctx.decode(args[4] if len(args) > 4 else None)
    from .op import MPI_SUM
    from .runtime import smpi_execute_flops
    ctx.comm.allreduce(_payload(comm_size, datatype), MPI_SUM)
    smpi_execute_flops(comp_size)


@action("alltoall")
def _alltoall(ctx, args):
    send_size = _parse_double(args[2])
    recv_size = _parse_double(args[3]) if len(args) > 3 else send_size
    datatype = ctx.decode(args[4] if len(args) > 4 else None)
    n = ctx.comm.size()
    ctx.comm.alltoall([_payload(send_size, datatype) for _ in range(n)])


@action("gather")
def _gather(ctx, args):
    send_size = _parse_double(args[2])
    root = int(args[4]) if len(args) > 4 else 0
    datatype = ctx.decode(args[5] if len(args) > 5 else None)
    ctx.comm.gather(_payload(send_size, datatype), root=root)


@action("allgather")
def _allgather(ctx, args):
    send_size = _parse_double(args[2])
    datatype = ctx.decode(args[4] if len(args) > 4 else None)
    ctx.comm.allgather(_payload(send_size, datatype))


@action("scatter")
def _scatter(ctx, args):
    send_size = _parse_double(args[2])
    root = int(args[4]) if len(args) > 4 else 0
    datatype = ctx.decode(args[5] if len(args) > 5 else None)
    n = ctx.comm.size()
    # Every rank passes the full (same-shaped) list: size-staged
    # selectors need the message size everywhere (the MPI count
    # contract); non-root payloads are never shipped.
    objs = [_payload(send_size, datatype) for _ in range(n)]
    ctx.comm.scatter(objs, root=root)


@action("reducescatter")
def _reducescatter(ctx, args):
    # "reducescatter 0 <recvcounts x n> <comp_size> <datatype>"
    # (ReduceScatterArgParser, smpi_replay.cpp:330-346).
    n = ctx.comm.size()
    recvcounts = [int(args[3 + i]) for i in range(n)]
    comp_size = _parse_double(args[3 + n]) if len(args) > 3 + n else 0.0
    from .op import MPI_SUM
    from .runtime import smpi_execute_flops
    ctx.comm.reduce_scatter(
        [np.zeros(max(c // 8, 1)) for c in recvcounts], MPI_SUM)
    smpi_execute_flops(comp_size)


@action("allgatherv")
def _allgatherv(ctx, args):
    # our TI writer emits "allgatherv <send_size> <st> <rt>"
    send_size = _parse_double(args[2])
    ctx.comm.allgatherv(_payload(send_size, dt.MPI_BYTE))


@action("gatherv")
def _gatherv(ctx, args):
    # "gatherv <send_size> <root> <st> <rt>" (root printed when >= 0)
    send_size = _parse_double(args[2])
    root = int(args[3]) if len(args) > 3 and args[3].isdigit() else 0
    ctx.comm.gatherv(_payload(send_size, dt.MPI_BYTE), root=root)


@action("scatterv")
def _scatterv(ctx, args):
    # "scatterv <sendcounts x n> <root> <st> <rt>"
    n = ctx.comm.size()
    counts = [int(float(args[2 + i])) for i in range(n)]
    root = int(args[2 + n]) if len(args) > 2 + n and \
        args[2 + n].lstrip("-").isdigit() else 0
    objs = [_payload(c, dt.MPI_BYTE) for c in counts]
    ctx.comm.scatterv(objs, root=max(root, 0))


@action("alltoallv")
def _alltoallv(ctx, args):
    # send_buf_size, n sendcounts, recv_buf_size, n recvcounts
    # (AllToAllVArgParser, smpi_replay.cpp:370-396).
    n = ctx.comm.size()
    sendcounts = [int(args[3 + i]) for i in range(n)]
    datatype = ctx.decode(args[4 + 2 * n] if len(args) > 5 + 2 * n
                          else None)
    ctx.comm.alltoall([_payload(c, datatype) for c in sendcounts])


def _payload(count: float, datatype: dt.Datatype):
    """A dummy payload whose wire size is exactly count x datatype bytes
    (byte-granular so chunking algorithms split like the reference)."""
    return np.zeros(max(int(count * datatype.size()), 1), np.uint8)


# ---------------------------------------------------------------------------
# Trace reading (xbt_replay.cpp): merged file => per-rank queues.
# ---------------------------------------------------------------------------

def _actions_for_rank(trace_path: str, rank: int) -> List[List[str]]:
    """Read this rank's action list. trace_path may be (a) a merged
    action file whose lines start with the rank, (b) a file listing one
    action file per rank (what the TI tracer emits as master file), or
    (c) a per-rank file directly."""
    with open(trace_path) as f:
        first = f.readline().split()
    if first and len(first) == 1 and os.path.exists(first[0]):
        # (b) master list: one path per rank. Containers are created in
        # first-touch order (a send arrow can pre-create a peer's file),
        # so the list is NOT rank-ordered — match by the rank-N filename
        # the TI tracer uses, falling back to list position for
        # foreign-named files.
        with open(trace_path) as f:
            paths = f.read().split()
        wanted = f"rank-{rank}.txt"
        path = next((p for p in paths
                     if os.path.basename(p) == wanted), None)
        if path is None:
            path = paths[rank]
        with open(path) as f:
            return [l.split() for l in f if l.strip()
                    and not l.startswith("#")]
    actions = []
    with open(trace_path) as f:
        for line in f:
            parts = line.split("#", 1)[0].split()
            if parts and parts[0] == str(rank):
                actions.append(parts)
    return actions


def replay_main(trace_path: str, checkpoint_file: Optional[str] = None,
                resume_from: Optional[dict] = None) -> None:
    """The per-rank replay actor body (smpi_replay_main).

    Checkpoint/resume (the SURVEY §5 upgrade over the reference, which
    has no user-facing simulation checkpointing): a ``checkpoint``
    action in the trace barriers all ranks — a globally quiescent point
    with no traffic in flight — and dumps {clock, per-rank action
    index} to ``checkpoint_file``. Resuming replays the same trace on a
    fresh engine with each rank fast-forwarded past its recorded index
    and the clock pre-advanced, reaching the identical final timestamp
    as an uninterrupted run (determinism makes the state at a quiescent
    point a pure function of (trace, index, clock))."""
    import json

    from . import runtime
    comm = runtime.world()
    rank = comm.rank()
    ctx = ReplayContext(comm)
    actions = _actions_for_rank(trace_path, rank)
    start_index = 0
    if resume_from is not None:
        mine = resume_from["ranks"][str(rank)]
        start_index = mine["index"]
        # Re-establish this rank's local clock: at a quiescent point
        # the per-rank state is exactly (position, local time) — ranks
        # exit the checkpoint barrier at different times and must
        # resume at their own.
        from ..s4u import this_actor
        if mine["clock"] > 0:
            this_actor.sleep_for(mine["clock"])
    for index, act in enumerate(actions):
        if index < start_index:
            continue
        name = act[1]
        if name == "checkpoint":
            comm.barrier()
            if checkpoint_file is not None:
                # Out-of-band state capture (no simulated cost — the
                # checkpointer observes the simulation from outside,
                # like the reference MC reads the MCed process): each
                # rank records (next index, local clock); the last one
                # writes the file.
                _ckpt_pending[str(rank)] = {"index": index + 1,
                                            "clock": runtime.wtime()}
                if len(_ckpt_pending) == comm.size():
                    with open(checkpoint_file, "w") as f:
                        json.dump({"ranks": dict(_ckpt_pending)}, f)
                    _ckpt_pending.clear()
            continue
        handler = _handlers.get(name)
        assert handler is not None, f"Replay action '{name}' unknown"
        handler(ctx, act)
    # Drain leftover async requests (smpi_replay_main:783-800).
    leftovers = ctx.storage.all_requests()
    if leftovers:
        Request.waitall(leftovers)


def smpi_replay_run(platform: str, trace_path: str, np_ranks: int,
                    configs=(), checkpoint_file: Optional[str] = None,
                    resume_from: Optional[str] = None):
    """Replay a TI trace end-to-end: build engine + ranks, run, return
    the engine (inspect .clock for the simulated makespan).

    ``checkpoint_file`` records the state at the trace's `checkpoint`
    action; ``resume_from`` restarts from such a file (fresh engine,
    clock pre-advanced, ranks fast-forwarded)."""
    import json

    from .runtime import smpirun

    state = None
    if resume_from is not None:
        with open(resume_from) as f:
            state = json.load(f)
    _ckpt_pending.clear()   # an aborted run must not leak staged state
    return smpirun(lambda: replay_main(trace_path, checkpoint_file, state),
                   platform, np=np_ranks, configs=list(configs))
