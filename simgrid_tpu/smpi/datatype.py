"""MPI datatypes (reference src/smpi/mpi/smpi_datatype.cpp).

A datatype carries its wire size (what the network model charges) and,
when it maps to a numpy dtype, the element type used by reduction ops.
Derived types (contiguous/vector/indexed/struct) compute their size and
extent like the reference; data movement itself ships whole Python
payloads, so pack/unpack layout juggling is unnecessary in simulation.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class Datatype:
    def __init__(self, size: int, np_dtype=None, name: str = "",
                 extent: Optional[int] = None):
        self.size_ = size          # bytes per element on the wire
        self.np_dtype = np_dtype
        self.name = name
        self.extent_ = extent if extent is not None else size
        self.committed = False

    def size(self) -> int:
        return self.size_

    def extent(self) -> int:
        return self.extent_

    def commit(self) -> "Datatype":
        self.committed = True
        return self

    def dup(self) -> "Datatype":
        return Datatype(self.size_, self.np_dtype, self.name, self.extent_)

    def __repr__(self):
        return f"<Datatype {self.name or self.size_}B>"

    # -- derived constructors (smpi_datatype.cpp create_*) ----------------
    @staticmethod
    def create_contiguous(count: int, base: "Datatype") -> "Datatype":
        return Datatype(count * base.size_, base.np_dtype,
                        f"contig({count},{base.name})",
                        count * base.extent_)

    @staticmethod
    def create_vector(count: int, blocklen: int, stride: int,
                      base: "Datatype") -> "Datatype":
        size = count * blocklen * base.size_
        extent = ((count - 1) * stride + blocklen) * base.extent_
        return Datatype(size, base.np_dtype,
                        f"vector({count},{blocklen},{stride})", extent)

    @staticmethod
    def create_indexed(blocklens: List[int], displs: List[int],
                       base: "Datatype") -> "Datatype":
        size = sum(blocklens) * base.size_
        extent = (max((d + b) for d, b in zip(displs, blocklens))
                  * base.extent_) if blocklens else 0
        return Datatype(size, base.np_dtype, "indexed", extent)

    @staticmethod
    def create_struct(blocklens: List[int], displs: List[int],
                      types: List["Datatype"]) -> "Datatype":
        size = sum(b * t.size_ for b, t in zip(blocklens, types))
        extent = max((d + b * t.extent_)
                     for d, b, t in zip(displs, blocklens, types)) \
            if blocklens else 0
        return Datatype(size, None, "struct", extent)


MPI_BYTE = Datatype(1, np.uint8, "MPI_BYTE")
MPI_CHAR = Datatype(1, np.int8, "MPI_CHAR")
MPI_SHORT = Datatype(2, np.int16, "MPI_SHORT")
MPI_INT = Datatype(4, np.int32, "MPI_INT")
MPI_UNSIGNED = Datatype(4, np.uint32, "MPI_UNSIGNED")
MPI_LONG = Datatype(8, np.int64, "MPI_LONG")
MPI_UNSIGNED_LONG = Datatype(8, np.uint64, "MPI_UNSIGNED_LONG")
MPI_FLOAT = Datatype(4, np.float32, "MPI_FLOAT")
MPI_DOUBLE = Datatype(8, np.float64, "MPI_DOUBLE")
# (value, index) pairs for MAXLOC/MINLOC
MPI_DOUBLE_INT = Datatype(12, None, "MPI_DOUBLE_INT")

# Trace ids: the numeric datatype codes used in TI traces, matching the
# reference's id2type registry (smpi_datatype.cpp:37-66) so traces are
# interchangeable with the reference's replay engine.
_TRACE_IDS = {
    "MPI_DOUBLE": "0", "MPI_INT": "1", "MPI_CHAR": "2", "MPI_SHORT": "3",
    "MPI_LONG": "4", "MPI_FLOAT": "5", "MPI_BYTE": "6",
    "MPI_UNSIGNED": "11", "MPI_UNSIGNED_LONG": "12",
    "MPI_DOUBLE_INT": "32",
}
_ID_TO_TYPE = {}


def encode(datatype: Optional[Datatype]) -> str:
    """Datatype -> trace id (Datatype::encode)."""
    if datatype is None:
        return _TRACE_IDS["MPI_DOUBLE"]
    return _TRACE_IDS.get(datatype.name, "6")


def decode(datatype_id: str) -> Datatype:
    """Trace id (or name) -> Datatype (Datatype::decode); unknown ids
    fall back to MPI_BYTE like unrecognized TAU trace types."""
    if not _ID_TO_TYPE:
        by_name = {name: obj for name, obj in globals().items()
                   if isinstance(obj, Datatype)}
        for name, tid in _TRACE_IDS.items():
            _ID_TO_TYPE[tid] = by_name[name]
            _ID_TO_TYPE[name] = by_name[name]
    return _ID_TO_TYPE.get(datatype_id, MPI_BYTE)


def payload_size(payload, datatype: Optional[Datatype]) -> float:
    """Wire size of a payload: count * datatype size for arrays, or a
    best-effort estimate for plain Python objects."""
    if isinstance(payload, np.ndarray):
        if datatype is not None:
            return payload.size * datatype.size_
        return payload.nbytes
    if datatype is not None:
        try:
            return len(payload) * datatype.size_
        except TypeError:
            return datatype.size_
    if isinstance(payload, (bytes, bytearray)):
        return len(payload)
    return 8.0
