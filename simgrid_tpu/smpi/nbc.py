"""Non-blocking collectives (reference src/smpi/internals/
smpi_nbc_impl.cpp): each I-collective posts its whole point-to-point
pattern immediately (the reference NBC implementations are the flat/
linear algorithms precisely so every request can be posted up front)
and returns a request completed by wait/test, with the reduction
applied at completion time."""

from __future__ import annotations

from typing import Callable, List, Optional

from .op import MPI_SUM, Op
from .request import Request

# NBC tags live in their own reserved range so an outstanding
# I-collective never cross-matches a concurrent *blocking* collective
# on the same communicator (the reference keeps separate system tags
# for its nbc implementations too).
TAG_IBARRIER = -111
TAG_IBCAST = -110
TAG_IREDUCE = -112
TAG_IALLREDUCE = -113
TAG_IALLTOALL = -114
TAG_IGATHER = -115
TAG_IALLGATHER = -116
TAG_ISCATTER = -117


class NbcRequest:
    """A collective-in-flight: sub-requests + a completion combiner."""

    def __init__(self, sends: List[Request], recvs: List[Request],
                 finish: Optional[Callable[[List], object]] = None):
        self._sends = sends
        self._recvs = recvs
        self._finish = finish
        self.finished = False
        self._result = None

    def wait(self):
        if self.finished:
            return self._result
        data = [r.wait() for r in self._recvs]
        for r in self._sends:
            r.wait()
        self.finished = True
        if self._finish is not None:
            self._result = self._finish(data)
        return self._result

    def test(self) -> bool:
        if self.finished:
            return True
        if all(r.finished or r.test() for r in self._recvs + self._sends):
            self.wait()
            return True
        return False


from .intercomm import MPI_PROC_NULL, MPI_ROOT


def _is_inter(comm) -> bool:
    return getattr(comm, "is_inter", lambda: False)()


def _fold(op: Op, data):
    """Reduce received contributions in ascending-rank order (keeps
    non-commutative ops deterministic)."""
    result = data[-1]
    for i in range(len(data) - 2, -1, -1):
        result = op(data[i], result)
    return result


def ibarrier(comm) -> NbcRequest:
    """Flat ibarrier (smpi_nbc_impl.cpp ibarrier): everyone -> 0, then
    0 -> everyone; all requests posted now."""
    if _is_inter(comm):
        # intercomm barrier: full flat exchange with the remote group
        # (p2p on an InterComm addresses the remote side), completing
        # only after every remote rank has entered — all posted now
        nrem = comm.remote_size()
        sends = [comm.isend(b"", dst, TAG_IBARRIER)
                 for dst in range(nrem)]
        recvs = [comm.irecv(src, TAG_IBARRIER) for src in range(nrem)]
        return NbcRequest(sends, recvs, lambda _: None)
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return NbcRequest([], [])
    if rank == 0:
        recvs = [comm.irecv(src, TAG_IBARRIER) for src in range(1, size)]

        def finish(_):
            reqs = [comm.isend(b"", dst, TAG_IBARRIER)
                    for dst in range(1, size)]
            for r in reqs:
                r.wait()
        return NbcRequest([], recvs, finish)
    send = comm.isend(b"", 0, TAG_IBARRIER)
    recv = comm.irecv(0, TAG_IBARRIER)
    return NbcRequest([send], [recv], lambda _: None)


def ibcast(comm, obj, root: int = 0) -> NbcRequest:
    """Flat ibcast (smpi_nbc_impl.cpp ibcast): root isends to all."""
    if _is_inter(comm):
        # origin side: MPI_ROOT ships to every remote rank, other
        # origin ranks pass MPI_PROC_NULL and are complete immediately
        if root == MPI_ROOT:
            sends = [comm.isend(obj, dst, TAG_IBCAST)
                     for dst in range(comm.remote_size())]
            return NbcRequest(sends, [], lambda _: obj)
        if root == MPI_PROC_NULL:
            return NbcRequest([], [])
        recv = comm.irecv(root, TAG_IBCAST)   # root = remote rank
        return NbcRequest([], [recv], lambda data: data[0])
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return NbcRequest([], [], lambda _: obj)
    if rank == root:
        sends = [comm.isend(obj, dst, TAG_IBCAST)
                 for dst in range(size) if dst != root]
        return NbcRequest(sends, [], lambda _: obj)
    recv = comm.irecv(root, TAG_IBCAST)
    return NbcRequest([], [recv], lambda data: data[0])


def ireduce(comm, sendobj, op: Op = MPI_SUM, root: int = 0) -> NbcRequest:
    """Flat ireduce: root irecvs from all, folds at completion."""
    if _is_inter(comm):
        if root == MPI_ROOT:
            nrem = comm.remote_size()
            recvs = [comm.irecv(src, TAG_IREDUCE) for src in range(nrem)]
            return NbcRequest([], recvs, lambda data: _fold(op, data))
        if root == MPI_PROC_NULL:
            return NbcRequest([], [])
        return NbcRequest([comm.isend(sendobj, root, TAG_IREDUCE)], [],
                          lambda _: None)
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return NbcRequest([], [], lambda _: sendobj)
    if rank != root:
        return NbcRequest([comm.isend(sendobj, root, TAG_IREDUCE)], [],
                          lambda _: None)
    others = [src for src in range(size) if src != root]
    recvs = [comm.irecv(src, TAG_IREDUCE) for src in others]

    def finish(data):
        parts = [None] * size
        parts[root] = sendobj
        for src, d in zip(others, data):
            parts[src] = d
        result = parts[size - 1]
        for i in range(size - 2, -1, -1):
            result = op(parts[i], result)
        return result
    return NbcRequest([], recvs, finish)


def iallreduce(comm, sendobj, op: Op = MPI_SUM) -> NbcRequest:
    """Flat iallreduce: exchange with everyone, fold at completion
    (smpi_nbc_impl.cpp iallreduce)."""
    if _is_inter(comm):
        # MPI-2 intercomm allreduce: each side gets the reduction of
        # the OTHER side's data; flat cross-group exchange
        nrem = comm.remote_size()
        sends = [comm.isend(sendobj, dst, TAG_IALLREDUCE)
                 for dst in range(nrem)]
        recvs = [comm.irecv(src, TAG_IALLREDUCE) for src in range(nrem)]
        return NbcRequest(sends, recvs, lambda data: _fold(op, data))
    rank, size = comm.rank(), comm.size()
    if size == 1:
        return NbcRequest([], [], lambda _: sendobj)
    others = [r for r in range(size) if r != rank]
    sends = [comm.isend(sendobj, dst, TAG_IALLREDUCE) for dst in others]
    recvs = [comm.irecv(src, TAG_IALLREDUCE) for src in others]

    def finish(data):
        parts = [None] * size
        parts[rank] = sendobj
        for src, d in zip(others, data):
            parts[src] = d
        result = parts[size - 1]
        for i in range(size - 2, -1, -1):
            result = op(parts[i], result)
        return result
    return NbcRequest(sends, recvs, finish)


def igather(comm, sendobj, root: int = 0) -> NbcRequest:
    rank, size = comm.rank(), comm.size()
    if rank != root:
        return NbcRequest([comm.isend(sendobj, root, TAG_IGATHER)], [],
                          lambda _: None)
    others = [src for src in range(size) if src != root]
    recvs = [comm.irecv(src, TAG_IGATHER) for src in others]

    def finish(data):
        parts = [None] * size
        parts[root] = sendobj
        for src, d in zip(others, data):
            parts[src] = d
        return parts
    return NbcRequest([], recvs, finish)


def iscatter(comm, sendobjs, root: int = 0) -> NbcRequest:
    rank, size = comm.rank(), comm.size()
    if rank == root:
        sends = [comm.isend(sendobjs[dst], dst, TAG_ISCATTER)
                 for dst in range(size) if dst != root]
        return NbcRequest(sends, [], lambda _: sendobjs[root])
    recv = comm.irecv(root, TAG_ISCATTER)
    return NbcRequest([], [recv], lambda data: data[0])


def iallgather(comm, sendobj) -> NbcRequest:
    rank, size = comm.rank(), comm.size()
    others = [r for r in range(size) if r != rank]
    sends = [comm.isend(sendobj, dst, TAG_IALLGATHER) for dst in others]
    recvs = [comm.irecv(src, TAG_IALLGATHER) for src in others]

    def finish(data):
        parts = [None] * size
        parts[rank] = sendobj
        for src, d in zip(others, data):
            parts[src] = d
        return parts
    return NbcRequest(sends, recvs, finish)


def ialltoall(comm, sendobjs) -> NbcRequest:
    rank, size = comm.rank(), comm.size()
    others = [r for r in range(size) if r != rank]
    sends = [comm.isend(sendobjs[dst], dst, TAG_IALLTOALL)
             for dst in others]
    recvs = [comm.irecv(src, TAG_IALLTOALL) for src in others]

    def finish(data):
        parts = [None] * size
        parts[rank] = sendobjs[rank]
        for src, d in zip(others, data):
            parts[src] = d
        return parts
    return NbcRequest(sends, recvs, finish)


TAG_IREDUCE_SCATTER = -118
TAG_ISCAN = -119


def ireduce_scatter(comm, sendobjs, op: Op = MPI_SUM) -> NbcRequest:
    """Pairwise ireduce_scatter: ship the j-th segment to j, fold the
    n received contributions to my segment at completion."""
    rank, size = comm.rank(), comm.size()
    others = [r for r in range(size) if r != rank]
    sends = [comm.isend(sendobjs[dst], dst, TAG_IREDUCE_SCATTER)
             for dst in others]
    recvs = [comm.irecv(src, TAG_IREDUCE_SCATTER) for src in others]

    def finish(data):
        parts = [None] * size
        parts[rank] = sendobjs[rank]
        for src, d in zip(others, data):
            parts[src] = d
        result = parts[size - 1]
        for i in range(size - 2, -1, -1):
            result = op(parts[i], result)
        return result
    return NbcRequest(sends, recvs, finish)


def _iscan_impl(comm, sendobj, op: Op, exclusive: bool) -> NbcRequest:
    """Flat i(ex)scan: send to every higher rank, receive from every
    lower one, fold in rank order at completion.  O(n^2) messages but
    every request posts up front — the NBC contract (the reference's
    nbc scans use chained patterns; the flat shape is this rebuild's
    postable equivalent)."""
    rank, size = comm.rank(), comm.size()
    sends = [comm.isend(sendobj, dst, TAG_ISCAN)
             for dst in range(rank + 1, size)]
    lowers = list(range(rank))
    recvs = [comm.irecv(src, TAG_ISCAN) for src in lowers]

    def finish(data):
        acc = None
        for d in data:                 # ranks 0..rank-1, in order
            acc = d if acc is None else op(acc, d)
        if exclusive:
            return acc                 # rank 0: undefined (None)
        return sendobj if acc is None else op(acc, sendobj)
    return NbcRequest(sends, recvs, finish)


def iscan(comm, sendobj, op: Op = MPI_SUM) -> NbcRequest:
    return _iscan_impl(comm, sendobj, op, exclusive=False)


def iexscan(comm, sendobj, op: Op = MPI_SUM) -> NbcRequest:
    return _iscan_impl(comm, sendobj, op, exclusive=True)
