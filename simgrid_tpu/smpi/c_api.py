"""PMPI C bindings: run *unmodified* MPI C programs on the simulator.

Role equivalent of the reference's src/smpi/bindings/smpi_pmpi*.cpp +
smpicc + mmap privatization (smpi_global.cpp:540-608), redesigned for
this framework:

* ``tools/smpicc`` compiles the user's C sources into a shared object,
  renaming ``main`` to ``smpi_c_main`` and linking in one generic
  trampoline (native/smpi_shim.c) instead of 300 PMPI wrappers;
* every rank actor dlopens a PRIVATE COPY of that .so, giving each rank
  its own globals (.data/.bss) — in-process privatization without mmap
  games;
* every MPI call in C marshals its arguments into a flat array and
  forwards to ``_dispatch`` below, which runs on the rank's actor
  thread, translates handles, moves bytes between C buffers and numpy
  payloads, and issues the same Request/collective machinery the Python
  API uses (so algorithms, selectors, tracing and replay all apply);
* host compute between MPI calls is measured with a monotonic clock and
  injected as simulated flops, exactly the reference's bench loop
  (smpi_bench.cpp:53-78 smpi_bench_begin/end), honoring
  smpi/simulate-computation and smpi/cpu-threshold.

Known divergences (documented, by design):
* MPI_Abort returns to the caller (the callback boundary cannot
  longjmp over C frames); other ranks' subsequent MPI calls fail with
  MPI_ERR_OTHER and the simulation ends when mains return.
* An actor kill that lands while the rank executes C code terminates
  the MPI call with an error instead of unwinding the C stack.
"""

from __future__ import annotations

import copy as _copy
import ctypes
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.config import config
from . import op as _ops
from . import runtime
from .comm import Comm
from .datatype import Datatype
from .group import Group
from .op import Op
from .request import (MPI_ANY_SOURCE as PY_ANY_SOURCE,
                      MPI_ANY_TAG as PY_ANY_TAG, Request, Status)

# (smpi/simulate-computation is declared in utils/config.py)

# -- C-side constants (mirror include/smpi/mpi.h) ---------------------------
MPI_SUCCESS = 0
MPI_ERR_COMM = 1
MPI_ERR_ARG = 2
MPI_ERR_TYPE = 3
MPI_ERR_REQUEST = 4
MPI_ERR_INTERN = 5
MPI_ERR_OTHER = 16

C_ANY_SOURCE = -1
C_ANY_TAG = -1
C_PROC_NULL = -2
C_UNDEFINED = -32766
C_IN_PLACE = -222          # (void*)-222 seen as a signed long long

COMM_NULL, COMM_WORLD, COMM_SELF = 0, 1, 2

_i32 = ctypes.c_int
_pi32 = ctypes.POINTER(ctypes.c_int)
_pi64 = ctypes.POINTER(ctypes.c_longlong)


def _dt_struct(fields):
    return np.dtype(fields, align=True)


#: predefined datatype handles -> Datatype (sizes are the C ABI's)
_PREDEF_DTYPES: Dict[int, Datatype] = {}


def _predef(handle, size, np_dtype, name):
    _PREDEF_DTYPES[handle] = Datatype(size, np_dtype, name)


_predef(1, 1, np.uint8, "MPI_BYTE")
_predef(2, 1, np.int8, "MPI_CHAR")
_predef(3, 2, np.int16, "MPI_SHORT")
_predef(4, 4, np.int32, "MPI_INT")
_predef(5, 8, np.int64, "MPI_LONG")
_predef(6, 8, np.int64, "MPI_LONG_LONG")
_predef(7, 1, np.int8, "MPI_SIGNED_CHAR")
_predef(8, 1, np.uint8, "MPI_UNSIGNED_CHAR")
_predef(9, 2, np.uint16, "MPI_UNSIGNED_SHORT")
_predef(10, 4, np.uint32, "MPI_UNSIGNED")
_predef(11, 8, np.uint64, "MPI_UNSIGNED_LONG")
_predef(12, 8, np.uint64, "MPI_UNSIGNED_LONG_LONG")
_predef(13, 4, np.float32, "MPI_FLOAT")
_predef(14, 8, np.float64, "MPI_DOUBLE")
_predef(15, 16, np.longdouble, "MPI_LONG_DOUBLE")
_predef(16, 4, np.int32, "MPI_WCHAR")
_predef(17, 1, np.uint8, "MPI_C_BOOL")
_predef(18, 1, np.int8, "MPI_INT8_T")
_predef(19, 2, np.int16, "MPI_INT16_T")
_predef(20, 4, np.int32, "MPI_INT32_T")
_predef(21, 8, np.int64, "MPI_INT64_T")
_predef(22, 1, np.uint8, "MPI_UINT8_T")
_predef(23, 2, np.uint16, "MPI_UINT16_T")
_predef(24, 4, np.uint32, "MPI_UINT32_T")
_predef(25, 8, np.uint64, "MPI_UINT64_T")
# value+index pairs use the C struct layout (alignment padding and all),
# so MAXLOC/MINLOC see exactly what the C program wrote
_di = _dt_struct([("v", "<f8"), ("i", "<i4")])
_predef(26, _di.itemsize, _di, "MPI_DOUBLE_INT")
_fi = _dt_struct([("v", "<f4"), ("i", "<i4")])
_predef(27, _fi.itemsize, _fi, "MPI_FLOAT_INT")
_li = _dt_struct([("v", "<i8"), ("i", "<i4")])
_predef(28, _li.itemsize, _li, "MPI_LONG_INT")
_ii = _dt_struct([("v", "<i4"), ("i", "<i4")])
_predef(29, _ii.itemsize, _ii, "MPI_2INT")
_predef(30, 8, np.int64, "MPI_AINT")
_predef(31, 8, np.int64, "MPI_OFFSET")
_predef(32, 8, np.int64, "MPI_COUNT")
_predef(33, 1, np.uint8, "MPI_PACKED")
_predef(34, 16, np.complex128, "MPI_DOUBLE_COMPLEX")
_predef(35, 8, np.complex64, "MPI_COMPLEX")
_predef(36, 8, np.complex64, "MPI_C_FLOAT_COMPLEX")
_predef(37, 16, np.complex128, "MPI_C_DOUBLE_COMPLEX")
# np.clongdouble is already the full complex type
_predef(38, np.dtype(np.clongdouble).itemsize, np.clongdouble,
        "MPI_C_LONG_DOUBLE_COMPLEX")
_si = _dt_struct([("v", "<i2"), ("i", "<i4")])
_predef(39, _si.itemsize, _si, "MPI_SHORT_INT")
_ldi = _dt_struct([("v", np.longdouble), ("i", "<i4")])
_predef(40, _ldi.itemsize, _ldi, "MPI_LONG_DOUBLE_INT")
_predef(41, 0, None, "MPI_UB")      # legacy extent markers
_predef(42, 0, None, "MPI_LB")
# optional fixed-size / Fortran datatypes (mpi.h 43-61)
_predef(43, 4, np.float32, "MPI_REAL4")
_predef(44, 8, np.float64, "MPI_REAL8")
_predef(45, 16, np.longdouble, "MPI_REAL16")
_predef(46, 8, np.complex64, "MPI_COMPLEX8")
_predef(47, 16, np.complex128, "MPI_COMPLEX16")
_predef(48, 32, None, "MPI_COMPLEX32")
_predef(49, 1, np.int8, "MPI_INTEGER1")
_predef(50, 2, np.int16, "MPI_INTEGER2")
_predef(51, 4, np.int32, "MPI_INTEGER4")
_predef(52, 8, np.int64, "MPI_INTEGER8")
_predef(53, 16, None, "MPI_INTEGER16")
_predef(54, 4, np.float32, "MPI_REAL")
_predef(55, 4, np.int32, "MPI_INTEGER")
_predef(56, 4, np.int32, "MPI_LOGICAL")
_predef(57, 1, np.int8, "MPI_CHARACTER")
_r2 = _dt_struct([("a", "<f4"), ("b", "<f4")])
_predef(58, _r2.itemsize, _r2, "MPI_2REAL")
_d2 = _dt_struct([("a", "<f8"), ("b", "<f8")])
_predef(59, _d2.itemsize, _d2, "MPI_2DOUBLE_PRECISION")
_i2p = _dt_struct([("a", "<i4"), ("b", "<i4")])
_predef(60, _i2p.itemsize, _i2p, "MPI_2INTEGER")
_predef(61, 8, np.float64, "MPI_DOUBLE_PRECISION")

#: basic-element byte sizes within one extent, for the pair/composite
#: named types (MPI_Get_elements + external32 byte order need basic
#: granularity; plain named types are a single basic element)
_PREDEF_BASICS = {26: [8, 4], 27: [4, 4], 28: [8, 4], 29: [4, 4],
                  39: [2, 4], 40: [16, 4], 58: [4, 4], 59: [8, 8],
                  60: [4, 4], 34: [8, 8], 35: [4, 4], 36: [4, 4],
                  37: [8, 8], 38: [16, 16], 46: [4, 4], 47: [8, 8],
                  48: [16, 16]}
for _h, _b in _PREDEF_BASICS.items():
    _PREDEF_DTYPES[_h].c_basics = _b
# the value+index pair types are stored padded (C struct ABI) but their
# MPI size is the sum of the components (pairtype-size-extent)
for _h in (26, 27, 28, 29, 39, 40, 58, 59, 60):
    _PREDEF_DTYPES[_h].c_mpi_size = sum(_PREDEF_BASICS[_h])

# constructor combiners (mpi.h values)
(C_COMBINER_NAMED, C_COMBINER_DUP, C_COMBINER_CONTIGUOUS,
 C_COMBINER_VECTOR, C_COMBINER_HVECTOR, C_COMBINER_INDEXED,
 C_COMBINER_HINDEXED, C_COMBINER_INDEXED_BLOCK,
 C_COMBINER_HINDEXED_BLOCK, C_COMBINER_STRUCT, C_COMBINER_SUBARRAY,
 C_COMBINER_DARRAY, C_COMBINER_RESIZED) = range(1, 14)
C_DISTRIBUTE_BLOCK, C_DISTRIBUTE_CYCLIC, C_DISTRIBUTE_NONE = 121, 122, 123
C_DISTRIBUTE_DFLT_DARG = -49767


def _basics_of(dt: Datatype):
    """REPEATING PATTERN of basic-element byte sizes in typemap order
    (consumers cycle it, so homogeneous replication keeps the pattern
    compact — a 2^31-element type must not expand a per-element
    list)."""
    b = getattr(dt, "c_basics", None)
    if b is None:
        b = [dt.size_] if dt.size_ else []
    return b


def _align_of(dt: Datatype) -> int:
    """C alignment requirement (for the struct-extent epsilon)."""
    a = getattr(dt, "c_align", None)
    if a:
        return a
    b = _basics_of(dt)
    return min(max(b), 16) if b else 1

#: predefined op handles -> Op ("loc" ops resolved separately)
_PREDEF_OPS: Dict[int, Op] = {
    1: _ops.MPI_MAX, 2: _ops.MPI_MIN, 3: _ops.MPI_SUM, 4: _ops.MPI_PROD,
    5: _ops.MPI_LAND, 6: _ops.MPI_BAND, 7: _ops.MPI_LOR, 8: _ops.MPI_BOR,
    9: _ops.MPI_LXOR, 10: _ops.MPI_BXOR,
}
OP_MAXLOC, OP_MINLOC = 11, 12


def _loc_op(minloc: bool) -> Op:
    """MAXLOC/MINLOC over structured (value, index) arrays laid out as
    the C pair structs."""
    def fn(a, b):
        if minloc:
            take_b = (b["v"] < a["v"]) | ((b["v"] == a["v"])
                                          & (b["i"] < a["i"]))
        else:
            take_b = (b["v"] > a["v"]) | ((b["v"] == a["v"])
                                          & (b["i"] < a["i"]))
        out = a.copy()
        out[take_b] = b[take_b]
        return out
    return Op(fn, "MPI_MINLOC" if minloc else "MPI_MAXLOC")


_OP_MAXLOC_STRUCT = _loc_op(False)
_OP_MINLOC_STRUCT = _loc_op(True)


class _CRankCtx:
    """Per-rank handle tables + bench clock."""

    def __init__(self):
        self.comms: Dict[int, Comm] = {}
        self.next_comm = 10
        # per-rank copies: MPI_Type_set_name on a predefined type must
        # not leak across ranks or later programs in this process
        self.dtypes: Dict[int, Datatype] = {
            h: _copy.copy(d) for h, d in _PREDEF_DTYPES.items()}
        self.next_dtype = 100
        self.ops: Dict[int, Op] = dict(_PREDEF_OPS)
        self.next_op = 32
        self.reqs: Dict[int, "_CReq"] = {}
        self.next_req = 1
        # handle 1 = MPI_GROUP_EMPTY (mpi.h:45), predefined
        self.groups: Dict[int, Group] = {1: Group([])}
        self.next_group = 10
        self.files: Dict[int, object] = {}
        self.next_file = 1
        self.comm_attrs: Dict[int, Dict[int, int]] = {}
        self.type_attrs: Dict[int, Dict[int, int]] = {}
        self.keyvals: Dict[int, dict] = {}    # unified comm/type/win
        self.next_keyval = 64
        self.errhandlers: Dict[int, int] = {}  # handle -> C fn addr
        self.next_errh = 10       # 0=NULL 1=RETURN 2=FATAL predefined
        self.comm_errh: Dict[int, int] = {}
        self.user_err_strings: Dict[int, str] = {}
        self.user_err_class: Dict[int, int] = {}  # dyn code -> class
        self.last_used_code = 74  # MPI_ERR_LASTCODE (mpi.h:245)
        self.wins: Dict[int, dict] = {}
        self.next_win = 1
        self.messages: Dict[int, object] = {}     # MPI_Mprobe plucks
        self.next_msg = 1
        self.cart_topos: Dict[int, object] = {}
        self.graph_topos: Dict[int, object] = {}
        self.comm_names: Dict[int, str] = {}
        self.bench_t0: Optional[float] = None
        self.initialized = False
        self.finalized = False
        self.dead = False
        self.exit_code: Optional[int] = None


class _CReq:
    __slots__ = ("req", "c_addr", "arr", "kind", "dt", "post", "cap")

    def __init__(self, req, c_addr: int, arr, kind: str,
                 dt: Optional[Datatype] = None, post=None,
                 cap: Optional[int] = None):
        self.req = req
        self.c_addr = c_addr
        self.arr = arr
        self.kind = kind          # "send" | "recv" | "nbc"
        self.dt = dt
        self.post = post          # nbc: result -> C buffers copier
        self.cap = cap            # recv: posted-buffer byte limit


_ctxs: Dict[int, _CRankCtx] = {}


def _ctx() -> _CRankCtx:
    state = runtime.this_rank_state()
    key = id(state.actor_impl)
    ctx = _ctxs.get(key)
    if ctx is None:
        ctx = _ctxs[key] = _CRankCtx()
    return ctx


# ---------------------------------------------------------------------------
# Bench loop (smpi_bench.cpp:53-78)
# ---------------------------------------------------------------------------

def _now() -> float:
    import time
    return time.perf_counter()


def _bench_end(ctx: _CRankCtx) -> None:
    """Host time since the last MPI call returned -> simulated compute."""
    if ctx.bench_t0 is None:
        return
    elapsed = _now() - ctx.bench_t0
    ctx.bench_t0 = None
    if config["smpi/simulate-computation"]:
        runtime.smpi_execute(elapsed)


def _bench_begin(ctx: _CRankCtx) -> None:
    ctx.bench_t0 = _now()


# ---------------------------------------------------------------------------
# Buffer <-> numpy marshalling
# ---------------------------------------------------------------------------

def _dt(ctx: _CRankCtx, handle: int) -> Datatype:
    return ctx.dtypes[int(handle)]


class _StridedSegs:
    """Lazy (count x step)-strided repetition of an inner segment map.
    MPI_Count-scale types (datatype/large-count builds a vector of
    2^30 strided blocks) cannot afford the dense per-block list; this
    iterates on demand and answers bounds in closed form."""
    __slots__ = ("count", "step", "inner")

    def __init__(self, count, step, inner):
        self.count = count
        self.step = step
        self.inner = inner

    def __iter__(self):
        for b in range(self.count):
            base = b * self.step
            for off, n in self.inner:
                yield (base + off, n)

    def __len__(self):
        return self.count * len(self.inner)


def _seg_bounds(segs):
    """(min offset, max offset+len) without materializing a lazy map."""
    if isinstance(segs, _StridedSegs):
        ilo, ihi = _seg_bounds(segs.inner)
        span = (segs.count - 1) * segs.step if segs.count else 0
        return min(0, span) + ilo, max(0, span) + ihi
    if not segs:
        return 0, 0
    return (min(o for o, _ in segs), max(o + n for o, n in segs))


#: dense segment lists beyond this length switch to _StridedSegs
_SEG_CAP = 65536


def _coalesce(segs):
    """Merge adjacent (offset, nbytes) segments."""
    out = []
    for off, n in segs:
        if n <= 0:
            continue
        if out and out[-1][0] + out[-1][1] == off:
            out[-1] = (out[-1][0], out[-1][1] + n)
        else:
            out.append((off, n))
    return out


def _segments_of(dt: Datatype):
    """The datatype's TYPE MAP as contiguous byte segments within one
    extent (the MPI standard's (type, disp) map, compressed to bytes —
    smpi_datatype derived serialization role).  Derived constructors
    attach c_segments; anything without one is contiguous."""
    segs = getattr(dt, "c_segments", None)
    if segs is None:
        segs = [(0, dt.size_)] if dt.size_ else []
    return segs


def _is_contiguous(dt: Datatype) -> bool:
    segs = _segments_of(dt)
    return (dt.extent_ == dt.size_
            and not getattr(dt, "c_lb", 0)
            and (not segs or segs == [(0, dt.size_)]))


def _arr_in(addr: int, count: int, dt: Datatype):
    """Copy `count` elements out of the C buffer into a fresh PACKED
    numpy array, gathering through the datatype's type map (strided
    vectors, UB-padded structs, nested constructions)."""
    count = int(count)
    nbytes = count * dt.size_
    # addr 0 with a non-contiguous type is MPI_BOTTOM: the datatype's
    # absolute displacements (MPI_Get_address) are the real addresses
    if nbytes <= 0 or (addr == 0 and _is_contiguous(dt)):
        return np.zeros(0, dt.np_dtype if dt.np_dtype is not None
                        else np.uint8)
    if _is_contiguous(dt):
        # single writable copy (no bytes->bytearray double copy: the
        # pt2pt/large_message 2.16 GB payload goes through here)
        out = np.empty(int(nbytes), np.uint8)
        ctypes.memmove(out.ctypes.data, int(addr), int(nbytes))
        if (dt.np_dtype is not None
                and nbytes % np.dtype(dt.np_dtype).itemsize == 0):
            return out.view(dt.np_dtype)
        return out
    else:
        segs = _segments_of(dt)
        raw = bytearray()
        for e in range(count):
            base = int(addr) + e * dt.extent_
            for off, n in segs:
                raw += ctypes.string_at(base + off, n)
    if dt.np_dtype is not None and len(raw) % np.dtype(dt.np_dtype).itemsize == 0:
        return np.frombuffer(raw, dtype=dt.np_dtype)
    return np.frombuffer(raw, dtype=np.uint8)


def _arr_out(addr: int, arr, max_bytes: Optional[int] = None,
             dt: Optional[Datatype] = None) -> None:
    """Copy a packed numpy payload into the C buffer at `addr`,
    scattering through the datatype's type map (addr 0 = MPI_BOTTOM
    when the type carries absolute displacements)."""
    if arr is None or (addr == 0 and (dt is None or _is_contiguous(dt))):
        return
    a = np.ascontiguousarray(arr)
    data = a.tobytes()
    if dt is not None and dt.size_ and not _is_contiguous(dt):
        count = len(data) // dt.size_
        segs = _segments_of(dt)
        pos = 0
        for e in range(count):
            base = int(addr) + e * dt.extent_
            for off, n in segs:
                ctypes.memmove(base + off, data[pos:pos + n], n)
                pos += n
        rem = len(data) - pos
        if rem > 0:
            # partial trailing element: fill the typemap prefix
            base = int(addr) + count * dt.extent_
            for off, n in segs:
                take = min(n, rem)
                ctypes.memmove(base + off, data[pos:pos + take], take)
                pos += take
                rem -= take
                if rem <= 0:
                    break
        return
    n = len(data) if max_bytes is None else min(len(data), int(max_bytes))
    if n:
        ctypes.memmove(int(addr), data, n)


def _recv_buf(count: int, dt: Datatype):
    nbytes = int(count) * dt.size_
    if dt.np_dtype is not None:
        itemsize = np.dtype(dt.np_dtype).itemsize
        if nbytes % itemsize == 0:
            return np.zeros(nbytes // itemsize, dt.np_dtype)
    return np.zeros(nbytes, np.uint8)


#: sizeof(MPI_Status) in mpi.h (SOURCE, TAG, ERROR, cancelled_: ints;
#: count_: long long at offset 16) — array handlers MUST step by this
_STATUS_BYTES = 24


def _set_status(addr: int, src: int, tag: int, err: int, nbytes,
                cancelled: bool = False, keep_error: bool = True) -> None:
    """keep_error defaults True: the MPI standard (§3.7.3) allows the
    MPI_ERROR field to be written only by multi-completion calls
    (WAITALL/WAITSOME/TESTALL/TESTSOME) — mirror of MPICH's
    MPIR_Status_set_empty, which leaves MPI_ERROR untouched."""
    if addr == 0:
        return
    p = ctypes.cast(int(addr), _pi32)
    p[0] = int(src)
    p[1] = int(tag)
    if not keep_error:
        p[2] = int(err)
    p[3] = 1 if cancelled else 0
    try:
        ctypes.cast(int(addr) + 16, _pi64)[0] = int(nbytes)
    except (OverflowError, ValueError):
        ctypes.cast(int(addr) + 16, _pi64)[0] = 0


def _status_from(addr: int, st: Status) -> None:
    src = st.source if st.source != PY_ANY_SOURCE else C_ANY_SOURCE
    tag = st.tag if st.tag != PY_ANY_TAG else C_ANY_TAG
    _set_status(addr, src, tag, MPI_SUCCESS, st.count, st.cancelled)


def _write_i32(addr: int, value: int) -> None:
    if addr:
        ctypes.cast(int(addr), _pi32)[0] = int(value)


def _write_i64(addr: int, value: int) -> None:
    if addr:
        ctypes.cast(int(addr), _pi64)[0] = int(value)


def _read_i32s(addr: int, n: int) -> List[int]:
    p = ctypes.cast(int(addr), _pi32)
    return [p[i] for i in range(n)]


# ---------------------------------------------------------------------------
# Handle resolution
# ---------------------------------------------------------------------------

def _comm_of(ctx: _CRankCtx, handle: int) -> Optional[Comm]:
    handle = int(handle)
    if handle == COMM_WORLD:
        return runtime.world()
    if handle == COMM_SELF:
        comm = ctx.comms.get(COMM_SELF)
        if comm is None:
            me = runtime.this_rank()
            comm = Comm(Group([me]), id=("self", me))
            ctx.comms[COMM_SELF] = comm
        return comm
    return ctx.comms.get(handle)


def _new_comm_handle(ctx: _CRankCtx, comm: Optional[Comm],
                     parent: Optional[int] = None) -> int:
    if comm is None:
        return COMM_NULL
    h = ctx.next_comm
    ctx.next_comm += 1
    ctx.comms[h] = comm
    # every comm-creating call propagates the parent's error handler
    # (MPI-3 §8.3.1; Comm_dup additionally copies attributes)
    if parent is not None and int(parent) in ctx.comm_errh:
        ctx.comm_errh[h] = ctx.comm_errh[int(parent)]
    return h


def _op_of(ctx: _CRankCtx, handle: int, dt: Datatype,
           dt_handle: int = 0, count: Optional[int] = None) -> Op:
    handle = int(handle)
    if handle in (OP_MAXLOC, OP_MINLOC):
        if dt.np_dtype is not None and np.dtype(dt.np_dtype).names:
            return (_OP_MINLOC_STRUCT if handle == OP_MINLOC
                    else _OP_MAXLOC_STRUCT)
        return _ops.MPI_MINLOC if handle == OP_MINLOC else _ops.MPI_MAXLOC
    op = ctx.ops[handle]
    hint = getattr(op, "_dt_hint", None)
    if hint is not None:
        # user MPI_User_function: pass the real datatype handle and
        # element count through to the C callback
        hint["handle"] = int(dt_handle)
        hint["count"] = None if count is None else int(count)
    return op


_USER_OP_CFUNC = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p,
                                  _pi32, _pi32)


def _user_op(fn_addr: int, commute: bool, dt_hint: Dict) -> Op:
    cfn = _USER_OP_CFUNC(fn_addr)

    def fn(a, b):
        a = np.ascontiguousarray(a)
        inout = np.ascontiguousarray(b).copy()
        n = _i32(int(dt_hint.get("count") or a.size))
        dth = _i32(int(dt_hint.get("handle") or 0))
        cfn(a.ctypes.data, inout.ctypes.data, ctypes.byref(n),
            ctypes.byref(dth))
        return inout

    op = Op(fn, "user", commutative=bool(commute))
    op._cfn = cfn          # keep the callback alive
    op._dt_hint = dt_hint
    return op


# ---------------------------------------------------------------------------
# Request helpers
# ---------------------------------------------------------------------------

def _new_req_handle(ctx: _CRankCtx, creq: _CReq) -> int:
    h = ctx.next_req
    ctx.next_req += 1
    ctx.reqs[h] = creq
    return h


class _CPersist:
    """A persistent request (MPI_Send_init/Recv_init): an inactive
    spec plus, while started, the live _CReq (smpi_request.cpp
    persistent flag)."""

    __slots__ = ("kind", "spec", "inner")

    def __init__(self, kind: str, spec: dict):
        self.kind = kind          # "send" | "recv"
        self.spec = spec
        self.inner: Optional[_CReq] = None

    def start(self, ctx) -> None:
        s = self.spec
        comm, dt = s["comm"], s["dt"]
        if self.kind == "recv":
            arr = _recv_buf(s["count"], dt)
            req = comm.irecv(s["peer"], s["tag"], buf=arr,
                             count=s["count"], datatype=dt)
            self.inner = _CReq(req, s["buf"], arr, "recv", dt)
        else:
            arr = _arr_in(s["buf"], s["count"], dt)   # data read at Start
            if s["mode"] == 1:      # buffered: detached fire-and-forget
                req = Request("send", arr, s["count"], dt, s["peer"],
                              s["tag"], comm, detached=True,
                              is_isend=True).start()
            else:
                req = comm.isend(arr, s["peer"], s["tag"],
                                 count=s["count"], datatype=dt,
                                 ssend=(s["mode"] == 2))
            self.inner = _CReq(req, 0, arr, "send")


def _req_wait(creq, status: Status):
    kind = getattr(creq, "kind", None)
    if kind == "greq":
        return _greq_block(creq)    # status is filled at retirement
    if kind == "done":
        return None
    if kind == "nbc":
        return creq.req.wait()      # NbcRequest: no status argument
    return creq.req.wait(status)


def _req_test(creq, status: Status) -> bool:
    kind = getattr(creq, "kind", None)
    if kind == "greq":
        return creq.complete
    if kind == "done":
        return True
    if kind == "nbc":
        return creq.req.test()
    return creq.req.test(status)


def _complete_creq(ctx: _CRankCtx, handle: int) -> None:
    creq = ctx.reqs.pop(int(handle), None)
    if creq is None:
        return
    if creq.kind == "recv":
        if getattr(creq.req, "cancelled", False):
            return               # nothing was received
        arr = creq.arr
        # Scatter only the bytes that actually arrived: a short message
        # into a large derived-type recv must not write the posted
        # buffer's full extent (stack smash past the caller's array —
        # datatype/lots-of-types receives 16 B into an 8 KB type).
        got = getattr(creq.req, "real_size", None)
        nb = None
        if got is not None and np.isfinite(got):
            nb = int(got)
        if creq.cap is not None:
            # Mprobe stashes allocate at MESSAGE size; the posted
            # Imrecv buffer may be smaller — never scatter past it
            nb = creq.cap if nb is None else min(nb, creq.cap)
        if nb is not None:
            raw = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
            if nb < raw.size:
                arr = raw[:nb]
        _arr_out(creq.c_addr, arr, dt=creq.dt)
    elif creq.kind == "nbc" and creq.post is not None:
        creq.post(creq.req.wait())


def _translate_src(src: int) -> int:
    return PY_ANY_SOURCE if int(src) == C_ANY_SOURCE else int(src)


def _translate_tag(tag: int) -> int:
    return PY_ANY_TAG if int(tag) == C_ANY_TAG else int(tag)


# ---------------------------------------------------------------------------
# Operation handlers (each takes (ctx, args) -> int error code)
# ---------------------------------------------------------------------------

def _h_init(ctx, a):
    ctx.initialized = True
    return MPI_SUCCESS


def _h_finalize(ctx, a):
    # delete callbacks fire on COMM_SELF then COMM_WORLD attrs at the
    # very beginning of MPI_Finalize (MPI-2 §4.8 "at_exit" idiom,
    # attr/attrend — the reference skips this; we support it)
    for ch in (COMM_SELF, COMM_WORLD):
        store = ctx.comm_attrs.get(ch)
        if store:
            _attrs_free_all(ctx, store, ch, lifo=True)
    ctx.finalized = True
    return MPI_SUCCESS


def _h_initialized(ctx, a):
    _write_i32(a[0], 1 if ctx.initialized else 0)
    return MPI_SUCCESS


def _h_finalized(ctx, a):
    _write_i32(a[0], 1 if ctx.finalized else 0)
    return MPI_SUCCESS


def _h_abort(ctx, a):
    """Kill every other rank; the caller's C main keeps running (the
    callback cannot unwind C frames) but all its later MPI calls fail."""
    ctx.dead = True
    ctx.exit_code = int(a[1])
    from ..s4u import Actor
    Actor.kill_all()
    return MPI_SUCCESS


def _h_comm_rank(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    _write_i32(a[1], comm.rank())
    return MPI_SUCCESS


def _h_comm_size(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    _write_i32(a[1], comm.size())
    return MPI_SUCCESS


def _h_comm_dup(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    old = int(a[0])
    # attribute copy callbacks run first: a failing copy fn aborts the
    # dup and yields MPI_COMM_NULL (MPI-1.2 clarification, attr/attrerr)
    err, new_attrs = _attrs_copy_all(ctx, ctx.comm_attrs.get(old, {}),
                                     old)
    if err != MPI_SUCCESS:
        _write_i32(a[1], COMM_NULL)
        return err
    h = _new_comm_handle(ctx, comm.dup())
    if new_attrs:
        ctx.comm_attrs[h] = new_attrs
    # ... and the error handler (MPI-3 §6.4.2; errhan/commcall)
    if old in ctx.comm_errh:
        ctx.comm_errh[h] = ctx.comm_errh[old]
    # MPI_Comm_dup propagates the topology (MPI-3 §6.4.2; topo/topodup)
    if old in ctx.cart_topos:
        ctx.cart_topos[h] = ctx.cart_topos[old]
    if old in ctx.graph_topos:
        ctx.graph_topos[h] = ctx.graph_topos[old]
    _write_i32(a[1], h)
    return MPI_SUCCESS


def _h_comm_split(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    color, key = int(a[1]), int(a[2])
    new = comm.split(-1 if color == C_UNDEFINED else color, key)
    _write_i32(a[3], _new_comm_handle(ctx, new, parent=a[0]))
    return MPI_SUCCESS


def _h_comm_free(ctx, a):
    h = int(ctypes.cast(int(a[0]), _pi32)[0]) if a[0] else 0
    store = ctx.comm_attrs.get(h)
    if store:
        rc = _attrs_free_all(ctx, store, h)
        if rc != MPI_SUCCESS:
            return rc
    ctx.comm_attrs.pop(h, None)
    ctx.comm_errh.pop(h, None)
    ctx.comms.pop(h, None)
    _write_i32(a[0], COMM_NULL)
    return MPI_SUCCESS


def _h_comm_group(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    h = ctx.next_group
    ctx.next_group += 1
    ctx.groups[h] = comm.get_group()
    _write_i32(a[1], h)
    return MPI_SUCCESS


def _h_group_size(ctx, a):
    g = ctx.groups.get(int(a[0]))
    _write_i32(a[1], g.size() if g is not None else 0)
    return MPI_SUCCESS


def _h_group_rank(ctx, a):
    g = ctx.groups.get(int(a[0]))
    if g is None:
        _write_i32(a[1], C_UNDEFINED)
        return MPI_SUCCESS
    r = g.rank(runtime.this_rank())
    _write_i32(a[1], r if r >= 0 else C_UNDEFINED)
    return MPI_SUCCESS


def _h_get_processor_name(ctx, a):
    name = runtime.this_rank_state().host.name.encode()[:255]
    ctypes.memmove(int(a[0]), name + b"\0", len(name) + 1)
    _write_i32(a[1], len(name))
    return MPI_SUCCESS


# -- point-to-point ---------------------------------------------------------

def _h_send(ctx, a, ssend=False):
    buf, count, dth, dest, tag, ch = a[0], a[1], a[2], int(a[3]), int(a[4]), a[5]
    if dest == C_PROC_NULL:
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    arr = _arr_in(buf, count, dt)
    if ssend:
        comm.ssend(arr, dest, tag, count=int(count), datatype=dt)
    else:
        comm.send(arr, dest, tag, count=int(count), datatype=dt)
    return MPI_SUCCESS


def _h_recv(ctx, a):
    buf, count, dth, src, tag, ch, st_addr = (a[0], a[1], a[2], int(a[3]),
                                              int(a[4]), a[5], a[6])
    if src == C_PROC_NULL:
        _set_status(st_addr, C_PROC_NULL, C_ANY_TAG, MPI_SUCCESS, 0)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    arr = _recv_buf(count, dt)
    status = Status()
    comm.recv(_translate_src(src), _translate_tag(tag), buf=arr,
              count=int(count), datatype=dt, status=status)
    _arr_out(buf, arr, dt=dt)
    _status_from(st_addr, status)
    return MPI_SUCCESS


def _h_isend(ctx, a):
    buf, count, dth, dest, tag, ch, req_addr, ssend = \
        a[0], a[1], a[2], int(a[3]), int(a[4]), a[5], a[6], int(a[7])
    if dest == C_PROC_NULL:
        _write_i32(req_addr, 0)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    arr = _arr_in(buf, count, dt)
    req = comm.isend(arr, dest, int(tag), count=int(count), datatype=dt,
                     ssend=bool(ssend))
    _write_i32(req_addr, _new_req_handle(ctx, _CReq(req, 0, arr, "send")))
    return MPI_SUCCESS


def _h_irecv(ctx, a):
    buf, count, dth, src, tag, ch, req_addr = (a[0], a[1], a[2], int(a[3]),
                                               int(a[4]), a[5], a[6])
    if src == C_PROC_NULL:
        _write_i32(req_addr, 0)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    arr = _recv_buf(count, dt)
    req = comm.irecv(_translate_src(src), _translate_tag(tag), buf=arr,
                     count=int(count), datatype=dt)
    _write_i32(req_addr, _new_req_handle(ctx, _CReq(req, int(buf), arr,
                                                    "recv", dt)))
    return MPI_SUCCESS


def _finish_persist(persist: _CPersist) -> None:
    inner = persist.inner
    if inner is not None and inner.kind == "recv":
        _arr_out(inner.c_addr, inner.arr, dt=inner.dt)
    persist.inner = None


def _h_wait(ctx, a):
    req_addr, st_addr = a[0], a[1]
    h = ctypes.cast(int(req_addr), _pi32)[0] if req_addr else 0
    if h == 0:
        _set_status(st_addr, C_ANY_SOURCE, C_ANY_TAG, MPI_SUCCESS, 0)
        return MPI_SUCCESS
    entry = ctx.reqs.get(int(h))
    if entry is None:
        return MPI_ERR_REQUEST
    status = Status()
    if isinstance(entry, _CDoneReq):
        _set_status(st_addr, entry.src, entry.tag, MPI_SUCCESS,
                    entry.nbytes)
        ctx.reqs.pop(int(h), None)
        _write_i32(req_addr, 0)
        return MPI_SUCCESS
    if isinstance(entry, _CGreq):
        _greq_block(entry)
        rc = _greq_retire(ctx, h, entry, st_addr)
        _write_i32(req_addr, 0)
        return rc
    if isinstance(entry, _CPersist):
        # waiting an inactive persistent request returns immediately
        # with the EMPTY status; the handle survives either way
        if entry.inner is not None:
            _req_wait(entry.inner, status)
            _finish_persist(entry)
            _status_from(st_addr, status)
        else:
            _set_status(st_addr, C_ANY_SOURCE, C_ANY_TAG, MPI_SUCCESS, 0)
        return MPI_SUCCESS
    _req_wait(entry, status)
    _complete_creq(ctx, h)
    _status_from(st_addr, status)
    _write_i32(req_addr, 0)
    return MPI_SUCCESS


def _h_test(ctx, a):
    req_addr, flag_addr, st_addr = a[0], a[1], a[2]
    h = ctypes.cast(int(req_addr), _pi32)[0] if req_addr else 0
    if h == 0:
        _write_i32(flag_addr, 1)
        _set_status(st_addr, C_ANY_SOURCE, C_ANY_TAG, MPI_SUCCESS, 0)
        return MPI_SUCCESS
    entry = ctx.reqs.get(int(h))
    if entry is None:
        return MPI_ERR_REQUEST
    status = Status()
    if isinstance(entry, _CDoneReq):
        _write_i32(flag_addr, 1)
        _set_status(st_addr, entry.src, entry.tag, MPI_SUCCESS,
                    entry.nbytes)
        ctx.reqs.pop(int(h), None)
        _write_i32(req_addr, 0)
        return MPI_SUCCESS
    if isinstance(entry, _CGreq):
        if not entry.complete:
            _write_i32(flag_addr, 0)
            return MPI_SUCCESS
        _write_i32(flag_addr, 1)
        rc = _greq_retire(ctx, h, entry, st_addr)
        _write_i32(req_addr, 0)
        return rc
    if isinstance(entry, _CPersist):
        if entry.inner is None:
            _write_i32(flag_addr, 1)
            _set_status(st_addr, C_ANY_SOURCE, C_ANY_TAG, MPI_SUCCESS, 0)
            return MPI_SUCCESS
        done = _req_test(entry.inner, status)
        _write_i32(flag_addr, 1 if done else 0)
        if done:
            _finish_persist(entry)
            _status_from(st_addr, status)
        return MPI_SUCCESS
    done = _req_test(entry, status)
    _write_i32(flag_addr, 1 if done else 0)
    if done:
        _complete_creq(ctx, h)
        _status_from(st_addr, status)
        _write_i32(req_addr, 0)
    return MPI_SUCCESS


def _h_waitall(ctx, a):
    n, reqs_addr, sts_addr = int(a[0]), a[1], a[2]
    handles = _read_i32s(reqs_addr, n) if reqs_addr else []
    rc = MPI_SUCCESS
    for i, h in enumerate(handles):
        if h == 0:
            continue
        entry = ctx.reqs.get(h)
        if entry is None:
            continue
        status = Status()
        if isinstance(entry, _CDoneReq):
            if sts_addr:
                _set_status(int(sts_addr) + _STATUS_BYTES * i,
                            entry.src, entry.tag, MPI_SUCCESS,
                            entry.nbytes)
            ctx.reqs.pop(h, None)
            ctypes.cast(int(reqs_addr), _pi32)[i] = 0
            continue
        if isinstance(entry, _CGreq):
            _greq_block(entry)
            r = _greq_retire(ctx, h, entry,
                             (int(sts_addr) + _STATUS_BYTES * i)
                             if sts_addr else 0)
            if rc == MPI_SUCCESS:
                rc = r
            ctypes.cast(int(reqs_addr), _pi32)[i] = 0
            continue
        if isinstance(entry, _CPersist):
            if entry.inner is not None:
                _req_wait(entry.inner, status)
                _finish_persist(entry)
                if sts_addr:
                    _status_from(int(sts_addr) + _STATUS_BYTES * i, status)
            elif sts_addr:
                _set_status(int(sts_addr) + _STATUS_BYTES * i,
                            C_ANY_SOURCE, C_ANY_TAG, MPI_SUCCESS, 0)
            continue             # persistent handles survive waitall
        _req_wait(entry, status)
        _complete_creq(ctx, h)
        if sts_addr:
            _status_from(int(sts_addr) + _STATUS_BYTES * i, status)
        ctypes.cast(int(reqs_addr), _pi32)[i] = 0
    return rc


def _live_entries(ctx, handles):
    """(index, handle, creq, persist-or-None) for every ACTIVE entry
    (null handles and inactive persistent requests excluded)."""
    out = []
    for i, h in enumerate(handles):
        if h == 0:
            continue
        entry = ctx.reqs.get(h)
        if entry is None:
            continue
        if isinstance(entry, _CPersist):
            if entry.inner is not None:
                out.append((i, h, entry.inner, entry))
        else:
            out.append((i, h, entry, None))
    return out


def _kernel_reqs(live):
    """The subset backed by kernel Requests (waitany-able); greqs,
    done-reqs and nbc composites complete through other means."""
    return [e for e in live if getattr(e[2], "kind", None)
            in ("send", "recv")]


def _retire(ctx, h, creq, persist, status, reqs_addr, i) -> int:
    """Complete one finished entry: copy out, null the C slot for
    plain requests, flip persistents to inactive.  Returns the greq
    query/free error code (MPI_SUCCESS for ordinary requests)."""
    rc = MPI_SUCCESS
    if persist is not None:
        _finish_persist(persist)
        return rc
    if isinstance(creq, _CGreq):
        rc = _greq_finalize(ctx, h, creq, status)
    elif isinstance(creq, _CDoneReq):
        status.source, status.tag = creq.src, creq.tag
        status.count = creq.nbytes
        ctx.reqs.pop(h, None)
    else:
        _complete_creq(ctx, h)
    ctypes.cast(int(reqs_addr), _pi32)[i] = 0
    return rc


def _h_waitany(ctx, a):
    n, reqs_addr, idx_addr, st_addr = int(a[0]), a[1], a[2], a[3]
    handles = _read_i32s(reqs_addr, n) if reqs_addr else []
    live = _live_entries(ctx, handles)
    if not live:
        _write_i32(idx_addr, C_UNDEFINED)
        return MPI_SUCCESS
    status = Status()
    ready = next((e for e in live
                  if e[2].kind not in ("send", "recv")
                  and _req_test(e[2], status)), None)
    plain = _kernel_reqs(live)
    if ready is not None:
        i, h, creq, persist = ready
    elif plain:
        k = Request.waitany([e[2].req for e in plain], status)
        if k < 0:
            _write_i32(idx_addr, C_UNDEFINED)
            return MPI_SUCCESS
        i, h, creq, persist = plain[k]
    elif all(e[2].kind == "nbc" for e in live):
        # only unfinished I-collectives: block on the first (waitany
        # over mixed nbc sets degrades to that, documented divergence)
        i, h, creq, persist = live[0]
        creq.req.wait()
    else:
        # unfinished greqs in the mix: poll until something completes
        from ..s4u import this_actor
        while True:
            ready = next((e for e in live if _req_test(e[2], status)),
                         None)
            if ready is not None:
                break
            this_actor.sleep_for(1e-4)
        i, h, creq, persist = ready
    rc = _retire(ctx, h, creq, persist, status, reqs_addr, i)
    _status_from(st_addr, status)
    _write_i32(idx_addr, i)
    return rc


def _h_testall(ctx, a):
    n, reqs_addr, flag_addr, sts_addr = int(a[0]), a[1], a[2], a[3]
    handles = _read_i32s(reqs_addr, n) if reqs_addr else []
    live = _live_entries(ctx, handles)
    all_done = all(_req_test(c, Status()) for _, _, c, _ in live)
    _write_i32(flag_addr, 1 if all_done else 0)
    rc = MPI_SUCCESS
    if all_done:
        for i, h, c, persist in live:
            status = Status()
            _req_wait(c, status)    # already finished; fills status
            r = _retire(ctx, h, c, persist, status, reqs_addr, i)
            if rc == MPI_SUCCESS:
                rc = r
            if sts_addr:
                _status_from(int(sts_addr) + _STATUS_BYTES * i, status)
    return rc


def _h_testany(ctx, a):
    n, reqs_addr, idx_addr, flag_addr, st_addr = (int(a[0]), a[1], a[2],
                                                  a[3], a[4])
    handles = _read_i32s(reqs_addr, n) if reqs_addr else []
    live = _live_entries(ctx, handles)
    if not live:
        _write_i32(idx_addr, C_UNDEFINED)
        _write_i32(flag_addr, 1)
        return MPI_SUCCESS
    for i, h, c, persist in live:
        status = Status()
        if _req_test(c, status):
            rc = _retire(ctx, h, c, persist, status, reqs_addr, i)
            _status_from(st_addr, status)
            _write_i32(idx_addr, i)
            _write_i32(flag_addr, 1)
            return rc
    _write_i32(flag_addr, 0)
    return MPI_SUCCESS


def _h_waitsome(ctx, a):
    (n, reqs_addr, outcount_addr, indices_addr, sts_addr,
     blocking) = (int(a[0]), a[1], a[2], a[3], a[4], int(a[5]))
    handles = _read_i32s(reqs_addr, n) if reqs_addr else []
    live = _live_entries(ctx, handles)
    if not live:
        _write_i32(outcount_addr, C_UNDEFINED)
        return MPI_SUCCESS

    def completed():
        out = []
        for i, h, c, persist in live:
            status = Status()
            if _req_test(c, status):
                out.append((i, h, c, persist, status))
        return out

    done = completed()
    if not done and blocking:
        status = Status()
        plain = _kernel_reqs(live)
        if plain:
            k = Request.waitany([e[2].req for e in plain], status)
            if k >= 0:
                i, h, c, persist = plain[k]
                done = [(i, h, c, persist, status)]
        elif all(e[2].kind == "nbc" for e in live):
            i, h, c, persist = live[0]
            c.req.wait()
            done = [(i, h, c, persist, status)]
        else:
            from ..s4u import this_actor
            while not done:
                this_actor.sleep_for(1e-4)
                done = completed()
    rc = MPI_SUCCESS
    for j, (i, h, c, persist, status) in enumerate(done):
        r = _retire(ctx, h, c, persist, status, reqs_addr, i)
        if rc == MPI_SUCCESS:
            rc = r
        ctypes.cast(int(indices_addr), _pi32)[j] = i
        if sts_addr:
            _status_from(int(sts_addr) + _STATUS_BYTES * j, status)
    _write_i32(outcount_addr, len(done))
    return rc


def _probe_once(comm, src, tag):
    """One iprobe pass; on a match returns (src, tag, nbytes)."""
    st = Status()
    if not comm.iprobe(_translate_src(src), _translate_tag(tag),
                       status=st):
        return None
    return (st.source, st.tag, st.count)


def _h_probe(ctx, a):
    src, tag, ch, st_addr = int(a[0]), int(a[1]), a[2], a[3]
    if src == C_PROC_NULL:
        _set_status(st_addr, C_PROC_NULL, C_ANY_TAG, MPI_SUCCESS, 0)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    while True:
        # comm.iprobe itself injects the smpi/iprobe sleep on a miss;
        # when that flag is zeroed, sleep here anyway — a blocking
        # probe must never freeze simulated time
        hit = _probe_once(comm, src, tag)
        if hit is not None:
            break
        if config["smpi/iprobe"] <= 0:
            from ..s4u import this_actor
            this_actor.sleep_for(1e-4)
    _set_status(st_addr, hit[0], hit[1], MPI_SUCCESS, hit[2])
    return MPI_SUCCESS


def _h_iprobe(ctx, a):
    src, tag, ch, flag_addr, st_addr = (int(a[0]), int(a[1]), a[2], a[3],
                                        a[4])
    if src == C_PROC_NULL:
        _write_i32(flag_addr, 1)
        _set_status(st_addr, C_PROC_NULL, C_ANY_TAG, MPI_SUCCESS, 0)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    hit = _probe_once(comm, src, tag)
    _write_i32(flag_addr, 0 if hit is None else 1)
    if hit is not None:
        _set_status(st_addr, hit[0], hit[1], MPI_SUCCESS, hit[2])
    return MPI_SUCCESS


C_MESSAGE_NO_PROC = -1


class _CMsg:
    """A message plucked by MPI_Mprobe/Improbe (MPI-3 §3.8.2, reference
    smpi_pmpi_request.cpp mprobe role): the matching irecv is posted at
    probe time, which reserves exactly the probed message against any
    later recv on the same (source, tag); MPI_Mrecv/Imrecv drain it."""
    __slots__ = ("req", "arr", "src", "tag", "nbytes")

    def __init__(self, req, arr, src, tag, nbytes):
        self.req = req
        self.arr = arr
        self.src = src
        self.tag = tag
        self.nbytes = nbytes


def _pluck(ctx, comm, hit) -> int:
    src, tag, nbytes = hit
    arr = np.zeros(int(nbytes), np.uint8)
    req = comm.irecv(src, tag, buf=arr, count=int(nbytes),
                     datatype=_dt(ctx, 1))           # MPI_BYTE
    h = ctx.next_msg
    ctx.next_msg += 1
    ctx.messages[h] = _CMsg(req, arr, src, tag, int(nbytes))
    return h


def _h_mprobe(ctx, a):
    src, tag, ch, msg_addr, st_addr = (int(a[0]), int(a[1]), a[2], a[3],
                                       a[4])
    if src == C_PROC_NULL:
        _write_i32(msg_addr, C_MESSAGE_NO_PROC)
        _set_status(st_addr, C_PROC_NULL, C_ANY_TAG, MPI_SUCCESS, 0,
                    keep_error=True)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    while True:
        hit = _probe_once(comm, src, tag)
        if hit is not None:
            break
        if config["smpi/iprobe"] <= 0:
            from ..s4u import this_actor
            this_actor.sleep_for(1e-4)
    _write_i32(msg_addr, _pluck(ctx, comm, hit))
    _set_status(st_addr, hit[0], hit[1], MPI_SUCCESS, hit[2],
                keep_error=True)
    return MPI_SUCCESS


def _h_improbe(ctx, a):
    src, tag, ch, flag_addr, msg_addr, st_addr = (int(a[0]), int(a[1]),
                                                  a[2], a[3], a[4], a[5])
    if src == C_PROC_NULL:
        _write_i32(flag_addr, 1)
        _write_i32(msg_addr, C_MESSAGE_NO_PROC)
        _set_status(st_addr, C_PROC_NULL, C_ANY_TAG, MPI_SUCCESS, 0,
                    keep_error=True)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    hit = _probe_once(comm, src, tag)
    _write_i32(flag_addr, 0 if hit is None else 1)
    if hit is not None:
        _write_i32(msg_addr, _pluck(ctx, comm, hit))
        _set_status(st_addr, hit[0], hit[1], MPI_SUCCESS, hit[2],
                    keep_error=True)
    return MPI_SUCCESS


def _h_mrecv(ctx, a):
    buf, count, dth, msg_addr, st_addr = a[0], a[1], a[2], a[3], a[4]
    mh = ctypes.cast(int(msg_addr), _pi32)[0] if msg_addr else 0
    _write_i32(msg_addr, 0)                          # MPI_MESSAGE_NULL
    if mh == C_MESSAGE_NO_PROC:
        _set_status(st_addr, C_PROC_NULL, C_ANY_TAG, MPI_SUCCESS, 0,
                    keep_error=True)
        return MPI_SUCCESS
    m = ctx.messages.pop(mh, None)
    if m is None:
        return MPI_ERR_REQUEST
    status = Status()
    m.req.wait(status)
    dt = _dt(ctx, dth)
    arr = m.arr
    limit = int(count) * dt.size_          # never overrun the recv buf
    if arr.nbytes > limit:
        arr = arr.reshape(-1).view(np.uint8)[:limit]
    _arr_out(buf, arr, dt=dt)
    _set_status(st_addr, status.source, status.tag, MPI_SUCCESS,
                status.count, status.cancelled, keep_error=True)
    return MPI_SUCCESS


def _h_imrecv(ctx, a):
    buf, count, dth, msg_addr, req_addr = a[0], a[1], a[2], a[3], a[4]
    mh = ctypes.cast(int(msg_addr), _pi32)[0] if msg_addr else 0
    _write_i32(msg_addr, 0)
    if mh == C_MESSAGE_NO_PROC:
        # a real, already-complete request whose wait/test yields the
        # proc-null status (mprobe.c:268 demands a non-null handle)
        _write_i32(req_addr, _new_req_handle(ctx, _CDoneReq(
            C_PROC_NULL, C_ANY_TAG, 0)))
        return MPI_SUCCESS
    m = ctx.messages.pop(mh, None)
    if m is None:
        return MPI_ERR_REQUEST
    dt = _dt(ctx, dth)
    h = _new_req_handle(ctx, _CReq(m.req, int(buf), m.arr, "recv", dt,
                                   cap=int(count) * dt.size_))
    _write_i32(req_addr, h)
    return MPI_SUCCESS


class _CDoneReq:
    """An already-completed request with a canned status (the Imrecv-
    on-MESSAGE_NO_PROC handle)."""
    __slots__ = ("src", "tag", "nbytes")
    kind = "done"

    def __init__(self, src, tag, nbytes):
        self.src = src
        self.tag = tag
        self.nbytes = nbytes


# -- generalized requests (MPI-2 §8.2; reference smpi_request.cpp
#    generalized request support) ------------------------------------------

_GREQ_QUERY = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_void_p)
_GREQ_FREE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)
_GREQ_CANCEL = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                ctypes.c_int)


class _CGreq:
    """Completion is driven by the app via MPI_Grequest_complete;
    wait/test call the C query/free callbacks on retirement."""
    __slots__ = ("query", "free", "cancel", "extra", "complete")
    kind = "greq"

    def __init__(self, q, f, c, extra):
        self.query = _GREQ_QUERY(int(q)) if q else None
        self.free = _GREQ_FREE(int(f)) if f else None
        self.cancel = _GREQ_CANCEL(int(c)) if c else None
        self.extra = int(extra) if extra else None
        self.complete = False


def _greq_retire(ctx, h, g: _CGreq, st_addr) -> int:
    buf = (ctypes.c_ubyte * _STATUS_BYTES)()
    _set_status(ctypes.addressof(buf), C_ANY_SOURCE, C_ANY_TAG,
                MPI_SUCCESS, 0)
    rc = MPI_SUCCESS
    if g.query is not None:
        rc = int(g.query(g.extra, ctypes.addressof(buf)))
    if st_addr:
        ctypes.memmove(int(st_addr), buf, _STATUS_BYTES)
    if g.free is not None:
        frc = int(g.free(g.extra))
        if rc == MPI_SUCCESS:
            rc = frc
    ctx.reqs.pop(h, None)
    return rc


def _greq_block(g: _CGreq) -> None:
    from ..s4u import this_actor
    while not g.complete:
        this_actor.sleep_for(1e-4)


def _greq_query_into(g: _CGreq, status: Status) -> int:
    """Run the C query callback into a scratch status and lift the
    result into the Python Status (query may run several times;
    MPI-2 §8.2 allows it)."""
    buf = (ctypes.c_ubyte * _STATUS_BYTES)()
    _set_status(ctypes.addressof(buf), C_ANY_SOURCE, C_ANY_TAG,
                MPI_SUCCESS, 0, keep_error=False)
    rc = MPI_SUCCESS
    if g.query is not None:
        rc = int(g.query(g.extra, ctypes.addressof(buf)))
    p = ctypes.cast(ctypes.addressof(buf), _pi32)
    status.source = p[0]
    status.tag = p[1]
    status.cancelled = bool(p[3])
    status.count = ctypes.cast(ctypes.addressof(buf) + 16, _pi64)[0]
    return rc


def _greq_finalize(ctx, h, g: _CGreq, status: Status) -> int:
    """Retire a completed greq through the Python-Status paths
    (waitany/testany/testall/waitsome): query + free exactly once."""
    rc = _greq_query_into(g, status)
    if g.free is not None:
        frc = int(g.free(g.extra))
        if rc == MPI_SUCCESS:
            rc = frc
    ctx.reqs.pop(h, None)
    return rc


def _h_grequest_start(ctx, a):
    q, f, c, extra, req_addr = a[0], a[1], a[2], a[3], a[4]
    _write_i32(req_addr, _new_req_handle(ctx, _CGreq(q, f, c, extra)))
    return MPI_SUCCESS


def _h_grequest_complete(ctx, a):
    g = ctx.reqs.get(int(a[0]))
    if not isinstance(g, _CGreq):
        return MPI_ERR_REQUEST
    g.complete = True
    return MPI_SUCCESS


def _h_sendrecv(ctx, a):
    (sbuf, scount, stype, dest, stag,
     rbuf, rcount, rtype, src, rtag, ch, st_addr) = a[:12]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    sdt, rdt = _dt(ctx, stype), _dt(ctx, rtype)
    rreq = None
    status = Status()
    if int(src) != C_PROC_NULL:
        rarr = _recv_buf(rcount, rdt)
        rreq = comm.irecv(_translate_src(int(src)),
                          _translate_tag(int(rtag)), buf=rarr,
                          count=int(rcount), datatype=rdt)
    sreq = None
    if int(dest) != C_PROC_NULL:
        sarr = _arr_in(sbuf, scount, sdt)
        sreq = comm.isend(sarr, int(dest), int(stag), count=int(scount),
                          datatype=sdt)
    if rreq is not None:
        rreq.wait(status)
        _arr_out(rbuf, rarr, dt=rdt)
    else:
        status.source, status.tag, status.count = C_PROC_NULL, C_ANY_TAG, 0
    if sreq is not None:
        sreq.wait()
    _status_from(st_addr, status)
    return MPI_SUCCESS


def _h_bsend(ctx, a, is_ibsend=False):
    buf, count, dth, dest, tag, ch = (a[0], a[1], a[2], int(a[3]),
                                      int(a[4]), a[5])
    if dest == C_PROC_NULL:
        if is_ibsend:
            _write_i32(a[6], 0)
        return MPI_SUCCESS
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    arr = _arr_in(buf, count, dt)
    # buffered mode: the payload is copied and the sender never blocks
    # (detached kernel send = the attached-buffer semantics)
    req = Request("send", arr, int(count), dt, dest, int(tag), comm,
                  detached=True, is_isend=True).start()
    if is_ibsend:
        _write_i32(a[6], _new_req_handle(ctx, _CReq(req, 0, arr,
                                                    "send")))
    return MPI_SUCCESS


def _h_send_init(ctx, a):
    buf, count, dth, dest, tag, ch, req_addr, mode = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    spec = {"buf": int(buf), "count": int(count), "dt": _dt(ctx, dth),
            "peer": int(dest), "tag": int(tag), "comm": comm,
            "mode": int(mode)}
    h = _new_req_handle(ctx, _CPersist("send", spec))
    _write_i32(req_addr, h)
    return MPI_SUCCESS


def _h_recv_init(ctx, a):
    buf, count, dth, src, tag, ch, req_addr = a[:7]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    spec = {"buf": int(buf), "count": int(count), "dt": _dt(ctx, dth),
            "peer": _translate_src(int(src)),
            "tag": _translate_tag(int(tag)), "comm": comm}
    h = _new_req_handle(ctx, _CPersist("recv", spec))
    _write_i32(req_addr, h)
    return MPI_SUCCESS


def _h_start(ctx, a):
    h = ctypes.cast(int(a[0]), _pi32)[0] if a[0] else 0
    entry = ctx.reqs.get(int(h))
    if not isinstance(entry, _CPersist):
        return MPI_ERR_REQUEST
    if entry.inner is None:
        entry.start(ctx)
    return MPI_SUCCESS


def _h_startall(ctx, a):
    n, reqs_addr = int(a[0]), a[1]
    for h in _read_i32s(reqs_addr, n):
        entry = ctx.reqs.get(h)
        if isinstance(entry, _CPersist) and entry.inner is None:
            entry.start(ctx)
    return MPI_SUCCESS


def _h_request_free(ctx, a):
    h = ctypes.cast(int(a[0]), _pi32)[0] if a[0] else 0
    entry = ctx.reqs.pop(int(h), None)
    if isinstance(entry, _CGreq) and entry.free is not None:
        entry.free(entry.extra)
    _write_i32(a[0], 0)
    return MPI_SUCCESS


def _h_sendrecv_replace(ctx, a):
    buf, count, dth, dest, stag, src, rtag, ch, st_addr = a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    status = Status()
    rreq = None
    rarr = None
    if int(src) != C_PROC_NULL:
        rarr = _recv_buf(count, dt)
        rreq = comm.irecv(_translate_src(int(src)),
                          _translate_tag(int(rtag)), buf=rarr,
                          count=int(count), datatype=dt)
    sreq = None
    if int(dest) != C_PROC_NULL:
        sarr = _arr_in(buf, count, dt)     # snapshot before overwrite
        sreq = comm.isend(sarr, int(dest), int(stag), count=int(count),
                          datatype=dt)
    if rreq is not None:
        rreq.wait(status)
        _arr_out(buf, rarr, dt=dt)
    else:
        status.source, status.tag, status.count = C_PROC_NULL, C_ANY_TAG, 0
    if sreq is not None:
        sreq.wait()
    _status_from(st_addr, status)
    return MPI_SUCCESS


def _h_get_count(ctx, a):
    st_addr, dth, count_addr = a[0], a[1], a[2]
    if st_addr == 0:
        _write_i32(count_addr, 0)
        return MPI_SUCCESS
    nbytes = ctypes.cast(int(st_addr) + 16, _pi64)[0]
    dt = _dt(ctx, dth)
    if not dt.size_:
        _write_i32(count_addr, 0 if nbytes == 0 else C_UNDEFINED)
    elif nbytes % dt.size_ or nbytes // dt.size_ > 2**31 - 1:
        # partial element, or a count that does not fit an int
        # (MPI-3 §3.2.5: MPI_UNDEFINED in both cases)
        _write_i32(count_addr, C_UNDEFINED)
    else:
        _write_i32(count_addr, nbytes // dt.size_)
    return MPI_SUCCESS


# -- collectives ------------------------------------------------------------

def _h_barrier(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    comm.barrier()
    return MPI_SUCCESS


def _h_bcast(ctx, a):
    buf, count, dth, root, ch = a[0], a[1], a[2], int(a[3]), a[4]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    if _is_inter(comm):
        # MPI_ROOT on the origin side; remote rank on the leaf side
        if root == C_ROOT:
            comm.bcast(_arr_in(buf, count, dt), root)
        elif root != C_PROC_NULL:
            out = comm.bcast(None, root)
            _arr_out(buf, out, int(count) * dt.size_, dt=dt)
        return MPI_SUCCESS
    me = comm.rank()
    obj = _arr_in(buf, count, dt) if me == root else None
    out = comm.bcast(obj, root)
    if me != root:
        _arr_out(buf, out, int(count) * dt.size_, dt=dt)
    return MPI_SUCCESS


def _reduce_args(ctx, a):
    sbuf, rbuf, count, dth = a[0], a[1], a[2], a[3]
    dt = _dt(ctx, dth)
    if int(sbuf) == C_IN_PLACE:
        arr = _arr_in(rbuf, count, dt)
    else:
        arr = _arr_in(sbuf, count, dt)
    return arr, rbuf, int(count), dt


def _h_reduce(ctx, a):
    comm = _comm_of(ctx, a[6])
    if comm is None:
        return MPI_ERR_COMM
    arr, rbuf, count, dt = _reduce_args(ctx, a)
    op = _op_of(ctx, a[4], dt, dt_handle=a[3], count=count)
    root = int(a[5])
    if _is_inter(comm):
        if root == C_ROOT:
            res = comm.reduce(None, op, root)
            _arr_out(rbuf, np.asarray(res), count * dt.size_, dt=dt)
        elif root != C_PROC_NULL:
            comm.reduce(arr, op, root)
        return MPI_SUCCESS
    res = comm.reduce(arr, op, root)
    if comm.rank() == root:
        _arr_out(rbuf, np.asarray(res).astype(arr.dtype, copy=False),
                 count * dt.size_, dt=dt)
    return MPI_SUCCESS


def _h_allreduce(ctx, a):
    comm = _comm_of(ctx, a[5])
    if comm is None:
        return MPI_ERR_COMM
    # argument validation BEFORE any communication (smpi_pmpi_coll.cpp
    # order; teshsuite coll-allreduce probes each error path and the
    # erroneous calls must not corrupt the later real exchange)
    count_arg = int(ctypes.c_int(int(a[2]) & 0xFFFFFFFF).value)
    if count_arg < 0:
        return 6                        # MPI_ERR_COUNT
    if int(a[3]) == 0:
        return MPI_ERR_TYPE
    if int(a[4]) == 0:
        return 10                       # MPI_ERR_OP
    if count_arg > 0 and (int(a[0]) == 0 or int(a[1]) == 0):
        # address 0 is MPI_BOTTOM, legal with absolute-displacement
        # typemaps; a contiguous datatype at NULL is the error the
        # reference's CHECK_BUFFER reports (coll-allreduce probes it)
        dt0 = ctx.dtypes.get(int(a[3]))
        if dt0 is None or getattr(dt0, "c_segments", None) is None:
            return 31                   # MPI_ERR_BUFFER (mpi.h:222)
    arr, rbuf, count, dt = _reduce_args(ctx, a)
    op = _op_of(ctx, a[4], dt, dt_handle=a[3], count=count)
    res = comm.allreduce(arr, op)
    _arr_out(rbuf, np.asarray(res).astype(arr.dtype, copy=False),
             count * dt.size_, dt=dt)
    return MPI_SUCCESS


def _h_gather(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, root, ch = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root = comm.rank(), int(root)
    if _is_inter(comm):
        if root == C_ROOT:
            rdt = _dt(ctx, rtype)
            res = comm.gather(None, root)
            stride = int(rcount) * rdt.extent_
            for i, obj in enumerate(res):
                _arr_out(int(rbuf) + i * stride, obj,
                         int(rcount) * rdt.size_, dt=rdt)
        elif root != C_PROC_NULL:
            comm.gather(_arr_in(sbuf, scount, _dt(ctx, stype)), root)
        return MPI_SUCCESS
    rdt = _dt(ctx, rtype) if me == root else None
    if int(sbuf) == C_IN_PLACE and me == root:
        slice_addr = int(rbuf) + me * int(rcount) * rdt.extent_
        arr = _arr_in(slice_addr, rcount, rdt)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    res = comm.gather(arr, root)
    if me == root:
        stride = int(rcount) * rdt.extent_
        for i, obj in enumerate(res):
            _arr_out(int(rbuf) + i * stride, obj,
                     int(rcount) * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_gatherv(ctx, a):
    sbuf, scount, stype, rbuf, rcounts, displs, rtype, root, ch = a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root, n = comm.rank(), int(root), comm.size()
    if _is_inter(comm):
        if root == C_ROOT:
            rdt = _dt(ctx, rtype)
            n = comm.remote_size()
            counts = _read_i32s(rcounts, n)
            offs = _read_i32s(displs, n)
            res = comm.gatherv(None, root)
            for i, obj in enumerate(res):
                _arr_out(int(rbuf) + offs[i] * rdt.extent_, obj,
                         counts[i] * rdt.size_, dt=rdt)
        elif root != C_PROC_NULL:
            comm.gatherv(_arr_in(sbuf, scount, _dt(ctx, stype)), root)
        return MPI_SUCCESS
    if int(sbuf) == C_IN_PLACE and me == root:
        # MPI-2: root's contribution already sits at rbuf + displs[me]
        rdt = _dt(ctx, rtype)
        my_count = _read_i32s(rcounts, n)[me]
        my_off = _read_i32s(displs, n)[me]
        arr = _arr_in(int(rbuf) + my_off * rdt.extent_, my_count, rdt)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    res = comm.gatherv(arr, root)
    if me == root:
        rdt = _dt(ctx, rtype)
        counts = _read_i32s(rcounts, n)
        offs = _read_i32s(displs, n)
        for i, obj in enumerate(res):
            _arr_out(int(rbuf) + offs[i] * rdt.extent_, obj,
                     counts[i] * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_allgather(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, ch = a[:7]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    rdt = _dt(ctx, rtype)
    me = comm.rank()
    if int(sbuf) == C_IN_PLACE:
        slice_addr = int(rbuf) + me * int(rcount) * rdt.extent_
        arr = _arr_in(slice_addr, rcount, rdt)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    res = comm.allgather(arr)
    stride = int(rcount) * rdt.extent_
    for i, obj in enumerate(res):
        _arr_out(int(rbuf) + i * stride, obj,
                 int(rcount) * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_allgatherv(ctx, a):
    sbuf, scount, stype, rbuf, rcounts, displs, rtype, ch = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.remote_size() if _is_inter(comm) else comm.size()
    rdt = _dt(ctx, rtype)
    counts = _read_i32s(rcounts, n)
    offs = _read_i32s(displs, n)
    me = comm.rank()
    if int(sbuf) == C_IN_PLACE:
        slice_addr = int(rbuf) + offs[me] * rdt.extent_
        arr = _arr_in(slice_addr, counts[me], rdt)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    res = comm.allgatherv(arr)
    for i, obj in enumerate(res):
        _arr_out(int(rbuf) + offs[i] * rdt.extent_, obj,
                 counts[i] * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_scatter(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, root, ch = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root, n = comm.rank(), int(root), comm.size()
    if _is_inter(comm):
        if root == C_ROOT:
            sdt = _dt(ctx, stype)
            stride = int(scount) * sdt.extent_
            sendobjs = [_arr_in(int(sbuf) + i * stride, scount, sdt)
                        for i in range(comm.remote_size())]
            comm.scatter(sendobjs, root)
        elif root != C_PROC_NULL:
            res = comm.scatter(None, root)
            rdt = _dt(ctx, rtype)
            _arr_out(rbuf, res, int(rcount) * rdt.size_, dt=rdt)
        return MPI_SUCCESS
    sendobjs = None
    if me == root:
        sdt = _dt(ctx, stype)
        stride = int(scount) * sdt.extent_
        sendobjs = [_arr_in(int(sbuf) + i * stride, scount, sdt)
                    for i in range(n)]
    res = comm.scatter(sendobjs, root)
    if not (me == root and int(rbuf) == C_IN_PLACE):
        rdt = _dt(ctx, rtype)
        _arr_out(rbuf, res, int(rcount) * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_scatterv(ctx, a):
    sbuf, scounts, displs, stype, rbuf, rcount, rtype, root, ch = a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root, n = comm.rank(), int(root), comm.size()
    if _is_inter(comm):
        if root == C_ROOT:
            sdt = _dt(ctx, stype)
            n = comm.remote_size()
            counts = _read_i32s(scounts, n)
            offs = _read_i32s(displs, n)
            sendobjs = [_arr_in(int(sbuf) + offs[i] * sdt.extent_,
                                counts[i], sdt) for i in range(n)]
            comm.scatterv(sendobjs, root)
        elif root != C_PROC_NULL:
            res = comm.scatterv(None, root)
            rdt = _dt(ctx, rtype)
            _arr_out(rbuf, res, int(rcount) * rdt.size_, dt=rdt)
        return MPI_SUCCESS
    sendobjs = None
    if me == root:
        sdt = _dt(ctx, stype)
        counts = _read_i32s(scounts, n)
        offs = _read_i32s(displs, n)
        sendobjs = [_arr_in(int(sbuf) + offs[i] * sdt.extent_, counts[i],
                            sdt) for i in range(n)]
    res = comm.scatterv(sendobjs, root)
    if not (me == root and int(rbuf) == C_IN_PLACE):
        rdt = _dt(ctx, rtype)
        _arr_out(rbuf, res, int(rcount) * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_alltoall(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, ch = a[:7]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.remote_size() if _is_inter(comm) else comm.size()
    rdt = _dt(ctx, rtype)
    if int(sbuf) == C_IN_PLACE:
        # MPI-2.2: outgoing data is taken from recvbuf
        rstride_in = int(rcount) * rdt.extent_
        sendobjs = [_arr_in(int(rbuf) + i * rstride_in, rcount, rdt)
                    for i in range(n)]
    else:
        sdt = _dt(ctx, stype)
        sstride = int(scount) * sdt.extent_
        sendobjs = [_arr_in(int(sbuf) + i * sstride, scount, sdt)
                    for i in range(n)]
    res = comm.alltoall(sendobjs)
    rstride = int(rcount) * rdt.extent_
    for i, obj in enumerate(res):
        _arr_out(int(rbuf) + i * rstride, obj,
                 int(rcount) * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_alltoallv(ctx, a):
    sbuf, scounts, sdispls, stype, rbuf, rcounts, rdispls, rtype, ch = a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.remote_size() if _is_inter(comm) else comm.size()
    rdt = _dt(ctx, rtype)
    rc = _read_i32s(rcounts, n)
    ro = _read_i32s(rdispls, n)
    if int(sbuf) == C_IN_PLACE:
        # MPI-2.2: outgoing data is taken from recvbuf (rcounts/rdispls)
        sendobjs = [_arr_in(int(rbuf) + ro[i] * rdt.extent_, rc[i], rdt)
                    for i in range(n)]
    else:
        sdt = _dt(ctx, stype)
        sc = _read_i32s(scounts, n)
        so = _read_i32s(sdispls, n)
        sendobjs = [_arr_in(int(sbuf) + so[i] * sdt.extent_, sc[i], sdt)
                    for i in range(n)]
    res = comm.alltoallv(sendobjs)
    for i, obj in enumerate(res):
        _arr_out(int(rbuf) + ro[i] * rdt.extent_, obj,
                 rc[i] * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_scan(ctx, a, exclusive=False):
    comm = _comm_of(ctx, a[5])
    if comm is None:
        return MPI_ERR_COMM
    arr, rbuf, count, dt = _reduce_args(ctx, a)
    op = _op_of(ctx, a[4], dt, dt_handle=a[3], count=count)
    if exclusive:
        res = comm.exscan(arr, op)
        if res is None:       # rank 0: result buffer is undefined
            return MPI_SUCCESS
    else:
        res = comm.scan(arr, op)
    _arr_out(rbuf, np.asarray(res).astype(arr.dtype, copy=False),
             count * dt.size_, dt=dt)
    return MPI_SUCCESS


def _h_reduce_scatter(ctx, a):
    sbuf, rbuf, rcounts, dth, oph, ch = a[:6]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.size()
    dt = _dt(ctx, dth)
    counts = _read_i32s(rcounts, n)
    op = _op_of(ctx, oph, dt, dt_handle=dth)
    me = comm.rank()
    if _is_inter(comm):
        # intercomm reduce_scatter (MPI-2 §7.3.4): each side receives
        # the reduction of the OTHER side's full vector, scattered
        # over the LOCAL group per recvcounts (coll/redscatinter)
        full = _arr_in(sbuf, sum(counts), dt)
        remote_red = np.asarray(comm.allreduce(full, op))
        off = sum(counts[:me])
        _arr_out(rbuf, remote_red[off:off + counts[me]].astype(
            full.dtype, copy=False), counts[me] * dt.size_, dt=dt)
        return MPI_SUCCESS
    if int(sbuf) == C_IN_PLACE:
        total = sum(counts)
        full = _arr_in(rbuf, total, dt)
    else:
        full = _arr_in(sbuf, sum(counts), dt)
    sendobjs, off = [], 0
    for c in counts:
        sendobjs.append(full[off:off + c])
        off += c
    res = comm.reduce_scatter(sendobjs, op)
    _arr_out(rbuf, np.asarray(res).astype(full.dtype, copy=False),
             counts[me] * dt.size_, dt=dt)
    return MPI_SUCCESS


def _h_reduce_scatter_block(ctx, a):
    sbuf, rbuf, rcount, dth, oph, ch = a[:6]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.size()
    counts_arr = (ctypes.c_int * n)(*([int(rcount)] * n))
    return _h_reduce_scatter(
        ctx, [sbuf, rbuf, ctypes.addressof(counts_arr), dth, oph, ch])


# -- datatypes --------------------------------------------------------------

def _h_type_size(ctx, a):
    dt = _dt(ctx, a[0])
    size = int(getattr(dt, "c_mpi_size", dt.size_))
    if int(a[2]):                # MPI_Type_size_x: MPI_Count output
        _write_i64(a[1], size)
    else:
        _write_i32(a[1], size if size <= 2**31 - 1 else C_UNDEFINED)
    return MPI_SUCCESS


def _lbub_of(dt: Datatype):
    lb = int(getattr(dt, "c_lb", 0))
    return lb, lb + dt.extent_


def _set_bounds(dt: Datatype, placements, old: Datatype,
                lb_mark=None, ub_mark=None) -> None:
    """Derive the new type's lb/ub from where child instances were
    placed (MPI-3 §4.1.7): lb = min placement + child lb, ub = max
    placement + child ub; explicit MPI_LB/MPI_UB markers override."""
    if placements:
        lb_old, ub_old = _lbub_of(old)
        lb = min(placements) + lb_old
        ub = max(placements) + ub_old
    else:
        lb = ub = 0
    if lb_mark is not None:
        lb = lb_mark
    if ub_mark is not None:
        ub = ub_mark
    dt.c_lb = lb
    dt.extent_ = ub - lb


def _h_type_get_extent(ctx, a):
    dt = _dt(ctx, a[0])
    if int(a[3]):                # true extent: span of the actual data
        true_lb, true_ub = _seg_bounds(_segments_of(dt))
        _write_i64(a[1], true_lb)
        _write_i64(a[2], true_ub - true_lb)
        return MPI_SUCCESS
    _write_i64(a[1], int(getattr(dt, "c_lb", 0)))
    _write_i64(a[2], dt.extent_)
    return MPI_SUCCESS


def _new_dtype_handle(ctx, dt) -> int:
    # LIFO reuse of freed handle slots, like MPICH's handle pools: the
    # mpich3 suite (datatype/indexed-misc.c:457) deliberately reuses a
    # stale handle variable that aliases the most recently created type
    free = getattr(ctx, "free_dtype_handles", None)
    if free:
        h = free.pop()
    else:
        h = ctx.next_dtype
        ctx.next_dtype += 1
    ctx.dtypes[h] = dt
    return h


def _replicate(base: Datatype, times: int, step: int):
    """base's segments repeated `times` at `step`-byte intervals."""
    base_segs = _segments_of(base)
    if times <= 0:
        return []
    if base_segs == [(0, step)]:
        # gap-free repetition collapses to one run — essential for the
        # MPI_Count-scale types (datatype/large-count builds >2^31-byte
        # types; a per-element segment list would be gigabytes)
        return [(0, times * step)]
    if times * max(len(base_segs), 1) > _SEG_CAP:
        return _StridedSegs(times, step, base_segs)
    return _coalesce([(k * step + off, n)
                      for k in range(times) for off, n in base_segs])


def _h_type_contiguous(ctx, a):
    count, old = int(a[0]), _dt(ctx, a[1])
    dt = Datatype.create_contiguous(count, old)
    dt.c_segments = _replicate(old, count, old.extent_)
    if count > 0:
        _set_bounds(dt, [0, (count - 1) * old.extent_], old)
    dt.c_basics = _basics_of(old)
    dt.c_env = (C_COMBINER_CONTIGUOUS, [count], [], [int(a[1])])
    dt.c_env_types = [old]
    _write_i32(a[2], _new_dtype_handle(ctx, dt))
    return MPI_SUCCESS


def _h_type_vector(ctx, a):
    count, blocklen, stride, old = (int(a[0]), int(a[1]), int(a[2]),
                                    _dt(ctx, a[3]))
    dt = Datatype.create_vector(count, blocklen, stride, old)
    # C buffers really are strided: record the type map so
    # _arr_in/_arr_out gather/scatter through it; payloads travel
    # packed so the numpy element view no longer applies
    dt.np_dtype = None
    block = _replicate(old, blocklen, old.extent_)
    if block == [(0, stride * old.extent_)]:
        dt.c_segments = [(0, count * stride * old.extent_)] if count \
            else []
    elif count * max(len(block), 1) > _SEG_CAP:
        dt.c_segments = _StridedSegs(count, stride * old.extent_, block)
    else:
        dt.c_segments = _coalesce(
            [(b * stride * old.extent_ + off, n)
             for b in range(count) for off, n in block])
    dt.c_basics = _basics_of(old)
    if count > 0 and blocklen > 0:
        _set_bounds(dt, [(b * stride + i) * old.extent_
                         for b in (0, count - 1)
                         for i in (0, blocklen - 1)], old)
    dt.c_env = (C_COMBINER_VECTOR, [count, blocklen, stride], [],
                [int(a[3])])
    dt.c_env_types = [old]
    _write_i32(a[4], _new_dtype_handle(ctx, dt))
    return MPI_SUCCESS


def _h_type_commit(ctx, a):
    h = ctypes.cast(int(a[0]), _pi32)[0]
    _dt(ctx, h).commit()
    return MPI_SUCCESS


def _h_type_free(ctx, a):
    h = int(ctypes.cast(int(a[0]), _pi32)[0])
    if h in _PREDEF_DTYPES:
        return MPI_ERR_ARG       # freeing a predefined type is erroneous
    store = ctx.type_attrs.get(h)
    if store:
        rc = _attrs_free_all(ctx, store, h)
        if rc != MPI_SUCCESS:
            return rc
    ctx.type_attrs.pop(h, None)
    if ctx.dtypes.pop(h, None) is not None:
        if not hasattr(ctx, "free_dtype_handles"):
            ctx.free_dtype_handles = []
        ctx.free_dtype_handles.append(h)
    _write_i32(a[0], 0)
    return MPI_SUCCESS


def _h_op_create(ctx, a):
    fn_addr, commute, op_addr = int(a[0]), int(a[1]), a[2]
    h = ctx.next_op
    ctx.next_op += 1
    hint: Dict = {}
    ctx.ops[h] = _user_op(fn_addr, bool(commute), hint)
    _write_i32(op_addr, h)
    return MPI_SUCCESS


def _h_op_commutative(ctx, a):
    # predefined reduction ops are all commutative (MPI-3 §5.9.1);
    # user ops report the flag given to MPI_Op_create
    op = ctx.ops.get(int(a[0]))
    commute = 1 if op is None else int(bool(op.commutative))
    _write_i32(a[1], commute)
    return MPI_SUCCESS


def _h_reduce_local(ctx, a):
    """MPI_Reduce_local: inoutbuf = op(inbuf, inoutbuf), no
    communication (MPI-2.2 §5.9.7; coll/reduce_local)."""
    inbuf, inoutbuf, count, dth, oph = a[:5]
    count = int(ctypes.c_int(int(count) & 0xFFFFFFFF).value)
    if count < 0:
        return 6                        # MPI_ERR_COUNT
    if int(dth) == 0:                   # handles validate even at 0
        return MPI_ERR_TYPE
    if int(oph) == 0:
        return 10                       # MPI_ERR_OP
    if count == 0:
        return MPI_SUCCESS
    dt = _dt(ctx, dth)
    op = _op_of(ctx, oph, dt, dt_handle=dth, count=count)
    a_in = _arr_in(inbuf, count, dt)
    b_inout = _arr_in(inoutbuf, count, dt)
    res = op(a_in, b_inout)
    _arr_out(int(inoutbuf),
             np.asarray(res).astype(b_inout.dtype, copy=False),
             count * dt.size_, dt=dt)
    return MPI_SUCCESS


def _h_op_free(ctx, a):
    h = ctypes.cast(int(a[0]), _pi32)[0]
    ctx.ops.pop(int(h), None)
    _write_i32(a[0], 0)
    return MPI_SUCCESS


# -- MPI-IO (file content is size-only in simulation, so the handlers
# charge I/O time and fill statuses without moving buffer bytes) -------------

_IO_PLAIN, _IO_AT, _IO_ALL, _IO_SHARED = 0, 1, 2, 3


def _h_file_open(ctx, a):
    from .file import MpiFileError, file_open
    ch, name_addr, amode, fh_addr = a[:4]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    name = ctypes.string_at(int(name_addr)).decode()
    try:
        mf = file_open(comm, name, int(amode))
    except MpiFileError:
        _write_i32(fh_addr, 0)
        return MPI_ERR_OTHER
    h = ctx.next_file
    ctx.next_file += 1
    ctx.files[h] = mf
    _write_i32(fh_addr, h)
    return MPI_SUCCESS


def _file_of(ctx, handle):
    return ctx.files.get(int(handle))


def _h_file_close(ctx, a):
    h = ctypes.cast(int(a[0]), _pi32)[0] if a[0] else 0
    mf = _file_of(ctx, h)
    if mf is not None:
        mf.close()
        ctx.files.pop(int(h), None)
    _write_i32(a[0], 0)
    return MPI_SUCCESS


def _h_file_delete(ctx, a):
    from .file import file_delete
    name = ctypes.string_at(int(a[0])).decode()
    try:
        file_delete(name)
    except Exception:
        return MPI_ERR_OTHER
    return MPI_SUCCESS


def _h_file_seek(ctx, a, shared=False):
    mf = _file_of(ctx, a[0])
    if mf is None:
        return MPI_ERR_ARG
    if shared:
        mf.seek_shared(int(a[1]), int(a[2]))
    else:
        mf.seek(int(a[1]), int(a[2]))
    return MPI_SUCCESS


def _h_file_get_position(ctx, a):
    mf = _file_of(ctx, a[0])
    if mf is None:
        return MPI_ERR_ARG
    _write_i64(a[1], mf.get_position())
    return MPI_SUCCESS


def _h_file_get_size(ctx, a):
    mf = _file_of(ctx, a[0])
    if mf is None:
        return MPI_ERR_ARG
    _write_i64(a[1], mf.get_size())
    return MPI_SUCCESS


def _h_file_io(ctx, a, write: bool):
    fh, _buf, count, dth, st_addr, mode, offset = a[:7]
    mf = _file_of(ctx, fh)
    if mf is None:
        return MPI_ERR_ARG
    from .file import MpiFileError
    dt = _dt(ctx, dth)
    size = int(count) * dt.size_
    try:
        mode = int(mode)
        if mode == _IO_AT:
            moved = (mf.write_at(int(offset), size) if write
                     else mf.read_at(int(offset), size))
        elif mode == _IO_ALL:
            moved = mf.write_all(size) if write else mf.read_all(size)
        elif mode == _IO_SHARED:
            moved = (mf.write_shared(size) if write
                     else mf.read_shared(size))
        else:
            moved = mf.write(size) if write else mf.read(size)
    except MpiFileError:
        return MPI_ERR_OTHER
    _set_status(st_addr, 0, 0, MPI_SUCCESS, moved)
    return MPI_SUCCESS


def _h_file_sync(ctx, a):
    mf = _file_of(ctx, a[0])
    if mf is None:
        return MPI_ERR_ARG
    mf.sync()
    return MPI_SUCCESS


# -- SMPI extensions (SHARED_MALLOC / SAMPLE loops / smpi_execute) ----------

#: (file, line) -> ctypes buffer shared by ALL ranks (the aliasing is
#: the point, smpi_shared.cpp:6-60); address -> key for free
_c_shared_blocks: Dict = {}
_c_shared_by_addr: Dict[int, tuple] = {}
#: sample state per (file, line[, rank])
_c_samples: Dict = {}


def _unpack_double(bits: int) -> float:
    import struct
    return struct.unpack("<d", struct.pack("<q", int(bits)))[0]


def _h_shared_malloc(ctx, a):
    size, file_addr, line, out_addr = a[:4]
    key = (ctypes.string_at(int(file_addr)), int(line))
    buf = _c_shared_blocks.get(key)
    if buf is None or len(buf) < int(size):
        buf = ctypes.create_string_buffer(max(int(size), 1))
        _c_shared_blocks[key] = buf
        _c_shared_by_addr[ctypes.addressof(buf)] = key
    _write_i64(out_addr, ctypes.addressof(buf))
    return MPI_SUCCESS


def _h_shared_free(ctx, a):
    # blocks are shared across ranks: keep them until the run ends
    # (the reference refcounts; a rank's free must not yank the block
    # from under its peers)
    return MPI_SUCCESS


def _h_execute(ctx, a):
    amount = _unpack_double(a[0])
    if int(a[1]):
        runtime.smpi_execute_flops(amount)
    else:
        runtime.smpi_execute(amount)
    return MPI_SUCCESS


class _CSample:
    __slots__ = ("iters", "threshold", "count", "total", "sumsq", "t0",
                 "injected")

    def __init__(self, iters, threshold):
        self.iters = iters
        self.threshold = threshold
        self.count = 0
        self.total = 0.0
        self.sumsq = 0.0
        self.t0 = None
        self.injected = False

    def need_more(self) -> bool:
        """smpi_bench.cpp sample_enough_benchs: bench until the
        requested iteration budget is consumed AND the relative
        standard error falls under the threshold."""
        if self.count < max(self.iters, 2):
            return True
        if self.threshold <= 0.0:
            return False
        mean = self.total / self.count
        if mean == 0.0:
            return False
        var = self.sumsq / self.count - mean * mean
        stderr = (max(var, 0.0) ** 0.5) / (self.count ** 0.5)
        return stderr / abs(mean) > self.threshold


def _sample_key(ctx, a):
    is_global = bool(int(a[0]))
    key = (ctypes.string_at(int(a[1])), int(a[2]))
    if not is_global:
        key = key + (runtime.this_rank(),)
    return key


def _h_sample_1(ctx, a):
    key = _sample_key(ctx, a)
    if key not in _c_samples:
        _c_samples[key] = _CSample(int(a[3]), _unpack_double(a[4]))
    return MPI_SUCCESS


def _h_sample_2(ctx, a):
    from ..s4u import Engine, this_actor
    st = _c_samples.get(_sample_key(ctx, a))
    out_addr = a[4]
    if st is None or st.injected:
        _write_i64(out_addr, 0)
        return MPI_SUCCESS
    if st.need_more():
        st.t0 = Engine.get_clock()
        _write_i64(out_addr, 1)
        return MPI_SUCCESS
    # done benching: charge the mean simulated duration for every
    # remaining iteration in one go and stop the loop
    remaining = int(a[3]) - st.count
    mean = st.total / st.count if st.count else 0.0
    if remaining > 0 and mean > 0:
        this_actor.sleep_for(mean * remaining)
    st.injected = True
    _write_i64(out_addr, 0)
    return MPI_SUCCESS


def _h_sample_3(ctx, a):
    from ..s4u import Engine
    st = _c_samples.get(_sample_key(ctx, a))
    if st is not None and st.t0 is not None:
        dt = Engine.get_clock() - st.t0
        st.count += 1
        st.total += dt
        st.sumsq += dt * dt
        st.t0 = None
    return MPI_SUCCESS


def _h_sample_exit(ctx, a):
    return MPI_SUCCESS


# -- naming / comm-from-group / attributes / windows ------------------------

def _h_comm_get_name(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    h = int(a[0])
    name = ctx.comm_names.get(h)
    if name is None:
        name = ("MPI_COMM_WORLD" if h == COMM_WORLD
                else "MPI_COMM_SELF" if h == COMM_SELF
                else f"MPI_COMM_{h}")
    name = name.encode()
    ctypes.memmove(int(a[1]), name + b"\0", len(name) + 1)
    _write_i32(a[2], len(name))
    return MPI_SUCCESS


def _h_comm_create(ctx, a):
    comm = _comm_of(ctx, a[0])
    group = ctx.groups.get(int(a[1]))
    if comm is None or group is None:
        return MPI_ERR_COMM
    _write_i32(a[2], _new_comm_handle(ctx, comm.create(group),
                                       parent=a[0]))
    return MPI_SUCCESS


def _new_group_handle(ctx, group) -> int:
    if not group.world_ranks:
        return 1                # the canonical MPI_GROUP_EMPTY handle
    h = ctx.next_group
    ctx.next_group += 1
    ctx.groups[h] = group
    return h


def _h_group_incl(ctx, a, mode="incl"):
    group = ctx.groups.get(int(a[0]))
    if group is None:
        return MPI_ERR_ARG
    n = int(a[1])
    if mode == "range":
        flat = _read_i32s(a[2], 3 * n)
        ranges = [tuple(flat[3 * i:3 * i + 3]) for i in range(n)]
        new = group.range_incl(ranges)
    else:
        ranks = _read_i32s(a[2], n)
        new = group.incl(ranks) if mode == "incl" else group.excl(ranks)
    _write_i32(a[3], _new_group_handle(ctx, new))
    return MPI_SUCCESS


#: predefined COMM_WORLD attribute keyvals (mpi.h)
_ATTR_TAG_UB, _ATTR_WTIME_GLOBAL = 1, 4
_ATTR_HOST, _ATTR_IO, _ATTR_LASTUSEDCODE = 2, 3, 7
_ATTR_UNIVERSE, _ATTR_APPNUM = 5, 6
_WIN_BASE, _WIN_SIZE, _WIN_DISP = 16, 17, 18

#: persistent storage the attribute pointers point into
_attr_cells: Dict[int, ctypes.c_int] = {}


def _attr_cell(keyval: int, value: int) -> int:
    cell = _attr_cells.get(keyval)
    if cell is None:
        cell = _attr_cells[keyval] = ctypes.c_int(value)
    cell.value = value
    return ctypes.addressof(cell)


# Keyvals are refcounted MPICH-style (1 for the user handle + 1 per
# attached attribute): MPI_*_free_keyval only invalidates the user's
# handle; the callbacks survive until the last attribute detaches, so
# delete callbacks still fire at object-free time (MPI-3 §6.7.2,
# attr/fkeyval*, rma/fkeyvalwin).  Ids are never reused.
MPI_ERR_KEYVAL = 35

_ATTR_COPY_CFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
    ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_int))
_ATTR_DELETE_CFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
    ctypes.c_void_p)


def _kv_new(ctx, copy_fn, delete_fn, extra) -> int:
    h = ctx.next_keyval
    ctx.next_keyval += 1
    ctx.keyvals[h] = {"copy": int(copy_fn), "delete": int(delete_fn),
                      "extra": int(extra), "refs": 1, "freed": False}
    return h


def _kv_deref(ctx, kv: int) -> None:
    e = ctx.keyvals.get(kv)
    if e is not None:
        e["refs"] -= 1
        if e["refs"] <= 0:
            ctx.keyvals.pop(kv, None)


def _attr_fire_delete(ctx, store, oh: int, kv: int):
    """Run the delete callback; on success detach the attr. Returns an
    MPI error code (nonzero keeps the attribute attached, MPI-1.2
    clarification exercised by attr/attrerr)."""
    if kv not in store:
        return MPI_SUCCESS
    e = ctx.keyvals.get(kv)
    if e is not None and e["delete"]:
        rc = _ATTR_DELETE_CFUNC(e["delete"])(oh, kv, store[kv],
                                             e["extra"])
        if rc != MPI_SUCCESS:
            return rc
    store.pop(kv, None)
    _kv_deref(ctx, kv)
    return MPI_SUCCESS


def _attrs_set(ctx, store, oh: int, kv: int, value: int):
    e = ctx.keyvals.get(kv)
    if e is None or e["freed"]:
        return MPI_ERR_KEYVAL
    if kv in store:
        rc = _attr_fire_delete(ctx, store, oh, kv)
        if rc != MPI_SUCCESS:
            return rc
    store[kv] = value
    e["refs"] += 1
    return MPI_SUCCESS


def _attrs_copy_all(ctx, src_store, oldh: int):
    """Copy-callback pass for Comm_dup/Type_dup. Returns (err, dict)."""
    new_store: Dict[int, int] = {}
    for kv, value in list(src_store.items()):
        e = ctx.keyvals.get(kv)
        if e is None or not e["copy"]:
            continue
        out = ctypes.c_void_p(0)
        flag = ctypes.c_int(0)
        rc = _ATTR_COPY_CFUNC(e["copy"])(
            oldh, kv, e["extra"], value, ctypes.byref(out),
            ctypes.byref(flag))
        if rc != MPI_SUCCESS:
            for kv2 in new_store:
                _kv_deref(ctx, kv2)
            return rc, None
        if flag.value:
            new_store[kv] = out.value or 0
            e["refs"] += 1
    return MPI_SUCCESS, new_store


def _attrs_free_all(ctx, store, oh: int, lifo: bool = False):
    """Fire every delete callback at object-free time (insertion
    order, matching MPICH; COMM_SELF at finalize is LIFO per MPI-2.2,
    init/attrself); first error aborts the free."""
    keys = list(store)
    if lifo:
        keys.reverse()
    for kv in keys:
        rc = _attr_fire_delete(ctx, store, oh, kv)
        if rc != MPI_SUCCESS:
            return rc
    return MPI_SUCCESS


def _h_keyval_create(ctx, a):
    _write_i32(a[2], _kv_new(ctx, a[0], a[1], a[3]))
    return MPI_SUCCESS


def _h_keyval_free(ctx, a):
    h = ctypes.cast(int(a[0]), _pi32)[0] if a[0] else 0
    e = ctx.keyvals.get(int(h))
    if e is not None and not e["freed"]:
        e["freed"] = True
        _kv_deref(ctx, int(h))
    _write_i32(a[0], -1)      # MPI_KEYVAL_INVALID
    return MPI_SUCCESS


def _h_attr_put(ctx, a):
    ch, kv = int(a[0]), int(a[1])
    if _comm_of(ctx, ch) is None:
        return MPI_ERR_COMM
    return _attrs_set(ctx, ctx.comm_attrs.setdefault(ch, {}), ch, kv,
                      int(a[2]))


def _h_attr_get(ctx, a):
    ch, kv, val_addr, flag_addr = int(a[0]), int(a[1]), a[2], a[3]
    predefined = {
        _ATTR_TAG_UB: 2**30 - 1,
        _ATTR_HOST: C_PROC_NULL,        # no distinguished host process
        _ATTR_IO: C_ANY_SOURCE,         # every rank can do I/O
        _ATTR_WTIME_GLOBAL: 1,          # one simulated clock: global
        _ATTR_UNIVERSE: runtime.world().size(),
        _ATTR_APPNUM: 0,
        _ATTR_LASTUSEDCODE: ctx.last_used_code,
    }
    if kv in predefined:
        # MPI contract: *(void**)val receives a pointer to the value
        ctypes.cast(int(val_addr), _pi64)[0] = _attr_cell(
            kv, predefined[kv])
        _write_i32(flag_addr, 1)
        return MPI_SUCCESS
    if kv < 0:
        return MPI_ERR_KEYVAL
    stored = ctx.comm_attrs.get(ch, {}).get(kv)
    if stored is None:
        _write_i32(flag_addr, 0)
    else:
        ctypes.cast(int(val_addr), _pi64)[0] = stored
        _write_i32(flag_addr, 1)
    return MPI_SUCCESS


def _h_attr_delete(ctx, a):
    ch, kv = int(a[0]), int(a[1])
    store = ctx.comm_attrs.get(ch, {})
    if kv not in store:
        return MPI_SUCCESS if kv >= 0 else MPI_ERR_KEYVAL
    return _attr_fire_delete(ctx, store, ch, kv)


def _h_type_set_attr(ctx, a):
    th, kv = int(a[0]), int(a[1])
    if ctx.dtypes.get(th) is None:
        return MPI_ERR_TYPE
    return _attrs_set(ctx, ctx.type_attrs.setdefault(th, {}), th, kv,
                      int(a[2]))


def _h_type_get_attr(ctx, a):
    th, kv, val_addr, flag_addr = int(a[0]), int(a[1]), a[2], a[3]
    if kv < 0:
        return MPI_ERR_KEYVAL
    stored = ctx.type_attrs.get(th, {}).get(kv)
    if stored is None:
        _write_i32(flag_addr, 0)
    else:
        ctypes.cast(int(val_addr), _pi64)[0] = stored
        _write_i32(flag_addr, 1)
    return MPI_SUCCESS


def _h_type_delete_attr(ctx, a):
    th, kv = int(a[0]), int(a[1])
    store = ctx.type_attrs.get(th, {})
    if kv not in store:
        return MPI_SUCCESS if kv >= 0 else MPI_ERR_KEYVAL
    return _attr_fire_delete(ctx, store, th, kv)


# -- error handlers & dynamic error codes -----------------------------------
# Implicit MPI errors return codes (matching the reference SMPI default);
# MPI_Comm_call_errhandler / MPI_Win_call_errhandler honour the installed
# handler: ERRORS_RETURN is a no-op, a user handler (Comm_create_errhandler)
# is invoked via ctypes, and ERRORS_ARE_FATAL — the MPI default — aborts
# (errhan/errfatal runs under resultTest=TestErrFatal).

_ERRH_CFUNC = ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_int),
                               ctypes.POINTER(ctypes.c_int))

_ERR_STRINGS = {
    0: "MPI_SUCCESS: no error", 1: "MPI_ERR_BUFFER: invalid buffer",
    2: "MPI_ERR_COUNT: invalid count", 3: "MPI_ERR_TYPE: invalid datatype",
    4: "MPI_ERR_TAG: invalid tag", 5: "MPI_ERR_COMM: invalid communicator",
    6: "MPI_ERR_RANK: invalid rank", 7: "MPI_ERR_REQUEST: invalid request",
    12: "MPI_ERR_ARG: invalid argument", 13: "MPI_ERR_UNKNOWN: unknown",
    14: "MPI_ERR_TRUNCATE: message truncated",
    15: "MPI_ERR_OTHER: known error not in this list",
    16: "MPI_ERR_INTERN: internal error",
    17: "MPI_ERR_WIN: invalid window",
    35: "MPI_ERR_KEYVAL: invalid keyval",
}


def _h_errhandler_create(ctx, a):
    h = ctx.next_errh
    ctx.next_errh += 1
    ctx.errhandlers[h] = int(a[0])
    _write_i32(a[1], h)
    return MPI_SUCCESS


def _h_errhandler_free(ctx, a):
    # only the user handle dies: a handler installed on a comm/win
    # outlives it (MPI-3 §8.3, errhan/commcall frees then dups)
    _write_i32(a[0], 0)       # MPI_ERRHANDLER_NULL
    return MPI_SUCCESS


def _invoke_errhandler(ctx, errh: int, oh: int, code: int) -> int:
    if errh == 1:             # MPI_ERRORS_RETURN
        return MPI_SUCCESS
    fn = ctx.errhandlers.get(errh)
    if fn:
        c_oh, c_code = ctypes.c_int(oh), ctypes.c_int(code)
        _ERRH_CFUNC(fn)(ctypes.byref(c_oh), ctypes.byref(c_code))
        return MPI_SUCCESS
    sys.stderr.write("MPI: fatal error %d on rank %d (errhandler is "
                     "MPI_ERRORS_ARE_FATAL); aborting\n"
                     % (code, runtime.this_rank()))
    return _h_abort(ctx, (0, code or 1))


def _h_comm_set_errhandler(ctx, a):
    if _comm_of(ctx, a[0]) is None:
        return MPI_ERR_COMM
    ctx.comm_errh[int(a[0])] = int(a[1])
    return MPI_SUCCESS


def _h_comm_get_errhandler(ctx, a):
    _write_i32(a[1], ctx.comm_errh.get(int(a[0]), 2))
    return MPI_SUCCESS


def _h_comm_call_errhandler(ctx, a):
    ch = int(a[0])
    if _comm_of(ctx, ch) is None:
        return MPI_ERR_COMM
    return _invoke_errhandler(ctx, ctx.comm_errh.get(ch, 2), ch,
                              int(a[1]))


def _h_add_error_class(ctx, a):
    ctx.last_used_code += 1
    ctx.user_err_class[ctx.last_used_code] = ctx.last_used_code
    _write_i32(a[0], ctx.last_used_code)
    return MPI_SUCCESS


def _h_add_error_code(ctx, a):
    ctx.last_used_code += 1
    ctx.user_err_class[ctx.last_used_code] = int(a[0])
    _write_i32(a[1], ctx.last_used_code)
    return MPI_SUCCESS


def _h_add_error_string(ctx, a):
    ctx.user_err_strings[int(a[0])] = ctypes.string_at(
        int(a[1])).decode(errors="replace")[:255]
    return MPI_SUCCESS


def _h_error_string(ctx, a):
    code = int(ctypes.c_int(int(a[0]) & 0xFFFFFFFF).value)
    if code > 74:   # dynamic codes with no string registered are ""
        s = ctx.user_err_strings.get(code, "")
    else:
        s = (ctx.user_err_strings.get(code) or _ERR_STRINGS.get(code)
             or "MPI error %d" % code)
    b = s.encode()[:255]
    ctypes.memmove(int(a[1]), b + b"\0", len(b) + 1)
    _write_i32(a[2], len(b))
    return MPI_SUCCESS


def _h_error_class(ctx, a):
    code = int(ctypes.c_int(int(a[0]) & 0xFFFFFFFF).value)
    _write_i32(a[1], ctx.user_err_class.get(code, code))
    return MPI_SUCCESS


# -- one-sided communication (MPI-3 RMA) ------------------------------------
# Role of reference src/smpi/bindings/smpi_pmpi_win.cpp + smpi_win.cpp:
# handle translation + datatype-mapped marshalling here, epoch state
# machine and simulated transfers in win.py.

MPI_ERR_WIN = 17
MPI_ERR_RANK = 7
OP_REPLACE, OP_NO_OP = 13, 14
_WIN_FLAVOR_KV, _WIN_MODEL_KV = 19, 20
C_WIN_UNIFIED = 2


class _RmaReq:
    """Request adapter for MPI_Rget/Rget_accumulate (reply in flight)
    and the already-locally-complete Rput/Raccumulate (comm=None).

    Delivery into the user buffer happens at the EARLIER of the next
    window sync (unlock/flush/fence force-complete every outstanding
    request — MPI-3 §11.5.4, rma/rget-unlock reuses the buffer right
    after unlock_all) and MPI_Wait on the request; never twice."""

    __slots__ = ("_comm", "_payload", "_deliver", "finished")

    def __init__(self, comm=None, deliver=None):
        self._comm = comm
        self._payload = None
        self._deliver = deliver
        self.finished = comm is None

    def _complete(self):
        self._payload = self._comm.get_payload()[0]
        self.finished = True
        if self._deliver is not None:
            self._deliver(self._payload)

    def force(self) -> None:
        """Window-sync completion: receive + deliver now."""
        if not self.finished:
            self._comm.wait()
            self._complete()

    def wait(self):
        if not self.finished:
            self._comm.wait()
            self._complete()
        return self._payload

    def test(self) -> bool:
        if self.finished:
            return True
        if self._comm.test():
            self._complete()
            return True
        # raw s4u activity: inject the smpi/test clock advance here —
        # a busy Testall loop must let simulated time move or the
        # in-flight reply never completes (rma/rget-testall)
        sleep = config["smpi/test"]
        if sleep > 0:
            from ..s4u import this_actor
            this_actor.sleep_for(sleep)
        return False


def _win_entry(ctx, handle):
    return ctx.wins.get(int(handle))


def _new_win_handle(ctx, win, base, size, disp_unit, flavor,
                    keep=None) -> int:
    h = ctx.next_win
    ctx.next_win += 1
    # attr cells live as long as the win entry (get_attr returns
    # POINTERS to them)
    ctx.wins[h] = {"win": win, "base": int(base),
                   "size_cell": ctypes.c_longlong(int(size)),
                   "disp_cell": ctypes.c_int(int(disp_unit)),
                   "flavor_cell": ctypes.c_int(int(flavor)),
                   "model_cell": ctypes.c_int(C_WIN_UNIFIED),
                   "attrs": {}, "name": "", "errh": 0,
                   "keep": keep, "attached": []}
    return h


def _h_win_create(ctx, a):
    from .win import CMemory, Win
    base, size, disp, ch, win_addr = (int(a[0]), int(a[1]), int(a[2]),
                                      a[3], a[4])
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    win = Win(comm, memory=CMemory(base, max(disp, 1), size))
    _write_i32(win_addr, _new_win_handle(ctx, win, base, size, disp, 1))
    return MPI_SUCCESS


def _h_win_allocate(ctx, a, shared=False):
    from .win import CMemory, Win
    size, disp, ch, base_addr, win_addr = (int(a[0]), int(a[1]), a[3],
                                           a[4], a[5])
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    if shared:
        # one contiguous allocation spanning all ranks (every rank of
        # this simulated node shares the process address space, so
        # direct load/store into a peer's segment works natively,
        # matching MPI_WIN_UNIFIED); rank 0 owns the buffer object.
        sizes = comm.allgather(int(size))
        units = comm.allgather(int(disp))
        # exact sizes, NOT padded: MPI-3 §11.2.3 guarantees the default
        # (contiguous) layout puts rank i+1's segment at rank i's base
        # + size, and programs legally address neighbors that way
        aligned = list(sizes)
        r = comm.rank()
        shared_blob = None
        if r == 0:
            buf = (ctypes.c_char * max(sum(aligned), 1))()
            shared_blob = {"buf": buf, "base0": ctypes.addressof(buf)}
        shared_blob = comm.bcast(shared_blob, 0)
        offs = [sum(aligned[:i]) for i in range(len(sizes))]
        base = shared_blob["base0"] + offs[r]
        win = Win(comm, memory=CMemory(base, max(int(disp), 1), size))
        h = _new_win_handle(ctx, win, base, size, disp, 4,
                            keep=shared_blob["buf"])
        ctx.wins[h]["shared"] = {
            "bases": [shared_blob["base0"] + o for o in offs],
            "sizes": sizes, "units": units}
    else:
        buf = (ctypes.c_char * max(int(size), 1))()
        base = ctypes.addressof(buf)
        win = Win(comm, memory=CMemory(base, max(int(disp), 1), size))
        h = _new_win_handle(ctx, win, base, size, disp, 2, keep=buf)
    _write_i64(base_addr, ctx.wins[h]["base"])
    _write_i32(win_addr, h)
    return MPI_SUCCESS


def _h_win_create_dynamic(ctx, a):
    from .win import CMemory, Win
    ch, win_addr = a[1], a[2]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    # dynamic windows address by absolute MPI_Get_address values:
    # base 0, disp_unit 1 (MPI-3 §11.2.4)
    win = Win(comm, memory=CMemory(0, 1, 0))
    _write_i32(win_addr, _new_win_handle(ctx, win, 0, 0, 1, 3))
    return MPI_SUCCESS


def _h_win_attach(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["attached"].append((int(a[1]), int(a[2])))
    return MPI_SUCCESS


def _h_win_detach(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["attached"] = [(b, s) for b, s in entry["attached"]
                         if b != int(a[1])]
    return MPI_SUCCESS


def _h_win_shared_query(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    shared = entry.get("shared")
    if shared is None:
        return MPI_ERR_WIN
    rank = int(a[1])
    if rank == C_PROC_NULL:
        # first rank with a non-empty segment (MPI-3 §11.2.3)
        rank = next((i for i, s in enumerate(shared["sizes"]) if s), 0)
    _write_i64(a[2], shared["sizes"][rank])
    _write_i32(a[3], shared["units"][rank])
    _write_i64(a[4], shared["bases"][rank])
    return MPI_SUCCESS


def _h_win_free(ctx, a):
    h = int(ctypes.cast(int(a[0]), _pi32)[0]) if a[0] else 0
    entry = ctx.wins.get(h)
    if entry is not None:
        # delete-attr callbacks fire on free (MPI-3 §6.7.2)
        rc = _attrs_free_all(ctx, entry["attrs"], h)
        if rc != MPI_SUCCESS:
            return rc
        ctx.wins.pop(h, None)
        entry["win"].free()
    _write_i32(a[0], 0)
    return MPI_SUCCESS


def _h_win_fence(ctx, a):
    entry = _win_entry(ctx, a[1])
    if entry is None:
        return MPI_ERR_WIN
    entry["win"].fence(int(a[0]))
    return MPI_SUCCESS


def _h_win_get_attr(ctx, a):
    wh, kv, val_addr, flag_addr = int(a[0]), int(a[1]), a[2], a[3]
    entry = ctx.wins.get(wh)
    if entry is None:
        return MPI_ERR_WIN
    p64 = ctypes.cast(int(val_addr), _pi64)
    if kv == _WIN_BASE:
        p64[0] = entry["base"]
    elif kv == _WIN_SIZE:
        p64[0] = ctypes.addressof(entry["size_cell"])
    elif kv == _WIN_DISP:
        p64[0] = ctypes.addressof(entry["disp_cell"])
    elif kv == _WIN_FLAVOR_KV:
        p64[0] = ctypes.addressof(entry["flavor_cell"])
    elif kv == _WIN_MODEL_KV:
        p64[0] = ctypes.addressof(entry["model_cell"])
    else:
        stored = entry["attrs"].get(kv)
        if stored is None:
            _write_i32(flag_addr, 0)
            return MPI_SUCCESS
        p64[0] = stored
    _write_i32(flag_addr, 1)
    return MPI_SUCCESS


def _h_win_set_attr(ctx, a):
    entry = ctx.wins.get(int(a[0]))
    if entry is None:
        return MPI_ERR_WIN
    return _attrs_set(ctx, entry["attrs"], int(a[0]), int(a[1]),
                      int(a[2]))


def _h_win_delete_attr(ctx, a):
    entry = ctx.wins.get(int(a[0]))
    if entry is None:
        return MPI_ERR_WIN
    kv = int(a[1])
    if kv not in entry["attrs"]:
        return MPI_SUCCESS if kv >= 0 else MPI_ERR_KEYVAL
    return _attr_fire_delete(ctx, entry["attrs"], int(a[0]), kv)


def _h_win_keyval_create(ctx, a):
    _write_i32(a[2], _kv_new(ctx, a[0], a[1], a[3]))
    return MPI_SUCCESS


def _h_win_set_name(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["name"] = ctypes.string_at(int(a[1])).decode(errors="replace")[:127]
    return MPI_SUCCESS


def _h_win_get_name(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    name = entry["name"].encode()
    ctypes.memmove(int(a[1]), name + b"\0", len(name) + 1)
    _write_i32(a[2], len(name))
    return MPI_SUCCESS


def _h_win_get_group(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    group = entry["win"].comm.get_group()
    _write_i32(a[1], _new_group_handle(ctx, Group(group.world_ranks)))
    return MPI_SUCCESS


def _h_win_set_errhandler(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["errh"] = int(a[1])
    return MPI_SUCCESS


def _h_win_get_errhandler(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    _write_i32(a[1], entry["errh"])
    return MPI_SUCCESS


def _h_win_call_errhandler(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    return _invoke_errhandler(ctx, entry["errh"] or 2, int(a[0]),
                              int(a[1]))


def _rma_op_of(ctx, oph, dt):
    oph = int(oph)
    if oph == OP_REPLACE:
        return "replace"
    if oph == OP_NO_OP:
        return None
    return _op_of(ctx, oph, dt)


def _leaf_dt(dt: Datatype) -> Datatype:
    """The predefined leaf of a derived type (MPI restricts accumulate
    to a uniform predefined basic type; C-API derived types clear
    np_dtype because payloads travel packed)."""
    depth = 0
    while getattr(dt, "c_env_types", None) and depth < 64:
        dt = dt.c_env_types[0]
        depth += 1
    return dt


def _h_rma_put(ctx, a, with_req=False):
    obuf, ocount, odth, trank, tdisp, tcount, tdth, wh = a[:8]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    trank = int(trank)
    if trank == C_PROC_NULL or int(ocount) == 0 or int(tcount) == 0:
        if with_req:
            _write_i32(a[8], _new_req_handle(
                ctx, _CReq(_RmaReq(None), 0, None, "nbc")))
        return MPI_SUCCESS
    if trank < 0 or trank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    odt, tdt = _dt(ctx, odth), _dt(ctx, tdth)
    payload = _arr_in(obuf, ocount, odt)
    entry["win"].c_put(trank, (int(tdisp), int(tcount), tdt), payload,
                       int(ocount) * odt.size_)
    if with_req:
        _write_i32(a[8], _new_req_handle(
            ctx, _CReq(_RmaReq(None), 0, None, "nbc")))
    return MPI_SUCCESS


def _h_rma_get(ctx, a, with_req=False):
    obuf, ocount, odth, trank, tdisp, tcount, tdth, wh = a[:8]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    trank = int(trank)
    if trank == C_PROC_NULL or int(ocount) == 0 or int(tcount) == 0:
        if with_req:
            _write_i32(a[8], _new_req_handle(
                ctx, _CReq(_RmaReq(None), 0, None, "nbc")))
        return MPI_SUCCESS
    if trank < 0 or trank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    odt, tdt = _dt(ctx, odth), _dt(ctx, tdth)
    args = (int(tdisp), int(tcount), tdt)
    nbytes = int(tcount) * tdt.size_
    if with_req:
        comm = entry["win"].c_get_async(trank, args, nbytes)
        rreq = _RmaReq(comm, deliver=_scatter_closure(int(obuf), odt))
        entry["win"].register_async(rreq)
        _write_i32(a[8], _new_req_handle(ctx, _CReq(rreq, 0, None,
                                                    "nbc")))
        return MPI_SUCCESS
    payload = entry["win"].c_get(trank, args, nbytes)
    _arr_out(int(obuf), payload, dt=odt)
    return MPI_SUCCESS


def _scatter_closure(addr: int, dt: Datatype):
    def post(payload):
        _arr_out(addr, payload, dt=dt)
    return post


def _h_rma_acc(ctx, a, with_req=False):
    obuf, ocount, odth, trank, tdisp, tcount, tdth, oph, wh = a[:9]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    trank = int(trank)
    if trank == C_PROC_NULL or int(ocount) == 0 or int(tcount) == 0:
        if with_req:
            _write_i32(a[9], _new_req_handle(
                ctx, _CReq(_RmaReq(None), 0, None, "nbc")))
        return MPI_SUCCESS
    if trank < 0 or trank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    odt, tdt = _dt(ctx, odth), _dt(ctx, tdth)
    leaf = _leaf_dt(tdt)
    op = _rma_op_of(ctx, oph, leaf)
    payload = _arr_in(obuf, ocount, odt)
    entry["win"].c_acc(trank, (int(tdisp), int(tcount), tdt,
                               leaf.np_dtype), payload, op,
                       int(ocount) * odt.size_)
    if with_req:
        _write_i32(a[9], _new_req_handle(
            ctx, _CReq(_RmaReq(None), 0, None, "nbc")))
    return MPI_SUCCESS


def _h_rma_gacc(ctx, a, with_req=False):
    (obuf, ocount, odth, rbuf, rcount, rdth, trank, tdisp, tcount, tdth,
     oph, wh) = a[:12]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    trank = int(trank)
    if trank == C_PROC_NULL or int(tcount) == 0:
        if with_req:
            _write_i32(a[12], _new_req_handle(
                ctx, _CReq(_RmaReq(None), 0, None, "nbc")))
        return MPI_SUCCESS
    if trank < 0 or trank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    rdt, tdt = _dt(ctx, rdth), _dt(ctx, tdth)
    leaf = _leaf_dt(tdt)
    op = _rma_op_of(ctx, oph, leaf)
    if op is None or int(ocount) == 0:        # MPI_NO_OP: atomic read
        payload = np.zeros(0, np.uint8)
        nbytes = 0
    else:
        odt = _dt(ctx, odth)
        payload = _arr_in(obuf, ocount, odt)
        nbytes = int(ocount) * odt.size_
    args = (int(tdisp), int(tcount), tdt, leaf.np_dtype)
    if with_req:
        comm = entry["win"].c_gacc_async(trank, args, payload, op, nbytes)
        rreq = _RmaReq(comm, deliver=_scatter_closure(int(rbuf), rdt))
        entry["win"].register_async(rreq)
        _write_i32(a[12], _new_req_handle(ctx, _CReq(rreq, 0, None,
                                                     "nbc")))
        return MPI_SUCCESS
    old = entry["win"].c_gacc(trank, args, payload, op, nbytes)
    _arr_out(int(rbuf), old, dt=rdt)
    return MPI_SUCCESS


def _h_fetch_and_op(ctx, a):
    obuf, rbuf, dth, trank, tdisp, oph, wh = a[:7]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    trank = int(trank)
    if trank == C_PROC_NULL:
        return MPI_SUCCESS
    if trank < 0 or trank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    dt = _dt(ctx, dth)
    op = _rma_op_of(ctx, oph, dt)
    payload = (np.zeros(0, np.uint8) if op is None
               else _arr_in(obuf, 1, dt))
    old = entry["win"].c_gacc(trank, (int(tdisp), 1, dt, dt.np_dtype),
                              payload, op, dt.size_)
    _arr_out(int(rbuf), old, dt=dt)
    return MPI_SUCCESS


def _h_compare_and_swap(ctx, a):
    obuf, cbuf, rbuf, dth, trank, tdisp, wh = a[:7]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    trank = int(trank)
    if trank == C_PROC_NULL:
        return MPI_SUCCESS
    if trank < 0 or trank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    dt = _dt(ctx, dth)
    compare = _arr_in(cbuf, 1, dt)
    new = _arr_in(obuf, 1, dt)
    old = entry["win"].c_cas(trank, (int(tdisp), 1, dt), compare, new)
    _arr_out(int(rbuf), old, dt=dt)
    return MPI_SUCCESS


def _h_win_lock(ctx, a):
    lt, rank, assertion, wh = int(a[0]), int(a[1]), int(a[2]), a[3]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    if rank == C_PROC_NULL:
        return MPI_SUCCESS
    if rank < 0 or rank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    try:
        entry["win"].lock(lt, rank, assertion)
    except RuntimeError:
        return MPI_ERR_OTHER
    return MPI_SUCCESS


def _h_win_unlock(ctx, a):
    rank, wh = int(a[0]), a[1]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    if rank == C_PROC_NULL:
        return MPI_SUCCESS
    if rank < 0 or rank >= entry["win"].comm.size():
        return MPI_ERR_RANK
    entry["win"].unlock(rank)
    return MPI_SUCCESS


def _h_win_lock_all(ctx, a):
    entry = _win_entry(ctx, a[1])
    if entry is None:
        return MPI_ERR_WIN
    entry["win"].lock_all(int(a[0]))
    return MPI_SUCCESS


def _h_win_unlock_all(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["win"].unlock_all()
    return MPI_SUCCESS


def _h_win_flush(ctx, a, local=False):
    rank, wh = int(a[0]), a[1]
    entry = _win_entry(ctx, wh)
    if entry is None:
        return MPI_ERR_WIN
    if rank == C_PROC_NULL:
        return MPI_SUCCESS
    if not local:
        entry["win"].flush(rank)
    return MPI_SUCCESS


def _h_win_flush_all(ctx, a, local=False):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    if not local:
        entry["win"].flush_all()
    return MPI_SUCCESS


def _h_win_sync(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["win"].sync()
    return MPI_SUCCESS


def _win_group_ranks(entry, group: Group):
    cg = entry["win"].comm.get_group()
    return [cg.rank(w) for w in group.world_ranks]


def _h_win_start(ctx, a):
    gh, assertion, wh = a[0], int(a[1]), a[2]
    entry = _win_entry(ctx, wh)
    group = ctx.groups.get(int(gh))
    if entry is None or group is None:
        return MPI_ERR_WIN
    entry["win"].start(_win_group_ranks(entry, group), assertion)
    return MPI_SUCCESS


def _h_win_complete(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["win"].complete()
    return MPI_SUCCESS


def _h_win_post(ctx, a):
    gh, assertion, wh = a[0], int(a[1]), a[2]
    entry = _win_entry(ctx, wh)
    group = ctx.groups.get(int(gh))
    if entry is None or group is None:
        return MPI_ERR_WIN
    entry["win"].post(_win_group_ranks(entry, group), assertion)
    return MPI_SUCCESS


def _h_win_wait(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    entry["win"].wait()
    return MPI_SUCCESS


def _h_win_test(ctx, a):
    entry = _win_entry(ctx, a[0])
    if entry is None:
        return MPI_ERR_WIN
    _write_i32(a[1], 1 if entry["win"].test() else 0)
    return MPI_SUCCESS


def _h_type_struct(ctx, a):
    count, bl_addr, disp_addr, types_addr, out_addr = a[:5]
    n = int(count)
    blocklens = _read_i32s(bl_addr, n)
    disp_p = ctypes.cast(int(disp_addr), _pi64)
    displs = [disp_p[i] for i in range(n)]
    type_handles = _read_i32s(types_addr, n)
    types = [_dt(ctx, t) for t in type_handles]
    dt = Datatype.create_struct(blocklens, displs, types)
    dt.np_dtype = None
    segs = []
    for bl, d, child in zip(blocklens, displs, types):
        if child.size_ == 0:
            continue             # UB/LB markers carry no data
        segs.extend((int(d) + off, n)
                    for off, n in _replicate(child, bl, child.extent_))
    dt.c_segments = _coalesce(segs)
    # lb/ub per MPI-3 §4.1.7: min/max over placed children, overridden
    # by legacy MPI_LB/MPI_UB markers; without a UB marker the extent is
    # padded to the most-aligned member (the standard's epsilon)
    lb = ub = None
    lb_mark = ub_mark = None
    align = 1
    for bl, d, th, child in zip(blocklens, displs, type_handles, types):
        if th == 42:             # MPI_LB
            lb_mark = int(d) if lb_mark is None else min(lb_mark, int(d))
            continue
        if th == 41:             # MPI_UB
            ub_mark = int(d) if ub_mark is None else max(ub_mark, int(d))
            continue
        if bl <= 0:
            continue
        align = max(align, _align_of(child))
        clb, cub = _lbub_of(child)
        for i in (0, bl - 1):
            base = int(d) + i * child.extent_
            lb = base + clb if lb is None else min(lb, base + clb)
            ub = base + cub if ub is None else max(ub, base + cub)
    if lb is None:
        lb = 0
    if ub is None:
        ub = lb
    if lb_mark is not None:
        lb = lb_mark
    if ub_mark is not None:
        ub = ub_mark
    elif align > 1:
        ub += (align - (ub - lb) % align) % align
    dt.c_lb = lb
    dt.extent_ = ub - lb
    dt.c_align = align
    basics = []
    for bl, child in zip(blocklens, types):
        cb = _basics_of(child)
        if len(basics) + bl * len(cb) > 4096:
            basics = None             # degrade precision for huge maps
            break
        basics.extend(cb * bl)
    dt.c_basics = basics if basics is not None else [dt.size_ or 1]
    dt.c_env = (C_COMBINER_STRUCT, [n] + list(blocklens),
                [int(d) for d in displs], list(type_handles))
    dt.c_env_types = list(types)
    _write_i32(out_addr, _new_dtype_handle(ctx, dt))
    return MPI_SUCCESS


def _read_i64s(addr: int, n: int) -> List[int]:
    p = ctypes.cast(int(addr), _pi64)
    return [p[i] for i in range(n)]


def _derived(ctx, out_addr, old, size, extent, segs, name) -> Datatype:
    """Register a derived type and return it (handlers attach their
    envelope/basics afterwards)."""
    dt = Datatype(size, None, name, extent)
    dt.c_align = _align_of(old)
    # typemap ORDER is definitional (MPI_Pack serializes in map order —
    # a transpose type packs columns, not ascending addresses), so
    # segments are kept in construction order, never sorted
    dt.c_segments = segs if isinstance(segs, _StridedSegs) \
        else _coalesce(segs)
    if dt.c_segments == [(0, size)] and extent == size:
        dt.np_dtype = old.np_dtype       # degenerate-contiguous
    _write_i32(out_addr, _new_dtype_handle(ctx, dt))
    return dt


def _h_type_indexed(ctx, a):
    count, bl_addr, disp_addr, oldh, out_addr, in_bytes = (
        int(a[0]), a[1], a[2], a[3], a[4], int(a[5]))
    old = _dt(ctx, oldh)
    bls = _read_i32s(bl_addr, count)
    displs = (_read_i64s(disp_addr, count) if in_bytes
              else _read_i32s(disp_addr, count))
    unit = 1 if in_bytes else old.extent_
    segs = []
    ext = 0
    for bl, d in zip(bls, displs):
        if bl <= 0:
            continue   # zero blocks carry no data and no bounds
        base = int(d) * unit
        segs.extend((base + off, n)
                    for off, n in _replicate(old, bl, old.extent_))
        ext = max(ext, base + bl * old.extent_)
    dt = _derived(ctx, out_addr, old, sum(bls) * old.size_, ext, segs,
                  "hindexed" if in_bytes else "indexed")
    dt.c_basics = _basics_of(old)
    _set_bounds(dt, [int(d) * unit + i * old.extent_
                     for bl, d in zip(bls, displs) if bl > 0
                     for i in (0, bl - 1)], old)
    if in_bytes:
        dt.c_env = (C_COMBINER_HINDEXED, [count] + list(bls),
                    [int(d) for d in displs], [int(oldh)])
    else:
        dt.c_env = (C_COMBINER_INDEXED,
                    [count] + list(bls) + [int(d) for d in displs], [],
                    [int(oldh)])
    dt.c_env_types = [old]
    return MPI_SUCCESS


def _h_type_hvector(ctx, a):
    count, blocklen, stride, oldh, out_addr = (int(a[0]), int(a[1]),
                                               int(a[2]), a[3], a[4])
    old = _dt(ctx, oldh)
    block = _replicate(old, blocklen, old.extent_)
    if block == [(0, stride)]:
        segs = [(0, count * stride)] if count else []
    elif count * max(len(block), 1) > _SEG_CAP:
        segs = _StridedSegs(count, stride, block)
    else:
        segs = [(b * stride + off, n)
                for b in range(count) for off, n in block]
    ext = (count - 1) * stride + blocklen * old.extent_ if count else 0
    dt = _derived(ctx, out_addr, old,
                  count * blocklen * old.size_, max(ext, 0), segs,
                  "hvector")
    dt.c_basics = _basics_of(old)
    if count > 0 and blocklen > 0:
        _set_bounds(dt, [b * stride + i * old.extent_
                         for b in (0, count - 1)
                         for i in (0, blocklen - 1)], old)
    dt.c_env = (C_COMBINER_HVECTOR, [count, blocklen], [stride],
                [int(a[3])])
    dt.c_env_types = [old]
    return MPI_SUCCESS


def _h_type_indexed_block(ctx, a):
    count, blocklen, disp_addr, oldh, out_addr, in_bytes = (
        int(a[0]), int(a[1]), a[2], a[3], a[4], int(a[5]))
    old = _dt(ctx, oldh)
    displs = (_read_i64s(disp_addr, count) if in_bytes
              else _read_i32s(disp_addr, count))
    unit = 1 if in_bytes else old.extent_
    block = _replicate(old, blocklen, old.extent_)
    segs = []
    ext = 0
    for d in displs:
        base = int(d) * unit
        segs.extend((base + off, n) for off, n in block)
        ext = max(ext, base + blocklen * old.extent_)
    dt = _derived(ctx, out_addr, old,
                  count * blocklen * old.size_, ext, segs,
                  "indexed_block")
    dt.c_basics = _basics_of(old)
    if blocklen > 0:
        _set_bounds(dt, [int(d) * unit + i * old.extent_
                         for d in displs for i in (0, blocklen - 1)], old)
    if in_bytes:
        dt.c_env = (C_COMBINER_HINDEXED_BLOCK, [count, blocklen],
                    [int(d) for d in displs], [int(oldh)])
    else:
        dt.c_env = (C_COMBINER_INDEXED_BLOCK,
                    [count, blocklen] + [int(d) for d in displs], [],
                    [int(oldh)])
    dt.c_env_types = [old]
    return MPI_SUCCESS


def _h_type_dup(ctx, a):
    old = _dt(ctx, a[0])
    # MPI_Type_dup is the one type constructor that copies attributes
    # (MPI-3 §6.7.4; attr/fkeyvaltype)
    err, new_attrs = _attrs_copy_all(ctx, ctx.type_attrs.get(int(a[0]),
                                                             {}),
                                     int(a[0]))
    if err != MPI_SUCCESS:
        _write_i32(a[1], 0)
        return err
    dt = Datatype(old.size_, old.np_dtype, old.name, old.extent_)
    dt.c_segments = _segments_of(old)
    dt.c_basics = list(_basics_of(old))
    dt.c_lb = int(getattr(old, "c_lb", 0))
    dt.c_env = (C_COMBINER_DUP, [], [], [int(a[0])])
    dt.c_env_types = [old]
    h = _new_dtype_handle(ctx, dt)
    if new_attrs:
        ctx.type_attrs[h] = new_attrs
    _write_i32(a[1], h)
    return MPI_SUCCESS


def _h_type_subarray(ctx, a):
    ndims, sizes_a, subs_a, starts_a, order, oldh, out_addr = (
        int(a[0]), a[1], a[2], a[3], int(a[4]), a[5], a[6])
    old = _dt(ctx, oldh)
    sizes = _read_i32s(sizes_a, ndims)
    subs = _read_i32s(subs_a, ndims)
    starts = _read_i32s(starts_a, ndims)
    if order == 57:          # MPI_ORDER_FORTRAN: mirror to C order
        sizes, subs, starts = sizes[::-1], subs[::-1], starts[::-1]
    # C order: last dim contiguous; element strides per dim
    strides = [1] * ndims
    for d in range(ndims - 2, -1, -1):
        strides[d] = strides[d + 1] * sizes[d + 1]

    segs = []

    def walk(d, elem_off):
        if d == ndims - 1:
            base = (elem_off + starts[d]) * old.extent_
            segs.extend((base + off, n)
                        for off, n in _replicate(old, subs[d],
                                                 old.extent_))
            return
        for i in range(subs[d]):
            walk(d + 1, elem_off + (starts[d] + i) * strides[d])

    walk(0, 0)
    total = 1
    nsub = 1
    for s in sizes:
        total *= s
    for s in subs:
        nsub *= s
    dt = _derived(ctx, out_addr, old, nsub * old.size_,
                  total * old.extent_, segs, "subarray")
    dt.c_basics = _basics_of(old)
    osizes = _read_i32s(sizes_a, ndims)
    osubs = _read_i32s(subs_a, ndims)
    ostarts = _read_i32s(starts_a, ndims)
    dt.c_env = (C_COMBINER_SUBARRAY,
                [ndims] + osizes + osubs + ostarts + [int(order)], [],
                [int(oldh)])
    dt.c_env_types = [old]
    return MPI_SUCCESS


def _h_type_resized(ctx, a):
    old, lb, extent, out_addr = _dt(ctx, a[0]), int(a[1]), int(a[2]), a[3]
    dt = Datatype(old.size_, old.np_dtype, f"resized({old.name})",
                  extent)
    dt.c_segments = _segments_of(old)
    dt.c_basics = list(_basics_of(old))
    dt.c_lb = lb
    dt.c_env = (C_COMBINER_RESIZED, [], [lb, extent], [int(a[0])])
    dt.c_env_types = [old]
    _write_i32(out_addr, _new_dtype_handle(ctx, dt))
    return MPI_SUCCESS


def _h_type_get_name(ctx, a):
    dt = _dt(ctx, a[0])
    if int(a[3]):                # set mode
        # NUL-terminated read (a fixed-width read could walk past the
        # end of a short caller buffer), truncated per MPI to 127 chars
        raw = ctypes.string_at(int(a[1]))[:127]
        dt.name = raw.decode(errors="replace")
        return MPI_SUCCESS
    name = (dt.name or "").encode()[:127]
    ctypes.memmove(int(a[1]), name + b"\0", len(name) + 1)
    _write_i32(a[2], len(name))
    return MPI_SUCCESS


# -- cartesian topologies ----------------------------------------------------

def _h_cart_create(ctx, a):
    from .group import Group as _Group
    from .topo import CartTopology
    ch, ndims, dims_addr, per_addr, _reorder, out_addr = a[:6]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = int(ndims)
    dims = _read_i32s(dims_addr, n)
    periods = _read_i32s(per_addr, n)
    nnodes = 1
    for d in dims:
        nnodes *= d
    grid = comm.create(_Group(
        [comm.world_rank_of(r) for r in range(nnodes)]))
    if grid is None:
        _write_i32(out_addr, COMM_NULL)
        return MPI_SUCCESS
    h = _new_comm_handle(ctx, grid)
    ctx.cart_topos[h] = CartTopology(grid, dims, periods)
    _write_i32(out_addr, h)
    return MPI_SUCCESS


def _cart_of(ctx, handle):
    return ctx.cart_topos.get(int(handle))


def _h_cart_get(ctx, a):
    topo = _cart_of(ctx, a[0])
    if topo is None:
        return MPI_ERR_COMM
    maxdims = int(a[1])
    dims, periods, coords = topo.get()
    for i in range(min(maxdims, len(dims))):
        ctypes.cast(int(a[2]), _pi32)[i] = dims[i]
        ctypes.cast(int(a[3]), _pi32)[i] = 1 if periods[i] else 0
        ctypes.cast(int(a[4]), _pi32)[i] = coords[i]
    return MPI_SUCCESS


def _h_cart_rank(ctx, a):
    topo = _cart_of(ctx, a[0])
    if topo is None:
        return MPI_ERR_COMM
    coords = _read_i32s(a[1], len(topo.dims))
    _write_i32(a[2], topo.rank(coords))
    return MPI_SUCCESS


def _h_cart_coords(ctx, a):
    topo = _cart_of(ctx, a[0])
    if topo is None:
        return MPI_ERR_COMM
    coords = topo.coords(int(a[1]))
    for i in range(min(int(a[2]), len(coords))):
        ctypes.cast(int(a[3]), _pi32)[i] = coords[i]
    return MPI_SUCCESS


def _h_cart_shift(ctx, a):
    topo = _cart_of(ctx, a[0])
    if topo is None:
        return MPI_ERR_COMM
    src, dst = topo.shift(int(a[1]), int(a[2]))
    _write_i32(a[3], C_PROC_NULL if src is None or src < 0 else src)
    _write_i32(a[4], C_PROC_NULL if dst is None or dst < 0 else dst)
    return MPI_SUCCESS


def _h_cart_sub(ctx, a):
    from .group import Group as _Group
    from .topo import CartTopology
    topo = _cart_of(ctx, a[0])
    comm = _comm_of(ctx, a[0])
    if topo is None or comm is None:
        return MPI_ERR_COMM
    remain = [bool(v) for v in _read_i32s(a[1], len(topo.dims))]
    me = topo.coords(comm.rank())
    if not any(remain):
        # dropping every dimension behaves like Cart_create(ndims=0):
        # only rank 0 gets the zero-dim communicator (MPICH semantics,
        # topo/cartsuball)
        members = [0]
    else:
        members = [r for r in range(topo.nnodes)
                   if all(keep or topo.coords(r)[i] == me[i]
                          for i, keep in enumerate(remain))]
    sub = comm.create(_Group([comm.world_rank_of(r) for r in members]))
    if sub is None:
        _write_i32(a[2], COMM_NULL)
        return MPI_SUCCESS
    h = _new_comm_handle(ctx, sub)
    sub_dims = [d for d, keep in zip(topo.dims, remain) if keep]
    sub_per = [p for p, keep in zip(topo.periodic, remain) if keep]
    # a zero-dimensional result is still a cartesian communicator
    # (topo/cartzero expects Cartdim_get == 0 on it)
    ctx.cart_topos[h] = CartTopology(sub, sub_dims, sub_per)
    _write_i32(a[2], h)
    return MPI_SUCCESS


def _h_cartdim_get(ctx, a):
    topo = _cart_of(ctx, a[0])
    if topo is None:
        return MPI_ERR_COMM
    _write_i32(a[1], len(topo.dims))
    return MPI_SUCCESS


def _h_dims_create(ctx, a):
    from .topo import dims_create
    nnodes, ndims, dims_addr = int(a[0]), int(a[1]), a[2]
    dims = _read_i32s(dims_addr, ndims)
    out = dims_create(nnodes, ndims, dims)
    for i, d in enumerate(out):
        ctypes.cast(int(dims_addr), _pi32)[i] = d
    return MPI_SUCCESS


def _h_topo_test(ctx, a):
    h = int(a[0])
    if _cart_of(ctx, h) is not None:
        _write_i32(a[1], 1)                    # MPI_CART
    elif h in ctx.graph_topos:
        topo = ctx.graph_topos[h]
        from .topo import DistGraphTopology
        _write_i32(a[1], 3 if isinstance(topo, DistGraphTopology) else 2)
    else:
        _write_i32(a[1], C_UNDEFINED)
    return MPI_SUCCESS


def _h_topo_map(ctx, a):
    """MPI_Cart_map / MPI_Graph_map without reordering (like the
    reference smpi): ranks below the topology size keep their rank,
    the rest get MPI_UNDEFINED."""
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    mode = int(a[4])
    if mode == 0:                 # cart: size = prod(dims)
        ndims = int(a[1])
        dims = _read_i32s(a[2], ndims)
        size = 1
        for d in dims:
            size *= d
    else:                         # graph: nnodes, by value
        size = int(a[2])
    rank = comm.rank()
    _write_i32(a[3], rank if rank < size else C_UNDEFINED)
    return MPI_SUCCESS


def _weights_ptr(addr):
    """Readable address, or None for the MPI_UNWEIGHTED(1) /
    MPI_WEIGHTS_EMPTY(2) / NULL sentinels.  Note only MPI_UNWEIGHTED
    makes the GRAPH unweighted — WEIGHTS_EMPTY just means this rank
    contributes zero edges to a weighted graph."""
    return None if int(addr) in (0, 1, 2) else int(addr)


def _is_unweighted(addr) -> bool:
    return int(addr) == 1          # MPI_UNWEIGHTED


def _h_dist_graph_create(ctx, a):
    from .topo import DistGraphTopology
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    me = comm.rank()
    if int(a[7]):                 # adjacent: my lists are explicit
        indeg, outdeg = int(a[1]), int(a[3])
        sources = _read_i32s(a[2], indeg)
        dests = _read_i32s(a[4], outdeg)
        weighted = not (_is_unweighted(a[5]) and _is_unweighted(a[8]))
        swp, dwp = _weights_ptr(a[5]), _weights_ptr(a[8])
        sweights = (_read_i32s(swp, indeg) if swp else []) \
            if weighted else None
        dweights = (_read_i32s(dwp, outdeg) if dwp else []) \
            if weighted else None
    else:
        # general form: every rank contributes (source, deg, dests[,w])
        # triples naming arbitrary edges; allgather and filter mine
        n = int(a[1])
        srcs = _read_i32s(a[2], n)
        degs = _read_i32s(a[3], n)
        total = sum(degs)
        dests_flat = _read_i32s(a[4], total)
        weighted = not _is_unweighted(a[5])
        wp = _weights_ptr(a[5])
        w_flat = _read_i32s(wp, total) if wp else [0] * total
        edges = []
        pos = 0
        for src, deg in zip(srcs, degs):
            for k in range(deg):
                edges.append((src, dests_flat[pos + k], w_flat[pos + k]))
            pos += deg
        all_edges = [e for part in comm.allgather(edges) for e in part]
        sources = [s for s, d, w in all_edges if d == me]
        dests = [d for s, d, w in all_edges if s == me]
        # weighted-ness is collective: any contributor with real
        # weights makes the graph weighted
        weighted = any(comm.allgather(weighted))
        sweights = [w for s, d, w in all_edges if d == me] \
            if weighted else None
        dweights = [w for s, d, w in all_edges if s == me] \
            if weighted else None
    grid = comm.dup()
    h = _new_comm_handle(ctx, grid)
    ctx.graph_topos[h] = DistGraphTopology(grid, sources, dests,
                                           sweights, dweights)
    _write_i32(a[6], h)
    return MPI_SUCCESS


def _h_dist_graph_neighbors(ctx, a):
    from .topo import DistGraphTopology
    topo = ctx.graph_topos.get(int(a[0]))
    if not isinstance(topo, DistGraphTopology):
        return MPI_ERR_COMM
    if int(a[7]) == 0:            # counts
        _write_i32(a[1], len(topo.sources))
        _write_i32(a[2], len(topo.destinations))
        _write_i32(a[3], 1 if topo.weighted else 0)
        return MPI_SUCCESS
    maxin, maxout = int(a[1]), int(a[4])
    pi = ctypes.cast(int(a[2]), _pi32) if a[2] else None
    po = ctypes.cast(int(a[5]), _pi32) if a[5] else None
    for i, v in enumerate(topo.sources[:maxin]):
        pi[i] = v
    for i, v in enumerate(topo.destinations[:maxout]):
        po[i] = v
    swp, dwp = _weights_ptr(a[3]), _weights_ptr(a[6])
    if topo.weighted and swp:
        pw = ctypes.cast(swp, _pi32)
        for i, v in enumerate(topo.source_weights[:maxin]):
            pw[i] = v
    if topo.weighted and dwp:
        pw = ctypes.cast(dwp, _pi32)
        for i, v in enumerate(topo.dest_weights[:maxout]):
            pw[i] = v
    return MPI_SUCCESS


def _h_pack(ctx, a):
    """Pack (direction 0): typed buffer -> contiguous bytes at
    *position; Unpack (1): the reverse. The shim swapped args so both
    directions share (typed_buf, count, dt, packed_buf, size, pos)."""
    typed_buf, count, dth, packed_buf, packed_size, pos_addr, _ch, \
        direction = a[:8]
    dt = _dt(ctx, dth)
    pos = ctypes.cast(int(pos_addr), _pi32)[0]
    count = int(count)
    struct_sz = dt.size_
    mpi_sz = int(getattr(dt, "c_mpi_size", struct_sz))
    basics = list(getattr(dt, "c_basics", ()) or ())
    # value+index pair types pack at their MPI size (6 for SHORT_INT),
    # not their padded C struct size (8): strip/reinsert the ABI
    # padding between the two members (datatype/pairtype-pack)
    paired = mpi_sz != struct_sz and len(basics) == 2
    per = mpi_sz if paired else struct_sz
    nbytes = count * per
    if pos + nbytes > int(packed_size):
        return MPI_ERR_OTHER
    if paired:
        b0, b1 = basics
        off1 = -(-b0 // b1) * b1        # member 1 at its alignment
    if int(direction) == 0:
        arr = _arr_in(typed_buf, count, dt)     # gather through typemap
        data = np.ascontiguousarray(arr).tobytes()
        if paired:
            rows = np.frombuffer(data, np.uint8).reshape(count,
                                                         struct_sz)
            packed = np.empty((count, per), np.uint8)
            packed[:, :b0] = rows[:, :b0]
            packed[:, b0:per] = rows[:, off1:off1 + b1]
            data = packed.tobytes()
        ctypes.memmove(int(packed_buf) + pos, data, nbytes)
    else:
        raw = ctypes.string_at(int(packed_buf) + pos, nbytes)
        if paired:
            rows = np.frombuffer(raw, np.uint8).reshape(count, per)
            structs = np.zeros((count, struct_sz), np.uint8)
            structs[:, :b0] = rows[:, :b0]
            structs[:, off1:off1 + b1] = rows[:, b0:per]
            raw = structs.tobytes()
        arr = np.frombuffer(bytearray(raw), np.uint8)
        _arr_out(typed_buf, arr, dt=dt)         # scatter through typemap
    ctypes.cast(int(pos_addr), _pi32)[0] = pos + nbytes
    return MPI_SUCCESS


def _h_graph_create(ctx, a):
    from .topo import GraphTopology
    ch, nnodes, index_a, edges_a, _reorder, out_addr = a[:6]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    nnodes = int(nnodes)
    index = _read_i32s(index_a, nnodes)
    nedges = index[-1] if index else 0
    edges = _read_i32s(edges_a, nedges)
    if nnodes < comm.size():
        # MPI-3 §7.5.3: ranks beyond nnodes (everyone, for an empty
        # graph) get MPI_COMM_NULL; the creation stays collective
        members = [comm.group.actor(r) for r in range(nnodes)]
        grid = comm.create(Group(members))
        if grid is None:
            _write_i32(out_addr, 0)
            return MPI_SUCCESS
    else:
        grid = comm.dup()
    h = _new_comm_handle(ctx, grid)
    ctx.graph_topos[h] = GraphTopology(grid, index, edges)
    _write_i32(out_addr, h)
    return MPI_SUCCESS


def _graph_topo_of(ctx, handle):
    """Legacy-graph topology lookup; dist-graph comms do not answer
    the MPI-1 graph queries (MPI_ERR_TOPOLOGY analog)."""
    from .topo import GraphTopology
    topo = ctx.graph_topos.get(int(handle))
    return topo if isinstance(topo, GraphTopology) else None


def _h_graph_neighbors(ctx, a):
    ch, rank, maxn, out_addr, count_only = (a[0], int(a[1]), int(a[2]),
                                            a[3], int(a[4]))
    topo = _graph_topo_of(ctx, ch)
    if topo is None:
        return MPI_ERR_COMM
    nbrs = topo.neighbors(rank)
    if count_only:
        _write_i32(out_addr, len(nbrs))
        return MPI_SUCCESS
    for i, nb in enumerate(nbrs[:maxn]):
        ctypes.cast(int(out_addr), _pi32)[i] = nb
    return MPI_SUCCESS


def _h_graphdims_get(ctx, a):
    topo = _graph_topo_of(ctx, a[0])
    if topo is None:
        return MPI_ERR_COMM
    _write_i32(a[1], len(topo.index))
    _write_i32(a[2], len(topo.edges))
    return MPI_SUCCESS


def _h_graph_get(ctx, a):
    ch, maxindex, maxedges, index_addr, edges_addr = (a[0], int(a[1]),
                                                      int(a[2]), a[3],
                                                      a[4])
    topo = _graph_topo_of(ctx, ch)
    if topo is None:
        return MPI_ERR_COMM
    for i, v in enumerate(topo.index[:maxindex]):
        ctypes.cast(int(index_addr), _pi32)[i] = v
    for i, v in enumerate(topo.edges[:maxedges]):
        ctypes.cast(int(edges_addr), _pi32)[i] = v
    return MPI_SUCCESS


# -- non-blocking collectives -----------------------------------------------

def _nbc_handle(ctx, req, req_addr, post=None) -> int:
    h = _new_req_handle(ctx, _CReq(req, 0, None, "nbc", post=post))
    _write_i32(req_addr, h)
    return MPI_SUCCESS


def _h_ibarrier(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    return _nbc_handle(ctx, comm.ibarrier(), a[1])


def _h_ibcast(ctx, a):
    buf, count, dth, root, ch, req_addr = a[:6]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    root = int(root)
    if _is_inter(comm):
        obj = _arr_in(buf, count, dt) if root == C_ROOT else None
        req = comm.ibcast(obj, root)
        post = None
        if root >= 0:                       # leaf side receives
            post = lambda res: _arr_out(buf, res,
                                        int(count) * dt.size_, dt=dt)
        return _nbc_handle(ctx, req, req_addr, post)
    me = comm.rank()
    obj = _arr_in(buf, count, dt) if me == root else None
    req = comm.ibcast(obj, root)
    post = None
    if me != root:
        post = lambda res: _arr_out(buf, res, int(count) * dt.size_,
                                    dt=dt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_ireduce(ctx, a):
    sbuf, rbuf, count, dth, oph, root, ch, req_addr = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    root = int(root)
    if _is_inter(comm) and root in (C_ROOT, C_PROC_NULL):
        arr = np.zeros(0) if int(sbuf) in (0, C_IN_PLACE) \
            else _arr_in(sbuf, count, dt)
        req = comm.ireduce(arr, _op_of(ctx, oph, dt, dt_handle=dth,
                                       count=int(count)), root)
        post = None
        if root == C_ROOT:
            post = lambda res: _arr_out(
                rbuf, np.asarray(res), int(count) * dt.size_, dt=dt)
        return _nbc_handle(ctx, req, req_addr, post)
    arr = _arr_in(rbuf if int(sbuf) == C_IN_PLACE else sbuf, count, dt)
    op = _op_of(ctx, oph, dt, dt_handle=dth, count=int(count))
    req = comm.ireduce(arr, op, root)
    post = None
    if not _is_inter(comm) and comm.rank() == root:
        post = lambda res: _arr_out(
            rbuf, np.asarray(res).astype(arr.dtype, copy=False),
            int(count) * dt.size_, dt=dt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_iallreduce(ctx, a):
    sbuf, rbuf, count, dth, oph, ch, req_addr = a[:7]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    arr = _arr_in(rbuf if int(sbuf) == C_IN_PLACE else sbuf, count, dt)
    op = _op_of(ctx, oph, dt, dt_handle=dth, count=int(count))
    req = comm.iallreduce(arr, op)
    post = lambda res: _arr_out(
        rbuf, np.asarray(res).astype(arr.dtype, copy=False),
        int(count) * dt.size_, dt=dt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_igather(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, root, ch, req_addr = a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root = comm.rank(), int(root)
    if int(sbuf) == C_IN_PLACE and me == root:
        rdt0 = _dt(ctx, rtype)
        arr = _arr_in(int(rbuf) + me * int(rcount) * rdt0.extent_,
                      rcount, rdt0)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    req = comm.igather(arr, root)
    post = None
    if me == root:
        rdt = _dt(ctx, rtype)
        stride = int(rcount) * rdt.extent_

        def post(res):
            for i, obj in enumerate(res):
                _arr_out(int(rbuf) + i * stride, obj,
                         int(rcount) * rdt.size_, dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_iscatter(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, root, ch, req_addr = a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root, n = comm.rank(), int(root), comm.size()
    sendobjs = None
    if me == root:
        sdt = _dt(ctx, stype)
        stride = int(scount) * sdt.extent_
        sendobjs = [_arr_in(int(sbuf) + i * stride, scount, sdt)
                    for i in range(n)]
    req = comm.iscatter(sendobjs, root)
    rdt = _dt(ctx, rtype)
    post = lambda res: _arr_out(rbuf, res, int(rcount) * rdt.size_)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_iallgather(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, ch, req_addr = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    rdt = _dt(ctx, rtype)
    stride = int(rcount) * rdt.extent_
    if int(sbuf) == C_IN_PLACE:
        arr = _arr_in(int(rbuf) + comm.rank() * stride, rcount, rdt)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    req = comm.iallgather(arr)

    def post(res):
        for i, obj in enumerate(res):
            _arr_out(int(rbuf) + i * stride, obj,
                     int(rcount) * rdt.size_, dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_ialltoall(ctx, a):
    sbuf, scount, stype, rbuf, rcount, rtype, ch, req_addr = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.size()
    rdt = _dt(ctx, rtype)
    rstride = int(rcount) * rdt.extent_
    if int(sbuf) == C_IN_PLACE:
        sendobjs = [_arr_in(int(rbuf) + i * rstride, rcount, rdt)
                    for i in range(n)]
    else:
        sdt = _dt(ctx, stype)
        sstride = int(scount) * sdt.extent_
        sendobjs = [_arr_in(int(sbuf) + i * sstride, scount, sdt)
                    for i in range(n)]
    req = comm.ialltoall(sendobjs)

    def post(res):
        for i, obj in enumerate(res):
            _arr_out(int(rbuf) + i * rstride, obj,
                     int(rcount) * rdt.size_, dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_alltoallw(ctx, a):
    """Per-peer counts/byte-displacements/TYPES (the most general
    alltoall); payloads already carry their own sizes, so the v
    machinery serves (smpi equivalent of Coll_alltoallw)."""
    sbuf, scounts, sdispls, stypes, rbuf, rcounts, rdispls, rtypes, ch = \
        a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.remote_size() if _is_inter(comm) else comm.size()
    rc = _read_i32s(rcounts, n)
    ro = _read_i32s(rdispls, n)
    rt = _read_i32s(rtypes, n)
    if int(sbuf) != C_IN_PLACE:
        # the s-side arrays are NULL under MPI_IN_PLACE (MPI-2.2)
        sc = _read_i32s(scounts, n)
        so = _read_i32s(sdispls, n)   # BYTE displacements in alltoallw
        st = _read_i32s(stypes, n)
    if int(sbuf) == C_IN_PLACE:
        # the send blocks alias the receive buffer: COPY them now, or
        # a peer still reading our block sees it already overwritten
        # by our own incoming results (payloads travel by reference
        # inside the simulator; coll/alltoallw2's IN_PLACE section)
        sendobjs = [np.array(_arr_in(int(rbuf) + ro[i], rc[i],
                                     _dt(ctx, rt[i])), copy=True)
                    for i in range(n)]
    else:
        sendobjs = [_arr_in(int(sbuf) + so[i], sc[i], _dt(ctx, st[i]))
                    for i in range(n)]
    res = comm.alltoallv(sendobjs)
    for i, obj in enumerate(res):
        rdt = _dt(ctx, rt[i])
        _arr_out(int(rbuf) + ro[i], obj, rc[i] * rdt.size_, dt=rdt)
    return MPI_SUCCESS


def _h_ialltoallw(ctx, a):
    sbuf, scounts, sdispls, stypes, rbuf, rcounts, rdispls, rtypes, ch, \
        req_addr = a[:10]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.size()
    rc = _read_i32s(rcounts, n)
    ro = _read_i32s(rdispls, n)
    rt = _read_i32s(rtypes, n)
    if int(sbuf) == C_IN_PLACE:
        # the s-side arrays are NULL under MPI_IN_PLACE, and the send
        # blocks alias the receive buffer: copy (see _h_alltoallw)
        sendobjs = [np.array(_arr_in(int(rbuf) + ro[i], rc[i],
                                     _dt(ctx, rt[i])), copy=True)
                    for i in range(n)]
    else:
        sc = _read_i32s(scounts, n)
        so = _read_i32s(sdispls, n)
        st = _read_i32s(stypes, n)
        sendobjs = [_arr_in(int(sbuf) + so[i], sc[i], _dt(ctx, st[i]))
                    for i in range(n)]
    req = comm.ialltoall(sendobjs)

    def post(res):
        for i, obj in enumerate(res):
            rdt = _dt(ctx, rt[i])
            _arr_out(int(rbuf) + ro[i], obj, rc[i] * rdt.size_, dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_iscatterv(ctx, a):
    sbuf, scounts, displs, stype, rbuf, rcount, rtype, root, ch, \
        req_addr = a[:10]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root, n = comm.rank(), int(root), comm.size()
    sendobjs = None
    if me == root:
        sdt = _dt(ctx, stype)
        counts = _read_i32s(scounts, n)
        offs = _read_i32s(displs, n)
        sendobjs = [_arr_in(int(sbuf) + offs[i] * sdt.extent_, counts[i],
                            sdt) for i in range(n)]
    req = comm.iscatter(sendobjs, root)
    rdt = _dt(ctx, rtype)
    if me == root and int(rbuf) == C_IN_PLACE:
        post = None
    else:
        post = lambda res: _arr_out(rbuf, res, int(rcount) * rdt.size_,
                                    dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_igatherv(ctx, a):
    sbuf, scount, stype, rbuf, rcounts, displs, rtype, root, ch, \
        req_addr = a[:10]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    me, root, n = comm.rank(), int(root), comm.size()
    if int(sbuf) == C_IN_PLACE and me == root:
        rdt0 = _dt(ctx, rtype)
        arr = _arr_in(
            int(rbuf) + _read_i32s(displs, n)[me] * rdt0.extent_,
            _read_i32s(rcounts, n)[me], rdt0)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    req = comm.igather(arr, root)
    post = None
    if me == root:
        rdt = _dt(ctx, rtype)
        counts = _read_i32s(rcounts, n)
        offs = _read_i32s(displs, n)

        def post(res):
            for i, obj in enumerate(res):
                _arr_out(int(rbuf) + offs[i] * rdt.extent_, obj,
                         counts[i] * rdt.size_, dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_iallgatherv(ctx, a):
    sbuf, scount, stype, rbuf, rcounts, displs, rtype, ch, req_addr = \
        a[:9]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.size()
    rdt = _dt(ctx, rtype)
    counts = _read_i32s(rcounts, n)
    offs = _read_i32s(displs, n)
    if int(sbuf) == C_IN_PLACE:
        me = comm.rank()
        arr = _arr_in(int(rbuf) + offs[me] * rdt.extent_, counts[me],
                      rdt)
    else:
        arr = _arr_in(sbuf, scount, _dt(ctx, stype))
    req = comm.iallgather(arr)

    def post(res):
        for i, obj in enumerate(res):
            _arr_out(int(rbuf) + offs[i] * rdt.extent_, obj,
                     counts[i] * rdt.size_, dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_ialltoallv(ctx, a):
    sbuf, scounts, sdispls, stype, rbuf, rcounts, rdispls, rtype, ch, \
        req_addr = a[:10]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.size()
    rdt = _dt(ctx, rtype)
    rc = _read_i32s(rcounts, n)
    ro = _read_i32s(rdispls, n)
    if int(sbuf) == C_IN_PLACE:
        sendobjs = [_arr_in(int(rbuf) + ro[i] * rdt.extent_, rc[i], rdt)
                    for i in range(n)]
    else:
        sdt = _dt(ctx, stype)
        sc = _read_i32s(scounts, n)
        so = _read_i32s(sdispls, n)
        sendobjs = [_arr_in(int(sbuf) + so[i] * sdt.extent_, sc[i], sdt)
                    for i in range(n)]
    req = comm.ialltoall(sendobjs)

    def post(res):
        for i, obj in enumerate(res):
            _arr_out(int(rbuf) + ro[i] * rdt.extent_, obj,
                     rc[i] * rdt.size_, dt=rdt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_ireduce_scatter(ctx, a):
    sbuf, rbuf, counts_or_count, dth, oph, ch, req_addr, block = a[:8]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    n = comm.size()
    dt = _dt(ctx, dth)
    if int(block):
        counts = [int(counts_or_count)] * n
    else:
        counts = _read_i32s(counts_or_count, n)
    me = comm.rank()
    if int(sbuf) == C_IN_PLACE:
        full = _arr_in(rbuf, sum(counts), dt)
    else:
        full = _arr_in(sbuf, sum(counts), dt)
    sendobjs, off = [], 0
    for c in counts:
        sendobjs.append(full[off:off + c])
        off += c
    op = _op_of(ctx, oph, dt, dt_handle=dth)
    req = comm.ireduce_scatter(sendobjs, op)
    post = lambda res: _arr_out(
        rbuf, np.asarray(res).astype(full.dtype, copy=False),
        counts[me] * dt.size_, dt=dt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_iscan(ctx, a, exclusive=False):
    sbuf, rbuf, count, dth, oph, ch, req_addr = a[:7]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    dt = _dt(ctx, dth)
    arr = _arr_in(rbuf if int(sbuf) == C_IN_PLACE else sbuf, count, dt)
    op = _op_of(ctx, oph, dt, dt_handle=dth, count=int(count))
    req = comm.iexscan(arr, op) if exclusive else comm.iscan(arr, op)

    def post(res):
        if res is None:        # exscan rank 0: undefined
            return
        _arr_out(rbuf, np.asarray(res).astype(arr.dtype, copy=False),
                 int(count) * dt.size_, dt=dt)
    return _nbc_handle(ctx, req, req_addr, post)


def _h_comm_create_group(ctx, a):
    """Collective only over the GROUP's members (MPI-3
    MPI_Comm_create_group): id allocation must not touch the
    parent-collective counter (see Comm.create_group)."""
    comm = _comm_of(ctx, a[0])
    group = ctx.groups.get(int(a[1]))
    if comm is None or group is None:
        return MPI_ERR_COMM
    _write_i32(a[3], _new_comm_handle(ctx, comm.create_group(
        group, int(a[2])), parent=a[0]))
    return MPI_SUCCESS


def _h_comm_idup(ctx, a):
    from .nbc import NbcRequest
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    h = _new_comm_handle(ctx, comm.dup(), parent=a[0])
    old = int(a[0])
    if old in ctx.cart_topos:         # same copy semantics as Comm_dup
        ctx.cart_topos[h] = ctx.cart_topos[old]
    if old in ctx.graph_topos:
        ctx.graph_topos[h] = ctx.graph_topos[old]
    _write_i32(a[1], h)
    # the dup is immediate here; hand back an already-complete request
    h = _new_req_handle(ctx, _CReq(NbcRequest([], [], lambda _: None),
                                   0, None, "nbc"))
    _write_i32(a[2], h)
    return MPI_SUCCESS


def _h_comm_set_name(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    ctx.comm_names[int(a[0])] = ctypes.string_at(int(a[1])).decode()
    return MPI_SUCCESS


def _h_comm_split_type(ctx, a):
    ch, split_type, key, out_addr = a[:4]
    comm = _comm_of(ctx, ch)
    if comm is None:
        return MPI_ERR_COMM
    # one uniform collective for every rank: MPI_UNDEFINED callers must
    # still participate (comm/cmsplit_type mixes SHARED and UNDEFINED)
    st = int(split_type)
    me_host = runtime.this_rank_state().host
    mine = me_host.name if st != C_UNDEFINED else None
    hosts = comm.allgather(mine)
    if st == C_UNDEFINED:
        color = -1
    else:
        # MPI_COMM_TYPE_SHARED: ranks sharing a host
        color = sorted({h for h in hosts if h is not None}).index(
            me_host.name)
    new = comm.split(color, int(key))
    _write_i32(out_addr, _new_comm_handle(ctx, new, parent=ch))
    return MPI_SUCCESS


def _h_comm_compare(ctx, a):
    c1, c2 = _comm_of(ctx, a[0]), _comm_of(ctx, a[1])
    if c1 is None or c2 is None:
        return MPI_ERR_COMM
    if int(a[0]) == int(a[1]):
        result = 0                      # MPI_IDENT
    elif c1.group.world_ranks == c2.group.world_ranks:
        result = 1                      # MPI_CONGRUENT
    elif set(c1.group.world_ranks) == set(c2.group.world_ranks):
        result = 2                      # MPI_SIMILAR
    else:
        result = 3                      # MPI_UNEQUAL
    _write_i32(a[2], result)
    return MPI_SUCCESS


def _h_group_setop(ctx, a):
    g1 = ctx.groups.get(int(a[0]))
    mode = int(a[3])
    if g1 is None:
        return MPI_ERR_ARG
    if mode == 3:                       # range_excl
        n = int(a[4])
        flat = _read_i32s(a[5], 3 * n)
        ranges = [tuple(flat[3 * i:3 * i + 3]) for i in range(n)]
        keep = set()
        for first, last, stride in ranges:
            step = stride if stride else 1
            keep.update(range(first, last + (1 if step > 0 else -1),
                              step))
        new = g1.excl(sorted(keep))
    else:
        g2 = ctx.groups.get(int(a[1]))
        if g2 is None:
            return MPI_ERR_ARG
        new = (g1.union(g2) if mode == 0
               else g1.intersection(g2) if mode == 1
               else g1.difference(g2))
    _write_i32(a[2], _new_group_handle(ctx, new))
    return MPI_SUCCESS


def _h_group_translate(ctx, a):
    g1 = ctx.groups.get(int(a[0]))
    g2 = ctx.groups.get(int(a[3]))
    if g1 is None or g2 is None:
        return MPI_ERR_ARG
    n = int(a[1])
    if n <= 0:                       # n=0 with NULL arrays is legal
        return MPI_SUCCESS
    src = ctypes.cast(int(a[2]), ctypes.POINTER(ctypes.c_int * n)).contents
    out = (ctypes.c_int * n)(*g1.translate_ranks(src, g2))
    ctypes.memmove(int(a[4]), out, 4 * n)
    return MPI_SUCCESS


def _h_group_compare(ctx, a):
    g1 = ctx.groups.get(int(a[0]))
    g2 = ctx.groups.get(int(a[1]))
    if g1 is None or g2 is None:
        return MPI_ERR_ARG
    if g1.world_ranks == g2.world_ranks:
        result = 0                      # MPI_IDENT
    elif set(g1.world_ranks) == set(g2.world_ranks):
        result = 2                      # MPI_SIMILAR
    else:
        result = 3                      # MPI_UNEQUAL
    _write_i32(a[2], result)
    return MPI_SUCCESS


def _is_inter(comm) -> bool:
    return getattr(comm, "remote_group", None) is not None


C_ROOT = -3


def _h_intercomm_create(ctx, a):
    from .intercomm import intercomm_create
    local = _comm_of(ctx, a[0])
    peer = _comm_of(ctx, a[2])
    if local is None:
        return MPI_ERR_COMM
    ic = intercomm_create(local, int(a[1]), peer, int(a[3]), int(a[4]))
    _write_i32(a[5], _new_comm_handle(ctx, ic))
    return MPI_SUCCESS


def _h_intercomm_merge(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None or not _is_inter(comm):
        return MPI_ERR_COMM
    _write_i32(a[2], _new_comm_handle(ctx, comm.merge(bool(int(a[1])))))
    return MPI_SUCCESS


def _h_cancel(ctx, a):
    """MPI_Cancel: succeeds only while the message/recv is unmatched
    (the kernel comm still WAITING in the mailbox); a matched operation
    completes normally and MPI_Test_cancelled reports false."""
    req_addr = a[0]
    h = ctypes.cast(int(req_addr), _pi32)[0] if req_addr else 0
    if h == 0:
        return MPI_SUCCESS
    entry = ctx.reqs.get(int(h))
    if entry is None:
        return MPI_ERR_REQUEST
    if isinstance(entry, _CGreq):
        if entry.cancel is not None:
            entry.cancel(entry.extra, 1 if entry.complete else 0)
        return MPI_SUCCESS
    creq = entry.inner if isinstance(entry, _CPersist) else entry
    req = getattr(creq, "req", None)
    if req is not None and hasattr(req, "cancel"):
        req.cancel()
    return MPI_SUCCESS


def _h_comm_remote_size(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None or not _is_inter(comm):
        return MPI_ERR_COMM
    _write_i32(a[1], comm.remote_size())
    return MPI_SUCCESS


def _h_comm_test_inter(ctx, a):
    comm = _comm_of(ctx, a[0])
    if comm is None:
        return MPI_ERR_COMM
    _write_i32(a[1], 1 if _is_inter(comm) else 0)
    return MPI_SUCCESS


def _h_request_get_status(ctx, a):
    """Non-destructive completion query: tests the request but leaves
    the handle live (MPI_Request_get_status)."""
    h, flag_addr, st_addr = int(a[0]), a[1], a[2]
    entry = ctx.reqs.get(h)
    if h == 0 or entry is None:
        _write_i32(flag_addr, 1)
        # MPI-2.2: MPI_REQUEST_NULL yields the EMPTY status (source
        # MPI_ANY_SOURCE, tag MPI_ANY_TAG, error MPI_SUCCESS, count 0)
        # — pt2pt/rqstatus checks the fields, so the struct cannot be
        # left holding caller stack garbage
        _set_status(st_addr, C_ANY_SOURCE, C_ANY_TAG, MPI_SUCCESS, 0,
                    False, keep_error=False)
        return MPI_SUCCESS
    status = Status()
    if isinstance(entry, _CPersist):
        done = entry.inner is None or _req_test(entry.inner, status)
    else:
        done = _req_test(entry, status)
    _write_i32(flag_addr, 1 if done else 0)
    if done:
        _status_from(st_addr, status)
    return MPI_SUCCESS


def _h_type_get_envelope(ctx, a):
    dt = _dt(ctx, a[0])
    env = getattr(dt, "c_env", None)
    if env is None:
        _write_i32(a[1], 0)
        _write_i32(a[2], 0)
        _write_i32(a[3], 0)
        _write_i32(a[4], C_COMBINER_NAMED)
        return MPI_SUCCESS
    comb, ints, aints, dts = env
    _write_i32(a[1], len(ints))
    _write_i32(a[2], len(aints))
    _write_i32(a[3], len(dts))
    _write_i32(a[4], comb)
    return MPI_SUCCESS


def _h_type_get_contents(ctx, a):
    dth, max_i, max_a, max_d = int(a[0]), int(a[1]), int(a[2]), int(a[3])
    ints_a, aints_a, dts_a = a[4], a[5], a[6]
    dt = _dt(ctx, dth)
    env = getattr(dt, "c_env", None)
    if env is None:
        return MPI_ERR_ARG    # erroneous on a NAMED type (MPI-3 §4.1.13)
    comb, ints, aints, handles = env
    objs = getattr(dt, "c_env_types", None)
    if ints_a:
        pi = ctypes.cast(int(ints_a), _pi32)
        for i, v in enumerate(ints[:max_i]):
            pi[i] = int(v)
    if aints_a:
        pa = ctypes.cast(int(aints_a), _pi64)
        for i, v in enumerate(aints[:max_a]):
            pa[i] = int(v)
    if dts_a:
        pd = ctypes.cast(int(dts_a), _pi32)
        for i, h in enumerate(handles[:max_d]):
            h = int(h)
            # predefined handles are returned as-is; a derived child
            # gets a FRESH handle (the standard returns new references
            # that survive the original being freed)
            if h in _PREDEF_DTYPES:
                pd[i] = h
            else:
                obj = (ctx.dtypes.get(h) if objs is None
                       else objs[min(i, len(objs) - 1)])
                pd[i] = _new_dtype_handle(ctx, obj) if obj is not None \
                    else 0
    return MPI_SUCCESS


def _h_get_elements(ctx, a):
    st_addr, dth, count_addr, mode = a[0], a[1], a[2], int(a[3])

    def put(v):
        if mode == 1:
            _write_i64(count_addr, v)
        else:
            # Get_elements returns int: overflow -> MPI_UNDEFINED
            _write_i32(count_addr, v if v <= 2**31 - 1 else C_UNDEFINED)

    if mode == 2:                # MPI_Status_set_elements(_x)
        dt = _dt(ctx, dth)
        n = ctypes.cast(int(count_addr), _pi64)[0]
        if st_addr:
            ctypes.cast(int(st_addr) + 16, _pi64)[0] = int(n * dt.size_)
        return MPI_SUCCESS
    if st_addr == 0:
        put(0)
        return MPI_SUCCESS
    nbytes = ctypes.cast(int(st_addr) + 16, _pi64)[0]
    dt = _dt(ctx, dth)
    basics = _basics_of(dt)
    if not basics or nbytes <= 0:
        put(0)
        return MPI_SUCCESS
    per_full = sum(basics)
    full = nbytes // per_full
    rem = nbytes - full * per_full
    n = full * len(basics)
    for b in basics:
        if rem >= b:
            n += 1
            rem -= b
        else:
            break
    put(n)
    return MPI_SUCCESS


def _h_type_lbub(ctx, a):
    dt = _dt(ctx, a[0])
    mode = int(a[2])
    lb = int(getattr(dt, "c_lb", 0))
    if mode == 0:
        val = lb
    elif mode == 1:
        val = lb + dt.extent_
    else:
        val = dt.extent_
    _write_i64(a[1], val)
    return MPI_SUCCESS


def _h_type_darray(ctx, a):
    size, rank, ndims = int(a[0]), int(a[1]), int(a[2])
    gsizes = _read_i32s(a[3], ndims)
    distribs = _read_i32s(a[4], ndims)
    dargs = _read_i32s(a[5], ndims)
    psizes = _read_i32s(a[6], ndims)
    order = int(a[7])
    oldh = a[8]
    old = _dt(ctx, oldh)
    out_addr = a[9]
    # rank -> process coords in the psizes grid (C row-major)
    coords = []
    for d in range(ndims):
        block = 1
        for dd in range(d + 1, ndims):
            block *= psizes[dd]
        coords.append((rank // block) % psizes[d])
    gs, ds, da, ps, co = gsizes, distribs, dargs, psizes, coords
    if order == 57:              # MPI_ORDER_FORTRAN: mirror to C order
        gs, ds, da, ps, co = (gs[::-1], ds[::-1], da[::-1], ps[::-1],
                              co[::-1])
    # per-dimension owned global indices (block / cyclic(b) / none)
    idx = []
    for g, dist, darg, p, c in zip(gs, ds, da, ps, co):
        if dist == C_DISTRIBUTE_NONE:
            own = list(range(g))
        elif dist == C_DISTRIBUTE_CYCLIC:
            b = 1 if darg == C_DISTRIBUTE_DFLT_DARG else darg
            own = [i for start in range(c * b, g, p * b)
                   for i in range(start, min(start + b, g))]
        else:                    # MPI_DISTRIBUTE_BLOCK
            b = ((g + p - 1) // p if darg == C_DISTRIBUTE_DFLT_DARG
                 else darg)
            own = list(range(c * b, min(c * b + b, g)))
        idx.append(own)
    strides = [1] * ndims
    for d in range(ndims - 2, -1, -1):
        strides[d] = strides[d + 1] * gs[d + 1]
    segs = []
    old_segs = _segments_of(old)

    def walk(d, off):
        if d == ndims:
            base = off * old.extent_
            segs.extend((base + o, n) for o, n in old_segs)
            return
        for i in idx[d]:
            walk(d + 1, off + i * strides[d])

    walk(0, 0)
    nloc = 1
    for own in idx:
        nloc *= len(own)
    total = 1
    for g in gs:
        total *= g
    dt = _derived(ctx, out_addr, old, nloc * old.size_,
                  total * old.extent_, segs, "darray")
    dt.c_basics = _basics_of(old)
    dt.c_env = (C_COMBINER_DARRAY,
                [size, rank, ndims] + gsizes + distribs + dargs + psizes
                + [order], [], [int(oldh)])
    dt.c_env_types = [old]
    return MPI_SUCCESS


def _h_pack_external(ctx, a):
    """external32 pack/unpack: identical layout to the native pack but
    every basic element is byte-swapped to big-endian."""
    typed_buf, count, dth, packed_buf, packed_size, pos_addr, mode = a[:7]
    dt = _dt(ctx, dth)
    mode = int(mode)
    nbytes = int(count) * dt.size_
    if mode == 2:                # MPI_Pack_external_size
        _write_i64(pos_addr, nbytes)
        return MPI_SUCCESS
    basics = _basics_of(dt) or [1]
    per = sum(basics)
    # packed elements may carry trailing ABI padding (the pair types
    # ship their padded C struct: size_ 16 vs MPI size 12 for
    # MPI_DOUBLE_INT): swap the basic elements, pass padding through.
    # Derived types built FROM a padded pair type inherit c_basics but
    # not c_mpi_size — recover the per-element pad from the structured
    # np dtype's itemsize (the element stride in the packed stream).
    pad = 0
    if per:
        if int(getattr(dt, "c_mpi_size", dt.size_)) != dt.size_:
            pad = dt.size_ - per
        elif dt.np_dtype is not None:
            isz = np.dtype(dt.np_dtype).itemsize
            if isz > per and dt.size_ % isz == 0:
                pad = isz - per

    def swap(data):
        out = bytearray(data)
        i = 0
        while i < len(out):
            for b in basics:
                if i + b > len(out):
                    return bytes(out[:len(data)])
                out[i:i + b] = data[i:i + b][::-1]
                i += b
            i += pad             # padding bytes stay as-is
        return bytes(out)

    pos = ctypes.cast(int(pos_addr), _pi64)[0]
    if mode == 0:                # pack
        if pos + nbytes > int(packed_size):
            return MPI_ERR_OTHER
        arr = _arr_in(typed_buf, count, dt)
        data = swap(np.ascontiguousarray(arr).tobytes())
        if nbytes:
            ctypes.memmove(int(packed_buf) + pos, data, nbytes)
    else:                        # unpack
        raw = ctypes.string_at(int(packed_buf) + pos, nbytes) if nbytes \
            else b""
        arr = np.frombuffer(bytearray(swap(raw)), np.uint8)
        _arr_out(typed_buf, arr, dt=dt)
    ctypes.cast(int(pos_addr), _pi64)[0] = pos + nbytes
    return MPI_SUCCESS


_MATCH_SIZE = {(1, 4): 43, (1, 8): 44, (1, 16): 45,
               (2, 1): 49, (2, 2): 50, (2, 4): 51, (2, 8): 52,
               (3, 8): 46, (3, 16): 47, (3, 32): 48}


def _h_type_match_size(ctx, a):
    h = _MATCH_SIZE.get((int(a[0]), int(a[1])))
    if h is None:
        return MPI_ERR_ARG
    _write_i32(a[2], h)
    return MPI_SUCCESS


_HANDLERS = {
    1: _h_init, 2: _h_finalize, 3: _h_initialized, 4: _h_finalized,
    5: _h_abort, 6: _h_comm_rank, 7: _h_comm_size, 8: _h_comm_dup,
    9: _h_comm_split, 10: _h_comm_free, 11: _h_send,
    12: lambda c, a: _h_send(c, a, ssend=True), 13: _h_recv, 14: _h_isend,
    15: _h_irecv, 16: _h_wait, 17: _h_test, 18: _h_waitall, 19: _h_waitany,
    20: _h_testall, 21: _h_probe, 22: _h_iprobe, 23: _h_sendrecv,
    24: _h_get_count, 25: _h_barrier, 26: _h_bcast, 27: _h_reduce,
    28: _h_allreduce, 29: _h_gather, 30: _h_gatherv, 31: _h_allgather,
    32: _h_allgatherv, 33: _h_scatter, 34: _h_scatterv, 35: _h_alltoall,
    36: _h_alltoallv, 37: _h_scan,
    38: lambda c, a: _h_scan(c, a, exclusive=True), 39: _h_reduce_scatter,
    40: _h_reduce_scatter_block, 41: _h_type_size, 42: _h_type_get_extent,
    43: _h_type_contiguous, 44: _h_type_vector, 45: _h_type_commit,
    46: _h_type_free, 47: _h_op_create, 48: _h_op_free, 49: _h_comm_group,
    50: _h_group_size, 51: _h_group_rank, 52: _h_get_processor_name,
    53: _h_file_open, 54: _h_file_close, 55: _h_file_delete,
    56: _h_file_seek, 57: lambda c, a: _h_file_seek(c, a, shared=True),
    58: _h_file_get_position, 59: _h_file_get_size,
    60: lambda c, a: _h_file_io(c, a, write=False),
    61: lambda c, a: _h_file_io(c, a, write=True), 62: _h_file_sync,
    63: _h_shared_malloc, 64: _h_shared_free, 65: _h_execute,
    66: _h_sample_1, 67: _h_sample_2, 68: _h_sample_3,
    69: _h_sample_exit, 70: _h_comm_get_name, 71: _h_comm_create,
    72: _h_group_incl, 73: lambda c, a: _h_group_incl(c, a, "excl"),
    74: lambda c, a: _h_group_incl(c, a, "range"),
    75: _h_keyval_create, 76: _h_keyval_free, 77: _h_attr_put,
    78: _h_attr_get, 79: _h_attr_delete, 80: _h_win_create,
    81: _h_win_free, 82: _h_win_fence, 83: _h_win_get_attr,
    84: _h_win_set_attr, 85: _h_type_struct, 86: _h_ibarrier,
    87: _h_ibcast, 88: _h_ireduce, 89: _h_iallreduce, 90: _h_igather,
    91: _h_iscatter, 92: _h_iallgather, 93: _h_ialltoall,
    94: _h_type_get_name, 95: _h_cart_create, 96: _h_cart_get,
    97: _h_cart_rank, 98: _h_cart_coords, 99: _h_cart_shift,
    100: _h_cart_sub, 101: _h_cartdim_get, 102: _h_dims_create,
    103: _h_topo_test, 104: _h_alltoallw, 105: _h_ialltoallw,
    106: _h_iscatterv, 107: _h_igatherv, 108: _h_iallgatherv,
    109: _h_ialltoallv, 110: _h_ireduce_scatter, 111: _h_iscan,
    112: lambda c, a: _h_iscan(c, a, exclusive=True),
    113: _h_type_resized, 114: _h_bsend,
    115: lambda c, a: _h_bsend(c, a, is_ibsend=True),
    116: _h_send_init, 117: _h_recv_init, 118: _h_start,
    119: _h_startall, 120: _h_request_free, 121: _h_sendrecv_replace,
    122: _h_testany, 123: _h_waitsome, 124: _h_type_indexed,
    125: _h_type_hvector, 126: _h_type_indexed_block, 127: _h_type_dup,
    128: _h_type_subarray, 129: _h_pack, 130: _h_graph_create,
    131: _h_graph_neighbors, 132: _h_graphdims_get, 133: _h_graph_get,
    134: _h_request_get_status, 135: _h_comm_create_group,
    136: _h_comm_idup, 137: _h_comm_set_name, 138: _h_comm_split_type,
    139: _h_group_setop, 140: _h_group_translate,
    141: _h_group_compare, 142: _h_comm_compare,
    143: _h_intercomm_create, 144: _h_intercomm_merge,
    145: _h_comm_remote_size, 146: _h_comm_test_inter, 147: _h_cancel,
    148: _h_type_get_envelope, 149: _h_type_get_contents,
    150: _h_get_elements, 151: _h_type_lbub, 152: _h_type_darray,
    153: _h_pack_external, 154: _h_type_match_size, 155: _h_topo_map,
    156: _h_dist_graph_create, 157: _h_dist_graph_neighbors,
    # one-sided (MPI-3 RMA)
    158: _h_rma_put, 159: _h_rma_get, 160: _h_rma_acc, 161: _h_rma_gacc,
    162: _h_fetch_and_op, 163: _h_compare_and_swap,
    164: lambda c, a: _h_rma_put(c, a, with_req=True),
    165: lambda c, a: _h_rma_get(c, a, with_req=True),
    166: lambda c, a: _h_rma_acc(c, a, with_req=True),
    167: lambda c, a: _h_rma_gacc(c, a, with_req=True),
    168: _h_win_allocate,
    169: lambda c, a: _h_win_allocate(c, a, shared=True),
    170: _h_win_create_dynamic, 171: _h_win_attach, 172: _h_win_detach,
    173: _h_win_shared_query, 174: _h_win_lock, 175: _h_win_unlock,
    176: _h_win_lock_all, 177: _h_win_unlock_all, 178: _h_win_flush,
    179: lambda c, a: _h_win_flush(c, a, local=True),
    180: _h_win_flush_all,
    181: lambda c, a: _h_win_flush_all(c, a, local=True),
    182: _h_win_sync, 183: _h_win_start, 184: _h_win_complete,
    185: _h_win_post, 186: _h_win_wait, 187: _h_win_test,
    188: _h_win_get_group, 189: _h_win_set_name, 190: _h_win_get_name,
    191: _h_win_keyval_create, 192: _h_keyval_free,
    193: _h_win_delete_attr, 194: _h_win_set_errhandler,
    195: _h_win_get_errhandler, 196: _h_win_call_errhandler,
    # matched probe + generalized requests
    197: _h_mprobe, 198: _h_improbe, 199: _h_mrecv, 200: _h_imrecv,
    201: _h_grequest_start, 202: _h_grequest_complete,
    # datatype attributes
    203: _h_keyval_create, 204: _h_type_set_attr, 205: _h_type_get_attr,
    206: _h_type_delete_attr,
    # error handlers + dynamic error codes
    207: _h_errhandler_create, 208: _h_errhandler_free,
    209: _h_comm_set_errhandler, 210: _h_comm_get_errhandler,
    211: _h_comm_call_errhandler, 212: _h_add_error_class,
    213: _h_add_error_code, 214: _h_add_error_string,
    215: _h_error_string, 216: _h_error_class,
    217: _h_op_commutative, 218: _h_reduce_local,
}

#: ops that are pure local queries — no bench end/begin cycle needed
#: (sample_2/3 stay non-local: the bench injection right before their
#: handlers is what prices the sampled loop body)
_LOCAL_OPS = {3, 4, 24, 41, 42, 45, 46, 48, 50, 51, 63, 64, 66, 69,
              70, 72, 73, 74, 75, 76, 77, 78, 79, 83, 84, 85, 94, 96,
              97, 98, 99, 101, 102, 103, 129, 130, 131, 132, 133,
              134, 135, 136, 137, 139, 140, 141, 142,
              171, 172, 173, 188, 189, 190, 191, 192, 193, 194, 195,
              196, 201, 202, 203, 204, 205, 206, 207, 208, 209, 210,
              211, 212, 213, 214, 215, 216, 217, 218}


def _dispatch_py(opcode: int, args) -> int:
    try:
        ctx = _ctx()
    except Exception:
        sys.stderr.write("smpi.c_api: MPI call outside a rank actor\n")
        return MPI_ERR_INTERN
    if ctx.dead:
        return MPI_ERR_OTHER
    local = opcode in _LOCAL_OPS
    try:
        if not local:
            _bench_end(ctx)
        handler = _HANDLERS.get(opcode)
        if handler is None:
            return MPI_ERR_INTERN
        return handler(ctx, args)
    except Exception as exc:
        from ..exceptions import ForcefulKillException
        if isinstance(exc, ForcefulKillException):
            # the actor was killed while blocked inside this MPI call;
            # we cannot unwind the C frames below us — mark the rank
            # dead so every later call returns an error fast
            ctx.dead = True
            return MPI_ERR_OTHER
        import traceback
        traceback.print_exc()
        return MPI_ERR_INTERN
    finally:
        if not local and not ctx.dead:
            _bench_begin(ctx)


def _wtime_py() -> float:
    from ..s4u import Engine
    try:
        ctx = _ctx()
        _bench_end(ctx)
        now = Engine.get_clock()
        _bench_begin(ctx)
        return now
    except Exception:
        return 0.0


_DISPATCH_CFUNC = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int, _pi64)
_WTIME_CFUNC = ctypes.CFUNCTYPE(ctypes.c_double)

_dispatch_cb = _DISPATCH_CFUNC(_dispatch_py)
_wtime_cb = _WTIME_CFUNC(_wtime_py)


# ---------------------------------------------------------------------------
# Compilation (tools/smpicc calls this too)
# ---------------------------------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def compile_program(sources: Sequence[str], output: str,
                    extra_flags: Sequence[str] = ()) -> str:
    """smpicc: compile MPI C/C++ sources into a simulator-loadable .so
    (reference src/smpi/smpicc.in — same trick: ``-Dmain=...`` renames
    the user's main so every rank can call it)."""
    root = _repo_root()
    cxx = any(str(s).endswith((".cc", ".cpp", ".cxx")) for s in sources)
    cc = os.environ.get("SMPI_CC", "g++" if cxx else "gcc")
    cmd = [cc, "-shared", "-fPIC", "-O2",
           "-I" + os.path.join(root, "include", "smpi"),
           "-I" + os.path.join(root, "include"),   # smpi/mpi.h, simgrid/*
           "-Dmain=smpi_c_main",
           *[str(s) for s in sources],
           os.path.join(root, "native", "smpi_shim.c"),
           "-o", output, "-lm", *extra_flags]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"smpicc failed ({' '.join(cmd)}):\n"
                           f"{proc.stderr}")
    return output


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------

def run_c_program(program_so: str, np_ranks: Optional[int] = None,
                  platform: Optional[str] = None,
                  hosts: Optional[Sequence[str]] = None,
                  hostfile: Optional[str] = None,
                  configs: Sequence[str] = (),
                  app_args: Sequence[str] = ()):
    """smpirun for compiled C programs: deploy np ranks, each dlopening
    a private copy of `program_so` (per-rank globals) and running its
    renamed main. Returns (engine, exit_codes)."""
    tmpdir = tempfile.mkdtemp(prefix="smpi-priv-")
    # C mains put real arrays on the actor stack (mpich3 bsendfrag:
    # 4 x 68 KB locals); default to the reference's 8 MiB stacks
    # (sg_config.cpp contexts/stack-size) unless the caller chose one
    if not any("contexts/stack-size" in c for c in configs):
        configs = ("contexts/stack-size:8388608", *configs)
    exit_codes: Dict[int, int] = {}
    _ctxs.clear()
    _c_shared_blocks.clear()
    _c_shared_by_addr.clear()
    _c_samples.clear()

    def rank_main():
        rank = runtime.this_rank()
        path = os.path.join(tmpdir, f"rank{rank}.so")
        shutil.copy(program_so, path)
        lib = ctypes.CDLL(path, mode=ctypes.RTLD_LOCAL)
        lib.smpi_set_callbacks(_dispatch_cb, _wtime_cb)
        lib.smpi_c_main.restype = ctypes.c_int
        argv_bytes = [os.fsencode(program_so)] + \
            [a.encode() if isinstance(a, str) else a for a in app_args]
        argc = len(argv_bytes)
        argv = (ctypes.c_char_p * (argc + 1))(*argv_bytes, None)
        ctx = _ctx()
        _bench_begin(ctx)
        rc = lib.smpi_c_main(_i32(argc), argv)
        ctx.bench_t0 = None
        exit_codes[rank] = (ctx.exit_code if ctx.exit_code is not None
                            else int(rc))

    try:
        engine = runtime.smpirun(rank_main, platform=platform, np=np_ranks,
                                 hosts=hosts, hostfile=hostfile,
                                 configs=configs)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return engine, exit_codes
