"""MPI groups (reference src/smpi/mpi/smpi_group.cpp): an ordered set of
world ranks with the usual set algebra."""

from __future__ import annotations

from typing import List, Optional

MPI_UNDEFINED = -32766


class Group:
    def __init__(self, world_ranks: List[int]):
        self.world_ranks = list(world_ranks)
        self._index = {w: i for i, w in enumerate(self.world_ranks)}

    def size(self) -> int:
        return len(self.world_ranks)

    def rank(self, world_rank: int) -> int:
        """Group rank of a world rank (MPI_UNDEFINED if absent)."""
        return self._index.get(world_rank, MPI_UNDEFINED)

    def actor(self, group_rank: int) -> int:
        """World rank at position group_rank."""
        return self.world_ranks[group_rank]

    def incl(self, ranks: List[int]) -> "Group":
        return Group([self.world_ranks[r] for r in ranks])

    def excl(self, ranks: List[int]) -> "Group":
        excluded = set(ranks)
        return Group([w for i, w in enumerate(self.world_ranks)
                      if i not in excluded])

    def range_incl(self, ranges) -> "Group":
        out = []
        for first, last, stride in ranges:
            out.extend(self.world_ranks[r] for r in
                       range(first, last + (1 if stride > 0 else -1), stride))
        return Group(out)

    def union(self, other: "Group") -> "Group":
        out = list(self.world_ranks)
        seen = set(out)
        out.extend(w for w in other.world_ranks if w not in seen)
        return Group(out)

    def intersection(self, other: "Group") -> "Group":
        theirs = set(other.world_ranks)
        return Group([w for w in self.world_ranks if w in theirs])

    def difference(self, other: "Group") -> "Group":
        theirs = set(other.world_ranks)
        return Group([w for w in self.world_ranks if w not in theirs])

    def translate_ranks(self, ranks: List[int],
                        other: "Group") -> List[int]:
        # MPI_PROC_NULL passes through unchanged (MPI-3 §6.3.2,
        # group/gtranks); absent ranks map to MPI_UNDEFINED.  Kept as
        # one comprehension over the cached index: group/gtranksperf
        # times 2M translations.
        idx = other._index
        wr = self.world_ranks
        return [r if r == -2 else idx.get(wr[r], MPI_UNDEFINED)
                for r in ranks]
