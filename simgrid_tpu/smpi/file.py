"""MPI-IO: MPI_File over the file_system plugin's simulated storage.

Reference: src/smpi/mpi/smpi_file.cpp — each rank holds a plugin File
handle resolved on its own host's mounted storage; a per-path shared
file pointer + mutex serves the *_shared operations; the *_ordered
operations compute each rank's slot with a prefix scan of the sizes and
then behave like read_at/write_at (the reference's File::op_all /
seek_shared machinery, redesigned on the existing collectives).

Sizes are byte counts (callers multiply count * datatype.size(), which
is what the PMPI layer charges too).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..s4u.synchro import Mutex
from .op import MPI_SUM

# amode flags (values are this ABI's own; the MPI standard leaves them
# implementation-defined)
MPI_MODE_RDONLY = 2
MPI_MODE_RDWR = 8
MPI_MODE_WRONLY = 4
MPI_MODE_CREATE = 1
MPI_MODE_EXCL = 64
MPI_MODE_DELETE_ON_CLOSE = 16
MPI_MODE_UNIQUE_OPEN = 32
MPI_MODE_APPEND = 128
MPI_MODE_SEQUENTIAL = 256

MPI_SEEK_SET = 0
MPI_SEEK_CUR = 1
MPI_SEEK_END = 2


class MpiFileError(Exception):
    pass


class _SharedState:
    """Per-(comm, path) shared file pointer + mutex
    (smpi_file.cpp:40-60 shared_file_pointer_/shared_mutex_)."""

    def __init__(self):
        self.pointer = 0
        self.mutex = Mutex()


_shared: Dict[Tuple, _SharedState] = {}


class MpiFile:
    """An MPI file handle: per-rank plugin File + collective ops."""

    def __init__(self, comm, filename: str, amode: int):
        from ..plugins.file_system import File
        self.comm = comm
        self.filename = filename
        self.amode = amode
        self.closed = False
        self._file = File(filename)       # resolved on this rank's host
        if (amode & MPI_MODE_EXCL) and self._file.get_size() > 0:
            raise MpiFileError(
                f"MPI_MODE_EXCL: {filename} already exists")
        if amode & MPI_MODE_APPEND:
            self._file.seek(0, MPI_SEEK_END)
        key = (comm.id, filename)
        state = _shared.get(key)
        if state is None:
            state = _shared[key] = _SharedState()
        self._state = state
        # open is collective (smpi_file.cpp File constructor ends with
        # a barrier over the communicator)
        comm.barrier()

    # -- individual file pointer ----------------------------------------
    def seek(self, offset: int, whence: int = MPI_SEEK_SET) -> None:
        self._file.seek(int(offset), whence)

    def get_position(self) -> int:
        return self._file.tell()

    def get_size(self) -> int:
        return self._file.get_size()

    def read(self, size: int) -> int:
        """Read `size` bytes at the individual pointer; returns bytes
        actually moved (clamped at EOF like the reference)."""
        self._check(MPI_MODE_WRONLY, "read")
        return self._file.read(int(size))

    def write(self, size: int) -> int:
        self._check(MPI_MODE_RDONLY, "write")
        return self._file.write(int(size))

    def read_at(self, offset: int, size: int) -> int:
        """Explicit-offset read; does not move the individual pointer."""
        pos = self._file.tell()
        self._file.seek(int(offset))
        moved = self.read(size)
        self._file.seek(pos)
        return moved

    def write_at(self, offset: int, size: int) -> int:
        pos = self._file.tell()
        self._file.seek(int(offset))
        moved = self.write(size)
        self._file.seek(pos)
        return moved

    # -- shared file pointer --------------------------------------------
    def read_shared(self, size: int) -> int:
        with self._state.mutex:
            moved = self.read_at(self._state.pointer, size)
            self._state.pointer += moved
        return moved

    def write_shared(self, size: int) -> int:
        with self._state.mutex:
            moved = self.write_at(self._state.pointer, size)
            self._state.pointer += moved
        return moved

    def seek_shared(self, offset: int, whence: int = MPI_SEEK_SET) -> None:
        with self._state.mutex:
            if whence == MPI_SEEK_SET:
                self._state.pointer = int(offset)
            elif whence == MPI_SEEK_CUR:
                self._state.pointer += int(offset)
            else:
                self._state.pointer = self.get_size() + int(offset)

    def get_position_shared(self) -> int:
        return self._state.pointer

    # -- collective ops --------------------------------------------------
    def read_all(self, size: int) -> int:
        """Every rank reads at its individual pointer; completion is
        collective (smpi_file.cpp File::op_all)."""
        moved = self.read(size)
        self.comm.barrier()
        return moved

    def write_all(self, size: int) -> int:
        moved = self.write(size)
        self.comm.barrier()
        return moved

    def read_ordered(self, size: int) -> int:
        """Rank-ordered shared read: an exclusive prefix scan of the
        sizes assigns each rank its slot after the shared pointer
        (reference implements ordered ops with MPI_Scan the same way)."""
        return self._ordered(size, write=False)

    def write_ordered(self, size: int) -> int:
        return self._ordered(size, write=True)

    def _ordered(self, size: int, write: bool) -> int:
        size = int(size)
        before = self.comm.exscan(size, MPI_SUM)
        offset = self._state.pointer + (0 if before is None else int(before))
        if write:
            moved = self.write_at(offset, size)
        else:
            moved = self.read_at(offset, size)
        total = self.comm.allreduce(size, MPI_SUM)
        if self.comm.rank() == 0:
            self._state.pointer += int(total)
        self.comm.barrier()
        return moved

    # -- lifecycle --------------------------------------------------------
    def sync(self) -> None:
        """No caching layer is simulated: sync is a barrier."""
        self.comm.barrier()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self.amode & MPI_MODE_DELETE_ON_CLOSE:
            # one deleter is enough; rank 0 of the communicator does it
            if self.comm.rank() == 0:
                self._file.unlink()
        _shared.pop((self.comm.id, self.filename), None)

    def _check(self, forbidden: int, what: str) -> None:
        if self.closed:
            raise MpiFileError(f"{what} on closed file {self.filename}")
        if self.amode & forbidden:
            raise MpiFileError(
                f"{what} not permitted by amode on {self.filename}")


def file_open(comm, filename: str, amode: int) -> MpiFile:
    """MPI_File_open (collective over comm)."""
    return MpiFile(comm, filename, amode)


def file_delete(filename: str, host=None) -> None:
    """MPI_File_delete (not collective)."""
    from ..plugins.file_system import File
    File(filename, host).unlink()
