"""Schedule capture: record a collective's posted comms into a tape.

Two entry points, both producing ``collectives.schedule`` per-rank
programs from the REAL algorithm implementations in ``coll.py`` (not
the mirrored generators — that is the point: the generators are
proved against this module by tests/test_collectives.py):

* ``record_algorithm(op, algo, ranks, payload)`` runs one named
  algorithm on ``ranks`` threads over :class:`RecordingComm` shims —
  every ``isend``/``irecv``/``wait`` the algorithm posts is recorded
  as a :class:`~..collectives.schedule.Prog` op, while the payloads
  rendezvous through in-memory queues so the algorithm's own data flow
  (reduction combines, chunk rotation) runs for real.

* ``CaptureScope`` patches ``coll.dispatch`` so a live SMPI program —
  e.g. a C binary driven through ``smpi/c_api`` — records every
  top-level collective it issues; ``scope.schedule()`` then replays
  the recorded call sequence through the same thread harness,
  CONCATENATING per-rank programs so multi-phase dependency chains
  (NAS-style allreduce; alltoall; allreduce ...) fall out of the
  frontier walk with no explicit barrier records.

The shim decomposes exactly like ``smpi.Comm``: blocking ``send`` is
post + wait, ``sendrecv`` is irecv, isend, wait(recv), wait(send), and
matching is per-(src, dst, tag) FIFO — the non-overtaking rule the
runtime's mailboxes apply.  Wildcard receives cannot be compiled into
a static tape and raise :class:`CaptureError` (so ``barrier``, whose
linear algorithm receives from MPI_ANY_SOURCE, is not capturable).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..collectives.schedule import CollectiveSchedule, Prog, build_schedule
from .datatype import payload_size
from .op import MPI_SUM, Op
from .request import MPI_ANY_SOURCE, MPI_ANY_TAG

#: rendezvous timeout — a capture that blocks this long has deadlocked
#: (mismatched posts), which build_schedule would also reject
_TIMEOUT = 30.0

#: collectives the thread harness knows how to re-invoke (op name ->
#: argument shape); everything else raises at capture time
_CAPTURABLE = ("bcast", "reduce", "allreduce", "alltoall")


class CaptureError(RuntimeError):
    pass


class _Rendezvous:
    """Per-(src, dst, tag) FIFO queues carrying the real payloads
    between recording threads (the in-memory stand-in for the
    runtime's mailboxes)."""

    def __init__(self):
        self._q: Dict[tuple, queue.Queue] = {}
        self._lock = threading.Lock()

    def chan(self, src: int, dst: int, tag: int) -> queue.Queue:
        k = (src, dst, tag)
        with self._lock:
            q = self._q.get(k)
            if q is None:
                q = self._q[k] = queue.Queue()
            return q


class RecordedRequest:
    """The shim's Request: wait() records the wait op and, for recvs,
    blocks on the rendezvous channel for the real payload."""

    __slots__ = ("comm", "kind", "peer", "tag", "h", "_done", "_data")

    def __init__(self, comm: "RecordingComm", kind: str, peer: int,
                 tag: int, h: int):
        self.comm = comm
        self.kind = kind
        self.peer = peer
        self.tag = tag
        self.h = h
        self._done = False
        self._data = None

    def wait(self, status=None):
        if self._done:
            return self._data
        self.comm.prog.wait(self.h)
        if self.kind == "recv":
            chan = self.comm._rdv.chan(self.peer, self.comm._rank,
                                       self.tag)
            try:
                self._data = chan.get(timeout=_TIMEOUT)
            except queue.Empty:
                raise CaptureError(
                    f"capture deadlocked: rank {self.comm._rank} recv "
                    f"from {self.peer} tag {self.tag} never matched")
        self._done = True
        return self._data


class RecordingComm:
    """Comm-shaped shim: the p2p surface coll.py algorithms touch
    (rank/size/send/recv/isend/irecv/sendrecv), recording each post
    into a Prog while shipping payloads eagerly through queues."""

    def __init__(self, rank: int, size: int, rdv: _Rendezvous,
                 prog: Prog):
        self._rank = rank
        self._size = size
        self._rdv = rdv
        self.prog = prog

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    # -- p2p, decomposed exactly like smpi.Comm ---------------------------

    def isend(self, buf, dest: int, tag: int = 0, count=None,
              datatype=None, ssend: bool = False) -> RecordedRequest:
        h = self.prog.isend(dest, tag, payload_size(buf, datatype))
        # eager: the channel buffers, so sends never block — same
        # completion semantics the schedule compiler assumes
        self._rdv.chan(self._rank, dest, tag).put(buf)
        return RecordedRequest(self, "send", dest, tag, h)

    def send(self, buf, dest: int, tag: int = 0, count=None,
             datatype=None) -> None:
        self.isend(buf, dest, tag).wait()

    def irecv(self, source: int = MPI_ANY_SOURCE,
              tag: int = MPI_ANY_TAG, buf=None, count=None,
              datatype=None) -> RecordedRequest:
        if source == MPI_ANY_SOURCE or tag == MPI_ANY_TAG:
            raise CaptureError(
                "wildcard receive cannot be compiled into a static "
                "schedule tape (rank %d, source=%r tag=%r)"
                % (self._rank, source, tag))
        h = self.prog.irecv(source, tag)
        return RecordedRequest(self, "recv", source, tag, h)

    def recv(self, source: int = MPI_ANY_SOURCE,
             tag: int = MPI_ANY_TAG, buf=None, count=None,
             datatype=None, status=None):
        return self.irecv(source, tag).wait(status)

    def sendrecv(self, sendbuf, dest: int, recvsource: int,
                 sendtag: int = 0, recvtag: int = MPI_ANY_TAG,
                 status=None):
        rreq = self.irecv(recvsource, recvtag)
        sreq = self.isend(sendbuf, dest, sendtag)
        data = rreq.wait(status)
        sreq.wait()
        return data


def _invoke(fn: Callable, op: str, comm: RecordingComm, payload,
            mpi_op: Op, root: int):
    if op == "bcast":
        return fn(comm, payload, root)
    if op == "reduce":
        return fn(comm, payload, mpi_op, root)
    if op == "allreduce":
        return fn(comm, payload, mpi_op)
    if op == "alltoall":
        return fn(comm, payload)
    raise CaptureError(f"cannot capture collective {op!r}; "
                       f"capturable: {_CAPTURABLE}")


def _run_threads(ranks: int, progs: List[Prog],
                 thunk: Callable[[RecordingComm, int], None]) -> None:
    """Run one thread per rank over fresh RecordingComms appending to
    ``progs``; re-raise the first rank failure."""
    rdv = _Rendezvous()
    errs: List[Tuple[int, BaseException]] = []

    def body(r: int) -> None:
        try:
            thunk(RecordingComm(r, ranks, rdv, progs[r]), r)
        except BaseException as e:      # noqa: BLE001 — surfaced below
            errs.append((r, e))

    threads = [threading.Thread(target=body, args=(r,), daemon=True)
               for r in range(ranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=_TIMEOUT + 5.0)
        if t.is_alive():
            raise CaptureError("capture threads wedged (deadlocked "
                               "collective?)")
    if errs:
        r, e = errs[0]
        raise CaptureError(f"rank {r} failed during capture: "
                           f"{e!r}") from e


def default_payload(op: str, ranks: int, payload: float):
    """Per-rank payload factory matching the size conventions of
    collectives.schedule.GENERATORS: bcast/reduce/allreduce get one
    ``payload``-byte buffer (elements × 8 for lr — pass elems × 8
    bytes and the ndarray length carries the element count), alltoall
    a list of per-destination ``payload``-byte buffers."""
    def one(nbytes: float):
        n = int(nbytes)
        if n % 8 == 0 and n > 0:
            return np.zeros(n // 8, np.float64)
        return np.zeros(max(n, 1), np.uint8)

    if op == "alltoall":
        return lambda r: [one(payload) for _ in range(ranks)]
    return lambda r: one(payload)


def record_algorithm(op: str, algo: str, ranks: int, payload,
                     mpi_op: Optional[Op] = None, root: int = 0,
                     progs: Optional[List[Prog]] = None) -> List[Prog]:
    """Run the REAL ``coll.py`` algorithm ``op``/``algo`` on ``ranks``
    recording threads.  ``payload`` is a per-rank factory (rank ->
    object) or a plain object shared by all ranks.  Appends into
    ``progs`` when given (multi-phase chaining) and returns the
    program list."""
    from . import coll
    from ..utils.config import config
    fn = coll.dispatch_name(op, algo)
    mpi_op = MPI_SUM if mpi_op is None else mpi_op
    if progs is None:
        progs = [Prog() for _ in range(ranks)]
    elif len(progs) != ranks:
        raise CaptureError(f"progs has {len(progs)} ranks, need {ranks}")

    def thunk(comm: RecordingComm, r: int) -> None:
        pay = payload(r) if callable(payload) else payload
        _invoke(fn, op, comm, pay, mpi_op, root)

    # Pin the selector to the algorithm under test so nested
    # self-dispatches (allreduce_lr's remainder chunk) resolve the way
    # the named algorithm would resolve them in a run configured for
    # it, not the way the ambient config happens to point.
    flag = f"smpi/{op}"
    prev = config[flag]
    config[flag] = algo
    try:
        _run_threads(ranks, progs, thunk)
    finally:
        config[flag] = prev
    return progs


def capture_schedule(op: str, algo: str, ranks: int, payload,
                     mpi_op: Optional[Op] = None,
                     root: int = 0) -> CollectiveSchedule:
    """record_algorithm + build_schedule in one step."""
    return build_schedule(record_algorithm(op, algo, ranks, payload,
                                           mpi_op=mpi_op, root=root))


class CaptureScope:
    """Record every top-level collective a live SMPI program issues.

    Patches ``coll.dispatch`` so each per-rank invocation notes
    (algorithm fn, payload shape descriptor) in the rank's call list
    while still running the real algorithm (the program's data flow is
    undisturbed).  Nested dispatches (redbcast's inner reduce + bcast)
    are not recorded — replaying the outer call re-derives them.

    ``schedule()`` replays the j-th call of every rank together
    through the thread harness, asserting the program is SPMD (same op
    sequence on every rank), and compiles one CollectiveSchedule whose
    per-rank frontier chains the phases.
    """

    def __init__(self):
        self._calls: Dict[int, List[tuple]] = {}
        self._depth: Dict[int, int] = {}
        self._ranks: Optional[int] = None
        self._orig = None

    # -- context management ----------------------------------------------

    def __enter__(self) -> "CaptureScope":
        from . import coll
        if self._orig is not None:
            raise CaptureError("CaptureScope is not reentrant")
        self._orig = coll.dispatch
        coll.dispatch = self._dispatch
        return self

    def __exit__(self, *exc) -> None:
        from . import coll
        coll.dispatch = self._orig
        self._orig = None

    # -- the patched selector --------------------------------------------

    def _dispatch(self, opname: str) -> Callable:
        real = self._orig(opname)

        def wrapped(comm, *args, **kw):
            r = comm.rank()
            d = self._depth.get(r, 0)
            if d == 0:
                self._note(opname, real, comm, r, args, kw)
            self._depth[r] = d + 1
            try:
                return real(comm, *args, **kw)
            finally:
                self._depth[r] = d

        return wrapped

    def _note(self, opname: str, fn: Callable, comm, rank: int,
              args: tuple, kw: dict) -> None:
        if opname not in _CAPTURABLE:
            raise CaptureError(
                f"collective {opname!r} cannot be captured into a "
                f"schedule tape (capturable: {_CAPTURABLE})")
        size = comm.size()
        if self._ranks is None:
            self._ranks = size
        elif size != self._ranks:
            raise CaptureError(
                f"capture spans communicators of different sizes "
                f"({self._ranks} vs {size}); one communicator only")
        self._calls.setdefault(rank, []).append(
            (opname, fn, _describe(opname, args, kw)))

    # -- replay ------------------------------------------------------------

    @property
    def n_phases(self) -> int:
        if not self._calls:
            return 0
        return max(len(c) for c in self._calls.values())

    def schedule(self) -> CollectiveSchedule:
        if self._orig is not None:
            # replaying inside the scope would record the replay's own
            # nested dispatches into _calls mid-iteration
            raise CaptureError("call schedule() after the scope exits")
        ranks = self._ranks
        if ranks is None:
            raise CaptureError("no collectives captured")
        per_rank = []
        for r in range(ranks):
            if r not in self._calls:
                raise CaptureError(f"rank {r} issued no collectives "
                                   f"(non-SPMD program?)")
            per_rank.append(self._calls[r])
        n = len(per_rank[0])
        for r, calls in enumerate(per_rank):
            if len(calls) != n:
                raise CaptureError(
                    f"rank {r} issued {len(calls)} collectives, rank 0 "
                    f"issued {n}; capture needs an SPMD sequence")
        progs = [Prog() for _ in range(ranks)]
        for j in range(n):
            phase = [per_rank[r][j] for r in range(ranks)]
            opname, fn = phase[0][0], phase[0][1]
            for r, (o, f, _) in enumerate(phase):
                if o != opname or f is not fn:
                    raise CaptureError(
                        f"phase {j}: rank {r} ran {o} but rank 0 ran "
                        f"{opname}; capture needs an SPMD sequence")

            def thunk(comm: RecordingComm, r: int,
                      _phase=phase, _op=opname, _fn=fn) -> None:
                payload, mpi_op, root = _rebuild(_op, _phase[r][2])
                _invoke(_fn, _op, comm, payload, mpi_op, root)

            _run_threads(ranks, progs, thunk)
        return build_schedule(progs)


def _describe(opname: str, args: tuple, kw: dict):
    """Shape descriptor of one rank's call: enough to replay with a
    value-free payload (coll.py control flow depends on rank, size and
    payload type/length only — never on element values)."""
    if opname == "bcast":
        obj = args[0] if args else kw.get("obj")
        root = args[1] if len(args) > 1 else kw.get("root", 0)
        return (_desc(obj), None, int(root))
    if opname == "reduce":
        obj = args[0] if args else kw.get("sendobj")
        op = args[1] if len(args) > 1 else kw.get("op", MPI_SUM)
        root = args[2] if len(args) > 2 else kw.get("root", 0)
        return (_desc(obj), op, int(root))
    if opname == "allreduce":
        obj = args[0] if args else kw.get("sendobj")
        op = args[1] if len(args) > 1 else kw.get("op", MPI_SUM)
        return (_desc(obj), op, 0)
    # alltoall
    objs = args[0] if args else kw.get("sendobjs")
    return ([_desc(o) for o in objs], None, 0)


def _rebuild(opname: str, desc: tuple):
    d, op, root = desc
    if opname == "alltoall":
        return [_synth(x) for x in d], op, root
    return _synth(d), op, root


def _desc(obj):
    if isinstance(obj, np.ndarray):
        return ("nd", obj.shape, obj.dtype.str)
    if isinstance(obj, (bytes, bytearray)):
        return ("bytes", len(obj))
    return ("obj",)


def _synth(d):
    if d[0] == "nd":
        return np.zeros(d[1], np.dtype(d[2]))
    if d[0] == "bytes":
        return b"\0" * d[1]
    return 0.0
