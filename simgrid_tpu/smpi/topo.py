"""MPI process topologies: cartesian + graph (reference
src/smpi/mpi/smpi_topo.cpp).

Pure rank arithmetic over an existing communicator: Cart_create slices
(or reorders trivially — like the reference, reorder is accepted and
ignored), rank<->coords conversion is row-major, shifts wrap on
periodic dimensions and return MPI_PROC_NULL (-1) off the edge
(smpi_topo.cpp Topo_Cart::shift), and Dims_create factors nnodes into
balanced dimensions (:273-322)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

MPI_PROC_NULL = -1


class CartTopology:
    """MPI_Cart_create result (Topo_Cart). Ranks >= nnodes are excluded
    — MPI gives them MPI_COMM_NULL, here ``comm.cart_create`` returns
    None for them and this constructor refuses direct misuse."""

    def __init__(self, comm, dims: Sequence[int],
                 periodic: Sequence[int], reorder: bool = False):
        assert len(dims) == len(periodic)
        nnodes = 1
        for d in dims:
            nnodes *= d
        assert nnodes <= comm.size(), \
            (f"Cart topology of {nnodes} nodes over a communicator of "
             f"{comm.size()}")
        assert comm.rank() < nnodes, \
            (f"Rank {comm.rank()} is not part of this {nnodes}-node "
             f"cartesian topology (MPI_COMM_NULL)")
        self.dims = list(dims)
        self.periodic = [bool(p) for p in periodic]
        self.nnodes = nnodes
        self.comm = comm

    # -- rank <-> coords (row-major, smpi_topo.cpp Topo_Cart::coords) ----
    def rank(self, coords: Sequence[int]) -> int:
        r = 0
        for dim, per, c in zip(self.dims, self.periodic, coords):
            if c < 0 or c >= dim:
                assert per, f"Coordinate {c} out of non-periodic dim {dim}"
                c %= dim
            r = r * dim + c
        return r

    def coords(self, rank: int) -> List[int]:
        out = [0] * len(self.dims)
        for i in range(len(self.dims) - 1, -1, -1):
            out[i] = rank % self.dims[i]
            rank //= self.dims[i]
        return out

    def shift(self, direction: int, disp: int,
              rank: Optional[int] = None) -> Tuple[int, int]:
        """MPI_Cart_shift: (rank_source, rank_dest) for a displacement
        along a dimension; MPI_PROC_NULL past non-periodic edges."""
        if rank is None:
            rank = self.comm.rank()
        coords = self.coords(rank)

        def neighbor(offset: int) -> int:
            c = list(coords)
            c[direction] += offset
            if not self.periodic[direction] and \
                    not (0 <= c[direction] < self.dims[direction]):
                return MPI_PROC_NULL
            c[direction] %= self.dims[direction]
            return self.rank(c)

        return neighbor(-disp), neighbor(disp)

    def get(self) -> Tuple[List[int], List[bool], List[int]]:
        """MPI_Cart_get: (dims, periods, my coords)."""
        return (list(self.dims), list(self.periodic),
                self.coords(self.comm.rank()))

    def sub(self, remain_dims: Sequence[bool]) -> "SubCartTopology":
        """MPI_Cart_sub: the slice of ranks sharing this rank's dropped
        coordinates, projected onto the remaining dimensions. Neighbor
        queries return ranks of the PARENT communicator (what halo code
        sends to)."""
        return SubCartTopology(self, remain_dims)


class SubCartTopology:
    """A cartesian sub-grid (MPI_Cart_sub result): dims are the kept
    dimensions, ranks translate back to the parent communicator."""

    def __init__(self, parent: CartTopology, remain_dims: Sequence[bool]):
        self.parent = parent
        self.remain = [bool(k) for k in remain_dims]
        self.dims = [d for d, keep in zip(parent.dims, self.remain)
                     if keep] or [1]
        self.periodic = [p for p, keep in zip(parent.periodic, self.remain)
                         if keep] or [False]
        self._my_full = parent.coords(parent.comm.rank())

    def _to_parent_rank(self, sub_coords: Sequence[int]) -> int:
        full = list(self._my_full)
        it = iter(sub_coords)
        for i, keep in enumerate(self.remain):
            if keep:
                full[i] = next(it)
        return self.parent.rank(full)

    def my_coords(self) -> List[int]:
        return [c for c, keep in zip(self._my_full, self.remain) if keep]

    def shift(self, direction: int, disp: int) -> Tuple[int, int]:
        """(source, dest) as PARENT communicator ranks."""
        coords = self.my_coords()
        if not coords:
            # Every dimension dropped: a 1-node grid has no neighbors.
            return MPI_PROC_NULL, MPI_PROC_NULL

        def neighbor(offset: int) -> int:
            c = list(coords)
            c[direction] += offset
            if not self.periodic[direction] and \
                    not (0 <= c[direction] < self.dims[direction]):
                return MPI_PROC_NULL
            c[direction] %= self.dims[direction]
            return self._to_parent_rank(c)

        return neighbor(-disp), neighbor(disp)


def dims_create(nnodes: int, ndims: int,
                dims: Optional[List[int]] = None) -> List[int]:
    """MPI_Dims_create (smpi_topo.cpp:273-322): factor nnodes into
    ndims balanced dimensions, honoring pre-set (non-zero) entries."""
    out = list(dims) if dims else [0] * ndims
    assert len(out) == ndims
    fixed = 1
    free_slots = []
    for i, d in enumerate(out):
        if d > 0:
            fixed *= d
        else:
            free_slots.append(i)
    assert nnodes % fixed == 0, \
        f"nnodes {nnodes} not divisible by fixed dims product {fixed}"
    remaining = nnodes // fixed
    if not free_slots:
        assert remaining == 1
        return out

    # Prime-factorize and distribute largest-first onto smallest dims.
    factors = []
    n, p = remaining, 2
    while p * p <= n:
        while n % p == 0:
            factors.append(p)
            n //= p
        p += 1
    if n > 1:
        factors.append(n)
    sizes = [1] * len(free_slots)
    for f in sorted(factors, reverse=True):
        sizes[sizes.index(min(sizes))] *= f
    for slot, size in zip(free_slots, sorted(sizes, reverse=True)):
        out[slot] = size
    return out


class GraphTopology:
    """MPI_Graph_create result (Topo_Graph): index/edges adjacency."""

    def __init__(self, comm, index: Sequence[int], edges: Sequence[int],
                 reorder: bool = False):
        self.comm = comm
        self.index = list(index)
        self.edges = list(edges)

    def neighbors(self, rank: int) -> List[int]:
        lo = self.index[rank - 1] if rank > 0 else 0
        return self.edges[lo:self.index[rank]]

    def neighbors_count(self, rank: int) -> int:
        return len(self.neighbors(rank))


class DistGraphTopology:
    """MPI-2.2 distributed graph topology (MPI_Dist_graph_create*):
    per-rank in/out neighbor lists, assembled collectively for the
    general constructor."""

    def __init__(self, comm, sources, destinations,
                 source_weights=None, dest_weights=None):
        self.comm = comm
        self.sources = list(sources)            # my in-neighbors
        self.destinations = list(destinations)  # my out-neighbors
        self.source_weights = source_weights
        self.dest_weights = dest_weights
        # weighted iff either side carries weights (a rank may have
        # indegree 0 in a weighted graph)
        self.weighted = (source_weights is not None
                         or dest_weights is not None)
