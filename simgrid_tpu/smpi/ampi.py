"""AMPI migration hooks + greedy load balancer.

Reference: src/smpi/plugins/sampi_loadbalancer.cpp (AMPI_Migrate
machinery, replay actions, the migration-frequency flag),
src/smpi/plugins/load_balancer/LoadBalancer.cpp (the greedy balancer),
src/smpi/plugins/ampi/ampi.cpp (iteration markers, tracked
allocations feeding the migration payload size).

The balancer observes per-actor computation (recorded from every
completed single-host exec), normalizes per-host load by the host's
computed flops (host_load plugin), and greedily reassigns the heaviest
actors to the least-loaded hosts.  ``AMPI_Migrate`` runs it every
``smpi/plugin/lb/migration-frequency`` calls, bills a host-to-host
transfer of the rank's tracked memory, and migrates the calling actor
to its new host.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional

from ..utils.config import config, declare_flag
from ..utils.signal import Signal
from ..utils import log as _log

_logger = _log.get_category("plugin_load_balancer")

declare_flag(
    "smpi/plugin/lb/migration-frequency",
    "After how many calls to the migration function should the migration "
    "be actually executed?", 10,
    aliases=["smpi/plugin/lb/migration_frequency"])

#: AMPI iteration signals (ampi.cpp on_iteration_in/out)
on_iteration_in = Signal()
on_iteration_out = Signal()


class LoadBalancer:
    """Greedy balancer (LoadBalancer.cpp:45-135): actors sorted by
    recorded computation (heaviest first), hosts kept in a least-loaded
    heap (lazy-deletion entries instead of the reference's mutable
    fibonacci handles); an actor moves to the least-loaded host when
    that strictly lowers the load of its current host and doesn't empty
    it."""

    def __init__(self):
        self.actor_computation: Dict[int, float] = {}
        self.new_mapping: Dict[int, object] = {}    # pid -> host

    def record_actor_computation(self, pid: int, load: float) -> None:
        self.actor_computation[pid] = \
            self.actor_computation.get(pid, 0.0) + load

    def _computed_flops(self, host) -> float:
        from ..plugins import host_load
        try:
            total = host_load.get_computed_flops(host)
        except AssertionError:      # plugin not active: no normalization
            return 1.0
        return total if total > 0 else 1.0

    def run(self, engine) -> None:
        hosts = [h for h in engine.get_all_hosts() if h.is_on()]
        assert hosts, "No hosts available; are they all switched off?"
        actors = [a for h in hosts for a in h.actor_list
                  if not a.daemonized]
        for actor in actors:
            self.new_mapping[actor.pid] = actor.host
        comp = self.actor_computation
        actors.sort(key=lambda a: comp.get(a.pid, 0.0), reverse=True)

        load: Dict[str, float] = {}
        count: Dict[str, int] = {}
        heap: List = []
        seq = 0
        for host in hosts:
            total = self._computed_flops(host)
            load[host.name] = sum(comp.get(a.pid, 0.0) / total
                                  for a in host.actor_list
                                  if not a.daemonized)
            count[host.name] = sum(1 for a in host.actor_list
                                   if not a.daemonized)
            heapq.heappush(heap, (load[host.name], seq, host))
            seq += 1
            _logger.debug("Host %s initialized to %f", host.name,
                          load[host.name])

        def push(host):
            nonlocal seq
            heapq.heappush(heap, (load[host.name], seq, host))
            seq += 1

        for actor in actors:
            # skip stale heap entries (the lazy-deletion analogue of
            # the reference's in-place fibonacci-heap updates)
            while heap and heap[0][0] != load[heap[0][2].name]:
                heapq.heappop(heap)
            if not heap:
                break
            target = heap[0][2]
            cur = self.new_mapping[actor.pid]
            acomp = comp.get(actor.pid, 0.0)
            if (target is not cur
                    and load[target.name] + acomp < load[cur.name]
                    and count[cur.name] > 1):
                heapq.heappop(heap)
                load[cur.name] = max(0.0, load[cur.name] - acomp)
                load[target.name] += acomp
                count[cur.name] -= 1
                count[target.name] += 1
                self.new_mapping[actor.pid] = target
                _logger.debug("Assigning actor %d to host %s", actor.pid,
                              target.name)
                push(target)
                push(cur)

        from ..plugins import host_load
        for host in hosts:
            try:
                host_load.reset(host)   # reset for the next iterations
            except AssertionError:
                break
        self.actor_computation.clear()

    def get_mapping(self, actor) -> Optional[object]:
        return self.new_mapping.get(actor.pid, actor.host)


#: the plugin singleton (sampi_loadbalancer.cpp:30)
lb = LoadBalancer()

# per-pid AMPI state (ampi.cpp memory_size / migration_call_counter)
_memory_size: Dict[int, float] = {}
_migration_calls: Dict[int, int] = {}
_lb_ran = False


def ampi_malloc(pid: int, size: float) -> None:
    """_sampi_malloc's accounting half: AMPI applications route their
    allocations here so AMPI_Migrate can bill the rank's live memory as
    the migration payload."""
    _memory_size[pid] = _memory_size.get(pid, 0.0) + size


def ampi_free(pid: int, size: float) -> None:
    _memory_size[pid] = max(0.0, _memory_size.get(pid, 0.0) - size)


def AMPI_Iteration_in(comm) -> int:
    from ..s4u import Actor
    on_iteration_in(Actor.self())
    return 1


def AMPI_Iteration_out(comm) -> int:
    from ..s4u import Actor
    on_iteration_out(Actor.self())
    return 1


def AMPI_Migrate(comm, memory_consumption: Optional[float] = None) -> None:
    """sampi_loadbalancer.cpp:44-105 MigrateAction::kernel."""
    global _lb_ran
    from ..s4u import Actor, Engine, this_actor

    me = Actor.self()
    pid = me.pid
    _migration_calls[pid] = _migration_calls.get(pid, 0) + 1
    freq = int(config["smpi/plugin/lb/migration-frequency"])
    if freq <= 0 or _migration_calls[pid] % freq != 0:
        return          # freq 0 disables migration entirely

    comm.barrier()
    if not _lb_ran:
        _lb_ran = True
        _logger.debug("Process %d runs the load balancer", pid)
        lb.run(Engine.get_instance())
    comm.barrier()
    _lb_ran = False     # behind the barrier: all ranks passed the if

    cur = me.host
    target = lb.get_mapping(me)
    if target is not None and target is not cur:
        mem = memory_consumption
        if mem is None:
            mem = _memory_size.get(pid, 0.0)
        # the migration traffic: a cur->target transfer of the rank's
        # memory (parallel_execute with only that one comm amount)
        this_actor.parallel_execute([cur, target], [0.0, 0.0],
                                    [0.0, max(mem, 1.0), 0.0, 0.0])
        _logger.debug("Migrating process %d from %s to %s", pid,
                      cur.name, target.name)
        this_actor.set_host(target)
    comm.barrier()


def sg_load_balancer_plugin_init(engine=None) -> None:
    """sg_load_balancer_plugin_init: record every completed exec's cost
    against its issuer and register the AMPI replay actions."""
    from ..s4u import Engine
    from ..kernel.activity import ExecImpl

    e = engine if engine is not None else Engine.get_instance()

    def on_exec_done(impl):
        if impl.simcalls and len(impl.hosts) == 1 and impl.flops_amounts:
            lb.record_actor_computation(impl.simcalls[0].issuer.pid,
                                        impl.flops_amounts[0])

    e.pimpl.connect_signal(ExecImpl.on_completion, on_exec_done)
    _register_replay_actions()


def _register_replay_actions() -> None:
    from . import replay, runtime

    @replay.action("migrate")
    def _migrate(ctx, act):
        # only parameter: the memory consumption of the current rank
        mem = float(act[2]) if len(act) > 2 else 0.0
        AMPI_Migrate(ctx.comm, mem)

    @replay.action("iteration_in")
    def _iter_in(ctx, act):
        AMPI_Iteration_in(ctx.comm)

    @replay.action("iteration_out")
    def _iter_out(ctx, act):
        AMPI_Iteration_out(ctx.comm)
